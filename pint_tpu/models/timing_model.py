"""TimingModel + Component registry — the evaluation core.

Counterpart of reference ``timing_model.py:155,3401``; the architecture is
deliberately different (TPU-first):

* Components register via ``__init_subclass__`` (no metaclass) into
  ``Component.component_types``.
* Evaluation is a **pure function of a flat float64 parameter vector**: for a
  given (model structure, TOABatch) pair the model builds and caches a jitted
  ``phase_fn(values_vector) -> (Phase, delay)``; design matrices come from
  ``jax.jacfwd`` of that same function instead of per-component hand-coded
  partials (reference registers thousands of lines of ``d_delay_d_*`` /
  ``d_phase_d_*``; here autodiff covers every parameter automatically).
* Mask parameters are resolved to boolean arrays on the host and baked into
  the trace as constants (data-dependent shapes never enter jit).
* Components still see the accumulated delay of earlier components (ordering
  is semantic, reference ``timing_model.py:1595-1598``).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.dd import DD, dd_from_float, dd_from_longdouble, dd_mul, dd_sub
from pint_tpu.exceptions import (
    MissingParameter,
    TimingModelError,
    UnknownParameter,
)
from pint_tpu.logging import log
from pint_tpu.models.parameter import (
    MJDParameter,
    Parameter,
    boolParameter,
    floatParameter,
    intParameter,
    maskParameter,
    prefixParameter,
    strParameter,
)
from pint_tpu.phase import Phase

__all__ = ["Component", "DelayComponent", "PhaseComponent", "TimingModel",
           "DEFAULT_ORDER", "OFFSET_PRIOR_WEIGHT"]

#: Variance [s^2] of the uninformative prior on the marginalized overall
#: phase offset (``augment_basis_for_offset``).  1e10 s^2, not the
#: reference/enterprise 1e40: the weight flows into jitted Woodbury graphs,
#: and TPU f64 emulation has float32 RANGE, so sqrt(1e40)-scaled basis
#: columns overflow to inf on device (measured round 5,
#: tools/tpu_chi2_isolate.py).  Still uninformative by ~26 orders for a
#: 4e15 s^-2 information content; note logdet/lnlikelihood carry the
#: (arbitrary) additive constant log(weight)/2 of this improper prior, so
#: absolute lnlikelihood values differ from enterprise's by a constant that
#: cancels in every likelihood ratio.
OFFSET_PRIOR_WEIGHT = 1e10

#: Delay/phase component evaluation order (matches the reference semantics)
DEFAULT_ORDER = [
    "astrometry",
    "jump_delay",
    "troposphere",
    "solar_system_shapiro",
    "solar_wind",
    "solar_windx",
    "dispersion_constant",
    "dispersion_dmx",
    "dispersion_jump",
    "chromatic_constant",
    "chromatic_cmx",
    "pulsar_system",
    "frequency_dependent",
    "fdjump",
    "absolute_phase",
    "spindown",
    "glitch",
    "piecewise_spindown",
    "phase_jump",
    "wave",
    "wavex",
    "dmwavex",
    "cmwavex",
    "ifunc",
]

DAY_S = 86400.0


def check_contiguous_indices(idxs, component: str, prefix: str, start: int = 0):
    """Raise MissingParameter unless *idxs* is exactly [start, start+1, ...]
    — gaps (or duplicates) in a Taylor/prefix family silently renumber which
    coefficients are used, so they must be an error."""
    from pint_tpu.exceptions import MissingParameter as _MP

    expected = list(range(start, start + len(idxs)))
    if sorted(idxs) != expected:
        missing = sorted(set(range(start, max(idxs) + 1)) - set(idxs))
        bad = missing[0] if missing else max(idxs)
        raise _MP(component, f"{prefix}{bad}",
                  f"{prefix} terms must be contiguous from {prefix}{start}")


class Component:
    """Base class: a set of parameters + delay/phase/noise contributions."""

    register = False
    category = ""
    component_types: Dict[str, type] = {}

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        if cls.__dict__.get("register", False):
            Component.component_types[cls.__name__] = cls

    def __init__(self):
        self.params: List[str] = []
        self._params_dict: Dict[str, Parameter] = {}
        self._parent: Optional["TimingModel"] = None

    # -- parameter management ---------------------------------------------
    def add_param(self, param: Parameter, setup: bool = False):
        self._params_dict[param.name] = param
        param._component = self
        if param.name not in self.params:
            self.params.append(param.name)
        if setup:
            self.setup()
        return param

    def remove_param(self, name: str):
        self._params_dict.pop(name, None)
        if name in self.params:
            self.params.remove(name)

    def __getattr__(self, name):
        d = object.__getattribute__(self, "__dict__").get("_params_dict", {})
        if name in d:
            return d[name]
        raise AttributeError(f"{type(self).__name__} has no attribute {name!r}")

    @property
    def free_params_component(self) -> List[str]:
        return [p for p in self.params if not self._params_dict[p].frozen]

    def setup(self):
        """Called after parameters are set; build prefix lists etc."""

    def validate(self):
        """Raise if required parameters are missing/invalid."""

    def match_param_alias(self, key: str) -> Optional[str]:
        for name, p in self._params_dict.items():
            if p.name_matches(key):
                return name
        return None

    def get_prefix_mapping_component(self, prefix: str) -> Dict[int, str]:
        """{index: parameter name} for every ``PREFIX<idx>`` parameter on this
        component (reference ``timing_model.py get_prefix_mapping_component``)."""
        out = {}
        for name in self.params:
            if name.startswith(prefix) and name[len(prefix):].isdigit():
                out[int(name[len(prefix):])] = name
        return dict(sorted(out.items()))

    # -- reference user-API long tail (timing_model.py Component) -----------
    @property
    def aliases_map(self) -> Dict[str, str]:
        """{alias or name: parameter name} for this component (reference
        ``timing_model.py aliases_map``)."""
        out: Dict[str, str] = {}
        for name, p in self._params_dict.items():
            out[name] = name
            for a in p.aliases:
                out[a] = name
        return out

    def match_param_aliases(self, alias: str) -> str:
        """Resolve an alias to this component's parameter name; raises
        UnknownParameter when nothing matches (reference
        ``timing_model.py match_param_aliases``; the lenient
        None-returning form is :meth:`match_param_alias`)."""
        hit = self.match_param_alias(alias)
        if hit is None:
            raise UnknownParameter(
                f"{alias!r} is not a parameter or alias of "
                f"{type(self).__name__}")
        return hit

    def get_params_of_type(self, param_type: str) -> List[str]:
        """Parameter names whose class matches ``param_type`` (e.g.
        'floatParameter', 'maskParameter'; reference
        ``timing_model.py get_params_of_type``)."""
        want = param_type.lower()
        return [n for n, p in self._params_dict.items()
                if type(p).__name__.lower() == want]

    @property
    def param_prefixs(self) -> Dict[str, List[str]]:
        """{prefix: [parameter names]} for prefixed families (reference
        spelling ``param_prefixs``)."""
        out: Dict[str, List[str]] = {}
        for n, p in self._params_dict.items():
            pre = getattr(p, "prefix", None)
            if pre:
                out.setdefault(pre, []).append(n)
        return out

    def is_in_parfile(self, parfile_dict) -> bool:
        """True when the parsed par-file keys select this component
        (reference ``timing_model.py is_in_parfile``)."""
        keys = {str(k).upper() for k in parfile_dict}
        amap = {a.upper() for a in self.aliases_map}
        return bool(keys & amap)

    def param_help(self) -> str:
        """Help text for this component's parameters."""
        lines = [f"Component {type(self).__name__}:"]
        for n in self.params:
            p = self._params_dict[n]
            lines.append(f"  {n:<15} {p.units or '':<12} "
                         f"{p.description or ''}")
        return "\n".join(lines) + "\n"

    def print_par(self, format: str = "pint") -> str:
        """Par-file lines for this component's set parameters (reference
        ``timing_model.py print_par``)."""
        return "".join(self._params_dict[n].as_parfile_line(format=format)
                       for n in self.params)

    def register_deriv_funcs(self, func, param: str) -> None:
        """Accepted for reference compatibility and intentionally inert:
        design-matrix columns come from jax.jacfwd of the phase/delay
        functions, so a hand-registered derivative is superseded by
        autodiff of the same quantity (reference
        ``timing_model.py register_deriv_funcs``)."""
        log.debug(f"register_deriv_funcs({param}): ignored — derivatives "
                  "come from autodiff in this framework")

    def set_special_params(self, spec_params: List) -> None:
        """Add dynamically-created parameters (mask/prefix family members)
        to this component (reference ``timing_model.py set_special_params``)."""
        for p in spec_params:
            if p.name not in self.params:
                self.add_param(p)

    def validate_toas(self, toas) -> None:
        """Hook: raise when the TOAs lack data this component needs
        (reference ``timing_model.py validate_toas``); default is no
        requirement."""

    # -- host-side evaluation context ---------------------------------------
    def build_context(self, toas) -> dict:
        """Precompute static per-TOAs data (masks, selections) for the trace."""
        return {}


class DelayComponent(Component):
    kind = "delay"

    def barycentric_freq(self, pv, batch):
        """Observing frequency Doppler-shifted to the SSB when an astrometry
        component provides it; topocentric otherwise.  Single shared
        implementation for every frequency-dependent delay component."""
        parent = self._parent
        if parent is not None:
            for comp in parent.components.values():
                if hasattr(comp, "barycentric_radio_freq"):
                    return comp.barycentric_radio_freq(pv, batch)
        return batch.freq

    def delay_func(self, pv, batch, ctx, acc_delay):
        """Return (N,) float64 delay seconds. ``acc_delay`` is the summed
        delay of all earlier components (barycentring chain)."""
        raise NotImplementedError


class PhaseComponent(Component):
    kind = "phase"

    def phase_func(self, pv, batch, ctx, delay):
        """Return a Phase contribution given the total delay (seconds)."""
        raise NotImplementedError


class TimingModel:
    """Container of components with compiled pure-function evaluation."""

    def __init__(self, name: str = "", components: Optional[List[Component]] = None):
        self.name = name
        self.components: Dict[str, Component] = {}
        self.top_level_params: List[str] = []
        self._top_params_dict: Dict[str, Parameter] = {}
        for p in [
            strParameter("PSR", description="Pulsar name", aliases=["PSRJ", "PSRB"]),
            strParameter("EPHEM", description="Solar-system ephemeris"),
            strParameter("CLOCK", description="Timescale (e.g. TT(BIPM2021))", aliases=["CLK"]),
            strParameter("UNITS", description="Timescale units (TDB/TCB)"),
            strParameter("TIMEEPH", description="Time ephemeris (FB90/IF99)"),
            strParameter("T2CMETHOD", description="Terrestrial->celestial method"),
            strParameter("BINARY", description="Binary model name"),
            boolParameter("DILATEFREQ", value=False, description="tempo2 DILATEFREQ"),
            boolParameter("PLANET_SHAPIRO", value=False, description="Include planet Shapiro delays"),
            MJDParameter("START", description="Start of fit range"),
            MJDParameter("FINISH", description="End of fit range"),
            floatParameter("RM", units="rad m^-2", description="Rotation measure"),
            strParameter("INFO", description="Info flag"),
            floatParameter("CHI2", units="", description="Fit chi2"),
            floatParameter("CHI2R", units="", description="Reduced chi2"),
            floatParameter("TRES", units="us", description="TOA residual RMS"),
            floatParameter("DMRES", units="pc/cm3", description="DM residual RMS"),
            intParameter("NTOA", description="Number of TOAs"),
            intParameter("EPHVER", description="Ephemeris version (ignored)"),
            strParameter("DMDATA", description="Wideband DM data flag"),
        ]:
            self._top_params_dict[p.name] = p
            self.top_level_params.append(p.name)
        self._cache: Dict[tuple, dict] = {}
        for c in components or []:
            self.add_component(c, validate=False)

    # ------------------------------------------------------------------
    # component management
    # ------------------------------------------------------------------
    def add_component(self, comp: Component, order: Optional[List[str]] = None,
                      validate: bool = True):
        self.components[type(comp).__name__] = comp
        comp._parent = self
        if validate:
            comp.setup()
            comp.validate()
        self._cache.clear()

    def remove_component(self, name: str):
        comp = self.components.pop(name)
        comp._parent = None
        self._cache.clear()

    def sorted_components(self, kind: str) -> List[Component]:
        comps = [c for c in self.components.values() if getattr(c, "kind", None) == kind]
        order = {cat: i for i, cat in enumerate(DEFAULT_ORDER)}
        return sorted(comps, key=lambda c: order.get(c.category, len(order)))

    @property
    def delay_components(self) -> List[Component]:
        return self.sorted_components("delay")

    @property
    def phase_components(self) -> List[Component]:
        return self.sorted_components("phase")

    @property
    def noise_components(self) -> List[Component]:
        return [c for c in self.components.values() if getattr(c, "kind", None) == "noise"]

    def setup(self):
        for c in self.components.values():
            c.setup()
        self._cache.clear()

    def validate(self, allow_tcb: bool = False):
        units = getattr(self, "UNITS", None)
        if units is not None and units.value not in (None, "TDB", "TCB"):
            raise TimingModelError(f"UNITS={units.value} not supported")
        if units is not None and units.value == "TCB" and not allow_tcb:
            raise TimingModelError(
                "TCB par files must be converted to TDB (use convert_tcb_tdb)"
            )
        for c in self.components.values():
            c.validate()

    def validate_toas(self, toas):
        for c in self.components.values():
            if hasattr(c, "validate_toas"):
                c.validate_toas(toas)

    # ------------------------------------------------------------------
    # parameter access
    # ------------------------------------------------------------------
    def __getattr__(self, name):
        d = object.__getattribute__(self, "__dict__")
        top = d.get("_top_params_dict", {})
        if name in top:
            return top[name]
        for comp in d.get("components", {}).values():
            if name in comp._params_dict:
                return comp._params_dict[name]
        # forward component *methods* (add_DMX_range, add_swx_range, ...) the
        # way the reference TimingModel does (reference ``timing_model.py``
        # __getattr__ component delegation) — but only methods a subclass
        # introduces; base-class machinery (add_param, build_context, ...)
        # must not silently bind to an arbitrary component
        for comp in d.get("components", {}).values():
            if callable(getattr(type(comp), name, None)) \
                    and getattr(Component, name, None) is None \
                    and getattr(DelayComponent, name, None) is None \
                    and getattr(PhaseComponent, name, None) is None:
                return getattr(comp, name)
        raise AttributeError(f"TimingModel has no parameter or attribute {name!r}")

    def __getitem__(self, name) -> Parameter:
        return getattr(self, name)

    def __contains__(self, name) -> bool:
        try:
            getattr(self, name)
            return True
        except AttributeError:
            return False

    @property
    def params(self) -> List[str]:
        out = list(self.top_level_params)
        for comp in self.components.values():
            out += comp.params
        return out

    @property
    def free_params(self) -> List[str]:
        return [p for p in self.params if p not in self.top_level_params
                and not getattr(self, p).frozen]

    @free_params.setter
    def free_params(self, names: List[str]):
        names = set(names)
        unknown = names - set(self.params)
        if unknown:
            raise UnknownParameter(f"Unknown parameters: {sorted(unknown)}")
        for p in self.params:
            if p in self.top_level_params:
                continue
            getattr(self, p).frozen = p not in names
        self._cache.clear()

    @property
    def fittable_params(self) -> List[str]:
        return [p for p in self.params
                if p not in self.top_level_params and getattr(self, p).continuous]

    def get_params_of_type(self, kind: str) -> List[str]:
        cls = {"maskParameter": maskParameter, "prefixParameter": prefixParameter,
               "MJDParameter": MJDParameter, "floatParameter": floatParameter}[kind]
        return [p for p in self.params if isinstance(getattr(self, p), cls)]

    def get_prefix_list(self, prefix: str, start_index: int = 0) -> List[float]:
        """Contiguous values [PREFIX0, PREFIX1, ...] (reference
        ``timing_model.py get_prefix_list``)."""
        out = []
        i = start_index
        while True:
            name = f"{prefix}{i}"
            try:
                p = getattr(self, name)
            except AttributeError:
                break
            out.append(p.value if p.value is not None else 0.0)
            i += 1
        return out

    @property
    def is_binary(self) -> bool:
        """Does the model describe a binary pulsar? (reference
        ``timing_model.py:853``)"""
        return any(type(c).__name__.startswith("Binary")
                   for c in self.components.values())

    @property
    def params_ordered(self) -> List[str]:
        """Alias of :attr:`params` (reference keeps both; ours is already
        in component order)."""
        return self.params

    def keys(self) -> List[str]:
        return self.params

    def items(self):
        return [(p, getattr(self, p)) for p in self.params]

    def get_params_dict(self, which: str = "free",
                        kind: str = "value") -> Dict[str, object]:
        """{name: value|uncertainty|parameter} for free or all parameters
        (reference ``timing_model.py get_params_dict``)."""
        if which == "free":
            names = self.free_params
        elif which == "all":
            names = [p for p in self.params
                     if p not in self.top_level_params]
        else:
            raise ValueError(f"Unknown which {which!r}")
        out = {}
        for p in names:
            par = getattr(self, p)
            if kind == "value":
                out[p] = par.value
            elif kind == "uncertainty":
                out[p] = par.uncertainty
            elif kind in ("quantity", "parameter"):
                out[p] = par
            else:
                raise ValueError(f"Unknown kind {kind!r}")
        return out

    def get_params_mapping(self) -> Dict[str, str]:
        """{parameter: component name} (reference ``get_params_mapping``)."""
        out = {p: "TimingModel" for p in self.top_level_params}
        for name, comp in self.components.items():
            for p in comp.params:
                out[p] = name
        return out

    def set_param_values(self, values: Dict[str, float]) -> None:
        """Bulk-assign parameter values (reference ``set_param_values``)."""
        for p, v in values.items():
            getattr(self, p).value = v
        self._cache.clear()

    def set_param_uncertainties(self, values: Dict[str, float]) -> None:
        for p, v in values.items():
            getattr(self, p).uncertainty = v

    def find_empty_masks(self, toas, freeze: bool = False) -> List[str]:
        """Mask parameters selecting zero TOAs (reference
        ``find_empty_masks``): these make the fit singular; with
        ``freeze=True`` they are frozen on the spot."""
        out = []
        for p in self.params:
            par = getattr(self, p)
            if isinstance(par, maskParameter) and not par.frozen:
                if len(par.select_toa_mask(toas)) == 0:
                    out.append(p)
                    if freeze:
                        log.info(f"'{p}' has no TOAs so freezing")
                        par.frozen = True
        return out

    def delete_jump_and_flags(self, toas, jump_num: int) -> None:
        """Remove JUMP<jump_num> and its -gui_jump flags (reference
        ``delete_jump_and_flags``; pintk jump workflow).  Pass the TOAs
        whose flags were stamped by ``add_jump``/``jump_params_to_flags``,
        or None to edit the model only."""
        comp = self.components.get("PhaseJump")
        name = f"JUMP{jump_num}"
        if comp is None or name not in comp._params_dict:
            raise ValueError(f"No {name} in the model")
        comp.remove_param(name)
        comp.setup()
        if not comp.jumps:
            self.remove_component("PhaseJump")
        if toas is not None:
            for fl in toas.flags:
                # both flag conventions: -gui_jump (pintk add_jump) and
                # -jump (jump_params_to_flags)
                if fl.get("gui_jump") == str(jump_num):
                    del fl["gui_jump"]
                if fl.get("jump") == str(jump_num):
                    del fl["jump"]
            toas._version += 1
        self._cache.clear()

    def add_tzr_toa(self, toas) -> None:
        """Attach an AbsPhase component with the TZR anchored on the first
        TOA when none exists (reference ``add_tzr_toa``)."""
        from pint_tpu.models.absolute_phase import AbsPhase

        if "AbsPhase" in self.components:
            return
        comp = AbsPhase()
        self.add_component(comp, validate=False)
        mjd = float(np.asarray(toas.get_mjds())[0])
        self.TZRMJD.value = mjd
        self.TZRSITE.value = str(toas.obs[0])
        f = float(np.asarray(toas.freq_mhz)[0])
        self.TZRFRQ.value = f if np.isfinite(f) else 0.0
        self.setup()

    def total_dispersion_slope(self, toas) -> np.ndarray:
        """Total DM converted to dispersion slope [s MHz^2] (reference
        ``total_dispersion_slope``)."""
        from pint_tpu import DMconst

        return np.asarray(self.total_dm(toas)) * DMconst

    def get_prefix_mapping(self, prefix: str) -> Dict[int, str]:
        """{index: name} over all components for ``PREFIX<idx>`` parameters
        (reference ``timing_model.py get_prefix_mapping``); raises ValueError
        when no component carries the prefix."""
        out: Dict[int, str] = {}
        for comp in self.components.values():
            out.update(comp.get_prefix_mapping_component(prefix))
        if not out:
            raise ValueError(f"Cannot find prefix {prefix!r} in the model")
        return dict(sorted(out.items()))

    def match_param_aliases(self, key: str) -> str:
        for p in self.top_level_params:
            if self._top_params_dict[p].name_matches(key):
                return p
        for comp in self.components.values():
            hit = comp.match_param_alias(key)
            if hit:
                return hit
        raise UnknownParameter(f"Unrecognized parfile parameter {key!r}")

    # ------------------------------------------------------------------
    # evaluation machinery
    # ------------------------------------------------------------------
    def _build_context(self, toas) -> dict:
        ctx = {}
        for name, comp in self.components.items():
            ctx[name] = comp.build_context(toas)
        return ctx

    def _get_compiled(self, toas, free_names: Tuple[str, ...]) -> dict:
        """Compiled evaluation bundle for (toas, free-parameter set).

        Two-level cache: the jitted functions take ``(values, batch, ctx)``
        as traced arguments, so mutated TOAs (simulation shifts, fit
        re-anchoring) reuse the same XLA executable; only the host-side
        batch/ctx pytrees are rebuilt (keyed by the TOAs' version counter).
        """
        import weakref

        fn_key = (free_names, len(toas))
        # weak-keyed so entries die with the TOAs object (no id-reuse
        # aliasing, no unbounded growth of retained device arrays)
        data = self._cache.setdefault("data", weakref.WeakKeyDictionary())
        ver = getattr(toas, "_version", 0)
        entry = data.get(toas)
        if entry is None or entry[0] != ver:
            entry = (ver, toas.to_batch(), self._build_context(toas))
            data[toas] = entry
        _, batch, ctx = entry

        if fn_key not in self._cache.setdefault("fns", {}):
            delay_comps = self.delay_components
            phase_comps = self.phase_components
            comp_names = {id(c): n for n, c in self.components.items()}

            def eval_fn(values, const_pv, batch, ctx):
                pv = dict(const_pv)
                for i, nm in enumerate(free_names):
                    pv[nm] = values[i]
                acc = jnp.zeros(batch.ntoas, dtype=jnp.float64)
                for comp in delay_comps:
                    acc = acc + comp.delay_func(pv, batch, ctx[comp_names[id(comp)]], acc)
                phase = Phase(jnp.zeros(batch.ntoas, dtype=jnp.float64),
                              jnp.zeros(batch.ntoas, dtype=jnp.float64))
                for comp in phase_comps:
                    phase = phase + comp.phase_func(pv, batch, ctx[comp_names[id(comp)]], acc)
                return phase, acc

            self._cache["fns"][fn_key] = {
                "eval": jax.jit(eval_fn),
                "jac_frac": jax.jit(jax.jacfwd(
                    lambda v, c, b, x: eval_fn(v, c, b, x)[0].frac, argnums=0)),
            }
        fns = self._cache["fns"][fn_key]
        const_pv = self._const_pv()
        return {
            "batch": batch,
            "ctx": ctx,
            "eval": lambda v: fns["eval"](v, const_pv, batch, ctx),
            "jac_frac": lambda v: fns["jac_frac"](v, const_pv, batch, ctx),
            "free_names": free_names,
        }

    def _const_pv(self) -> dict:
        """Current numeric parameter values as a pytree of traced leaves.

        Passed as a jit *argument* (not baked constants) so parameter-value
        edits — fitter steps, grid freezing, user tweaks — never serve a
        stale compiled function.  Epoch (MJD) parameters become DD scalars,
        preserving full precision through the trace.
        """
        out = {}
        for comp in self.components.values():
            for p in comp.params:
                par = comp._params_dict[p]
                if isinstance(par, strParameter) or isinstance(par, boolParameter):
                    continue
                v = par.value
                if isinstance(par, MJDParameter):
                    out[p] = dd_from_longdouble(
                        np.longdouble(v) if v is not None else np.longdouble(0.0))
                elif isinstance(v, (list, tuple)):
                    out[p] = jnp.asarray(v, dtype=jnp.float64)
                elif isinstance(v, (int, float)) or v is None:
                    out[p] = float(v) if v is not None else 0.0
        return out

    def _free_values(self, free_names) -> jnp.ndarray:
        return jnp.array([float(getattr(self, p).value or 0.0)
                          for p in free_names], dtype=jnp.float64)

    # -- public evaluation API ---------------------------------------------
    def delay(self, toas, cutoff_component: str = "", include_last: bool = True):
        """Total delay in seconds (float64 ndarray).

        ``cutoff_component`` truncates the ordered accumulation at the named
        component — the partial delay earlier components have produced when
        that component runs (reference ``timing_model.py:1565``'s
        cutoff/include_last semantics, used e.g. for barycentering: the
        delay *before* the binary model).
        """
        if not cutoff_component:
            c = self._get_compiled(toas, tuple(self.free_params))
            _, d = c["eval"](self._free_values(c["free_names"]))
            return np.asarray(d)
        comps = self.delay_components
        by_id = {id(cc): n for n, cc in self.components.items()}
        names = [by_id[id(cc)] for cc in comps]  # in evaluation order
        if cutoff_component not in names:
            raise ValueError(f"No delay component named {cutoff_component!r}")
        stop = names.index(cutoff_component) + (1 if include_last else 0)
        self._get_compiled(toas, tuple(self.free_params))  # warm batch/ctx
        entry = self._cache["data"][toas]
        batch, ctx = entry[1], entry[2]
        pv = dict(self._const_pv())
        for nm in self.free_params:
            pv[nm] = float(getattr(self, nm).value or 0.0)
        acc = jnp.zeros(batch.ntoas, dtype=jnp.float64)
        for name, comp in list(zip(names, comps))[:stop]:
            acc = acc + comp.delay_func(pv, batch, ctx[name], acc)
        return np.asarray(acc)

    def phase(self, toas, abs_phase: bool = False) -> Phase:
        """Model phase at each TOA (Phase pytree on host)."""
        c = self._get_compiled(toas, tuple(self.free_params))
        ph, _ = c["eval"](self._free_values(c["free_names"]))
        if abs_phase and "AbsPhase" in self.components:
            tzr = self.components["AbsPhase"].get_TZR_toas(self)
            ctz = self._get_compiled(tzr, tuple(self.free_params))
            tzph, _ = ctz["eval"](self._free_values(c["free_names"]))
            ph = ph - Phase(tzph.int_[0], tzph.frac[0])
        return ph

    def total_delay_and_phase(self, toas):
        c = self._get_compiled(toas, tuple(self.free_params))
        return c["eval"](self._free_values(c["free_names"]))

    def _frozen_fingerprint(self, free) -> tuple:
        """Values of the non-free continuous parameters: the linear-column
        cache must reseed when any of them is edited directly (a column
        linear in the FREE params can still be a function of frozen ones)."""
        free_set = set(free)
        out = []
        for comp in self.components.values():
            for p in comp.params:
                if p in free_set:
                    continue
                v = comp._params_dict[p].value
                if isinstance(v, (int, float)):
                    out.append((p, float(v)))
        return tuple(out)

    def _jac_frac_linear_cached(self, toas, free, c) -> np.ndarray:
        """d frac/d params with constant (linear-parameter) columns cached.

        Most NANOGrav-scale columns (DMX bins, jumps, FD) are exactly
        constant in the parameter values, and the reference profile shows
        the design matrix as the benchmark's dominant cost (SURVEY §6:
        68%).  Classification is LAZY: the first call costs exactly one
        Jacobian (one-shot fits pay nothing extra); the second call runs
        the ~1e-3-cycle probe to split columns, after which only the
        nonlinear subset is re-derived per call.

        Entries live in a WeakKeyDictionary keyed by the TOAs object (same
        anti-aliasing rationale as ``_get_compiled``'s data cache) and
        reseed when the TOAs version, the frozen-parameter values, or a
        free-parameter step beyond the probed envelope invalidates them.
        """
        import weakref

        values = np.asarray(self._free_values(free))
        store = self._cache.setdefault("lincols",
                                       weakref.WeakKeyDictionary())
        per_toas = store.get(toas)
        if per_toas is None:
            per_toas = {}
            store[toas] = per_toas
        ver = getattr(toas, "_version", 0)
        frozen = self._frozen_fingerprint(free)
        entry = per_toas.get(free)
        if entry is not None and (entry["ver"] != ver
                                  or entry["frozen"] != frozen):
            entry = None
        if entry is not None and entry["dp"] is not None and np.any(
                np.abs(values - entry["values0"]) > entry["dp"]):
            # the classification was only probed over a ~1e-3-cycle
            # envelope; a step that leaves it could expose curvature in a
            # "linear" column (converging fits leave it at most once)
            entry = None
        if entry is None:
            # lazy seed: one exact Jacobian, no probe yet
            J0 = np.asarray(c["jac_frac"](values))
            per_toas[free] = {"ver": ver, "frozen": frozen, "J0": J0,
                              "values0": values, "dp": None, "nl": None,
                              "sub_jac": None}
            return J0
        if entry["nl"] is None:
            # second call: classify now (the fit is iterating, so the
            # probe's cost amortizes from here on)
            from pint_tpu.utils import (classify_linear_columns,
                                        linearity_probe_steps)

            dp = linearity_probe_steps(entry["J0"])
            if np.any(np.abs(values - entry["values0"]) > dp):
                # first step already left the envelope: reseed at the new
                # values and stay lazy
                J0 = np.asarray(c["jac_frac"](values))
                per_toas[free] = {"ver": ver, "frozen": frozen, "J0": J0,
                                  "values0": values, "dp": None, "nl": None,
                                  "sub_jac": None}
                return J0
            # domain-aware probe: a combined step can leave a parameter's
            # physical domain (e.g. SINI past 1) and NaN the whole probe
            # Jacobian, which would classify EVERY column nonlinear.
            # Shrink until finite; columns still non-finite at the
            # smallest step stay conservatively nonlinear.
            dp_eff = np.where(np.isfinite(dp), dp, 0.0)
            for _ in range(4):
                J1 = np.asarray(c["jac_frac"](jnp.asarray(
                    entry["values0"] + dp_eff)))
                if np.all(np.isfinite(J1)):
                    break
                dp_eff = dp_eff / 8.0
            nl = classify_linear_columns(entry["J0"], J1)
            # the reuse envelope is what was ACTUALLY probed: a shrunk
            # probe validated flatness only over dp_eff, so steps beyond
            # it must reseed
            entry["dp"] = np.where(dp_eff > 0, dp_eff, dp)
            entry["nl"] = nl
            if len(nl):
                fns = self._cache["fns"][(free, len(toas))]
                eval_fn = fns["eval"]
                nl_idx = jnp.asarray(nl, dtype=jnp.int32)

                def sub_jac(vals, const_pv, batch, ctx):
                    def f(sub):
                        ph, _ = eval_fn(vals.at[nl_idx].set(sub), const_pv,
                                        batch, ctx)
                        return ph.frac
                    return jax.jacfwd(f)(vals[nl_idx])

                entry["sub_jac"] = jax.jit(sub_jac)
        J = entry["J0"].copy()
        if entry["sub_jac"] is not None:
            const_pv = self._const_pv()
            data_entry = self._cache["data"][toas]
            batch, ctx = data_entry[1], data_entry[2]
            J[:, entry["nl"]] = np.asarray(
                entry["sub_jac"](jnp.asarray(values), const_pv, batch, ctx))
        return J

    def designmatrix(self, toas, incfrozen: bool = False,
                     incoffset: bool = True, reuse_linear: bool = False):
        """(M, names, units): M columns are -d_phase_d_param/F0 [+ offset].

        Derivatives come from jax.jacfwd through the full (dd-precision)
        phase function — covering every continuous parameter with no
        hand-registered partials (reference ``timing_model.py:2174``).
        With ``reuse_linear=True`` (iterative fitters) constant columns are
        served from cache and only genuinely nonlinear ones recomputed —
        see :meth:`_jac_frac_linear_cached`.
        """
        free = self.design_param_names(incfrozen=incfrozen)
        c = self._get_compiled(toas, free)
        if reuse_linear:
            J = self._jac_frac_linear_cached(toas, free, c)
        else:
            J = np.asarray(c["jac_frac"](self._free_values(free)))  # (N, nfree)
        F0 = float(self.F0.value)
        incoffset = incoffset and "PhaseOffset" not in self.components
        names = (["Offset"] if incoffset else []) + list(free)
        ncols = len(names)
        M = np.zeros((len(toas), ncols))
        col = 0
        if incoffset:
            M[:, 0] = 1.0 / F0
            col = 1
        M[:, col:] = -J / F0
        units = ["s/s"] + [f"s/({getattr(self, p).units})" for p in free] if incoffset \
            else [f"s/({getattr(self, p).units})" for p in free]
        return M, names, units

    def design_param_names(self, incfrozen: bool = False) -> tuple:
        """Parameters that get design-matrix columns: continuous, non-epoch,
        non-noise (noise params enter via GP bases, not the timing M)."""
        return tuple(p for p in self.params
                     if p not in self.top_level_params
                     and (incfrozen or not getattr(self, p).frozen)
                     and getattr(self, p).continuous
                     and not isinstance(getattr(self, p), MJDParameter)
                     and not self._is_noise_param(p))

    def _is_noise_param(self, name: str) -> bool:
        par = getattr(self, name)
        comp = par._component
        return comp is not None and getattr(comp, "kind", None) == "noise"

    # -- wideband DM evaluation ---------------------------------------------
    def _dm_components(self) -> List[Component]:
        return [c for c in self.delay_components if hasattr(c, "dm_func")]

    def _get_compiled_dm(self, toas, free_names: Tuple[str, ...]) -> dict:
        """Compiled total-DM bundle, structured like ``_get_compiled`` but
        summing component ``dm_func`` contributions (reference
        ``timing_model.py:1645 total_dm``)."""
        base = self._get_compiled(toas, free_names)  # reuses batch/ctx caches
        fn_key = (free_names, len(toas))
        if fn_key not in self._cache.setdefault("dm_fns", {}):
            dm_comps = self._dm_components()
            comp_names = {id(c): n for n, c in self.components.items()}

            def dm_fn(values, const_pv, batch, ctx):
                pv = dict(const_pv)
                for i, nm in enumerate(free_names):
                    pv[nm] = values[i]
                dm = jnp.zeros(batch.ntoas, dtype=jnp.float64)
                for comp in dm_comps:
                    dm = dm + comp.dm_func(pv, batch, ctx[comp_names[id(comp)]])
                return dm

            self._cache["dm_fns"][fn_key] = {
                "dm": jax.jit(dm_fn),
                "jac_dm": jax.jit(jax.jacfwd(dm_fn, argnums=0)),
            }
        fns = self._cache["dm_fns"][fn_key]
        const_pv = self._const_pv()
        batch, ctx = base["batch"], base["ctx"]
        return {
            "dm": lambda v: fns["dm"](v, const_pv, batch, ctx),
            "jac_dm": lambda v: fns["jac_dm"](v, const_pv, batch, ctx),
            "free_names": free_names,
        }

    def total_dm(self, toas) -> np.ndarray:
        """Model DM at each TOA in pc/cm^3 (reference ``timing_model.py:1645``)."""
        c = self._get_compiled_dm(toas, tuple(self.free_params))
        return np.asarray(c["dm"](self._free_values(c["free_names"])))

    def d_dm_d_param(self, toas, param: str) -> np.ndarray:
        """d(total_dm)/d(param) via autodiff (reference ``timing_model.py:2140``)."""
        c = self._get_compiled_dm(toas, (param,))
        return np.asarray(c["jac_dm"](self._free_values((param,))))[:, 0]

    def dm_designmatrix(self, toas, incfrozen: bool = False, incoffset: bool = True):
        """(Md, names, units): DM-residual design matrix rows, column-aligned
        with :meth:`designmatrix` (zero Offset column; zero columns for
        parameters that do not affect DM)."""
        free = self.design_param_names(incfrozen=incfrozen)
        c = self._get_compiled_dm(toas, free)
        J = np.asarray(c["jac_dm"](self._free_values(free)))  # (N, nfree)
        incoffset = incoffset and "PhaseOffset" not in self.components
        names = (["Offset"] if incoffset else []) + list(free)
        M = np.zeros((len(toas), len(names)))
        M[:, 1 if incoffset else 0:] = J
        units = (["pc/cm3"] if incoffset else []) + \
            [f"pc/cm3/({getattr(self, p).units})" for p in free]
        return M, names, units

    def scaled_dm_uncertainty(self, toas) -> np.ndarray:
        """DMEFAC/DMEQUAD-scaled wideband DM uncertainties in pc/cm^3
        (reference ``timing_model.py:1722``)."""
        err = toas.get_dm_errors()
        if err is None:
            raise ValueError("TOAs have no wideband DM errors (-pp_dme flags)")
        err = np.asarray(err, dtype=np.float64)
        for c in self.noise_components:
            if hasattr(c, "scale_dm_sigma"):
                err = c.scale_dm_sigma(self, toas, err)
        return err

    def d_phase_d_param(self, toas, delay, param: str) -> np.ndarray:
        """Numerical-free analytic derivative via autodiff (for reference-API
        parity, ``timing_model.py:2005``)."""
        c = self._get_compiled(toas, (param,))
        J = c["jac_frac"](self._free_values((param,)))
        return np.asarray(J)[:, 0]

    def d_phase_d_param_num(self, toas, param: str, step: float = 1e-2) -> np.ndarray:
        """Finite-difference derivative (reference ``timing_model.py:2079``).

        ``step`` is relative to the parameter value (absolute when zero).
        The int and frac phase parts are differenced separately: their sum at
        ~1e9 cycles would lose the sub-cycle signal to float64 cancellation.
        """
        par = getattr(self, param)
        v0 = float(par.value or 0.0)
        h = abs(v0) * step if v0 != 0 else step
        phases = []
        for v in (v0 + h, v0 - h):
            par.value = v
            phases.append(self.phase(toas))
        par.value = v0
        d = (np.asarray(phases[0].int_) - np.asarray(phases[1].int_)) + (
            np.asarray(phases[0].frac) - np.asarray(phases[1].frac))
        return d / (2 * h)

    def get_derived_params(self, rms: Optional[float] = None,
                           ntoas: Optional[int] = None,
                           returndict: bool = False):
        """Human-readable block of derived quantities with 1-sigma
        uncertainties (reference ``timing_model.py:3171``).

        ``rms`` [us] and ``ntoas`` enable the ELL1 validity check.  Instead
        of the reference's ``uncertainties`` package, errors propagate
        through each formula by jax autodiff of the closed-form expression
        (linear propagation, independent errors).  Returns the string, or
        ``(string, dict)`` with ``returndict=True``; dict values are
        ``(value, sigma)`` pairs (sigma 0.0 where no propagation is
        defined), except ``"Binary"`` which is the component name string.
        """
        import jax

        from pint_tpu import derived_quantities as dq

        def up(fn, names):
            """(value, sigma) of fn(*param_values) via jax.grad."""
            vals = np.array([float(getattr(self, n).value) for n in names])
            errs = np.array([float(getattr(self, n).uncertainty or 0.0)
                             for n in names])
            v = float(fn(*vals))
            if not np.any(errs):
                return v, 0.0
            g = np.asarray(jax.grad(lambda xs: fn(*xs))(jnp.asarray(vals)))
            # a singular gradient (e.g. arctan2 at the origin) contributes
            # nothing where the corresponding uncertainty is zero
            terms = np.where(errs == 0.0, 0.0, g * errs)
            return v, float(np.sqrt(np.sum(terms**2)))

        def fmt(v, e, unit=""):
            u = f" {unit}" if unit else ""
            return f"{v:.12g} +/- {e:.3g}{u}" if e else f"{v:.12g}{u}"

        out = {}
        s = "Derived Parameters:\n"
        if "F0" in self and self.F0.value is not None:
            p, pe = up(lambda f0: 1.0 / f0, ["F0"])
            out["P (s)"] = (p, pe)
            s += f"Period = {fmt(p, pe, 's')}\n"
            if "F1" in self and self.F1.value is not None:
                pd, pde = up(lambda f0, f1: -f1 / f0**2, ["F0", "F1"])
                out["Pdot (s/s)"] = (pd, pde)
                s += f"Pdot = {fmt(pd, pde)}\n"
                f0v, f1v = float(self.F0.value), float(self.F1.value)
                if f1v < 0.0:
                    out["age"] = (dq.pulsar_age(f0v, f1v), 0.0)
                    out["B"] = (dq.pulsar_B(f0v, f1v), 0.0)
                    out["Blc"] = (dq.pulsar_B_lightcyl(f0v, f1v), 0.0)
                    out["Edot"] = (dq.pulsar_edot(f0v, f1v), 0.0)
                    s += (f"Characteristic age = {out['age'][0]:.4g} yr "
                          "(braking index = 3)\n")
                    s += f"Surface magnetic field = {out['B'][0]:.3g} G\n"
                    s += ("Magnetic field at light cylinder = "
                          f"{out['Blc'][0]:.4g} G\n")
                    s += (f"Spindown Edot = {out['Edot'][0]:.4g} erg/s "
                          "(I=1e45 g cm^2)\n")
                else:
                    s += "Not computing Age, B, or Edot since F1 > 0.0\n"
        if "PX" in self and self.PX.value and not self.PX.frozen:
            # PX in mas -> distance in pc
            d, de = up(lambda px: 1000.0 / px, ["PX"])
            out["Dist (pc)"] = (d, de)
            s += f"\nParallax distance = {fmt(d, de, 'pc')}\n"
        if self.is_binary:
            binary = next(n for n in self.components if n.startswith("Binary"))
            out["Binary"] = binary
            s += f"\nBinary model {binary}\n"
            bcomp = self.components[binary]
            pb, pbe = bcomp.pb()
            pbe = float(pbe or 0.0)
            out["PB (d)"] = (pb, pbe)
            s += f"Orbital Period  (PB) = {fmt(pb, pbe, 'd')}\n"
            pbdot = bcomp.pbdot_pair()
            if pbdot is not None:
                out["PBDOT (s/s)"] = pbdot
                s += f"Orbital Pdot (PBDOT) = {fmt(*pbdot)}\n"
            ell1 = binary.startswith("BinaryELL1")
            if ell1:
                s += "Conversion from ELL1 parameters:\n"
                ecc = up(lambda e1, e2: jnp.hypot(e1, e2), ["EPS1", "EPS2"])
                om = up(lambda e1, e2: jnp.rad2deg(jnp.arctan2(e1, e2))
                        % 360.0, ["EPS1", "EPS2"])
                out["ECC"], out["OM (deg)"] = ecc, om
                s += f"ECC = {fmt(*ecc)}\nOM  = {fmt(*om, 'deg')}\n"
                t0v = float(self.TASC.value) + pb * om[0] / 360.0
                t0e = float(np.hypot(float(self.TASC.uncertainty or 0.0),
                                     pb * om[1] / 360.0))
                out["T0"] = (t0v, t0e)
                s += f"T0  = {fmt(t0v, t0e)}\n"
                if rms is not None and ntoas is not None:
                    from pint_tpu.utils import ELL1_check
                    s += ELL1_check(float(self.A1.value), ecc[0], rms, ntoas,
                                    outstring=True)
                s += "\n"
            eccv = out["ECC"][0] if ell1 else float(self.ECC.value or 0.0)
            tsun = dq.TSUN_S
            if self.A1.value is not None and not self.A1.frozen:
                fm = up(lambda a1: 4.0 * jnp.pi**2 * a1**3
                        / (tsun * (pb * 86400.0) ** 2), ["A1"])
                out["Mass Function (Msun)"] = fm
                s += f"Mass function = {fmt(*fm, 'Msun')}\n"
                mcmed = dq.companion_mass(pb, float(self.A1.value), i_deg=60.0)
                mcmin = dq.companion_mass(pb, float(self.A1.value), i_deg=90.0)
                out["Mc,med (Msun)"] = (mcmed, 0.0)
                out["Mc,min (Msun)"] = (mcmin, 0.0)
                s += ("Min / Median Companion mass (assuming Mpsr = 1.4 Msun)"
                      f" = {mcmin:.4f} / {mcmed:.4f} Msun\n")
            if "OMDOT" in self and self.OMDOT.value:
                mt = up(lambda od: (od * jnp.pi / 180.0 / 86400.0 / 365.25
                                    / (3.0 * tsun ** (2.0 / 3.0)
                                       * (pb * 86400.0 / (2 * jnp.pi))
                                       ** (-5.0 / 3.0)
                                       / (1.0 - eccv**2))) ** 1.5, ["OMDOT"])
                out["Mtot (Msun)"] = mt
                s += f"Total mass, assuming GR, from OMDOT is {fmt(*mt, 'Msun')}\n"
            if "SINI" in self and self.SINI.value is not None \
                    and 0.0 <= float(self.SINI.value) < 1.0 \
                    and self.M2.value is not None:
                if not self.SINI.frozen:
                    cosi = up(lambda si: jnp.sqrt(1.0 - si**2), ["SINI"])
                    inc = up(lambda si: jnp.rad2deg(jnp.arcsin(si)), ["SINI"])
                    s += "From SINI in model:\n"
                    s += f"    cos(i) = {fmt(*cosi)}\n"
                    s += f"    i = {fmt(*inc, 'deg')}\n"
                mp = dq.pulsar_mass(pb, float(self.A1.value),
                                    float(self.M2.value),
                                    float(np.degrees(np.arcsin(
                                        float(self.SINI.value)))))
                out["Mp (Msun)"] = (mp, 0.0)
                s += f"Pulsar mass (Shapiro Delay) = {mp:.4f} Msun"
        return (s, out) if returndict else s

    def d_phase_d_toa(self, toas, sample_step: Optional[float] = None
                      ) -> np.ndarray:
        """Topocentric spin frequency [Hz]: central-difference derivative of
        phase with respect to arrival time (reference ``timing_model.py:1962``).

        ``sample_step`` is the half-step in seconds; the default is two spin
        periods, matching the reference, so the O(h^2) truncation error is
        ~F2-sized.  The shifted evaluations re-derive the observatory state
        at the displaced epochs so the Roemer-rate (Doppler, ~1e-4
        fractional) term enters the derivative; the int and frac phase parts
        are differenced separately to dodge float64 cancellation at ~1e9
        absolute cycles.
        """
        import copy as _copy

        h = (2.0 / float(self.F0.value) if sample_step is None
             else float(sample_step))
        phases = []
        for sgn in (-1.0, 1.0):
            t = _copy.deepcopy(toas)
            t.adjust_TOAs(np.full(t.ntoas, sgn * h))
            if t.ssb_obs_pos_km is not None:
                # adjust_TOAs shifts utc+tdb in lockstep (dTDB/dUTC deviates
                # from 1 by ~1e-8, i.e. ~1e-11 s over a 2-period step —
                # far below the h^2 truncation term); only the ephemeris
                # state needs re-deriving at the displaced epochs
                t.compute_posvels(ephem=t.ephem, planets=t.planets)
            phases.append(self.phase(t, abs_phase=False))
        dp_int = np.asarray(phases[1].int_) - np.asarray(phases[0].int_)
        dp_frac = np.asarray(phases[1].frac) - np.asarray(phases[0].frac)
        return (dp_int + dp_frac) / (2.0 * h)

    # ------------------------------------------------------------------
    # convenience physics accessors
    # ------------------------------------------------------------------
    def get_barycentric_toas(self, toas):
        """Barycentric TOA MJDs (longdouble) = TDB - delay(non-binary)."""
        d = self.delay(toas)
        return toas.tdb - np.asarray(d, dtype=np.longdouble) / np.longdouble(DAY_S)

    def scaled_toa_uncertainty(self, toas) -> np.ndarray:
        """EFAC/EQUAD-scaled TOA uncertainties in seconds."""
        err = np.asarray(toas.error_us) * 1e-6
        for c in self.noise_components:
            if hasattr(c, "scale_toa_sigma"):
                err = c.scale_toa_sigma(self, toas, err)
        return err

    def psr_direction(self) -> np.ndarray:
        """Unit vector SSB -> pulsar (ICRS) at POSEPOCH/PEPOCH — the
        catalog engine's sky entry point: Hellings-Downs angular
        separations between array pulsars are arccos of these vectors'
        pairwise dot products (:mod:`pint_tpu.catalog.crosscorr`).
        Raises :class:`~pint_tpu.exceptions.MissingComponent` when the
        model carries no astrometry component to take a position from."""
        from pint_tpu.exceptions import MissingComponent
        from pint_tpu.models.astrometry import Astrometry

        for c in self.components.values():
            if isinstance(c, Astrometry):
                return np.asarray(c.ssb_to_psb_xyz_ICRS(), dtype=np.float64)
        raise MissingComponent(
            f"{getattr(self, 'name', '?')}: no astrometry component — "
            "cross-pulsar correlations need a sky position")

    def toa_covariance_matrix(self, toas) -> np.ndarray:
        """Full N x N TOA covariance (diag sigma^2 + correlated terms)."""
        sigma = self.scaled_toa_uncertainty(toas)
        cov = np.diag(sigma**2)
        U, w = self.noise_model_basis_weight(toas)
        if U is not None:
            cov = cov + (U * w) @ U.T
        return cov

    def noise_model_designmatrix(self, toas):
        Us, _, _ = self.noise_basis_by_component(toas)
        return np.hstack(Us) if Us else None

    def noise_model_basis_weight(self, toas):
        Us, ws, _ = self.noise_basis_by_component(toas)
        if not Us:
            return None, None
        return np.hstack(Us), np.concatenate(ws)

    def augment_basis_for_offset(self, U, w, n: Optional[int] = None):
        """Marginalize the overall phase offset: append a ones column with
        an uninformative prior when no explicit PhaseOffset parameter
        is fitted (reference ``residuals.py:600-604``).  Single source of
        truth for every correlated chi2/likelihood evaluation — the grid
        kernel, ``Residuals``, and the noise likelihood must stay
        definitionally identical.

        The prior weight is 1e10 s^2, not the reference/enterprise 1e40:
        this weight flows into jitted Woodbury graphs, and on TPU f64 is
        emulated with float32-RANGE arithmetic, so sqrt(1e40)-scaled basis
        columns overflow to inf mid-graph (measured round 5,
        tools/tpu_chi2_isolate.py).  1e10 s^2 is still uninformative by
        ~26 orders: the marginalized offset shrinks by 1/(w * sum(1/sigma^2))
        ~ 2.5e-26 for the B1855 workload, far below f64 resolution."""
        if "PhaseOffset" in self.components:
            return np.asarray(U), np.asarray(w)
        n = len(U) if n is None else n
        return (np.hstack([np.asarray(U), np.ones((n, 1))]),
                np.concatenate([np.asarray(w), [OFFSET_PRIOR_WEIGHT]]))

    def full_designmatrix(self, toas):
        """[timing M | noise basis] (reference ``timing_model.py:1752``)."""
        M, names, units = self.designmatrix(toas)
        U = self.noise_model_designmatrix(toas)
        if U is None:
            return M, names, units
        return np.hstack([M, U]), names, units

    def full_basis_weight(self, toas) -> np.ndarray:
        """Weights for the full design matrix: 1e40 (uninformative, matching
        enterprise) for timing columns, GP weights for noise columns
        (reference ``timing_model.py:1777``).  HOST-ONLY: 1e40-scale weights
        overflow TPU f64 emulation's float32 range inside jitted graphs —
        use ``OFFSET_PRIOR_WEIGHT`` semantics (see its docstring) for
        anything that flows on-device."""
        phi_tm = np.full(self.ntmpar, 1e40)  # jaxlint: disable=f32-unsafe-literal -- HOST-ONLY by contract (docstring)
        _, w = self.noise_model_basis_weight(toas)
        return phi_tm if w is None else np.concatenate([phi_tm, w])

    def noise_basis_by_component(self, toas):
        """One host pass over the correlated-noise components: returns
        (bases list, weights list, {component: (offset, size)}).  Single
        source of truth for the column layout used by
        ``noise_model_basis_weight``/``noise_model_dimensions``.

        Cached per (TOAs version, noise parameter values): fitters and the
        grid rebuild these bases several times per call, and the ECORR
        quantization + Fourier matrices are O(N_toa * n_basis) host work.
        """
        import weakref

        comps = [(n, c) for n, c in self.components.items()
                 if getattr(c, "kind", None) == "noise"
                 and hasattr(c, "basis_weight_pair")]
        pkey = tuple(
            (name, p, str(c._params_dict[p].value))
            for name, c in comps for p in c.params
        )
        cache = self._cache.setdefault("noise_basis", weakref.WeakKeyDictionary())
        ver = getattr(toas, "_version", 0)
        hit = cache.get(toas)
        if hit is not None and hit[0] == (ver, pkey):
            return hit[1]
        Us, ws, dims = [], [], {}
        off = 0
        for name, c in comps:
            U, w = c.basis_weight_pair(self, toas)
            Us.append(U)
            ws.append(w)
            dims[name] = (off, U.shape[1])
            off += U.shape[1]
        cache[toas] = ((ver, pkey), (Us, ws, dims))
        return Us, ws, dims

    def noise_model_dimensions(self, toas) -> Dict[str, tuple]:
        """(offset, size) of each correlated-noise component's basis columns
        within the noise design matrix (reference ``timing_model.py:1792``)."""
        return self.noise_basis_by_component(toas)[2]

    @property
    def ntmpar(self) -> int:
        """Number of timing-model design-matrix columns incl. the implicit
        offset (reference ``timing_model.py:2285``; noise parameters have no
        design column)."""
        return len(self.design_param_names()) + int("PhaseOffset" not in self.components)

    @property
    def has_correlated_errors(self) -> bool:
        return any(getattr(c, "introduces_correlated_errors", False)
                   for c in self.noise_components)

    # ------------------------------------------------------------------
    # par-file round trip
    # ------------------------------------------------------------------
    def as_parfile(self, comment: Optional[str] = None,
                   format: str = "pint") -> str:
        """Par-file text; ``format`` in ``pint``/``tempo``/``tempo2``
        applies the reference's output-dialect tweaks (A1DOT->XDOT,
        STIGMA->VARSIGMA, KIN/KOM DT92->IAU for tempo, ECL pinned to
        IERS2003 and T2CMETHOD commented for tempo2; reference
        ``timing_model.py:2862``, ``parameter.py:471``)."""
        lines = [f"# Created by pint_tpu\n" if comment is None else f"# {comment}\n"]
        if format.lower() != "pint":
            lines.append(f"# Format: {format.lower()}\n")
        for p in self.top_level_params:
            par = self._top_params_dict[p]
            if par.value is not None and par.value != "" and par.value is not False:
                lines.append(par.as_parfile_line(format))
        for comp in self.components.values():
            for p in comp.params:
                ln = comp._params_dict[p].as_parfile_line(format)
                if ln:
                    lines.append(ln)
        return "".join(lines)

    def write_parfile(self, path: str, comment: Optional[str] = None,
                      format: str = "pint"):
        with open(path, "w") as f:
            f.write(self.as_parfile(comment, format=format))

    def compare(self, other: "TimingModel", nodmx: bool = False,
                threshold_sigma: float = 3.0, verbosity: str = "max") -> str:
        """Tabular parameter comparison with sigma-change columns
        (reference ``timing_model.py:2293``).

        Columns: value1 (+/- unc), value2 (+/- unc), Diff_Sigma1 = (v2-v1)
        in units of self's uncertainty, Diff_Sigma2 in units of other's.
        Verbosity: "max" = every parameter, "med" = changed or significant,
        "min" = |Diff_Sigma| >= threshold only, "check" = only the names of
        parameters that cross the threshold.
        """
        def _fmt(par):
            if par is None or par.value is None:
                return "--"
            try:
                v = float(par.value)
            except (TypeError, ValueError):
                return str(par.value)
            u = par.uncertainty
            return f"{v:.10g}" + (f" +/- {float(u):.2g}" if u else "")

        rows = [f"{'PARAMETER':<15} {'SELF':>28} {'OTHER':>28} "
                f"{'Diff_Sigma1':>12} {'Diff_Sigma2':>12}"]
        flagged = []
        names = [p for p in self.params if p not in self.top_level_params]
        for p in names:
            if nodmx and p.startswith("DMX"):
                continue
            par1 = getattr(self, p)
            par2 = getattr(other, p) if p in other else None
            v1, v2 = par1.value, par2.value if par2 is not None else None
            if v1 is None and v2 is None:
                continue
            sig1 = sig2 = None
            try:
                d = float(v2) - float(v1)
                if par1.uncertainty:
                    sig1 = d / float(par1.uncertainty)
                if par2 is not None and par2.uncertainty:
                    sig2 = d / float(par2.uncertainty)
            except (TypeError, ValueError):
                pass
            crossed = any(s is not None and abs(s) >= threshold_sigma
                          for s in (sig1, sig2))
            if crossed:
                flagged.append(p)
            if verbosity == "min" and not crossed:
                continue
            if verbosity == "med" and v1 == v2 and not crossed:
                continue
            if verbosity == "check":
                continue
            s1 = f"{sig1:12.3f}" if sig1 is not None else f"{'--':>12}"
            s2 = f"{sig2:12.3f}" if sig2 is not None else f"{'--':>12}"
            mark = " !" if crossed else ""
            rows.append(f"{p:<15} {_fmt(par1):>28} {_fmt(par2):>28} "
                        f"{s1} {s2}{mark}")
        if verbosity == "check":
            return "\n".join(flagged)
        if flagged:
            rows.append(f"# parameters changed by >= {threshold_sigma} "
                        f"sigma: {', '.join(flagged)}")
        return "\n".join(rows)

    def __repr__(self):
        comps = ", ".join(self.components)
        return f"TimingModel({self.name or getattr(self.PSR, 'value', '')}: {comps})"

    def __deepcopy__(self, memo):
        import copy

        new = object.__new__(TimingModel)
        memo[id(self)] = new
        for k, v in self.__dict__.items():
            if k == "_cache":
                new._cache = {}  # compiled jax functions are not copyable
            else:
                new.__dict__[k] = copy.deepcopy(v, memo)
        for c in new.components.values():
            c._parent = new
        return new

    # ------------------------------------------------------------------
    # reference user-API long tail (timing_model.py:1276-2860)
    # ------------------------------------------------------------------
    def map_component(self, component) -> Tuple[Component, int, list, str]:
        """(component, order index, host list, kind) for a component name or
        instance (reference ``timing_model.py:1276``)."""
        comp = self.components[component] if isinstance(component, str) \
            else component
        if comp not in self.components.values():
            raise AttributeError(f"{comp} is not in the model")
        kind = getattr(comp, "kind", "")
        if kind == "delay":
            host = self.delay_components
        elif kind == "phase":
            host = self.phase_components
        elif kind == "noise":
            host = self.noise_components
        else:
            host = [c for c in self.components.values()
                    if getattr(c, "kind", "") == kind]
        return comp, host.index(comp), host, kind

    def get_component_type(self, component_type: str) -> list:
        """Components of the named kind ('DelayComponent'/'PhaseComponent'/
        'NoiseComponent', reference ``timing_model.py get_component_type``)."""
        kind = {"delaycomponent": "delay", "phasecomponent": "phase",
                "noisecomponent": "noise"}.get(
                    component_type.lower().replace("_", ""),
                    component_type.lower())
        return [c for c in self.components.values()
                if getattr(c, "kind", "") == kind]

    def get_components_by_category(self) -> Dict[str, list]:
        """{category: [components]} (reference
        ``timing_model.py get_components_by_category``)."""
        out: Dict[str, list] = {}
        for c in self.components.values():
            out.setdefault(c.category, []).append(c)
        return out

    def get_params_of_component_type(self, component_type: str) -> List[str]:
        """All parameter names on components of the given kind (reference
        ``timing_model.py get_params_of_component_type``)."""
        out: List[str] = []
        for c in self.get_component_type(component_type):
            out += c.params
        return out

    def search_cmp_attr(self, name: str):
        """First component carrying attribute ``name`` (reference
        ``timing_model.py search_cmp_attr``); None when absent."""
        for c in self.components.values():
            try:
                getattr(c, name)
                return c
            except AttributeError:
                continue
        return None

    @property
    def has_time_correlated_errors(self) -> bool:
        """True when a basis-noise (ECORR / red / DM / chromatic GP)
        component is present (reference ``timing_model.py:345``)."""
        return any(hasattr(c, "basis_weight_pair") or
                   hasattr(c, "ecorr_basis_weight_pair") or
                   hasattr(c, "pl_basis_weight_pair") or
                   getattr(c, "is_basis_noise", False)
                   for c in self.noise_components) \
            or self.has_correlated_errors

    def add_param_from_top(self, param, target_component: str,
                           setup: bool = False):
        """Add a parameter to the named component ('' = top level;
        reference ``timing_model.py add_param_from_top``)."""
        if target_component == "":
            self._top_params_dict[param.name] = param
            self.top_level_params.append(param.name)
            return param
        if target_component not in self.components:
            raise AttributeError(
                f"Cannot find component {target_component!r} in the model")
        return self.components[target_component].add_param(param, setup=setup)

    def remove_param(self, param: str) -> None:
        """Remove a parameter from whichever component hosts it (reference
        ``timing_model.py remove_param``)."""
        if param in self._top_params_dict:
            del self._top_params_dict[param]
            self.top_level_params.remove(param)
            return
        for c in self.components.values():
            if param in c.params:
                c.remove_param(param)
                self._cache.clear()
                return
        raise AttributeError(f"Parameter {param!r} is not in the model")

    def validate_component_types(self) -> None:
        """Sanity-check the component graph: every component has a known
        kind and a registered category slot (reference
        ``timing_model.py validate_component_types``)."""
        for name, c in self.components.items():
            kind = getattr(c, "kind", None)
            if kind not in ("delay", "phase", "noise", "tzr"):
                raise TimingModelError(
                    f"Component {name} has unknown kind {kind!r}")
            if not isinstance(c.category, str) or not c.category:
                raise TimingModelError(
                    f"Component {name} has no category")

    def param_help(self) -> str:
        """Description of every parameter (reference
        ``timing_model.py param_help``)."""
        lines = []
        for p in self.params:
            par = getattr(self, p)
            lines.append(f"{p:<15} {par.units or '':<12} "
                         f"{par.description or ''}")
        return "\n".join(lines) + "\n"

    def use_aliases(self, reset_to_default: bool = True,
                    alias_translation: Optional[Dict[str, str]] = None):
        """Control the name each parameter is written under (reference
        ``timing_model.py:2833``): reset to canonical names and/or install
        an output-name translation (e.g. tempo2 spellings)."""
        for p in self.params:
            par = getattr(self, p)
            if reset_to_default:
                par.use_alias = None
            if alias_translation is not None and p in alias_translation:
                par.use_alias = alias_translation[p]

    def as_ICRS(self, epoch=None) -> "TimingModel":
        """Equatorial-astrometry version of this model (reference
        ``timing_model.py as_ICRS``)."""
        from pint_tpu.modelutils import model_ecliptic_to_equatorial

        import copy as _copy

        m = _copy.deepcopy(self)
        if epoch is not None:
            m.change_posepoch(float(epoch))
        if "AstrometryEcliptic" in m.components:
            m = model_ecliptic_to_equatorial(m)
        return m

    def as_ECL(self, epoch=None, ecl: str = "IERS2010") -> "TimingModel":
        """Ecliptic-astrometry version of this model (reference
        ``timing_model.py as_ECL``)."""
        from pint_tpu.modelutils import model_equatorial_to_ecliptic

        import copy as _copy

        m = _copy.deepcopy(self)
        if epoch is not None:
            m.change_posepoch(float(epoch))
        if "AstrometryEquatorial" in m.components:
            m = model_equatorial_to_ecliptic(m)
        if m.ECL.value is None:
            m.ECL.value = ecl
        return m

    def d_delay_d_param(self, toas, param: str, acc_delay=None) -> np.ndarray:
        """d(total delay)/d(param) [s/unit] by autodiff of the delay
        accumulation (reference ``timing_model.py d_delay_d_param`` — hand
        partials there, jacfwd here)."""
        self._get_compiled(toas, tuple(self.free_params))
        entry = self._cache["data"][toas]
        batch, ctx = entry[1], entry[2]
        const_pv = self._const_pv()
        comps = self.delay_components
        names = [type(c).__name__ for c in comps]
        v0 = float(getattr(self, param).value or 0.0)

        def total_delay(v):
            pv = dict(const_pv)
            pv[param] = v
            acc = jnp.zeros(batch.ntoas, dtype=jnp.float64)
            for nm, comp in zip(names, comps):
                acc = acc + comp.delay_func(pv, batch, ctx[nm], acc)
            return acc

        return np.asarray(jax.jacfwd(total_delay)(jnp.float64(v0)))

    def d_delay_d_param_num(self, toas, param: str,
                            step: float = 1e-2) -> np.ndarray:
        """Finite-difference delay derivative (reference
        ``timing_model.py:2111``)."""
        par = getattr(self, param)
        v0 = float(par.value or 0.0)
        h = abs(v0) * step if v0 != 0 else step
        out = []
        # parameter values flow into the compiled functions as arguments
        # (_const_pv / free vector), so no cache invalidation is needed for
        # a pure value perturbation
        for v in (v0 + h, v0 - h):
            par.value = v
            out.append(self.delay(toas))
        par.value = v0
        return (out[0] - out[1]) / (2 * h)

    def d_toasigma_d_param(self, toas, param: str) -> np.ndarray:
        """d(scaled TOA sigma)/d(param) for noise parameters (reference
        ``timing_model.py d_toasigma_d_param``), by central difference on
        the host-side sigma scaling."""
        par = getattr(self, param)
        v0 = float(par.value or 0.0)
        h = max(abs(v0) * 1e-6, 1e-9)
        out = []
        for v in (v0 + h, v0 - h):
            par.value = v
            out.append(self.scaled_toa_uncertainty(toas))
        par.value = v0
        return (out[0] - out[1]) / (2 * h)

    def dm_covariance_matrix(self, toas) -> np.ndarray:
        """Wideband DM-data covariance (diagonal of scaled DM errors
        squared; reference ``timing_model.py dm_covariance_matrix``)."""
        sigma = self.scaled_dm_uncertainty(toas)
        return np.diag(np.asarray(sigma) ** 2)

    def jump_flags_to_params(self, toas) -> None:
        """Convert -jump/-gui_jump flags on the TOAs into JUMP parameters
        (reference ``timing_model.py jump_flags_to_params``, the inverse of
        ``delete_jump_and_flags``)."""
        from pint_tpu.models.jump import PhaseJump
        from pint_tpu.models.parameter import maskParameter

        idxs = set()
        for fl in toas.flags:
            for key in ("jump", "gui_jump"):
                if key in fl:
                    idxs.add(int(float(fl[key])))
        if not idxs:
            return
        if "PhaseJump" not in self.components:
            self.add_component(PhaseJump(), validate=False)
        comp = self.components["PhaseJump"]
        for i in sorted(idxs):
            # normalize flags FIRST (also for pre-existing JUMP<i> params,
            # and for float-spelled flags like '3.0'), so the -jump mask
            # matches every TOA in the group
            for fl in toas.flags:
                for key in ("jump", "gui_jump"):
                    if key in fl and int(float(fl[key])) == i:
                        fl["jump"] = str(i)
            # an equivalent jump may already exist under ANY index (par
            # files number JUMPs independently of the flag value): match by
            # mask, not by name, or a degenerate duplicate gets created
            existing = any(
                getattr(self._top_or_comp_param(p), "key", "").lstrip("-")
                == "jump"
                and getattr(self._top_or_comp_param(p), "key_value", None)
                == [str(i)]
                for p in self.params if p.startswith("JUMP"))
            if existing:
                continue
            comp.add_param(maskParameter("JUMP", index=i, key="-jump",
                                         key_value=[str(i)], units="s",
                                         value=0.0, frozen=False),
                           setup=True)
        self.setup()

    def _top_or_comp_param(self, name: str):
        try:
            return getattr(self, name)
        except AttributeError:
            return None


# ---------------------------------------------------------------------------
# component-pool introspection (reference ``timing_model.py:3798
# AllComponents``) and the property_exists decorator
# (``timing_model.py:132``)
# ---------------------------------------------------------------------------

class ModelMeta(type):
    """Accepted for reference-style declarations
    (``class X(Component, metaclass=ModelMeta)``, reference
    ``timing_model.py:3385``).  Registration itself is performed by
    ``Component.__init_subclass__`` — this metaclass only validates that a
    ``register = True`` class really is a Component (a non-Component in the
    registry would crash AllComponents/ModelBuilder instantiation)."""

    def __init__(cls, name, bases, dct):
        super().__init__(name, bases, dct)
        if dct.get("register", False) and not issubclass(cls, Component):
            raise TypeError(
                f"{name}: register=True requires subclassing Component")


def property_exists(f):
    """``@property`` that re-raises an internal AttributeError as TypeError.

    A plain property swallowing an accidental AttributeError makes
    ``__getattr__``-based delegation report "no such attribute" instead of
    the real bug (reference ``timing_model.py:132``)."""
    import functools

    from pint_tpu.exceptions import PropertyAttributeError

    @functools.wraps(f)
    def wrapper(self):
        try:
            return f(self)
        except AttributeError as e:
            raise PropertyAttributeError(
                f"property {f.__name__} raised AttributeError internally: {e}"
            ) from e

    return property(wrapper)


class AllComponents:
    """Pool of one (valueless) instance of every registered component, for
    model building and parameter searching (reference
    ``timing_model.py:3798``)."""

    def __init__(self):
        self.components: Dict[str, Component] = {
            k: v() for k, v in Component.component_types.items()}

    @property
    def param_component_map(self) -> Dict[str, List[str]]:
        """{parameter name: [component names]} (aliases excluded;
        reference ``timing_model.py:3825``)."""
        out: Dict[str, List[str]] = {}
        for cname, comp in self.components.items():
            for p in comp.params:
                out.setdefault(p, []).append(cname)
        return out

    @property
    def component_category_map(self) -> Dict[str, str]:
        """{component name: category} (reference
        ``timing_model.py component_category_map``)."""
        return {k: c.category for k, c in self.components.items()}

    @property
    def category_component_map(self) -> Dict[str, List[str]]:
        """{category: [component names]} (reference
        ``timing_model.py category_component_map``)."""
        out: Dict[str, List[str]] = {}
        for k, c in self.components.items():
            out.setdefault(c.category, []).append(k)
        return out

    @property
    def component_unique_params(self) -> Dict[str, List[str]]:
        """{component: params hosted by no other component} (reference
        ``timing_model.py component_unique_params``)."""
        p2c = self.param_component_map
        out: Dict[str, List[str]] = {}
        for k, c in self.components.items():
            out[k] = [p for p in c.params if len(p2c[p]) == 1]
        return out

    def param_to_unit(self, name: str) -> str:
        """Unit string of a parameter or alias (reference
        ``timing_model.py param_to_unit``)."""
        for comp in self.components.values():
            hit = comp.match_param_alias(name)
            if hit is not None:
                return comp._params_dict[hit].units
        pint_name, _ = self.alias_to_pint_param(name)
        from pint_tpu.models.parameter import split_prefixed_name

        prefix, _i = split_prefixed_name(pint_name)
        for comp in self.components.values():
            for p in comp.params:
                if p.startswith(prefix):
                    return comp._params_dict[p].units
        raise ValueError(f"Unknown parameter {name!r}")

    def repeatable_param(self) -> set:
        """Names (and aliases) of repeatable parameters (reference
        ``timing_model.py repeatable_param``)."""
        out = set()
        for comp in self.components.values():
            for p in comp.params:
                par = comp._params_dict[p]
                if getattr(par, "repeatable", False):
                    # the repeatable KEY is the family prefix (JUMP, EFAC),
                    # not the indexed instance name (JUMP1)
                    out.add(getattr(par, "prefix", par.name))
                    out.update(a.rstrip("0123456789") if a[-1:].isdigit()
                               else a for a in par.aliases)
        return out

    def search_binary_components(self, system_name: str) -> Component:
        """The binary component implementing ``system_name`` (e.g. 'ELL1');
        raises UnknownBinaryModel otherwise (reference
        ``timing_model.py:3998``)."""
        from pint_tpu.exceptions import UnknownBinaryModel

        key = f"Binary{system_name}"
        if key in self.components:
            return self.components[key]
        raise UnknownBinaryModel(f"Unknown binary model {system_name!r}")

    def alias_to_pint_param(self, alias: str) -> Tuple[str, str]:
        """(canonical parameter name, matched component parameter) for an
        alias, resolving prefix/mask indices (e.g. ``T2EFAC2`` -> EFAC2;
        reference ``timing_model.py:4046``)."""
        from pint_tpu.exceptions import PrefixError
        from pint_tpu.models.parameter import split_prefixed_name

        for comp in self.components.values():
            hit = comp.match_param_alias(alias)
            if hit is not None:
                return hit, alias
        # indexed family: match the prefix against each component's
        # exemplar aliases, then re-attach the index
        try:
            prefix, index = split_prefixed_name(alias)
        except (ValueError, PrefixError):
            raise ValueError(f"{alias!r} is not a parameter or alias")
        if index >= 0:
            for comp in self.components.values():
                hit = comp.match_param_alias(prefix) \
                    or comp.match_param_alias(prefix + "1")
                if hit is not None:
                    base, _ = split_prefixed_name(hit) \
                        if hit[-1].isdigit() else (hit, -1)
                    return f"{base}{index}", alias
        raise ValueError(f"{alias!r} is not a parameter or alias")
