"""Solar-system Shapiro delay (reference ``solar_system_shapiro.py``).

delay = -2 T_obj ln((r - r.n_psr)/AU) per body, Sun always, planets when
PLANET_SHAPIRO is set (reference ``solar_system_shapiro.py:59,83``).
Positions come in as obs->object vectors in light-seconds.
"""

from __future__ import annotations

import jax.numpy as jnp

import pint_tpu
from pint_tpu.models.timing_model import DelayComponent

__all__ = ["SolarSystemShapiro"]

_AU_LS = pint_tpu.AU_LS

_T_PLANET = {
    "jupiter": pint_tpu.Tjupiter,
    "saturn": pint_tpu.Tsaturn,
    "venus": pint_tpu.Tvenus,
    "uranus": pint_tpu.Turanus,
    "neptune": pint_tpu.Tneptune,
}


class SolarSystemShapiro(DelayComponent):
    register = True
    category = "solar_system_shapiro"

    @staticmethod
    def ss_obj_shapiro_delay(obj_pos_ls, psr_dir, T_obj):
        """-2 T ln((r - r.n)/AU); obj_pos is obs->object in light-seconds."""
        r = jnp.linalg.norm(obj_pos_ls, axis=1)
        rcostheta = jnp.sum(obj_pos_ls * psr_dir, axis=1)
        return -2.0 * T_obj * jnp.log((r - rcostheta) / _AU_LS)

    def _psr_dir(self, pv, batch):
        for comp in self._parent.components.values():
            if hasattr(comp, "ssb_to_psb_xyz"):
                return comp.ssb_to_psb_xyz(pv, batch.tdb.hi)
        raise ValueError("SolarSystemShapiro requires an astrometry component")

    def delay_func(self, pv, batch, ctx, acc_delay):
        psr_dir = self._psr_dir(pv, batch)
        delay = self.ss_obj_shapiro_delay(batch.obs_sun_pos, psr_dir, pint_tpu.Tsun)
        planet_shapiro = getattr(self._parent, "PLANET_SHAPIRO", None)
        if planet_shapiro is not None and planet_shapiro.value:
            for name, T in _T_PLANET.items():
                if name in batch.planet_pos:
                    delay = delay + self.ss_obj_shapiro_delay(
                        batch.planet_pos[name], psr_dir, T)
        return delay
