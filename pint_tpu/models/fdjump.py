"""FDJUMP: system-dependent frequency-dependent profile delays.

Reference ``fdjump.py:15,152``: for each mask parameter FDpJUMPq,
delay += c * y^p on the selected TOAs, where y = ln(f/1 GHz) when
FDJUMPLOG is true (NANOGrav convention) or (f/1 GHz) when false
(tempo2 convention, the default there).
"""

from __future__ import annotations

import re

import jax.numpy as jnp
import numpy as np

from pint_tpu.models.parameter import boolParameter, maskParameter
from pint_tpu.models.timing_model import DelayComponent

__all__ = ["FDJump"]

fdjump_max_index = 20

_FDJ_RE = re.compile(r"^FD(\d+)JUMP(\d+)")


class FDJump(DelayComponent):
    register = True
    category = "fdjump"

    def __init__(self):
        super().__init__()
        self.add_param(boolParameter(
            "FDJUMPLOG", value=True,
            description="Use log-frequency (Y) or linear frequency (N) for FDJUMPs"))
        # exemplars carry value=None so unset indices never reach the par
        # file (as_parfile_line skips None) or the TOA selection
        for j in range(1, fdjump_max_index + 1):
            self.add_param(maskParameter(
                f"FD{j}JUMP", index=1, units="s",
                description=f"System-dependent FD delay of polynomial index {j}"))
        self.fdjumps = []

    def setup(self):
        self.fdjumps = [p for p in self.params if _FDJ_RE.match(p)]

    def get_fd_index(self, par: str) -> int:
        m = _FDJ_RE.match(par)
        if not m:
            raise ValueError(f"{par} is not an FDJUMP parameter")
        return int(m.group(1))

    def build_context(self, toas):
        n = len(toas)
        masks = {}
        for p in self.fdjumps:
            par = self._params_dict[p]
            if par.key is None and par.value in (None, 0.0):
                continue
            m = np.zeros(n)
            m[par.select_toa_mask(toas)] = 1.0
            masks[p] = jnp.asarray(m)
        return {"masks": masks}

    def delay_func(self, pv, batch, ctx, acc_delay):
        f_ghz = batch.freq / 1000.0
        if bool(self.FDJUMPLOG.value):
            y = jnp.log(f_ghz)
            y = jnp.where(jnp.isfinite(y), y, 0.0)
        else:
            y = f_ghz
        d = jnp.zeros(batch.ntoas)
        for p in self.fdjumps:
            if p not in ctx["masks"]:
                continue
            d = d + pv.get(p, 0.0) * y ** self.get_fd_index(p) * ctx["masks"][p]
        return d
