"""Timing models: parameters, components, model builder.

Public surface mirrors the reference (``pint.models``): ``get_model``,
``get_model_and_toas``, ``TimingModel``, component classes.
"""

from pint_tpu.models.timing_model import TimingModel, Component  # noqa: F401
from pint_tpu.models.parameter import (  # noqa: F401
    Parameter,
    floatParameter,
    strParameter,
    boolParameter,
    intParameter,
    MJDParameter,
    AngleParameter,
    prefixParameter,
    maskParameter,
)
from pint_tpu.models import spindown  # noqa: F401
from pint_tpu.models import astrometry  # noqa: F401
from pint_tpu.models import dispersion_model  # noqa: F401
from pint_tpu.models import solar_system_shapiro  # noqa: F401
from pint_tpu.models import absolute_phase  # noqa: F401
from pint_tpu.models import phase_offset  # noqa: F401
from pint_tpu.models import jump  # noqa: F401
from pint_tpu.models import noise_model  # noqa: F401
from pint_tpu.models import binary  # noqa: F401
from pint_tpu.models import glitch  # noqa: F401
from pint_tpu.models import wave  # noqa: F401
from pint_tpu.models import wavex  # noqa: F401
from pint_tpu.models import frequency_dependent  # noqa: F401
from pint_tpu.models import fdjump  # noqa: F401
from pint_tpu.models import solar_wind  # noqa: F401
from pint_tpu.models import chromatic  # noqa: F401
from pint_tpu.models import troposphere  # noqa: F401
from pint_tpu.models import ifunc  # noqa: F401
from pint_tpu.models import piecewise  # noqa: F401
from pint_tpu.models.model_builder import get_model, get_model_and_toas  # noqa: F401
