"""Typed model parameters (counterpart of reference ``parameter.py``).

Values are stored in canonical par-file units as plain floats (F0 in Hz,
DM in pc/cm^3, PMRA in mas/yr, JUMP in s, angles in **radians**, epochs as
numpy longdouble MJD).  No astropy Quantities: the unit is metadata used at
the par-file boundary and for display; jitted evaluation consumes the raw
float (or a DD pair for epochs).

Parameter kinds: float/str/bool/int/MJD/Angle plus
* :class:`prefixParameter` — indexed families (F0, F1, ..., DMX_0001),
* :class:`maskParameter` — parameters selecting TOA subsets
  (JUMP -fe 430, EFAC -f L-wide, DMX ranges) with host-side mask resolution,
* :class:`pairParameter`, :class:`funcParameter` for completeness.
"""

from __future__ import annotations

import re
from typing import Callable, List, Optional

import numpy as np

from pint_tpu.exceptions import PrefixError
from pint_tpu.io.par import fortran_float

__all__ = [
    "Parameter",
    "floatParameter",
    "strParameter",
    "boolParameter",
    "intParameter",
    "MJDParameter",
    "AngleParameter",
    "prefixParameter",
    "maskParameter",
    "pairParameter",
    "funcParameter",
    "split_prefixed_name",
]

_PREFIX_RE = re.compile(r"^([A-Za-z][A-Za-z0-9_]*?_?)(\d+)$")


def split_prefixed_name(name: str):
    """Split 'F12' -> ('F', 12), 'DMX_0001' -> ('DMX_', 1); raise otherwise."""
    m = _PREFIX_RE.match(name)
    if m is None:
        raise PrefixError(f"Not a prefixed parameter name: {name!r}")
    return m.group(1), int(m.group(2))


def parse_angle(s: str, is_ra: bool = False) -> float:
    """Parse 'hh:mm:ss.s' / 'dd:mm:ss.s' / decimal degrees -> radians."""
    s = s.strip()
    if ":" in s:
        sign = -1.0 if s.lstrip().startswith("-") else 1.0
        parts = s.lstrip("+-").split(":")
        val = abs(float(parts[0]))
        if len(parts) > 1:
            val += float(parts[1]) / 60.0
        if len(parts) > 2:
            val += float(parts[2]) / 3600.0
        val *= sign
        deg = val * 15.0 if is_ra else val
    else:
        deg = fortran_float(s)
        if is_ra and abs(deg) <= 24.0 and ":" not in s:
            # bare number for RA is in hours by tempo convention
            deg = deg * 15.0
    return deg * np.pi / 180.0


def format_angle(rad: float, is_ra: bool = False, ndp: int = 8) -> str:
    deg = rad * 180.0 / np.pi
    if is_ra:
        hours = deg / 15.0 % 24.0
        h = int(hours)
        m = int((hours - h) * 60)
        s = (hours - h - m / 60.0) * 3600.0
        return f"{h:02d}:{m:02d}:{s:0{3 + ndp}.{ndp}f}"
    sign = "-" if deg < 0 else ""
    deg = abs(deg)
    d = int(deg)
    m = int((deg - d) * 60)
    s = (deg - d - m / 60.0) * 3600.0
    return f"{sign}{d:d}:{m:02d}:{s:0{3 + ndp}.{ndp}f}"


class Parameter:
    """Base parameter: name, value, units metadata, frozen flag, aliases."""

    def __init__(self, name: str, value=None, units: str = "", description: str = "",
                 frozen: bool = True, aliases: Optional[List[str]] = None,
                 uncertainty=None, continuous: bool = True, **kw):
        self.name = name
        self.units = units
        self.description = description
        self.frozen = frozen
        self.aliases = aliases or []
        self.uncertainty = uncertainty
        self.continuous = continuous
        self.value = value
        self.use_alias = None  # output name override (use_aliases)
        self._component = None  # set by Component.add_param
        self._prior = None  # lazily defaults to the unbounded uniform

    @property
    def prior(self):
        """Prior distribution for Bayesian inference (reference
        ``parameter.py`` prior hook); defaults to an improper flat prior."""
        if self._prior is None:
            from pint_tpu.models.priors import Prior, UniformUnboundedRV

            self._prior = Prior(UniformUnboundedRV())
        return self._prior

    @prior.setter
    def prior(self, p):
        self._prior = p

    def prior_pdf(self, value=None, logpdf: bool = False):
        v = self.value if value is None else value
        return self.prior.logpdf(v) if logpdf else self.prior.pdf(v)

    # -- par-file boundary -------------------------------------------------
    def str2value(self, s: str):
        return fortran_float(s)

    def value2str(self, v) -> str:
        return repr(v)

    def from_parfile_fields(self, fields: List[str]):
        """Set value/fit/uncertainty from raw par-file fields."""
        if not fields:
            return
        self.value = self.str2value(fields[0])
        if len(fields) >= 2:
            f1 = fields[1]
            if f1 in ("0", "1"):
                self.frozen = f1 != "1"
                if len(fields) >= 3:
                    try:
                        self.uncertainty = self.str2value(fields[2])
                    except ValueError:
                        pass
            else:
                try:
                    self.uncertainty = self.str2value(f1)
                except ValueError:
                    pass

    #: spelling swaps for tempo/tempo2 output (reference ``parameter.py:471``)
    _FORMAT_RENAME = {"A1DOT": "XDOT", "STIGMA": "VARSIGMA"}
    #: PINT-only parameters dropped from tempo/tempo2 output
    _PINT_ONLY = {"DMRES", "SWM", "SWP"}

    def as_parfile_line(self, format: str = "pint") -> str:
        fmt = format.lower()
        if fmt not in ("pint", "tempo", "tempo2"):
            raise ValueError(f"parfile format must be pint/tempo/tempo2, "
                             f"not {format!r}")
        if self.value is None:
            return ""
        name, value = self.use_alias or self.name, self.value
        if fmt != "pint":
            if name in self._PINT_ONLY:
                return ""
            name = self._FORMAT_RENAME.get(name, name)
        if fmt == "tempo" and self.name in ("KIN", "KOM"):
            # DT92 -> IAU convention (reference ``parameter.py:497-505``)
            value = (180.0 if self.name == "KIN" else 90.0) - value
        if fmt == "tempo2" and self.name == "ECL" and value != "IERS2003":
            # tempo2 only implements the IERS2003 ecliptic
            value = "IERS2003"
        line = f"{name:<15} {self.value2str(value):>25}"
        if not self.frozen:
            line += " 1"
        if self.uncertainty is not None:
            if self.frozen:
                line += " 0"
            line += f" {self.value2str(self.uncertainty)}"
        if fmt == "tempo2" and self.name == "T2CMETHOD":
            line = "#" + line
        return line + "\n"

    @property
    def quantity(self):
        return self.value

    def as_latex(self):
        """(label, value) LaTeX fragments for publication tables (reference
        ``parameter.py as_latex``; consumed by ``output.publish``)."""
        from pint_tpu.output.publish import _fmt_uncertainty

        name = self.name.replace("_", r"\_")
        unit = str(self.units).replace("^", r"\^{}") if self.units else ""
        label = f"{name} ({unit})" if unit else name
        if isinstance(self.value, (int, float, np.floating, np.integer)):
            val = _fmt_uncertainty(float(self.value), self.uncertainty)
        else:
            val = str(self.value)
        return label, val

    @property
    def uncertainty_value(self):
        """Bare-float uncertainty (reference ``parameter.py`` exposes both a
        Quantity ``uncertainty`` and this float view; here both are floats)."""
        return self.uncertainty

    @uncertainty_value.setter
    def uncertainty_value(self, v):
        self.uncertainty = v

    #: can this parameter appear multiple times in a par file?
    #: (mask/prefix subclasses override; reference ``parameter.py repeatable``)
    repeatable = False

    def add_alias(self, alias: str) -> None:
        """Register an extra input alias (reference
        ``parameter.py add_alias``)."""
        if alias not in self.aliases:
            self.aliases.append(alias)

    def from_parfile_line(self, line: str) -> bool:
        """Parse one par-file line into this parameter; returns False when
        the key does not match (reference ``parameter.py
        from_parfile_line``)."""
        fields = line.split()
        if not fields or not self.name_matches(fields[0]):
            return False
        self.from_parfile_fields(fields[1:])
        return True

    def set(self, value) -> None:
        """Set the value from a string or number (reference
        ``parameter.py Parameter.set``)."""
        self.value = self.str2value(value) if isinstance(value, str) \
            else value

    def str_quantity(self, quantity) -> str:
        """Reference spelling for :meth:`value2str`."""
        return self.value2str(quantity)

    def help_line(self) -> str:
        """One-line help (reference ``parameter.py help_line``)."""
        out = f"{self.name:<15} {self.description or ''}"
        if self.units:
            out += f" ({self.units})"
        return out

    def value_as_latex(self) -> str:
        """The value half of :meth:`as_latex`."""
        return self.as_latex()[1]

    def __repr__(self):
        fit = "" if self.frozen else " fit"
        return f"{type(self).__name__}({self.name}={self.value}{fit})"

    def name_matches(self, key: str) -> bool:
        key = key.upper()
        return key == self.name.upper() or key in (a.upper() for a in self.aliases)


class floatParameter(Parameter):
    """Float parameter; optional tempo-style unit scaling: par values with
    magnitude above ``scale_threshold`` are multiplied by ``scale_factor``
    (e.g. XDOT given in 1e-12 ls/s; reference ``parameter.py`` unit_scale).
    """

    def __init__(self, *a, unit_scale: bool = False, scale_factor: float = 1e-12,
                 scale_threshold: float = 1e-7, **kw):
        self.unit_scale = unit_scale
        self.scale_factor = scale_factor
        self.scale_threshold = scale_threshold
        super().__init__(*a, **kw)

    def str2value(self, s):
        v = fortran_float(s)
        if self.unit_scale and abs(v) > self.scale_threshold:
            v *= self.scale_factor
        return v

    def value2str(self, v):
        # shortest string that round-trips the float64 exactly (%.15g can
        # drop the 16th digit: an F0 ulp is ~2e-5 cycles over a decade span)
        return repr(float(v))


class strParameter(Parameter):
    def str2value(self, s):
        return s

    def value2str(self, v):
        return str(v)


class boolParameter(Parameter):
    def str2value(self, s):
        return s.upper() in ("Y", "YES", "T", "TRUE", "1")

    def value2str(self, v):
        return "Y" if v else "N"


class intParameter(Parameter):
    def str2value(self, s):
        return int(float(s))

    def value2str(self, v):
        return str(int(v))


class MJDParameter(Parameter):
    """Epoch parameter: value is numpy longdouble MJD (full precision)."""

    def __init__(self, *a, **kw):
        kw.setdefault("units", "MJD")
        super().__init__(*a, **kw)

    @property
    def value(self):
        return self._value

    @value.setter
    def value(self, v):
        # reference parity: ``model.PEPOCH.value = "54500.0001"`` parses at
        # full longdouble precision
        self._value = self.str2value(v) if isinstance(v, str) else v

    def str2value(self, s):
        return np.longdouble(s.translate(str.maketrans("Dd", "Ee")))

    def value2str(self, v):
        return str(np.longdouble(v))

    @property
    def value_float(self) -> float:
        return float(self.value) if self.value is not None else None


class AngleParameter(Parameter):
    """Angle parameter stored in radians; par IO in h:m:s or d:m:s."""

    def __init__(self, *a, angle_type: str = "dms", **kw):
        self.angle_type = angle_type  # 'hms' (RA), 'dms' (DEC), 'deg', 'rad'
        kw.setdefault("units", {"hms": "hourangle", "dms": "deg"}.get(angle_type, angle_type))
        super().__init__(*a, **kw)

    @property
    def value(self):
        return self._value

    @value.setter
    def value(self, v):
        # reference parity: ``model.RAJ.value = "04:37:15.9"`` parses
        self._value = self.str2value(v) if isinstance(v, str) else v

    def str2value(self, s):
        if self.angle_type == "hms":
            return parse_angle(s, is_ra=True)
        if self.angle_type == "dms":
            return parse_angle(s, is_ra=False)
        if self.angle_type == "deg":
            return fortran_float(s) * np.pi / 180.0
        return fortran_float(s)

    def value2str(self, v):
        if self.angle_type == "hms":
            return format_angle(v, is_ra=True)
        if self.angle_type == "dms":
            return format_angle(v, is_ra=False)
        if self.angle_type == "deg":
            return f"{v * 180.0 / np.pi:.13f}"
        return f"{v:.15g}"

    def from_parfile_fields(self, fields):
        # uncertainties on angles come in arcsec (dms) / s-of-time (hms)
        if not fields:
            return
        self.value = self.str2value(fields[0])
        rest = fields[1:]
        if rest and rest[0] in ("0", "1"):
            self.frozen = rest[0] != "1"
            rest = rest[1:]
        if rest:
            try:
                err = fortran_float(rest[0])
                scale = np.pi / (180.0 * 3600.0)
                if self.angle_type == "hms":
                    scale *= 15.0
                self.uncertainty = err * scale
            except ValueError:
                pass


class prefixParameter(floatParameter):
    """One member of an indexed family (F2, DMX_0017, GLF0_2...).

    ``prefix`` and ``index`` are derived from the name; components create new
    members on demand while reading par files (reference ``parameter.py:1063``).
    """

    def __init__(self, name: str, *a, **kw):
        self.prefix, self.index = split_prefixed_name(name)
        self.unit_template: Optional[Callable[[int], str]] = kw.pop("unit_template", None)
        self.description_template = kw.pop("description_template", None)
        super().__init__(name, *a, **kw)

    def new_param(self, index: int, **overrides) -> "prefixParameter":
        if self.index >= 0 and "_" in self.prefix:
            nm = f"{self.prefix}{index:04d}"
        else:
            nm = f"{self.prefix}{index}"
        kw = dict(units=self.units, description=self.description, frozen=True)
        kw.update(overrides)
        p = prefixParameter(nm, **kw)
        if self.unit_template:
            p.units = self.unit_template(index)
        return p


class maskParameter(floatParameter):
    """Parameter applying to a flag/observatory/MJD/frequency-selected TOA
    subset (reference ``parameter.py:1433``).

    Par syntax: ``JUMP -fe 430 0.0 1`` or ``JUMP MJD 57000 57100 0.0`` etc.
    ``select_toa_mask(toas)`` resolves to integer indices on the host; the
    jitted evaluator consumes the baked boolean array.
    """

    repeatable = True

    def __init__(self, name: str, index: int = 1, key: Optional[str] = None,
                 key_value: Optional[list] = None, **kw):
        self.prefix = name
        self.index = index
        self.key = key
        self.key_value = list(key_value) if key_value else []
        self.origin_name = name
        super().__init__(f"{name}{index}", **kw)

    def from_parfile_fields(self, fields: List[str]):
        # forms: [key, key_value..., value, (fit), (uncertainty)]
        if not fields:
            return
        key = fields[0].lower()
        if key.startswith("-"):
            self.key = key
            self.key_value = [fields[1]]
            rest = fields[2:]
        elif key in ("mjd", "freq"):
            self.key = key
            self.key_value = [fortran_float(fields[1]), fortran_float(fields[2])]
            rest = fields[3:]
        elif key in ("tel", "name"):
            self.key = key
            self.key_value = [fields[1]]
            rest = fields[2:]
        else:
            # tempo-style "JUMP value" with no selector (rare; tim-file jumps)
            self.key = None
            rest = fields
        if rest:
            self.value = self.str2value(rest[0])
            rest = rest[1:]
        if rest and rest[0] in ("0", "1"):
            self.frozen = rest[0] != "1"
            rest = rest[1:]
        if rest:
            try:
                self.uncertainty = self.str2value(rest[0])
            except ValueError:
                pass

    def as_parfile_line(self, format: str = "pint") -> str:
        if self.value is None:
            return ""
        if self.key is None:
            sel = ""
        elif self.key in ("mjd", "freq"):
            sel = f" {self.key.upper()} {self.key_value[0]} {self.key_value[1]}"
        elif self.key in ("tel", "name"):
            sel = f" {self.key.upper()} {self.key_value[0]}"
        else:
            sel = f" {self.key} {' '.join(str(v) for v in self.key_value)}"
        line = f"{self.origin_name}{sel} {self.value2str(self.value)}"
        if not self.frozen:
            line += " 1"
        if self.uncertainty is not None:
            line += f" {self.value2str(self.uncertainty)}"
        return line + "\n"

    def select_toa_mask(self, toas) -> np.ndarray:
        """Integer indices of the TOAs this parameter applies to."""
        n = len(toas)
        if self.key is None:
            return np.arange(n)
        if self.key == "mjd":
            m = np.asarray(toas.get_mjds(), dtype=np.float64)
            lo, hi = float(self.key_value[0]), float(self.key_value[1])
            return np.nonzero((m >= lo) & (m <= hi))[0]
        if self.key == "freq":
            f = toas.get_freqs()
            lo, hi = float(self.key_value[0]), float(self.key_value[1])
            return np.nonzero((f >= lo) & (f <= hi))[0]
        if self.key == "tel":
            from pint_tpu.observatory import get_observatory

            want = get_observatory(str(self.key_value[0])).name
            return np.nonzero(toas.get_obss() == want)[0]
        if self.key == "name":
            names = np.array([fl.get("name", "") for fl in toas.flags])
            return np.nonzero(names == str(self.key_value[0]))[0]
        # flag key, e.g. -fe 430
        flag = self.key.lstrip("-")
        want = str(self.key_value[0])
        sel = np.array([fl.get(flag) == want for fl in toas.flags])
        return np.nonzero(sel)[0]

    def name_matches(self, key: str) -> bool:
        # a bare par-file key ("EFAC", "JUMP") matches the indexed exemplar
        key = key.upper()
        if key == self.origin_name.upper() or key == self.name.upper():
            return True
        return key in (a.upper() for a in self.aliases)

    def compare_key_value(self, other_param) -> bool:
        """True when this mask selects the same TOAs as ``other_param``
        (same key and key values, order-insensitive; reference
        ``parameter.py:2170``)."""
        if getattr(other_param, "key", None) is None and self.key is None:
            return True
        if (self.key or "").lstrip("-") != \
                (getattr(other_param, "key", "") or "").lstrip("-"):
            return False
        return sorted(map(str, self.key_value)) == \
            sorted(map(str, getattr(other_param, "key_value", [])))

    def new_param(self, index: int, **overrides) -> "maskParameter":
        kw = dict(units=self.units, description=self.description, frozen=True,
                  aliases=list(self.aliases))
        kw.update(overrides)
        return maskParameter(self.origin_name, index=index, **kw)


class pairParameter(floatParameter):
    """Parameter whose value is a pair of floats (reference ``parameter.py:1781``).

    Pairs that end in digits (WAVE1, IFUNC3) form prefix families the model
    builder grows on demand, like :class:`prefixParameter`."""

    def __init__(self, name: str, *a, **kw):
        try:
            self.prefix, self.index = split_prefixed_name(name)
        except Exception:
            self.prefix, self.index = name, -1
        super().__init__(name, *a, **kw)

    def str2value(self, s):
        return [fortran_float(x) for x in s.split()]

    def from_parfile_fields(self, fields):
        if len(fields) >= 2:
            self.value = [fortran_float(fields[0]), fortran_float(fields[1])]

    def value2str(self, v):
        return f"{v[0]:.15g} {v[1]:.15g}"

    def new_param(self, index: int, **overrides) -> "pairParameter":
        kw = dict(units=self.units, description=self.description, frozen=True,
                  continuous=self.continuous)
        kw.update(overrides)
        return pairParameter(f"{self.prefix}{index}", **kw)


class funcParameter(floatParameter):
    """Read-only parameter computed live from other model parameters
    (reference ``parameter.py:2372``).

    ``params`` are resolved through the host component's parent model at
    read time, so ``.value``/``.quantity`` always reflect the current
    state; the value is ``None`` while unattached or while any source is
    unset.  With ``inpar=False`` (the default) the par-file line is
    written commented out.
    """

    def __init__(self, name: str, func: Callable = None, params=(),
                 inpar: bool = False, **kw):
        self.func = func
        self.source_params = [p if isinstance(p, str) else p[0]
                              for p in params]
        self.inpar = inpar
        super().__init__(name, **kw)
        self.frozen = True

    def _host_model(self):
        comp = getattr(self, "_component", None)
        return getattr(comp, "_parent", None) if comp is not None else None

    @property
    def value(self):
        model = self._host_model()
        if model is None or self.func is None:
            return None
        try:
            vals = [getattr(model, p).value for p in self.source_params]
        except AttributeError:
            return None
        if any(v is None for v in vals):
            return None
        return self.func(*(float(v) for v in vals))

    @value.setter
    def value(self, v):
        if v is not None:
            raise ValueError(
                f"funcParameter {self.name} is read-only (computed from "
                f"{self.source_params})")

    def as_parfile_line(self, format: str = "pint") -> str:
        line = super().as_parfile_line(format)
        if line and not self.inpar:
            line = "# " + line
        return line

    def evaluate(self, model):
        """Explicit evaluation against a given model (no attachment needed)."""
        vals = [getattr(model, p).value for p in self.source_params]
        return self.func(*vals) if self.func else None
