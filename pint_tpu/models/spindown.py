"""Spindown: Taylor-series pulse phase F0, F1, ... (reference ``spindown.py``).

Phase = sum_n F_n dt^(n+1)/(n+1)!  evaluated in **double-double** Horner form
(the one place absolute precision matters: F0*dt ~ 1e9-1e12 cycles).  dt is
(TOA_tdb - delay) - PEPOCH in seconds, assembled without precision loss from
the batch's dd time and the dd PEPOCH offset.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu.dd import day2sec_exact, mul_mod1
from pint_tpu.exceptions import MissingParameter
from pint_tpu.models.parameter import MJDParameter, prefixParameter
from pint_tpu.models.timing_model import DAY_S, PhaseComponent
from pint_tpu.phase import Phase

__all__ = ["Spindown", "SpindownBase"]


class SpindownBase(PhaseComponent):
    """Marker base for spindown-like phase components (reference
    ``spindown.py:15``): lets callers test ``isinstance(c, SpindownBase)``
    without naming every concrete spindown family."""


class Spindown(SpindownBase):
    """Reference: ``spindown.py:21``; phase at ``spindown.py:142``."""

    register = True
    category = "spindown"

    def __init__(self):
        super().__init__()
        self.add_param(prefixParameter("F0", units="Hz", description="Spin frequency"))
        self.add_param(prefixParameter("F1", units="Hz/s", description="Spin frequency derivative"))
        self.add_param(MJDParameter("PEPOCH", description="Epoch of spin parameters"))
        self.num_spin_terms = 2

    def setup(self):
        # contiguity check for F-terms added by the builder
        idxs = sorted(
            int(name[1:]) for name in self.params
            if name.startswith("F") and name[1:].isdigit()
        )
        self.num_spin_terms = len(idxs)
        if idxs != list(range(len(idxs))):
            missing = min(set(range(max(idxs) + 1)) - set(idxs))
            raise MissingParameter("Spindown", f"F{missing}",
                                   "Spin terms F0..Fn must be contiguous")

    def validate(self):
        if self.F0.value is None:
            raise MissingParameter("Spindown", "F0")

    @property
    def F_terms(self):
        """The F0..Fn Parameter objects in order (reference
        ``spindown.py F_terms``)."""
        return [self._params_dict[f"F{i}"] for i in range(self.num_spin_terms)]

    def get_spin_terms(self, pv):
        return [pv.get(f"F{i}", 0.0) for i in range(self.num_spin_terms)]

    def build_context(self, toas):
        return {}

    def _time_components(self, pv, batch, delay):
        """Decompose dt = (tdb - delay - PEPOCH) seconds into exact float64
        "fold" components plus a small float64 tail (TPU-safe: no error-free
        transforms — see dd.py on f64 excess precision).

        Returns ``(folds, tail)``: dt = sum(folds) + tail (to <= ~2**-45 s),
        where each fold term is an exact float64 the F0 product must be
        folded mod 1 against.  ``tail`` is dominated by the accumulated
        delay (up to ~500 s Roemer), so ``F0 * tail`` reaches ~1e5 cycles —
        but it is a *single float64 product* (one rounding, ~1e-11 cycles
        absolute) added to the fold fraction, and ``Phase.make`` renormalizes
        the carry, so no precision argument rests on |tail| being small.
        """
        T = batch.tdb_seconds()  # exact host-built pair
        folds = [T.hi]
        tail = T.lo - delay
        if self.PEPOCH.value is not None and "PEPOCH" in pv:
            pe = pv["PEPOCH"]
            # same-scale MJDs: the hi difference is Sterbenz-exact, the
            # day->sec scaling splits into two exact products
            e1, e2 = day2sec_exact(pe.hi - batch.tdb0)
            folds += [-e1, -e2]
            tail = tail - pe.lo * DAY_S
        return folds, tail

    def phase_func(self, pv, batch, ctx, delay):
        """Phase = sum_n F_n dt^(n+1)/(n+1)!.

        The dominant F0*dt term (~1e10 cycles needing 1e-9) is evaluated by
        folding each exact time component mod 1 (``mul_mod1``); every other
        contribution is orders of magnitude below float64's ~1e-11-cycle
        error at these magnitudes and uses plain arithmetic (reference
        ``spindown.py:142`` semantics).
        """
        import math

        folds, tail = self._time_components(pv, batch, delay)
        terms = self.get_spin_terms(pv)
        F0 = jnp.float64(terms[0])
        k = jnp.zeros(batch.ntoas)
        f = jnp.zeros(batch.ntoas)
        for t in folds:
            ki, fi = mul_mod1(F0, jnp.broadcast_to(jnp.asarray(t), (batch.ntoas,)))
            k = k + ki
            f = f + fi
        dt64 = sum(folds) + tail  # collapsed dt: fine for the F1+ terms
        f = f + F0 * tail
        if len(terms) > 1:
            acc = jnp.zeros(batch.ntoas)
            for i in range(len(terms) - 1, 0, -1):
                c = jnp.asarray(terms[i], dtype=jnp.float64) / math.factorial(i + 1)
                acc = acc * dt64 + c
            f = f + acc * dt64 * dt64
        return Phase.make(k, f)

    def change_pepoch(self, new_epoch, toas=None, delay=None):
        """Shift PEPOCH, adjusting F-terms (reference ``spindown.py`` PEPOCH move)."""
        from pint_tpu.utils import taylor_horner_deriv

        old = np.longdouble(self.PEPOCH.value)
        dt = float((np.longdouble(new_epoch) - old) * np.longdouble(DAY_S))
        terms = [float(self._params_dict[f"F{i}"].value or 0.0)
                 for i in range(self.num_spin_terms)]
        for i in range(self.num_spin_terms):
            newv = float(taylor_horner_deriv(dt, terms, deriv_order=i))
            self._params_dict[f"F{i}"].value = newv
        self.PEPOCH.value = np.longdouble(new_epoch)
