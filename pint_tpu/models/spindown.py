"""Spindown: Taylor-series pulse phase F0, F1, ... (reference ``spindown.py``).

Phase = sum_n F_n dt^(n+1)/(n+1)!  evaluated in **double-double** Horner form
(the one place absolute precision matters: F0*dt ~ 1e9-1e12 cycles).  dt is
(TOA_tdb - delay) - PEPOCH in seconds, assembled without precision loss from
the batch's dd time and the dd PEPOCH offset.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu.dd import dd_from_longdouble, dd_sub, taylor_horner_dd
from pint_tpu.exceptions import MissingParameter
from pint_tpu.models.parameter import MJDParameter, prefixParameter
from pint_tpu.models.timing_model import DAY_S, PhaseComponent
from pint_tpu.phase import phase_from_dd

__all__ = ["Spindown"]


class Spindown(PhaseComponent):
    """Reference: ``spindown.py:21``; phase at ``spindown.py:142``."""

    register = True
    category = "spindown"

    def __init__(self):
        super().__init__()
        self.add_param(prefixParameter("F0", units="Hz", description="Spin frequency"))
        self.add_param(prefixParameter("F1", units="Hz/s", description="Spin frequency derivative"))
        self.add_param(MJDParameter("PEPOCH", description="Epoch of spin parameters"))
        self.num_spin_terms = 2

    def setup(self):
        # contiguity check for F-terms added by the builder
        idxs = sorted(
            int(name[1:]) for name in self.params
            if name.startswith("F") and name[1:].isdigit()
        )
        self.num_spin_terms = len(idxs)
        if idxs != list(range(len(idxs))):
            missing = min(set(range(max(idxs) + 1)) - set(idxs))
            raise MissingParameter("Spindown", f"F{missing}",
                                   "Spin terms F0..Fn must be contiguous")

    def validate(self):
        if self.F0.value is None:
            raise MissingParameter("Spindown", "F0")

    def get_spin_terms(self, pv):
        return [pv.get(f"F{i}", 0.0) for i in range(self.num_spin_terms)]

    def build_context(self, toas):
        return {}

    def get_dt_dd(self, pv, batch, delay):
        """(tdb - delay - PEPOCH) seconds as DD.

        PEPOCH flows in as a traced DD scalar (pv["PEPOCH"]); when unset, the
        batch reference epoch tdb0 stands in (reference ``spindown.py:125``
        uses the first TOA).
        """
        from pint_tpu.dd import dd_mul

        t = dd_sub(batch.tdb_seconds(), delay)
        if self.PEPOCH.value is None:
            return t
        offset = dd_mul(dd_sub(pv["PEPOCH"], batch.tdb0), DAY_S)
        return dd_sub(t, offset)

    def phase_func(self, pv, batch, ctx, delay):
        dt = self.get_dt_dd(pv, batch, delay)
        coeffs = [jnp.float64(0.0)] + self.get_spin_terms(pv)
        return phase_from_dd(taylor_horner_dd(dt, coeffs))

    def change_pepoch(self, new_epoch, toas=None, delay=None):
        """Shift PEPOCH, adjusting F-terms (reference ``spindown.py`` PEPOCH move)."""
        from pint_tpu.utils import taylor_horner_deriv

        old = np.longdouble(self.PEPOCH.value)
        dt = float((np.longdouble(new_epoch) - old) * np.longdouble(DAY_S))
        terms = [float(self._params_dict[f"F{i}"].value or 0.0)
                 for i in range(self.num_spin_terms)]
        for i in range(self.num_spin_terms):
            newv = float(taylor_horner_deriv(dt, terms, deriv_order=i))
            self._params_dict[f"F{i}"].value = newv
        self.PEPOCH.value = np.longdouble(new_epoch)
