"""Par file -> TimingModel assembly (reference ``model_builder.py:96,775``).

Component selection walks the registered component classes and picks those
whose parameters (or aliases/prefix families) appear in the par file, plus
always-on defaults (SolarSystemShapiro when astrometry is present).  Repeated
mask keys (JUMP/EFAC/...) become indexed maskParameters; prefixed families
(F2, DMX_0002, GLF0_2) are grown on demand.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from pint_tpu.exceptions import (
    MissingParameter,
    TimingModelError,
    UnknownBinaryModel,
)
from pint_tpu.io.par import ParLine, parse_parfile
from pint_tpu.logging import log
from pint_tpu.models.parameter import (
    maskParameter,
    pairParameter,
    prefixParameter,
    split_prefixed_name,
)
from pint_tpu.models.timing_model import Component, TimingModel

__all__ = ["ModelBuilder", "get_model", "get_model_and_toas",
           "parse_parfile", "guess_binary_model"]


def guess_binary_model(parfile_dict) -> list:
    """Priority-ordered binary-model guesses for a parsed par-file dict
    (reference ``model_builder.py:969``); the first entry is the best
    guess.  Accepts the :func:`parse_parfile` output (or any mapping whose
    keys are parameter names)."""
    keys = {str(k).upper() for k in parfile_dict}
    best = ModelBuilder.guess_t2_model(keys)
    order = ["BinaryELL1H", "BinaryELL1k", "BinaryELL1", "BinaryDDK",
             "BinaryDDS", "BinaryDDGR", "BinaryDDH", "BinaryDD", "BinaryBT"]
    ranked = [best] + [m for m in order if m != best]
    return [m[len("Binary"):] for m in ranked]

#: par keys silently ignored (reference ``timing_model.py:96 ignore_params``)
IGNORE_PARAMS = {
    "NITS", "IBOOT", "MODE", "PLANET_SHAPIRO2", "GAIN", "EPHVER",
    "DMMODEL", "DMOFF", "DM_SERIES", "TRACK",
}

IGNORE_PREFIX = {"DMXF1_", "DMXF2_", "DMXEP_", "DMXCM_"}


class ModelBuilder:
    """Assemble a TimingModel from parsed par-file entries."""

    def __init__(self):
        # instantiate one template of every registered component
        self.templates: Dict[str, Component] = {}
        for name, cls in Component.component_types.items():
            try:
                self.templates[name] = cls()
            except Exception as e:  # pragma: no cover - registration errors
                log.warning(f"Could not instantiate component {name}: {e}")

    # -- component choice ---------------------------------------------------
    def choose_components(self, entries, allow_T2: bool = False) -> List[str]:
        keys = set(entries.keys())
        chosen: List[str] = []

        def has(*names):
            return any(n in keys for n in names)

        if has("RAJ", "RA"):
            chosen.append("AstrometryEquatorial")
        elif has("ELONG", "LAMBDA"):
            chosen.append("AstrometryEcliptic")
        if has("F0"):
            chosen.append("Spindown")
        if chosen and any(c.startswith("Astrometry") for c in chosen):
            if "SolarSystemShapiro" in self.templates:
                chosen.append("SolarSystemShapiro")
        if has("DM") or any(k.startswith("DM") and k[2:].isdigit() for k in keys):
            chosen.append("DispersionDM")
        if any(k.startswith("DMX_") for k in keys):
            chosen.append("DispersionDMX")
        if has("DMJUMP"):
            chosen.append("DispersionJump")
        if has("JUMP"):
            chosen.append("PhaseJump")
        if has("TZRMJD"):
            chosen.append("AbsPhase")
        if has("PHOFF"):
            chosen.append("PhaseOffset")
        if has("NE_SW", "NE1AU", "SOLARN0") and "SolarWindDispersion" in self.templates:
            chosen.append("SolarWindDispersion")
        if any(k.startswith("SWXDM_") for k in keys) and "SolarWindDispersionX" in self.templates:
            chosen.append("SolarWindDispersionX")
        if (has("CM", "TNCHROMIDX")
                or any(k.startswith("CM") and k[2:].isdigit() for k in keys)) \
                and "ChromaticCM" in self.templates:
            chosen.append("ChromaticCM")
        if any(k.startswith("CMX_") for k in keys) and "ChromaticCMX" in self.templates:
            chosen.append("ChromaticCMX")
        if any(k.startswith("GLEP_") or k.startswith("GLF0_") for k in keys) \
                and "Glitch" in self.templates:
            chosen.append("Glitch")
        if has("WAVE_OM") and "Wave" in self.templates:
            chosen.append("Wave")
        if has("WXEPOCH") or any(k.startswith("WXSIN_") for k in keys):
            if "WaveX" in self.templates:
                chosen.append("WaveX")
        if has("DMWXEPOCH") or any(k.startswith("DMWXSIN_") for k in keys):
            if "DMWaveX" in self.templates:
                chosen.append("DMWaveX")
        if has("CMWXEPOCH") or any(k.startswith("CMWXSIN_") for k in keys):
            if "CMWaveX" in self.templates:
                chosen.append("CMWaveX")
                # TNCHROMIDX lives on ChromaticCM (reference ``cmwavex.py``
                # validates it exists in the model)
                if "ChromaticCM" not in chosen and "ChromaticCM" in self.templates:
                    chosen.append("ChromaticCM")
        if any(k.startswith("FD") and k[2:].isdigit() for k in keys) \
                and "FD" in self.templates:
            chosen.append("FD")
        if any(k.startswith("FDJUMPDM") for k in keys) \
                and "FDJumpDM" in self.templates:
            chosen.append("FDJumpDM")
        if any(k.startswith("FD") and "JUMP" in k and not k.startswith("FDJUMPDM")
               for k in keys) and "FDJump" in self.templates:
            chosen.append("FDJump")
        if has("SIFUNC") and "IFunc" in self.templates:
            chosen.append("IFunc")
        if has("CORRECT_TROPOSPHERE") and "TroposphereDelay" in self.templates:
            # always attach the component; its CORRECT_TROPOSPHERE bool gates
            # the delay, so "N" parses cleanly instead of warning
            # (reference model_builder semantics)
            chosen.append("TroposphereDelay")
        # noise components
        if has("EFAC", "T2EFAC", "EQUAD", "T2EQUAD", "TNEQ") and "ScaleToaError" in self.templates:
            chosen.append("ScaleToaError")
        if has("DMEFAC", "DMEQUAD") and "ScaleDmError" in self.templates:
            chosen.append("ScaleDmError")
        if has("ECORR", "TNECORR") and "EcorrNoise" in self.templates:
            chosen.append("EcorrNoise")
        if has("RNAMP", "TNREDAMP") and "PLRedNoise" in self.templates:
            chosen.append("PLRedNoise")
        if has("TNDMAMP") and "PLDMNoise" in self.templates:
            chosen.append("PLDMNoise")
        if has("TNCHROMAMP") and "PLChromNoise" in self.templates:
            chosen.append("PLChromNoise")
        if has("TNSWAMP") and "PLSWNoise" in self.templates:
            chosen.append("PLSWNoise")
        # binary
        if "BINARY" in keys:
            binary_name = entries["BINARY"][0].value
            comp = self.binary_component_for(binary_name, keys, allow_T2=allow_T2)
            chosen.append(comp)
        # PiecewiseSpindown
        if any(k.startswith("PWF0_") for k in keys) and "PiecewiseSpindown" in self.templates:
            chosen.append("PiecewiseSpindown")
        return chosen

    def binary_component_for(self, binary_name: str, keys=(),
                             allow_T2: bool = False) -> str:
        want = f"Binary{binary_name}"
        if want in self.templates:
            return want
        # case-insensitive (par files write ELL1K for ELL1k etc.)
        for t in self.templates:
            if t.lower() == want.lower():
                return t
        if binary_name.upper() == "T2":
            if not allow_T2:
                raise UnknownBinaryModel(
                    "BINARY T2 is not directly supported; pass allow_T2=True "
                    "to substitute the closest implemented model")
            guess = self.guess_t2_model(keys)
            log.warning(f"BINARY T2 approximated by {guess} (allow_T2)")
            return guess
        available = sorted(t for t in self.templates if t.startswith("Binary"))
        raise UnknownBinaryModel(
            f"BINARY {binary_name} is not supported (available: {available})"
        )

    @staticmethod
    def guess_t2_model(keys) -> str:
        """Map a tempo2 'T2' binary to the closest implemented model from
        the parameters present (reference ``model_builder.py:969
        guess_binary_model``)."""
        keys = set(keys)
        if "EPS1" in keys or "TASC" in keys:
            if "H3" in keys or "H4" in keys or "STIGMA" in keys:
                return "BinaryELL1H"
            if "LNEDOT" in keys:
                return "BinaryELL1k"
            return "BinaryELL1"
        if "KIN" in keys or "KOM" in keys:
            return "BinaryDDK"
        if "SHAPMAX" in keys:
            return "BinaryDDS"
        if "MTOT" in keys:
            return "BinaryDDGR"
        if "H3" in keys or "STIGMA" in keys:
            return "BinaryDDH"
        if "OMDOT" in keys or "M2" in keys or "GAMMA" in keys:
            return "BinaryDD"
        return "BinaryBT"

    # -- main ---------------------------------------------------------------
    def __call__(self, parfile, allow_tcb: bool = False,
                 allow_T2: bool = False) -> TimingModel:
        entries = parse_parfile(parfile) if not isinstance(parfile, dict) else parfile
        tm = TimingModel()
        chosen = self.choose_components(entries, allow_T2=allow_T2)
        for cname in chosen:
            cls = Component.component_types[cname]
            tm.add_component(cls(), validate=False)

        used: set = set()
        # top-level params first
        for key, rows in entries.items():
            if key in tm.top_level_params:
                tm._top_params_dict[key].from_parfile_fields(rows[0].fields)
                used.add(key)
                continue
            for p in tm.top_level_params:
                if tm._top_params_dict[p].name_matches(key):
                    tm._top_params_dict[p].from_parfile_fields(rows[0].fields)
                    used.add(key)
                    break
        # component params
        for key, rows in entries.items():
            if key in used or key in IGNORE_PARAMS:
                continue
            if any(key.startswith(pre) for pre in IGNORE_PREFIX):
                continue
            if self._assign(tm, key, rows):
                used.add(key)
            else:
                log.warning(f"Unrecognized parfile line: {key} {rows[0].fields}")
                # unknown params land in the ingestion Diagnostics report
                # when the entries came through parse_parfile
                diags = getattr(entries, "diagnostics", None)
                if diags is not None:
                    diags.warning(
                        "par-unknown-param",
                        f"unknown parameter {key} {rows[0].fields}",
                        line=getattr(rows[0], "line", None), quiet=True)
        # name
        if tm.PSR.value:
            tm.name = tm.PSR.value
        for comp in tm.components.values():
            comp.setup()
        # reference semantics (model_builder.py:139,168): True converts the
        # model to TDB, "raw" loads the TCB model untouched, False raises
        if allow_tcb not in (True, False, "raw"):
            raise ValueError("allow_tcb must be True, False, or 'raw'")
        tm.validate(allow_tcb=allow_tcb in (True, "raw"))
        if allow_tcb is True and (tm.UNITS.value or "").upper() == "TCB":
            from pint_tpu.models.tcb_conversion import convert_tcb_tdb

            convert_tcb_tdb(tm)
        return tm

    def _assign(self, tm: TimingModel, key: str, rows: List[ParLine]) -> bool:
        # 1. direct name/alias match in some component
        for comp in tm.components.values():
            hit = comp.match_param_alias(key)
            if hit is not None:
                par = comp._params_dict[hit]
                if isinstance(par, maskParameter):
                    self._assign_masks(comp, par, rows)
                else:
                    par.from_parfile_fields(rows[0].fields)
                return True
        # 2. prefix-family growth (F2, DMX_0002, ...)
        try:
            prefix, index = split_prefixed_name(key)
        except Exception:
            return False
        for comp in tm.components.values():
            exemplar = None
            for pname in comp.params:
                par = comp._params_dict[pname]
                if (isinstance(par, prefixParameter)
                        or (isinstance(par, pairParameter) and par.index >= 0)) \
                        and par.prefix == prefix:
                    exemplar = par
                    break
            if exemplar is not None:
                newp = exemplar.new_param(index)
                newp.name = key
                newp.index = index
                newp.from_parfile_fields(rows[0].fields)
                comp.add_param(newp)
                return True
        return False

    def _assign_masks(self, comp, exemplar: maskParameter, rows: List[ParLine]):
        """Each repeated mask line becomes its own indexed parameter."""
        for i, ln in enumerate(rows):
            if i == 0 and exemplar.value in (None, 0.0) and not exemplar.key:
                target = exemplar
            else:
                target = exemplar.new_param(index=self._next_mask_index(comp, exemplar))
                comp.add_param(target)
            target.from_parfile_fields(ln.fields)

    @staticmethod
    def _next_mask_index(comp, exemplar) -> int:
        idxs = [comp._params_dict[p].index for p in comp.params
                if isinstance(comp._params_dict[p], maskParameter)
                and comp._params_dict[p].origin_name == exemplar.origin_name]
        return max(idxs) + 1 if idxs else 1


def get_model(parfile, allow_tcb: bool = False, allow_T2: bool = False) -> TimingModel:
    """Reference-parity entry point (``model_builder.py:775``)."""
    return ModelBuilder()(parfile, allow_tcb=allow_tcb, allow_T2=allow_T2)


def get_model_and_toas(parfile, timfile, ephem=None, planets=None,
                       include_bipm=None, allow_tcb=False, allow_T2=False,
                       **kw) -> Tuple[TimingModel, "object"]:
    """Load both model and TOAs (reference ``model_builder.py:858``)."""
    from pint_tpu.toa import get_TOAs

    model = get_model(parfile, allow_tcb=allow_tcb, allow_T2=allow_T2)
    toas = get_TOAs(
        timfile, model=model, ephem=ephem,
        planets=planets if planets is not None else False,
        include_bipm=include_bipm, **kw,
    )
    return model, toas


def convert_binary_params_dict(parfile_dict, convert_komkin: bool = True,
                               drop_ddk_sini: bool = True,
                               force_binary_model: "str | None" = None):
    """Rewrite a parsed par-file dict's BINARY line to the best-guess
    supported model (reference ``model_builder.py:1024``): T2 (or any
    unsupported) binary models are replaced by the highest-priority guess
    from :func:`guess_binary_model`; for a DDK result the KIN/KOM angles are
    converted between the IAU and DT92 conventions and SINI is dropped
    (DDK derives it from KIN).

    Accepts either this module's ``parse_parfile`` output (lists of
    ``ParLine``) or a plain {KEY: [value-string]} mapping; the input mapping
    is edited in place and returned.
    """
    from pint_tpu.io.par import ParLine

    def _get(key):
        rows = parfile_dict.get(key)
        if not rows:
            return None
        row = rows[0]
        return " ".join(row.fields) if isinstance(row, ParLine) else str(row)

    def _set(key, value_str: str):
        rows = parfile_dict.get(key)
        if rows and isinstance(rows[0], ParLine):
            parfile_dict[key] = [ParLine(key, value_str.split())]
        else:
            parfile_dict[key] = [value_str]

    binary = _get("BINARY")
    if not binary:
        return parfile_dict
    binary = binary.split()[0]
    if not force_binary_model and f"Binary{binary}" in \
            Component.component_types:
        return parfile_dict  # already a supported model: leave it alone
    if force_binary_model:
        guesses = [force_binary_model]
    else:
        guesses = guess_binary_model(parfile_dict)
        log.info(f"Compatible binary models: {', '.join(guesses)}; "
                 f"using {guesses[0]}")
    _set("BINARY", guesses[0])
    if convert_komkin:
        # IAU <-> DT92: KIN' = 180 - KIN, KOM' = 90 - KOM (reference
        # parameter.py:497-505 conventions)
        for key, zero in (("KIN", 180.0), ("KOM", 90.0)):
            val = _get(key)
            if val is not None:
                fields = val.split()
                fields[0] = repr(zero - float(fields[0]))
                _set(key, " ".join(fields))
    if drop_ddk_sini and guesses[0] == "DDK":
        if parfile_dict.pop("SINI", None) is not None:
            log.info("Dropped SINI from the DDK model (derived from KIN)")
    return parfile_dict
