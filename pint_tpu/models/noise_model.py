"""Noise components: white-noise scaling, ECORR, power-law Fourier GP bases.

Counterpart of reference ``noise_model.py`` (``ScaleToaError`` :37,
``ScaleDmError`` :223, ``EcorrNoise`` :327, ``PLDMNoise`` :450, ``PLSWNoise``
:623, ``PLChromNoise`` :785, ``PLRedNoise`` :967).  TPU-first split: the
(basis, weight) pairs are built **once on the host** (they depend only on TOA
epochs/frequencies and integer mode counts, not on fitted timing parameters)
and enter jitted GLS solves / Woodbury chi2 as constant device arrays.  The
white-noise sigma scaling is a pure function of (EFAC, EQUAD) consumed by both
host paths and the jitted likelihoods.
"""

from __future__ import annotations

import warnings
from typing import Dict, List, Optional, Tuple

import numpy as np

from pint_tpu import DMconst
from pint_tpu.logging import log
from pint_tpu.models.parameter import floatParameter, intParameter, maskParameter
from pint_tpu.models.timing_model import Component

__all__ = [
    "NoiseComponent",
    "ScaleToaError",
    "ScaleDmError",
    "EcorrNoise",
    "PLRedNoise",
    "PLDMNoise",
    "PLChromNoise",
    "PLSWNoise",
    "powerlaw",
    "fourier_design_matrix",
    "rednoise_freqs",
    "ecorr_epochs",
    "ecorr_quantization_matrix",
    "create_ecorr_quantization_matrix",
    "create_fourier_design_matrix",
    "get_ecorr_epochs",
    "get_ecorr_nweights",
    "get_rednoise_freqs",
]

DAY_S = 86400.0
#: 1/year in Hz
FYR = 1.0 / (365.25 * DAY_S)
_FREF_MHZ = 1400.0


# ----------------------------------------------------------------------
# basis helpers (reference ``noise_model.py:1180-1345``)
# ----------------------------------------------------------------------
def ecorr_epochs(t_s: np.ndarray, dt: float = 1.0, nmin: int = 2) -> List[List[int]]:
    """Group TOAs (seconds) into observing epochs closer than ``dt`` seconds;
    keep only groups of >= ``nmin`` members (reference ``get_ecorr_epochs``)."""
    if len(t_s) == 0:
        return []
    isort = np.argsort(t_s)
    ref = t_s[isort[0]]
    groups: List[List[int]] = [[int(isort[0])]]
    for i in isort[1:]:
        if t_s[i] - ref < dt:
            groups[-1].append(int(i))
        else:
            ref = t_s[i]
            groups.append([int(i)])
    return [g for g in groups if len(g) >= nmin]


def ecorr_quantization_matrix(t_s: np.ndarray, dt: float = 1.0, nmin: int = 2) -> np.ndarray:
    """(N, n_epoch) 0/1 matrix mapping TOAs to epochs (reference
    ``create_ecorr_quantization_matrix``)."""
    groups = ecorr_epochs(t_s, dt=dt, nmin=nmin)
    U = np.zeros((len(t_s), len(groups)))
    for k, g in enumerate(groups):
        U[g, k] = 1.0
    return U


def rednoise_freqs(Tspan_s: float, n_lin: int, n_log: Optional[int] = None,
                   f_min_ratio: float = 1.0) -> np.ndarray:
    """Fourier mode frequencies: ``n_lin`` linear modes k/T (k=1..n_lin),
    optionally preceded by ``n_log`` log-spaced modes from ``f_min_ratio/T``
    up to (not including) 1/T (reference ``get_rednoise_freqs`` with
    logmode=0)."""
    f_lin = np.arange(1, n_lin + 1) / Tspan_s
    if n_log is None or n_log <= 0:
        return f_lin
    f_min = f_min_ratio / Tspan_s
    f_log = np.logspace(np.log10(f_min), np.log10(1.0 / Tspan_s), n_log,
                        endpoint=False)
    return np.concatenate([f_log, f_lin])


def fourier_design_matrix(t_s: np.ndarray, f: np.ndarray) -> np.ndarray:
    """(N, 2*len(f)) matrix of alternating sin/cos columns (reference
    ``create_fourier_design_matrix``)."""
    arg = 2.0 * np.pi * t_s[:, None] * f[None, :]
    F = np.empty((len(t_s), 2 * len(f)))
    F[:, 0::2] = np.sin(arg)
    F[:, 1::2] = np.cos(arg)
    return F


def _powerlaw_psd(f, A, gamma):
    """Factored power-law PSD, dtype-generic: ``f``/``A``/``gamma`` may be
    numpy values or jax tracers.  The ``fyr^-3 (f/fyr)^-gamma`` form is
    algebraically identical to ``fyr^(gamma-3) f^-gamma`` but has no
    ~1e44 ``f**-gamma`` intermediate, so it survives float32-RANGE
    arithmetic (TPU f64 emulation); the single source of truth shared by
    the host path below and the traced builder in ``noisefit.py``
    (regression: TestPowerlawRangeSafety evaluates it at true f32)."""
    x = f / FYR
    return A**2 / 12.0 / np.pi**2 * FYR ** (-3.0) * x ** (-gamma)


def powerlaw(f: np.ndarray, A: float, gamma: float) -> np.ndarray:
    """Power-law PSD in the enterprise/GW convention (reference
    ``noise_model.py:1330``): P(f) = A^2/(12 pi^2) fyr^(gamma-3) f^-gamma."""
    return _powerlaw_psd(np.asarray(f, float), A, gamma)


def _tdb_seconds(toas) -> np.ndarray:
    return np.asarray(toas.tdb, dtype=np.float64) * DAY_S


def _bary_freq_mhz(model, toas) -> np.ndarray:
    """Doppler-corrected (barycentric) radio frequency, host-side."""
    from pint_tpu.models.astrometry import Astrometry

    astro = next((c for c in model.components.values() if isinstance(c, Astrometry)),
                 None)
    freq = np.asarray(toas.get_freqs(), dtype=np.float64)
    if astro is None or toas.ssb_obs_vel_kms is None:
        return freq
    batch = toas.to_batch()
    f = astro.barycentric_radio_freq(model._const_pv(), batch)
    return np.asarray(f)


# ----------------------------------------------------------------------
# components
# ----------------------------------------------------------------------
class NoiseComponent(Component):
    kind = "noise"
    introduces_correlated_errors = False
    introduces_dm_errors = False
    is_time_correlated = False
    is_ecorr = False

    def _masks_of(self, prefix: str) -> List[str]:
        return sorted(
            (p for p in self.params
             if p.startswith(prefix) and p[len(prefix):].isdigit()),
            key=lambda p: int(p[len(prefix):]),
        )


class ScaleToaError(NoiseComponent):
    """EFAC/EQUAD/TNEQ white-noise scaling (reference ``noise_model.py:37``).

    sigma' = EFAC * sqrt(sigma^2 + EQUAD^2), applied per mask selection;
    TNEQ (log10 seconds) is converted to an equivalent EQUAD at setup.
    """

    register = True
    category = "scale_toa_error"

    def __init__(self):
        super().__init__()
        self.add_param(maskParameter("EFAC", index=1, units="",
                                     aliases=["T2EFAC", "TNEF"],
                                     description="Multiplier on TOA uncertainties"))
        self.add_param(maskParameter("EQUAD", index=1, units="us",
                                     aliases=["T2EQUAD"],
                                     description="Error added in quadrature (us)"))
        self.add_param(maskParameter("TNEQ", index=1, units="log10(s)",
                                     description="Quadrature error, log10(seconds)"))

    def setup(self):
        # convert TNEQ entries into EQUAD equivalents (reference :111-137):
        # a TNEQ whose selection any existing EQUAD already covers is
        # dropped in favor of the EQUAD; otherwise it becomes a new EQUAD
        for tneq in self._masks_of("TNEQ"):
            tp = self._params_dict[tneq]
            if tp.value is None or tp.key is None:
                continue
            equad_sels = {
                (self._params_dict[e].key, tuple(self._params_dict[e].key_value))
                for e in self._masks_of("EQUAD")
                if self._params_dict[e].value is not None
            }
            if (tp.key, tuple(tp.key_value)) in equad_sels:
                log.warning(f"{tneq} {tp.key} {tp.key_value} is provided by an "
                            "EQUAD; using EQUAD")
                continue
            idx = tp.index
            while (f"EQUAD{idx}" in self._params_dict
                   and self._params_dict[f"EQUAD{idx}"].value is not None):
                idx += 1
            if f"EQUAD{idx}" not in self._params_dict:
                self.add_param(maskParameter("EQUAD", index=idx, units="us"))
            ep = self._params_dict[f"EQUAD{idx}"]
            ep.value = 10.0 ** tp.value * 1e6  # s -> us
            ep.key, ep.key_value = tp.key, list(tp.key_value)

    def validate(self):
        for prefix in ("EFAC", "EQUAD"):
            seen = []
            for p in self._masks_of(prefix):
                par = self._params_dict[p]
                if par.value is None:
                    continue
                kv = (par.key, tuple(par.key_value))
                if kv in seen:
                    raise ValueError(f"Duplicate {prefix} selection {kv}")
                seen.append(kv)

    def scale_toa_sigma(self, model, toas, sigma_s: np.ndarray) -> np.ndarray:
        """Apply EQUADs (quadrature) then EFACs (multiplier); seconds."""
        out = np.array(sigma_s, dtype=np.float64, copy=True)
        for p in self._masks_of("EQUAD"):
            par = self._params_dict[p]
            if par.value is None:
                continue
            idx = par.select_toa_mask(toas)
            if len(idx):
                out[idx] = np.hypot(out[idx], par.value * 1e-6)
            else:
                warnings.warn(f"EQUAD {par.name} selects no TOAs")
        for p in self._masks_of("EFAC"):
            par = self._params_dict[p]
            if par.value is None:
                continue
            idx = par.select_toa_mask(toas)
            if len(idx):
                out[idx] *= par.value
            else:
                warnings.warn(f"EFAC {par.name} selects no TOAs")
        return out


    def sigma_scaled_cov_matrix(self, toas) -> np.ndarray:
        """diag(scaled sigma^2) (reference ``noise_model.py
        sigma_scaled_cov_matrix``)."""
        sigma = self._parent.scaled_toa_uncertainty(toas)
        return np.diag(np.asarray(sigma) ** 2)


class ScaleDmError(NoiseComponent):
    """DMEFAC/DMEQUAD scaling of wideband DM uncertainties (reference
    ``noise_model.py:223``)."""

    register = True
    category = "scale_dm_error"

    def __init__(self):
        super().__init__()
        self.add_param(maskParameter("DMEFAC", index=1, units="",
                                     description="Multiplier on DM uncertainties"))
        self.add_param(maskParameter("DMEQUAD", index=1, units="pc/cm3",
                                     description="DM error added in quadrature"))

    def scale_dm_sigma(self, model, toas, sigma_dm: np.ndarray) -> np.ndarray:
        out = np.array(sigma_dm, dtype=np.float64, copy=True)
        for p in self._masks_of("DMEQUAD"):
            par = self._params_dict[p]
            if par.value is None:
                continue
            idx = par.select_toa_mask(toas)
            out[idx] = np.hypot(out[idx], par.value)
        for p in self._masks_of("DMEFAC"):
            par = self._params_dict[p]
            if par.value is None:
                continue
            idx = par.select_toa_mask(toas)
            out[idx] *= par.value
        return out

    def dm_sigma_scaled_cov_matrix(self, toas) -> np.ndarray:
        """diag(scaled DM sigma^2) (reference ``noise_model.py
        dm_sigma_scaled_cov_matrix``)."""
        sigma = self._parent.scaled_dm_uncertainty(toas)
        return np.diag(np.asarray(sigma) ** 2)


class EcorrNoise(NoiseComponent):
    """Epoch-correlated white noise via a quantization basis (reference
    ``noise_model.py:327``): U maps TOAs to observing epochs (TOAs within 1 s),
    weight = ECORR^2 (seconds^2)."""

    register = True
    category = "ecorr_noise"
    introduces_correlated_errors = True
    is_ecorr = True

    def __init__(self):
        super().__init__()
        self.add_param(maskParameter("ECORR", index=1, units="us",
                                     aliases=["TNECORR"],
                                     description="Epoch-correlated error (us)"))

    def validate(self):
        seen = []
        for p in self._masks_of("ECORR"):
            par = self._params_dict[p]
            if par.value is None:
                continue
            kv = (par.key, tuple(par.key_value))
            if kv in seen:
                raise ValueError(f"Duplicate ECORR selection {kv}")
            seen.append(kv)

    def basis_weight_pair(self, model, toas) -> Tuple[np.ndarray, np.ndarray]:
        t = _tdb_seconds(toas)
        pars = [self._params_dict[p] for p in self._masks_of("ECORR")
                if self._params_dict[p].value is not None]
        umats, weights = [], []
        for par in pars:
            idx = par.select_toa_mask(toas)
            if len(idx):
                umats.append((idx, ecorr_quantization_matrix(t[idx])))
            else:
                warnings.warn(f"ECORR {par.name} selects no TOAs")
                umats.append((idx, np.zeros((0, 0))))
            weights.append((par.value * 1e-6) ** 2)
        nc = sum(u.shape[1] for _, u in umats)
        U = np.zeros((len(t), nc))
        w = np.zeros(nc)
        col = 0
        for (idx, um), wt in zip(umats, weights):
            nn = um.shape[1]
            U[idx, col:col + nn] = um
            w[col:col + nn] = wt
            col += nn
        return U, w

    def cov_matrix(self, model, toas) -> np.ndarray:
        U, w = self.basis_weight_pair(model, toas)
        return (U * w) @ U.T

    # -- reference-named surface (noise_model.py:327-440) -------------------
    def get_ecorrs(self) -> list:
        """The ECORR maskParameters in use (reference
        ``noise_model.py:389``)."""
        return [self._params_dict[p] for p in self._masks_of("ECORR")
                if self._params_dict[p].value is not None]

    def get_noise_basis(self, toas) -> np.ndarray:
        """The quantization matrix U (reference ``noise_model.py:392``)."""
        return self.basis_weight_pair(self._parent, toas)[0]

    def get_noise_weights(self, toas) -> np.ndarray:
        """Per-epoch weights ECORR^2 [s^2] (reference
        ``noise_model.py get_noise_weights``)."""
        return self.basis_weight_pair(self._parent, toas)[1]

    def ecorr_basis_weight_pair(self, toas):
        """Reference spelling (``noise_model.py
        ecorr_basis_weight_pair``)."""
        return self.basis_weight_pair(self._parent, toas)

    def ecorr_cov_matrix(self, toas) -> np.ndarray:
        """Reference spelling (``noise_model.py ecorr_cov_matrix``)."""
        return self.cov_matrix(self._parent, toas)


class _PLNoiseBase(NoiseComponent):
    """Shared machinery of the power-law Fourier GP components.

    Each subclass sets ``_pl_prefix`` (rn/dm/chrom/sw) and gets the
    reference-spelled ``pl_<prefix>_basis_weight_pair`` /
    ``pl_<prefix>_cov_matrix`` methods generated in ``__init_subclass__``
    — defined here, discoverably, instead of module-tail monkey-patching.
    """

    introduces_correlated_errors = True
    is_time_correlated = True
    #: reference naming infix: pl_<infix>_basis_weight_pair
    _pl_prefix = ""

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        if cls.__dict__.get("_pl_prefix"):
            pre = cls._pl_prefix

            def pair(self, toas):
                return self.basis_weight_pair(self._parent, toas)

            def cov(self, toas):
                return self.cov_matrix(self._parent, toas)

            pair.__doc__ = (f"(basis, weights) (reference ``noise_model.py "
                            f"pl_{pre}_basis_weight_pair``).")
            cov.__doc__ = (f"Covariance contribution (reference "
                           f"``noise_model.py pl_{pre}_cov_matrix``).")
            setattr(cls, f"pl_{pre}_basis_weight_pair", pair)
            setattr(cls, f"pl_{pre}_cov_matrix", cov)

    #: subclass config: (amp par, gam par, nmode par, nlog par, logfac par,
    #: tspan par or None, default number of linear modes)
    _plc: Tuple[str, str, str, str, str, Optional[str], int] = ()

    def get_plc_vals(self):
        amp_p, gam_p, c_p, flog_p, fac_p, _, default_c = self._plc
        n_lin = int(self._params_dict[c_p].value or default_c)
        nlog_par = self._params_dict[flog_p].value
        n_log = int(nlog_par) if nlog_par is not None else None
        fac = self._params_dict[fac_p].value or 2.0
        amp = 10.0 ** self._params_dict[amp_p].value
        gam = self._params_dict[gam_p].value
        f_min_ratio = 1.0 / fac**n_log if n_log is not None else 1.0
        return amp, gam, n_lin, n_log, f_min_ratio

    def _tspan_s(self, toas) -> float:
        tspan_p = self._plc[5]
        if tspan_p is not None:
            v = self._params_dict[tspan_p].value
            if v is not None:
                return float(v) * 365.25 * DAY_S
        t = _tdb_seconds(toas)
        return float(np.max(t) - np.min(t))

    def get_time_frequencies(self, toas):
        t = _tdb_seconds(toas)
        T = self._tspan_s(toas)
        _, _, n_lin, n_log, f_min_ratio = self.get_plc_vals()
        return t, rednoise_freqs(T, n_lin, n_log=n_log, f_min_ratio=f_min_ratio)

    def _chromatic_scale(self, model, toas) -> Optional[np.ndarray]:
        """Per-TOA multiplier of the Fourier basis; None = achromatic."""
        return None

    def get_noise_basis(self, model, toas) -> np.ndarray:
        t, f = self.get_time_frequencies(toas)
        F = fourier_design_matrix(t, f)
        D = self._chromatic_scale(model, toas)
        return F if D is None else F * D[:, None]

    def get_noise_weights(self, toas) -> np.ndarray:
        amp, gam, *_ = self.get_plc_vals()
        _, f = self.get_time_frequencies(toas)
        df = np.diff(np.concatenate([[0.0], f]))
        return powerlaw(np.repeat(f, 2), amp, gam) * np.repeat(df, 2)

    def basis_weight_pair(self, model, toas) -> Tuple[np.ndarray, np.ndarray]:
        return self.get_noise_basis(model, toas), self.get_noise_weights(toas)

    def cov_matrix(self, model, toas) -> np.ndarray:
        F, phi = self.basis_weight_pair(model, toas)
        return (F * phi) @ F.T


class PLRedNoise(_PLNoiseBase):
    """Achromatic power-law red noise (reference ``noise_model.py:967``).

    TNREDAMP is log10 amplitude in the GW convention; the tempo1-style
    RNAMP/RNIDX pair is converted on read.
    """

    register = True
    category = "pl_red_noise"
    _pl_prefix = "rn"
    _plc = ("TNREDAMP", "TNREDGAM", "TNREDC", "TNREDFLOG",
            "TNREDFLOG_FACTOR", "TNREDTSPAN", 30)

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("RNAMP", units="", description="Red-noise amplitude (tempo1 convention)"))
        self.add_param(floatParameter("RNIDX", units="", description="Red-noise spectral index (tempo1)"))
        self.add_param(floatParameter("TNREDAMP", units="", description="log10 red-noise amplitude"))
        self.add_param(floatParameter("TNREDGAM", units="", description="Red-noise spectral index gamma"))
        self.add_param(intParameter("TNREDC", description="Number of linear red-noise modes"))
        self.add_param(intParameter("TNREDFLOG", description="Number of log-spaced modes"))
        self.add_param(floatParameter("TNREDFLOG_FACTOR", units="", description="Log-spacing factor"))
        self.add_param(floatParameter("TNREDTSPAN", units="year", description="Fundamental-period override"))

    def get_plc_vals(self):
        if self.TNREDAMP.value is None and self.RNAMP.value is not None:
            # tempo1 RNAMP (us yr^1/2-ish) -> GW-convention amplitude
            fac = (86400.0 * 365.24 * 1e6) / (2.0 * np.pi * np.sqrt(3.0))
            amp = self.RNAMP.value / fac
            gam = -1.0 * self.RNIDX.value
            n_lin = int(self.TNREDC.value or 30)
            nlog = self.TNREDFLOG.value
            n_log = int(nlog) if nlog is not None else None
            facl = self.TNREDFLOG_FACTOR.value or 2.0
            fmr = 1.0 / facl**n_log if n_log is not None else 1.0
            return amp, gam, n_lin, n_log, fmr
        return super().get_plc_vals()


class PLDMNoise(_PLNoiseBase):
    """Power-law DM noise: Fourier basis scaled by (1400 MHz / f)^2
    (reference ``noise_model.py:450``)."""

    register = True
    category = "pl_DM_noise"
    _pl_prefix = "dm"
    introduces_dm_errors = True
    _plc = ("TNDMAMP", "TNDMGAM", "TNDMC", "TNDMFLOG",
            "TNDMFLOG_FACTOR", "TNDMTSPAN", 30)

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("TNDMAMP", units="", description="log10 DM-noise amplitude"))
        self.add_param(floatParameter("TNDMGAM", units="", description="DM-noise spectral index"))
        self.add_param(intParameter("TNDMC", description="Number of DM-noise modes"))
        self.add_param(intParameter("TNDMFLOG", description="Number of log-spaced modes"))
        self.add_param(floatParameter("TNDMFLOG_FACTOR", units="", description="Log-spacing factor"))
        self.add_param(floatParameter("TNDMTSPAN", units="year", description="Fundamental-period override"))

    def _chromatic_scale(self, model, toas):
        return (_FREF_MHZ / _bary_freq_mhz(model, toas)) ** 2


class PLChromNoise(_PLNoiseBase):
    """Power-law chromatic noise with index TNCHROMIDX from the ChromaticCM
    component (reference ``noise_model.py:785``)."""

    register = True
    category = "pl_chrom_noise"
    _pl_prefix = "chrom"
    _plc = ("TNCHROMAMP", "TNCHROMGAM", "TNCHROMC", "TNCHROMFLOG",
            "TNCHROMFLOG_FACTOR", "TNCHROMTSPAN", 30)

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("TNCHROMAMP", units="", description="log10 chromatic-noise amplitude"))
        self.add_param(floatParameter("TNCHROMGAM", units="", description="Chromatic-noise spectral index"))
        self.add_param(intParameter("TNCHROMC", description="Number of chromatic-noise modes"))
        self.add_param(intParameter("TNCHROMFLOG", description="Number of log-spaced modes"))
        self.add_param(floatParameter("TNCHROMFLOG_FACTOR", units="", description="Log-spacing factor"))
        self.add_param(floatParameter("TNCHROMTSPAN", units="year", description="Fundamental-period override"))

    def _chromatic_scale(self, model, toas):
        alpha = 4.0
        if model is not None and "TNCHROMIDX" in model:
            alpha = float(model.TNCHROMIDX.value or 4.0)
        return (_FREF_MHZ / _bary_freq_mhz(model, toas)) ** alpha


class PLSWNoise(_PLNoiseBase):
    """Power-law solar-wind density fluctuations: Fourier basis scaled by the
    solar-wind DM geometry at n_earth = 1 cm^-3 (reference
    ``noise_model.py:623``)."""

    register = True
    category = "pl_sw_noise"
    _pl_prefix = "sw"
    _plc = ("TNSWAMP", "TNSWGAM", "TNSWC", "TNSWFLOG",
            "TNSWFLOG_FACTOR", None, 100)

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("TNSWAMP", units="", description="log10 solar-wind-noise amplitude"))
        self.add_param(floatParameter("TNSWGAM", units="", description="Solar-wind-noise spectral index"))
        self.add_param(intParameter("TNSWC", description="Number of solar-wind-noise modes"))
        self.add_param(intParameter("TNSWFLOG", description="Number of log-spaced modes"))
        self.add_param(floatParameter("TNSWFLOG_FACTOR", units="", description="Log-spacing factor"))

    def _chromatic_scale(self, model, toas):
        sw = model.components.get("SolarWindDispersion")
        if sw is None:
            raise ValueError("PLSWNoise requires a SolarWindDispersion component")
        geometry = np.asarray(
            sw.solar_wind_geometry(model._const_pv(), toas.to_batch()))
        freq = _bary_freq_mhz(model, toas)
        return geometry * DMconst / freq**2


# -- reference-spelled aliases (``noise_model.py:1180-1345``) -------------
create_ecorr_quantization_matrix = ecorr_quantization_matrix
create_fourier_design_matrix = fourier_design_matrix
#: reference spellings (``noise_model.py:1160,1201``)
get_ecorr_epochs = ecorr_epochs


def get_rednoise_freqs(t, nmodes, Tspan=None, logmode=None, f_min=None,
                       nlog=None):
    """Red-noise Fourier frequencies over the data span (reference
    ``noise_model.py:1201``): ``nmodes`` linear modes k/T, optionally
    preceded by ``nlog`` log-spaced modes below 1/T.  ``t`` is TOA times in
    seconds (any units cancel against Tspan)."""
    import numpy as _np

    T = float(Tspan) if Tspan is not None else float(_np.max(t) - _np.min(t))
    if logmode is not None and not (nlog and f_min):
        raise ValueError(
            "logmode requires nlog and f_min (reference noise_model.py:1201 "
            "log-spaced parameters must all be provided)")
    if nlog and nlog > 0:
        ratio = f_min * T if f_min else 1.0
        return rednoise_freqs(T, int(nmodes), n_log=int(nlog),
                              f_min_ratio=ratio)
    return rednoise_freqs(T, int(nmodes))



def get_ecorr_nweights(t_s, dt: float = 1.0, nmin: int = 2) -> int:
    """Number of ECORR epochs the quantization basis will carry (reference
    ``noise_model.py get_ecorr_nweights``)."""
    return len(ecorr_epochs(np.asarray(t_s, dtype=np.float64), dt=dt,
                            nmin=nmin))

