"""Tropospheric propagation delay: Davis zenith delay + Niell mapping.

Reference ``troposphere_delay.py:16``: hydrostatic zenith delay from surface
pressure (US standard atmosphere vs altitude), scaled by the Niell (1996)
mapping function of source altitude (with annual coefficient variation and
a height correction); the wet zenith delay is zero by default (tempo2
convention).  The delay has no fittable parameters and depends only weakly
on the (frozen) sky position, so the whole per-TOA delay is computed on the
host in ``build_context`` with astropy alt-az and baked into the trace —
the TPU-idiomatic treatment of quasi-static inputs.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu.logging import log
from pint_tpu.models.parameter import boolParameter
from pint_tpu.models.timing_model import DelayComponent

__all__ = ["TroposphereDelay"]

C_M_S = 299792458.0
EARTH_R_KM = 6356.766  # US std atmosphere polar radius used by the reference

# Niell hydrostatic coefficients at latitudes 0,15,30,45,60,75,90 deg
_LAT = np.array([0.0, 15.0, 30.0, 45.0, 60.0, 75.0, 90.0])
_A_AVG = np.array([0.0, 1.2769934, 1.2683230, 1.2465397, 1.2196049, 1.2045996, 0.0]) * 1e-3
_B_AVG = np.array([0.0, 2.9153695, 2.9152299, 2.9288445, 2.9022565, 2.9024912, 0.0]) * 1e-3
_C_AVG = np.array([0.0, 62.610505, 62.837393, 63.721774, 63.824265, 64.258455, 0.0]) * 1e-3
_A_AMP = np.array([0.0, 0.0, 1.2709626, 2.6523662, 3.4000452, 4.1202191, 0.0]) * 1e-5
_B_AMP = np.array([0.0, 0.0, 2.1414979, 3.0160779, 7.2562722, 11.723375, 0.0]) * 1e-5
_C_AMP = np.array([0.0, 0.0, 9.0128400, 4.3497037, 84.795348, 170.37206, 0.0]) * 1e-5
_A_HT, _B_HT, _C_HT = 2.53e-5, 5.49e-3, 1.14e-3
# wet-map coefficients
_AW = np.array([0.0, 5.8021897, 5.6794847, 5.8118019, 5.9727542, 6.1641693, 0.0]) * 1e-4
_BW = np.array([0.0, 1.4275268, 1.5138625, 1.4572752, 1.5007428, 1.7599082, 0.0]) * 1e-3
_CW = np.array([0.0, 4.3472961, 4.6729510, 4.3908931, 4.4626982, 5.4736038, 0.0]) * 1e-2

_MIN_ALT_DEG = 5.0

# WGS84 ellipsoid
_WGS84_A = 6378137.0
_WGS84_F = 1.0 / 298.257223563
_WGS84_E2 = _WGS84_F * (2.0 - _WGS84_F)


def _geodetic_lat_height(xyz_m):
    """Geodetic latitude [rad] and height [m] from ITRF xyz (Bowring's
    iteration; replaces astropy EarthLocation in a dependency-free stack)."""
    x, y, z = xyz_m
    p = np.hypot(x, y)
    lat = np.arctan2(z, p * (1 - _WGS84_E2))
    for _ in range(5):
        sin_lat = np.sin(lat)
        N = _WGS84_A / np.sqrt(1 - _WGS84_E2 * sin_lat**2)
        h = p / np.cos(lat) - N
        lat = np.arctan2(z, p * (1 - _WGS84_E2 * N / (N + h)))
    sin_lat = np.sin(lat)
    N = _WGS84_A / np.sqrt(1 - _WGS84_E2 * sin_lat**2)
    h = p / np.cos(lat) - N
    return float(lat), float(h)


def _geodetic_up(xyz_m):
    """Unit surface-normal (geodetic zenith) in ITRF."""
    lat, _ = _geodetic_lat_height(xyz_m)
    lon = np.arctan2(xyz_m[1], xyz_m[0])
    return np.array([np.cos(lat) * np.cos(lon), np.cos(lat) * np.sin(lon),
                     np.sin(lat)])


def _herring_map(alt_rad, a, b, c):
    sin_e = np.sin(alt_rad)
    top = 1.0 + a / (1.0 + b / (1.0 + c))
    bot = sin_e + a / (sin_e + b / (sin_e + c))
    return top / bot


def _interp_coeff(abs_lat_deg, avg, amp, year_frac):
    """Nearest-neighbor latitude interpolation of the annual coefficient
    (reference ``troposphere_delay.py mapping_function``)."""
    vals = avg[None, :] + amp[None, :] * np.cos(2 * np.pi * year_frac)[:, None]
    out = np.empty(len(year_frac))
    for j in range(len(year_frac)):
        out[j] = np.interp(abs_lat_deg, _LAT, vals[j])
    return out


def pressure_from_altitude_kpa(h_m: float) -> float:
    """US standard atmosphere (CRC handbook ch. 14) pressure at altitude."""
    h_km = h_m / 1e3
    gph = EARTH_R_KM * h_km / (EARTH_R_KM + h_km)
    if gph > 11.0:
        log.warning("Pressure approximation invalid above 11 km")
    T = 288.15 - 0.0065 * gph * 1e3
    return 101.325 * (288.15 / T) ** -5.25575


def zenith_delay_s(lat_rad: float, h_m: float) -> float:
    """Davis et al. (1985) hydrostatic zenith delay in seconds."""
    p = pressure_from_altitude_kpa(h_m)
    return (p / 43.921) / (C_M_S * (1 - 0.00266 * np.cos(2 * lat_rad)
                                    - 0.00028 * h_m / 1e3))


class TroposphereDelay(DelayComponent):
    register = True
    category = "troposphere"

    def __init__(self):
        super().__init__()
        self.add_param(boolParameter("CORRECT_TROPOSPHERE", value=True,
                                     description="Enable tropospheric delay"))

    def build_context(self, toas):
        if not bool(self.CORRECT_TROPOSPHERE.value):
            return {"delay": jnp.zeros(len(toas))}
        try:
            delay = self._compute_host_delay(toas)
        except Exception as e:  # barycentric TOAs etc. have no altitude
            log.warning(f"Troposphere delay disabled: {e}")
            delay = np.zeros(len(toas))
        return {"delay": jnp.asarray(delay)}

    def _compute_host_delay(self, toas) -> np.ndarray:
        from pint_tpu.earth import itrf_to_gcrs_matrix
        from pint_tpu.observatory import get_observatory

        astro = None
        for comp in (self._parent.components if self._parent else {}).values():
            if hasattr(comp, "coords_as_ICRS"):
                astro = comp
        if astro is None:
            raise ValueError("no astrometry component for source position")
        ra, dec = astro.coords_as_ICRS()
        psr = np.array([np.cos(dec) * np.cos(ra), np.cos(dec) * np.sin(ra),
                        np.sin(dec)])

        utc = np.asarray(toas.get_mjds(), dtype=np.float64)
        delay = np.zeros(len(toas))
        for site in np.unique(toas.get_obss()):
            m = toas.get_obss() == site
            obs = get_observatory(site)
            xyz = getattr(obs, "itrf_xyz", None)
            if xyz is None:
                continue  # barycenter/geocenter: no troposphere
            lat, h_m = _geodetic_lat_height(xyz)
            # source altitude = 90 deg - angle(zenith, psr); the geodetic
            # zenith in GCRS comes from rotating the ITRF surface normal
            up_itrf = _geodetic_up(xyz)
            R = itrf_to_gcrs_matrix(utc[m])  # (n,3,3)
            zen = np.einsum("nij,j->ni", R, up_itrf)
            alt = np.pi / 2 - np.arccos(np.clip(zen @ psr, -1.0, 1.0))
            valid = alt >= np.radians(_MIN_ALT_DEG)
            if not np.all(valid):
                log.warning(f"{np.sum(~valid)} TOAs below {_MIN_ALT_DEG} deg "
                            f"altitude at {site}: troposphere delay zeroed")
            # year fraction from MJD (reference _get_year_fraction_fast)
            yf = ((utc[m] - 28.0) % 365.25) / 365.25
            if lat < 0:
                yf = (yf + 0.5) % 1.0
            abs_lat = abs(np.degrees(lat))
            a = _interp_coeff(abs_lat, _A_AVG, _A_AMP, yf)
            b = _interp_coeff(abs_lat, _B_AVG, _B_AMP, yf)
            c = _interp_coeff(abs_lat, _C_AVG, _C_AMP, yf)
            base = _herring_map(alt, a, b, c)
            fcorr = _herring_map(alt, _A_HT, _B_HT, _C_HT)
            hmap = base + (1.0 / np.sin(alt) - fcorr) * (h_m / 1e3)
            aw = np.interp(abs_lat, _LAT, _AW)
            bw = np.interp(abs_lat, _LAT, _BW)
            cw = np.interp(abs_lat, _LAT, _CW)
            wet_map = _herring_map(alt, aw, bw, cw)
            wet_zenith = 0.0  # tempo2 default; hook for weather data
            d = zenith_delay_s(lat, h_m) * hmap + wet_zenith * wet_map
            d = np.where(valid, d, 0.0)
            delay[m] = d
        return delay

    # -- reference-named evaluation surface (troposphere_delay.py:16+) -----
    def troposphere_delay(self, toas, acc_delay=None) -> np.ndarray:
        """Total tropospheric delay [s] at the TOAs (reference
        ``troposphere_delay.py troposphere_delay``): zero when
        CORRECT_TROPOSPHERE is off or the site has no ground location —
        exactly what the model applies."""
        if not bool(self.CORRECT_TROPOSPHERE.value):
            return np.zeros(len(toas))
        try:
            return self._compute_host_delay(toas)
        except ValueError:
            # barycentric/space TOAs: no troposphere (matches build_context)
            return np.zeros(len(toas))

    def pressure_from_altitude(self, h_m: float) -> float:
        """Surface pressure [kPa] from altitude (reference
        ``troposphere_delay.py pressure_from_altitude``)."""
        return pressure_from_altitude_kpa(h_m)

    def zenith_delay(self, lat_rad: float, h_m: float) -> float:
        """Hydrostatic zenith delay [s] (reference
        ``troposphere_delay.py zenith_delay``)."""
        return zenith_delay_s(lat_rad, h_m)

    def wet_zenith_delay(self) -> float:
        """Wet zenith delay [s]: zero, the tempo2 default without weather
        data (reference ``troposphere_delay.py:250``)."""
        return 0.0

    def mapping_function(self, alt_rad, lat_rad, h_m: float,
                         year_frac=0.0) -> np.ndarray:
        """Niell hydrostatic mapping function incl. height correction
        (reference ``troposphere_delay.py mapping_function``); ``alt_rad``
        and ``year_frac`` broadcast per TOA.  Southern sites get the same
        half-year seasonal shift the model's own delay path applies."""
        alt = np.atleast_1d(np.asarray(alt_rad, dtype=np.float64))
        lat = float(lat_rad)
        yf = np.broadcast_to(
            np.asarray(year_frac, dtype=np.float64), alt.shape).copy()
        if lat < 0:
            yf = (yf + 0.5) % 1.0
        abs_lat = abs(np.degrees(lat))
        a = _interp_coeff(abs_lat, _A_AVG, _A_AMP, yf)
        b = _interp_coeff(abs_lat, _B_AVG, _B_AMP, yf)
        c = _interp_coeff(abs_lat, _C_AVG, _C_AMP, yf)
        base = _herring_map(alt, a, b, c)
        fcorr = _herring_map(alt, _A_HT, _B_HT, _C_HT)
        out = base + (1.0 / np.sin(alt) - fcorr) * (float(h_m) / 1e3)
        return out.reshape(np.shape(alt_rad)) if np.shape(alt_rad) else out[0]

    def wet_map(self, alt_rad, lat_rad) -> np.ndarray:
        """Niell wet mapping function (reference
        ``troposphere_delay.py wet_map``)."""
        alt = np.asarray(alt_rad, dtype=np.float64)
        abs_lat = abs(np.degrees(float(lat_rad)))
        aw = np.interp(abs_lat, _LAT, _AW)
        bw = np.interp(abs_lat, _LAT, _BW)
        cw = np.interp(abs_lat, _LAT, _CW)
        return _herring_map(alt, aw, bw, cw)

    #: reference name for the full delay model
    delay_model = troposphere_delay

    def delay_func(self, pv, batch, ctx, acc_delay):
        return ctx["delay"]
