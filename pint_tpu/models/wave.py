"""Tempo-style WAVE sinusoidal timing-noise model (phase component).

Reference ``wave.py:11,148``: phase = F0 * sum_k [a_k sin(k*om*dt) +
b_k cos(k*om*dt)], om = WAVE_OM [rad/day], dt = t_bary - WAVEEPOCH [days],
(a_k, b_k) = WAVEk [seconds] pair parameters.
"""

from __future__ import annotations

import jax.numpy as jnp

from pint_tpu.exceptions import MissingParameter
from pint_tpu.models.parameter import MJDParameter, floatParameter, pairParameter
from pint_tpu.models.timing_model import DAY_S, PhaseComponent
from pint_tpu.phase import Phase

__all__ = ["Wave"]


class Wave(PhaseComponent):
    register = True
    category = "wave"

    def __init__(self):
        super().__init__()
        self.add_param(MJDParameter("WAVEEPOCH",
                                    description="Reference epoch for wave solution"))
        self.add_param(floatParameter("WAVE_OM", units="rad/d",
                                      description="Base frequency of wave solution"))
        self.add_param(pairParameter("WAVE1", units="s", continuous=False,
                                     description="Wave sin/cos amplitudes"))
        self.num_wave_terms = 1

    def setup(self):
        terms = sorted(int(p[4:]) for p in self.params
                       if p.startswith("WAVE") and p[4:].isdigit())
        self.num_wave_terms = len(terms)
        if terms and terms != list(range(1, max(terms) + 1)):
            missing = min(set(range(1, max(terms) + 1)) - set(terms))
            raise MissingParameter("Wave", f"WAVE{missing}")

    def validate(self):
        if self.WAVE_OM.value is None:
            raise MissingParameter("Wave", "WAVE_OM")
        if self.WAVEEPOCH.value is None:
            pep = getattr(self._parent, "PEPOCH", None)
            if pep is None or pep.value is None:
                raise MissingParameter("Wave", "WAVEEPOCH",
                                       "WAVEEPOCH or PEPOCH required")
            self.WAVEEPOCH.value = pep.value

    def phase_func(self, pv, batch, ctx, delay):
        epoch = pv["WAVEEPOCH"]
        epoch = epoch.to_float() if hasattr(epoch, "to_float") else epoch
        dt_day = (batch.tdb.hi - epoch) + batch.tdb.lo - delay / DAY_S
        base = pv.get("WAVE_OM", 0.0) * dt_day
        times = jnp.zeros(batch.ntoas)
        for k in range(1, self.num_wave_terms + 1):
            # value check on the host parameter: an unset pair exemplar is
            # mapped to scalar 0.0 by _const_pv and must be skipped here
            if self._params_dict[f"WAVE{k}"].value is None:
                continue
            ab = pv.get(f"WAVE{k}")
            arg = k * base
            times = times + ab[0] * jnp.sin(arg) + ab[1] * jnp.cos(arg)
        return Phase.from_float(times * pv.get("F0", 0.0))
