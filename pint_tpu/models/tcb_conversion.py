"""TCB <-> TDB conversion of timing models.

Counterpart of reference ``tcb_conversion.py`` (same Irwin & Fukushima 1999
constants as tempo2): parameters scale by IFTE_K to the power of their
effective time dimensionality; epochs transform linearly about IFTE_MJD0.
The conversion is approximate — re-fit afterwards (same caveat as the
reference).
"""

from __future__ import annotations

import re

import numpy as np

from pint_tpu.logging import log
from pint_tpu.models.parameter import AngleParameter, MJDParameter

__all__ = ["IFTE_K", "IFTE_MJD0", "scale_parameter",
           "transform_mjd_parameter", "convert_tcb_tdb"]

IFTE_MJD0 = np.longdouble("43144.0003725")
IFTE_KM1 = np.longdouble("1.55051979176e-8")
IFTE_K = np.longdouble(1.0) + IFTE_KM1

#: effective dimensionality rules: exact names, then regex families.
#: The table lists each parameter's frequency-dimensionality (F0 -> 1,
#: F1 -> 2, A1 -> -1 because it enters as a time).  TCB seconds are shorter
#: than TDB seconds by IFTE_K, so frequencies grow under TCB->TDB:
#: x_tdb = x_tcb * K^dim (equivalently x_tcb / K^n with n the
#: time-dimensionality, reference ``tcb_conversion.py`` +
#: ``docs/tcb2tdb-factors.rst``): F0 and DM multiply by K, A1 divides by K.
_EXACT_DIM = {
    "PX": 1, "PMRA": 1, "PMDEC": 1, "PMELONG": 1, "PMELAT": 1,
    "A1": -1, "PB": -1, "OMDOT": 1, "EDOT": 1, "M2": -1, "MTOT": -1,
    "GAMMA": -1, "EPS1DOT": 1, "EPS2DOT": 1, "H3": -1, "H4": -1,
    "NE_SW": 1, "GLTD": -1,
    # dimensionless / angles / unscaled
    "ECC": 0, "OM": 0, "EPS1": 0, "EPS2": 0, "SINI": 0, "SHAPMAX": 0,
    "STIGMA": 0, "KIN": 0, "KOM": 0, "PBDOT": 0, "XPBDOT": 0, "A1DOT": 0,
    "RAJ": 0, "DECJ": 0, "ELONG": 0, "ELAT": 0, "GLPH": 0, "LNEDOT": 0,
}
_FAMILY_DIM = [
    (re.compile(r"^F(\d+)$"), lambda n: n + 1),
    (re.compile(r"^FB(\d+)$"), lambda n: n + 1),
    (re.compile(r"^DM(\d*)$"), lambda n: (n or 0) + 1),
    (re.compile(r"^DMX_\d+$"), lambda n: 1),
    (re.compile(r"^CM(\d*)$"), lambda n: (n or 0) + 1),
    (re.compile(r"^GLF0D?_\d+$"), lambda n: 1),
    (re.compile(r"^GLF1_\d+$"), lambda n: 2),
    (re.compile(r"^GLF2_\d+$"), lambda n: 3),
    (re.compile(r"^JUMP\d*$"), lambda n: -1),
    (re.compile(r"^NE_SW(\d+)$"), lambda n: n + 1),
]


def _effective_dim(name: str):
    if name in _EXACT_DIM:
        return _EXACT_DIM[name]
    for pat, fn in _FAMILY_DIM:
        m = pat.match(name)
        if m:
            g = m.groups()[0] if m.groups() else None
            return fn(int(g) if g else None)
    return None


def scale_parameter(model, param: str, n: int, backwards: bool = False):
    """x_tdb = x_tcb * IFTE_K**n (reference ``tcb_conversion.py:29``)."""
    p = -1 if backwards else 1
    factor = float(IFTE_K ** (p * n))
    if param in model and getattr(model, param).value is not None:
        par = getattr(model, param)
        par.value = par.value * factor
        if par.uncertainty is not None:
            par.uncertainty = par.uncertainty * factor


def transform_mjd_parameter(model, param: str, backwards: bool = False):
    """t_tdb = (t_tcb - IFTE_MJD0)/IFTE_K + IFTE_MJD0
    (reference ``tcb_conversion.py:70``)."""
    factor = IFTE_K if backwards else 1.0 / IFTE_K
    if param in model and getattr(model, param).value is not None:
        par = getattr(model, param)
        v = np.longdouble(par.value)
        par.value = float((v - IFTE_MJD0) * factor + IFTE_MJD0) \
            if not isinstance(par.value, np.longdouble) else \
            (v - IFTE_MJD0) * factor + IFTE_MJD0
        if par.uncertainty is not None:
            par.uncertainty = float(par.uncertainty * float(factor))


def convert_tcb_tdb(model, backwards: bool = False):
    """In-place approximate TCB->TDB (or back) conversion
    (reference ``tcb_conversion.py:98``)."""
    target = "TCB" if backwards else "TDB"
    if model.UNITS.value == target or (model.UNITS.value is None
                                       and not backwards):
        log.warning("Model already in target units; doing nothing")
        return model
    log.warning("Converting TCB<->TDB: the transformation is approximate; "
                "re-fit the resulting model")
    for name in model.params:
        if name in model.top_level_params:
            continue
        par = getattr(model, name)
        if par.value is None:
            continue
        if isinstance(par, MJDParameter):
            transform_mjd_parameter(model, name, backwards)
            continue
        if isinstance(par, AngleParameter):
            continue
        dim = _effective_dim(name)
        if dim:
            scale_parameter(model, name, dim, backwards)
    model.UNITS.value = target
    model.validate(allow_tcb=backwards)
    return model


def compute_effective_dimensionality(param_name: str) -> int:
    """Effective time-dimensionality n of a parameter for TCB<->TDB scaling
    (x_tdb = x_tcb * IFTE_K**n).

    The reference computes n from the astropy unit of
    ``quantity * scaling_factor`` (``parameter.py:2600``); this build keys
    the same information by parameter name (the tables this module's
    converter uses).  Raises ValueError for a parameter with no defined
    scaling.
    """
    dim = _effective_dim(str(param_name).upper())
    if dim is None:
        raise ValueError(
            f"No TCB<->TDB effective dimensionality defined for "
            f"{param_name!r}")
    return int(dim)
