"""Dispersion delays: DM Taylor series, DMX piecewise, DMJUMP.

Reference ``dispersion_model.py``: delay = K * DM(t) / f^2 with
K = 1/2.41e-4 s MHz^2 cm^3/pc (``pint.DMconst``); DM(t) is a Taylor series in
*years* about DMEPOCH (``dispersion_model.py:214 base_dm``).  Frequencies are
barycentric when an astrometry component is present
(``dispersion_model.py:51``).  DMX epochs are mask parameters resolved to
per-range boolean arrays on the host.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu import DMconst
from pint_tpu.exceptions import MissingParameter
from pint_tpu.models.parameter import (MJDParameter, floatParameter,
                                       maskParameter, prefixParameter)
from pint_tpu.models.timing_model import DelayComponent, check_contiguous_indices

__all__ = ["Dispersion", "DispersionDM", "DispersionDMX", "DispersionJump",
           "FDJumpDM"]

_DAY_PER_YEAR = 365.25


class Dispersion(DelayComponent):
    category = "dispersion_constant"

    def dispersion_time_delay(self, dm, freq):
        return dm * DMconst / freq**2


class DispersionDM(Dispersion):
    """Reference ``dispersion_model.py:129``."""

    register = True

    def __init__(self):
        super().__init__()
        self.add_param(prefixParameter("DM0", units="pc/cm3", description="Dispersion measure"))
        # DM is the canonical name for index 0
        dm0 = self._params_dict.pop("DM0")
        self.params.remove("DM0")
        dm0.name = "DM"
        dm0.prefix, dm0.index = "DM", 0
        self.add_param(dm0)
        self.add_param(prefixParameter("DM1", units="pc/cm3/yr", value=0.0,
                                       description="DM derivative"))
        self.add_param(MJDParameter("DMEPOCH", description="Epoch of DM measurement"))
        self.num_dm_terms = 2

    def setup(self):
        idxs = [0] + sorted(
            int(name[2:]) for name in self.params
            if name.startswith("DM") and name[2:].isdigit() and name != "DM"
        )
        check_contiguous_indices(idxs, "DispersionDM", "DM")
        self.num_dm_terms = len(idxs)

    def validate(self):
        if self.DM.value is None:
            raise MissingParameter("DispersionDM", "DM")
        higher = any((self._params_dict.get(f"DM{i}") is not None
                      and self._params_dict[f"DM{i}"].value)
                     for i in range(1, self.num_dm_terms))
        if higher and self.DMEPOCH.value is None:
            pep = getattr(self._parent, "PEPOCH", None)
            if pep is not None and pep.value is not None:
                self.DMEPOCH.value = pep.value
            else:
                raise MissingParameter("DispersionDM", "DMEPOCH")

    def get_dm_terms(self, pv):
        return [pv.get("DM", 0.0)] + [pv.get(f"DM{i}", 0.0)
                                      for i in range(1, self.num_dm_terms)]

    def base_dm(self, pv, batch):
        terms = self.get_dm_terms(pv)
        if len(terms) == 1:
            return terms[0] * jnp.ones_like(batch.freq)
        if self.DMEPOCH.value is not None and "DMEPOCH" in pv:
            dmepoch = pv["DMEPOCH"]
            dmepoch = dmepoch.to_float() if hasattr(dmepoch, "to_float") else dmepoch
        else:
            dmepoch = batch.tdb0
        dt_yr = (batch.tdb.hi - dmepoch) / _DAY_PER_YEAR
        import math

        acc = jnp.zeros_like(dt_yr)
        for i in range(len(terms) - 1, -1, -1):
            acc = acc * dt_yr + terms[i] / math.factorial(i)
        return acc

    def dm_func(self, pv, batch, ctx):
        return self.base_dm(pv, batch)

    def delay_func(self, pv, batch, ctx, acc_delay):
        freq = self.barycentric_freq(pv, batch)
        return self.dispersion_time_delay(self.base_dm(pv, batch), freq)

    def change_dmepoch(self, new_epoch):
        """Shift DMEPOCH, adjusting the DM Taylor terms so the DM(t) curve is
        unchanged (reference ``dispersion_model.py:274``)."""
        from pint_tpu.utils import taylor_horner_deriv

        terms = [float(self._params_dict["DM"].value or 0.0)] + [
            float(self._params_dict[f"DM{i}"].value or 0.0)
            for i in range(1, self.num_dm_terms)]
        if self.DMEPOCH.value is None:
            if any(t != 0.0 for t in terms[1:]):
                raise ValueError(
                    "DMEPOCH is not set but DM derivatives are nonzero")
            self.DMEPOCH.value = np.longdouble(new_epoch)
            return
        dt_yr = float((np.longdouble(new_epoch)
                       - np.longdouble(self.DMEPOCH.value)) / _DAY_PER_YEAR)
        for i in range(len(terms)):
            name = "DM" if i == 0 else f"DM{i}"
            self._params_dict[name].value = float(
                taylor_horner_deriv(dt_yr, terms, deriv_order=i))
        self.DMEPOCH.value = np.longdouble(new_epoch)


class DispersionDMX(Dispersion):
    """Piecewise-epoch DM offsets (reference ``dispersion_model.py:307``)."""

    register = True
    category = "dispersion_dmx"

    def __init__(self):
        super().__init__()
        # bare DMX: the nominal bin width [d] (reference
        # ``dispersion_model.py DMX`` parameter; informational)
        self.add_param(floatParameter("DMX", units="d", frozen=True,
                                      description="Nominal DMX bin width"))
        self.add_param(prefixParameter("DMX_0001", units="pc/cm3", value=0.0,
                                       description="DM offset in range"))
        self.add_param(prefixParameter("DMXR1_0001", units="MJD",
                                       description="Range start MJD"))
        self.add_param(prefixParameter("DMXR2_0001", units="MJD",
                                       description="Range end MJD"))
        self.dmx_indices = [1]

    def setup(self):
        self.dmx_indices = sorted(
            int(name[4:]) for name in self.params if name.startswith("DMX_")
        )

    # -- reference range-management API (dispersion_model.py:343-470) -------
    def get_indices(self):
        """Indices of the DMX ranges in use (reference
        ``dispersion_model.py get_indices``)."""
        import numpy as _np

        return _np.array(self.dmx_indices)

    def add_DMX_range(self, mjd_start, mjd_end, index=None, dmx=0.0,
                      frozen=True):
        """Add one DMX range (reference ``dispersion_model.py:343``);
        returns the assigned index."""
        if index is None:
            index = max(self.dmx_indices, default=0) + 1
        index = int(index)
        if float(mjd_end) < float(mjd_start):
            raise ValueError("mjd_end must come after mjd_start")
        nm = f"DMX_{index:04d}"
        if nm in self._params_dict and self._params_dict[nm].value not in (None,):
            if index in self.dmx_indices and \
                    self._params_dict.get(f"DMXR1_{index:04d}") is not None \
                    and self._params_dict[f"DMXR1_{index:04d}"].value is not None:
                raise ValueError(f"DMX index {index} already in use")
        for pre, val, fr in (("DMX_", float(dmx), bool(frozen)),
                             ("DMXR1_", float(mjd_start), True),
                             ("DMXR2_", float(mjd_end), True)):
            pnm = f"{pre}{index:04d}"
            if pnm in self._params_dict:
                self._params_dict[pnm].value = val
                if pre == "DMX_":
                    self._params_dict[pnm].frozen = fr
            else:
                try:
                    exemplar = next(self._params_dict[q]
                                    for q in self.params
                                    if q.startswith(pre))
                except StopIteration:
                    raise KeyError(
                        f"No {pre} parameter left to use as an exemplar")
                p = exemplar.new_param(index, value=val)
                if pre == "DMX_":
                    p.frozen = fr
                self.add_param(p)
        self.setup()
        if self._parent is not None:
            self._parent._cache.clear()
        return index

    def add_DMX_ranges(self, mjd_starts, mjd_ends, indices=None, dmxs=0.0,
                       frozens=True):
        """Add several DMX ranges (reference ``dispersion_model.py
        add_DMX_ranges``)."""
        import numpy as _np

        mjd_starts = _np.atleast_1d(mjd_starts)
        mjd_ends = _np.atleast_1d(mjd_ends)
        n = len(mjd_starts)
        if len(mjd_ends) != n:
            raise ValueError("mjd_starts and mjd_ends must match in length")
        if indices is None:
            start = max(self.dmx_indices, default=0)
            indices = list(range(start + 1, start + 1 + n))
        dmxs = _np.broadcast_to(_np.atleast_1d(dmxs), (n,))
        frozens = _np.broadcast_to(_np.atleast_1d(frozens), (n,))
        if len(set(int(i) for i in indices)) != n:
            raise ValueError("Duplicate indices in add_DMX_ranges")
        return [self.add_DMX_range(s0, e0, index=int(i), dmx=d, frozen=bool(f))
                for s0, e0, i, d, f in zip(mjd_starts, mjd_ends, indices,
                                           dmxs, frozens)]

    def remove_DMX_range(self, index):
        """Remove DMX range(s) by index (reference ``dispersion_model.py
        remove_DMX_range``)."""
        import numpy as _np

        for idx in _np.atleast_1d(index):
            idx = int(idx)
            for pre in ("DMX_", "DMXR1_", "DMXR2_"):
                self.remove_param(f"{pre}{idx:04d}")
        self.setup()
        if self._parent is not None:
            self._parent._cache.clear()

    def validate(self):
        for i in self.dmx_indices:
            for pre in ("DMXR1_", "DMXR2_"):
                nm = f"{pre}{i:04d}"
                if nm not in self._params_dict or self._params_dict[nm].value is None:
                    raise MissingParameter("DispersionDMX", nm)

    def build_context(self, toas):
        mjds = np.asarray(toas.get_mjds(), dtype=np.float64)
        masks = []
        for i in self.dmx_indices:
            r1 = float(self._params_dict[f"DMXR1_{i:04d}"].value)
            r2 = float(self._params_dict[f"DMXR2_{i:04d}"].value)
            masks.append(((mjds >= r1) & (mjds <= r2)).astype(np.float64))
        return {"masks": jnp.asarray(np.array(masks)) if masks else None}

    def add_DMX_range(self, mjd_start, mjd_end, index=None, dmx=0.0,
                      frozen: bool = True) -> int:
        """Add one DMX bin (reference ``dispersion_model.py add_DMX_range``).
        Returns the assigned index."""
        if mjd_end is not None and mjd_start is not None \
                and float(mjd_end) < float(mjd_start):
            raise ValueError("Starting MJD is greater than ending MJD.")
        if index is None:
            index = max(self.dmx_indices, default=0) + 1
        index = int(index)
        if f"DMX_{index:04d}" in self._params_dict:
            raise ValueError(
                f"Index '{index}' is already in use in this model. "
                f"Please choose another.")
        if self.dmx_indices:
            # template from ANY surviving bin (bin 1 may have been merged away)
            i0 = self.dmx_indices[0]
            self.add_param(self._params_dict[f"DMX_{i0:04d}"].new_param(
                index, value=float(dmx), frozen=frozen))
            self.add_param(self._params_dict[f"DMXR1_{i0:04d}"].new_param(
                index, value=float(mjd_start)))
            self.add_param(self._params_dict[f"DMXR2_{i0:04d}"].new_param(
                index, value=float(mjd_end)))
        else:
            self.add_param(prefixParameter(
                f"DMX_{index:04d}", units="pc/cm3", value=float(dmx),
                frozen=frozen, description="DM offset in range"))
            self.add_param(prefixParameter(
                f"DMXR1_{index:04d}", units="MJD", value=float(mjd_start),
                description="Range start MJD"))
            self.add_param(prefixParameter(
                f"DMXR2_{index:04d}", units="MJD", value=float(mjd_end),
                description="Range end MJD"))
        self.setup()
        if self._parent is not None:
            self._parent.setup()
        return index

    def remove_DMX_range(self, index) -> None:
        """Remove one or more DMX bins by index (reference
        ``dispersion_model.py remove_DMX_range``)."""
        indices = [index] if isinstance(index, (int, np.integer)) else list(index)
        for i in indices:
            i = int(i)
            if f"DMX_{i:04d}" not in self._params_dict:
                raise ValueError(f"Index {i} not in DMX model")
            for pre in ("DMX_", "DMXR1_", "DMXR2_"):
                self.remove_param(f"{pre}{i:04d}")
        self.setup()
        if self._parent is not None:
            self._parent.setup()

    def dmx_dm(self, pv, batch, ctx):
        if ctx.get("masks") is None:
            return jnp.zeros_like(batch.freq)
        vals = jnp.stack([pv.get(f"DMX_{i:04d}", 0.0) for i in self.dmx_indices])
        return jnp.sum(vals[:, None] * ctx["masks"], axis=0)

    def dm_func(self, pv, batch, ctx):
        return self.dmx_dm(pv, batch, ctx)

    def delay_func(self, pv, batch, ctx, acc_delay):
        freq = self.barycentric_freq(pv, batch)
        return self.dispersion_time_delay(self.dmx_dm(pv, batch, ctx), freq)


class DispersionJump(Dispersion):
    """System-dependent DM offsets DMJUMP (reference ``dispersion_model.py:727``).

    Note: DMJUMP applies only to wideband DM measurements, not to the TOA
    delay (reference behavior); the delay contribution is zero.
    """

    register = True
    category = "dispersion_jump"

    def __init__(self):
        super().__init__()
        self.add_param(maskParameter("DMJUMP", index=1, units="pc/cm3", value=0.0,
                                     description="DM offset for selected TOAs"))
        self.dm_jumps = ["DMJUMP1"]

    def setup(self):
        self.dm_jumps = [p for p in self.params if p.startswith("DMJUMP")]

    def build_context(self, toas):
        n = len(toas)
        masks = {}
        for j in self.dm_jumps:
            idx = self._params_dict[j].select_toa_mask(toas)
            m = np.zeros(n)
            m[idx] = 1.0
            masks[j] = jnp.asarray(m)
        return {"masks": masks}

    def jump_dm(self, pv, batch, ctx):
        out = jnp.zeros_like(batch.freq)
        for j in self.dm_jumps:
            out = out - pv.get(j, 0.0) * ctx["masks"][j]
        return out

    def dm_func(self, pv, batch, ctx):
        return self.jump_dm(pv, batch, ctx)

    def delay_func(self, pv, batch, ctx, acc_delay):
        return jnp.zeros_like(batch.freq)


class FDJumpDM(Dispersion):
    """System-dependent DM offsets for narrowband datasets, with the
    corresponding dispersion delay (reference ``dispersion_model.py:808``).

    Unlike DMJUMP (wideband DM measurements only, zero delay), FDJUMPDM
    offsets *do* disperse the TOAs: delay = K * dm / f^2 with
    dm = -FDJUMPDM on the selected TOAs.
    """

    register = True
    category = "fdjumpdm"

    def __init__(self):
        super().__init__()
        self.add_param(maskParameter("FDJUMPDM", index=1, units="pc/cm3", value=0.0,
                                     description="System-dependent DM offset"))
        self.fdjump_dms = ["FDJUMPDM1"]

    def setup(self):
        self.fdjump_dms = [p for p in self.params if p.startswith("FDJUMPDM")]

    def build_context(self, toas):
        n = len(toas)
        masks = {}
        for j in self.fdjump_dms:
            idx = self._params_dict[j].select_toa_mask(toas)
            m = np.zeros(n)
            m[idx] = 1.0
            masks[j] = jnp.asarray(m)
        return {"masks": masks}

    def fdjump_dm(self, pv, batch, ctx):
        out = jnp.zeros_like(batch.freq)
        for j in self.fdjump_dms:
            out = out - pv.get(j, 0.0) * ctx["masks"][j]
        return out

    def dm_func(self, pv, batch, ctx):
        return self.fdjump_dm(pv, batch, ctx)

    def delay_func(self, pv, batch, ctx, acc_delay):
        freq = self.barycentric_freq(pv, batch)
        return self.dispersion_time_delay(self.fdjump_dm(pv, batch, ctx), freq)
