"""Astrometry: sky position, proper motion, parallax -> geometric delay.

Reference ``astrometry.py:155 solar_system_geometric_delay`` convention:
delay = -r_obs . n_psr  +  (PX term)  [seconds, positions in light-seconds].
Equatorial (RAJ/DECJ/PMRA/PMDEC) and ecliptic (ELONG/ELAT/PMELONG/PMELAT)
variants; the ecliptic frame uses the IERS2010 obliquity
(reference ``pulsar_ecliptic.py``).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu import OBL_IERS2010_RAD
from pint_tpu.exceptions import MissingParameter
from pint_tpu.models.parameter import AngleParameter, MJDParameter, floatParameter
from pint_tpu.models.timing_model import DAY_S, DelayComponent

__all__ = ["AstrometryEquatorial", "AstrometryEcliptic"]

#: mas/yr -> rad/day
_MASYR_TO_RADDAY = (np.pi / 180.0 / 3600.0 / 1000.0) / 365.25
#: kpc expressed in light-seconds
_KPC_LS = 3.0856775814913673e19 / 299792458.0
#: arcsec -> rad
_MAS_RAD = np.pi / 180.0 / 3600.0 / 1000.0


class Astrometry(DelayComponent):
    category = "astrometry"

    def ssb_to_psb_xyz(self, pv, epoch_mjd):
        """Unit vector(s) to the pulsar in ICRS at given float64 MJD(s)."""
        raise NotImplementedError

    def sun_angle_traced(self, pv, batch):
        """Pulsar-Sun elongation angle at each TOA (rad) — the ONE traced
        implementation, shared by both astrometry frames (the solar-wind
        component consumes it)."""
        L_hat = self.ssb_to_psb_xyz(pv, batch.tdb.hi)
        sun = batch.obs_sun_pos
        sun_hat = sun / jnp.linalg.norm(sun, axis=1, keepdims=True)
        return jnp.arccos(jnp.clip(jnp.sum(sun_hat * L_hat, axis=1),
                                   -1.0, 1.0))

    def barycentric_radio_freq(self, pv, batch):
        """Observed frequency corrected for observatory motion (MHz)."""
        L_hat = self.ssb_to_psb_xyz(pv, batch.tdb.hi)
        v_dot_L = jnp.sum(batch.ssb_obs_vel * L_hat, axis=1)
        return batch.freq * (1.0 - v_dot_L)

    def _geometric_delay(self, pv, batch, L_hat, px_mas):
        r = batch.ssb_obs_pos  # (N,3) light-seconds
        re_dot_L = jnp.sum(r * L_hat, axis=1)
        delay = -re_dot_L
        # parallax: 0.5 * re^2/L * (1 - (re.L)^2/re^2)   (ref astrometry.py:172-183)
        # written as a smooth multiple of PX so the PX design-matrix column is
        # nonzero even at PX == 0 (matching the reference's analytic partial)
        re_sqr = jnp.sum(r * r, axis=1)
        px_delay = (0.5 * re_sqr * (px_mas / _KPC_LS)
                    * (1.0 - re_dot_L**2 / jnp.maximum(re_sqr, 1e-30)))
        return delay + px_delay

    def delay_func(self, pv, batch, ctx, acc_delay):
        L_hat = self.ssb_to_psb_xyz(pv, batch.tdb.hi)
        return self._geometric_delay(pv, batch, L_hat, pv.get("PX", 0.0))


    # -- reference user functions (astrometry.py:114,469) -------------------
    def _pv_now(self) -> dict:
        pv = dict(self._parent._const_pv()) if self._parent is not None \
            else {}
        for p in self.params:
            v = self._params_dict[p].value
            if v is not None and isinstance(v, (int, float, np.floating)):
                pv[p] = float(v)
        return pv

    def ssb_to_psb_xyz_ICRS(self, epoch=None) -> np.ndarray:
        """Unit vector(s) SSB -> pulsar in ICRS at the given MJD epoch(s),
        proper motion applied (reference ``astrometry.py:469``)."""
        if epoch is None:
            epoch = self._posepoch_mjd_host()
        ep = jnp.asarray(np.atleast_1d(np.asarray(epoch, dtype=np.float64)))
        # both frames' ssb_to_psb_xyz return EQUATORIAL unit vectors (the
        # ecliptic variant rotates internally)
        xyz = np.asarray(self.ssb_to_psb_xyz(self._pv_now(), ep))
        return xyz.reshape(np.shape(epoch) + (3,)) if np.shape(epoch) \
            else xyz[0]

    def ssb_to_psb_xyz_ECL(self, epoch=None) -> np.ndarray:
        """Unit vector(s) SSB -> pulsar in the IERS2010 ecliptic frame:
        one vectorized inverse of the obliquity rotation the ecliptic
        component applies (``_COS_OBL``/``_SIN_OBL``).  Any epoch shape is
        accepted (flattened for the rotation, reshaped on return)."""
        xyz = np.asarray(self.ssb_to_psb_xyz_ICRS(epoch)).reshape(-1, 3)
        out = np.empty_like(xyz)
        out[:, 0] = xyz[:, 0]
        out[:, 1] = _COS_OBL * xyz[:, 1] + _SIN_OBL * xyz[:, 2]
        out[:, 2] = -_SIN_OBL * xyz[:, 1] + _COS_OBL * xyz[:, 2]
        return out.reshape(np.shape(epoch) + (3,)) if np.shape(epoch) \
            else out[0]

    def _posepoch_mjd_host(self) -> float:
        pe = self.POSEPOCH.value
        if pe is None and self._parent is not None:
            pep = getattr(self._parent, "PEPOCH", None)
            pe = pep.value if pep is not None else None
        if pe is None:
            raise ValueError("No POSEPOCH/PEPOCH to evaluate the position at")
        return float(pe)

    def get_psr_coords(self, epoch=None):
        """Sky coordinates [rad] at the epoch(s), proper motion applied,
        IN THIS COMPONENT'S FRAME — (RA, DEC) for equatorial models,
        (ELONG, ELAT) for ecliptic ones, like the reference
        (``astrometry.py get_psr_coords``).  Array epochs return arrays."""
        if isinstance(self, AstrometryEcliptic):
            v = np.asarray(self.ssb_to_psb_xyz_ECL(epoch)).reshape(-1, 3)
        else:
            v = np.asarray(self.ssb_to_psb_xyz_ICRS(epoch)).reshape(-1, 3)
        lon = np.arctan2(v[:, 1], v[:, 0]) % (2 * np.pi)
        lat = np.arcsin(np.clip(v[:, 2], -1.0, 1.0))
        if np.shape(epoch):
            return (lon.reshape(np.shape(epoch)),
                    lat.reshape(np.shape(epoch)))
        return float(lon[0]), float(lat[0])

    def sun_angle(self, toas, heliocenter: bool = True,
                  also_distance: bool = False):
        """Pulsar-observatory-Sun angle [rad] per TOA (reference
        ``astrometry.py:114``)."""
        if heliocenter:
            osv = np.asarray(toas.obs_sun_pos_km, dtype=np.float64)
        else:
            # barycenter-referenced: obs -> SSB
            osv = -np.asarray(toas.ssb_obs_pos_km, dtype=np.float64)
        r = np.sqrt(np.sum(osv**2, axis=1))
        tdb = np.asarray(toas.tdb, dtype=np.float64)
        psr = np.atleast_2d(self.ssb_to_psb_xyz_ICRS(tdb))
        cos_a = np.sum(osv * psr, axis=1) / r
        angle = np.arccos(np.clip(cos_a, -1.0, 1.0))
        return (angle, r) if also_distance else angle


class AstrometryEquatorial(Astrometry):
    """Reference ``astrometry.py:272``."""

    register = True

    def __init__(self):
        super().__init__()
        self.add_param(AngleParameter("RAJ", angle_type="hms", aliases=["RA"],
                                      description="Right ascension (J2000)"))
        self.add_param(AngleParameter("DECJ", angle_type="dms", aliases=["DEC"],
                                      description="Declination (J2000)"))
        self.add_param(floatParameter("PMRA", value=0.0, units="mas/yr",
                                      description="Proper motion in RA (mu_alpha* = mu_alpha cos(dec))"))
        self.add_param(floatParameter("PMDEC", value=0.0, units="mas/yr",
                                      description="Proper motion in DEC"))
        self.add_param(floatParameter("PX", value=0.0, units="mas", description="Parallax"))
        self.add_param(MJDParameter("POSEPOCH", description="Epoch of position"))

    def validate(self):
        if self.RAJ.value is None or self.DECJ.value is None:
            raise MissingParameter("AstrometryEquatorial", "RAJ/DECJ")
        if self.POSEPOCH.value is None and (self.PMRA.value or self.PMDEC.value):
            # fall back to PEPOCH like the reference
            pep = getattr(self._parent, "PEPOCH", None)
            if pep is not None and pep.value is not None:
                self.POSEPOCH.value = pep.value

    def _posepoch_mjd(self, batch):
        pe = self.POSEPOCH.value
        if pe is None and self._parent is not None:
            pep = getattr(self._parent, "PEPOCH", None)
            pe = pep.value if pep is not None else None
        return float(pe) if pe is not None else float(batch.tdb0)

    def ssb_to_psb_xyz(self, pv, epoch_mjd):
        ra0 = pv["RAJ"]
        dec0 = pv["DECJ"]
        # proper motion applied linearly from POSEPOCH (traced value; the
        # *presence* decision is structural, made at trace time)
        if self.POSEPOCH.value is not None and "POSEPOCH" in pv:
            pe = pv["POSEPOCH"]
            pe = pe.to_float() if hasattr(pe, "to_float") else pe
            dt_day = epoch_mjd - pe
        else:
            dt_day = jnp.zeros_like(epoch_mjd)
        dec = dec0 + pv.get("PMDEC", 0.0) * _MASYR_TO_RADDAY * dt_day
        ra = ra0 + pv.get("PMRA", 0.0) * _MASYR_TO_RADDAY * dt_day / jnp.cos(dec0)
        cd = jnp.cos(dec)
        return jnp.stack([cd * jnp.cos(ra), cd * jnp.sin(ra), jnp.sin(dec)], axis=-1)

    def build_context(self, toas):
        self._pe_cache = (float(self.POSEPOCH.value)
                          if self.POSEPOCH.value is not None else None)
        return {}

    def coords_as_ICRS(self):
        return float(self.RAJ.value), float(self.DECJ.value)

    def change_posepoch(self, new_epoch):
        """Move POSEPOCH, advancing RAJ/DECJ along the same proper-motion
        linearization the delay model evaluates (reference
        ``astrometry.py:629``)."""
        if self.POSEPOCH.value is None:
            raise ValueError("POSEPOCH is not currently set")
        dt_day = float(np.longdouble(new_epoch)
                       - np.longdouble(self.POSEPOCH.value))
        dec0 = float(self.DECJ.value)
        self.DECJ.value = dec0 + float(self.PMDEC.value or 0.0) \
            * _MASYR_TO_RADDAY * dt_day
        self.RAJ.value = float(self.RAJ.value) + float(self.PMRA.value or 0.0) \
            * _MASYR_TO_RADDAY * dt_day / np.cos(dec0)
        self.POSEPOCH.value = np.longdouble(new_epoch)



# rotation: ecliptic (IERS2010) -> equatorial
_COS_OBL = np.cos(OBL_IERS2010_RAD)
_SIN_OBL = np.sin(OBL_IERS2010_RAD)


class AstrometryEcliptic(Astrometry):
    """Reference ``astrometry.py:753`` (PulsarEcliptic frame, ``pulsar_ecliptic.py:20``)."""

    register = True

    def __init__(self):
        super().__init__()
        self.add_param(AngleParameter("ELONG", angle_type="deg", aliases=["LAMBDA"],
                                      description="Ecliptic longitude"))
        self.add_param(AngleParameter("ELAT", angle_type="deg", aliases=["BETA"],
                                      description="Ecliptic latitude"))
        self.add_param(floatParameter("PMELONG", value=0.0, units="mas/yr",
                                      aliases=["PMLAMBDA"], description="PM in ecliptic longitude"))
        self.add_param(floatParameter("PMELAT", value=0.0, units="mas/yr",
                                      aliases=["PMBETA"], description="PM in ecliptic latitude"))
        self.add_param(floatParameter("PX", value=0.0, units="mas", description="Parallax"))
        self.add_param(MJDParameter("POSEPOCH", description="Epoch of position"))
        from pint_tpu.models.parameter import strParameter

        self.add_param(strParameter("ECL", value="IERS2010", description="Ecliptic convention"))

    def validate(self):
        if self.ELONG.value is None or self.ELAT.value is None:
            raise MissingParameter("AstrometryEcliptic", "ELONG/ELAT")
        if self.POSEPOCH.value is None and (self.PMELONG.value or self.PMELAT.value):
            # fall back to PEPOCH like the reference (astrometry.py:753 family)
            pep = getattr(self._parent, "PEPOCH", None)
            if pep is not None and pep.value is not None:
                self.POSEPOCH.value = pep.value

    def build_context(self, toas):
        self._pe_cache = (float(self.POSEPOCH.value)
                          if self.POSEPOCH.value is not None else None)
        return {}

    def ssb_to_psb_xyz(self, pv, epoch_mjd):
        if self.POSEPOCH.value is not None and "POSEPOCH" in pv:
            pe = pv["POSEPOCH"]
            pe = pe.to_float() if hasattr(pe, "to_float") else pe
            dt_day = epoch_mjd - pe
        else:
            dt_day = jnp.zeros_like(epoch_mjd)
        lat = pv["ELAT"] + pv.get("PMELAT", 0.0) * _MASYR_TO_RADDAY * dt_day
        lon = pv["ELONG"] + pv.get("PMELONG", 0.0) * _MASYR_TO_RADDAY * dt_day / jnp.cos(pv["ELAT"])
        cb = jnp.cos(lat)
        x_e = cb * jnp.cos(lon)
        y_e = cb * jnp.sin(lon)
        z_e = jnp.sin(lat)
        # rotate ecliptic -> equatorial about x
        y = _COS_OBL * y_e - _SIN_OBL * z_e
        z = _SIN_OBL * y_e + _COS_OBL * z_e
        return jnp.stack([x_e, y, z], axis=-1)

    def change_posepoch(self, new_epoch):
        """Move POSEPOCH, advancing ELONG/ELAT along the proper-motion
        linearization (reference ``astrometry.py:1181``)."""
        if self.POSEPOCH.value is None:
            raise ValueError("POSEPOCH is not currently set")
        dt_day = float(np.longdouble(new_epoch)
                       - np.longdouble(self.POSEPOCH.value))
        lat0 = float(self.ELAT.value)
        self.ELAT.value = lat0 + float(self.PMELAT.value or 0.0) \
            * _MASYR_TO_RADDAY * dt_day
        self.ELONG.value = float(self.ELONG.value) \
            + float(self.PMELONG.value or 0.0) * _MASYR_TO_RADDAY * dt_day \
            / np.cos(lat0)
        self.POSEPOCH.value = np.longdouble(new_epoch)

    def coords_as_ICRS(self):
        v = np.asarray(self.ssb_to_psb_xyz(
            {"ELONG": self.ELONG.value, "ELAT": self.ELAT.value,
             "PMELONG": 0.0, "PMELAT": 0.0},
            np.array([0.0])))[0]
        ra = float(np.arctan2(v[1], v[0]) % (2 * np.pi))
        dec = float(np.arcsin(v[2]))
        return ra, dec

