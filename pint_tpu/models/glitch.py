"""Glitch phase model: permanent frequency steps + exponential recoveries.

Reference ``glitch.py:12,191``: for each glitch *i* with epoch GLEP_i, phase
picks up (for t > GLEP)::

    GLPH + dt*(GLF0 + dt*GLF1/2 + dt^2*GLF2/6) + GLF0D*GLTD*(1 - exp(-dt/GLTD))

with dt = (t_bary - GLEP) in seconds and GLTD in days.  The step mask is a
smooth-free ``where`` on traced dt, so autodiff gives the correct
(one-sided) derivatives for every glitch parameter.
"""

from __future__ import annotations

import jax.numpy as jnp

from pint_tpu.exceptions import MissingParameter
from pint_tpu.models.parameter import prefixParameter
from pint_tpu.models.timing_model import DAY_S, PhaseComponent
from pint_tpu.phase import Phase

__all__ = ["Glitch"]


class Glitch(PhaseComponent):
    register = True
    category = "glitch"

    def __init__(self):
        super().__init__()
        for name, units, desc in [
            ("GLEP_1", "MJD", "Epoch of glitch"),
            ("GLPH_1", "pulse phase", "Glitch phase increment"),
            ("GLF0_1", "Hz", "Permanent glitch spin frequency increment"),
            ("GLF1_1", "Hz/s", "Permanent glitch frequency-derivative increment"),
            ("GLF2_1", "Hz/s^2", "Permanent glitch second-derivative increment"),
            ("GLF0D_1", "Hz", "Decaying glitch frequency increment"),
            ("GLTD_1", "day", "Glitch decay time constant"),
        ]:
            # value=None: the index-1 exemplar must not register as a real
            # glitch when par files number glitches starting at >= 2
            p = prefixParameter(name, units=units, description=desc)
            self.add_param(p)
        self.glitch_indices = [1]

    def setup(self):
        # a glitch index exists iff some GL*_i parameter has a set value;
        # grow the family so every live index has the full parameter set
        idx_all = sorted({int(n.split("_")[1]) for n in self.params
                          if "_" in n and self._params_dict[n].value is not None})
        for i in idx_all:
            for pre in ("GLEP_", "GLPH_", "GLF0_", "GLF1_", "GLF2_", "GLF0D_", "GLTD_"):
                nm = f"{pre}{i}"
                if nm not in self._params_dict:
                    ex = self._params_dict[f"{pre}1"]
                    newp = ex.new_param(i, value=0.0)
                    newp.name = nm  # glitch indices are unpadded
                    self.add_param(newp)
        self.glitch_indices = idx_all

    def validate(self):
        for i in self.glitch_indices:
            if (self._params_dict[f"GLEP_{i}"].value or 0.0) == 0.0:
                raise MissingParameter("Glitch", f"GLEP_{i}")
            if (self._params_dict[f"GLF0D_{i}"].value or 0.0) != 0.0 and \
                    (self._params_dict[f"GLTD_{i}"].value or 0.0) == 0.0:
                raise MissingParameter(
                    "Glitch", f"GLTD_{i}", f"GLF0D_{i} set but GLTD_{i} is zero")

    def phase_func(self, pv, batch, ctx, delay):
        t_s = batch.tdb_seconds()
        phase = jnp.zeros(batch.ntoas)
        for i in self.glitch_indices:
            glep = pv.get(f"GLEP_{i}", 0.0)
            dt = (t_s.hi - (glep - batch.tdb0) * DAY_S) + t_s.lo - delay
            on = dt > 0.0
            dtp = jnp.where(on, dt, 0.0)
            poly = pv.get(f"GLPH_{i}", 0.0) + dtp * (
                pv.get(f"GLF0_{i}", 0.0)
                + dtp * (0.5 * pv.get(f"GLF1_{i}", 0.0)
                         + dtp * pv.get(f"GLF2_{i}", 0.0) / 6.0))
            tau = pv.get(f"GLTD_{i}", 0.0) * DAY_S
            safe_tau = jnp.where(tau > 0.0, tau, 1.0)
            decay = jnp.where(tau > 0.0,
                              pv.get(f"GLF0D_{i}", 0.0) * safe_tau
                              * (1.0 - jnp.exp(-dtp / safe_tau)),
                              0.0)
            phase = phase + jnp.where(on, poly + decay, 0.0)
        return Phase.from_float(phase)
