"""Fourier-basis red-noise approximants: WaveX, DMWaveX, CMWaveX.

Reference ``wavex.py:14`` (delay = sum_i WXSIN_i sin(2 pi f_i dt) +
WXCOS_i cos(...), f_i [1/d], dt = t_bary - WXEPOCH [days]),
``dmwavex.py:15`` (same series builds a DM, delay = DMconst*DM/f^2) and
``cmwavex.py:15`` (series builds a chromatic measure, delay =
DMconst*CM*(f/MHz)^-TNCHROMIDX).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu import DMconst
from pint_tpu.exceptions import MissingParameter
from pint_tpu.models.parameter import MJDParameter, prefixParameter
from pint_tpu.models.timing_model import DAY_S, DelayComponent

__all__ = ["WaveX", "DMWaveX", "CMWaveX"]

_TWO_PI = 2.0 * np.pi


class _WaveXBase(DelayComponent):
    """Shared machinery for the three Fourier series components."""

    #: prefix triplet, e.g. ("WXFREQ_", "WXSIN_", "WXCOS_")
    prefixes = ("WXFREQ_", "WXSIN_", "WXCOS_")
    epoch_name = "WXEPOCH"

    def _exemplar(self, pre):
        """Any existing member of the ``pre`` family (NOT hardcoded 0001:
        index 1 may have been removed)."""
        for p in self.params:
            if p.startswith(pre):
                return self._params_dict[p]
        raise KeyError(f"No {pre} parameter left to use as an exemplar")

    def setup(self):
        pf = self.prefixes[0]
        self.indices = sorted(int(p[len(pf):]) for p in self.params
                              if p.startswith(pf))
        # grow missing sin/cos partners with zero amplitude
        for i in self.indices:
            for pre in self.prefixes[1:]:
                nm = f"{pre}{i:04d}"
                if nm not in self._params_dict:
                    self.add_param(self._exemplar(pre).new_param(i, value=0.0))

    def validate(self):
        if getattr(self, self.epoch_name).value is None:
            pep = getattr(self._parent, "PEPOCH", None)
            if pep is None or pep.value is None:
                raise MissingParameter(type(self).__name__, self.epoch_name)
            getattr(self, self.epoch_name).value = pep.value
        pf = self.prefixes[0]
        for i in self.indices:
            if self._params_dict[f"{pf}{i:04d}"].value in (None, 0.0):
                raise MissingParameter(type(self).__name__, f"{pf}{i:04d}")

    # -- reference component-management API (wavex.py:72-260) ---------------
    def get_indices(self) -> "np.ndarray":
        """Indices of the components in use (reference
        ``wavex.py get_indices``)."""
        return np.array(self.indices)

    def _add_component(self, freq, index=None, sin=0.0, cos=0.0,
                       frozen=True):
        fpre, spre, cpre = self.prefixes
        if index is None:
            index = max(self.indices, default=0) + 1
        index = int(index)
        if f"{fpre}{index:04d}" in self._params_dict \
                and self._params_dict[f"{fpre}{index:04d}"].value is not None:
            raise ValueError(f"Index {index} already in use ({fpre})")
        for pre, val, fr in ((fpre, float(freq), True),
                             (spre, float(sin), frozen),
                             (cpre, float(cos), frozen)):
            nm = f"{pre}{index:04d}"
            if nm in self._params_dict:
                self._params_dict[nm].value = val
                self._params_dict[nm].frozen = bool(fr) if pre != fpre \
                    else self._params_dict[nm].frozen
            else:
                self.add_param(self._exemplar(pre).new_param(
                    index, value=val, frozen=bool(fr)))
        self.setup()
        if self._parent is not None:
            self._parent._cache.clear()
        return index

    def _remove_component(self, index) -> None:
        idxs = {int(i) for i in np.atleast_1d(index)}
        if idxs >= set(self.indices):
            # refuse BEFORE mutating: a raise must leave the model intact
            raise ValueError(
                "Removing the last component would leave the model unable "
                "to evaluate; delete the component instead")
        for idx in idxs:
            for pre in self.prefixes:
                self.remove_param(f"{pre}{idx:04d}")
        self.setup()
        if self._parent is not None:
            self._parent._cache.clear()

    def _add_components(self, freqs, indices=None, sins=0.0, coses=0.0,
                        frozens=True):
        freqs = np.atleast_1d(freqs)
        n = len(freqs)
        if indices is None:
            start = max(self.indices, default=0)
            indices = list(range(start + 1, start + 1 + n))
        sins = np.broadcast_to(np.atleast_1d(sins), (n,))
        coses = np.broadcast_to(np.atleast_1d(coses), (n,))
        frozens = np.broadcast_to(np.atleast_1d(frozens), (n,))
        if len(set(int(i) for i in indices)) != n:
            raise ValueError("Duplicate indices in add_components")
        out = []
        for f, i, si, c, fr in zip(freqs, indices, sins, coses, frozens):
            out.append(self._add_component(f, index=int(i), sin=si, cos=c,
                                           frozen=bool(fr)))
        return out

    def series(self, pv, batch, acc_delay):
        """sum_i [ SIN_i sin(2 pi f_i dt) + COS_i cos(2 pi f_i dt) ]."""
        epoch = pv[self.epoch_name]
        epoch = epoch.to_float() if hasattr(epoch, "to_float") else epoch
        dt_day = (batch.tdb.hi - epoch) + batch.tdb.lo - acc_delay / DAY_S
        fpre, spre, cpre = self.prefixes
        out = jnp.zeros(batch.ntoas)
        for i in self.indices:
            arg = _TWO_PI * pv.get(f"{fpre}{i:04d}", 0.0) * dt_day
            out = out + pv.get(f"{spre}{i:04d}", 0.0) * jnp.sin(arg) \
                      + pv.get(f"{cpre}{i:04d}", 0.0) * jnp.cos(arg)
        return out


class WaveX(_WaveXBase):
    """Achromatic Fourier delay (reference ``wavex.py:14``)."""

    register = True
    category = "wavex"
    prefixes = ("WXFREQ_", "WXSIN_", "WXCOS_")
    epoch_name = "WXEPOCH"

    def __init__(self):
        super().__init__()
        self.add_param(MJDParameter("WXEPOCH", description="WaveX reference epoch"))
        self.add_param(prefixParameter("WXFREQ_0001", units="1/d",
                                       description="WaveX component frequency"))
        self.add_param(prefixParameter("WXSIN_0001", units="s", value=0.0,
                                       description="WaveX sine amplitude"))
        self.add_param(prefixParameter("WXCOS_0001", units="s", value=0.0,
                                       description="WaveX cosine amplitude"))
        self.indices = [1]

    def delay_func(self, pv, batch, ctx, acc_delay):
        return self.series(pv, batch, acc_delay)

    def add_wavex_component(self, wxfreq, index=None, wxsin=0, wxcos=0,
                            frozen=True):
        """Add one WaveX component (reference ``wavex.py:72``); returns
        its index."""
        return self._add_component(wxfreq, index=index, sin=wxsin,
                                   cos=wxcos, frozen=frozen)

    def add_wavex_components(self, wxfreqs, indices=None, wxsins=0,
                             wxcoses=0, frozens=True):
        """Add several WaveX components (reference ``wavex.py:150``)."""
        return self._add_components(wxfreqs, indices=indices, sins=wxsins,
                                    coses=wxcoses, frozens=frozens)

    def remove_wavex_component(self, index):
        """Remove component(s) by index (reference ``wavex.py
        remove_wavex_component``)."""
        self._remove_component(index)


class DMWaveX(_WaveXBase):
    """Fourier DM-noise: the series is a DM in pc/cm^3
    (reference ``dmwavex.py:15``)."""

    register = True
    category = "dmwavex"
    prefixes = ("DMWXFREQ_", "DMWXSIN_", "DMWXCOS_")
    epoch_name = "DMWXEPOCH"

    def __init__(self):
        super().__init__()
        self.add_param(MJDParameter("DMWXEPOCH", description="DMWaveX reference epoch"))
        self.add_param(prefixParameter("DMWXFREQ_0001", units="1/d",
                                       description="DMWaveX component frequency"))
        self.add_param(prefixParameter("DMWXSIN_0001", units="pc/cm3", value=0.0,
                                       description="DMWaveX sine amplitude"))
        self.add_param(prefixParameter("DMWXCOS_0001", units="pc/cm3", value=0.0,
                                       description="DMWaveX cosine amplitude"))
        self.indices = [1]

    def dm_func(self, pv, batch, ctx):
        return self.series(pv, batch, jnp.zeros(batch.ntoas))

    def delay_func(self, pv, batch, ctx, acc_delay):
        dm = self.series(pv, batch, acc_delay)
        freq = self.barycentric_freq(pv, batch)
        return dm * DMconst / freq**2


    def add_dmwavex_component(self, dmwxfreq, index=None, dmwxsin=0,
                              dmwxcos=0, frozen=True):
        """Add one DMWaveX component (reference ``dmwavex.py``)."""
        return self._add_component(dmwxfreq, index=index, sin=dmwxsin,
                                   cos=dmwxcos, frozen=frozen)

    def add_dmwavex_components(self, dmwxfreqs, indices=None, dmwxsins=0,
                               dmwxcoses=0, frozens=True):
        return self._add_components(dmwxfreqs, indices=indices,
                                    sins=dmwxsins, coses=dmwxcoses,
                                    frozens=frozens)

    def remove_dmwavex_component(self, index):
        self._remove_component(index)


class CMWaveX(_WaveXBase):
    """Fourier chromatic-noise; needs TNCHROMIDX (from ChromaticCM)
    (reference ``cmwavex.py:15``)."""

    register = True
    category = "cmwavex"
    prefixes = ("CMWXFREQ_", "CMWXSIN_", "CMWXCOS_")
    epoch_name = "CMWXEPOCH"

    def __init__(self):
        super().__init__()
        self.add_param(MJDParameter("CMWXEPOCH", description="CMWaveX reference epoch"))
        self.add_param(prefixParameter("CMWXFREQ_0001", units="1/d",
                                       description="CMWaveX component frequency"))
        self.add_param(prefixParameter("CMWXSIN_0001", units="pc/cm3", value=0.0,
                                       description="CMWaveX sine amplitude"))
        self.add_param(prefixParameter("CMWXCOS_0001", units="pc/cm3", value=0.0,
                                       description="CMWaveX cosine amplitude"))
        self.indices = [1]

    def delay_func(self, pv, batch, ctx, acc_delay):
        cm = self.series(pv, batch, acc_delay)
        freq = self.barycentric_freq(pv, batch)
        alpha = pv.get("TNCHROMIDX", 4.0)
        return cm * DMconst * jnp.power(freq, -alpha)

    def add_cmwavex_component(self, cmwxfreq, index=None, cmwxsin=0,
                              cmwxcos=0, frozen=True):
        """Add one CMWaveX component (reference ``cmwavex.py``)."""
        return self._add_component(cmwxfreq, index=index, sin=cmwxsin,
                                   cos=cmwxcos, frozen=frozen)

    def add_cmwavex_components(self, cmwxfreqs, indices=None, cmwxsins=0,
                               cmwxcoses=0, frozens=True):
        return self._add_components(cmwxfreqs, indices=indices,
                                    sins=cmwxsins, coses=cmwxcoses,
                                    frozens=frozens)

    def remove_cmwavex_component(self, index):
        self._remove_component(index)
