"""Explicit fitted overall phase offset PHOFF (reference ``phase_offset.py:10``).

When present, the implicit 'Offset' design-matrix column is dropped and PHOFF
is fit like any other parameter; phase contribution is -PHOFF on every TOA.
"""

from __future__ import annotations

import jax.numpy as jnp

from pint_tpu.models.parameter import floatParameter
from pint_tpu.models.timing_model import PhaseComponent
from pint_tpu.phase import Phase

__all__ = ["PhaseOffset"]


class PhaseOffset(PhaseComponent):
    register = True
    category = "phase_offset"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("PHOFF", value=0.0, units="",
                                      description="Overall phase offset"))

    def build_context(self, toas):
        # PHOFF is the offset between physical TOAs and the TZR TOA: it
        # must NOT apply to the TZR TOA itself or it cancels out of the
        # absolute phase (reference ``phase_offset.py:37`` zero for
        # ``toas.tzr``; our TZR TOAs carry a "tzr" flag)
        import numpy as np

        mask = np.array([0.0 if "tzr" in fl else 1.0 for fl in toas.flags])
        return {"apply": jnp.asarray(mask)}

    def phase_func(self, pv, batch, ctx, delay):
        return Phase.from_float(-pv.get("PHOFF", 0.0) * ctx["apply"])
