"""Solar-wind dispersion: NE_SW spherical model (SWM=0), power-law (SWM=1),
and piecewise SWX ranges.

Reference ``solar_wind_dispersion.py:272,608``:

* SWM=0 (Edwards et al. 2006 eq. 29-30): DM = NE_SW * AU^2 * rho /
  (r sin rho), rho = pi - elongation.
* SWM=1 (Hazboun et al. 2022 eq. 11): DM = NE_SW * (b/AU)^-p * b *
  [I_inf(p) + I(z_sun/b, p)] with b = r sin(theta), z_sun = r cos(theta),
  I(u,p) = integral_0^u (1+t^2)^(-p/2) dt.  The reference evaluates I via
  scipy hyp2f1; here it is a fixed-order Gauss-Legendre quadrature after
  t = tan(phi), which is jit-compatible and differentiable in p (the
  reference needed hand-derived Pade expansions for dDM/dp; autodiff
  handles it).
* SWX (reference ``solar_wind_dispersion.py:608``): piecewise SWXDM_XXXX
  scaled by (geom(t,p)-geom_opp(p))/(geom_conj(p)-geom_opp(p)) so the DM
  runs 0 (opposition) to SWXDM (conjunction) in each range.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np
from jax.scipy.special import gammaln

from pint_tpu import AU_LS, DMconst, c as C_M_S
from pint_tpu.exceptions import MissingParameter
from pint_tpu.models.parameter import (
    MJDParameter,
    floatParameter,
    prefixParameter,
)
from pint_tpu.models.timing_model import DelayComponent, check_contiguous_indices

__all__ = ["SolarWindDispersion", "SolarWindDispersionX",
           "SolarWindDispersionBase"]

_PC_LS = 3.0856775814913673e16 / C_M_S  # parsec in light-seconds
_DAY_PER_YEAR = 365.25

# 64-point Gauss-Legendre nodes/weights on [-1, 1] (baked as trace constants)
# host numpy at module scope: a jnp.asarray here would initialize the jax
# BACKEND at import time (observed hanging every `import pint_tpu.models`
# while the TPU tunnel was wedged); trace-time ops convert these on demand
_GL_X, _GL_W = np.polynomial.legendre.leggauss(64)


def _sw_I_inf(p):
    """integral_0^inf (1+t^2)^(-p/2) dt = sqrt(pi)/2 * G((p-1)/2)/G(p/2)."""
    return 0.5 * jnp.sqrt(jnp.pi) * jnp.exp(gammaln((p - 1.0) / 2.0) - gammaln(p / 2.0))


def _sw_I(u, p):
    """integral_0^u (1+t^2)^(-p/2) dt via t = tan(phi) substitution:
    integral_0^arctan(u) cos(phi)^(p-2) dphi, 64-pt Gauss-Legendre."""
    phi_max = jnp.arctan(u)
    half = 0.5 * phi_max
    phi = half[..., None] * (_GL_X + 1.0)
    vals = jnp.cos(phi) ** (p - 2.0)
    return half * jnp.sum(_GL_W * vals, axis=-1)


def solar_wind_geometry_pl(r_ls, theta, p):
    """Hazboun et al. (2022) eq. 11 path geometry in parsecs (power-law index
    p > 1); r in light-seconds, theta = elongation [rad]."""
    b = r_ls * jnp.sin(theta)
    z_sun = r_ls * jnp.cos(theta)
    return (AU_LS / b) ** p * (b / _PC_LS) * (_sw_I_inf(p) + _sw_I(z_sun / b, p))


def solar_wind_geometry_spherical(r_ls, elongation):
    """Edwards et al. (2006) eq. 29-30 geometry in parsecs (1/r^2 density)."""
    rho = jnp.pi - elongation
    return (AU_LS**2) * rho / (r_ls * jnp.sin(rho)) / _PC_LS


class SolarWindDispersionBase(DelayComponent):
    """Shared geometry/astrometry plumbing for solar-wind components
    (reference ``solar_wind_dispersion.py:266`` base-class spelling)."""

    def _astrometry(self):
        for comp in self._parent.components.values():
            if hasattr(comp, "sun_angle_traced"):
                return comp
        raise MissingParameter(type(self).__name__, "RAJ/ELONG",
                               "solar wind needs an astrometry component")

    def _theta_r(self, pv, batch):
        astro = self._astrometry()
        theta = astro.sun_angle_traced(pv, batch)
        r = jnp.linalg.norm(batch.obs_sun_pos, axis=1)
        return theta, r

    def _theta0(self):
        """Minimum elongation (conjunction), from the pulsar's ecliptic
        latitude assuming a circular Earth orbit (reference
        ``solar_wind_dispersion.py:545-560`` 'simplified model')."""
        from pint_tpu import OBL_IERS2010_RAD

        astro = self._astrometry()
        ra, dec = astro.coords_as_ICRS()
        v = np.array([np.cos(dec) * np.cos(ra), np.cos(dec) * np.sin(ra), np.sin(dec)])
        ce, se = np.cos(OBL_IERS2010_RAD), np.sin(OBL_IERS2010_RAD)
        z_ecl = -se * v[1] + ce * v[2]
        beta = abs(float(np.arcsin(np.clip(z_ecl, -1, 1))))
        return max(beta, 1e-3)


class SolarWindDispersion(SolarWindDispersionBase):
    """Reference ``solar_wind_dispersion.py:272``."""

    register = True
    category = "solar_wind"

    def __init__(self):
        super().__init__()
        p = prefixParameter("NE_SW0", units="cm^-3", value=0.0,
                            description="Solar wind electron density at 1 AU",
                            aliases=["NE1AU", "SOLARN0"])
        p.name, p.prefix, p.index = "NE_SW", "NE_SW", 0
        self.add_param(p)
        self.add_param(prefixParameter("NE_SW1", units="cm^-3/yr", value=0.0,
                                       description="NE_SW derivative"))
        self.add_param(MJDParameter("SWEPOCH", description="Epoch of NE_SW"))
        self.add_param(floatParameter("SWM", units="", value=0.0, continuous=False,
                                      description="Solar wind model (0 spherical, 1 power-law)"))
        self.add_param(floatParameter("SWP", units="", value=2.0,
                                      description="Solar wind power-law index (SWM=1)"))
        self.num_ne_sw_terms = 2

    def setup(self):
        idxs = [0] + sorted(int(n[5:]) for n in self.params
                            if n.startswith("NE_SW") and n[5:].isdigit() and n != "NE_SW")
        check_contiguous_indices(idxs, "SolarWindDispersion", "NE_SW")
        self.num_ne_sw_terms = len(idxs)

    def validate(self):
        if int(self.SWM.value or 0) not in (0, 1):
            raise MissingParameter("SolarWindDispersion", "SWM",
                                   f"SWM={self.SWM.value} not implemented")
        higher = any((self._params_dict.get(f"NE_SW{i}") is not None
                      and self._params_dict[f"NE_SW{i}"].value)
                     for i in range(1, self.num_ne_sw_terms))
        if higher and self.SWEPOCH.value is None:
            raise MissingParameter("SolarWindDispersion", "SWEPOCH")

    def ne_sw(self, pv, batch):
        terms = [pv.get("NE_SW", 0.0)] + [pv.get(f"NE_SW{i}", 0.0)
                                          for i in range(1, self.num_ne_sw_terms)]
        if len(terms) == 1:
            return terms[0] * jnp.ones_like(batch.freq)
        if self.SWEPOCH.value is not None and "SWEPOCH" in pv:
            ep = pv["SWEPOCH"]
            ep = ep.to_float() if hasattr(ep, "to_float") else ep
        else:
            ep = batch.tdb0
        dt_yr = (batch.tdb.hi - ep) / _DAY_PER_YEAR
        acc = jnp.zeros_like(dt_yr)
        for i in range(len(terms) - 1, -1, -1):
            acc = acc * dt_yr + terms[i] / math.factorial(i)
        return acc

    def solar_wind_dm(self, pv, batch):
        theta, r = self._theta_r(pv, batch)
        if int(self.SWM.value or 0) == 0:
            geom = solar_wind_geometry_spherical(r, theta)
        else:
            geom = solar_wind_geometry_pl(r, theta, pv.get("SWP", 2.0))
        return self.ne_sw(pv, batch) * geom

    def dm_func(self, pv, batch, ctx):
        return self.solar_wind_dm(pv, batch)

    def delay_func(self, pv, batch, ctx, acc_delay):
        freq = self.barycentric_freq(pv, batch)
        return self.solar_wind_dm(pv, batch) * DMconst / freq**2


class SolarWindDispersionX(SolarWindDispersionBase):
    """Piecewise solar-wind DM (reference ``solar_wind_dispersion.py:608``)."""

    register = True
    category = "solar_windx"

    def __init__(self):
        super().__init__()
        self.add_param(prefixParameter("SWXDM_0001", units="pc/cm3", value=0.0,
                                       description="Max solar-wind DM in range"))
        self.add_param(prefixParameter("SWXP_0001", units="", value=2.0,
                                       description="Radial power-law index in range"))
        self.add_param(prefixParameter("SWXR1_0001", units="MJD",
                                       description="Range start MJD"))
        self.add_param(prefixParameter("SWXR2_0001", units="MJD",
                                       description="Range end MJD"))
        self.swx_indices = [1]

    def setup(self):
        self.swx_indices = sorted(int(n[6:]) for n in self.params
                                  if n.startswith("SWXDM_"))
        for i in self.swx_indices:
            if f"SWXP_{i:04d}" not in self._params_dict:
                self.add_param(self._params_dict["SWXP_0001"].new_param(i, value=2.0))

    def validate(self):
        for i in self.swx_indices:
            for pre in ("SWXR1_", "SWXR2_"):
                nm = f"{pre}{i:04d}"
                if nm not in self._params_dict or self._params_dict[nm].value is None:
                    raise MissingParameter("SolarWindDispersionX", nm)

    def add_swx_range(self, mjd_start, mjd_end, index=None, swxdm=0.0,
                      swxp=2.0, frozen: bool = True) -> int:
        """Add one SWX bin (reference ``solar_wind_dispersion.py
        add_swx_range``).  Returns the assigned index."""
        if float(mjd_end) < float(mjd_start):
            raise ValueError("Starting MJD is greater than ending MJD.")
        if index is None:
            index = max(self.swx_indices, default=0) + 1
        index = int(index)
        if f"SWXDM_{index:04d}" in self._params_dict:
            raise ValueError(
                f"Index '{index}' is already in use in this model. "
                f"Please choose another.")
        if self.swx_indices:
            # template from ANY surviving bin (bin 1 may have been removed)
            i0 = self.swx_indices[0]
            self.add_param(self._params_dict[f"SWXDM_{i0:04d}"].new_param(
                index, value=float(swxdm), frozen=frozen))
            self.add_param(self._params_dict[f"SWXP_{i0:04d}"].new_param(
                index, value=float(swxp)))
            self.add_param(self._params_dict[f"SWXR1_{i0:04d}"].new_param(
                index, value=float(mjd_start)))
            self.add_param(self._params_dict[f"SWXR2_{i0:04d}"].new_param(
                index, value=float(mjd_end)))
        else:
            self.add_param(prefixParameter(
                f"SWXDM_{index:04d}", units="pc/cm3", value=float(swxdm),
                frozen=frozen, description="Max solar-wind DM in range"))
            self.add_param(prefixParameter(
                f"SWXP_{index:04d}", units="", value=float(swxp),
                description="Radial power-law index in range"))
            self.add_param(prefixParameter(
                f"SWXR1_{index:04d}", units="MJD", value=float(mjd_start),
                description="Range start MJD"))
            self.add_param(prefixParameter(
                f"SWXR2_{index:04d}", units="MJD", value=float(mjd_end),
                description="Range end MJD"))
        self.setup()
        if self._parent is not None:
            self._parent.setup()
        return index

    def remove_swx_range(self, index) -> None:
        """Remove one or more SWX bins by index."""
        indices = [index] if isinstance(index, (int, np.integer)) else list(index)
        for i in indices:
            i = int(i)
            if f"SWXDM_{i:04d}" not in self._params_dict:
                raise ValueError(f"Index {i} not in SWX model")
            for pre in ("SWXDM_", "SWXP_", "SWXR1_", "SWXR2_"):
                self.remove_param(f"{pre}{i:04d}")
        self.setup()
        if self._parent is not None:
            self._parent.setup()

    def build_context(self, toas):
        mjds = np.asarray(toas.get_mjds(), dtype=np.float64)
        masks = []
        for i in self.swx_indices:
            r1 = float(self._params_dict[f"SWXR1_{i:04d}"].value)
            r2 = float(self._params_dict[f"SWXR2_{i:04d}"].value)
            masks.append(((mjds >= r1) & (mjds <= r2)).astype(np.float64))
        return {"masks": jnp.asarray(np.array(masks)) if masks else None,
                "theta0": self._theta0()}

    def swx_dm(self, pv, batch, ctx):
        theta, r = self._theta_r(pv, batch)
        theta0 = ctx["theta0"]
        r0 = jnp.asarray(AU_LS)
        dm = jnp.zeros(batch.ntoas)
        for k, i in enumerate(self.swx_indices):
            p = pv.get(f"SWXP_{i:04d}", 2.0)
            geom = solar_wind_geometry_pl(r, theta, p)
            g_conj = solar_wind_geometry_pl(r0, theta0, p)
            g_opp = solar_wind_geometry_pl(r0, jnp.pi - theta0, p)
            scale = (geom - g_opp) / (g_conj - g_opp)
            dm = dm + pv.get(f"SWXDM_{i:04d}", 0.0) * scale * ctx["masks"][k]
        return dm

    def dm_func(self, pv, batch, ctx):
        if ctx.get("masks") is None:
            return jnp.zeros(batch.ntoas)
        return self.swx_dm(pv, batch, ctx)

    def delay_func(self, pv, batch, ctx, acc_delay):
        if ctx.get("masks") is None:
            return jnp.zeros(batch.ntoas)
        freq = self.barycentric_freq(pv, batch)
        return self.swx_dm(pv, batch, ctx) * DMconst / freq**2
