"""Phase and delay jumps between instrument/receiver groups.

Reference ``jump.py:78 PhaseJump`` (phase += JUMP * F0 on the selected TOAs)
and ``jump.py:11 DelayJump`` (delay -= JUMP).  JUMPs are mask parameters.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu.models.parameter import maskParameter
from pint_tpu.models.timing_model import DelayComponent, PhaseComponent
from pint_tpu.phase import Phase

__all__ = ["PhaseJump", "DelayJump"]


class PhaseJump(PhaseComponent):
    register = True
    category = "phase_jump"

    def __init__(self):
        super().__init__()
        self.add_param(maskParameter("JUMP", index=1, units="s", value=0.0,
                                     description="Phase jump (seconds) for selected TOAs"))
        self.jumps = ["JUMP1"]

    def setup(self):
        self.jumps = [p for p in self.params if p.startswith("JUMP")]

    def build_context(self, toas):
        n = len(toas)
        masks = {}
        for j in self.jumps:
            idx = self._params_dict[j].select_toa_mask(toas)
            m = np.zeros(n)
            m[idx] = 1.0
            masks[j] = jnp.asarray(m)
        return {"masks": masks}

    def phase_func(self, pv, batch, ctx, delay):
        jphase = jnp.zeros(batch.ntoas)
        F0 = pv.get("F0", 0.0)
        for j in self.jumps:
            jphase = jphase + pv.get(j, 0.0) * F0 * ctx["masks"][j]
        return Phase.from_float(jphase)

    def get_number_of_jumps(self) -> int:
        return len(self.jumps)

    def jump_params_to_flags(self, toas):
        """Stamp -jump flags onto selected TOAs (pintk parity helper)."""
        for i, j in enumerate(self.jumps):
            for k in self._params_dict[j].select_toa_mask(toas):
                toas.flags[k]["jump"] = str(i + 1)


class DelayJump(DelayComponent):
    """Tempo-style delay jumps (reference ``jump.py:11``)."""

    register = True
    category = "jump_delay"

    def __init__(self):
        super().__init__()
        self.add_param(maskParameter("JUMP", index=1, units="s", value=0.0,
                                     description="Delay jump (seconds)"))
        self.jumps = ["JUMP1"]

    def setup(self):
        self.jumps = [p for p in self.params if p.startswith("JUMP")]

    def build_context(self, toas):
        n = len(toas)
        masks = {}
        for j in self.jumps:
            idx = self._params_dict[j].select_toa_mask(toas)
            m = np.zeros(n)
            m[idx] = 1.0
            masks[j] = jnp.asarray(m)
        return {"masks": masks}

    def delay_func(self, pv, batch, ctx, acc_delay):
        d = jnp.zeros(batch.ntoas)
        for j in self.jumps:
            d = d - pv.get(j, 0.0) * ctx["masks"][j]
        return d
