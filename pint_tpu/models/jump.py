"""Phase and delay jumps between instrument/receiver groups.

Reference ``jump.py:78 PhaseJump`` (phase += JUMP * F0 on the selected TOAs)
and ``jump.py:11 DelayJump`` (delay -= JUMP).  JUMPs are mask parameters.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu.models.parameter import maskParameter
from pint_tpu.models.timing_model import DelayComponent, PhaseComponent
from pint_tpu.phase import Phase

__all__ = ["PhaseJump", "DelayJump"]


class PhaseJump(PhaseComponent):
    register = True
    category = "phase_jump"

    def __init__(self):
        super().__init__()
        self.add_param(maskParameter("JUMP", index=1, units="s", value=0.0,
                                     description="Phase jump (seconds) for selected TOAs"))
        self.jumps = ["JUMP1"]

    def setup(self):
        self.jumps = [p for p in self.params if p.startswith("JUMP")]

    def build_context(self, toas):
        n = len(toas)
        masks = {}
        for j in self.jumps:
            idx = self._params_dict[j].select_toa_mask(toas)
            m = np.zeros(n)
            m[idx] = 1.0
            masks[j] = jnp.asarray(m)
        return {"masks": masks}

    def phase_func(self, pv, batch, ctx, delay):
        jphase = jnp.zeros(batch.ntoas)
        F0 = pv.get("F0", 0.0)
        for j in self.jumps:
            jphase = jphase + pv.get(j, 0.0) * F0 * ctx["masks"][j]
        return Phase.from_float(jphase)

    # -- reference pintk helper API (jump.py:156-290) -----------------------
    def get_jump_param_objects(self):
        """The maskParameter objects of this component's jumps (reference
        ``jump.py:156``)."""
        return [self._params_dict[j] for j in self.jumps]

    def add_jump_and_flags(self, toa_flags, value: float = 0.0,
                           frozen: bool = False) -> str:
        """Create a new gui-style jump over the given per-TOA flag dicts
        (reference ``jump.py:196``: pintk passes the selected rows of the
        flags column); stamps ``-gui_jump`` and returns the new parameter
        name."""
        used = []
        for j in self.jumps:
            p = self._params_dict[j]
            if getattr(p, "key", None) == "-gui_jump":
                used += [int(v) for v in p.key_value]
        ind = max(used, default=0) + 1
        toa_flags = list(toa_flags)
        # validate EVERYTHING before mutating anything: a raise must not
        # leave orphan flags pointing at a jump that was never created
        for fl in toa_flags:
            if fl.get("gui_jump"):
                raise ValueError(
                    "A selected TOA is already jumped by a gui jump; "
                    "unjump it first")
        for fl in toa_flags:
            fl["gui_jump"] = str(ind)
        # reuse JUMP1 when it is the unset ctor exemplar
        exemplar = self._params_dict.get("JUMP1")
        if len(self.jumps) == 1 and exemplar is not None \
                and getattr(exemplar, "key", None) is None:
            exemplar.key = "-gui_jump"
            exemplar.key_value = [str(ind)]
            exemplar.value = float(value)
            exemplar.frozen = frozen
            name = "JUMP1"
        else:
            idx = max((int(j[4:]) for j in self.jumps), default=0) + 1
            self.add_param(maskParameter("JUMP", index=idx, key="-gui_jump",
                                         key_value=[str(ind)], units="s",
                                         value=float(value), frozen=frozen),
                           setup=True)
            name = f"JUMP{idx}"
        self.setup()
        if self._parent is not None:
            self._parent._cache.clear()
            self._parent.setup()
        return name

    def delete_not_all_jump_toas(self, toa_flags, jump_num: int) -> None:
        """Remove the gui-jump flag from a SUBSET of a jump's TOAs
        (reference ``jump.py:256``); the jump parameter itself stays."""
        for fl in (toa_flags or []):
            if fl.get("gui_jump") == str(int(jump_num)):
                del fl["gui_jump"]
        if self._parent is not None:
            self._parent._cache.clear()

    def get_number_of_jumps(self) -> int:
        return len(self.jumps)

    def jump_params_to_flags(self, toas):
        """Stamp -jump flags onto selected TOAs (pintk parity helper)."""
        for i, j in enumerate(self.jumps):
            for k in self._params_dict[j].select_toa_mask(toas):
                toas.flags[k]["jump"] = str(i + 1)


class DelayJump(DelayComponent):
    """Tempo-style delay jumps (reference ``jump.py:11``)."""

    register = True
    category = "jump_delay"

    def __init__(self):
        super().__init__()
        self.add_param(maskParameter("JUMP", index=1, units="s", value=0.0,
                                     description="Delay jump (seconds)"))
        self.jumps = ["JUMP1"]

    def setup(self):
        self.jumps = [p for p in self.params if p.startswith("JUMP")]

    def build_context(self, toas):
        n = len(toas)
        masks = {}
        for j in self.jumps:
            idx = self._params_dict[j].select_toa_mask(toas)
            m = np.zeros(n)
            m[idx] = 1.0
            masks[j] = jnp.asarray(m)
        return {"masks": masks}

    def delay_func(self, pv, batch, ctx, acc_delay):
        d = jnp.zeros(batch.ntoas)
        for j in self.jumps:
            d = d - pv.get(j, 0.0) * ctx["masks"][j]
        return d
