"""FD: polynomial-in-log-frequency profile-evolution delay.

Reference ``frequency_dependent.py:13,88``:
delay = sum_{i>=1} FD_i * ln(f_bary/1 GHz)^i  [seconds].
"""

from __future__ import annotations

import jax.numpy as jnp

from pint_tpu.exceptions import MissingParameter
from pint_tpu.models.parameter import prefixParameter
from pint_tpu.models.timing_model import DelayComponent, check_contiguous_indices

__all__ = ["FD"]


class FD(DelayComponent):
    register = True
    category = "frequency_dependent"

    def __init__(self):
        super().__init__()
        self.add_param(prefixParameter("FD1", units="s", value=0.0,
                                       description="Log-frequency polynomial delay coefficient"))
        self.num_FD_terms = 1

    def setup(self):
        terms = sorted(int(p[2:]) for p in self.params
                       if p.startswith("FD") and p[2:].isdigit())
        self.num_FD_terms = len(terms)
        if terms:
            check_contiguous_indices(terms, "FD", "FD", start=1)

    def delay_func(self, pv, batch, ctx, acc_delay):
        freq = self.barycentric_freq(pv, batch)
        log_f = jnp.log(freq / 1000.0)  # MHz -> GHz
        log_f = jnp.where(jnp.isfinite(log_f), log_f, 0.0)
        # Horner over FD_n ... FD_1, zero constant term
        acc = jnp.zeros(batch.ntoas)
        for i in range(self.num_FD_terms, 0, -1):
            acc = (acc + pv.get(f"FD{i}", 0.0)) * log_f
        return acc
