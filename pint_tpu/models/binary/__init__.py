"""Binary models: jnp delay engines + par-facing components."""

from pint_tpu.models.binary import engines  # noqa: F401
from pint_tpu.models.binary.components import (  # noqa: F401
    BinaryBT,
    BinaryDD,
    BinaryDDGR,
    BinaryDDH,
    BinaryDDK,
    BinaryDDS,
    BinaryELL1,
    BinaryELL1H,
    BinaryELL1k,
    PulsarBinary,
)
