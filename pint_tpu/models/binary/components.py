"""Par-facing binary components bridging the timing model to the engines.

Counterpart of reference ``pulsar_binary.py:36 PulsarBinary`` and the
per-model classes (``binary_bt.py``, ``binary_dd.py``, ``binary_ell1.py``,
``binary_ddk.py``).  Each component:

* declares the par-file parameters (canonical units: PB days, A1 lt-s,
  OM/OMDOT deg & deg/yr, M2 Msun, epochs as MJDParameters, tempo 1e-12
  scaling on the DOT parameters),
* computes the barycentric time tt0 = (TDB - T0|TASC)*86400 - acc_delay in
  double-double then hands a float64 tt0 to the pure engine function
  (engines are smooth in t: the ~2e-8 s dd->f64 rounding enters the delay
  suppressed by the orbital velocity ~1e-4),
* resolves static structure (FBX vs PB orbits, H3/H4 vs H3/STIGMA, K96) at
  trace time so the jitted graph has no data-dependent branches.
"""

from __future__ import annotations

from typing import Callable, List, Optional

import jax.numpy as jnp
import numpy as np

from pint_tpu.dd import dd_mul, dd_sub
from pint_tpu.exceptions import MissingParameter, TimingModelError
from pint_tpu.logging import log
from pint_tpu.models.binary import engines as eng
from pint_tpu.models.parameter import (
    MJDParameter,
    boolParameter,
    floatParameter,
    intParameter,
    prefixParameter,
)
from pint_tpu.models.timing_model import DelayComponent

__all__ = [
    "PulsarBinary", "BinaryBT", "BinaryDD", "BinaryDDS", "BinaryDDH",
    "BinaryDDGR", "BinaryDDK", "BinaryELL1", "BinaryELL1H", "BinaryELL1k",
]

DAY_S = 86400.0


def _ecliptic_pm_to_equatorial(elong, elat, pm_elong, pm_elat):
    """Rotate proper motion from ecliptic (lambda*, beta) components to
    equatorial (alpha*, delta) components at the source position.

    Both inputs and outputs use the cos(lat)-scaled longitude convention
    (PMELONG ~ PMRA*).  All quantities may be traced scalars.
    """
    from pint_tpu import OBL_IERS2010_RAD

    ce, se = jnp.cos(OBL_IERS2010_RAD), jnp.sin(OBL_IERS2010_RAD)
    cb, sb = jnp.cos(elat), jnp.sin(elat)
    cl, sl = jnp.cos(elong), jnp.sin(elong)
    # source unit vector and local (e_lon, e_lat) basis, ecliptic frame
    n_ecl = jnp.array([cb * cl, cb * sl, sb])
    e_lon = jnp.array([-sl, cl, 0.0])
    e_lat = jnp.array([-sb * cl, -sb * sl, cb])

    def to_eq(v):
        return jnp.array([v[0], ce * v[1] - se * v[2], se * v[1] + ce * v[2]])

    n = to_eq(n_ecl)
    pm_vec = pm_elong * to_eq(e_lon) + pm_elat * to_eq(e_lat)
    ra = jnp.arctan2(n[1], n[0])
    dec = jnp.arcsin(jnp.clip(n[2], -1.0, 1.0))
    e_ra = jnp.array([-jnp.sin(ra), jnp.cos(ra), 0.0])
    e_dec = jnp.array([-jnp.sin(dec) * jnp.cos(ra),
                       -jnp.sin(dec) * jnp.sin(ra), jnp.cos(dec)])
    return jnp.dot(pm_vec, e_ra), jnp.dot(pm_vec, e_dec)


class PulsarBinary(DelayComponent):
    """Shared Keplerian parameter set + barycentric-time plumbing."""

    category = "pulsar_system"
    binary_model_name = "base"
    epoch_param = "T0"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("PB", units="d", description="Orbital period"))
        self.add_param(floatParameter("PBDOT", units="s/s", unit_scale=True,
                                      description="Orbital period derivative"))
        self.add_param(floatParameter("XPBDOT", units="s/s", unit_scale=True,
                                      description="Excess PBDOT over GR"))
        self.add_param(floatParameter("A1", units="ls",
                                      description="Projected semi-major axis"))
        self.add_param(floatParameter("A1DOT", units="ls/s", aliases=["XDOT"],
                                      unit_scale=True,
                                      description="d(A1)/dt"))
        self.add_param(MJDParameter("T0", description="Epoch of periastron"))
        self.add_param(floatParameter("ECC", units="", aliases=["E"],
                                      description="Eccentricity"))
        self.add_param(floatParameter("EDOT", units="1/s", unit_scale=True,
                                      description="Eccentricity derivative"))
        self.add_param(floatParameter("OM", units="deg",
                                      description="Longitude of periastron"))
        self.add_param(floatParameter("OMDOT", units="deg/yr",
                                      description="Periastron advance rate"))
        self.add_param(floatParameter("M2", units="Msun", description="Companion mass"))
        self.add_param(floatParameter("SINI", units="", description="Sine of inclination"))
        self.add_param(floatParameter("GAMMA", units="s",
                                      description="Einstein-delay amplitude"))
        self.add_param(prefixParameter("FB0", units="1/s", aliases=["FB"],
                                       description="Orbital frequency"))
        # ORBWAVES Fourier orbital-phase modulation (reference
        # pulsar_binary.py:62-72, binary_orbits.py:243)
        self.add_param(prefixParameter("ORBWAVEC0", units="",
                                       aliases=["ORBWAVEC"],
                                       description="ORBWAVE cosine amplitude"))
        self.add_param(prefixParameter("ORBWAVES0", units="",
                                       aliases=["ORBWAVES"],
                                       description="ORBWAVE sine amplitude"))
        self.add_param(floatParameter("ORBWAVE_OM", units="rad/s",
                                      description="Base ORBWAVE frequency"))
        self.add_param(MJDParameter("ORBWAVE_EPOCH",
                                    description="ORBWAVE reference epoch"))
        self._nfb = 0
        self._nwaves = 0

    def setup(self):
        idxs = sorted(int(p[2:]) for p in self.params
                      if p.startswith("FB") and p[2:].isdigit()
                      and self._params_dict[p].value is not None)
        self._nfb = (max(idxs) + 1) if idxs else 0
        nc = sorted(int(p[8:]) for p in self.params
                    if p.startswith("ORBWAVEC") and p[8:].isdigit()
                    and self._params_dict[p].value is not None)
        ns = sorted(int(p[8:]) for p in self.params
                    if p.startswith("ORBWAVES") and p[8:].isdigit()
                    and self._params_dict[p].value is not None)
        if nc or ns:
            if nc != list(range(len(nc))) or ns != list(range(len(ns))):
                raise TimingModelError(
                    f"ORBWAVE indices must be 0..k without gaps: {nc}/{ns}")
            if len(nc) != len(ns):
                raise TimingModelError(
                    f"Equal numbers of ORBWAVEC/ORBWAVES required "
                    f"({len(nc)} vs {len(ns)})")
        self._nwaves = len(nc)

    def validate(self):
        uses_fb = self._nfb > 0
        if not uses_fb and self.PB.value is None:
            raise MissingParameter(type(self).__name__, "PB (or FB0)")
        if self._nwaves:
            if self.ORBWAVE_OM.value is None:
                raise MissingParameter(type(self).__name__, "ORBWAVE_OM")
            if self.ORBWAVE_EPOCH.value is None:
                raise MissingParameter(type(self).__name__, "ORBWAVE_EPOCH")
        ep = self._params_dict[self.epoch_param]
        if ep.value is None:
            raise MissingParameter(type(self).__name__, self.epoch_param)
        if self.A1.value is None:
            raise MissingParameter(type(self).__name__, "A1")
        sini = self.SINI.value
        if sini is not None and not -1.0 <= sini <= 1.0:
            raise TimingModelError(f"SINI = {sini} must be within [-1, 1]")
        ecc = getattr(self, "ECC", None)
        if ecc is not None and ecc.value is not None and not 0 <= ecc.value < 1:
            raise TimingModelError(f"ECC = {ecc.value} must be within [0, 1)")

    # -- engine plumbing ----------------------------------------------------
    def _orbits_fn(self):
        """Static choice of orbit parameterization (reference
        ``binary_orbits.py``): ORBWAVES (on a PB or FBX base) when wave
        amplitudes are set, else FBX when any FBn is set, else PB."""
        fb_names = ([f"FB{i}" for i in range(self._nfb)]
                    if self._nfb else None)
        if self._nwaves:
            c_names = [f"ORBWAVEC{i}" for i in range(self._nwaves)]
            s_names = [f"ORBWAVES{i}" for i in range(self._nwaves)]
            ep_name = self.epoch_param

            def fn(pv, tt0):
                # tw = t - ORBWAVE_EPOCH = tt0 + (epoch - ORBWAVE_EPOCH)
                off = dd_mul(dd_sub(pv[ep_name], pv["ORBWAVE_EPOCH"]), DAY_S)
                tw = tt0 + (off.hi + off.lo)
                return eng.orbits_waves(pv, tt0, tw, c_names, s_names,
                                        fb_names=fb_names)

            return fn
        if fb_names:

            def fn(pv, tt0):
                return eng.orbits_fbx([pv.get(n, 0.0) for n in fb_names], tt0)

            return fn
        return eng.orbits_pb

    def _tt0(self, pv, batch, acc_delay):
        epoch = pv[self.epoch_param]
        d = dd_mul(dd_sub(batch.tdb, epoch), DAY_S)
        return (d.hi + d.lo) - acc_delay

    def binary_delay(self, pv, tt0):
        """Engine dispatch; subclasses override."""
        raise NotImplementedError

    def delay_func(self, pv, batch, ctx, acc_delay):
        return self.binary_delay(pv, self._tt0(pv, batch, acc_delay))

    #: (parameter, rate parameter, rate time unit) rows applied when the
    #: epoch moves by an integer number of orbits; TASC models override.
    _secular_rows = (("ECC", "EDOT", "s"), ("OM", "OMDOT", "yr"),
                     ("A1", "A1DOT", "s"))

    def change_binary_epoch(self, new_epoch):
        """Move the binary epoch (T0 or TASC) to the orbit boundary closest
        to ``new_epoch`` [MJD TDB], advancing PB (or the FB ladder) along
        PBDOT and the secular parameters (ECC/OM/A1, or EPS1/EPS2/A1 for
        TASC models) along their rates (reference ``pulsar_binary.py:598``,
        ``binary_ell1.py:228``).  FB2+ are ignored in choosing the integer
        orbit count, as in the reference."""
        from pint_tpu.utils import taylor_horner_deriv

        ep = self._params_dict[self.epoch_param]
        uses_fb = self._nfb > 0
        if not uses_fb:
            pb_d = float(self.PB.value)
            pbdot = float(self.PBDOT.value or 0.0)
        else:
            fb0 = float(self.FB0.value)
            fb1 = float(getattr(self, "FB1").value or 0.0) \
                if "FB1" in self._params_dict else 0.0
            pb_d = 1.0 / fb0 / DAY_S
            pbdot = -fb1 / fb0**2
        dt_d = float(np.longdouble(new_epoch) - np.longdouble(ep.value))
        d_orbits = dt_d / pb_d - pbdot * dt_d**2 / (2.0 * pb_d**2)
        n_orbits = float(np.round(d_orbits))
        if n_orbits == 0:
            return
        # epoch shift for exactly n integer orbits, to first order in PBDOT
        dt_io_d = pb_d * n_orbits + pb_d * pbdot * n_orbits**2 / 2.0
        ep.value = np.longdouble(ep.value) + np.longdouble(dt_io_d)
        if uses_fb and self._nfb > 2 \
                and getattr(self, "FB2").value is not None:
            log.warning("Ignoring orbital frequency derivatives higher than "
                        "FB1 in computing the new epoch; a model fit should "
                        "resolve this")
        if not uses_fb:
            self.PB.value = pb_d + pbdot * dt_io_d
        else:
            fbterms = [0.0] + [float(self._params_dict[f"FB{i}"].value or 0.0)
                               for i in range(self._nfb)]
            dt_io_s = dt_io_d * DAY_S
            for n in range(self._nfb):
                self._params_dict[f"FB{n}"].value = float(
                    taylor_horner_deriv(dt_io_s, fbterms, deriv_order=n + 1))
        for name, rate, unit in self._secular_rows:
            r = self._params_dict.get(rate)
            if r is None or r.value is None:
                continue
            dt_u = dt_io_d * DAY_S if unit == "s" else dt_io_d / 365.25
            p = self._params_dict[name]
            p.value = float(p.value or 0.0) + float(r.value) * dt_u

    def pb(self, t=None):
        """Orbital period and 1-sigma uncertainty at MJD time(s) ``t``
        (reference ``pulsar_binary.py:672``), from PB/PBDOT(+XPBDOT) or the
        FB frequency ladder.

        Unlike the reference (which returns days on the PB path but seconds
        on the FB path), both paths return **days**; the uncertainty is
        ``None`` when no source parameter carries one.
        """
        ep = self._params_dict[self.epoch_param]
        t_mjd = float(ep.value) if t is None else t
        dt_d = np.asarray(t_mjd, dtype=np.float64) - float(ep.value)
        if self.PB.value is not None:
            pb_d = float(self.PB.value)
            err2 = (float(self.PB.uncertainty) ** 2
                    if self.PB.uncertainty is not None else 0.0)
            pbdot = 0.0
            for name in ("PBDOT", "XPBDOT"):
                p = self._params_dict.get(name)
                if p is not None and p.value is not None:
                    pbdot += float(p.value)
                    if p.uncertainty is not None:
                        err2 += (float(p.uncertainty) * dt_d) ** 2
            val = pb_d + pbdot * dt_d
            err = np.sqrt(err2) if np.any(err2) else None
            return val, err
        if self._nfb:
            from pint_tpu.utils import taylor_horner

            dt_s = dt_d * DAY_S
            coeffs = [float(self._params_dict[f"FB{i}"].value or 0.0)
                      for i in range(self._nfb)]
            f = np.asarray(taylor_horner(dt_s, coeffs), dtype=np.float64)
            val = 1.0 / f / DAY_S
            # d(1/f)/dFB_i = -(dt^i / i!) / f^2
            import math

            err2 = np.zeros_like(np.asarray(dt_s, dtype=np.float64))
            any_err = False
            for i in range(self._nfb):
                u_i = self._params_dict[f"FB{i}"].uncertainty
                if u_i is not None:
                    any_err = True
                    err2 = err2 + (dt_s**i / math.factorial(i) / f**2
                                   * float(u_i)) ** 2
            err = np.sqrt(err2) / DAY_S if any_err else None
            return val, err
        raise AttributeError(
            "Neither PB nor FB0 is present in the timing model")

    def pbdot_pair(self):
        """(PBDOT, sigma) from FB1/FB0 when the FB ladder drives the orbit,
        else from PBDOT itself; ``None`` when neither is set.  Single home
        for the -FB1/FB0^2 derivation (also used by the derived-parameter
        report)."""
        fb1 = self._params_dict.get("FB1")
        if fb1 is not None and fb1.value:
            fb0 = self._params_dict["FB0"]
            f0v, f1v = float(fb0.value), float(fb1.value)
            val = -f1v / f0v**2
            err = float(np.hypot((fb1.uncertainty or 0.0) / f0v**2,
                                 2.0 * f1v * (fb0.uncertainty or 0.0)
                                 / f0v**3))
            return val, err
        p = self._params_dict.get("PBDOT")
        if p is not None and p.value:
            return float(p.value), float(p.uncertainty or 0.0)
        return None

    # -- orbital kinematics (reference ``timing_model.py:859-1080``) -------
    def _epoch_mjd(self, pv) -> float:
        epoch = pv[self.epoch_param]
        return float(epoch.hi + epoch.lo) if hasattr(epoch, "hi") \
            else float(epoch)

    def _host_tt0(self, barytimes, pv=None):
        """Barycentric MJD(TDB) times -> (seconds since the binary epoch,
        parameter dict).  Pass a prebuilt ``pv`` to skip rebuilding the
        parameter pytree in loops."""
        bts = np.atleast_1d(np.asarray(
            getattr(barytimes, "mjd", barytimes), dtype=np.float64))
        if pv is None:
            pv = self._parent._const_pv()
        return (bts - self._epoch_mjd(pv)) * 86400.0, pv

    def _mean_anomaly(self, pv, tt0) -> np.ndarray:
        orbits, _pbprime = self._orbits_fn()(pv, tt0)
        return np.asarray(eng.mean_anomaly(np.asarray(orbits)))

    def _true_anomaly(self, pv, tt0) -> np.ndarray:
        M = self._mean_anomaly(pv, tt0)
        ecc = np.asarray(eng.ecc_at(pv, tt0))
        E = np.asarray(eng.solve_kepler(M, ecc))
        return 2.0 * np.arctan2(np.sqrt(1 + ecc) * np.sin(E / 2),
                                np.sqrt(1 - ecc) * np.cos(E / 2))

    def _pb_days(self, pv) -> float:
        if pv.get("PB", 0.0):
            return float(pv["PB"])
        return 1.0 / float(pv["FB0"]) / 86400.0

    def orbital_phase(self, barytimes, anom: str = "mean",
                      radians: bool = True) -> np.ndarray:
        """Mean / eccentric / true anomaly at barycentric MJD(TDB) times
        (reference ``timing_model.py:859``); radians by default, cycles in
        [0, 1) with ``radians=False``."""
        tt0, pv = self._host_tt0(barytimes)
        if anom.lower() == "mean":
            out = self._mean_anomaly(pv, tt0)
        elif anom.lower().startswith("ecc"):
            M = self._mean_anomaly(pv, tt0)
            out = np.asarray(eng.solve_kepler(M, eng.ecc_at(pv, tt0)))
        elif anom.lower() == "true":
            out = self._true_anomaly(pv, tt0)
        else:
            raise ValueError(
                f"anom={anom!r} is not a recognized type of anomaly")
        out = np.remainder(out, 2 * np.pi)
        return out if radians else out / (2 * np.pi)

    def pulsar_radial_velocity(self, barytimes) -> np.ndarray:
        """Line-of-sight velocity of the pulsar about the system barycenter
        [m/s] (reference ``timing_model.py:933``; Lorimer & Kramer 2008 Eqn
        8.24 — the reference returns cgs)."""
        from pint_tpu import c as C_M_S

        tt0, pv = self._host_tt0(barytimes)
        nu = self._true_anomaly(pv, tt0)
        ecc = np.asarray(eng.ecc_at(pv, tt0))
        a1_s = np.asarray(eng.a1_at(pv, tt0))  # light-seconds
        omega = np.asarray(eng.omega_bt(pv, tt0))
        pb_s = self._pb_days(pv) * 86400.0
        psi = nu + omega
        return (2 * np.pi * a1_s / (pb_s * np.sqrt(1 - ecc**2))
                * (np.cos(psi) + ecc * np.cos(omega)) * C_M_S)

    def companion_radial_velocity(self, barytimes,
                                  massratio: float) -> np.ndarray:
        """Companion line-of-sight velocity [m/s]; ``massratio`` is
        m_pulsar/m_companion (reference ``timing_model.py:981``)."""
        return -self.pulsar_radial_velocity(barytimes) * massratio

    def _psi_minus_quarter(self, pv, ts) -> np.ndarray:
        """wrap(nu + omega - pi/2) into (-pi, pi]: zero at superior
        conjunction, continuous there (the 2*pi jump sits half an orbit
        away).  Single definition shared by the scan and the root find."""
        tt0, _ = self._host_tt0(ts, pv)
        psi = self._true_anomaly(pv, tt0) + np.asarray(eng.omega_bt(pv, tt0))
        return np.remainder(psi - np.pi / 2 + np.pi, 2 * np.pi) - np.pi

    def conjunction(self, baryMJD):
        """Barycentric MJD(TDB) of the first superior conjunction (true
        anomaly + omega = pi/2) after each input time (reference
        ``timing_model.py:1021``)."""
        from scipy.optimize import brentq

        bts = np.atleast_1d(np.asarray(
            getattr(baryMJD, "mjd", baryMJD), dtype=np.float64))
        pv = self._parent._const_pv()
        pb_d = self._pb_days(pv)

        def funct(t):
            return float(self._psi_minus_quarter(pv, t)[0])

        out = []
        # dense scan: near periastron of an eccentric orbit nu sweeps
        # rapidly, so coarse sampling can hop over the crossing entirely
        ngrid = 257
        for bt in bts:
            ts = np.linspace(bt, bt + pb_d, ngrid)
            x = self._psi_minus_quarter(pv, ts)
            for lb in range(len(x) - 1):
                # upward crossing; a root exactly on a grid point counts
                if x[lb] < 0 <= x[lb + 1] or x[lb] == 0:
                    break
            else:
                raise ValueError(
                    f"No superior conjunction found in [{bt}, {bt + pb_d}]")
            if x[lb] == 0:
                out.append(ts[lb])
            else:
                out.append(brentq(funct, ts[lb], ts[lb + 1]))
        return out[0] if len(out) == 1 else np.asarray(out)


class BinaryBT(PulsarBinary):
    """Blandford & Teukolsky model (reference ``binary_bt.py:17``)."""

    register = True
    binary_model_name = "BT"

    def binary_delay(self, pv, tt0):
        return eng.bt_delay(pv, tt0, orbits_fn=self._orbits_fn(),
                            use_pb=self._nfb == 0)


class BinaryDD(PulsarBinary):
    """Damour & Deruelle model (reference ``binary_dd.py:34``)."""

    register = True
    binary_model_name = "DD"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("A0", units="s", description="DD aberration A0"))
        self.add_param(floatParameter("B0", units="s", description="DD aberration B0"))
        self.add_param(floatParameter("DR", units="", description="Relativistic deformation of the orbit"))
        self.add_param(floatParameter("DTH", units="", aliases=["DTHETA"],
                                      description="Relativistic deformation of the orbit"))

    def binary_delay(self, pv, tt0):
        return eng.dd_delay(pv, tt0, orbits_fn=self._orbits_fn())


class BinaryDDS(BinaryDD):
    """DD with SHAPMAX = -log(1-SINI) (reference ``binary_dd.py:135``)."""

    register = True
    binary_model_name = "DDS"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("SHAPMAX", units="", description="-log(1-SINI)"))

    def validate(self):
        super().validate()
        sm = self.SHAPMAX.value
        if sm is not None and sm < -np.log(2):
            raise TimingModelError(f"SHAPMAX = {sm} must be > -log(2)")

    def binary_delay(self, pv, tt0):
        return eng.dds_delay(pv, tt0, orbits_fn=self._orbits_fn())


class BinaryDDH(BinaryDD):
    """DD with orthometric H3/STIGMA Shapiro parameters (reference
    ``binary_dd.py:211``)."""

    register = True
    binary_model_name = "DDH"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("H3", units="s", description="Orthometric Shapiro amplitude"))
        self.add_param(floatParameter("STIGMA", units="", aliases=["VARSIGMA", "STIG"],
                                      description="Orthometric Shapiro ratio"))

    def validate(self):
        super().validate()
        if self.H3.value is None or self.STIGMA.value is None:
            raise MissingParameter("BinaryDDH", "H3/STIGMA")

    def binary_delay(self, pv, tt0):
        return eng.ddh_delay(pv, tt0, orbits_fn=self._orbits_fn())


class BinaryDDGR(BinaryDD):
    """GR-constrained DD: PK parameters from (MTOT, M2) (reference
    ``binary_dd.py:382``)."""

    register = True
    binary_model_name = "DDGR"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("MTOT", units="Msun", description="Total system mass"))
        self.add_param(floatParameter("XOMDOT", units="deg/yr",
                                      description="Excess periastron advance over GR"))

    def validate(self):
        super().validate()
        if self.MTOT.value is None or self.M2.value is None:
            raise MissingParameter("BinaryDDGR", "MTOT/M2")
        if self.PB.value is None:
            # the GR constraint equations are written in terms of PB
            raise MissingParameter("BinaryDDGR", "PB",
                                   "DDGR requires PB (FB parameterization unsupported)")

    def binary_delay(self, pv, tt0):
        return eng.ddgr_delay(pv, tt0, orbits_fn=self._orbits_fn())


class BinaryDDK(BinaryDD):
    """DD with Kopeikin annual/secular parallax corrections (reference
    ``binary_ddk.py:45``)."""

    register = True
    binary_model_name = "DDK"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("KIN", units="deg", description="Orbital inclination"))
        self.add_param(floatParameter("KOM", units="deg",
                                      description="Longitude of ascending node"))
        self.add_param(boolParameter("K96", value=True,
                                     description="Apply proper-motion (Kopeikin 1996) corrections"))

    def validate(self):
        super().validate()
        if self.KIN.value is None or self.KOM.value is None:
            raise MissingParameter("BinaryDDK", "KIN/KOM")
        if self._parent is not None:
            if "PX" not in self._parent or self._parent.PX.value in (None, 0.0):
                raise TimingModelError("DDK needs a non-zero PX (Kopeikin parallax terms)")
            if "SINI" in self._parent and self._parent.SINI.value is not None:
                raise TimingModelError("DDK uses KIN; remove SINI from the par file")

    def delay_func(self, pv, batch, ctx, acc_delay):
        tt0 = self._tt0(pv, batch, acc_delay)
        astro = next((c for c in self._parent.components.values()
                      if hasattr(c, "ssb_to_psb_xyz")), None)
        if astro is None:
            raise TimingModelError("DDK requires an astrometry component")
        psr_pos = astro.ssb_to_psb_xyz(pv, batch.tdb.hi)
        pv2 = dict(pv)
        pv2["K96"] = 1.0 if self.K96.value else 0.0
        if "PMELONG" in pv and "PMRA" not in pv:
            # psr_pos (and the Kopeikin I0/J0 basis built from it) is
            # equatorial; rotate ecliptic proper motion into equatorial
            # (RA*, DEC) components so frames agree
            pv2["PMRA"], pv2["PMDEC"] = _ecliptic_pm_to_equatorial(
                pv["ELONG"], pv["ELAT"], pv.get("PMELONG", 0.0),
                pv.get("PMELAT", 0.0))
        return eng.ddk_delay(pv2, tt0, psr_pos, batch.ssb_obs_pos,
                             orbits_fn=self._orbits_fn())


class BinaryELL1(PulsarBinary):
    """Low-eccentricity Lange et al. (2001) model (reference
    ``binary_ell1.py:57``)."""

    register = True
    binary_model_name = "ELL1"
    epoch_param = "TASC"

    def __init__(self):
        super().__init__()
        self.add_param(MJDParameter("TASC", description="Epoch of ascending node"))
        self.add_param(floatParameter("EPS1", units="", description="First Laplace-Lagrange parameter"))
        self.add_param(floatParameter("EPS2", units="", description="Second Laplace-Lagrange parameter"))
        self.add_param(floatParameter("EPS1DOT", units="1/s", unit_scale=True,
                                      description="EPS1 derivative"))
        self.add_param(floatParameter("EPS2DOT", units="1/s", unit_scale=True,
                                      description="EPS2 derivative"))

    _secular_rows = (("EPS1", "EPS1DOT", "s"), ("EPS2", "EPS2DOT", "s"),
                     ("A1", "A1DOT", "s"))

    def validate(self):
        if self.TASC.value is None:
            if self.T0.value is not None and (self.EPS1.value or 0.0) == 0.0 \
                    and (self.EPS2.value or 0.0) == 0.0 \
                    and (self.ECC.value or 0.0) == 0.0:
                # circular orbit given with T0: TASC == T0
                self.TASC.value = self.T0.value
            else:
                raise MissingParameter(type(self).__name__, "TASC")
        super().validate()  # PB/A1 presence, SINI/ECC range checks
        if self.EPS1.value is None:
            self.EPS1.value = 0.0
        if self.EPS2.value is None:
            self.EPS2.value = 0.0

    def binary_delay(self, pv, tt0):
        return eng.ell1_delay(pv, tt0, orbits_fn=self._orbits_fn())

    # convenience conversions (reference ``ELL1_model.py:209-222``)
    def ell1_ecc(self) -> float:
        return float(np.hypot(self.EPS1.value or 0.0, self.EPS2.value or 0.0))

    def ell1_om_deg(self) -> float:
        return float(np.degrees(np.arctan2(self.EPS1.value or 0.0,
                                           self.EPS2.value or 0.0)) % 360.0)

    # -- orbital kinematics, ELL1 parameterization -------------------------
    # ELL1 has no periastron: the epoch is TASC and eccentricity lives in
    # EPS1/EPS2, so periastron-referenced anomalies are undefined (the
    # generic PulsarBinary math would silently use ECC=OM=0).
    def orbital_phase(self, barytimes, anom: str = "mean",
                      radians: bool = True) -> np.ndarray:
        """Orbital phase from the ascending node.  Only ``anom="mean"`` is
        defined for the ELL1 parameterization (reference raises for
        eccentric/true anomaly on ELL1 models)."""
        if anom.lower() != "mean":
            raise ValueError(
                f"anom={anom!r} is undefined for the ELL1 parameterization "
                "(EPS1/EPS2, no periastron); only 'mean' (phase from the "
                "ascending node) is available")
        return super().orbital_phase(barytimes, anom="mean", radians=radians)

    def pulsar_radial_velocity(self, barytimes) -> np.ndarray:
        """Line-of-sight velocity [m/s] in the small-eccentricity limit:
        v = K cos(Phi) with Phi the phase from the ascending node and
        K = 2 pi a1 / PB; the O(e) EPS1/EPS2 harmonic corrections
        (e ~ 1e-3 for ELL1-applicable orbits) are dropped."""
        from pint_tpu import c as C_M_S

        tt0, pv = self._host_tt0(barytimes)
        Phi = self._mean_anomaly(pv, tt0)
        a1_s = np.asarray(eng.a1_at(pv, tt0))
        pb_s = self._pb_days(pv) * 86400.0
        return 2 * np.pi * a1_s / pb_s * np.cos(Phi) * C_M_S

    def _psi_minus_quarter(self, pv, ts) -> np.ndarray:
        # superior conjunction at Phi = pi/2 from the ascending node
        tt0, _ = self._host_tt0(ts, pv)
        Phi = self._mean_anomaly(pv, tt0)
        return np.remainder(Phi - np.pi / 2 + np.pi, 2 * np.pi) - np.pi


class BinaryELL1H(BinaryELL1):
    """ELL1 with orthometric H3/H4/STIGMA Shapiro delay (reference
    ``binary_ell1.py:310``)."""

    register = True
    binary_model_name = "ELL1H"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("H3", units="s", description="Orthometric Shapiro amplitude"))
        self.add_param(floatParameter("H4", units="s", description="Fourth Shapiro harmonic"))
        self.add_param(floatParameter("STIGMA", units="", aliases=["VARSIGMA", "STIG"],
                                      description="Orthometric Shapiro ratio"))
        self.add_param(intParameter("NHARMS", value=7,
                                    description="Number of Shapiro harmonics"))

    def validate(self):
        super().validate()
        if self.H3.value is None:
            raise MissingParameter("BinaryELL1H", "H3")
        if self.H4.value is not None and self.STIGMA.value is not None:
            raise TimingModelError("Provide H4 or STIGMA, not both")

    def binary_delay(self, pv, tt0):
        use_h4 = self.H4.value is not None and self.STIGMA.value is None
        # exact form for H3/STIGMA with significant STIGMA (Freire & Wex
        # 2010 eq 28); harmonic sum otherwise
        exact = self.STIGMA.value is not None and self.STIGMA.value != 0.0
        return eng.ell1h_delay(pv, tt0, orbits_fn=self._orbits_fn(),
                               nharms=int(self.NHARMS.value or 7),
                               exact=exact, use_h4=use_h4)


class BinaryELL1k(BinaryELL1):
    """ELL1 with exponential eccentricity evolution and periastron advance
    (Susobhanan+ 2018; reference ``binary_ell1.py:423``)."""

    register = True
    binary_model_name = "ELL1k"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("LNEDOT", units="1/yr",
                                      description="Relative eccentricity derivative"))

    def binary_delay(self, pv, tt0):
        return eng.ell1k_delay(pv, tt0, orbits_fn=self._orbits_fn())


class BinaryBT_piecewise(BinaryBT):
    """BT with piecewise orbital parameters: per-range T0X_xxxx/A1X_xxxx
    overrides selected by [XR1_xxxx, XR2_xxxx] MJD windows (reference
    ``binary_bt.py:85 BinaryBTPiecewise``).

    Piece epochs are float64 MJD (sub-us T0 resolution), applied as exact
    float differences against the dd-precision global T0.
    """

    register = True
    binary_model_name = "BT_piecewise"

    def __init__(self):
        super().__init__()
        self.add_param(prefixParameter("T0X_0001", units="MJD",
                                       description="Piecewise T0 override"))
        self.add_param(prefixParameter("A1X_0001", units="ls",
                                       description="Piecewise A1 override"))
        self.add_param(prefixParameter("XR1_0001", units="MJD",
                                       description="Piece start MJD"))
        self.add_param(prefixParameter("XR2_0001", units="MJD",
                                       description="Piece end MJD"))
        self.piece_indices = []

    def setup(self):
        super().setup()
        self.piece_indices = sorted(
            int(p[4:]) for p in self.params
            if p.startswith("T0X_") and self._params_dict[p].value is not None)

    def validate(self):
        super().validate()
        for i in self.piece_indices:
            for pre in ("XR1_", "XR2_"):
                nm = f"{pre}{i:04d}"
                if nm not in self._params_dict or \
                        self._params_dict[nm].value is None:
                    raise MissingParameter("BinaryBT_piecewise", nm)

    def build_context(self, toas):
        mjds = np.asarray(toas.get_mjds(), dtype=np.float64)
        masks = []
        for i in self.piece_indices:
            r1 = float(self._params_dict[f"XR1_{i:04d}"].value)
            r2 = float(self._params_dict[f"XR2_{i:04d}"].value)
            masks.append(((mjds >= r1) & (mjds < r2)).astype(np.float64))
        return {"masks": jnp.asarray(np.array(masks)) if masks else None}

    def delay_func(self, pv, batch, ctx, acc_delay):
        tt0 = self._tt0(pv, batch, acc_delay)
        if ctx.get("masks") is None:
            return self.binary_delay(pv, tt0)
        t0 = pv["T0"]
        t0_hi = t0.hi if hasattr(t0, "hi") else t0
        t0_lo = t0.lo if hasattr(t0, "lo") else 0.0
        a1 = pv.get("A1", 0.0) * jnp.ones_like(tt0)
        for k, i in enumerate(self.piece_indices):
            m = ctx["masks"][k]
            # exact float difference against the dd global T0 (values are
            # close, so the subtraction cancels without rounding)
            dt_days = (t0_hi - pv.get(f"T0X_{i:04d}", 0.0)) + t0_lo
            tt0 = tt0 + m * dt_days * DAY_S
            a1 = a1 + m * (pv.get(f"A1X_{i:04d}", 0.0) - pv.get("A1", 0.0))
        pv2 = dict(pv)
        pv2["A1"] = a1
        return self.binary_delay(pv2, tt0)


#: reference class name (``binary_bt.py:85``)
BinaryBTPiecewise = BinaryBT_piecewise
