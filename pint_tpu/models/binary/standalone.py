"""Stand-alone binary engines under the reference's class names.

The compute path is the functional jnp engine set in
:mod:`pint_tpu.models.binary.engines` (<=1 ns parity vs the reference,
``tests/test_reference_parity.py``); these classes provide the reference's
object API on top (``binary_generic.py:15 PSR_BINARY``, ``DD_model.py
DDmodel``, ``ELL1_model.py ELL1model``, ``binary_orbits.py`` Orbit
classes):

    m = DDmodel()
    m.update_input(barycentric_toa=t_mjd, PB=..., A1=..., T0=..., ...)
    d = m.binary_delay()              # np.ndarray seconds
    dd = m.d_binarydelay_d_par("A1")  # autodiff, any parameter

Parameters use the reference's stand-alone units (PB days, A1 light-s,
OM deg, M2 Msun, T0/TASC MJD...).  Derivatives come from ``jax.jacfwd`` of
the engine — the reference's hand-written ``prtl_der`` chain
(``binary_generic.py:265``) has no counterpart because autodiff covers
every parameter.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.models.binary import engines as E

__all__ = [
    "PSR_BINARY", "BTmodel", "BTpiecewise", "DDmodel", "DDSmodel",
    "DDHmodel", "DDGRmodel", "DDKmodel", "ELL1BaseModel", "ELL1model",
    "ELL1Hmodel", "ELL1kmodel",
    "Orbit", "OrbitPB", "OrbitFBX", "OrbitWaves", "OrbitWavesFBX",
]

DAY_S = 86400.0


class PSR_BINARY:
    """Base stand-alone binary (reference ``binary_generic.py:15``)."""

    #: engine delay function (pv, tt0, **kw) -> seconds
    _delay_fn = None
    #: epoch parameter subtracted from the TOAs to form tt0
    t0_key = "T0"

    def __init__(self):
        self.pars: Dict[str, float] = {}
        self.barycentric_toa: Optional[np.ndarray] = None
        self.psr_pos = None      # DDK: (N, 3) unit vectors
        self.obs_pos = None      # DDK: (N, 3) km
        self.fit_params: list = []

    # -- reference API ------------------------------------------------------
    def update_input(self, barycentric_toa=None, **pars):
        """Set TOAs (MJD) and/or parameter values (reference
        ``binary_generic.py`` update_input)."""
        if barycentric_toa is not None:
            self.barycentric_toa = np.asarray(barycentric_toa,
                                              dtype=np.float64)
        for k, v in pars.items():
            self.pars[k] = float(v)

    def _tt0_and_pv(self, pars=None):
        pars = dict(self.pars if pars is None else pars)
        if self.barycentric_toa is None:
            raise ValueError("update_input(barycentric_toa=...) first")
        t0 = pars.get(self.t0_key)
        if t0 is None:
            raise ValueError(f"{self.t0_key} is not set")
        tt0 = (self.barycentric_toa - t0) * DAY_S
        pv = {k: v for k, v in pars.items() if k not in ("T0", "TASC")}
        return jnp.asarray(tt0), pv

    def _extra_kw(self) -> dict:
        return {}

    def binary_delay(self) -> np.ndarray:
        """Total binary delay [s] at the current TOAs/parameters."""
        tt0, pv = self._tt0_and_pv()
        out = type(self)._delay_fn(pv, tt0, **self._extra_kw())
        return np.asarray(jax.device_get(out), dtype=np.float64)

    def d_binarydelay_d_par(self, par: str) -> np.ndarray:
        """d(delay)/d(par) [s per par unit] by autodiff; the epoch
        parameter (T0/TASC) differentiates through tt0."""
        if par == self.t0_key:
            tt0, pv = self._tt0_and_pv()

            def f(t0_shift):
                return type(self)._delay_fn(pv, tt0 - t0_shift * DAY_S,
                                            **self._extra_kw())

            return np.asarray(jax.jacfwd(f)(0.0), dtype=np.float64)
        if par not in self.pars:
            raise KeyError(f"Parameter {par!r} is not set")
        tt0, pv = self._tt0_and_pv()

        def f(x):
            pv2 = dict(pv)
            pv2[par] = x
            return type(self)._delay_fn(pv2, tt0, **self._extra_kw())

        return np.asarray(jax.jacfwd(f)(self.pars[par]), dtype=np.float64)

    def __getattr__(self, name):
        pars = object.__getattribute__(self, "__dict__").get("pars", {})
        if name in pars:
            return pars[name]
        raise AttributeError(f"{type(self).__name__} has no attribute "
                             f"{name!r}")


class BTmodel(PSR_BINARY):
    """Blandford-Teukolsky (reference ``BT_model.py:141``)."""

    _delay_fn = staticmethod(E.bt_delay)


class BTpiecewise(PSR_BINARY):
    """Stand-alone BT with piecewise T0X/A1X overrides in [XR1, XR2) MJD
    windows (reference ``BT_piecewise.py BTpiecewise``): pass
    ``T0X_0001/A1X_0001/XR1_0001/XR2_0001``-style values through
    ``update_input`` alongside the global BT parameters; per-TOA A1 and
    tt0 shifts are applied exactly like the par-facing component
    (``components.py BinaryBT_piecewise``)."""

    _delay_fn = staticmethod(E.bt_delay)

    def binary_delay(self) -> np.ndarray:
        tt0, pv = self._tt0_and_pv()
        idxs = sorted(k[4:] for k in pv if k.startswith("T0X_"))
        if not idxs:
            out = E.bt_delay(pv, tt0)
            return np.asarray(jax.device_get(out), dtype=np.float64)
        mjds = jnp.asarray(self.barycentric_toa)
        t0 = self.pars[self.t0_key]
        a1 = pv.get("A1", 0.0) * jnp.ones_like(tt0)
        for ix in idxs:
            r1 = pv.get(f"XR1_{ix}")
            r2 = pv.get(f"XR2_{ix}")
            if r1 is None or r2 is None:
                raise ValueError(f"piece {ix}: XR1_{ix}/XR2_{ix} required")
            m = ((mjds >= r1) & (mjds < r2)).astype(tt0.dtype)
            tt0 = tt0 + m * (t0 - pv.get(f"T0X_{ix}", t0)) * DAY_S
            a1 = a1 + m * (pv.get(f"A1X_{ix}", pv.get("A1", 0.0))
                           - pv.get("A1", 0.0))
        pv2 = {k: v for k, v in pv.items()
               if not k.startswith(("T0X_", "A1X_", "XR1_", "XR2_"))}
        pv2["A1"] = a1
        out = E.bt_delay(pv2, tt0)
        return np.asarray(jax.device_get(out), dtype=np.float64)


class DDmodel(PSR_BINARY):
    """Damour-Deruelle (reference ``DD_model.py:854``)."""

    _delay_fn = staticmethod(E.dd_delay)


class DDSmodel(PSR_BINARY):
    """DD with SHAPMAX Shapiro parameterization (reference
    ``DDS_model.py``)."""

    _delay_fn = staticmethod(E.dds_delay)


class DDHmodel(PSR_BINARY):
    """DD with H3/STIGMA orthometric Shapiro (reference ``DDH_model.py``)."""

    _delay_fn = staticmethod(E.ddh_delay)


class DDGRmodel(PSR_BINARY):
    """GR-constrained DD (reference ``DDGR_model.py``)."""

    _delay_fn = staticmethod(E.ddgr_delay)


class DDKmodel(PSR_BINARY):
    """DD + Kopeikin annual/secular parallax terms (reference
    ``DDK_model.py``); needs ``psr_pos`` (unit vectors) and ``obs_pos``
    (km) set as attributes, like the reference."""

    _delay_fn = staticmethod(E.ddk_delay)

    def _extra_kw(self):
        if self.psr_pos is None or self.obs_pos is None:
            raise ValueError("DDKmodel needs psr_pos and obs_pos")
        obs = self.obs_pos
        # reference carries obs_pos as a km Quantity; engine wants light-s
        obs_km = np.asarray(getattr(obs, "value", obs), dtype=np.float64)
        from pint_tpu import c as C_M_S

        return dict(psr_pos=jnp.asarray(self.psr_pos),
                    obs_pos_ls=jnp.asarray(obs_km * 1e3 / C_M_S))


class ELL1BaseModel(PSR_BINARY):
    """Low-eccentricity Lange et al. expansion (reference
    ``ELL1_model.py:143``)."""

    _delay_fn = staticmethod(E.ell1_delay)
    t0_key = "TASC"


class ELL1model(ELL1BaseModel):
    pass


class ELL1Hmodel(ELL1BaseModel):
    """ELL1 with orthometric-harmonic Shapiro (reference
    ``ELL1H_model.py``)."""

    _delay_fn = staticmethod(E.ell1h_delay)

    def _extra_kw(self):
        nharms = int(self.pars.get("NHARMS", 7))
        # H3/H4 truncated-harmonic form when H4 is supplied and STIGMA is
        # neither set nor being fit (reference ELL1H fit_params semantics)
        use_h4 = "H4" in self.pars and "STIGMA" not in self.pars \
            and "STIGMA" not in self.fit_params
        return dict(nharms=nharms, use_h4=use_h4)


class ELL1kmodel(ELL1BaseModel):
    """ELL1 with exponentially-decaying eccentricity (reference
    ``ELL1k_model.py``)."""

    _delay_fn = staticmethod(E.ell1k_delay)


# ---------------------------------------------------------------------------
# orbit abstraction (reference ``binary_orbits.py``)
# ---------------------------------------------------------------------------

class Orbit:
    """Orbital-phase abstraction: maps (params, tt0) to orbit count
    (reference ``binary_orbits.py Orbit``); ``pbprime`` is the
    instantaneous orbital period [s]."""

    def _raw(self, pv, tt0):
        raise NotImplementedError

    def orbits(self, pv, tt0):
        return self._raw(pv, tt0)[0]

    def pbprime(self, pv, tt0):
        return self._raw(pv, tt0)[1]

    def __call__(self, pv, tt0):
        return self.orbits(pv, tt0)


class OrbitPB(Orbit):
    """PB/PBDOT parameterization (reference ``OrbitPB``)."""

    def _raw(self, pv, tt0):
        return E.orbits_pb(pv, tt0)


class OrbitFBX(Orbit):
    """FB0/FB1/... orbital-frequency Taylor series (reference
    ``OrbitFBX``)."""

    def _raw(self, pv, tt0):
        fbs = [pv[k] for k in _numeric_sorted(pv, "FB")]
        return E.orbits_fbx(jnp.asarray(fbs), tt0)


def _numeric_sorted(pv, prefix):
    """Parameter names ``<prefix><n>`` in NUMERIC index order (lexicographic
    sorting would put FB10 between FB1 and FB2)."""
    names = [k for k in pv if k.startswith(prefix)
             and k[len(prefix):].isdigit()]
    return sorted(names, key=lambda k: int(k[len(prefix):]))


class OrbitWaves(Orbit):
    """PB + ORBWAVE sinusoids (reference ``OrbitWaves``).

    ``t0_mjd`` is the binary epoch the tt0 argument is referenced to; the
    engine wants seconds since ORBWAVE_EPOCH, i.e.
    ``tt0 + (t0_mjd - ORBWAVE_EPOCH) * 86400``
    (reference ``binary/components.py`` tw construction)."""

    def __init__(self, t0_mjd: Optional[float] = None):
        self.t0_mjd = t0_mjd

    def _tw(self, pv, tt0):
        ow = pv.get("ORBWAVE_EPOCH")
        if ow is None:
            return tt0
        if self.t0_mjd is None:
            raise ValueError(
                "OrbitWaves with ORBWAVE_EPOCH needs t0_mjd (the epoch tt0 "
                "is referenced to) to place the waves in time")
        return tt0 + (self.t0_mjd - ow) * DAY_S

    def _raw(self, pv, tt0):
        return E.orbits_waves(pv, tt0, self._tw(pv, tt0),
                              _numeric_sorted(pv, "ORBWAVEC"),
                              _numeric_sorted(pv, "ORBWAVES"))


class OrbitWavesFBX(OrbitWaves):
    """FBX + ORBWAVE sinusoids (reference ``OrbitWavesFBX``)."""

    def _raw(self, pv, tt0):
        return E.orbits_waves(pv, tt0, self._tw(pv, tt0),
                              _numeric_sorted(pv, "ORBWAVEC"),
                              _numeric_sorted(pv, "ORBWAVES"),
                              fb_names=_numeric_sorted(pv, "FB"))
