"""Binary-orbit delay engines: pure jnp functions of (params, time).

TPU-first counterpart of the reference's stand-alone engines
(``stand_alone_psr_binaries/``: ``binary_generic.py``, ``binary_orbits.py``,
``BT_model.py``, ``DD_model.py``, ``DDS_model.py``, ``DDH_model.py``,
``DDGR_model.py``, ``DDK_model.py``, ``ELL1_model.py``, ``ELL1H_model.py``,
``ELL1k_model.py``).  Design differences:

* everything is a pure function of a parameter dict ``pv`` (traced floats)
  and ``tt0`` (seconds since T0/TASC) — no mutable engine objects, no hand
  derivative registry: ``jax.jacfwd`` through these functions supplies every
  partial;
* the Kepler equation is solved by fixed-iteration Newton (jit/vmap-safe,
  no data-dependent while loops on device);
* model variants (DDS/DDH/DDK/DDGR) are parameterizations feeding the same
  DD core, passed as precomputed (sini, m2, gamma, k, ...) inputs.

Physics references as in the reference code: Blandford & Teukolsky (1976),
Damour & Deruelle (1986), Taylor & Weisberg (1989), Lange et al. (2001),
Kopeikin (1995, 1996), Freire & Wex (2010), Susobhanan et al. (2018).
"""

from __future__ import annotations

import math

import jax.numpy as jnp

#: G * Msun / c^3 [s]
TSUN = 4.925490947000518e-6
#: 1 kpc in light-seconds
KPC_LS = 3.0856775814913673e19 / 299792458.0
SEC_PER_YEAR = 365.25 * 86400.0
DEG = math.pi / 180.0
TWO_PI = 2.0 * math.pi


def solve_kepler(M, e, niter: int = 15):
    """E - e sin E = M by Newton iteration (fixed count: trace-friendly;
    15 iterations converge to <1e-15 for e <= 0.95; reference
    ``binary_generic.py:335`` iterates to 5e-15).

    Steps are clamped to |dE| <= 1: near e -> 1 with small M the derivative
    1 - e cos E vanishes at the start point and raw Newton overshoots by
    ~1/(1-e) and never recovers; the clamp turns that into steady progress
    while leaving converged iterates (tiny steps) untouched.
    """
    E = M + e * jnp.sin(M)
    for _ in range(niter):
        dE = (E - e * jnp.sin(E) - M) / (1.0 - e * jnp.cos(E))
        E = E - jnp.clip(dE, -1.0, 1.0)
    return E


# ----------------------------------------------------------------------
# orbits: number of orbits + instantaneous period since T0/TASC
# ----------------------------------------------------------------------
def orbits_pb(pv, tt0):
    """PB/PBDOT/XPBDOT parameterization (reference ``binary_orbits.py:85``)."""
    pb_s = pv["PB"] * 86400.0
    pbdot = pv.get("PBDOT", 0.0) + pv.get("XPBDOT", 0.0)
    frac = tt0 / pb_s
    orbits = frac - 0.5 * pbdot * frac * frac
    pbprime = pb_s + pv.get("PBDOT", 0.0) * tt0
    return orbits, pbprime


def orbits_fbx(fb_values, tt0):
    """FB0,FB1,... orbital-frequency Taylor series (reference
    ``binary_orbits.py:159``): orbits = sum FBn tt0^(n+1)/(n+1)!."""
    orbits = jnp.zeros_like(tt0)
    freq = jnp.zeros_like(tt0)
    # Horner from the highest term down:
    #   orbits = sum FBn t^(n+1)/(n+1)!,  freq = d orbits/dt = sum FBn t^n/n!
    for n in range(len(fb_values) - 1, -1, -1):
        f = fb_values[n]
        orbits = (orbits * tt0) * (1.0 / (n + 2)) + f
        freq = (freq * tt0) * (1.0 / (n + 1)) + f
    orbits = orbits * tt0
    return orbits, 1.0 / freq


def orbits_waves(pv, tt0, tw, c_names, s_names, fb_names=None):
    """ORBWAVES orbital-phase Fourier modulation (reference
    ``binary_orbits.py:243 OrbitWaves`` / ``:455 OrbitWavesFBX``):

        orbits = base(tt0) + sum_k [C_k cos((k+1) OM tw) + S_k sin(...)]

    with ``tw = t - ORBWAVE_EPOCH`` seconds and OM = ORBWAVE_OM [rad/s].
    The PB base deliberately ignores PBDOT/XPBDOT (the reference's
    OrbitWaves parameter list excludes them); pbprime comes from the
    instantaneous frequency 1/pbprime_base + d(dphi)/dt.
    """
    om = pv.get("ORBWAVE_OM", 0.0)
    dphi = jnp.zeros_like(tt0)
    dphi_dot = jnp.zeros_like(tt0)
    for k, (cn, sn) in enumerate(zip(c_names, s_names)):
        c = pv.get(cn, 0.0)
        s = pv.get(sn, 0.0)
        w = (k + 1) * om
        ph = w * tw
        dphi = dphi + c * jnp.cos(ph) + s * jnp.sin(ph)
        dphi_dot = dphi_dot + w * (s * jnp.cos(ph) - c * jnp.sin(ph))
    if fb_names is not None:
        orbits0, pbp0 = orbits_fbx([pv.get(n, 0.0) for n in fb_names], tt0)
        return orbits0 + dphi, 1.0 / (1.0 / pbp0 + dphi_dot)
    pb_s = pv["PB"] * 86400.0
    return tt0 / pb_s + dphi, 1.0 / (1.0 / pb_s + dphi_dot)


def mean_anomaly(orbits):
    """Orbital phase in [0, 2pi) (reference ``binary_orbits.py:26``)."""
    return (orbits - jnp.floor(orbits)) * TWO_PI


# ----------------------------------------------------------------------
# shared secular evolutions
# ----------------------------------------------------------------------
def ecc_at(pv, tt0):
    return pv.get("ECC", 0.0) + tt0 * pv.get("EDOT", 0.0)


def a1_at(pv, tt0):
    return pv.get("A1", 0.0) + tt0 * pv.get("A1DOT", 0.0)


def omega_bt(pv, tt0):
    """omega = OM + OMDOT*tt0 [rad] (reference ``binary_generic.py:629``)."""
    return pv.get("OM", 0.0) * DEG + pv.get("OMDOT", 0.0) * DEG / SEC_PER_YEAR * tt0


# ----------------------------------------------------------------------
# BT (Blandford & Teukolsky 1976)
# ----------------------------------------------------------------------
def bt_delay(pv, tt0, orbits_fn=orbits_pb, use_pb: bool = True):
    """BT model delay (reference ``BT_model.py:141 BTdelay``):
    (L1 + L2) * R with L1 = alpha (cosE - e), L2 = (beta + GAMMA) sinE,
    R the 1st-order inverse-timing correction.  ``use_pb``: tempo uses the
    constant PB (not pbprime) in R (``BT_model.py:117``); pass False for
    FBX-parameterized orbits (static flag)."""
    orbits, pbprime = orbits_fn(pv, tt0)
    M = mean_anomaly(orbits)
    e = ecc_at(pv, tt0)
    E = solve_kepler(M, e)
    a1 = a1_at(pv, tt0)
    om = omega_bt(pv, tt0)
    sin_om, cos_om = jnp.sin(om), jnp.cos(om)
    sinE, cosE = jnp.sin(E), jnp.cos(E)
    alpha = a1 * sin_om
    beta = a1 * cos_om * jnp.sqrt(1.0 - e * e)
    gamma = pv.get("GAMMA", 0.0)
    L = alpha * (cosE - e) + (beta + gamma) * sinE
    pb_s = pv["PB"] * 86400.0 if use_pb else pbprime
    num = beta * cosE - alpha * sinE
    den = 1.0 - e * cosE
    return L * (1.0 - TWO_PI * num / (den * pb_s))


# ----------------------------------------------------------------------
# DD core (Damour & Deruelle 1986)
# ----------------------------------------------------------------------
def dd_state(pv, tt0, orbits_fn=orbits_pb, k_override=None):
    """Common DD quantities: E, nu, omega, ecc, a1 (with DR/DTH variants)."""
    orbits, pbprime = orbits_fn(pv, tt0)
    M = mean_anomaly(orbits)
    e = ecc_at(pv, tt0)
    E = solve_kepler(M, e)
    sinE, cosE = jnp.sin(E), jnp.cos(E)
    # true anomaly (DD eq [13])
    nu = 2.0 * jnp.arctan2(jnp.sqrt(1.0 + e) * jnp.sin(E / 2.0),
                           jnp.sqrt(1.0 - e) * jnp.cos(E / 2.0))
    # periastron advance: omega = OM + k*nu, k = OMDOT/n  (DD eq [25])
    if k_override is None:
        k = pv.get("OMDOT", 0.0) * DEG / SEC_PER_YEAR / (TWO_PI / pbprime)
    else:
        k = k_override
    # continuous true anomaly: nu + 2pi*orbits matches the reference's
    # accumulated omega evolution over many orbits
    nu_cont = nu + TWO_PI * jnp.floor(orbits) + jnp.where(nu < 0, TWO_PI, 0.0)
    omega = pv.get("OM", 0.0) * DEG + k * nu_cont
    return dict(orbits=orbits, pbprime=pbprime, M=M, e=e, E=E, sinE=sinE,
                cosE=cosE, nu=nu, omega=omega)


def dd_delay_core(st, a1, e, gamma, sini, m2_tsun, dr=0.0, dth=0.0,
                  a0=0.0, b0=0.0, shapiro_fn=None):
    """DD delay from a prepared state: inverse-timing Roemer+Einstein (eq
    [46-52]), Shapiro (eq [26]), aberration (eq [27])."""
    sinE, cosE = st["sinE"], st["cosE"]
    er = e * (1.0 + dr)
    eth = e * (1.0 + dth)
    sin_om, cos_om = jnp.sin(st["omega"]), jnp.cos(st["omega"])
    alpha = a1 * sin_om
    beta = a1 * jnp.sqrt(1.0 - eth * eth) * cos_om
    Dre = alpha * (cosE - er) + beta * sinE + gamma * sinE
    Drep = -alpha * sinE + (beta + gamma) * cosE
    Drepp = -alpha * cosE - (beta + gamma) * sinE
    nhat = TWO_PI / st["pbprime"] / (1.0 - e * cosE)
    delayI = Dre * (1.0 - nhat * Drep + (nhat * Drep) ** 2
                    + 0.5 * nhat**2 * Dre * Drepp
                    - 0.5 * e * sinE / (1.0 - e * cosE) * nhat**2 * Dre * Drep)
    if shapiro_fn is not None:
        delayS = shapiro_fn(st, sin_om, cos_om)
    else:
        brace = (1.0 - e * cosE
                 - sini * (sin_om * (cosE - e)
                           + jnp.sqrt(1.0 - e * e) * cos_om * sinE))
        delayS = -2.0 * m2_tsun * jnp.log(brace)
    # aberration (A0/B0)
    om_plus_nu = st["omega"] + st["nu"]
    delayA = (a0 * (jnp.sin(om_plus_nu) + e * sin_om)
              + b0 * (jnp.cos(om_plus_nu) + e * cos_om))
    return delayI + delayS + delayA


def dd_delay(pv, tt0, orbits_fn=orbits_pb):
    """Plain DD: SINI/M2 Shapiro, DR/DTH deformations (reference
    ``DD_model.py:854``)."""
    st = dd_state(pv, tt0, orbits_fn)
    return dd_delay_core(
        st, a1_at(pv, tt0), st["e"], pv.get("GAMMA", 0.0),
        pv.get("SINI", 0.0), pv.get("M2", 0.0) * TSUN,
        dr=pv.get("DR", 0.0), dth=pv.get("DTH", 0.0),
        a0=pv.get("A0", 0.0), b0=pv.get("B0", 0.0))


def dds_delay(pv, tt0, orbits_fn=orbits_pb):
    """DDS: SHAPMAX = -log(1 - sini) parameterization (reference
    ``DDS_model.py:61``)."""
    sini = 1.0 - jnp.exp(-pv.get("SHAPMAX", 0.0))
    st = dd_state(pv, tt0, orbits_fn)
    return dd_delay_core(
        st, a1_at(pv, tt0), st["e"], pv.get("GAMMA", 0.0),
        sini, pv.get("M2", 0.0) * TSUN,
        dr=pv.get("DR", 0.0), dth=pv.get("DTH", 0.0),
        a0=pv.get("A0", 0.0), b0=pv.get("B0", 0.0))


def ddh_delay(pv, tt0, orbits_fn=orbits_pb):
    """DDH: orthometric H3/STIGMA Shapiro parameters (Freire & Wex 2010
    eq 20, 22; reference ``DDH_model.py``): sini = 2 stig/(1+stig^2),
    m2 = H3/(Tsun stig^3)."""
    stig = pv.get("STIGMA", 0.0)
    h3 = pv.get("H3", 0.0)
    sini = 2.0 * stig / (1.0 + stig * stig)
    m2_tsun = h3 / jnp.maximum(stig, 1e-30) ** 3
    st = dd_state(pv, tt0, orbits_fn)
    return dd_delay_core(
        st, a1_at(pv, tt0), st["e"], pv.get("GAMMA", 0.0), sini, m2_tsun,
        dr=pv.get("DR", 0.0), dth=pv.get("DTH", 0.0),
        a0=pv.get("A0", 0.0), b0=pv.get("B0", 0.0))


def _ddgr_arr(mtot_tsun, m1_tsun, m2_tsun, n, niter: int = 20):
    """Relativistic semi-major-axis equation (Taylor & Weisberg 1989;
    reference ``DDGR_model.py:12 _solve_kepler``), fixed-point iterated.
    All masses in seconds (G M / c^3); returns (arr0, arr) in seconds."""
    arr0 = (mtot_tsun / n**2) ** (1.0 / 3.0)
    arr = arr0
    for _ in range(niter):
        arr = arr0 * (1.0 + (m1_tsun * m2_tsun / mtot_tsun**2 - 9.0)
                      * (mtot_tsun / (2.0 * arr))) ** (2.0 / 3.0)
    return arr0, arr


def ddgr_delay(pv, tt0, orbits_fn=orbits_pb):
    """DDGR: GR-constrained DD — SINI/GAMMA/k/DR/DTH/PBDOT derived from
    (MTOT, M2) (Taylor & Weisberg 1989 eq 15-25; reference
    ``DDGR_model.py:106 _updatePK``)."""
    mtot = pv.get("MTOT", 0.0) * TSUN
    m2 = pv.get("M2", 0.0) * TSUN
    m1 = mtot - m2
    pb_s = pv["PB"] * 86400.0
    n = TWO_PI / pb_s
    e0 = pv.get("ECC", 0.0)
    arr0, arr = _ddgr_arr(mtot, m1, m2, n)
    ar = arr * (m2 / mtot)
    sini = a1_at(pv, tt0) / ar
    gamma = e0 * m2 * (m1 + 2.0 * m2) / (n * arr0 * mtot)
    fe = (1.0 + (73.0 / 24.0) * e0**2 + (37.0 / 96.0) * e0**4) \
        * (1.0 - e0**2) ** (-3.5)
    pbdot_gr = (-192.0 * math.pi / 5.0) * n ** (5.0 / 3.0) \
        * m1 * m2 * mtot ** (-1.0 / 3.0) * fe
    k = 3.0 * mtot / (arr0 * (1.0 - e0**2)) \
        + pv.get("XOMDOT", 0.0) * DEG / SEC_PER_YEAR / n
    dr = (m1 * (3.0 * m1 + 6.0 * m2) + 2.0 * m2**2) / (mtot * arr)
    dth = (3.5 * m1**2 + 6.0 * m1 * m2 + 2.0 * m2**2) / (mtot * arr)
    pv2 = dict(pv)
    pv2["PBDOT"] = pv.get("PBDOT", 0.0) + pbdot_gr
    st = dd_state(pv2, tt0, orbits_fn, k_override=k)
    return dd_delay_core(st, a1_at(pv, tt0), st["e"], gamma, sini, m2,
                         dr=dr, dth=dth,
                         a0=pv.get("A0", 0.0), b0=pv.get("B0", 0.0))


def ddk_corrections(pv, tt0, psr_pos, obs_pos_ls):
    """Kopeikin annual-parallax + secular proper-motion corrections to
    (a1, omega, kin) (Kopeikin 1995 eq 15-19; 1996 eq 8-10; reference
    ``DDK_model.py``).  Returns (delta_a1, delta_omega [rad], kin [rad]).

    ``psr_pos``: (N,3) unit vector to the pulsar (same frame as obs_pos);
    ``obs_pos_ls``: (N,3) observatory position wrt SSB in light-seconds.
    """
    kom = pv.get("KOM", 0.0) * DEG
    kin0 = pv.get("KIN", 0.0) * DEG
    sin_kom, cos_kom = jnp.sin(kom), jnp.cos(kom)
    # sky-direction basis from the unit vector (Kopeikin 1995 eq 10)
    sin_lat = psr_pos[:, 2]
    cos_lat = jnp.sqrt(jnp.maximum(1.0 - sin_lat**2, 1e-30))
    sin_long = psr_pos[:, 1] / cos_lat
    cos_long = psr_pos[:, 0] / cos_lat
    delta_I0 = -obs_pos_ls[:, 0] * sin_long + obs_pos_ls[:, 1] * cos_long
    delta_J0 = (-obs_pos_ls[:, 0] * sin_lat * cos_long
                - obs_pos_ls[:, 1] * sin_lat * sin_long
                + obs_pos_ls[:, 2] * cos_lat)
    # proper motion [rad/s]: PMLONG = PMRA (or PMELONG), PMLAT = PMDEC
    mas_yr = DEG / 3600.0e3 / SEC_PER_YEAR
    pm_long = pv.get("PMRA", pv.get("PMELONG", 0.0)) * mas_yr
    pm_lat = pv.get("PMDEC", pv.get("PMELAT", 0.0)) * mas_yr
    k96 = pv.get("K96", 1.0)
    # Kopeikin 1996 eq 10: secular inclination change
    d_kin_pm = (-pm_long * sin_kom + pm_lat * cos_kom) * tt0 * k96
    kin = kin0 + d_kin_pm
    tan_kin = jnp.tan(kin)
    sin_kin = jnp.sin(kin)
    a1_0 = pv.get("A1", 0.0) + tt0 * pv.get("A1DOT", 0.0)
    # proper-motion corrections (Kopeikin 1996 eq 8, 9)
    d_a1_pm = a1_0 * d_kin_pm / tan_kin
    d_om_pm = (pm_long * cos_kom + pm_lat * sin_kom) / sin_kin * tt0 * k96
    # annual parallax corrections (Kopeikin 1995 eq 18, 19); distance from PX
    d_ls = KPC_LS / jnp.maximum(pv.get("PX", 1e-30), 1e-30)  # PX in mas
    kom_proj = delta_I0 * sin_kom - delta_J0 * cos_kom
    d_a1_px = (a1_0 + d_a1_pm * k96) / tan_kin / d_ls * kom_proj
    d_om_px = -(delta_I0 * cos_kom + delta_J0 * sin_kom) / sin_kin / d_ls
    return d_a1_pm * k96 + d_a1_px, d_om_pm * k96 + d_om_px, kin


def ddk_delay(pv, tt0, psr_pos, obs_pos_ls, orbits_fn=orbits_pb):
    """DDK: DD with Kopeikin corrections; inclination from KIN (reference
    ``DDK_model.py:141 SINI``)."""
    d_a1, d_om, kin = ddk_corrections(pv, tt0, psr_pos, obs_pos_ls)
    st = dd_state(pv, tt0, orbits_fn)
    st = dict(st)
    st["omega"] = st["omega"] + d_om
    return dd_delay_core(
        st, a1_at(pv, tt0) + d_a1, st["e"], pv.get("GAMMA", 0.0),
        jnp.sin(kin), pv.get("M2", 0.0) * TSUN,
        dr=pv.get("DR", 0.0), dth=pv.get("DTH", 0.0),
        a0=pv.get("A0", 0.0), b0=pv.get("B0", 0.0))


# ----------------------------------------------------------------------
# ELL1 family (Lange et al. 2001)
# ----------------------------------------------------------------------
def ell1_eps(pv, ttasc, ell1k: bool = False):
    """(eps1, eps2) at each epoch: linear EPS1DOT/EPS2DOT evolution
    (reference ``ELL1_model.py:72``), or the ELL1k exponential/rotating
    form when ``ell1k`` (``ELL1k_model.py:48``, Susobhanan+ 2018 eq 15).
    ``ell1k`` is a static (trace-time) flag."""
    if ell1k:
        omdot = pv.get("OMDOT", 0.0) * DEG / SEC_PER_YEAR
        lnedot = pv.get("LNEDOT", 0.0) / SEC_PER_YEAR
        scale = 1.0 + lnedot * ttasc
        c, s = jnp.cos(omdot * ttasc), jnp.sin(omdot * ttasc)
        eps1 = scale * (pv.get("EPS1", 0.0) * c + pv.get("EPS2", 0.0) * s)
        eps2 = scale * (pv.get("EPS2", 0.0) * c - pv.get("EPS1", 0.0) * s)
        return eps1, eps2
    eps1 = pv.get("EPS1", 0.0) + ttasc * pv.get("EPS1DOT", 0.0)
    eps2 = pv.get("EPS2", 0.0) + ttasc * pv.get("EPS2DOT", 0.0)
    return eps1, eps2


def ell1_roemer_terms(phi, eps1, eps2, first_order_dre: bool = False):
    """(Dre, Drep, Drepp)/a1: the third-order-in-e expansion of the ELL1
    Roemer delay and its Phi-derivatives (Zhu et al. 2019 eq 1 /
    Fiore et al. 2023 eq 4; reference ``ELL1_model.py:223,257,288``).

    ``first_order_dre`` (static flag): replace Dre with the first-order
    Susobhanan+ 2018 eq 6 form carrying an extra -3/2 eps1 constant term —
    the ELL1k convention (reference ``ELL1k_model.py:120 delayR``, which
    overrides only Dre and inherits the third-order Drep/Drepp).
    """
    s1, c1 = jnp.sin(phi), jnp.cos(phi)
    s2, c2 = jnp.sin(2 * phi), jnp.cos(2 * phi)
    s3, c3 = jnp.sin(3 * phi), jnp.cos(3 * phi)
    s4, c4 = jnp.sin(4 * phi), jnp.cos(4 * phi)
    e1, e2 = eps1, eps2
    if first_order_dre:
        dre = s1 + 0.5 * (e2 * s2 - e1 * (c2 + 3.0))
    else:
        dre = (s1 + 0.5 * (e2 * s2 - e1 * c2)
               - (1.0 / 8.0) * (5 * e2**2 * s1 - 3 * e2**2 * s3
                                - 2 * e2 * e1 * c1 + 6 * e2 * e1 * c3
                                + 3 * e1**2 * s1 + 3 * e1**2 * s3)
               - (1.0 / 12.0) * (5 * e2**3 * s2 + 3 * e1**2 * e2 * s2
                                 - 6 * e1 * e2**2 * c2 - 4 * e1**3 * c2
                                 - 4 * e2**3 * s4 + 12 * e1**2 * e2 * s4
                                 + 12 * e1 * e2**2 * c4 - 4 * e1**3 * c4))
    drep = (c1 + e1 * s2 + e2 * c2
            - (1.0 / 8.0) * (5 * e2**2 * c1 - 9 * e2**2 * c3
                             + 2 * e1 * e2 * s1 - 18 * e1 * e2 * s3
                             + 3 * e1**2 * c1 + 9 * e1**2 * c3)
            - (1.0 / 12.0) * (10 * e2**3 * c2 + 6 * e1**2 * e2 * c2
                              + 12 * e1 * e2**2 * s2 + 8 * e1**3 * s2
                              - 16 * e2**3 * c4 + 48 * e1**2 * e2 * c4
                              - 48 * e1 * e2**2 * s4 + 16 * e1**3 * s4))
    drepp = (-s1 + 2 * e1 * c2 - 2 * e2 * s2
             - (1.0 / 8.0) * (-5 * e2**2 * s1 + 27 * e2**2 * s3
                              + 2 * e1 * e2 * c1 - 54 * e1 * e2 * c3
                              - 3 * e1**2 * s1 - 27 * e1**2 * s3)
             - (1.0 / 12.0) * (-20 * e2**3 * s2 - 12 * e1**2 * e2 * s2
                               + 24 * e1 * e2**2 * c2 + 16 * e1**3 * c2
                               + 64 * e2**3 * s4 - 192 * e1**2 * e2 * s4
                               - 192 * e1 * e2**2 * c4 + 64 * e1**3 * c4))
    return dre, drep, drepp


def ell1_inverse_delay(pv, ttasc, orbits_fn=orbits_pb, ell1k: bool = False):
    """Inverse-timing Roemer part shared by the ELL1 family (reference
    ``ELL1_model.py:143 delayI``).  Returns (delayI, phi, pbprime).

    ELL1k replaces Dre with the first-order Susobhanan+ 2018 eq 6 form,
    which carries an extra -3/2 eps1 constant term (reference
    ``ELL1k_model.py:120 delayR``) while keeping the third-order
    Drep/Drepp of the base model (not overridden there).
    """
    orbits, pbprime = orbits_fn(pv, ttasc)
    phi = mean_anomaly(orbits)
    eps1, eps2 = ell1_eps(pv, ttasc, ell1k=ell1k)
    a1 = a1_at(pv, ttasc)
    dre_u, drep_u, drepp_u = ell1_roemer_terms(phi, eps1, eps2,
                                               first_order_dre=ell1k)
    Dre, Drep, Drepp = a1 * dre_u, a1 * drep_u, a1 * drepp_u
    nhat = TWO_PI / pbprime
    delayI = Dre * (1.0 - nhat * Drep + (nhat * Drep) ** 2
                    + 0.5 * nhat**2 * Dre * Drepp)
    return delayI, phi, pbprime


def ell1_delay(pv, ttasc, orbits_fn=orbits_pb, ell1k: bool = False):
    """ELL1: M2/SINI Shapiro (Lange et al. 2001 eq A16; reference
    ``ELL1_model.py:585``)."""
    delayI, phi, _ = ell1_inverse_delay(pv, ttasc, orbits_fn, ell1k=ell1k)
    m2 = pv.get("M2", 0.0) * TSUN
    sini = pv.get("SINI", 0.0)
    delayS = -2.0 * m2 * jnp.log(1.0 - sini * jnp.sin(phi))
    return delayI + delayS


def ell1k_delay(pv, ttasc, orbits_fn=orbits_pb):
    """ELL1k: ELL1 with exponential eccentricity evolution + periastron
    advance (Susobhanan et al. 2018; reference ``ELL1k_model.py``)."""
    return ell1_delay(pv, ttasc, orbits_fn, ell1k=True)


def _h3_fourier_harms(phi, stigma, nharms):
    """Sum of Shapiro-delay Fourier harmonics k=3..nharms with stigma^3
    factored out (Freire & Wex 2010 eq 10, 13; reference
    ``ELL1H_model.py fourier_component``).

    Harmonic k contributes (-1)^pwr * (2/k) * stigma^(k-3) * trig(k phi)
    with (pwr, trig) = ((k+1)/2, sin) for odd k and ((k+2)/2, cos) for even
    k (reference ``_ELL1H_fourier_basis``).
    """
    total = 0.0
    for k in range(3, int(nharms) + 1):
        pwr = (k + 1) // 2 if k % 2 == 1 else (k + 2) // 2
        coeff = ((-1.0) ** pwr) * 2.0 / k * stigma ** (k - 3)
        basis = jnp.sin(k * phi) if k % 2 == 1 else jnp.cos(k * phi)
        total = total + coeff * basis
    return total


def ell1h_delay(pv, ttasc, orbits_fn=orbits_pb, nharms: int = 7,
                exact: bool = False, use_h4: bool = False):
    """ELL1H: orthometric H3/STIGMA (or H3/H4 when ``use_h4``, a static
    flag) Shapiro delay using only the measurable 3rd-and-higher harmonics
    (Freire & Wex 2010 eq 19/28; reference ``ELL1H_model.py``)."""
    delayI, phi, _ = ell1_inverse_delay(pv, ttasc, orbits_fn)
    h3 = pv.get("H3", 0.0)
    if use_h4:
        # H3 == 0 means no measurable Shapiro signal: stigma -> 0 (the
        # reference zeroes the delay rather than dividing by zero)
        stigma = jnp.where(h3 == 0.0, 0.0,
                           pv["H4"] / jnp.where(h3 == 0.0, 1.0, h3))
    else:
        stigma = pv.get("STIGMA", 0.0)
    if exact:
        lognum = 1.0 + stigma**2 - 2.0 * stigma * jnp.sin(phi)
        delayS = (-2.0 * h3 / stigma**3
                  * (jnp.log(lognum) + 2 * stigma * jnp.sin(phi)
                     - stigma**2 * jnp.cos(2 * phi)))
    else:
        delayS = -2.0 * h3 * _h3_fourier_harms(phi, stigma, nharms)
    return delayI + delayS
