"""Chromatic (nu^-alpha) delays: ChromaticCM Taylor series + CMX piecewise.

Reference ``chromatic_model.py:30,118,313``: delay = CM(t) * DMconst *
(f/1 MHz)^(-TNCHROMIDX) with CM a Taylor series in years about CMEPOCH,
plus piecewise CMX_XXXX offsets in [CMXR1, CMXR2] ranges.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from pint_tpu import DMconst
from pint_tpu.exceptions import MissingParameter
from pint_tpu.models.parameter import MJDParameter, floatParameter, prefixParameter
from pint_tpu.models.timing_model import DelayComponent, check_contiguous_indices

__all__ = ["ChromaticCM", "ChromaticCMX"]

_DAY_PER_YEAR = 365.25


class Chromatic(DelayComponent):
    category = "chromatic_constant"

    def chromatic_time_delay(self, cm, alpha, freq):
        return cm * DMconst * jnp.power(freq, -alpha)


class ChromaticCM(Chromatic):
    """Reference ``chromatic_model.py:118``."""

    register = True

    def __init__(self):
        super().__init__()
        p = prefixParameter("CM0", units="pc/cm3", value=0.0,
                            description="Chromatic measure")
        self._params_dict.pop("CM0", None)
        p.name, p.prefix, p.index = "CM", "CM", 0
        self.add_param(p)
        self.add_param(prefixParameter("CM1", units="pc/cm3/yr", value=0.0,
                                       description="Chromatic measure derivative"))
        self.add_param(floatParameter("TNCHROMIDX", units="", value=4.0,
                                      description="Chromatic index alpha"))
        self.add_param(MJDParameter("CMEPOCH", description="Epoch of CM measurement"))
        self.num_cm_terms = 2

    def setup(self):
        idxs = [0] + sorted(int(n[2:]) for n in self.params
                            if n.startswith("CM") and n[2:].isdigit() and n != "CM")
        check_contiguous_indices(idxs, "ChromaticCM", "CM")
        self.num_cm_terms = len(idxs)

    def validate(self):
        higher = any((self._params_dict.get(f"CM{i}") is not None
                      and self._params_dict[f"CM{i}"].value)
                     for i in range(1, self.num_cm_terms))
        if higher and self.CMEPOCH.value is None:
            pep = getattr(self._parent, "PEPOCH", None)
            if pep is not None and pep.value is not None:
                self.CMEPOCH.value = pep.value
            else:
                raise MissingParameter("ChromaticCM", "CMEPOCH")

    def base_cm(self, pv, batch):
        terms = [pv.get("CM", 0.0)] + [pv.get(f"CM{i}", 0.0)
                                       for i in range(1, self.num_cm_terms)]
        if len(terms) == 1:
            return terms[0] * jnp.ones_like(batch.freq)
        if self.CMEPOCH.value is not None and "CMEPOCH" in pv:
            ep = pv["CMEPOCH"]
            ep = ep.to_float() if hasattr(ep, "to_float") else ep
        else:
            ep = batch.tdb0
        dt_yr = (batch.tdb.hi - ep) / _DAY_PER_YEAR
        acc = jnp.zeros_like(dt_yr)
        for i in range(len(terms) - 1, -1, -1):
            acc = acc * dt_yr + terms[i] / math.factorial(i)
        return acc

    def delay_func(self, pv, batch, ctx, acc_delay):
        freq = self.barycentric_freq(pv, batch)
        return self.chromatic_time_delay(self.base_cm(pv, batch),
                                         pv.get("TNCHROMIDX", 4.0), freq)


class ChromaticCMX(Chromatic):
    """Piecewise chromatic offsets (reference ``chromatic_model.py:313``)."""

    register = True
    category = "chromatic_cmx"

    def __init__(self):
        super().__init__()
        self.add_param(prefixParameter("CMX_0001", units="pc/cm3", value=0.0,
                                       description="CM offset in range"))
        self.add_param(prefixParameter("CMXR1_0001", units="MJD",
                                       description="Range start MJD"))
        self.add_param(prefixParameter("CMXR2_0001", units="MJD",
                                       description="Range end MJD"))
        self.cmx_indices = [1]

    def setup(self):
        self.cmx_indices = sorted(int(n[4:]) for n in self.params
                                  if n.startswith("CMX_"))

    def validate(self):
        for i in self.cmx_indices:
            for pre in ("CMXR1_", "CMXR2_"):
                nm = f"{pre}{i:04d}"
                if nm not in self._params_dict or self._params_dict[nm].value is None:
                    raise MissingParameter("ChromaticCMX", nm)

    def build_context(self, toas):
        mjds = np.asarray(toas.get_mjds(), dtype=np.float64)
        masks = []
        for i in self.cmx_indices:
            r1 = float(self._params_dict[f"CMXR1_{i:04d}"].value)
            r2 = float(self._params_dict[f"CMXR2_{i:04d}"].value)
            masks.append(((mjds >= r1) & (mjds <= r2)).astype(np.float64))
        return {"masks": jnp.asarray(np.array(masks)) if masks else None}

    def delay_func(self, pv, batch, ctx, acc_delay):
        if ctx.get("masks") is None:
            return jnp.zeros(batch.ntoas)
        vals = jnp.stack([pv.get(f"CMX_{i:04d}", 0.0) for i in self.cmx_indices])
        cm = jnp.sum(vals[:, None] * ctx["masks"], axis=0)
        freq = self.barycentric_freq(pv, batch)
        return self.chromatic_time_delay(cm, pv.get("TNCHROMIDX", 4.0), freq)
