"""Parameter priors for Bayesian inference.

Counterpart of reference ``models/priors.py:14 Prior`` (a thin wrapper over
scipy ``rv_continuous``/``rv_frozen``) with the same surface: ``pdf``,
``logpdf``, ``ppf``, ``rvs``.  Adds jax-evaluable fast paths for the two
distributions the samplers vectorize over (uniform, normal), so a batched
lnprior can run inside jit.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = [
    "Prior",
    "UniformUnboundedRV",
    "UniformBoundedRV",
    "GaussianBoundedRV",
    "GaussianRV_gen",
    "RandomInclinationPrior",
]


class UniformUnboundedRV:
    """Improper flat prior over the whole real line
    (reference ``priors.py:119`` region)."""

    kind = "uniform_unbounded"

    def pdf(self, x):
        return np.ones_like(np.asarray(x, dtype=float))

    def logpdf(self, x):
        return np.zeros_like(np.asarray(x, dtype=float))

    def ppf(self, q):
        raise NotImplementedError("Unbounded uniform prior has no ppf")

    def rvs(self, size=None, random_state=None):
        raise NotImplementedError("Cannot sample an unbounded uniform prior")


def UniformBoundedRV(lower_bound: float, upper_bound: float):
    """Frozen scipy uniform on [lower, upper] (reference parity helper)."""
    from scipy.stats import uniform

    return uniform(lower_bound, upper_bound - lower_bound)


def GaussianBoundedRV(loc: float = 0.0, scale: float = 1.0,
                      lower_bound: float = -np.inf, upper_bound: float = np.inf):
    """Frozen scipy truncated normal (reference ``GaussianRV_gen``)."""
    from scipy.stats import truncnorm

    a = (lower_bound - loc) / scale
    b = (upper_bound - loc) / scale
    return truncnorm(a, b, loc=loc, scale=scale)


def GaussianRV_gen(loc: float = 0.0, scale: float = 1.0):
    """Frozen scipy normal under the reference's spelling
    (``priors.py:119 GaussianRV_gen``); the bounded variant is
    :func:`GaussianBoundedRV`."""
    from scipy.stats import norm

    return norm(loc=loc, scale=scale)


class Prior:
    """Prior distribution attached to a Parameter (reference ``priors.py:14``).

    Wraps any scipy frozen distribution (or :class:`UniformUnboundedRV`).
    ``jax_spec`` returns ("uniform", lo, hi) / ("normal", mu, sigma) / None,
    letting the ensemble sampler evaluate simple priors inside jit.
    """

    def __init__(self, rv):
        self._rv = rv

    def pdf(self, value):
        return self._rv.pdf(value)

    def logpdf(self, value):
        return self._rv.logpdf(value)

    def ppf(self, q):
        return self._rv.ppf(q)

    def rvs(self, size=None, random_state=None):
        return self._rv.rvs(size=size, random_state=random_state)

    @property
    def is_unbounded(self) -> bool:
        return isinstance(self._rv, UniformUnboundedRV)

    def jax_spec(self) -> Optional[tuple]:
        """("uniform", lo, hi) or ("normal", mu, sigma) when the wrapped rv
        is one of the two vectorizable families, else None."""
        rv = self._rv
        name = getattr(getattr(rv, "dist", None), "name", None)
        if name == "uniform":
            lo = float(rv.ppf(0.0))
            hi = float(rv.ppf(1.0))
            return ("uniform", lo, hi)
        if name == "norm":
            return ("normal", float(rv.mean()), float(rv.std()))
        return None

    def __repr__(self):
        return f"Prior({self._rv!r})"


#: reference-spelled alias (``priors.py:119 GaussianRV_gen``)
GaussianRV_gen = GaussianBoundedRV


class RandomInclinationPrior:
    """pdf of sin(i) under an isotropic (uniform-in-cos-i) inclination
    prior: p(x) = x / sqrt(1 - x^2) on [0, 1) (reference ``priors.py:73``).
    Wrap in :class:`Prior` and attach to SINI."""

    a, b = 0.0, 1.0

    def pdf(self, v):
        v = np.asarray(v, dtype=np.float64)
        ok = (v >= 0) & (v < 1)
        with np.errstate(divide="ignore", invalid="ignore"):
            return np.where(ok, v / np.sqrt(1.0 - np.where(ok, v, 0.0) ** 2),
                            0.0)

    def logpdf(self, v):
        with np.errstate(divide="ignore", invalid="ignore"):
            return np.log(self.pdf(v))

    def ppf(self, q):
        # CDF = 1 - sqrt(1 - v^2)  =>  v = sqrt(1 - (1-q)^2)
        q = np.asarray(q, dtype=np.float64)
        return np.sqrt(1.0 - (1.0 - q) ** 2)

    def rvs(self, size=None, random_state=None):
        if isinstance(random_state, np.random.RandomState):
            # legacy-RandomState parity with the scipy-frozen priors
            return self.ppf(random_state.random_sample(size))
        return self.ppf(np.random.default_rng(random_state).random(size))
