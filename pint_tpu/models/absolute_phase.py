"""Absolute phase reference (TZR): TZRMJD/TZRSITE/TZRFRQ.

Reference ``absolute_phase.py:12``: the model phase is referenced to the
pulse arriving at TZRSITE at TZRMJD observed at TZRFRQ; ``TimingModel.phase``
with ``abs_phase=True`` subtracts the phase of that single reference TOA.
The TZR TOA is built once on the host (``make_TZR_toa`` parity,
``absolute_phase.py:130``) and cached.
"""

from __future__ import annotations

import numpy as np

from pint_tpu.exceptions import MissingParameter
from pint_tpu.models.parameter import MJDParameter, floatParameter, strParameter
from pint_tpu.models.timing_model import Component

__all__ = ["AbsPhase"]


class AbsPhase(Component):
    register = True
    category = "absolute_phase"
    kind = "tzr"

    def __init__(self):
        super().__init__()
        self.add_param(MJDParameter("TZRMJD", description="Epoch of the zero phase TOA"))
        self.add_param(strParameter("TZRSITE", description="Observatory of the zero phase TOA"))
        self.add_param(floatParameter("TZRFRQ", units="MHz",
                                      description="Frequency of the zero phase TOA"))
        self._tzr_toas = None

    def validate(self):
        if self.TZRMJD.value is None:
            raise MissingParameter("AbsPhase", "TZRMJD")

    def get_TZR_toas(self, model):
        """One-TOA TOAs at the TZR epoch (cached)."""
        if self._tzr_toas is not None:
            return self._tzr_toas
        from pint_tpu.toa import make_single_toa

        site = self.TZRSITE.value or "ssb"
        freq = self.TZRFRQ.value if self.TZRFRQ.value else np.inf
        ephem = None
        if model is not None and getattr(model, "EPHEM", None) is not None:
            ephem = model.EPHEM.value
        planets = bool(getattr(model, "PLANET_SHAPIRO", None)
                       and model.PLANET_SHAPIRO.value)
        self._tzr_toas = make_single_toa(
            np.longdouble(self.TZRMJD.value), site, freq_mhz=freq,
            ephem=ephem or "DE440", planets=planets,
        )
        return self._tzr_toas

    #: reference spelling (``absolute_phase.py:80``)
    get_TZR_toa = get_TZR_toas

    def make_TZR_toa(self, toas):
        """Fill TZRMJD/TZRSITE/TZRFRQ from the given TOAs when unset
        (reference ``absolute_phase.py:130``)."""
        import numpy as np

        if self.TZRMJD.value is None:
            self.TZRMJD.value = float(np.asarray(toas.get_mjds())[0])
        if not self.TZRSITE.value:
            self.TZRSITE.value = str(toas.obs[0])
        if self.TZRFRQ.value is None:
            self.TZRFRQ.value = float(toas.freq_mhz[0])
        self._tzr_toas = None

    def clear_cache(self):
        self._tzr_toas = None
