"""Flow training: a host-side Adam driver around one jitted step.

The whole optimization is ONE jitted ``value_and_grad`` step (loss +
Adam moment update + parameter update fused into a single executable)
driven by a host loop that owns the PRNG chain, telemetry, and
checkpointing:

* **determinism** — the base-sample key chain derives from
  ``TrainConfig.seed`` alone (``jax.random.split`` per step), so a
  fixed seed reproduces the ELBO trace bitwise on the same backend
  (pinned by tests);
* **checkpoint/resume** — steps are grouped into chunks persisted
  through :class:`~pint_tpu.runtime.checkpoint.SweepCheckpoint`
  (atomic writes, fingerprint-guarded): a crashed run resumes from
  the last completed chunk and — because the PRNG state rides in the
  chunk — continues bit-identically to an uninterrupted run;
* **sharding** — the MC sample axis is walker-shaped data
  parallelism: under a ``walker`` execution plan
  (``plan="auto"`` routes through
  :func:`~pint_tpu.runtime.plan.select_plan`) each step's base batch
  is placed over the mesh's first axis and the jitted step runs SPMD;
* **telemetry** — a ``flow_train`` event (step, elbo, lr) every
  ``log_every`` steps, validated by ``tools/telemetry_report
  --check``.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, List, Optional

import numpy as np

from pint_tpu.amortized.elbo import AmortizedVI
from pint_tpu.exceptions import UsageError
from pint_tpu.logging import log

__all__ = ["TrainConfig", "TrainResult", "train_flow"]


@dataclass(frozen=True)
class TrainConfig:
    """Adam schedule + sample budget for one training run."""

    steps: int = 300
    n_samples: int = 64        #: MC samples per ELBO estimate
    lr: float = 1e-2
    beta1: float = 0.9
    beta2: float = 0.999
    eps: float = 1e-8
    seed: int = 0
    #: steps per persisted checkpoint chunk
    checkpoint_chunk: int = 50
    #: flow_train telemetry cadence (steps)
    log_every: int = 25

    def __post_init__(self):
        if self.steps < 1:
            raise UsageError(f"steps must be >= 1, got {self.steps}")
        if self.n_samples < 1:
            raise UsageError(
                f"n_samples must be >= 1, got {self.n_samples}")
        if self.lr <= 0:
            raise UsageError(f"lr must be > 0, got {self.lr}")
        if self.checkpoint_chunk < 1:
            raise UsageError(f"checkpoint_chunk must be >= 1, got "
                             f"{self.checkpoint_chunk}")

    def to_dict(self) -> dict:
        return {"steps": self.steps, "n_samples": self.n_samples,
                "lr": self.lr, "beta1": self.beta1, "beta2": self.beta2,
                "eps": self.eps, "seed": self.seed,
                "checkpoint_chunk": self.checkpoint_chunk}


@dataclass
class TrainResult:
    """Outcome of one (possibly resumed) training run."""

    params: Any                      #: trained flow parameter pytree
    elbo_trace: np.ndarray           #: (steps,) per-step ELBO estimates
    steps: int
    resumed_steps: int = 0           #: steps replayed from a checkpoint
    config: Optional[TrainConfig] = None

    @property
    def elbo_final(self) -> float:
        return float(self.elbo_trace[-1])


def _adam_step_fn(vi: AmortizedVI, cfg: TrainConfig):
    """Build the ONE jitted training step: ``(params, m, v, t, z) ->
    (params, m, v, t, elbo)`` — loss, gradient, and the Adam update
    fused into a single executable."""
    import jax
    import jax.numpy as jnp

    elbo = vi.elbo_fn()
    b1, b2, lr, eps = cfg.beta1, cfg.beta2, cfg.lr, cfg.eps

    def step(params, m, v, t, z):
        loss, g = jax.value_and_grad(
            lambda p: -elbo(p, z))(params)
        t = t + 1
        m = jax.tree_util.tree_map(
            lambda mi, gi: b1 * mi + (1.0 - b1) * gi, m, g)
        v = jax.tree_util.tree_map(
            lambda vi_, gi: b2 * vi_ + (1.0 - b2) * gi * gi, v, g)
        c1 = 1.0 - b1 ** t
        c2 = 1.0 - b2 ** t
        params = jax.tree_util.tree_map(
            lambda p, mi, vi_: p - lr * (mi / c1)
            / (jnp.sqrt(vi_ / c2) + eps), params, m, v)
        return params, m, v, t, -loss

    return jax.jit(step)


def _resolve_plan(plan):
    if plan is None:
        return None
    if isinstance(plan, str):
        if plan != "auto":
            raise UsageError(f"plan={plan!r}: pass 'auto' or an "
                             "ExecutionPlan")
        from pint_tpu.runtime.plan import select_plan

        return select_plan("walker")
    return plan


def _emit_train_event(step: int, elbo: float, lr: float) -> None:
    from pint_tpu import config as _config

    if _config._telemetry_mode == "off":
        return
    if not math.isfinite(elbo):
        # the flow_train contract requires a finite numeric ELBO (the
        # strict-JSON runlog would stringify a nan/inf and --check
        # would then reject the record); divergence is already a loud
        # host-side warning, not an event
        return
    from pint_tpu import telemetry

    telemetry.lifecycle_event("flow_train", step=int(step),
                              elbo=float(elbo), lr=float(lr))


def _state_arrays(params, m, v, t, key, elbos: List[float]) -> dict:
    """Flatten the training state into the named numpy arrays one
    checkpoint chunk persists (leaf order is the pytree flatten order,
    stable for a fixed flow architecture)."""
    import jax

    out = {"t": np.asarray(int(t)), "key": np.asarray(key),
           "elbos": np.asarray(elbos, dtype=np.float64)}
    for tag, tree in (("p", params), ("m", m), ("v", v)):
        for i, leaf in enumerate(jax.tree_util.tree_leaves(tree)):
            out[f"{tag}_{i:03d}"] = np.asarray(leaf)
    return out


def _state_from_arrays(d: dict, treedef) -> tuple:
    import jax

    def leaves(tag):
        keys = sorted(k for k in d if k.startswith(f"{tag}_"))
        return [d[k] for k in keys]

    params = jax.tree_util.tree_unflatten(treedef, leaves("p"))
    m = jax.tree_util.tree_unflatten(treedef, leaves("m"))
    v = jax.tree_util.tree_unflatten(treedef, leaves("v"))
    return params, m, v, int(d["t"]), d["key"], list(d["elbos"])


def train_flow(vi: AmortizedVI, cfg: Optional[TrainConfig] = None,
               checkpoint: Optional[str] = None,
               plan=None) -> TrainResult:
    """Train ``vi``'s flow by maximizing the reparameterized ELBO.

    ``checkpoint`` names a directory: completed chunks
    (``cfg.checkpoint_chunk`` steps each) persist there and a crashed
    run resumes bit-identically (the chunk carries the PRNG state).
    The checkpoint fingerprint binds the flow architecture, the
    training schedule, and the posterior's vkey — resuming a different
    problem raises :class:`~pint_tpu.exceptions.CheckpointError`
    instead of silently mixing optimizations.

    ``plan`` (``"auto"`` or a ``walker``
    :class:`~pint_tpu.runtime.plan.ExecutionPlan`) shards each step's
    base-sample batch over the mesh's first axis; the sample count is
    padded up to a shard multiple once, at entry."""
    import jax

    cfg = cfg or TrainConfig()
    plan = _resolve_plan(plan)
    n = cfg.n_samples
    sharding = None
    if plan is not None and plan.mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        # the MC sample axis is walker-shaped: under a 2-axis
        # ('pulsar', 'walker') catalog plan the samples shard over
        # 'walker' (the data side owns 'pulsar')
        axis = "walker" if "walker" in plan.axes else plan.axes[0]
        shards = int(plan.mesh.shape[axis])
        n = n + ((-n) % shards)
        sharding = NamedSharding(plan.mesh, P(axis))
        if n != cfg.n_samples:
            log.info(f"train_flow: n_samples {cfg.n_samples} padded to "
                     f"{n} ({shards} shards)")

    step_fn = _adam_step_fn(vi, cfg)
    params = vi.flow.init()
    treedef = jax.tree_util.tree_structure(params)
    m = jax.tree_util.tree_map(np.zeros_like, params)
    v = jax.tree_util.tree_map(np.zeros_like, params)
    t = 0
    key = jax.random.PRNGKey(cfg.seed)
    elbos: List[float] = []

    ckpt = None
    nchunks = -(-cfg.steps // cfg.checkpoint_chunk)
    if checkpoint is not None:
        from pint_tpu.runtime.checkpoint import (SweepCheckpoint,
                                                 fingerprint_of)

        fp = fingerprint_of(flow=vi.flow.cfg.to_dict(),
                            specs=repr(vi.transform.specs),
                            labels=vi.param_labels,
                            train=cfg.to_dict(), n_padded=n,
                            vkey=repr(vi.vkey))
        ckpt = SweepCheckpoint(checkpoint, fp, nchunks,
                               sidecar={"what": "flow_train"})

    resumed = 0
    last_logged = -1
    for i in range(nchunks):
        lo = i * cfg.checkpoint_chunk
        hi = min(cfg.steps, lo + cfg.checkpoint_chunk)
        if ckpt is not None and ckpt.has(i):
            params, m, v, t, key, chunk_elbos = _state_from_arrays(
                ckpt.load(i), treedef)
            elbos.extend(chunk_elbos)
            resumed += hi - lo
            continue
        for step in range(lo, hi):
            key, sub = jax.random.split(key)
            z = jax.random.normal(sub, (n, vi.ndim), dtype=np.float64)
            if sharding is not None:
                z = jax.device_put(z, sharding)
            params, m, v, t, elbo = step_fn(params, m, v, t, z)
            elbos.append(float(elbo))
            if cfg.log_every and (step + 1) % cfg.log_every == 0:
                _emit_train_event(step + 1, elbos[-1], cfg.lr)
                last_logged = step + 1
        if ckpt is not None:
            ckpt.save(i, **_state_arrays(
                params, m, v, t, np.asarray(key),
                elbos[lo:hi]))
    if resumed:
        log.info(f"train_flow: resumed {resumed}/{cfg.steps} steps from "
                 f"{checkpoint}")
    trace = np.asarray(elbos, dtype=np.float64)
    if not np.isfinite(trace[-1]):
        log.warning(f"train_flow: final ELBO is {trace[-1]} — the flow "
                    "did not converge to a usable posterior")
    if last_logged != cfg.steps:
        _emit_train_event(cfg.steps, float(trace[-1]), cfg.lr)
    return TrainResult(params=params, elbo_trace=trace, steps=cfg.steps,
                       resumed_steps=resumed, config=cfg)
