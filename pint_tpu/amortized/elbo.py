"""Reparameterized ELBO over the repo's jitted posteriors.

:class:`AmortizedVI` bundles the three traced pieces one variational
fit needs — a :class:`~pint_tpu.amortized.flows.Flow`, its
:class:`~pint_tpu.amortized.flows.PriorTransform`, and a jax-traceable
batched lnposterior — and builds the scalar ELBO the training driver
differentiates:

    z ~ N(0, I)                       (reparameterized base samples)
    u, logdet = flow.forward(params, z)
    x, logjac = transform.constrain(u)
    log q(x)  = logN(z) - logdet - logjac
    ELBO      = E_z[ lnposterior(x) - log q(x) ]

The lnposterior comes from the ONE typed entry point the samplers
share (:meth:`pint_tpu.bayesian.BayesianTiming.batched_posterior` —
``value_and_grad`` flows through the compiled phase evaluation), or
from the catalog's cross-pulsar
:class:`~pint_tpu.catalog.likelihood.JointLikelihood` (the
``(log10_A, gamma)`` GW-background surface).  Because the transform
maps into the open prior support, every training sample has a finite
lnposterior and a finite gradient — the ``-inf`` prior boundary never
enters the expectation.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import numpy as np

from pint_tpu.amortized.flows import Flow, FlowConfig, PriorTransform
from pint_tpu.exceptions import UsageError

__all__ = ["AmortizedVI"]


class AmortizedVI:
    """One variational-inference problem: flow + prior transform +
    traced batched lnposterior.

    ``lnpost_batch`` must map a ``(N, ndim)`` jax array of parameter
    points to ``(N,)`` log-posteriors inside a trace.  ``specs`` are
    the per-parameter prior specs the transform aligns with
    (:meth:`~pint_tpu.models.priors.Prior.jax_spec` tuples).  ``vkey``
    is caller-supplied identity material for checkpoints and serve
    executables (the fitter constructors fill it with the established
    model-signature + TOA-version scheme)."""

    def __init__(self, lnpost_batch: Callable, specs: Sequence[tuple],
                 param_labels: Optional[Sequence[str]] = None,
                 flow: Optional[Flow] = None,
                 n_layers: int = 4, hidden: int = 32, seed: int = 0,
                 vkey: tuple = ()):
        if not callable(lnpost_batch):
            raise UsageError("lnpost_batch must be callable "
                             f"(got {type(lnpost_batch).__name__})")
        self.transform = PriorTransform(specs)
        ndim = self.transform.ndim
        if param_labels is None:
            param_labels = tuple(f"p{i}" for i in range(ndim))
        if len(param_labels) != ndim:
            raise UsageError(
                f"{len(param_labels)} labels for {ndim} prior specs")
        self.param_labels = tuple(str(p) for p in param_labels)
        self.lnpost_batch = lnpost_batch
        if flow is None:
            flow = Flow(FlowConfig(ndim=ndim, n_layers=n_layers,
                                   hidden=hidden, seed=seed))
        if flow.cfg.ndim != ndim:
            raise UsageError(
                f"flow ndim {flow.cfg.ndim} != {ndim} prior specs")
        self.flow = flow
        self.vkey = tuple(vkey)

    # -- constructors over the repo's posteriors ----------------------------

    @classmethod
    def from_bayesian(cls, bt, **flow_kw) -> "AmortizedVI":
        """From a :class:`~pint_tpu.bayesian.BayesianTiming` — the
        deduped :meth:`~pint_tpu.bayesian.BayesianTiming.
        batched_posterior` entry point supplies the traced fn, labels,
        and prior specs, and the vkey carries the model parameter/mask
        signature + TOA version (the grid-bundle invalidation
        discipline)."""
        from pint_tpu.grid import _model_param_sig

        bp = bt.batched_posterior()
        vkey = (_model_param_sig(bt.model),
                getattr(bt.toas, "_version", 0), len(bt.toas))
        return cls(bp.fn, bp.prior_specs, param_labels=bp.param_labels,
                   vkey=vkey, **flow_kw)

    @classmethod
    def from_fitter(cls, ftr, **flow_kw) -> "AmortizedVI":
        """From an :class:`~pint_tpu.mcmc_fitter.MCMCFitter` (or any
        fitter exposing ``batched_posterior`` through a BayesianTiming
        ``bt``)."""
        bt = getattr(ftr, "bt", None)
        if bt is None:
            raise UsageError(
                f"{type(ftr).__name__} has no BayesianTiming surface; "
                "build an MCMCFitter (or pass a BayesianTiming to "
                "from_bayesian)")
        return cls.from_bayesian(bt, **flow_kw)

    @classmethod
    def from_joint_likelihood(cls, jl,
                              log10_A_bounds: Tuple[float, float]
                              = (-18.0, -12.0),
                              gamma_bounds: Tuple[float, float]
                              = (0.0, 7.0),
                              **flow_kw) -> "AmortizedVI":
        """From the catalog's :class:`~pint_tpu.catalog.likelihood.
        JointLikelihood`: the 2-d ``(log10_A, gamma)`` GW-background
        posterior under uniform box priors.  The jitted joint kernel
        is traced with the padded per-pulsar data closed over, so the
        ELBO differentiates through exactly the executable the sampler
        dispatches."""
        specs = (("uniform", float(log10_A_bounds[0]),
                  float(log10_A_bounds[1])),
                 ("uniform", float(gamma_bounds[0]),
                  float(gamma_bounds[1])))
        fn = jl._fn()
        data = jl._data_args()
        widths = np.log(float(log10_A_bounds[1])
                        - float(log10_A_bounds[0])) \
            + np.log(float(gamma_bounds[1]) - float(gamma_bounds[0]))
        lnprior = -float(widths)

        def lnpost(points):
            return fn(points, *data) + lnprior

        return cls(lnpost, specs,
                   param_labels=("log10_A", "gamma"),
                   vkey=("joint_lnlike", jl.n_pulsars, jl.n_modes,
                         jl.pad_shape), **flow_kw)

    # -- the ELBO -----------------------------------------------------------

    @property
    def ndim(self) -> int:
        return self.transform.ndim

    def sample_and_logq(self, params, z):
        """``z (N, ndim)`` base samples -> ``(x, log_q)``: the flow
        samples in parameter space and their variational log-density
        (traceable; shared by the ELBO and the serve kernels so the
        two can never disagree on the density)."""
        u, logdet = self.flow.forward(params, z)
        x, logjac = self.transform.constrain(u)
        return x, self.flow.base_logpdf(z) - logdet - logjac

    def elbo_fn(self) -> Callable:
        """The traced scalar ELBO: ``(params, z) -> mean(lnpost(x) -
        log q(x))`` over the reparameterized base batch ``z``."""
        def elbo(params, z):
            import jax.numpy as jnp

            x, logq = self.sample_and_logq(params, z)
            return jnp.mean(self.lnpost_batch(x) - logq)

        return elbo
