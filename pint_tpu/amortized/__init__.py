"""Amortized inference engine: VI + normalizing flows as a warm
posterior endpoint (ROADMAP item 3; arXiv 2405.08857 is the method
retrieval, Vela.jl / arXiv 2412.15858 the noise-model surface).

Four pieces:

* :mod:`~pint_tpu.amortized.flows` — affine-coupling (RealNVP) layers
  with fixed seeded permutations in plain jnp, plus the
  :class:`~pint_tpu.amortized.flows.PriorTransform` that aligns the
  flow's base distribution with the prior families
  ``bayesian.py`` vectorizes (uniform -> sigmoid map into the support,
  normal -> affine), so every flow sample is in-support by
  construction and the ELBO never sees a ``-inf``;
* :mod:`~pint_tpu.amortized.elbo` — the reparameterized ELBO over any
  jax-traceable batched lnposterior: the deduped
  :meth:`~pint_tpu.bayesian.BayesianTiming.batched_posterior` entry
  point or the catalog's
  :class:`~pint_tpu.catalog.likelihood.JointLikelihood`;
* :mod:`~pint_tpu.amortized.train` — a host-side Adam driver around
  ONE jitted ``value_and_grad`` step, bitwise-deterministic for a
  fixed seed, checkpoint/resumable through
  :class:`~pint_tpu.runtime.checkpoint.SweepCheckpoint`, with the MC
  sample axis shardable under a ``walker`` execution plan;
* :mod:`~pint_tpu.amortized.posterior` — the trained flow as serve
  kernels: batched draw and log-prob executables registered in
  :class:`~pint_tpu.serving.warmup.WarmPool` /
  :class:`~pint_tpu.serving.aotcache.AOTCache` under the established
  vkey + device-fingerprint scheme, consumed by
  :class:`~pint_tpu.serving.service.TimingService`'s
  ``PosteriorRequest`` door.
"""

from pint_tpu.amortized.elbo import AmortizedVI
from pint_tpu.amortized.flows import Flow, FlowConfig, PriorTransform
from pint_tpu.amortized.posterior import AmortizedPosterior
from pint_tpu.amortized.train import TrainConfig, TrainResult, train_flow

__all__ = [
    "AmortizedVI",
    "AmortizedPosterior",
    "Flow",
    "FlowConfig",
    "PriorTransform",
    "TrainConfig",
    "TrainResult",
    "train_flow",
]
