"""Normalizing-flow layers in plain jnp: affine couplings + fixed
permutations, and the prior-aligned base transform.

The flow maps a standard-normal base through ``n_layers`` RealNVP
affine couplings (Dinh et al. 2017) into an *unconstrained* space
``u``, and a fixed :class:`PriorTransform` — built from the same
``("uniform", lo, hi)`` / ``("normal", mu, sigma)`` specs
``bayesian.py`` vectorizes priors into — carries ``u`` into the
parameter space: a sigmoid map into each uniform prior's support, an
affine map for each normal prior.  Two consequences the ELBO relies
on:

* every flow sample is strictly inside the prior support, so the
  lnposterior (and its gradient) is finite at every training sample —
  no ``-inf`` rejection branch exists to poison Adam;
* at the identity initialization (coupling nets zero-initialized) the
  variational distribution IS the prior-transformed standard normal,
  a sane starting point whatever the posterior.

Each coupling layer conditions on a fixed seeded index subset
(``perm[:d//2]``) and affinely transforms the complement — the fixed-
permutation mixing that lets d-dimensional structure reach every
coordinate after a few layers.  The coupling MLP matmuls route
through :func:`pint_tpu.precision.matmul` under the ``flow.coupling``
segment (f64 default; a reduced spec is the policy-driven bf16/f32
training path), and the log-scale outputs are tanh-clamped so a wild
training step cannot produce an overflowing ``exp``.

Everything here is traceable plain jnp + host-side configuration;
there is no framework dependency (no optax/flax — the container
ships neither).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass
from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

from pint_tpu.exceptions import UsageError

__all__ = ["FlowConfig", "PriorTransform", "Flow"]

_LOG_2PI = 1.8378770664093453  # log(2*pi)


@dataclass(frozen=True)
class FlowConfig:
    """Architecture of one flow: dimensionality, depth, width, and the
    seed the fixed permutations and initialization derive from.  The
    config (not the weights) is identity material:
    :meth:`digest` keys warm-pool/AOT executables and the on-disk
    manifest."""

    ndim: int
    n_layers: int = 4
    hidden: int = 32
    seed: int = 0
    #: log-scale clamp: coupling s outputs pass through
    #: ``s_cap * tanh(s / s_cap)`` so exp(s) stays bounded
    s_cap: float = 4.0

    def __post_init__(self):
        if self.ndim < 1:
            raise UsageError(f"FlowConfig.ndim must be >= 1, got "
                             f"{self.ndim}")
        if self.n_layers < 0:
            raise UsageError(f"FlowConfig.n_layers must be >= 0, got "
                             f"{self.n_layers}")
        if self.hidden < 1:
            raise UsageError(f"FlowConfig.hidden must be >= 1, got "
                             f"{self.hidden}")
        if self.s_cap <= 0:
            raise UsageError(f"FlowConfig.s_cap must be > 0, got "
                             f"{self.s_cap}")

    def to_dict(self) -> dict:
        return {"ndim": self.ndim, "n_layers": self.n_layers,
                "hidden": self.hidden, "seed": self.seed,
                "s_cap": self.s_cap}

    @classmethod
    def from_dict(cls, d: dict) -> "FlowConfig":
        try:
            return cls(ndim=int(d["ndim"]), n_layers=int(d["n_layers"]),
                       hidden=int(d["hidden"]), seed=int(d["seed"]),
                       s_cap=float(d["s_cap"]))
        except (KeyError, TypeError, ValueError) as e:
            raise UsageError(f"malformed FlowConfig dict: {e}") from e

    def digest(self) -> str:
        """Process-stable identity of the architecture."""
        return hashlib.sha256(json.dumps(
            self.to_dict(), sort_keys=True).encode()).hexdigest()[:16]


class PriorTransform:
    """The fixed output map aligning the flow with the prior families
    of :meth:`pint_tpu.models.priors.Prior.jax_spec`.

    Built from a sequence of ``("uniform", lo, hi)`` / ``("normal",
    mu, sigma)`` specs (one per parameter).  :meth:`constrain` maps an
    unconstrained point into parameter space (sigmoid into each
    uniform support, affine for normals) and returns the per-sample
    log-Jacobian ``log |dx/du|``; :meth:`unconstrain` is the exact
    inverse, returning ``log |du/dx|`` plus an in-support mask so a
    log-prob query outside a uniform prior's box reports ``-inf``
    instead of a clipped lie."""

    def __init__(self, specs: Sequence[tuple]):
        if not specs:
            raise UsageError("PriorTransform needs at least one prior "
                             "spec")
        is_uniform, a, b = [], [], []
        for i, spec in enumerate(specs):
            if spec is None or len(spec) != 3:
                raise UsageError(
                    f"prior spec {i} is {spec!r}; expected ('uniform', "
                    "lo, hi) or ('normal', mu, sigma) — only the "
                    "vectorizable families bayesian.py jits are "
                    "flow-compatible")
            kind, p, q = spec
            if kind == "uniform":
                if not float(q) > float(p):
                    raise UsageError(
                        f"prior spec {i}: uniform needs hi > lo, got "
                        f"({p}, {q})")
                is_uniform.append(True)
                a.append(float(p))
                b.append(float(q) - float(p))
            elif kind == "normal":
                if not float(q) > 0:
                    raise UsageError(
                        f"prior spec {i}: normal needs sigma > 0, got "
                        f"{q}")
                is_uniform.append(False)
                a.append(float(p))
                b.append(float(q))
            else:
                raise UsageError(
                    f"prior spec {i}: unknown family {kind!r} (known: "
                    "uniform, normal)")
        self.specs = tuple(tuple(s) for s in specs)
        self._is_uniform = np.asarray(is_uniform, dtype=bool)
        self._a = np.asarray(a, dtype=np.float64)
        self._b = np.asarray(b, dtype=np.float64)
        # clamp bounds in the ORIGINAL spec values: for a box narrow
        # relative to its center, fl(lo + width * sigmoid(u)) can
        # overshoot hi by an ulp — a clamp keeps the in-support-by-
        # construction invariant exact (normal dims are unclamped)
        self._lo = np.where(self._is_uniform, self._a, -np.inf)
        self._hi = np.where(self._is_uniform,
                            [float(s[2]) for s in self.specs], np.inf)

    @property
    def ndim(self) -> int:
        return len(self._a)

    def digest(self) -> str:
        """Process-stable identity of the transform: the traced
        constrain/unconstrain maps bake these bounds in as constants,
        so anything caching a compiled kernel must key on this."""
        return hashlib.sha256(repr(self.specs).encode()).hexdigest()[:16]

    def constrain(self, u):
        """``u (..., ndim)`` -> ``(x, log_jac)`` with ``log_jac`` the
        per-sample ``sum log |dx_i/du_i|`` (traceable)."""
        import jax
        import jax.numpy as jnp

        uni = jnp.asarray(self._is_uniform)
        a = jnp.asarray(self._a)
        b = jnp.asarray(self._b)
        su = jax.nn.sigmoid(u)
        x = jnp.where(uni, a + b * su, a + b * u)
        x = jnp.clip(x, jnp.asarray(self._lo), jnp.asarray(self._hi))
        lj = jnp.where(uni,
                       jnp.log(b) + jax.nn.log_sigmoid(u)
                       + jax.nn.log_sigmoid(-u),
                       jnp.log(b))
        return x, jnp.sum(lj, axis=-1)

    def unconstrain(self, x):
        """``x (..., ndim)`` -> ``(u, log_jac_inv, in_support)``:
        the inverse map, its per-sample ``sum log |du_i/dx_i|``, and a
        per-sample bool that is False when any uniform coordinate
        falls outside its support (where the density is exactly zero).
        The support check is boundary-INCLUSIVE: a flow draw whose
        sigmoid saturates in f64 lands exactly on the box edge, and
        reporting the flow's own draw as zero-density would be a
        rounding artifact, not a measurement — the edge evaluates at
        the clamp's finite (large) density instead."""
        import jax.numpy as jnp

        uni = jnp.asarray(self._is_uniform)
        a = jnp.asarray(self._a)
        b = jnp.asarray(self._b)
        p = (x - a) / b
        inb = jnp.all(jnp.where(uni, (p >= 0.0) & (p <= 1.0), True),
                      axis=-1)
        tiny = jnp.finfo(jnp.float64).tiny
        pc = jnp.clip(p, tiny, 1.0 - 1e-16)
        u = jnp.where(uni, jnp.log(pc) - jnp.log1p(-pc), p)
        lj = jnp.where(uni,
                       -jnp.log(b) - jnp.log(pc) - jnp.log1p(-pc),
                       -jnp.log(b))
        return u, jnp.sum(lj, axis=-1), inb

    def to_dict(self) -> dict:
        return {"specs": [list(s) for s in self.specs]}

    @classmethod
    def from_dict(cls, d: dict) -> "PriorTransform":
        try:
            return cls([tuple(s) for s in d["specs"]])
        except (KeyError, TypeError) as e:
            raise UsageError(f"malformed PriorTransform dict: {e}") from e


class Flow:
    """A RealNVP flow: parameters are a plain dict pytree, the
    forward/inverse maps are traceable methods closing over the static
    architecture (masks, permutations, precision spec).

    ``spec`` is the resolved ``flow.coupling``
    :class:`~pint_tpu.precision.SegmentSpec` the coupling MLP matmuls
    trace under; ``None`` resolves override -> manifest -> the
    bit-identical f64 default at construction (host-side, once — the
    traced closures never consult the policy)."""

    def __init__(self, cfg: FlowConfig, spec=None):
        self.cfg = cfg
        if spec is None:
            from pint_tpu.precision import segment_spec

            spec = segment_spec("flow.coupling")
        self.spec = spec
        # fixed seeded permutations: layer i conditions on perm[:d//2]
        # and transforms perm[d//2:].  ndim == 1 admits no coupling
        # split; the flow is then the learned diagonal affine alone.
        rng = np.random.default_rng(cfg.seed)
        d = cfg.ndim
        self._splits: List[Tuple[np.ndarray, np.ndarray]] = []
        if d >= 2:
            for _ in range(cfg.n_layers):
                perm = rng.permutation(d)
                self._splits.append((perm[: d // 2].copy(),
                                     perm[d // 2:].copy()))
        self._init_rng_state = rng.bit_generator.state

    @property
    def n_coupling_layers(self) -> int:
        return len(self._splits)

    @staticmethod
    def base_logpdf(z):
        """Standard-normal log-density of the base samples, per
        sample (a method, not a module function: the traced ELBO and
        serve kernels reach it through their Flow instance, keeping
        the module's function surface host-only for the
        host-call-in-jit lint)."""
        import jax.numpy as jnp

        return -0.5 * jnp.sum(z * z, axis=-1) \
            - 0.5 * z.shape[-1] * _LOG_2PI

    # -- parameters ---------------------------------------------------------

    def init(self) -> Dict[str, Any]:
        """Identity-initialized parameter pytree: the conditioner
        hidden layer gets small seeded random weights (symmetry
        breaking), the s/t output layers start at zero — so the
        freshly built flow is exactly the base distribution."""
        rng = np.random.default_rng()
        rng.bit_generator.state = self._init_rng_state
        cfg = self.cfg
        layers = []
        for idx_a, idx_b in self._splits:
            d_in, d_out = len(idx_a), len(idx_b)
            layers.append({
                "W1": rng.normal(size=(d_in, cfg.hidden))
                / np.sqrt(max(d_in, 1)),
                "b1": np.zeros(cfg.hidden),
                "Ws": np.zeros((cfg.hidden, d_out)),
                "bs": np.zeros(d_out),
                "Wt": np.zeros((cfg.hidden, d_out)),
                "bt": np.zeros(d_out),
            })
        return {"layers": layers,
                "loc": np.zeros(cfg.ndim),
                "log_scale": np.zeros(cfg.ndim)}

    # -- traced maps --------------------------------------------------------

    def _net(self, layer, h_in):
        """The coupling conditioner: one tanh hidden layer -> (s, t),
        with s tanh-clamped at ``s_cap``.  Matmuls route through the
        ``flow.coupling`` precision segment."""
        import jax.numpy as jnp

        from pint_tpu.precision import matmul as _pmatmul

        h = jnp.tanh(_pmatmul(h_in, layer["W1"], self.spec)
                     + layer["b1"])
        s_raw = _pmatmul(h, layer["Ws"], self.spec) + layer["bs"]
        t = _pmatmul(h, layer["Wt"], self.spec) + layer["bt"]
        cap = self.cfg.s_cap
        return cap * jnp.tanh(s_raw / cap), t

    def forward(self, params, z):
        """Base -> unconstrained: ``z (..., ndim)`` -> ``(u, logdet)``
        with ``logdet = log |du/dz|`` per sample (traceable)."""
        import jax.numpy as jnp

        x = jnp.asarray(z)
        logdet = jnp.zeros(x.shape[:-1])
        for layer, (idx_a, idx_b) in zip(params["layers"], self._splits):
            xa = x[..., idx_a]
            s, t = self._net(layer, xa)
            yb = x[..., idx_b] * jnp.exp(s) + t
            x = x.at[..., idx_b].set(yb)
            logdet = logdet + jnp.sum(s, axis=-1)
        scale = jnp.exp(params["log_scale"])
        u = params["loc"] + scale * x
        return u, logdet + jnp.sum(params["log_scale"])

    def inverse(self, params, u):
        """Unconstrained -> base: ``u (..., ndim)`` -> ``(z,
        logdet_inv)`` with ``logdet_inv = log |dz/du|`` (traceable;
        exact inverse of :meth:`forward`)."""
        import jax.numpy as jnp

        x = (jnp.asarray(u) - params["loc"]) \
            * jnp.exp(-params["log_scale"])
        logdet = jnp.zeros(x.shape[:-1]) - jnp.sum(params["log_scale"])
        for layer, (idx_a, idx_b) in zip(reversed(params["layers"]),
                                         reversed(self._splits)):
            xa = x[..., idx_a]
            s, t = self._net(layer, xa)
            xb = (x[..., idx_b] - t) * jnp.exp(-s)
            x = x.at[..., idx_b].set(xb)
            logdet = logdet - jnp.sum(s, axis=-1)
        return x, logdet
