"""The trained flow as a posterior: draw + log-prob serve kernels.

:class:`AmortizedPosterior` holds a trained flow (architecture +
weights + prior transform + provenance) and exposes the two serve
kernels the warm layer registers:

* **draw** — ``(params, keys (batch, 2)) -> (batch, n, ndim)``: each
  coalesced request samples from its OWN fold of the service key
  (requests never share a PRNG key), the draw count ``n`` is static
  per executable (bucketed by the service's draw ladder);
* **log_prob** — ``(params, points (batch, n, ndim)) -> (batch, n)``:
  the exact flow density via the analytic coupling inverse; points
  outside a uniform prior's support report ``-inf`` (zero density),
  and padded query rows are sliced away by the caller.

Both kernels live in module-level jit registries keyed by
``(flow digest, precision key, shape)`` — the serving discipline: one
executable per shape family process-wide, warmable into a
:class:`~pint_tpu.serving.warmup.WarmPool` and persistable through
the :class:`~pint_tpu.serving.aotcache.AOTCache` under
:meth:`AmortizedPosterior.serve_vkey` (flow config digest + precision
key + the training posterior's vkey + the established
device-fingerprint scheme downstream).

:meth:`AmortizedPosterior.save` / :meth:`load` persist the trained
flow with the aotcache manifest discipline: an npz of weight leaves
next to a JSON sidecar of identity material, verified FIELD BY FIELD
on load — any mismatch or corruption raises the typed
:class:`~pint_tpu.exceptions.CheckpointError` rather than serving a
wrong posterior.  **No saved flow and no registration means no new
executables exist** — the default service path is byte-identical to
the pre-amortized layer.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from pint_tpu.amortized.elbo import AmortizedVI
from pint_tpu.amortized.flows import Flow, FlowConfig, PriorTransform
from pint_tpu.exceptions import CheckpointError, UsageError

__all__ = ["AmortizedPosterior", "FLOW_MANIFEST_SCHEMA"]

FLOW_MANIFEST_SCHEMA = "pint_tpu.amortized.flow/1"

#: module-level serve-kernel registries: one jitted executable per
#: (flow digest, precision key, static shape) process-wide — repeat
#: endpoints retrace into the warm dispatch cache, never a new program
_DRAW_JIT: Dict[tuple, Any] = {}
_LOGPROB_JIT: Dict[tuple, Any] = {}


class AmortizedPosterior:
    """A trained flow posterior: host conveniences + serve kernels."""

    def __init__(self, flow: Flow, transform: PriorTransform, params,
                 param_labels: Sequence[str], vkey: tuple = (),
                 _vkey_repr: Optional[str] = None):
        if flow.cfg.ndim != transform.ndim:
            raise UsageError(
                f"flow ndim {flow.cfg.ndim} != transform ndim "
                f"{transform.ndim}")
        if len(param_labels) != flow.cfg.ndim:
            raise UsageError(
                f"{len(param_labels)} labels for ndim {flow.cfg.ndim}")
        self.flow = flow
        self.transform = transform
        self.params = params
        self.param_labels = tuple(str(p) for p in param_labels)
        self.vkey = tuple(vkey)
        # identity string for serve_vkey: a LOADED posterior carries
        # the sidecar's stored repr verbatim, so train-process and
        # load-process executables share one AOT-cache identity
        self._vkey_repr = _vkey_repr if _vkey_repr is not None \
            else repr(self.vkey)

    @classmethod
    def from_training(cls, vi: AmortizedVI, result) -> "AmortizedPosterior":
        """Bundle a finished :func:`~pint_tpu.amortized.train.
        train_flow` run into a servable posterior."""
        return cls(flow=vi.flow, transform=vi.transform,
                   params=result.params, param_labels=vi.param_labels,
                   vkey=vi.vkey)

    @property
    def ndim(self) -> int:
        return self.flow.cfg.ndim

    def serve_vkey(self) -> tuple:
        """AOT-cache / warm-pool version key for this posterior's
        executables: kernel schema + flow architecture digest + prior
        transform digest + precision key + the training posterior's
        identity — an edited model, re-validated TOA set, retrained
        architecture, moved prior box, or precision flip can never
        replay a stale export."""
        return ("amortized_posterior", 1, self.flow.cfg.digest(),
                self.transform.digest(), self.flow.spec.key(),
                self._vkey_repr)

    def ident(self) -> str:
        """Short executable-name identity: everything the traced
        kernels bake in as constants (architecture, prior transform,
        precision, training-posterior vkey).  The serving door folds
        this into executable names, so a pool/registry entry compiled
        for one posterior can never be replayed for another that
        merely shares shapes."""
        return hashlib.sha256(repr(self.serve_vkey()).encode()
                              ).hexdigest()[:12]

    # -- serve kernels ------------------------------------------------------

    def _registry_key(self, n: int) -> tuple:
        # the kernels close over the flow architecture, the precision
        # spec, AND the prior transform — all of it keys the cache
        # (same-shape posteriors with different boxes must never share
        # a compiled kernel)
        return (self.flow.cfg.digest(), self.transform.digest(),
                self.flow.spec.key(), int(n))

    def draw_kernel(self, n: int):
        """The batched draw executable for ``n`` static draws:
        ``(params, keys (batch, 2) uint32) -> (batch, n, ndim)`` —
        one flow sample stream per key row."""
        if n < 1:
            raise UsageError(f"draw count must be >= 1, got {n}")
        key = self._registry_key(n)
        fn = _DRAW_JIT.get(key)
        if fn is None:
            import jax

            flow, transform, ndim = self.flow, self.transform, self.ndim

            def one(params, k):
                z = jax.random.normal(k, (n, ndim), dtype=np.float64)
                u, _ = flow.forward(params, z)
                x, _ = transform.constrain(u)
                return x

            fn = jax.jit(jax.vmap(one, in_axes=(None, 0)))
            _DRAW_JIT[key] = fn
        return fn

    def logprob_kernel(self, n: int):
        """The batched log-prob executable for ``n`` static query
        points: ``(params, points (batch, n, ndim)) -> (batch, n)``."""
        if n < 1:
            raise UsageError(f"query count must be >= 1, got {n}")
        key = self._registry_key(n)
        fn = _LOGPROB_JIT.get(key)
        if fn is None:
            import jax
            import jax.numpy as jnp

            flow, transform = self.flow, self.transform

            def one(params, pts):
                u, lj_inv, inb = transform.unconstrain(pts)
                z, ld_inv = flow.inverse(params, u)
                logq = flow.base_logpdf(z) + ld_inv + lj_inv
                return jnp.where(inb, logq, -jnp.inf)

            fn = jax.jit(jax.vmap(one, in_axes=(None, 0)))
            _LOGPROB_JIT[key] = fn
        return fn

    # -- host conveniences --------------------------------------------------

    def draw(self, n: int, seed: int = 0) -> np.ndarray:
        """``(n, ndim)`` posterior draws (host convenience around the
        serve kernel; the service door owns key discipline for
        coalesced requests)."""
        import jax

        keys = jax.random.PRNGKey(int(seed))[None, :]
        return np.asarray(self.draw_kernel(int(n))(self.params,
                                                   keys))[0]

    def log_prob(self, points) -> np.ndarray:
        """``(n,)`` flow log-densities at ``points (n, ndim)``."""
        pts = np.atleast_2d(np.asarray(points, dtype=np.float64))
        if pts.shape[-1] != self.ndim:
            raise UsageError(
                f"points are (n, {self.ndim}); got {pts.shape}")
        return np.asarray(self.logprob_kernel(pts.shape[0])(
            self.params, pts[None, ...]))[0]

    # -- persistence (the aotcache manifest discipline) ---------------------

    def _manifest(self, leaf_names: List[str],
                  weights_sha256: str) -> dict:
        return {
            "schema": FLOW_MANIFEST_SCHEMA,
            "config": self.flow.cfg.to_dict(),
            "transform": self.transform.to_dict(),
            "param_labels": list(self.param_labels),
            "vkey": self._vkey_repr,
            "spec_key": list(self.flow.spec.key()),
            "leaves": leaf_names,
            "weights_sha256": weights_sha256,
        }

    def save(self, path: str) -> str:
        """Persist the trained flow: ``<path>.npz`` (weight leaves) +
        ``<path>.json`` (identity sidecar).  Each file replaces
        atomically, and the sidecar carries the weight file's sha256 —
        a crash between the two replaces leaves a pair the load-time
        digest check refuses, never a silently mismatched
        weights/identity combination."""
        import jax

        leaves, _ = jax.tree_util.tree_flatten(self.params)
        names = [f"leaf_{i:03d}" for i in range(len(leaves))]
        arrays = {nm: np.asarray(lf) for nm, lf in zip(names, leaves)}
        npz, sidecar = path + ".npz", path + ".json"
        tmp = npz + ".tmp.npz"
        np.savez(tmp, **arrays)
        with open(tmp, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()
        os.replace(tmp, npz)
        tmp = sidecar + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self._manifest(names, digest), f, sort_keys=True)
        os.replace(tmp, sidecar)
        return npz

    @classmethod
    def load(cls, path: str, expect_vkey: Optional[tuple] = None
             ) -> "AmortizedPosterior":
        """Load a saved flow, verifying the sidecar FIELD BY FIELD
        against the weights file; any mismatch, truncation, or — when
        ``expect_vkey`` is given — identity drift raises the typed
        :class:`~pint_tpu.exceptions.CheckpointError` (a wrong
        posterior must never be served)."""
        npz, sidecar = path + ".npz", path + ".json"
        try:
            with open(sidecar, encoding="utf-8") as f:
                man = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise CheckpointError(
                f"{sidecar}: unreadable/invalid flow sidecar ({e})") \
                from e
        if man.get("schema") != FLOW_MANIFEST_SCHEMA:
            raise CheckpointError(
                f"{sidecar}: schema {man.get('schema')!r} != "
                f"{FLOW_MANIFEST_SCHEMA!r}")
        for key in ("config", "transform", "param_labels", "vkey",
                    "spec_key", "leaves", "weights_sha256"):
            if key not in man:
                raise CheckpointError(f"{sidecar}: missing field "
                                      f"{key!r}")
        cfg = FlowConfig.from_dict(man["config"])
        transform = PriorTransform.from_dict(man["transform"])
        labels = [str(p) for p in man["param_labels"]]
        if expect_vkey is not None and man["vkey"] != repr(
                tuple(expect_vkey)):
            raise CheckpointError(
                f"{sidecar}: flow was trained for vkey {man['vkey']}, "
                f"caller expects {tuple(expect_vkey)!r} — a stale or "
                "foreign flow must not serve this workload")
        # the npz/sidecar pair replaces in two steps: the digest check
        # refuses a crash-window pairing of new weights with a stale
        # sidecar whose leaf shapes happen to match
        try:
            with open(npz, "rb") as f:
                digest = hashlib.sha256(f.read()).hexdigest()
        except OSError as e:
            raise CheckpointError(
                f"{npz}: unreadable flow weights ({e})") from e
        if digest != man["weights_sha256"]:
            raise CheckpointError(
                f"{npz}: weight digest {digest[:12]} does not match "
                f"the sidecar's {str(man['weights_sha256'])[:12]} — "
                "torn save or foreign weights; refusing to serve a "
                "mismatched posterior")
        try:
            with np.load(npz, allow_pickle=False) as d:
                arrays = {k: d[k] for k in d.files}
        except (OSError, ValueError) as e:
            raise CheckpointError(
                f"{npz}: unreadable flow weights ({e})") from e
        if sorted(arrays) != sorted(man["leaves"]):
            raise CheckpointError(
                f"{npz}: weight leaves {sorted(arrays)} do not match "
                f"the sidecar's {sorted(man['leaves'])}")
        # rebuild the pytree from the architecture's own structure so
        # a leaf-count drift (truncated npz, foreign architecture)
        # fails loudly here, not at the first dispatch
        import jax

        from pint_tpu.precision import SegmentSpec

        # ALWAYS pin the sidecar's stored spec (the f64 default
        # included): spec=None would re-resolve the ambient
        # policy/manifest, and a reduced resolution would serve a
        # different-precision posterior than the one verified above
        spec_key = tuple(man["spec_key"])
        try:
            spec = SegmentSpec(segment="flow.coupling",
                               compute_dtype=str(spec_key[0]),
                               accumulation=str(spec_key[1]))
        except (IndexError, UsageError) as e:
            raise CheckpointError(
                f"{sidecar}: malformed spec_key {spec_key!r} ({e})") \
                from e
        flow = Flow(cfg, spec=spec)
        template = flow.init()
        leaves, treedef = jax.tree_util.tree_flatten(template)
        if len(leaves) != len(man["leaves"]):
            raise CheckpointError(
                f"{npz}: {len(man['leaves'])} stored leaves for an "
                f"architecture with {len(leaves)}")
        loaded = [arrays[nm] for nm in man["leaves"]]
        for tpl, got, nm in zip(leaves, loaded, man["leaves"]):
            if np.shape(tpl) != np.shape(got):
                raise CheckpointError(
                    f"{npz}: leaf {nm} has shape {np.shape(got)}, "
                    f"architecture expects {np.shape(tpl)}")
        params = jax.tree_util.tree_unflatten(treedef, loaded)
        return cls(flow=flow, transform=transform, params=params,
                   param_labels=labels,
                   _vkey_repr=str(man["vkey"]))
