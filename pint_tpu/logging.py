"""Logging setup for pint_tpu.

The reference uses loguru with per-message dedup filters
(reference ``src/pint/logging.py:1-60``).  loguru is not a dependency here;
this module provides the same surface — ``setup(level)``, dedup of repeated
messages, warning capture — on top of the stdlib ``logging`` module.
"""

from __future__ import annotations

import logging as _logging
import sys
import warnings

__all__ = ["setup", "log", "levels", "LogFilter"]

levels = ["TRACE", "DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL"]

log = _logging.getLogger("pint_tpu")


class LogFilter(_logging.Filter):
    """Filter that suppresses duplicate messages.

    Mirrors the reference's ``LogFilter`` dedup behaviour: messages listed in
    ``onlyonce`` (or, if ``onlyonce_level`` is set, every message at or below
    that level) are emitted a single time per process.
    """

    def __init__(self, onlyonce: list[str] | None = None, dedup_all: bool = False):
        super().__init__()
        self.onlyonce = set(onlyonce or [])
        self.dedup_all = dedup_all
        self._seen: set[str] = set()

    def filter(self, record: _logging.LogRecord) -> bool:  # noqa: A003
        msg = record.getMessage()
        if self.dedup_all or any(msg.startswith(o) for o in self.onlyonce):
            if msg in self._seen:
                return False
            self._seen.add(msg)
        return True


_DEFAULT_ONLYONCE = [
    "Using EPHEM =",
    "Using CLK =",
    "Using UNITS =",
    "No pulse number flags found",
    "SSB obs pos",
    "Setting pulse numbers",
    "Clock file",
    "Using built-in analytic solar-system ephemeris",
]

_configured = False


def setup(level: str = "INFO", usecolors: bool = True, dedup: bool = True) -> int:
    """Configure the pint_tpu logger; returns a handler id for parity."""
    global _configured
    for h in list(log.handlers):
        log.removeHandler(h)
    handler = _logging.StreamHandler(sys.stderr)
    fmt = "%(asctime)s %(levelname)-8s %(name)s %(message)s"
    handler.setFormatter(_logging.Formatter(fmt, datefmt="%H:%M:%S"))
    if dedup:
        handler.addFilter(LogFilter(onlyonce=_DEFAULT_ONLYONCE))
    log.addHandler(handler)
    log.setLevel(getattr(_logging, level if level != "TRACE" else "DEBUG"))
    log.propagate = False
    if not _configured:
        _logging.captureWarnings(False)
        _configured = True
    return id(handler)


def showwarning(message, category, filename, lineno, file=None, line=None):
    """``warnings.showwarning`` replacement routing through this logger
    (reference ``logging.py:85``); installed by :func:`capture_warnings`."""
    name = category.__name__ if category else "Warning"
    log.warning(f"{name}: {message} ({filename}:{lineno})")


def capture_warnings(enable: bool = True) -> None:
    """Route Python warnings through the pint_tpu logger."""
    if enable:
        warnings.showwarning = showwarning
    else:
        warnings.showwarning = warnings._showwarning_orig  # type: ignore[attr-defined]


setup("WARNING")


def get_level(starting_level_name: str, verbosity: int, quietness: int) -> str:
    """Map a base level and -v/-q counts to a level name (reference
    ``logging.py:336``; used by CLI scripts)."""
    start = levels.index(starting_level_name) \
        if starting_level_name in levels else levels.index("INFO")
    return levels[min(max(start - verbosity + quietness, 0), len(levels) - 1)]

