"""Compensated-accumulation primitives for policy-driven matmul segments.

These are the TRACED building blocks the hot-path kernels call in place
of a bare ``a @ b`` or ``.astype``: the inputs round to the segment's
compute dtype once, the matrix units run at that dtype, and the result
re-enters the f64 world through an accumulation mode that bounds what
the downcast can cost:

* ``native`` — the product stays in the compute dtype and upcasts once
  at the segment boundary (the raw MXU regime);
* ``f64`` — XLA accumulates the contraction in f64
  (``preferred_element_type``): products of f32 inputs are exactly
  representable in f64, so only the INPUT rounding survives;
* ``two_sum`` — the contraction axis is split into K blocks, each block
  accumulated in f64, and the block partials are folded through the L0
  error-free transforms (:func:`pint_tpu.dd.two_sum`): the segment
  boundary is a compensated (hi, lo) pair, so the cross-block
  accumulation contributes exactly nothing — the paper's dd-split
  applied as a matmul reduction.

All three modes are pure jnp/lax arithmetic — jit/vmap/shard-safe.
Given host numpy operands (the fitters' host Gram path) the same
semantics run in numpy (compute-dtype rounding, f64 or ``two_sum_np``
accumulation), so a policy flip cannot mean different math on the two
sides of a host/device boundary.

The f64 default spec short-circuits to the plain ``a @ b`` the
pre-precision kernels ran — **bit-identical by construction**, which is
what lets every consumer route unconditionally through this module.

:func:`downcast` is the ONE sanctioned cast entry for the precision
core: jaxlint's ``unguarded-downcast`` rule flags bare
``.astype(float32/bfloat16)`` in the core files, and routing the cast
through here is the fix the rule demands.

jax imports are function-local: importing the precision package must
not import jax (the serving/catalog import discipline).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pint_tpu.exceptions import UsageError
from pint_tpu.precision.policy import COMPUTE_DTYPES, SegmentSpec

__all__ = ["downcast", "promote_f64", "matmul", "two_sum_accumulate",
           "DEFAULT_SPLIT"]

#: default number of contraction-axis blocks for ``two_sum``
#: accumulation (enough blocks that each partial's f64 accumulation
#: error stays far below the fold's error-free boundary)
DEFAULT_SPLIT = 8


def _np_dtype(compute_dtype: str):
    if compute_dtype == "float64":
        return np.float64
    if compute_dtype == "float32":
        return np.float32
    # numpy has no native bfloat16: jax's ml_dtypes dependency provides
    # the dtype, so host-side bf16 rounding matches the device's
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


def _jnp_dtype(compute_dtype: str):
    import jax.numpy as jnp

    return {"float64": jnp.float64, "float32": jnp.float32,
            "bfloat16": jnp.bfloat16}[compute_dtype]


def _is_host(*arrays) -> bool:
    return all(isinstance(a, np.ndarray) for a in arrays)


def downcast(x, compute_dtype: str):
    """The sanctioned precision-core cast: ``x`` rounded to
    ``compute_dtype``.  Works on host numpy and traced jax arrays; a
    ``float64`` request is the identity (never an upcast surprise)."""
    if compute_dtype not in COMPUTE_DTYPES:
        raise UsageError(f"downcast target {compute_dtype!r} not in "
                         f"{COMPUTE_DTYPES}")
    if compute_dtype == "float64":
        return x
    if isinstance(x, np.ndarray):
        return x.astype(_np_dtype(compute_dtype))
    return x.astype(_jnp_dtype(compute_dtype))


def promote_f64(x):
    """Segment-boundary upcast back to f64 (host or traced)."""
    if isinstance(x, np.ndarray):
        return x.astype(np.float64)
    import jax.numpy as jnp

    return x.astype(jnp.float64)


def _two_sum_traced(a, b):
    """Branch-free Knuth two_sum WITHOUT :func:`pint_tpu.dd._opaque`'s
    optimization barrier: the barrier has no vmap batching rule, and
    these folds run inside vmapped kernels (the chunked grid, the
    batched serve kernel).  Under IEEE-correct f64 (CPU, native-f64
    accelerators) this is still the exact error-free transform; under
    a TPU excess-precision regime XLA may fold the error term to zero,
    degrading the fold to PLAIN f64 summation of the partials — a loss
    bounded by ~n_partials ulp of the dominant partial, orders below
    every segment budget (the budgets are measured on-device by the
    probes either way)."""
    s = a + b
    bb = s - a
    e = (a - (s - bb)) + (b - bb)
    return s, e


def two_sum_accumulate(partials):
    """Fold a sequence of f64 partial sums error-free: returns
    ``hi + lo`` where the running sum is carried as a compensated
    (hi, lo) pair through the two_sum transform — the dd-split segment
    boundary.  Host numpy partials fold through
    :func:`pint_tpu.dd.two_sum_np` (IEEE-correct on the host); traced
    partials through the vmap-safe :func:`_two_sum_traced`."""
    partials = list(partials)
    if not partials:
        raise UsageError("two_sum_accumulate needs at least one partial")
    if _is_host(*partials):
        from pint_tpu.dd import two_sum_np as _two_sum
    else:
        _two_sum = _two_sum_traced
    hi = partials[0]
    lo = None
    for p in partials[1:]:
        hi, e = _two_sum(hi, p)
        lo = e if lo is None else lo + e
    return hi if lo is None else hi + lo


def _split_slices(k: int, split: int):
    """Static contraction-axis blocks: ``split`` near-equal slices of
    range(k) (fewer when k is small), computed at trace time."""
    n = max(1, min(int(split), int(k)))
    bounds = np.linspace(0, k, n + 1).astype(int)
    return [slice(int(a), int(b)) for a, b in zip(bounds[:-1], bounds[1:])
            if b > a]


def _dd_split_jnp(x, ct):
    """Dekker-style operand split: ``x = hi + lo`` with both parts in
    the reduced dtype — ``hi`` the rounded value, ``lo`` the rounded
    remainder (exact for f32: an f64's tail rounds to one f32)."""
    import jax.numpy as jnp

    hi = x.astype(ct)
    lo = (x - hi.astype(jnp.float64)).astype(ct)
    return hi, lo


def _matmul_jnp(a, b, spec: SegmentSpec, split: int):
    import jax.numpy as jnp

    ct = _jnp_dtype(spec.compute_dtype)
    if spec.accumulation == "two_prod":
        # the dd-split matmul: three reduced-precision matrix-unit
        # passes whose f64-accumulated sum recovers ~ulp(ct)^2 relative
        # accuracy (the dropped lo@lo term); the three partials fold
        # error-free through two_sum
        ah, al_ = _dd_split_jnp(a, ct)
        bh, bl_ = _dd_split_jnp(b, ct)
        f64 = jnp.float64
        parts = [jnp.matmul(ah, bh, preferred_element_type=f64),
                 jnp.matmul(ah, bl_, preferred_element_type=f64),
                 jnp.matmul(al_, bh, preferred_element_type=f64)]
        return two_sum_accumulate(parts)
    al = a.astype(ct)
    bl = b.astype(ct)
    if spec.accumulation == "native":
        return jnp.matmul(al, bl).astype(jnp.float64)
    if spec.accumulation == "f64":
        return jnp.matmul(al, bl, preferred_element_type=jnp.float64)
    # two_sum: block the contraction axis, accumulate each block in
    # f64, fold the block partials error-free
    k = a.shape[-1]
    parts = []
    for sl in _split_slices(k, split):
        ab = al[..., sl]
        bb = bl[sl] if bl.ndim == 1 else bl[..., sl, :]
        parts.append(jnp.matmul(ab, bb,
                                preferred_element_type=jnp.float64))
    return two_sum_accumulate(parts)


def _matmul_np(a, b, spec: SegmentSpec, split: int):
    ct = _np_dtype(spec.compute_dtype)
    # host semantics mirror the device's: inputs round to the compute
    # dtype; f64/two_sum accumulation upcasts the ROUNDED inputs so the
    # products are exact and only the input rounding survives (products
    # of two f32 are exactly representable in f64 — same property the
    # preferred_element_type path relies on)
    if spec.accumulation == "two_prod":
        ah = a.astype(ct)
        al_ = (a - ah.astype(np.float64)).astype(ct)
        bh = b.astype(ct)
        bl_ = (b - bh.astype(np.float64)).astype(ct)
        ah64, al64 = ah.astype(np.float64), al_.astype(np.float64)
        bh64, bl64 = bh.astype(np.float64), bl_.astype(np.float64)
        return two_sum_accumulate([np.matmul(ah64, bh64),
                                   np.matmul(ah64, bl64),
                                   np.matmul(al64, bh64)])
    al = a.astype(ct)
    bl = b.astype(ct)
    if spec.accumulation == "native":
        return np.matmul(al, bl).astype(np.float64)
    a64 = al.astype(np.float64)
    b64 = bl.astype(np.float64)
    if spec.accumulation == "f64":
        return np.matmul(a64, b64)
    k = a.shape[-1]
    parts = []
    for sl in _split_slices(k, split):
        bb = b64[sl] if b64.ndim == 1 else b64[..., sl, :]
        parts.append(np.matmul(a64[..., sl], bb))
    return two_sum_accumulate(parts)


def matmul(a, b, spec: Optional[SegmentSpec] = None,
           split: int = DEFAULT_SPLIT):
    """Policy matmul: ``a @ b`` computed under ``spec``.

    ``spec=None`` or an f64 spec is EXACTLY ``a @ b`` (same op, same
    bits) — the default path costs nothing and changes nothing.  A
    reduced spec rounds the operands to the compute dtype once and
    re-enters f64 through the spec's accumulation mode.  Dispatches to
    numpy when both operands are host arrays (the fitters' host Gram
    path), jnp otherwise (traced kernels)."""
    if spec is None or not spec.reduced:
        return a @ b
    if _is_host(a, b):
        return _matmul_np(a, b, spec, split)
    return _matmul_jnp(a, b, spec, split)
