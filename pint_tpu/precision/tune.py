"""Per-segment precision probes: measure, decide, persist.

The generalization of PR 10's single f32 Woodbury chi2-correction probe
(:func:`pint_tpu.autotune.search.tune_precision`): every registered
segment (:data:`pint_tpu.precision.policy.SEGMENTS`) gets a probe that
runs the segment's ACTUAL consumer kernel twice — once at the f64
default, once at the candidate reduced spec — on the workload's real
operands, and measures the relative disagreement of the quantities the
segment feeds (chi2, step vector, lnlikelihood).

Decision discipline (the PR 10 contract, per segment):

* **unforced** (``force=False``): the reduced spec ships only when the
  measured disagreement sits below the segment's ``safe_rel`` bar
  (chi2 rel < 1e-12 discipline) — on every realistic f64-native
  workload this records the f64 default with the measured margin;
* **forced** (``force=True``, the CPU demonstration / acceptance run):
  the reduced spec records with the segment's ``forced_budget`` as its
  admitted budget, and is REFUSED (f64 recorded, with the reason) when
  the measured disagreement exceeds even that budget — a forced run
  still cannot ship a broken segment;
* either way the decision persists as a ``precision.<segment>`` key in
  the tuning manifest (vkey + device-fingerprint scheme) and a
  ``precision_probe`` telemetry event records segment, dtypes, measured
  rel err, and the decision.

Everything here is host-side orchestration (eager kernel evaluations,
manifest I/O) — calling it from traced code is flagged by jaxlint's
host-call-in-jit rule.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from pint_tpu import config
from pint_tpu.exceptions import UsageError
from pint_tpu.logging import log
from pint_tpu.precision.policy import (
    SEGMENTS,
    SegmentSpec,
    precision_vkey,
)

__all__ = ["probe_segment", "tune_precision_segments"]

#: representative joint-lnlike point for the catalog.lnlike probe
_LNLIKE_POINT = (-14.5, 13.0 / 3.0)


#: finite stand-in for an outright-failed probe (rel = inf) in JSON
#: artifacts and events: committed manifests and the strict-JSON event
#: stream must never carry an Infinity token (the runlog would
#: stringify it, failing the numeric attr contract; json.dump would
#: write non-RFC JSON into tuning.json)
_REL_FAILED_SENTINEL = 1e300


def _finite_rel(rel: float) -> float:
    import math

    return float(rel) if math.isfinite(rel) else _REL_FAILED_SENTINEL


def _emit_probe(segment: str, spec: SegmentSpec, rel: float,
                budget: float, decision: str) -> None:
    if config._telemetry_mode == "off":
        return
    from pint_tpu import telemetry

    telemetry.lifecycle_event(
        "precision_probe", segment=segment,
        dtype=spec.compute_dtype, accumulation=spec.accumulation,
        rel_err=_finite_rel(rel), budget=float(budget),
        decision=decision)


def _rel(a: np.ndarray, b: np.ndarray, scale: Optional[float] = None
         ) -> float:
    """Relative disagreement of ``a`` vs reference ``b``: worst of the
    elementwise deviations over ``scale`` (default: the reference's own
    magnitude floor-clamped)."""
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    if not np.all(np.isfinite(a)):
        return float("inf")
    s = scale if scale is not None else max(float(np.max(np.abs(b))),
                                            1e-300)
    return float(np.max(np.abs(a - b)) / s)


def _serve_outputs(M, r, w, phiinv, pad_free, spec: Optional[SegmentSpec]):
    """One eager serve-kernel evaluation under ``spec`` (the real
    consumer kernel, not a model of it)."""
    from pint_tpu.serving.batcher import serve_kernel

    dx, err, chi2, chi2_init = serve_kernel(M, r, w, phiinv, pad_free,
                                            spec=spec)
    return (np.asarray(dx), np.asarray(err), float(chi2),
            float(chi2_init))


def _serve_system_rel(ftr, spec: SegmentSpec) -> float:
    """f64-vs-``spec`` disagreement of the linearized-fit kernel on the
    fitter's actual system: worst of chi2 (relative to chi2) and the
    step vector (relative to the step's own scale)."""
    from pint_tpu.serving.batcher import FitRequest, pad_request

    q = FitRequest.from_fitter(ftr)
    ops = pad_request(q, q.n_toas, q.n_free)
    dx64, err64, chi2_64, _ = _serve_outputs(*ops, None)
    dxr, _, chi2_r, _ = _serve_outputs(*ops, spec)
    step_scale = max(float(np.linalg.norm(dx64)),
                     float(np.linalg.norm(err64)), 1e-300)
    return max(_rel(np.array([chi2_r]), np.array([chi2_64]),
                    scale=max(abs(chi2_64), 1e-300)),
               float(np.linalg.norm(dxr - dx64)) / step_scale)


def _probe_gls_design(ftr, spec: SegmentSpec, **_) -> float:
    """The GLS solve under the segment, on the PATH the fitter actually
    dispatches (the PR 10 "scoped to what was probed" discipline): a
    correlated-noise system probes the Schur fast path
    (:func:`pint_tpu.gls_fitter._schur_gls_solve` — reduced noise-block
    Gram, coupling, and timing Grams, fresh caches both sides), a
    white/dense system the plain normal-equation build + hardened
    solve.  The compared quantities are the full solution vector and
    the post-step chi2; a reduced Gram whose Cholesky fails outright
    measures as infinite disagreement — refused, never shipped."""
    from pint_tpu.exceptions import NonFiniteSystemError, \
        SingularMatrixError
    from pint_tpu.gls_fitter import (
        _schur_gls_solve,
        gls_normal_equations,
        linearized_system,
    )
    from pint_tpu.runtime.solve import solve_normal_cholesky

    M, r, w, phiinv, params, _ = linearized_system(ftr.model, ftr.toas,
                                                   resids=ftr.resids)
    Nvec = 1.0 / w
    ntm = len(params)
    failures = (np.linalg.LinAlgError, SingularMatrixError,
                NonFiniteSystemError)
    if M.shape[1] > ntm:
        # the Schur fast path the production correlated-noise fit takes
        _, x64, _ = _schur_gls_solve(M, r, Nvec, phiinv, ntm, {})
        try:
            _, xr, _ = _schur_gls_solve(M, r, Nvec, phiinv, ntm, {},
                                        spec=spec)
        except failures:
            return float("inf")
    else:
        mtcm64, mtcy64 = gls_normal_equations(M, r, Nvec=Nvec,
                                              phiinv=phiinv)
        mtcmr, mtcyr = gls_normal_equations(M, r, Nvec=Nvec,
                                            phiinv=phiinv, spec=spec)
        _, x64, _ = solve_normal_cholesky(mtcm64, mtcy64,
                                          name="precision probe f64")
        try:
            _, xr, _ = solve_normal_cholesky(
                mtcmr, mtcyr, name="precision probe reduced")
        except failures:
            return float("inf")
    x64 = np.asarray(x64)
    xr = np.asarray(xr)
    step_scale = max(float(np.linalg.norm(x64)), 1e-300)
    rel_x = float(np.linalg.norm(xr - x64)) / step_scale
    chi2_64 = float(r @ (w * (r - M @ x64)))
    chi2_r = float(r @ (w * (r - M @ xr)))
    if not np.isfinite(chi2_r):
        return float("inf")
    return max(rel_x, abs(chi2_r - chi2_64) / max(abs(chi2_64), 1e-300))


def _probe_grid_gram(ftr, spec: SegmentSpec,
                     grid_params: Optional[Sequence[str]] = None,
                     points=None, **_) -> float:
    """The chunked GLS grid kernel under the segment: build the real
    kernel twice (f64 vs ``spec``) over a small representative point
    set and compare the chi2 surface + refit values."""
    from pint_tpu.grid import build_grid_gls_chi2_fn

    if grid_params is None or points is None:
        raise UsageError("grid.gram probe needs grid_params + points")
    import jax.numpy as jnp

    points = np.asarray(points, dtype=np.float64)[:4]
    chunk = int(points.shape[0])
    fn64, _, _ = build_grid_gls_chi2_fn(
        ftr.model, ftr.toas, tuple(grid_params), niter=1, chunk=chunk,
        precision=SegmentSpec(segment="grid.gram"))
    fnr, _, _ = build_grid_gls_chi2_fn(
        ftr.model, ftr.toas, tuple(grid_params), niter=1, chunk=chunk,
        precision=spec)
    c64, v64, _ = fn64(jnp.asarray(points))
    cr, vr, _ = fnr(jnp.asarray(points))
    rel_c = _rel(cr, c64, scale=max(float(np.max(np.abs(c64))), 1e-300))
    vscale = max(float(np.max(np.abs(v64))), 1e-300)
    return max(rel_c, float(np.max(np.abs(np.asarray(vr)
                                          - np.asarray(v64)))) / vscale)


def _probe_serve_gram(ftr, spec: SegmentSpec, **_) -> float:
    return _serve_system_rel(ftr, spec)


def _probe_catalog_fit(ftr, spec: SegmentSpec, catalog=None, **_) -> float:
    """The catalog batched-fit kernel shares the serve kernel; the
    probe measures it per member system (worst member wins) — or, with
    no catalog supplied, on the fitter's system as the representative
    (the same kernel either way)."""
    if catalog is None:
        return _serve_system_rel(ftr, spec)
    pulsars = list(getattr(catalog, "pulsars", catalog))
    rels = [_serve_system_rel(p.fitter, spec) for p in pulsars[:4]]
    return max(rels) if rels else float("inf")


def _probe_catalog_lnlike(ftr, spec: SegmentSpec, catalog=None,
                          **_) -> float:
    """The joint HD lnlikelihood under the segment, at a representative
    (log10_A, gamma) point; skipped (treated as unprobeable) without a
    catalog of >= 2 pulsars."""
    if catalog is None:
        raise UsageError("catalog.lnlike probe needs a catalog")
    from pint_tpu.catalog.likelihood import JointLikelihood

    jl64 = JointLikelihood(catalog, n_modes=3,
                           precision=SegmentSpec(segment="catalog.lnlike"))
    jlr = JointLikelihood(catalog, n_modes=3, precision=spec)
    l64 = jl64.lnlike(*_LNLIKE_POINT)
    lr = jlr.lnlike(*_LNLIKE_POINT)
    if not np.isfinite(lr):
        return float("inf")
    return abs(lr - l64) / max(abs(l64), 1.0)


_PROBES = {
    "gls.design": _probe_gls_design,
    "grid.gram": _probe_grid_gram,
    "serve.gram": _probe_serve_gram,
    "catalog.fit": _probe_catalog_fit,
    "catalog.lnlike": _probe_catalog_lnlike,
    # grid.correction is owned by the PR 10 probe
    # (autotune.tune_precision, manifest key grid.correction_dtype)
}


def probe_segment(segment: str, ftr, spec: SegmentSpec, **kw) -> float:
    """Measured f64-vs-``spec`` relative disagreement of one segment's
    consumer kernel on the workload's real operands (inf = the reduced
    kernel failed outright)."""
    fn = _PROBES.get(segment)
    if fn is None:
        raise UsageError(
            f"no probe for segment {segment!r} (probeable: "
            f"{sorted(_PROBES)})")
    return float(fn(ftr, spec, **kw))


def tune_precision_segments(ftr, segments: Optional[Sequence[str]] = None,
                            compute_dtype: str = "float32",
                            accumulation: str = "two_prod",
                            force: bool = False,
                            grid_params: Optional[Sequence[str]] = None,
                            points=None, catalog=None,
                            tuning_manifest=None) -> Dict[str, Any]:
    """Probe every (or the named) probeable segment for ``ftr``'s
    workload at the candidate ``(compute_dtype, accumulation)`` and
    record one ``precision.<segment>`` decision each (see the module
    docstring for the ship/refuse discipline).  Segments whose probe
    prerequisites are missing (no catalog for ``catalog.lnlike``, no
    grid axes for ``grid.gram``) are skipped with a log line, not
    failed.  Returns ``{segment: TuningDecision}``."""
    from pint_tpu.autotune.manifest import TuningDecision

    if compute_dtype == "float64":
        raise UsageError("probing float64 against itself is vacuous; "
                         "pass a reduced compute_dtype")
    names = list(segments) if segments is not None else sorted(_PROBES)
    out: Dict[str, Any] = {}
    for segment in names:
        d = SEGMENTS.get(segment)
        if d is None:
            raise UsageError(f"unknown precision segment {segment!r}")
        if segment not in _PROBES:
            raise UsageError(f"segment {segment!r} has no probe (its "
                             "decision is owned elsewhere — see SEGMENTS)")
        budget = d.forced_budget if force else d.safe_rel
        cand = SegmentSpec(segment=segment, compute_dtype=compute_dtype,
                           accumulation=accumulation, budget=budget,
                           source="forced" if force else "tuned")
        try:
            rel = probe_segment(segment, ftr, cand,
                                grid_params=grid_params, points=points,
                                catalog=catalog)
        except UsageError as e:
            log.info(f"precision: segment {segment} not probed ({e})")
            continue
        safe = rel < budget
        # persisted numbers are always finite: an outright-failed probe
        # (rel = inf) records the sentinel, never an Infinity token
        rel_store = _finite_rel(rel)
        if safe:
            value_spec = SegmentSpec(
                segment=segment, compute_dtype=compute_dtype,
                accumulation=accumulation, budget=budget,
                rel_err=rel_store, source="forced" if force else "tuned")
            value = value_spec.to_value()
            decision_word = compute_dtype
        else:
            value = SegmentSpec(segment=segment).to_value()
            value["rel_err"] = rel_store
            decision_word = "float64"
        reason = (f"{compute_dtype}+{accumulation} disagrees with f64 by "
                  f"{rel:.3e} — " + ("below" if safe else "above")
                  + f" the {budget:g} "
                  + ("forced" if force else "safety") + " budget"
                  + ("" if safe else "; f64 retained"))
        vkey = precision_vkey(segment, model=ftr.model, toas=ftr.toas) \
            if d.model_bound else precision_vkey(segment)
        dec = TuningDecision(
            name=f"precision.{segment}", value=value,
            static_default=SegmentSpec(segment=segment).to_value(),
            vkey=vkey, basis="forced" if force else "probe",
            measured={"rel_err": rel_store, "budget": budget,
                      "safe_rel": d.safe_rel,
                      "probe_failed": not np.isfinite(rel)},
            reason=reason)
        if tuning_manifest is not None:
            tuning_manifest.record(dec)
        _emit_probe(segment, cand, rel, budget, decision_word)
        out[segment] = dec
    return out
