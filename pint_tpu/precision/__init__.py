"""Precision-tuning layer: bf16/f32 matmul segments under the dd-split
error budget (ROADMAP item 4).

Three pieces:

* :mod:`~pint_tpu.precision.policy` — :class:`SegmentSpec` descriptors
  (segment name, compute dtype, accumulation mode, admitted error
  budget) for the named hot-path segments, resolved override ->
  tuning-manifest (``precision.<segment>`` keys) -> bit-identical f64
  default;
* :mod:`~pint_tpu.precision.compensated` — the traced primitives the
  kernels call in place of bare ``a @ b`` / ``.astype``:
  :func:`downcast` (the one sanctioned cast entry jaxlint's
  ``unguarded-downcast`` rule points at) and :func:`matmul` with
  ``native`` / ``f64`` / ``two_sum`` (dd error-free fold) accumulation
  back to f64;
* :mod:`~pint_tpu.precision.tune` — per-segment probes that run the
  real consumer kernels f64-vs-reduced on the workload's actual
  operands and persist ``precision.<segment>`` decisions only inside
  each segment's stated budget.

Consumers: the GLS fitter's normal-equation/Schur Grams
(``gls.design``), the chunked GLS grid kernel (``grid.gram`` +
PR 10's ``grid.correction``), the batched serve kernel
(``serve.gram``), and the catalog batched-fit / joint-lnlikelihood
kernels (``catalog.fit`` / ``catalog.lnlike``).
"""

from pint_tpu.precision.compensated import (
    DEFAULT_SPLIT,
    downcast,
    matmul,
    promote_f64,
    two_sum_accumulate,
)
from pint_tpu.precision.policy import (
    ACCUMULATIONS,
    COMPUTE_DTYPES,
    SEGMENTS,
    PrecisionPolicy,
    SegmentDef,
    SegmentSpec,
    active_policy,
    describe_segments,
    override_spec,
    precision_vkey,
    segment_spec,
    set_policy,
    spec_from_decision,
    use_policy,
)
from pint_tpu.precision.tune import probe_segment, tune_precision_segments

__all__ = [
    "ACCUMULATIONS", "COMPUTE_DTYPES", "DEFAULT_SPLIT", "SEGMENTS",
    "PrecisionPolicy", "SegmentDef", "SegmentSpec", "active_policy",
    "describe_segments", "downcast", "matmul", "override_spec",
    "precision_vkey", "probe_segment", "promote_f64", "segment_spec",
    "set_policy", "spec_from_decision", "tune_precision_segments",
    "two_sum_accumulate", "use_policy",
]
