"""Precision policy: named hot-path segments and their compute dtypes.

A **segment** is one named bulk-linear-algebra region of the hot path
(a design-matrix product, a Gram block, the batched serve kernel, the
joint-lnlikelihood projections).  Each segment the kernels consume is
described by a :class:`SegmentSpec` — compute dtype, accumulation mode,
and the error budget the decision was admitted under — and the default
spec for EVERY segment is full float64, which the compensated layer
(:mod:`pint_tpu.precision.compensated`) turns into the plain ``a @ b``
the pre-precision kernels ran: **no manifest and no override means
bit-identical f64 everywhere**.

Resolution order for :func:`segment_spec`:

1. an **override policy** installed with :func:`set_policy` /
   :func:`use_policy` (tests, the bench's forced-f64 reference pass,
   explicit deployments) wins outright;
2. a **tuned decision** in the autotune manifest
   (``precision.<segment>`` keys, recorded by
   :func:`pint_tpu.precision.tune.tune_precision_segments` under the
   established vkey + device-fingerprint scheme) — verified field by
   field by the manifest layer, validated again here
   (:func:`spec_from_decision`), and degraded to f64 on ANY miss or
   malformation;
3. the **f64 default**.

A reduced spec shipping to a consumer emits a ``precision_applied``
telemetry event (segment, dtypes, source) validated by
``tools/telemetry_report --check``.

Everything here is host-side decision plumbing; the traced primitives
live in :mod:`pint_tpu.precision.compensated`.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

from pint_tpu import config
from pint_tpu.exceptions import UsageError

__all__ = ["COMPUTE_DTYPES", "ACCUMULATIONS", "SEGMENTS", "SegmentDef",
           "SegmentSpec", "PrecisionPolicy", "active_policy", "set_policy",
           "use_policy", "override_spec", "segment_spec", "precision_vkey",
           "spec_from_decision", "describe_segments"]

#: dtypes a segment may compute its matmuls in
COMPUTE_DTYPES = ("float64", "float32", "bfloat16")
#: how a reduced segment's products re-enter f64:
#: ``native`` (product dtype, one upcast at the end), ``f64`` (XLA
#: accumulates the dot in f64 via preferred_element_type), ``two_sum``
#: (split-K partial products folded error-free through the L0 dd
#: transforms — the dd/two_sum-accumulated segment boundary),
#: ``two_prod`` (Dekker-style operand dd-split: each f64 operand
#: becomes a reduced-dtype (hi, lo) pair and the product is the
#: f64-accumulated hi@hi + hi@lo + lo@hi — three reduced-precision
#: matrix-unit passes recovering ~ulp(reduced)^2 relative accuracy,
#: the split the paper's L0 two_prod transform applies elementwise)
ACCUMULATIONS = ("native", "f64", "two_sum", "two_prod")

_SHORT = {"float64": "f64", "float32": "f32", "bfloat16": "bf16"}
_ACC_SHORT = {"native": "", "f64": "+a64", "two_sum": "+dd",
              "two_prod": "+split"}


@dataclass(frozen=True)
class SegmentDef:
    """Registry entry for one tunable segment."""

    name: str
    description: str
    #: a probe may ship reduced precision unrequested only below this
    #: measured f64-vs-reduced relative disagreement (the chi2 rel
    #: < 1e-12 discipline of PR 10's correction probe)
    safe_rel: float
    #: the budget a FORCED reduced decision is admitted (and later
    #: asserted) under — the f32-regime demonstration bound
    forced_budget: float
    #: whether the vkey binds to a (model, toas) workload or is
    #: deployment-generic (kernel-schema versioned)
    model_bound: bool = False


#: the segments the hot-path kernels consume, with their stated budgets
SEGMENTS: Dict[str, SegmentDef] = {s.name: s for s in (
    SegmentDef("gls.design",
               "GLS normal-equation build + Schur Gram blocks "
               "(gls_fitter: M^T W M, noise-block and coupling Grams)",
               safe_rel=1e-12, forced_budget=1e-3, model_bound=True),
    SegmentDef("grid.gram",
               "per-point design/Gram products inside the chunked GLS "
               "grid kernel (grid.py gn_step)",
               safe_rel=1e-12, forced_budget=1e-3, model_bound=True),
    SegmentDef("grid.correction",
               "Woodbury chi2-correction segment of the grid kernel "
               "(PR 10's dd-split-guarded probe; decision key "
               "grid.correction_dtype)",
               safe_rel=1e-12, forced_budget=1e-4, model_bound=True),
    SegmentDef("serve.gram",
               "the batched serve kernel's Gram/projection/step "
               "products (serving/batcher serve_kernel)",
               safe_rel=1e-12, forced_budget=1e-3),
    SegmentDef("catalog.fit",
               "the catalog batched-fit kernel (jit(vmap(serve_kernel)) "
               "per bucket, catalog/batchfit)",
               safe_rel=1e-12, forced_budget=1e-3),
    SegmentDef("catalog.lnlike",
               "joint Hellings-Downs lnlikelihood Gram/projection "
               "products (catalog/likelihood)",
               safe_rel=1e-9, forced_budget=1e-3),
    SegmentDef("flow.coupling",
               "the amortized-inference flow's coupling-MLP matmuls "
               "(amortized/flows; ELBO training and the draw/log-prob "
               "serve kernels trace the same segment — no per-workload "
               "probe exists, the decision is owned by the training "
               "run's policy/manifest)",
               safe_rel=1e-9, forced_budget=1e-2),
)}


@dataclass(frozen=True)
class SegmentSpec:
    """One segment's resolved precision configuration.

    ``budget`` is the error bar the configuration was admitted under
    (0.0 for the f64 default: the bit-identical contract); ``rel_err``
    the probe-measured f64-vs-reduced disagreement, when one exists.
    Frozen + hashable: kernel caches key executables on
    :meth:`key`."""

    segment: str
    compute_dtype: str = "float64"
    accumulation: str = "native"
    budget: float = 0.0
    rel_err: Optional[float] = None
    source: str = "default"          #: default | tuned | forced

    def __post_init__(self):
        if self.compute_dtype not in COMPUTE_DTYPES:
            raise UsageError(
                f"segment {self.segment!r}: compute_dtype "
                f"{self.compute_dtype!r} not in {COMPUTE_DTYPES}")
        if self.accumulation not in ACCUMULATIONS:
            raise UsageError(
                f"segment {self.segment!r}: accumulation "
                f"{self.accumulation!r} not in {ACCUMULATIONS}")

    @property
    def reduced(self) -> bool:
        return self.compute_dtype != "float64"

    def key(self) -> Tuple[str, str]:
        """The executable-cache key material: what changes the traced
        kernel (dtype + accumulation; budgets/provenance do not)."""
        if not self.reduced:
            return ("float64", "native")
        return (self.compute_dtype, self.accumulation)

    def tag(self) -> str:
        """Human/manifest tag: ``f64`` or e.g. ``f32+dd``."""
        if not self.reduced:
            return "f64"
        return _SHORT[self.compute_dtype] + _ACC_SHORT[self.accumulation]

    def suffix(self) -> str:
        """Executable-name suffix: empty for the f64 default (existing
        warm-pool/AOT names unchanged), ``@<tag>`` for a reduced
        kernel — a pool warmed at one precision can never serve a
        dispatch at another."""
        return "" if not self.reduced else f"@{self.tag()}"

    def to_value(self) -> dict:
        """The JSON decision value the tuning manifest stores."""
        return {"compute_dtype": self.compute_dtype,
                "accumulation": self.accumulation,
                "budget": self.budget, "rel_err": self.rel_err}


def default_spec(segment: str) -> SegmentSpec:
    _require_segment(segment)
    return SegmentSpec(segment=segment)


def _require_segment(segment: str) -> SegmentDef:
    d = SEGMENTS.get(segment)
    if d is None:
        raise UsageError(f"unknown precision segment {segment!r}; "
                         f"known: {sorted(SEGMENTS)}")
    return d


class PrecisionPolicy:
    """A segment -> :class:`SegmentSpec` mapping with an f64 default.

    :meth:`forced` builds the all-segments reduced policy the forced
    CPU demonstration and the acceptance tests install; the empty
    policy (:meth:`f64`) is the explicit everything-full-precision
    override the bench's reference pass uses (it WINS over a manifest,
    unlike no policy at all)."""

    def __init__(self, specs: Optional[Dict[str, SegmentSpec]] = None):
        self.specs: Dict[str, SegmentSpec] = dict(specs or {})
        for name in self.specs:
            _require_segment(name)

    def spec_for(self, segment: str) -> SegmentSpec:
        _require_segment(segment)
        return self.specs.get(segment) or SegmentSpec(segment=segment)

    @classmethod
    def f64(cls) -> "PrecisionPolicy":
        """Everything forced full f64 (the reference-pass override)."""
        return cls({})

    @classmethod
    def forced(cls, compute_dtype: str, accumulation: str = "f64",
               segments: Optional[Tuple[str, ...]] = None
               ) -> "PrecisionPolicy":
        """Every (or the named) segment forced to ``compute_dtype``,
        budgeted at its registered forced budget."""
        if compute_dtype not in COMPUTE_DTYPES:
            raise UsageError(f"compute_dtype {compute_dtype!r} not in "
                             f"{COMPUTE_DTYPES}")
        names = tuple(segments) if segments is not None \
            else tuple(SEGMENTS)
        specs = {}
        for name in names:
            d = _require_segment(name)
            if compute_dtype == "float64":
                continue
            specs[name] = SegmentSpec(
                segment=name, compute_dtype=compute_dtype,
                accumulation=accumulation, budget=d.forced_budget,
                source="forced")
        return cls(specs)


#: the process override policy (None: resolve through the manifest)
_override: Optional[PrecisionPolicy] = None


def active_policy() -> Optional[PrecisionPolicy]:
    return _override


def set_policy(policy: Optional[PrecisionPolicy]) -> None:
    """Install (or clear, with ``None``) the process override policy."""
    global _override
    if policy is not None and not isinstance(policy, PrecisionPolicy):
        raise UsageError(
            f"set_policy takes a PrecisionPolicy or None, got "
            f"{type(policy).__name__}")
    _override = policy


@contextlib.contextmanager
def use_policy(policy: Optional[PrecisionPolicy]):
    """Scoped :func:`set_policy` (tests; the bench's reference pass)."""
    global _override
    prev = _override
    set_policy(policy)
    try:
        yield policy
    finally:
        _override = prev


def override_spec(segment: str) -> Optional[SegmentSpec]:
    """The override policy's spec for ``segment``, or None when no
    override is installed (manifest resolution applies)."""
    if _override is None:
        return None
    return _override.spec_for(segment)


def precision_vkey(segment: str, model=None, toas=None) -> tuple:
    """The manifest vkey for one segment's decision.  Model-bound
    segments carry the full parameter/mask signature + TOA version (the
    solve-rung/correction-dtype discipline: any edit falls back to
    f64); deployment-generic segments carry the kernel schema
    version."""
    d = _require_segment(segment)
    if not d.model_bound:
        return ("precision", segment, 1)
    if model is None or toas is None:
        raise UsageError(
            f"precision segment {segment!r} is model-bound; its vkey "
            "needs (model, toas)")
    from pint_tpu.grid import _model_param_sig

    return ("precision", segment, _model_param_sig(model),
            getattr(toas, "_version", 0), len(toas))


def spec_from_decision(segment: str, value: Any) -> Optional[SegmentSpec]:
    """Validate a manifest decision value into a :class:`SegmentSpec`;
    ``None`` on any malformation (the consumer degrades to f64 — a
    corrupt entry must never pick a dtype)."""
    if not isinstance(value, dict):
        return None
    dt = value.get("compute_dtype")
    acc = value.get("accumulation", "native")
    budget = value.get("budget", 0.0)
    rel = value.get("rel_err")
    if dt not in COMPUTE_DTYPES or acc not in ACCUMULATIONS:
        return None
    if not isinstance(budget, (int, float)) or isinstance(budget, bool) \
            or budget < 0:
        return None
    if rel is not None and (not isinstance(rel, (int, float))
                            or isinstance(rel, bool) or rel < 0):
        return None
    try:
        return SegmentSpec(segment=segment, compute_dtype=dt,
                           accumulation=acc, budget=float(budget),
                           rel_err=None if rel is None else float(rel),
                           source="tuned")
    except UsageError:
        return None


def _emit_applied(spec: SegmentSpec) -> None:
    if config._telemetry_mode == "off":
        return
    from pint_tpu import telemetry

    attrs = {"segment": spec.segment,
             "compute_dtype": spec.compute_dtype,
             "accumulation": spec.accumulation, "source": spec.source,
             "budget": spec.budget}
    if spec.rel_err is not None:
        attrs["rel_err"] = spec.rel_err
    telemetry.lifecycle_event("precision_applied", **attrs)


def segment_spec(segment: str, model=None, toas=None,
                 vkey: Optional[tuple] = None) -> SegmentSpec:
    """The spec a consumer should trace ``segment`` with, resolved
    override -> manifest -> f64 default (see the module docstring).
    Host-side: never call from traced code — resolve at kernel-build
    time and close the spec over the trace."""
    d = _require_segment(segment)
    o = override_spec(segment)
    if o is not None:
        if o.reduced:
            _emit_applied(o)
        return o
    if config.tune_dir() is None:
        return SegmentSpec(segment=segment)
    if segment == "grid.correction":
        # PR 10's probe owns this decision under its legacy manifest
        # key (grid.correction_dtype); ONE source of truth — the spec
        # here simply mirrors what the grid builder would resolve
        if model is None or toas is None:
            return SegmentSpec(segment=segment)
        from pint_tpu import autotune

        dt = autotune.resolve_correction_dtype(model, toas)
        if dt == "float64":
            return SegmentSpec(segment=segment)
        return SegmentSpec(segment=segment, compute_dtype=dt,
                           accumulation="native", budget=d.safe_rel,
                           source="tuned")
    if vkey is None:
        if d.model_bound and (model is None or toas is None):
            # a model-bound segment consulted without its workload
            # cannot be keyed: the safe answer is the default
            return SegmentSpec(segment=segment)
        vkey = precision_vkey(segment, model=model, toas=toas)
    from pint_tpu import autotune

    value, source = autotune.resolve(f"precision.{segment}", vkey, None,
                                     requested=False)
    if source != "tuned" or value is None:
        return SegmentSpec(segment=segment)
    spec = spec_from_decision(segment, value)
    if spec is None:
        return SegmentSpec(segment=segment)
    if spec.reduced:
        _emit_applied(spec)
    return spec


def describe_segments(model=None, toas=None) -> Dict[str, dict]:
    """Resolved spec summary per registered segment (the bench's
    ``precision{segments}`` stamp): model-bound segments resolve with
    the given workload (default f64 when none is supplied)."""
    out: Dict[str, dict] = {}
    for name, d in SEGMENTS.items():
        if d.model_bound and (model is None or toas is None):
            spec = override_spec(name) or SegmentSpec(segment=name)
        else:
            spec = segment_spec(name, model=model, toas=toas)
        out[name] = {"compute_dtype": spec.compute_dtype,
                     "accumulation": spec.accumulation,
                     "source": spec.source, "tag": spec.tag()}
    return out
