"""TOA container + ingestion pipeline + frozen device batch.

Counterpart of reference ``toa.py`` (``get_TOAs`` ``toa.py:109``, ``TOAs``
``toa.py:1183``), redesigned for a host/device split:

* :class:`TOAs` — host-side container of numpy arrays (longdouble times,
  flags, observatory codes) with the one-time pipeline
  ``apply_clock_corrections -> compute_TDBs -> compute_posvels`` (the same
  stages as reference ``toa.py:2184,2251,2323``).
* :class:`TOABatch` — a frozen pytree of device arrays (double-double TDB,
  positions in light-seconds) consumed by jitted model evaluation.  This is
  the natural device boundary: everything ERFA/ephemeris-flavored stays on
  the host exactly as the reference memoizes it in astropy table columns.
"""

from __future__ import annotations

import hashlib
import os
import pickle
from dataclasses import dataclass, field, replace
from fractions import Fraction
from typing import Dict, List, NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

from pint_tpu import c as C_M_S
from pint_tpu.dd import DD, two_prod_np as _two_prod_np, two_sum_np as _two_sum_np
from pint_tpu.exceptions import (
    InvalidTOAError,
    PintPickleError,
    TimSyntaxError,
    TOAIntegrityError,
    UsageError,
)
from pint_tpu.io.tim import RawTOA, format_toa_line, read_tim_file
from pint_tpu.logging import log
from pint_tpu.observatory import get_observatory

__all__ = ["TOA", "TOAs", "TOABatch", "get_TOAs", "get_TOAs_list",
           "get_TOAs_array", "merge_TOAs", "make_single_toa", "build_table",
           "load_pickle", "save_pickle", "read_toa_file"]

C_KM_S = C_M_S / 1e3
DAY_S = 86400.0


import re as _re
from collections.abc import MutableMapping


class FlagDict(MutableMapping):
    """Validated per-TOA flag mapping (reference ``toa.py:932``): string
    keys (stored lowercase, no leading ``-``), single-token string values;
    setting an empty value deletes the flag.  Plain dicts remain accepted
    everywhere flags flow — this class is the validating container for
    user-constructed TOAs."""

    _key_re = _re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*$")

    def __init__(self, *args, **kwargs):
        self.store = {}
        self.update(dict(*args, **kwargs))

    @staticmethod
    def from_dict(d: dict) -> "FlagDict":
        r = FlagDict()
        r.update(d)
        return r

    @staticmethod
    def check_allowed_key(k) -> None:
        if not isinstance(k, str):
            raise InvalidTOAError(f"flag {k!r} must be a string")
        if k.startswith("-"):
            raise InvalidTOAError(
                "flags should be stored without their leading -")
        if not FlagDict._key_re.match(k):
            raise InvalidTOAError(f"flag {k!r} is not a valid flag name")

    @staticmethod
    def check_allowed_value(k, v) -> None:
        if not isinstance(v, str):
            raise InvalidTOAError(f"value {v!r} for flag {k} must be a string")
        if v and len(v.split()) != 1:
            raise InvalidTOAError(
                f"value {v!r} for flag {k} cannot contain whitespace")

    def __setitem__(self, key, val):
        self.check_allowed_key(key)
        self.check_allowed_value(key, val)
        if val:
            self.store[key.lower()] = val
        else:
            self.store.pop(key.lower(), None)

    def __delitem__(self, key):
        del self.store[key.lower()]

    def __getitem__(self, key):
        return self.store[key.lower()]

    def __iter__(self):
        return iter(self.store)

    def __len__(self):
        return len(self.store)

    def __repr__(self):
        return f"FlagDict({self.store!r})"

    def __str__(self):
        return str(self.store)

    def copy(self) -> "FlagDict":
        return FlagDict.from_dict(self.store)


class TOABatch(NamedTuple):
    """Frozen device-side TOA data (a JAX pytree of arrays).

    Positions are in light-seconds (so Roemer delays are plain dot products
    with unit vectors), velocities in ls/s.  ``tdb`` is the double-double
    TDB MJD; ``tdb_s`` is seconds since ``tdb0`` (an arbitrary integer MJD
    near the data midpoint) as a DD pair — the form the spindown polynomial
    consumes.
    """

    tdb: DD          # (N,) MJD, double-double
    tdb0: jnp.ndarray  # scalar reference MJD (integer-valued)
    tdb_s: DD        # (N,) seconds since tdb0, exact host-built pair
    freq: jnp.ndarray  # (N,) MHz
    error_us: jnp.ndarray  # (N,) microseconds
    ssb_obs_pos: jnp.ndarray  # (N,3) light-seconds
    ssb_obs_vel: jnp.ndarray  # (N,3) ls/s
    obs_sun_pos: jnp.ndarray  # (N,3) light-seconds
    planet_pos: dict  # name -> (N,3) light-seconds (obs -> planet)
    pulse_number: Optional[jnp.ndarray] = None  # (N,) or None
    delta_pulse_number: Optional[jnp.ndarray] = None

    @property
    def ntoas(self) -> int:
        return self.freq.shape[0]

    def tdb_seconds(self) -> DD:
        """Seconds since tdb0 as a double-double pair (host-precomputed:
        in-trace day->sec dd arithmetic is not TPU-safe, see dd.py)."""
        return self.tdb_s


@dataclass(eq=False)  # identity hash: TOAs are weak-cache keys in TimingModel
class TOAs:
    """Host-side TOA table (reference ``TOAs``, ``toa.py:1183``)."""

    utc_mjd: np.ndarray  # (N,) longdouble, as-read MJDs (site arrival, UTC-ish)
    error_us: np.ndarray  # (N,) float64
    freq_mhz: np.ndarray  # (N,) float64 (inf for infinite frequency)
    obs: np.ndarray  # (N,) object str — canonical observatory names
    flags: List[Dict[str, str]]
    commands: List = field(default_factory=list)
    filename: Optional[str] = None

    # pipeline products
    clock_corr_s: Optional[np.ndarray] = None
    tdb: Optional[np.ndarray] = None  # longdouble MJD
    #: low-order float64 residual of utc_mjd/tdb on platforms where
    #: longdouble is just double (arm64) — carries the sub-double part of
    #: the parsed MJD so the device-side DD keeps 2^-106 precision.
    utc_mjd_lo: Optional[np.ndarray] = None
    tdb_lo: Optional[np.ndarray] = None
    ssb_obs_pos_km: Optional[np.ndarray] = None
    ssb_obs_vel_kms: Optional[np.ndarray] = None
    obs_sun_pos_km: Optional[np.ndarray] = None
    planet_pos_km: Dict[str, np.ndarray] = field(default_factory=dict)
    ephem: Optional[str] = None
    include_bipm: bool = True
    include_gps: bool = True
    bipm_version: str = "BIPM2021"
    planets: bool = False
    pulse_number: Optional[np.ndarray] = None
    delta_pulse_number: Optional[np.ndarray] = None
    #: quarantine state from :meth:`validate` (True = quarantined); carried
    #: through slicing, merging, adjust_TOAs, and pickling
    quarantine_mask: Optional[np.ndarray] = None
    #: per-TOA list of quarantine reasons (parallel to the rows)
    quarantine_reasons: Optional[List[List[str]]] = None
    #: bumped on every in-place mutation; model caches key on it
    _version: int = 0

    # ------------------------------------------------------------------
    @classmethod
    def from_raw(cls, raw: List[RawTOA], commands=None, filename=None) -> "TOAs":
        n = len(raw)
        err = np.empty(n, dtype=np.float64)
        freq = np.empty(n, dtype=np.float64)
        obs = np.empty(n, dtype=object)
        flags = []
        for i, t in enumerate(raw):
            err[i] = t.error_us
            freq[i] = t.freq_mhz if t.freq_mhz > 0 else np.inf
            obs[i] = get_observatory(t.obs).name
            fl = dict(t.flags)
            if t.name:
                fl.setdefault("name", t.name)
            flags.append(fl)
        utc, utc_lo = cls._mjds_from_raw(raw)
        t = cls(utc, err, freq, obs, flags, commands or [], filename)
        t.utc_mjd_lo = utc_lo
        return t

    @staticmethod
    def _mjds_from_raw(raw: List[RawTOA]):
        """MJD strings -> (longdouble hi, optional float64 lo).

        Platforms whose longdouble is just double (arm64: eps > 2e-19, the
        check the reference makes at ``pulsar_mjd.py:47-59`` before
        refusing to run) route through the native C++ dd parser instead
        (exact to 2^-106) and keep the low-order part as a separate float64
        array — collapsing it into a degraded longdouble would quantize
        TOAs at ~1 us.  x87 platforms use the numpy longdouble parser,
        which is both adequate and faster, and return lo=None."""
        from pint_tpu import native

        longdouble_ok = np.finfo(np.longdouble).eps < 2e-19
        if not longdouble_ok:
            if not native.available():
                log.warning(
                    "longdouble on this platform is only double precision "
                    "and the native dd parser is unavailable; TOA times "
                    "will be quantized at ~1 us (the reference refuses to "
                    "run on such platforms, pulsar_mjd.py:47-59)")
            else:
                hi, lo = native.str2dd_batch(
                    [f"{t.mjd_int}.{t.mjd_frac_str}" for t in raw])
                return (np.asarray(hi, dtype=np.longdouble),
                        np.asarray(lo, dtype=np.float64))
        return np.array([t.mjd_longdouble() for t in raw],
                        dtype=np.longdouble), None

    def __len__(self) -> int:
        return len(self.utc_mjd)

    def __setstate__(self, state):
        """Tolerate pickles written before fields were added (unpickling
        bypasses __init__, so dataclass defaults don't apply)."""
        self.__dict__.update(state)
        from dataclasses import MISSING, fields
        for f_ in fields(type(self)):
            if f_.name not in self.__dict__:
                if f_.default is not MISSING:
                    self.__dict__[f_.name] = f_.default
                elif f_.default_factory is not MISSING:
                    self.__dict__[f_.name] = f_.default_factory()

    @property
    def ntoas(self) -> int:
        return len(self)

    def get_clusters(self, gap_limit_hr: float = 2.0,
                     add_column: bool = False) -> np.ndarray:
        """Cluster TOAs into observing epochs separated by gaps longer than
        ``gap_limit_hr`` hours (reference ``toa.py get_clusters`` /
        ``_cluster_by_gaps``).  Returns the per-TOA cluster index (clusters
        numbered in time order); with ``add_column`` the index is also
        stamped as a ``-cluster`` flag.  Unsorted MJDs are handled (the
        clustering sorts defensively); empty and single-TOA datasets get
        the trivial answer instead of a shape error."""
        if gap_limit_hr <= 0:
            raise UsageError(f"gap_limit_hr must be positive, "
                             f"got {gap_limit_hr}")
        mjds = np.asarray(self.get_mjds(), dtype=np.float64)
        if len(mjds) == 0:
            return np.empty(0, dtype=np.int64)
        order = np.argsort(mjds, kind="stable")
        gaps = np.diff(mjds[order]) > gap_limit_hr / 24.0
        cluster_sorted = np.concatenate([[0], np.cumsum(gaps)])
        clusters = np.empty(len(mjds), dtype=np.int64)
        clusters[order] = cluster_sorted
        if add_column:
            for i, c in enumerate(clusters):
                self.flags[i]["cluster"] = str(int(c))
            self._version += 1
        return clusters

    def __getitem__(self, index) -> "TOAs":
        idx = np.atleast_1d(np.arange(len(self))[index])
        new = replace(
            self,
            utc_mjd=self.utc_mjd[idx],
            error_us=self.error_us[idx],
            freq_mhz=self.freq_mhz[idx],
            obs=self.obs[idx],
            # per-TOA dicts are copied: flag edits on a slice (get_clusters
            # add_column, gui jumps) must not leak into the parent
            flags=[dict(self.flags[i]) for i in idx],
        )
        for name in ("clock_corr_s", "tdb", "utc_mjd_lo", "tdb_lo",
                     "ssb_obs_pos_km", "ssb_obs_vel_kms",
                     "obs_sun_pos_km", "pulse_number", "delta_pulse_number",
                     "quarantine_mask"):
            v = getattr(self, name)
            if v is not None:
                setattr(new, name, v[idx])
        if self.quarantine_reasons is not None:
            new.quarantine_reasons = [list(self.quarantine_reasons[i])
                                      for i in idx]
        new.planet_pos_km = {k: v[idx] for k, v in self.planet_pos_km.items()}
        return new

    # ------------------------------------------------------------------
    # input integrity: validation + quarantine
    # ------------------------------------------------------------------
    def validate(self, policy: Optional[str] = None,
                 check_coverage: bool = True,
                 max_error_us: Optional[float] = None,
                 ephem: Optional[str] = None):
        """Run the TOA integrity checks (:mod:`pint_tpu.integrity`):
        NaN/inf MJDs, non-positive/absurd/non-finite uncertainties,
        duplicate (MJD, obs, freq) rows, and (``check_coverage``) epochs
        outside clock-chain or ephemeris coverage.

        ``strict`` (default ingestion policy) raises
        :class:`~pint_tpu.exceptions.TOAIntegrityError` when anything is
        found; ``lenient`` moves offenders into the quarantine mask with a
        logged summary; ``collect`` quarantines silently.  Returns the
        :class:`~pint_tpu.integrity.QuarantineReport`; the report also
        rides on ``self.last_validation``.
        """
        from pint_tpu.config import ingestion_policy
        from pint_tpu.integrity.quarantine import (
            ABSURD_ERROR_US,
            row_delta,
            run_toa_checks,
        )

        policy = policy or ingestion_policy()
        report = run_toa_checks(
            self, check_coverage=check_coverage,
            max_error_us=ABSURD_ERROR_US if max_error_us is None
            else max_error_us,
            ephem=ephem)
        # typed changed-row delta vs the PREVIOUS APPLIED mask:
        # consumers with derived per-row state (the streaming cache)
        # downdate/update exactly the changed rows instead of
        # invalidating — stamped before the strict raise so even a
        # refused pass reports what changed.  A clean earlier pass
        # stored mask=None, which is NOT "never validated":
        # _applied_validation_n disambiguates — and ONLY passes whose
        # mask was actually applied count (a strict-policy pass that
        # raised never became anyone's baseline), so the first
        # successful validation after a refusal still reports every
        # row as added.
        prev = self.quarantine_mask
        applied_n = getattr(self, "_applied_validation_n", None)
        if prev is None and applied_n is not None:
            # rows beyond the previous pass's length (merged-in since)
            # still report as added
            prev = np.zeros(min(applied_n, len(self)), dtype=bool)
        report.delta = row_delta(prev, report.mask)
        self.last_validation = report
        if report and policy == "strict":
            raise TOAIntegrityError(
                f"TOA validation failed under the strict ingestion "
                f"policy:\n{report.render()}", report=report)
        # the mask always mirrors the LATEST validation: a clean re-run
        # releases rows a previous pass quarantined (repaired data must
        # not stay silently excluded)
        self.quarantine_mask = report.mask if report else None
        self.quarantine_reasons = report.reasons_by_row() if report else None
        self._applied_validation_n = len(self)
        self._version += 1
        if report and policy == "lenient":
            log.warning(report.render())
        return report

    @property
    def n_quarantined(self) -> int:
        m = self.quarantine_mask
        return int(np.sum(m)) if m is not None else 0

    def certified(self) -> "TOAs":
        """The rows :meth:`validate` did not quarantine — the only rows a
        fitter or grid sweep should consume.  Without quarantined rows
        this is ``self`` (no copy)."""
        m = self.quarantine_mask
        if m is None or not np.any(m):
            return self
        return self[~np.asarray(m, dtype=bool)]

    def quarantined(self) -> "TOAs":
        """The quarantined rows (for inspection/repair)."""
        m = self.quarantine_mask
        if m is None:
            return self[np.zeros(len(self), dtype=bool)]
        return self[np.asarray(m, dtype=bool)]

    # ------------------------------------------------------------------
    # pipeline
    # ------------------------------------------------------------------
    def apply_clock_corrections(self, include_gps=True, include_bipm=True,
                                bipm_version="BIPM2021", limits="warn"):
        """Site clock chain + GPS + BIPM + tim TIME offsets (reference
        ``toa.py:2184``)."""
        self.include_gps, self.include_bipm = include_gps, include_bipm
        self.bipm_version = bipm_version
        corr = np.zeros(len(self), dtype=np.float64)
        # 'to' flag: TIME command offsets from the tim file
        for i, fl in enumerate(self.flags):
            if "to" in fl:
                corr[i] += float(fl["to"])
        utc64 = np.asarray(self.utc_mjd, dtype=np.float64)
        for site in np.unique(self.obs):
            m = self.obs == site
            ob = get_observatory(site)
            corr[m] += ob.clock_corrections(
                utc64[m], include_gps=include_gps, include_bipm=include_bipm,
                bipm_version=bipm_version, limits=limits,
            )
        self.clock_corr_s = corr
        self._version += 1
        return self

    def corrected_utc_mjd(self) -> np.ndarray:
        cc = self.clock_corr_s if self.clock_corr_s is not None else 0.0
        return self.utc_mjd + np.asarray(cc, dtype=np.longdouble) / np.longdouble(DAY_S)

    def compute_TDBs(self, method="default", ephem=None):
        """Corrected UTC -> TDB longdouble MJD (reference ``toa.py:2251``)."""
        if self.utc_mjd_lo is not None:
            # pair path (degraded longdouble): apply clock corr + TDB offset
            # in seconds via an error-free transform so no absolute-MJD
            # rounding (ulp(55000) ~ 0.3 us) lands in the hi word
            utc64 = np.asarray(self.utc_mjd, dtype=np.float64)
            cc = (self.clock_corr_s if self.clock_corr_s is not None
                  else np.zeros_like(utc64))
            corr64 = utc64 + cc / DAY_S  # argument precision only
            off = np.empty_like(utc64)
            for site in np.unique(self.obs):
                m = self.obs == site
                off[m] = get_observatory(site).get_TDB_offset_seconds(
                    corr64[m], method=method, ephem=ephem)
            hi, err = _two_sum_np(utc64, (cc + off) / DAY_S)
            hi, lo = _two_sum_np(hi, err + self.utc_mjd_lo)
            self.tdb = np.asarray(hi, dtype=np.longdouble)
            self.tdb_lo = lo
        else:
            utc = self.corrected_utc_mjd()
            tdb = np.empty_like(utc)
            for site in np.unique(self.obs):
                m = self.obs == site
                tdb[m] = get_observatory(site).get_TDBs(utc[m], method=method,
                                                        ephem=ephem)
            self.tdb = tdb
            self.tdb_lo = None
        self._version += 1
        return self

    def compute_posvels(self, ephem="DE440", planets=False):
        """Fill observatory/Sun/planet position columns (reference
        ``toa.py:2323``)."""
        from pint_tpu.ephemeris import load_ephemeris

        if self.tdb is None:
            self.compute_TDBs(ephem=ephem or "DE440")
        self.ephem = ephem or "DE440"
        self.planets = planets
        eph = load_ephemeris(self.ephem)
        n = len(self)
        utc64 = np.asarray(self.corrected_utc_mjd(), dtype=np.float64)
        tdb64 = np.asarray(self.tdb, dtype=np.float64)
        pos = np.empty((n, 3))
        vel = np.empty((n, 3))
        for site in np.unique(self.obs):
            m = self.obs == site
            ob = get_observatory(site)
            if getattr(ob, "needs_flags", False):
                # spacecraft: GCRS position rides in per-TOA flags
                fl = [self.flags[i] for i in np.where(m)[0]]
                pv = ob.posvel_flags(utc64[m], tdb64[m], fl, ephem=self.ephem)
            else:
                pv = ob.posvel(utc64[m], tdb64[m], ephem=self.ephem)
            pos[m], vel[m] = pv.pos, pv.vel
        self.ssb_obs_pos_km, self.ssb_obs_vel_kms = pos, vel
        sun_pos, _ = eph.posvel_ssb("sun", tdb64)
        self.obs_sun_pos_km = sun_pos - pos
        self.planet_pos_km = {}
        if planets:
            for pl in ("jupiter", "saturn", "venus", "uranus", "neptune"):
                ppos, _ = eph.posvel_ssb(pl, tdb64)
                self.planet_pos_km[pl] = ppos - pos
        self._version += 1
        return self

    # ------------------------------------------------------------------
    def get_mjds(self, high_precision=False):
        return self.utc_mjd if high_precision else np.asarray(self.utc_mjd, dtype=np.float64)

    def get_errors(self) -> np.ndarray:
        return self.error_us

    def get_freqs(self) -> np.ndarray:
        return self.freq_mhz

    def get_obss(self) -> np.ndarray:
        return self.obs

    def get_flag_value(self, flag: str, fill_value=None, as_type=None):
        vals = []
        valid = []
        for i, fl in enumerate(self.flags):
            if flag in fl:
                v = fl[flag]
                vals.append(as_type(v) if as_type else v)
                valid.append(i)
            else:
                vals.append(fill_value)
        return vals, valid

    # -- wideband DM data (reference ``residuals.py:1062 get_dm_data``) -----
    @property
    def wideband(self) -> bool:
        """True when every TOA carries a wideband DM measurement flag."""
        return len(self) > 0 and all("pp_dm" in fl for fl in self.flags)

    def get_dms(self) -> Optional[np.ndarray]:
        """Wideband DM measurements (pc/cm^3) from -pp_dm flags, or None."""
        vals, valid = self.get_flag_value("pp_dm", as_type=float)
        if len(valid) != len(self):
            return None
        return np.asarray(vals, dtype=np.float64)

    def get_dm_errors(self) -> Optional[np.ndarray]:
        """Wideband DM uncertainties (pc/cm^3) from -pp_dme flags, or None."""
        vals, valid = self.get_flag_value("pp_dme", as_type=float)
        if len(valid) != len(self):
            return None
        return np.asarray(vals, dtype=np.float64)

    def update_dms(self, dms: np.ndarray, errors: Optional[np.ndarray] = None):
        """Set the wideband DM flags on every TOA (simulation uses this)."""
        for i, fl in enumerate(self.flags):
            fl["pp_dm"] = repr(float(dms[i]))
            if errors is not None:
                fl["pp_dme"] = repr(float(errors[i]))
        self._version = getattr(self, "_version", 0) + 1

    def get_pulse_numbers(self) -> Optional[np.ndarray]:
        if self.pulse_number is not None:
            return self.pulse_number
        vals, valid = self.get_flag_value("pn", as_type=float)
        if len(valid) == len(self):
            return np.asarray(vals, dtype=np.float64)
        if valid:
            log.warning("Some but not all TOAs have pulse-number flags; ignoring")
        return None

    def compute_pulse_numbers(self, model):
        """Assign each TOA the nearest integer pulse number under *model*."""
        ph = model.phase(self, abs_phase=True)
        self.pulse_number = np.asarray(ph.int_) + np.round(np.asarray(ph.frac))
        return self.pulse_number

    def adjust_TOAs(self, delta_seconds: np.ndarray):
        """Shift arrival times in place (simulation uses this)."""
        delta_day = np.asarray(delta_seconds, dtype=np.float64) / DAY_S
        if self.utc_mjd_lo is not None:
            # pair path (degraded longdouble): error-free two_sum keeps the
            # shifted time exact to 2^-106
            hi, lo = _two_sum_np(np.asarray(self.utc_mjd, np.float64),
                                 delta_day)
            hi, lo = _two_sum_np(hi, lo + self.utc_mjd_lo)
            self.utc_mjd = np.asarray(hi, dtype=np.longdouble)
            self.utc_mjd_lo = lo
            if self.tdb is not None:
                hi, lo = _two_sum_np(np.asarray(self.tdb, np.float64),
                                     delta_day)
                hi, lo = _two_sum_np(hi, lo + self.tdb_lo)
                self.tdb = np.asarray(hi, dtype=np.longdouble)
                self.tdb_lo = lo
        else:
            self.utc_mjd = self.utc_mjd + np.asarray(delta_seconds, dtype=np.longdouble) / np.longdouble(DAY_S)
            if self.tdb is not None:
                self.tdb = self.tdb + np.asarray(delta_seconds, dtype=np.longdouble) / np.longdouble(DAY_S)
        self._version += 1
        return self

    def renumber(self):
        return self

    def first_MJD(self) -> float:
        return float(np.min(self.get_mjds()))

    def last_MJD(self) -> float:
        return float(np.max(self.get_mjds()))

    # ------------------------------------------------------------------
    # reference user-API long tail (toa.py:1856-2100)
    # ------------------------------------------------------------------
    @property
    def observatories(self) -> set:
        """Set of observatory names present (reference ``toa.py
        observatories``)."""
        return set(str(o) for o in self.obs)

    def get_Tspan(self) -> float:
        """Total span of the TOAs in days (reference ``get_Tspan``)."""
        m = np.asarray(self.get_mjds(), dtype=np.float64)
        return float(m.max() - m.min()) if len(m) else 0.0

    def get_all_flags(self) -> list:
        """Sorted list of every flag name used (reference
        ``get_all_flags``)."""
        names: set = set()
        for fl in self.flags:
            names |= set(fl)
        return sorted(names)

    def get_flags(self) -> list:
        """The per-TOA flag dictionaries (reference ``get_flags`` returns
        the flags column)."""
        return self.flags

    def get_obs_groups(self):
        """Iterate (observatory name, index array) groups (reference
        ``get_obs_groups``)."""
        obs = np.asarray([str(o) for o in self.obs])
        for name in sorted(set(obs)):
            yield name, np.nonzero(obs == name)[0]

    def get_highest_density_range(self, ndays: float = 7.0):
        """(start, end) MJD of the ``ndays``-wide window holding the most
        TOAs (reference ``get_highest_density_range``)."""
        m = np.sort(np.asarray(self.get_mjds(), dtype=np.float64))
        if not len(m):
            raise UsageError("no TOAs")
        counts = np.searchsorted(m, m + float(ndays), side="right") \
            - np.arange(len(m))
        i = int(np.argmax(counts))
        return m[i], m[i] + float(ndays)

    def is_wideband(self) -> bool:
        """True when every TOA carries wideband DM info (reference
        ``is_wideband``; also available as the ``wideband`` property)."""
        return self.wideband

    def get_summary(self) -> str:
        """Short ASCII summary (reference ``toa.py:1931``)."""
        s = f"Number of TOAs:  {len(self)}\n"
        s += f"Number of commands:  {len(self.commands)}\n"
        s += (f"Number of observatories: {len(self.observatories)} "
              f"{sorted(self.observatories)}\n")
        if len(self):
            s += (f"MJD span:  {self.first_MJD():.3f} to "
                  f"{self.last_MJD():.3f}\n")
        err = np.asarray(self.error_us, dtype=np.float64)
        freq = np.asarray(self.freq_mhz, dtype=np.float64)
        for obs, grp in self.get_obs_groups():
            s += f"{obs} TOAs ({len(grp)}):\n"
            s += f"  Min freq:      {np.min(freq[grp]):.3f} MHz\n"
            s += f"  Max freq:      {np.max(freq[grp]):.3f} MHz\n"
            s += f"  Min error:     {np.min(err[grp]):.3g} us\n"
            s += f"  Max error:     {np.max(err[grp]):.3g} us\n"
            s += f"  Median error:  {np.median(err[grp]):.3g} us\n"
        return s

    def print_summary(self) -> None:
        """Print :meth:`get_summary` (reference ``toa.py:1954``)."""
        print(self.get_summary())

    def phase_columns_from_flags(self) -> None:
        """Populate pulse_number/delta_pulse_number from -pn/-padd flags
        (reference ``toa.py:1959``); raises when no TOA carries -pn."""
        pn, valid = self.get_flag_value("pn", as_type=float)
        if not valid:
            raise InvalidTOAError(
                "No pulse number flags (-pn) found in the TOAs")
        col = np.full(len(self), np.nan)
        for i in valid:
            col[i] = pn[i]
        self.pulse_number = col
        for fl in self.flags:
            fl.pop("pn", None)
        padd, pvalid = self.get_flag_value("padd", as_type=float)
        if pvalid:
            d = np.zeros(len(self))
            for i in pvalid:
                d[i] = padd[i]
            self.delta_pulse_number = d
        self._version = getattr(self, "_version", 0) + 1

    def remove_pulse_numbers(self) -> None:
        """Drop the pulse-number columns (reference
        ``remove_pulse_numbers``)."""
        self.pulse_number = None
        self.delta_pulse_number = None
        self._version = getattr(self, "_version", 0) + 1

    def select(self, selectarray) -> None:
        """In-place boolean selection, undoable with :meth:`unselect`
        (reference ``toa.py:1895``; prefer ``toas[mask]``)."""
        import copy as _copy
        import warnings as _warnings

        _warnings.warn("Please use boolean indexing on the object instead: "
                       "toas[selectarray].", DeprecationWarning)
        if not hasattr(self, "_select_stack"):
            self._select_stack = []
        stack, self._select_stack = self._select_stack, []
        try:
            snapshot = _copy.deepcopy(self)  # stack excluded: O(N) memory
        finally:
            self._select_stack = stack
        self._select_stack.append(snapshot)
        new = self[np.asarray(selectarray)]
        for k, v in new.__dict__.items():
            if k != "_select_stack":
                self.__dict__[k] = v
        self._version = getattr(self, "_version", 0) + 1

    def unselect(self) -> None:
        """Undo the last :meth:`select` (reference ``toa.py:1920``)."""
        import warnings as _warnings

        _warnings.warn("Please use boolean indexing on the object instead.",
                       DeprecationWarning)
        try:
            old = self._select_stack.pop()
        except (AttributeError, IndexError):
            from pint_tpu.logging import log as _log

            _log.error("No previous TOA table found.  No changes made.")
            return
        stack = getattr(self, "_select_stack", [])
        self.__dict__.update(old.__dict__)
        self._select_stack = stack
        self._version = getattr(self, "_version", 0) + 1

    def merge(self, *others) -> "TOAs":
        """Merge other TOAs objects into a new one (reference instance
        method over :func:`merge_TOAs`)."""
        return merge_TOAs([self, *others])

    def to_TOA_list(self) -> list:
        """List of single :class:`TOA` objects (reference
        ``to_TOA_list``)."""
        out = []
        mjds = np.asarray(self.utc_mjd)
        for i in range(len(self)):
            out.append(TOA((float(np.floor(mjds[i])),
                            float(mjds[i] - np.floor(mjds[i]))),
                           error=float(self.error_us[i]),
                           obs=str(self.obs[i]),
                           freq=float(self.freq_mhz[i]),
                           flags=dict(self.flags[i])))
        return out

    def update_all_times(self, ephem=None, planets=None) -> None:
        """Recompute clock corrections, TDBs, and position/velocity columns
        (reference ``update_all_times``); use after editing arrival times
        or site data."""
        self.clock_corr_s = None
        self.apply_clock_corrections(include_gps=self.include_gps,
                                     include_bipm=self.include_bipm,
                                     bipm_version=self.bipm_version)
        self.compute_TDBs(ephem=ephem or self.ephem)
        self.compute_posvels(ephem=ephem or self.ephem or "DE440",
                             planets=self.planets if planets is None
                             else planets)

    def update_mjd_float(self) -> None:
        """Refresh cached float-MJD views (reference ``update_mjd_float``);
        float views are computed on demand here, so only the version
        counter is bumped."""
        self._version = getattr(self, "_version", 0) + 1

    def check_hashes(self, timfile: Optional[str] = None) -> bool:
        """True when the source tim files are unchanged since this object
        was built (reference ``toa.py:1856``; the pickle cache uses the
        same hashes)."""
        src = timfile or self.filename
        if not src:
            return True
        try:
            current = _tim_hashes(src)
        except OSError:
            return False
        stored = getattr(self, "_hashes", None)
        if stored is None:
            # nothing recorded at load (e.g. object built programmatically):
            # edits since load are undetectable — say so instead of
            # pretending to verify
            raise UsageError(
                "No source hashes were recorded when this TOAs object was "
                "built; cannot verify against the tim file")
        return stored == current

    # ------------------------------------------------------------------
    def to_batch(self, tdb0: Optional[float] = None) -> TOABatch:
        """Freeze into a device pytree (light-second units, dd times)."""
        if self.tdb is None:
            raise UsageError(
                "Run compute_TDBs/compute_posvels before to_batch()")
        if self.ssb_obs_pos_km is None:
            raise UsageError("Run compute_posvels before to_batch()")
        if tdb0 is None:
            tdb0 = float(np.round(np.mean(np.asarray(self.tdb, dtype=np.float64))))
        planet = {
            k: jnp.asarray(v / C_KM_S) for k, v in self.planet_pos_km.items()
        }
        pn = None if self.pulse_number is None else jnp.asarray(self.pulse_number)
        dpn = None if self.delta_pulse_number is None else jnp.asarray(self.delta_pulse_number)
        if self.tdb_lo is not None:
            # degraded-longdouble platform: rebuild the exact pair carried
            # from the native parser instead of the (lossy) longdouble column
            hi, lo = _two_sum_np(np.asarray(self.tdb, np.float64), self.tdb_lo)
        else:
            hi = np.asarray(self.tdb, dtype=np.float64)
            lo = np.asarray(self.tdb - hi.astype(np.longdouble), dtype=np.float64)
        tdb_dd = DD(jnp.asarray(hi), jnp.asarray(lo))
        # seconds since tdb0 as an exact host-built pair (pure-numpy EFTs:
        # device-side day->sec dd conversion is unsafe under TPU f64 excess
        # precision, see dd.py)
        d_hi = hi - tdb0  # same-scale MJDs: Sterbenz-exact
        s_hi, s_err = _two_prod_np(d_hi, DAY_S)
        s_hi, s_err2 = _two_sum_np(s_hi, s_err + lo * DAY_S)
        tdb_s = DD(jnp.asarray(s_hi), jnp.asarray(s_err2))
        return TOABatch(
            tdb=tdb_dd,
            tdb0=jnp.float64(tdb0),
            tdb_s=tdb_s,
            freq=jnp.asarray(self.freq_mhz),
            error_us=jnp.asarray(self.error_us),
            ssb_obs_pos=jnp.asarray(self.ssb_obs_pos_km / C_KM_S),
            ssb_obs_vel=jnp.asarray(self.ssb_obs_vel_kms / C_KM_S),
            obs_sun_pos=jnp.asarray(self.obs_sun_pos_km / C_KM_S),
            planet_pos=planet,
            pulse_number=pn,
            delta_pulse_number=dpn,
        )

    # ------------------------------------------------------------------
    def write_TOA_file(self, path, name="pint_tpu", format="tempo2"):
        """Write a .tim file (reference ``toa.py`` TOAs.write_TOA_file)."""
        with open(path, "w") as f:
            if format.lower() in ("tempo2", "1"):
                f.write("FORMAT 1\n")
            for i in range(len(self)):
                ii, frac = _mjd_line_parts(
                    self.utc_mjd[i],
                    self.utc_mjd_lo[i] if self.utc_mjd_lo is not None
                    else None)
                fl = dict(self.flags[i])
                nm = fl.pop("name", name)
                f.write(format_toa_line(
                    ii, frac, self.error_us[i], self.freq_mhz[i],
                    self.obs[i], name=nm, flags=fl, fmt=format))

    def save_pickle(self, path):
        with open(path, "wb") as f:
            pickle.dump(self, f)

    @staticmethod
    def load_pickle(path) -> "TOAs":
        with open(path, "rb") as f:
            return pickle.load(f)


def _file_hash(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        h.update(f.read())
    return h.hexdigest()


def parse_clock_bipm(clock_value):
    """(include_bipm, bipm_version|None) implied by a par-file CLOCK value
    (reference model_builder/toa CLK handling).  include_bipm is None when
    the CLOCK string decides nothing."""
    clk = str(clock_value or "").upper()
    if clk.startswith("TT(BIPM"):
        ver = clk[3:].rstrip(")")
        return True, (ver if ver and ver != "BIPM" else None)
    if clk in ("TT(TAI)", "UTC(NIST)", "TT"):
        return False, None
    return None, None


def _resolve_pipeline_options(model, ephem, planets, include_bipm,
                              bipm_version):
    """Fill ephem/planets/BIPM settings from the model the way get_TOAs
    does (single source of truth for every TOAs constructor)."""
    if model is not None:
        if ephem is None and getattr(model, "EPHEM", None) is not None:
            ephem = str(model.EPHEM.value)
        if include_bipm is None and getattr(model, "CLOCK", None) is not None:
            include_bipm, ver = parse_clock_bipm(model.CLOCK.value)
            if ver:
                bipm_version = ver
        if planets is False and getattr(model, "PLANET_SHAPIRO", None) is not None:
            planets = bool(model.PLANET_SHAPIRO.value)
    if include_bipm is None:
        include_bipm = True
    return ephem, planets, include_bipm, bipm_version


def _finalize_toas(t: TOAs, ephem, planets, include_gps, include_bipm,
                   bipm_version, limits) -> TOAs:
    """Run the post-parse ingestion pipeline (clock chain, TDB, posvels)."""
    t.apply_clock_corrections(include_gps=include_gps,
                              include_bipm=include_bipm,
                              bipm_version=bipm_version, limits=limits)
    t.compute_TDBs(ephem=ephem or "DE440")
    t.compute_posvels(ephem=ephem or "DE440", planets=planets)
    return t


def get_TOAs(timfile: str, ephem: Optional[str] = None, planets: bool = False,
             include_gps: bool = True, include_bipm: Optional[bool] = None,
             bipm_version: str = "BIPM2021", model=None, limits: str = "warn",
             usepickle: bool = False, policy: Optional[str] = None,
             validate: bool = True) -> TOAs:
    """Load a tim file and run the full ingestion pipeline (reference
    ``toa.py:109``).

    ``policy`` overrides the process-wide ingestion policy for both the
    tim parse and the post-parse :meth:`TOAs.validate` structural checks
    (NaN/zero-error/duplicate rows quarantined in lenient/collect mode,
    typed errors in strict mode).  The parse's
    :class:`~pint_tpu.integrity.Diagnostics` report rides on the result
    as ``.ingest_diagnostics``.  ``validate=False`` skips the integrity
    pass (the parse policy still applies).
    """
    from pint_tpu.config import ingestion_policy
    from pint_tpu.integrity.diagnostics import Diagnostics

    ephem, planets, include_bipm, bipm_version = _resolve_pipeline_options(
        model, ephem, planets, include_bipm, bipm_version)
    # resolve the policy HERE so the pickle cache keys on the policy that
    # actually applied (a later set_ingestion_policy must miss the cache)
    policy = policy or ingestion_policy()
    pickle_key = (ephem, planets, include_gps, include_bipm, bipm_version,
                  limits, policy, validate)
    if usepickle:
        t = _load_toa_pickle(timfile, pickle_key)
        if t is not None:
            log.info(f"Loaded {len(t)} TOAs from pickle cache for {timfile}")
            return t
    diags = Diagnostics(timfile)
    raw, commands = read_tim_file(timfile, policy=policy, diagnostics=diags)
    if not raw:
        raise TimSyntaxError("no TOAs found in file", file=timfile)
    t = TOAs.from_raw(raw, commands, filename=timfile)
    t.ingest_diagnostics = diags
    # record source hashes at LOAD time so check_hashes can detect edits
    try:
        t._hashes = _tim_hashes(timfile)
    except OSError:
        pass
    if validate:
        # structural checks only: coverage checks need the clock/ephemeris
        # machinery and stay opt-in via an explicit t.validate() call
        t.validate(policy=policy, check_coverage=False)
    _finalize_toas(t, ephem, planets, include_gps, include_bipm,
                   bipm_version, limits)
    log.info(f"Loaded {len(t)} TOAs from {timfile} "
             f"(ephem={t.ephem}, planets={planets}, bipm={include_bipm})")
    if usepickle:
        _save_toa_pickle(timfile, pickle_key, t)
    return t


class TOA:
    """A single time of arrival (reference ``toa.py TOA``): programmatic
    construction unit for :func:`get_TOAs_list`.

    ``mjd`` may be a float MJD, an ``(int_part, frac_part)`` pair of floats
    carried at full combined precision, or an ``"58000.0000123..."``
    string.  Remaining attributes mirror the tim columns.
    """

    def __init__(self, mjd, error: float = 0.0, obs: str = "bary",
                 freq: float = float("inf"), scale=None, flags=None,
                 name: str = "unk", **kwargs):
        self.mjd = mjd
        self.error = float(error)
        self.obs = obs
        self.freq = float(freq)
        if scale not in (None, "utc"):
            # silently reinterpreting e.g. tdb input as site-UTC would shift
            # the time by ~69 s through the clock chain; refuse loudly
            raise NotImplementedError(
                f"TOA scale={scale!r} is not supported: times are site-UTC "
                "(the tim-file convention). Convert to UTC first.")
        self.scale = scale
        self.flags = dict(flags or {})
        for k, v in kwargs.items():  # reference accepts flags as kwargs
            self.flags.setdefault(k.lstrip("-"), str(v))
        self.name = name

    def __str__(self):
        return (f"{self.mjd}: {self.error} us error at '{self.obs}' at "
                f"{self.freq} MHz")

    def as_line(self) -> str:
        """This TOA as a tempo2-format tim line (same lossless emitter as
        ``TOAs.write_TOA_file``)."""
        hi, lo = _split_mjd_value(self.mjd)
        mjd_i, frac = _mjd_line_parts(hi, lo if lo else None)
        return format_toa_line(mjd_i, frac, self.error, self.freq, self.obs,
                               flags=self.flags, name=self.name)


def _mjd_line_parts(mjd, lo=None):
    """(longdouble hi, optional float64 lo) MJD -> (int day, fraction
    digits) for tim-line formatting.  With a lo word (degraded-longdouble
    platforms) the Fraction path emits the full (hi, lo) value so a
    write/read round trip through the native dd parser is lossless;
    otherwise the longdouble fraction is printed to 16 digits.  Shared by
    ``TOAs.write_TOA_file`` and ``TOA.as_line``."""
    ii = int(np.floor(mjd))
    if lo:
        fr = Fraction(float(mjd)) - ii + Fraction(float(lo))
        if fr < 0:  # lo may push just below the floor of hi
            ii -= 1
            fr += 1
        digits = 25
        q = round(fr * 10**digits)
        frac = f"{q:0{digits}d}".rstrip("0")
    else:
        ff = np.format_float_positional(mjd - ii, precision=16, trim="-")
        if ff.startswith("1"):  # fraction rounded up to the next day
            return ii + 1, "0"
        frac = ff.split(".")[1] if "." in ff else "0"
    return ii, frac or "0"


def _pair_split(a, b):
    """(mjd1, mjd2) arrays/scalars -> (longdouble hi, float64 lo) with the
    low-order word preserved on degraded-longdouble platforms.  Single
    implementation shared by the scalar and array construction paths."""
    hi = np.asarray(a, dtype=np.longdouble) + np.asarray(b, dtype=np.longdouble)
    if np.finfo(np.longdouble).eps > 2e-19:
        # error-free transform via the shared audited primitive
        s, lo = _two_sum_np(np.asarray(a, dtype=np.float64),
                            np.asarray(b, dtype=np.float64))
    else:
        lo = np.zeros_like(np.asarray(hi, dtype=np.float64))
    return hi, lo


def _split_mjd_value(mjd):
    """float | (i, f) pair | str -> (longdouble hi, float64 lo)."""
    if isinstance(mjd, (tuple, list)) and len(mjd) == 2:
        hi, lo = _pair_split(mjd[0], mjd[1])
        return np.longdouble(hi), float(lo)
    if isinstance(mjd, str):
        i, _, f = mjd.partition(".")
        r = RawTOA(mjd_int=int(i), mjd_frac_str=f or "0", error_us=0.0,
                   freq_mhz=0.0, obs="bary")
        if np.finfo(np.longdouble).eps > 2e-19:
            # degraded longdouble: the native dd parser preserves the
            # sub-double part, same as the tim-file path (_mjds_from_raw)
            from pint_tpu import native

            if native.available():
                hi_, lo_ = native.str2dd_batch([f"{r.mjd_int}."
                                                f"{r.mjd_frac_str}"])
                return np.longdouble(hi_[0]), float(lo_[0])
        return r.mjd_longdouble(), 0.0
    return np.longdouble(mjd), 0.0


def get_TOAs_list(toa_list, ephem: Optional[str] = None,
                  planets: bool = False, include_gps: bool = True,
                  include_bipm: Optional[bool] = None,
                  bipm_version: str = "BIPM2021", model=None,
                  limits: str = "warn", commands=None) -> TOAs:
    """Build and prepare a TOAs object from :class:`TOA` objects (reference
    ``toa.py get_TOAs_list``): same pipeline as :func:`get_TOAs` without a
    tim file."""
    ephem, planets, include_bipm, bipm_version = _resolve_pipeline_options(
        model, ephem, planets, include_bipm, bipm_version)
    t = build_table(toa_list, commands=commands)
    return _finalize_toas(t, ephem, planets, include_gps, include_bipm,
                          bipm_version, limits)


def build_table(toa_list, filename: Optional[str] = None,
                commands=None) -> TOAs:
    """Columnar :class:`TOAs` store from :class:`TOA` objects (reference
    ``toa.py:859 build_table``).  The reference returns the astropy Table
    backing a TOAs object; here the columnar store *is* the TOAs object, so
    this returns an un-finalized ``TOAs`` (no clock/ephemeris pipeline run —
    pass it through :func:`get_TOAs_list` or ``_finalize_toas`` for that)."""
    n = len(toa_list)
    if n == 0:
        raise InvalidTOAError("build_table: empty TOA list")
    utc = np.empty(n, dtype=np.longdouble)
    lo = np.zeros(n, dtype=np.float64)
    err = np.empty(n, dtype=np.float64)
    freq = np.empty(n, dtype=np.float64)
    obs = np.empty(n, dtype=object)
    flags = []
    for i, tt in enumerate(toa_list):
        utc[i], lo[i] = _split_mjd_value(tt.mjd)
        err[i] = tt.error
        freq[i] = tt.freq if tt.freq > 0 else np.inf
        obs[i] = get_observatory(tt.obs).name
        fl = dict(tt.flags)
        if tt.name and tt.name != "unk":
            fl.setdefault("name", tt.name)
        flags.append(fl)
    t = TOAs(utc, err, freq, obs, flags, list(commands or []), filename)
    if np.any(lo):
        t.utc_mjd_lo = lo
    return t


def get_TOAs_array(times, obs: str, errors=1.0, freqs=np.inf, flags=None,
                   ephem: Optional[str] = None, planets: bool = False,
                   include_gps: bool = True,
                   include_bipm: Optional[bool] = None,
                   bipm_version: str = "BIPM2021", model=None,
                   limits: str = "warn", **kwargs) -> TOAs:
    """Build and prepare TOAs from arrays at a single observatory
    (reference ``toa.py:2729``).  ``times`` is an MJD array or an
    ``(mjd1, mjd2)`` pair of arrays summing to full precision; scalar
    ``errors``/``freqs`` broadcast; ``flags`` is one dict for all TOAs or a
    list of per-TOA dicts.  Remaining kwargs become shared flags."""
    ephem, planets, include_bipm, bipm_version = _resolve_pipeline_options(
        model, ephem, planets, include_bipm, bipm_version)
    if isinstance(times, tuple) and len(times) == 2:
        # (mjd1, mjd2) pair — scalar pairs are one TOA, array pairs are
        # elementwise (a 2-element *list* is two independent TOAs)
        hi, lo = _pair_split(times[0], times[1])
        utc = np.atleast_1d(hi)
        lo = np.atleast_1d(lo)
    else:
        utc = np.atleast_1d(np.asarray(times, dtype=np.longdouble))
        lo = None
    n = len(utc)
    err = np.broadcast_to(np.asarray(errors, dtype=np.float64), (n,)).copy()
    freq = np.broadcast_to(np.asarray(freqs, dtype=np.float64), (n,)).copy()
    freq[freq <= 0] = np.inf
    site = get_observatory(obs).name
    obs_arr = np.full(n, site, dtype=object)
    if flags is None:
        flag_list = [dict() for _ in range(n)]
    elif isinstance(flags, dict):
        flag_list = [dict(flags) for _ in range(n)]
    else:
        if len(flags) != n:
            raise InvalidTOAError("flags list length must match times")
        flag_list = [dict(f) for f in flags]
    for k, v in kwargs.items():
        for f in flag_list:
            f.setdefault(k.lstrip("-"), str(v))
    t = TOAs(utc, err, freq, obs_arr, flag_list, [], None)
    if lo is not None and np.any(lo):
        t.utc_mjd_lo = np.asarray(lo, dtype=np.float64)
    return _finalize_toas(t, ephem, planets, include_gps, include_bipm,
                          bipm_version, limits)


def load_pickle(toafilename: str,
                picklefilename: Optional[str] = None) -> "TOAs":
    """Load pickled TOAs, un-gzipping if necessary (reference
    ``toa.py:333``): tries ``<name>.pickle.gz``, ``<name>.pickle``, and
    the bare name unless an explicit pickle path is given.  Content is
    sniffed (gzip magic), so a gzipped pickle under any name loads; an
    unreadable candidate falls through to the next."""
    import gzip

    candidates = ([picklefilename] if picklefilename is not None else
                  [toafilename + ".pickle.gz", toafilename + ".pickle",
                   toafilename])
    for cand in candidates:
        if not os.path.exists(cand):
            continue
        try:
            with open(cand, "rb") as f:
                gzipped = f.read(2) == b"\x1f\x8b"
            opener = gzip.open if gzipped else open
            with opener(cand, "rb") as f:
                return pickle.load(f)
        except (OSError, EOFError, pickle.UnpicklingError, ValueError):
            continue  # e.g. a truncated .gz next to a valid .pickle
    raise PintPickleError(f"No readable pickle found for {toafilename}")


def save_pickle(toas: "TOAs", picklefilename: Optional[str] = None) -> None:
    """Write TOAs to a ``.pickle.gz`` (reference ``toa.py:373``); the
    default name derives from the TOAs' source tim file.  Merged TOAs
    (no single source file) require an explicit name."""
    import gzip

    if picklefilename is None:
        if not toas.filename:
            raise UsageError(
                "TOAs have no (single) source filename; please provide "
                "picklefilename")
        picklefilename = str(toas.filename) + ".pickle.gz"
    opener = gzip.open if str(picklefilename).endswith(".gz") else open
    with opener(picklefilename, "wb") as f:
        pickle.dump(toas, f)


def read_toa_file(filename):
    """(raw TOAs, commands) from a tim file — reference ``toa.py:701``
    naming for :func:`pint_tpu.io.tim.read_tim_file`."""
    return read_tim_file(filename)


PICKLE_SUFFIX = ".pint_tpu_toas.pickle"


def _tim_file_set(timfile: str, _seen=None) -> List[str]:
    """The tim file plus every (recursively) INCLUDEd file, resolved the
    same way the parser resolves them (reference ``check_hashes`` covers all
    constituent files, ``toa.py:1856``)."""
    _seen = _seen if _seen is not None else []
    if timfile in _seen or not os.path.exists(timfile):
        return _seen
    _seen.append(timfile)
    with open(timfile) as f:
        for ln in f:
            fields = ln.split()
            if len(fields) >= 2 and fields[0].upper() == "INCLUDE":
                _tim_file_set(os.path.join(os.path.dirname(timfile),
                                           fields[1]), _seen)
    return _seen


def _tim_hashes(timfile: str) -> Dict[str, str]:
    return {p: _file_hash(p) for p in _tim_file_set(timfile)}


def _load_toa_pickle(timfile: str, key) -> Optional[TOAs]:
    """Hash-invalidated TOA pickle cache (reference ``toa.py:333,373`` load
    path + ``check_hashes`` ``toa.py:1856``): the cache is served only when
    the SHA256 of the tim file *and every INCLUDEd file* and the pipeline
    settings all match."""
    import pickle

    path = timfile + PICKLE_SUFFIX
    if not os.path.exists(path):
        return None
    try:
        with open(path, "rb") as f:
            d = pickle.load(f)
        if d.get("tim_sha") != _tim_hashes(timfile) or d.get("key") != key:
            log.info(f"TOA pickle cache for {timfile} is stale; rebuilding")
            return None
        return d["toas"]
    except Exception as e:
        log.warning(f"Failed to read TOA pickle {path}: {e}")
        return None


def _save_toa_pickle(timfile: str, key, t: TOAs) -> None:
    import pickle

    path = timfile + PICKLE_SUFFIX
    try:
        with open(path, "wb") as f:
            pickle.dump({"tim_sha": _tim_hashes(timfile), "key": key,
                         "toas": t}, f)
    except OSError as e:  # read-only data dir: cache is best-effort
        log.warning(f"Could not write TOA pickle {path}: {e}")


def _merge_time_pair(toas_list, hi_name, lo_name):
    """Merged (hi, lo) columns under the invariant: when a lo column is
    present, hi is exactly a double.  Inputs lacking a lo column (x87
    longdouble builds) contribute the sub-double part of their longdouble as
    lo and a truncated hi, so no precision is lost on either side."""
    new_hi, new_lo = [], []
    for t in toas_list:
        h, v = getattr(t, hi_name), getattr(t, lo_name)
        if v is not None:
            new_hi.append(h)
            new_lo.append(v)
        else:
            h64 = np.asarray(h, np.float64)
            new_hi.append(h64.astype(np.longdouble))
            new_lo.append(np.asarray(h - h64.astype(np.longdouble),
                                     dtype=np.float64))
    return np.concatenate(new_hi), np.concatenate(new_lo)


def merge_TOAs(toas_list: List[TOAs]) -> TOAs:
    """Concatenate TOAs containers (reference ``toa.py merge_TOAs``)."""
    first = toas_list[0]
    utc_pair = any(t.utc_mjd_lo is not None for t in toas_list)
    if utc_pair:
        utc_hi, utc_lo = _merge_time_pair(toas_list, "utc_mjd", "utc_mjd_lo")
    else:
        utc_hi = np.concatenate([t.utc_mjd for t in toas_list])
        utc_lo = None
    out = replace(
        first,
        utc_mjd=utc_hi,
        error_us=np.concatenate([t.error_us for t in toas_list]),
        freq_mhz=np.concatenate([t.freq_mhz for t in toas_list]),
        obs=np.concatenate([t.obs for t in toas_list]),
        flags=[fl for t in toas_list for fl in t.flags],
    )
    out.utc_mjd_lo = utc_lo
    tdb_pair = (any(t.tdb_lo is not None for t in toas_list)
                and all(t.tdb is not None for t in toas_list))
    if tdb_pair:
        out.tdb, out.tdb_lo = _merge_time_pair(toas_list, "tdb", "tdb_lo")
    else:
        out.tdb_lo = None
    for name in ("clock_corr_s", "ssb_obs_pos_km", "ssb_obs_vel_kms",
                 "obs_sun_pos_km", "pulse_number", "delta_pulse_number") \
            + (() if tdb_pair else ("tdb",)):
        vals = [getattr(t, name) for t in toas_list]
        setattr(out, name, np.concatenate(vals) if all(v is not None for v in vals) else None)
    out.planet_pos_km = {}
    if all(t.planet_pos_km.keys() == first.planet_pos_km.keys() for t in toas_list):
        for k in first.planet_pos_km:
            out.planet_pos_km[k] = np.concatenate([t.planet_pos_km[k] for t in toas_list])
    # quarantine state is carried: inputs without a mask contribute
    # all-certified rows
    if any(t.quarantine_mask is not None for t in toas_list):
        out.quarantine_mask = np.concatenate([
            t.quarantine_mask if t.quarantine_mask is not None
            else np.zeros(len(t), dtype=bool) for t in toas_list])
        out.quarantine_reasons = []
        for t in toas_list:
            out.quarantine_reasons.extend(
                [list(r) for r in t.quarantine_reasons]
                if t.quarantine_reasons is not None
                else [[] for _ in range(len(t))])
    else:
        out.quarantine_mask = None
        out.quarantine_reasons = None
    if len(toas_list) > 1:
        # no single source file: save_pickle must demand an explicit name
        # rather than silently writing under the first input's name
        out.filename = None
    return out


def make_single_toa(mjd, obs: str, freq_mhz: float = np.inf,
                    error_us: float = 0.0, ephem: str = "DE440",
                    include_gps=True, include_bipm=True,
                    bipm_version="BIPM2021", planets=False) -> TOAs:
    """Build a one-TOA TOAs (for TZR reference TOAs, reference
    ``absolute_phase.py:130 make_TZR_toa``)."""
    utc = np.array([mjd], dtype=np.longdouble)
    t = TOAs(
        utc_mjd=utc,
        error_us=np.array([error_us]),
        freq_mhz=np.array([freq_mhz if freq_mhz and freq_mhz > 0 else np.inf]),
        obs=np.array([get_observatory(obs).name], dtype=object),
        flags=[{"tzr": "True"}],
    )
    t.apply_clock_corrections(include_gps=include_gps, include_bipm=include_bipm,
                              bipm_version=bipm_version)
    t.compute_TDBs(ephem=ephem)
    t.compute_posvels(ephem=ephem, planets=planets)
    return t
