"""Photon event file -> TOAs conversion for X-ray/gamma-ray missions.

Counterpart of reference ``event_toas.py:75,315`` (``load_fits_TOAs`` /
``get_fits_TOAs`` / per-mission ``get_event_TOAs`` wrappers).  Mission
defaults mirror the reference's built-in config (extension names, energy
columns, default uncertainties); MJDREF/TIMESYS/TIMEREF are read from the
event header itself, as the reference does.

TIMEREF handling:
* SOLARSYSTEM (barycentered, TIMESYS=TDB) -> obs='barycenter'
* GEOCENTRIC -> obs='geocenter'
* LOCAL -> needs a satellite observatory with an orbit file
  (:func:`pint_tpu.observatory.satellite_obs.get_satellite_observatory`).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from pint_tpu.fits_utils import get_hdu, read_fits
from pint_tpu.logging import log
from pint_tpu.toa import TOAs

__all__ = ["load_fits_TOAs", "load_event_TOAs", "get_fits_TOAs",
           "get_event_TOAs", "get_NICER_TOAs", "get_NuSTAR_TOAs",
           "get_XMM_TOAs", "get_RXTE_TOAs", "get_Swift_TOAs",
           "get_IXPE_TOAs", "check_timesys", "check_timeref",
           "create_mission_config", "read_mission_info_from_heasoft"]

#: default per-photon uncertainty in us (reference ``event_toas.py:44``)
_default_uncertainty = {
    "NICER": 0.1, "RXTE": 2.5, "XMM": 48.0, "NuSTAR": 65.0, "IXPE": 20.0,
    "default": 1.0,
}

#: mission name -> (extension, energy column, obs alias for LOCAL times)
MISSION_CONFIG: Dict[str, dict] = {
    "generic": {"fits_extension": "EVENTS", "ecol": "PI", "obs": ""},
    "nicer": {"fits_extension": "EVENTS", "ecol": "PI", "obs": "NICER"},
    "nustar": {"fits_extension": "EVENTS", "ecol": "PI", "obs": "NuSTAR"},
    "xmm": {"fits_extension": "EVENTS", "ecol": "PI", "obs": "XMM"},
    "xte": {"fits_extension": "XTE_SE", "ecol": "PHA", "obs": "RXTE"},
    "swift": {"fits_extension": "EVENTS", "ecol": "PI", "obs": "Swift"},
    "ixpe": {"fits_extension": "EVENTS", "ecol": "PI", "obs": "IXPE"},
    "fermi": {"fits_extension": "EVENTS", "ecol": "ENERGY", "obs": "Fermi"},
}


VALID_TIMESYS = ("TT", "TDB")
VALID_TIMEREF = ("LOCAL", "GEOCENTRIC", "SOLARSYSTEM")


def check_timesys(timesys: str) -> None:
    """Raise unless *timesys* is TT or TDB (reference ``event_toas.py:220``)."""
    if timesys not in VALID_TIMESYS:
        raise ValueError("Timesys has to be TDB or TT")


def check_timeref(timeref: str) -> None:
    """Raise for an unsupported TIMEREF (reference ``event_toas.py:225``)."""
    if timeref not in VALID_TIMEREF:
        raise ValueError("Timeref is invalid")


def read_mission_info_from_heasoft() -> dict:
    """Mission defaults from a HEASOFT install's xselect.mdb when $HEADAS
    is set (reference ``event_toas.py:75``); {} otherwise — this deployment
    ships no HEASOFT, so the built-in MISSION_CONFIG is the source."""
    import os

    headas = os.getenv("HEADAS")
    if not headas:
        return {}
    fname = os.path.join(headas, "bin", "xselect.mdb")
    if not os.path.exists(fname):
        return {}
    info: dict = {}
    with open(fname) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("!"):
                continue
            key, _, value = line.partition(" ")
            parts = key.split(":")
            if len(parts) < 2:
                continue
            mission = parts[0].lower()
            info.setdefault(mission, {})[":".join(parts[1:])] = value.strip()
    return info


def create_mission_config() -> dict:
    """Built-in mission configurations merged with any HEASOFT xselect.mdb
    entries (reference ``event_toas.py:117``)."""
    config = {m: dict(c) for m, c in MISSION_CONFIG.items()}
    for mission, d in read_mission_info_from_heasoft().items():
        cfg = config.setdefault(mission, {"fits_extension": "EVENTS",
                                          "ecol": "PI", "obs": mission})
        if "events" in d:
            cfg["fits_extension"] = d["events"]
        ecol = d.get("ecol")
        if ecol:
            cfg["ecol"] = ecol
    return config


def _timesys(hdr) -> str:
    ts = str(hdr.get("TIMESYS", "")).strip().upper()
    check_timesys(ts)
    return ts


def _timeref(hdr) -> str:
    tr = str(hdr.get("TIMEREF", "LOCAL")).strip().upper()
    check_timeref(tr)
    return tr


def load_event_TOAs(eventname: str, mission: str, weights=None,
                    minmjd: float = -np.inf, maxmjd: float = np.inf,
                    errors: Optional[float] = None):
    """Raw (mjds, energies, weights, timesys, timeref, errors) from a
    mission event file (reference ``event_toas.py:455``; alias of
    :func:`load_fits_TOAs` with mission-config defaults)."""
    return load_fits_TOAs(eventname, mission=mission, weights=weights,
                          minmjd=minmjd, maxmjd=maxmjd, errors=errors)


def load_fits_TOAs(eventname: str, mission: str = "generic",
                   weights=None, extension: Optional[str] = None,
                   timesys: Optional[str] = None, timeref: Optional[str] = None,
                   minmjd: float = -np.inf, maxmjd: float = np.inf,
                   errors: Optional[float] = None):
    """Read a photon event FITS file into raw (mjd, flags) lists
    (reference ``event_toas.py:245``)."""
    config = create_mission_config()  # built-ins + any HEASOFT xselect.mdb
    cfg = config.get(mission.lower(), config["generic"])
    extension = extension or cfg["fits_extension"]
    hdus = read_fits(eventname)
    hdu = get_hdu(hdus, extension)
    hdr = hdu.header
    ts = timesys or _timesys(hdr)
    tr = timeref or _timeref(hdr)
    from pint_tpu.fits_utils import read_fits_event_mjds

    mjds = read_fits_event_mjds(hdu)
    data = hdu.data()
    energies = data.get(cfg["ecol"])
    keep = (np.asarray(mjds, dtype=np.float64) >= minmjd) & \
           (np.asarray(mjds, dtype=np.float64) <= maxmjd)
    mjds = mjds[keep]
    if energies is not None:
        energies = np.asarray(energies, dtype=np.float64)[keep]
    if weights is not None:
        weights = np.asarray(weights, dtype=np.float64)[keep]
    if errors is None:
        errors = _default_uncertainty.get(cfg.get("obs", ""),
                                          _default_uncertainty["default"])
    return mjds, energies, weights, ts, tr, errors


def get_fits_TOAs(eventname: str, mission: str = "generic", weights=None,
                  extension: Optional[str] = None,
                  timesys: Optional[str] = None, timeref: Optional[str] = None,
                  minmjd: float = -np.inf, maxmjd: float = np.inf,
                  errors: Optional[float] = None, ephem: Optional[str] = None,
                  planets: bool = False) -> TOAs:
    """Photon event file -> TOAs (reference ``event_toas.py:315``)."""
    mjds, energies, weights, ts, tr, errors = load_fits_TOAs(
        eventname, mission=mission, weights=weights, extension=extension,
        timesys=timesys, timeref=timeref, minmjd=minmjd, maxmjd=maxmjd,
        errors=errors)
    if ts == "TT" and tr != "SOLARSYSTEM":
        # the ingestion pipeline expects UTC; TT event times must be
        # converted or the UTC->TT chain would be applied twice (~69 s)
        from pint_tpu.timescales import tt_to_utc_mjd

        mjds = tt_to_utc_mjd(mjds)
    n = len(mjds)
    cfg = MISSION_CONFIG.get(mission.lower(), MISSION_CONFIG["generic"])
    if tr == "SOLARSYSTEM":
        if ts != "TDB":
            raise ValueError("Barycentered events must be TIMESYS=TDB")
        obsname = "barycenter"
    elif tr == "GEOCENTRIC":
        obsname = "geocenter"
    else:
        from pint_tpu.observatory import get_observatory

        try:
            obsname = get_observatory(cfg["obs"]).name
        except KeyError:
            raise ValueError(
                f"Unbarycentered {mission} events need a satellite "
                "observatory: load an orbit file with "
                "pint_tpu.observatory.satellite_obs.get_satellite_observatory "
                f"({cfg['obs']!r} is not registered)")
    flags: List[dict] = []
    for i in range(n):
        fl = {}
        if energies is not None:
            fl["energy"] = repr(float(energies[i]))
        if weights is not None:
            fl["weight"] = repr(float(weights[i]))
        flags.append(fl)
    ts_obj = TOAs(
        utc_mjd=np.asarray(mjds, dtype=np.longdouble),
        error_us=np.full(n, float(errors)),
        freq_mhz=np.full(n, np.inf),
        obs=np.array([obsname] * n, dtype=object),
        flags=flags,
    )
    if tr == "SOLARSYSTEM":
        # already barycentric: TDB = given times, site at SSB
        ts_obj.clock_corr_s = np.zeros(n)
        ts_obj.compute_TDBs(ephem=ephem or "DE440")
        ts_obj.compute_posvels(ephem=ephem or "DE440", planets=planets)
    else:
        ts_obj.apply_clock_corrections(include_bipm=False)
        ts_obj.compute_TDBs(ephem=ephem or "DE440")
        ts_obj.compute_posvels(ephem=ephem or "DE440", planets=planets)
    return ts_obj


def get_event_TOAs(eventname: str, mission: str, **kw) -> TOAs:
    """Generic mission wrapper (reference ``event_toas.py:519``)."""
    return get_fits_TOAs(eventname, mission=mission, **kw)


def get_NICER_TOAs(eventname: str, **kw) -> TOAs:
    return get_event_TOAs(eventname, "nicer", **kw)


def get_NuSTAR_TOAs(eventname: str, **kw) -> TOAs:
    return get_event_TOAs(eventname, "nustar", **kw)


def get_XMM_TOAs(eventname: str, **kw) -> TOAs:
    return get_event_TOAs(eventname, "xmm", **kw)


def get_RXTE_TOAs(eventname: str, **kw) -> TOAs:
    return get_event_TOAs(eventname, "xte", **kw)


def get_Swift_TOAs(eventname: str, **kw) -> TOAs:
    return get_event_TOAs(eventname, "swift", **kw)


def get_IXPE_TOAs(eventname: str, **kw) -> TOAs:
    return get_event_TOAs(eventname, "ixpe", **kw)
