"""Generalized-least-squares fitters for correlated noise models.

Counterpart of reference ``fitter.py:1939 GLSFitter`` / ``fitter.py:1399
DownhillGLSFitter``.  Two equivalent paths (reference ``fitter.py:2003-2025``):

* ``full_cov=False`` (default): augmented design matrix ``[M | U]`` with
  diagonal white noise ``Nvec`` and basis priors ``phiinv`` — the Woodbury
  form, linear in N_toa memory.
* ``full_cov=True``: dense N x N TOA covariance, Cholesky-factored.

The normal-equation solves run on device through ``jax.scipy.linalg``
(Cholesky first, SVD fallback with singular-value thresholding, reference
``fitter.py:2030-2037,2621``); basis matrices are host-built constants.
"""

from __future__ import annotations

import warnings
from typing import List, Optional, Tuple

import jax.numpy as jnp
import jax.scipy.linalg as jsl
import numpy as np

from pint_tpu.exceptions import (
    CorrelatedErrors,
    DegeneracyWarning,
    NonFiniteSystemError,
    SingularMatrixError,
    UsageError,
)
from pint_tpu.fitter import DownhillFitter, Fitter
from pint_tpu.logging import log
from pint_tpu.runtime.solve import (
    SolveDiagnostics,
    hardened_cholesky,
    solve_normal_cholesky,
)
from pint_tpu.telemetry import event as _tevent
from pint_tpu.telemetry import jaxevents as _jaxevents
from pint_tpu.telemetry import span as _span
from pint_tpu.utils import normalize_designmatrix

__all__ = ["GLSFitter", "DownhillGLSFitter", "linearized_system"]

#: exceptions that send a fitter from the Cholesky ladder to its SVD path
_CHOLESKY_FAILURES = (np.linalg.LinAlgError, SingularMatrixError)


def _solve_cholesky(mtcm: np.ndarray, mtcy: np.ndarray):
    """xvar, xhat, diagnostics from M^T C^-1 M via the hardened ladder
    (reference ``fitter.py:2759`` + runtime guardrail): plain Cholesky is
    bit-identical to the old solve; a near-singular system escalates
    through jittered rungs before the caller's SVD path.  Raises
    :class:`SingularMatrixError` when the ladder is exhausted and
    :class:`NonFiniteSystemError` on NaN/inf input (never retried into
    silent garbage).  Always the FULL ladder: the autotuner's tuned
    entry rung is measured on the Schur path's factorizations and is
    consumed only there (:func:`_schur_gls_solve`)."""
    return solve_normal_cholesky(mtcm, mtcy, name="GLS normal equations")


def _solve_svd(mtcm: np.ndarray, mtcy: np.ndarray, threshold: float,
               params: List[str]):
    """SVD solve with degenerate directions removed (reference
    ``fitter.py:2729`` + ``apply_Sdiag_threshold`` ``fitter.py:2621``).
    Returns (xvar, xhat, diagnostics)."""
    if not (np.all(np.isfinite(mtcm)) and np.all(np.isfinite(mtcy))):
        raise NonFiniteSystemError(
            "GLS normal equations contain NaN/inf; refusing the SVD solve")
    U, s, Vt = (np.asarray(x) for x in jnp.linalg.svd(jnp.asarray(mtcm),
                                                      full_matrices=False))
    if threshold > 0:
        bad = s < threshold * s.max()
        if bad.any():
            # columns beyond len(params) are unnamed noise-basis columns
            badp = [params[i] if i < len(params) else f"<noise basis {i}>"
                    for i in np.argsort(np.abs(Vt[bad]).max(0))[::-1][:3]]
            warnings.warn(
                f"Degenerate parameter directions (e.g. {badp}) removed",
                DegeneracyWarning)
        s = np.where(bad, np.inf, s)
    xvar = (Vt.T / s) @ Vt
    xhat = Vt.T @ ((U.T @ mtcy) / s)
    sf = s[np.isfinite(s)]
    cond = float(sf.max() / max(sf.min(), 1e-300)) if sf.size else np.inf
    return xvar, xhat, SolveDiagnostics(method="svd", jitter=0.0,
                                        attempts=1, condition=cond)


def build_augmented_system(model, toas, wideband: bool = False):
    """Shared Woodbury-form system builder for every GLS-family fitter:
    normalized ``[M_timing | noise basis]`` (wideband: timing rows are the
    stacked [toa; dm] blocks, noise basis padded with zero DM rows), plus
    (params, norm, phiinv, Nvec, noise_dims).  Single source of truth for
    the timing-prior weighting (1e40, enterprise convention) and basis
    padding.  HOST-ONLY NUMBERS: these weights enter as ``phiinv`` = 1e-40
    added to host-factored normal equations; never move them into a jitted
    graph — TPU f64 emulation has float32 RANGE and 1e40-scale weights
    overflow there (that is why the on-device offset prior is the separate
    ``timing_model.OFFSET_PRIOR_WEIGHT`` = 1e10)."""
    M_tm, params, units = model.designmatrix(toas, reuse_linear=True)
    if wideband:
        M_dm, _, _ = model.dm_designmatrix(toas)
        M_q = np.vstack([M_tm, M_dm])
    else:
        M_q = M_tm
    n_rows, n_toa = M_q.shape[0], M_tm.shape[0]
    Us, ws, dims = model.noise_basis_by_component(toas)
    if Us:
        U = np.hstack(Us)
        if n_rows > n_toa:
            U = np.vstack([U, np.zeros((n_rows - n_toa, U.shape[1]))])
        M = np.hstack([M_q, U])
        # host-only enterprise prior weight (docstring above): never traced
        weights = np.concatenate(
            [np.full(len(params), 1e40)] + ws)  # jaxlint: disable=f32-unsafe-literal
    else:
        M = M_q
        weights = np.full(len(params), 1e40)  # jaxlint: disable=f32-unsafe-literal -- host-only prior weight, see docstring
    M, norm = normalize_designmatrix(M, params)
    M, norm = np.asarray(M), np.asarray(norm)
    phiinv = 1.0 / weights / norm**2
    if wideband:
        Nvec = np.concatenate([model.scaled_toa_uncertainty(toas),
                               model.scaled_dm_uncertainty(toas)]) ** 2
    else:
        Nvec = model.scaled_toa_uncertainty(toas) ** 2
    return M, params, norm, phiinv, Nvec, dims


def linearized_system(model, toas, resids=None):
    """``(M, r, w, phiinv, params, norm)`` — the normalized
    Woodbury-form linearized GLS system at the model's current state,
    as flat host arrays: the batch-axis entry point the serving
    batcher (:meth:`pint_tpu.serving.batcher.FitRequest.from_fitter`)
    and the PTA catalog engine (:mod:`pint_tpu.catalog`) stack per
    pulsar into padded ``(pulsar, n_toas, n_free)`` buckets.  ``w`` is
    the white-noise weight ``1/Nvec`` (a zero weight marks a padded
    row downstream).  ``resids`` defaults to a fresh
    :class:`~pint_tpu.residuals.Residuals` at the current state."""
    if resids is None:
        from pint_tpu.residuals import Residuals

        resids = Residuals(toas, model)
    M, params, norm, phiinv, Nvec, _ = build_augmented_system(model, toas)
    r = np.asarray(resids.time_resids, dtype=np.float64)
    return (M, r, 1.0 / np.asarray(Nvec, dtype=np.float64), phiinv,
            tuple(params), np.asarray(norm, dtype=np.float64))


def _design_spec(model, toas):
    """The resolved ``gls.design`` precision segment for this workload
    (override -> manifest ``precision.gls.design`` key -> bit-identical
    f64 default).  Host-side; resolved once per step and closed over
    the Gram products below."""
    from pint_tpu.precision import segment_spec

    return segment_spec("gls.design", model=model, toas=toas)


def gls_normal_equations(M: np.ndarray, r: np.ndarray,
                         Nvec: Optional[np.ndarray] = None,
                         phiinv: Optional[np.ndarray] = None,
                         cov: Optional[np.ndarray] = None,
                         spec=None):
    """mtcm, mtcy for either GLS path (reference ``fitter.py:2696,2712``).

    ``spec`` (a :class:`pint_tpu.precision.SegmentSpec`) drives the
    ``gls.design`` precision segment: the ``M^T C^-1 M`` / ``M^T C^-1
    r`` contractions run at its compute dtype with its accumulation
    back to f64.  ``None``/f64 is exactly the pre-precision build."""
    from pint_tpu.precision import matmul as _pmatmul

    if cov is not None:
        cf, _, _ = hardened_cholesky(cov, name="TOA covariance")
        cm = np.asarray(jsl.cho_solve((jnp.asarray(cf), True), jnp.asarray(M)))
        mtcm = _pmatmul(M.T, cm, spec)
        mtcy = _pmatmul(cm.T, r, spec)
    else:
        cinv = 1.0 / Nvec
        mtcm = _pmatmul(M.T, cinv[:, None] * M, spec)
        mtcm = mtcm + np.diag(phiinv)
        mtcy = _pmatmul(M.T, cinv * r, spec)
    return mtcm, mtcy


def _schur_gls_solve(M: np.ndarray, r: np.ndarray, Nvec: np.ndarray,
                     phiinv: np.ndarray, ntm: int, cache: dict,
                     ladder=None, spec=None):
    """Solve the augmented system via a Schur complement on the noise
    block.

    The normal matrix is ``[[A, C], [C^T, D]]`` with the timing block A
    (ntm^2) and noise block ``D = M_u^T W M_u + diag(phiinv_u)``.  D is
    identical on every iteration of a fit (the basis and the noise
    parameters are fixed while timing parameters move), so its Gram matrix
    and Cholesky are cached across iterations — removing the dominant
    O(n*nu^2) matmul and the O((ntm+nu)^3) dense factorization per step.
    Returns (xvar_t, xhat, diagnostics) with xvar_t the (ntm, ntm)
    marginal timing covariance ``(A - C D^-1 C^T)^-1`` (exactly what the
    full-system inverse's timing block is) and xhat the full solution
    vector.  Both factorizations run through the hardened jitter ladder
    (``ladder``: the autotuner's tuned entry-rung suffix, default full);
    ladder exhaustion raises :class:`SingularMatrixError` for the
    caller's SVD path, non-finite inputs raise
    :class:`NonFiniteSystemError` outright.
    """
    from pint_tpu.precision import matmul as _pmatmul
    from pint_tpu.runtime.solve import JITTER_LADDER

    ladder = ladder or JITTER_LADDER
    if not np.all(np.isfinite(r)):
        raise NonFiniteSystemError(
            "GLS residual vector contains NaN/inf; refusing the solve")
    W = 1.0 / Nvec
    M_t, M_u = M[:, :ntm], M[:, ntm:]
    pu = phiinv[ntm:]
    WM_u = W[:, None] * M_u
    # gls.design precision segment key: a policy flip must invalidate
    # the cached noise-block factor (same Gram, different arithmetic)
    skey = None if spec is None else spec.key()
    hit = cache.get("schur")
    # exact invalidation: the factor is only reused while the noise block's
    # every input is bitwise unchanged (cheap O(n*nu) compares vs the
    # O(n*nu^2) Gram it saves)
    if (hit is not None and hit[0] == M.shape and hit[1] == ntm
            and np.array_equal(hit[2], pu) and np.array_equal(hit[3], Nvec)
            and np.array_equal(hit[4], M_u) and hit[7] == skey):
        L_D, jit_D = hit[5], hit[6]
    else:
        D = _pmatmul(M_u.T, WM_u, spec) + np.diag(pu)
        L_D, jit_D, _ = hardened_cholesky(D, name="GLS noise block",
                                          ladder=ladder)
        cache["schur"] = (M.shape, ntm, pu.copy(), Nvec.copy(), M_u.copy(),
                          L_D, jit_D, skey)
    A = _pmatmul(M_t.T, W[:, None] * M_t, spec) + np.diag(phiinv[:ntm])
    C = _pmatmul(M_t.T, WM_u, spec)
    b_t = M_t.T @ (W * r)
    b_u = WM_u.T @ r
    Y = np.asarray(jsl.solve_triangular(jnp.asarray(L_D), jnp.asarray(C.T),
                                        lower=True))
    z_u = np.asarray(jsl.solve_triangular(jnp.asarray(L_D),
                                          jnp.asarray(b_u), lower=True))
    S = A - Y.T @ Y
    L_S, jit_S, attempts = hardened_cholesky(S, name="GLS Schur complement",
                                             ladder=ladder)
    x_t = np.asarray(jsl.cho_solve((jnp.asarray(L_S), True),
                                   jnp.asarray(b_t - Y.T @ z_u)))
    xvar_t = np.asarray(jsl.cho_solve((jnp.asarray(L_S), True),
                                      jnp.eye(ntm, dtype=jnp.float64)))
    # noise amplitudes: back-substitute x_u = D^-1 (b_u - C^T x_t)
    x_u = np.asarray(jsl.cho_solve((jnp.asarray(L_D), True),
                                   jnp.asarray(b_u - C.T @ x_t)))
    dS = np.diag(L_S)
    jitter = max(jit_D, jit_S)
    diag = SolveDiagnostics(
        method="cholesky" if jitter == 0.0 else "cholesky-jitter",
        jitter=float(jitter), attempts=attempts,
        condition=float((dS.max() / max(dS.min(), 1e-300)) ** 2))
    return xvar_t, np.concatenate([x_t, x_u]), diag


def _try_schur_path(fitter, M, r, Nvec, phiinv, ntm, norm):
    """Shared Schur fast-path assembly for GLSFitter and the wideband
    fitters: returns (dpars, errs, covmat) or None when the Cholesky
    fails (caller falls back to the dense/SVD path).  The fitter carries
    the cross-iteration cache (and, when tuned, the autotuner's ladder
    entry rung on ``_solve_ladder``)."""
    if not hasattr(fitter, "_gls_cache"):
        fitter._gls_cache = {}
    try:
        xvar_t, xhat, diag = _schur_gls_solve(
            M, r, Nvec, phiinv, ntm, fitter._gls_cache,
            ladder=getattr(fitter, "_solve_ladder", None),
            spec=getattr(fitter, "_precision_spec", None))
    except _CHOLESKY_FAILURES:
        # ladder exhausted: the dense path's own ladder/SVD takes over
        # (NonFiniteSystemError propagates — retrying cannot fix NaNs)
        return None
    fitter.solve_diagnostics = diag
    dpars = xhat / norm
    errs = np.concatenate([
        np.sqrt(np.maximum(np.diag(xvar_t), 0.0)) / norm[:ntm],
        np.zeros(len(norm) - ntm)])  # noise-column errs are never consumed
    covmat = (xvar_t / norm[:ntm]).T / norm[:ntm]
    return dpars, errs, covmat


def _make_gls_cholesky_solve():
    import jax

    def solve(mtcm, mtcy):
        L = jnp.linalg.cholesky(mtcm)
        return jsl.cho_solve((L, True), mtcy)

    return jax.jit(solve)


#: ONE jitted Cholesky solve for cost attribution — per-call jit objects
#: would recompile on every profile_gls_solve instead of hitting the
#: executable cache
_gls_cholesky_solve = _make_gls_cholesky_solve()


def _make_gls_normal_equations(spec=None):
    import jax

    from pint_tpu.precision import matmul as _pmatmul

    def normal_eq(M, r, Nvec, phiinv):
        cinv = 1.0 / Nvec
        mtcm = _pmatmul(M.T, cinv[:, None] * M, spec) + jnp.diag(phiinv)
        mtcy = _pmatmul(M.T, cinv * r, spec)
        return mtcm, mtcy

    return jax.jit(normal_eq)


#: ONE jitted Woodbury-form normal-equation build per gls.design
#: precision key, for the same warm-cache reason as _gls_cholesky_solve
#: — and the distributed observatory's collective accounting target:
#: with the TOA axis sharded, the M^T C^-1 M / M^T C^-1 r contractions
#: become cross-device all-reduces.  The f64 instance keeps the
#: historical module-level name.
_gls_normal_equations = _make_gls_normal_equations()
_gls_normal_equations_by_spec = {("float64", "native"):
                                 _gls_normal_equations}


def _gls_normal_equations_for(spec=None):
    """The jitted normal-equation build traced under ``spec`` (module-
    level per precision key, so repeat profiling/warming retraces into
    the warm executable cache instead of compiling fresh)."""
    if spec is None or not spec.reduced:
        return _gls_normal_equations
    key = spec.key()
    fn = _gls_normal_equations_by_spec.get(key)
    if fn is None:
        fn = _make_gls_normal_equations(spec)
        _gls_normal_equations_by_spec[key] = fn
    return fn


def _tuned_gram_build() -> str:
    """The tuned collective form of the sharded Gram build —
    ``"scatter"`` (static default: the reduce-scatter kernel) or
    ``"allreduce"`` (the legacy build, when the plan-strategy tunable
    measured it faster on this system).  The routing half of the
    ``plan.strategy`` decision: a measured winner that nothing enacts
    would be manifest fiction."""
    from pint_tpu import autotune as _autotune

    strategy = _autotune.resolve_plan_strategy("gls_normal_eq")
    if strategy is not None and strategy.get("build") == "allreduce":
        return "allreduce"
    return "scatter"


def _allreduce_normal_equations(M: np.ndarray, r: np.ndarray,
                                Nvec: np.ndarray, phiinv: np.ndarray,
                                mesh, spec=None):
    """The legacy sharded build: jit over TOA-sharded operands, the
    Gram contractions compiling into full all-reduces.  Zero-weight
    row padding to the shard multiple (``Nvec`` pads 1.0) — exact, not
    trimmed.  Kept as the plan-strategy tunable's comparison candidate
    and its routed form when measured faster."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis = mesh.axis_names[0]
    shards = int(mesh.shape[axis])
    pad = (-len(r)) % shards
    if pad:
        M = np.vstack([M, np.zeros((pad, M.shape[1]))])
        r = np.concatenate([r, np.zeros(pad)])
        Nvec = np.concatenate([Nvec, np.ones(pad)])
    specs = (P(axis, None), P(axis), P(axis), P())
    args = [jax.device_put(jnp.asarray(a), NamedSharding(mesh, s))
            for a, s in zip((M, r, Nvec, phiinv), specs)]
    mtcm, mtcy = _gls_normal_equations_for(spec)(*args)
    return np.asarray(mtcm), np.asarray(mtcy)


def _sharded_normal_equations(M: np.ndarray, r: np.ndarray,
                              Nvec: np.ndarray, phiinv: np.ndarray, plan,
                              spec=None):
    """The Woodbury normal-equation build executed on ``plan``'s mesh —
    by default the reduce-scatter kernel (:func:`pint_tpu.runtime.
    workperbyte.scattered_normal_equations`): per-shard partial Grams
    are ``psum_scatter``'d so each device materializes only its slice
    of the normal matrix (K^2/D bytes per collective instead of the
    old full-Gram all-reduce's K^2 per device), gathered once before
    the host Cholesky.  A tuned ``plan.strategy`` decision whose
    measured winner is the legacy all-reduce build routes there
    instead (:func:`_tuned_gram_build`).  Either way rows are
    zero-padded to a shard multiple (``Nvec`` pads with 1.0), which
    contributes exactly zero to every sum — results are identical to
    the host build, not trimmed."""
    if _tuned_gram_build() == "allreduce":
        return _allreduce_normal_equations(M, r, Nvec, phiinv,
                                           plan.mesh, spec=spec)
    from pint_tpu.runtime.workperbyte import scattered_normal_equations

    return scattered_normal_equations(M, r, Nvec, phiinv, plan, spec=spec)


class GLSFitter(Fitter):
    """One-shot GLS fitter (reference ``fitter.py:1939``).

    ``fit_toas(plan=...)`` routes the normal-equation build through the
    execution-plan layer: the TOA axis is sharded over the plan's mesh
    and the Gram contractions become cross-device all-reduces, under
    elastic supervision (device loss during the sharded build degrades
    the plan one rung and re-runs instead of failing the fit).
    """

    def __init__(self, toas, model, residuals=None, track_mode=None):
        super().__init__(toas, model, residuals=residuals, track_mode=track_mode)
        self.method = "generalized_least_square"
        #: active ExecutionPlan for the sharded normal-equation build
        #: (None: host build + Schur fast path, the single-device route)
        self.plan = None

    def _gls_step(self, threshold: float = 0.0, full_cov: bool = False):
        """One linearized GLS solve; returns (dpars, errs, cov, params).

        Builds the timing design matrix and each noise basis exactly once
        per step; ``self._noise_dims`` records the (offset, size) column
        layout for noise-amplitude extraction.
        """
        r = np.asarray(self.resids.time_resids)
        self._noise_dims = None
        # gls.design precision segment: resolved once per step (manifest
        # memoized; f64 default short-circuits) and threaded through the
        # Gram builds below AND the Schur fast path via the fitter attr
        self._precision_spec = _design_spec(self.model, self.toas)
        spec = self._precision_spec
        if full_cov:
            M_tm, params, units = self.get_designmatrix()
            M, norm = normalize_designmatrix(M_tm, params)
            M, norm = np.asarray(M), np.asarray(norm)
            cov = self.model.toa_covariance_matrix(self.toas)
            mtcm, mtcy = gls_normal_equations(M, r, cov=cov, spec=spec)
        else:
            M, params, norm, phiinv, Nvec, dims = build_augmented_system(
                self.model, self.toas)
            self._noise_dims = dims
            ntm = len(params)
            plan = getattr(self, "plan", None)
            if plan is not None and plan.mesh is not None:
                # routed multichip path: TOA-sharded Woodbury build on
                # the plan's mesh, elastic-supervised (a device loss
                # mid-build degrades the plan and re-runs); the host
                # Cholesky/SVD ladder below consumes the result
                # unchanged
                from pint_tpu.runtime.elastic import run_with_degradation

                # the gls.design spec is forwarded only when reduced:
                # the f64 default keeps the routed seam's historical
                # 5-argument signature (fault-injection fakes included)
                skw = {"spec": spec} if spec.reduced else {}
                (mtcm, mtcy), self.plan, self.last_elastic_report = \
                    run_with_degradation(
                        plan,
                        lambda p: _sharded_normal_equations(
                            M, r, Nvec, phiinv, p, **skw)
                        if p.mesh is not None
                        else gls_normal_equations(M, r, Nvec=Nvec,
                                                  phiinv=phiinv,
                                                  spec=spec),
                        what="GLS sharded normal equations")
            else:
                if threshold <= 0 and M.shape[1] > ntm:
                    # Schur-complement fast path: the noise block is
                    # constant across a fit's iterations (cached factor);
                    # only the timing system is solved per step
                    out = _try_schur_path(self, M, r, Nvec, phiinv, ntm,
                                          norm)
                    if out is not None:
                        return (*out, params)
                mtcm, mtcy = gls_normal_equations(M, r, Nvec=Nvec,
                                                  phiinv=phiinv,
                                                  spec=spec)
        if threshold <= 0:
            try:
                # the tuned entry rung (_solve_ladder) deliberately
                # does NOT apply here: it was measured on the Schur
                # path's factorizations; this dense mtcm is a
                # different matrix and gets the full ladder
                xvar, xhat, diag = _solve_cholesky(mtcm, mtcy)
            except _CHOLESKY_FAILURES:
                xvar, xhat, diag = _solve_svd(mtcm, mtcy, threshold, params)
        else:
            xvar, xhat, diag = _solve_svd(mtcm, mtcy, threshold, params)
        self.solve_diagnostics = diag
        dpars = xhat / norm
        errs = np.sqrt(np.diag(xvar)) / norm
        covmat = (xvar / norm).T / norm
        return dpars, errs, covmat, params

    def _apply_step(self, dpars, errs, covmat, params):
        for i, p in enumerate(params):
            if p == "Offset":
                continue
            par = getattr(self.model, p)
            par.value = float(par.value or 0.0) + float(dpars[i])
            par.uncertainty = float(errs[i])
            self.errors[p] = float(errs[i])
        ntm = len(params)
        self._set_covariance(covmat[:ntm, :ntm], params)
        self.fitted_params = params

    def _store_noise_ampls(self, dpars, ntm):
        """Maximum-likelihood GP amplitudes for each correlated component
        (reference ``fitter.py:2070-2085``)."""
        if self._noise_dims is None:
            return
        self.resids.noise_ampls = {
            comp: dpars[ntm + off:ntm + off + size]
            for comp, (off, size) in self._noise_dims.items()
        }

    def gls_solve_executable(self):
        """(jitted solve fn, (mtcm, mtcy)) — the GLS normal-equation
        Cholesky solve at this fitter's current system shapes, as one
        jittable executable for AOT cost attribution
        (:func:`pint_tpu.telemetry.costs.profile_gls_solve`).  This is
        the device-side core of the solve ladder's first rung (plain
        Cholesky + cho_solve); the hardened escalation around it is host
        control flow and carries no analyzable executable of its own.
        The jitted fn is the module-level :func:`_gls_cholesky_solve`
        (shapes are traced arguments), so repeat profiling retraces into
        the warm executable cache instead of compiling fresh."""
        r = np.asarray(self.resids.time_resids)
        M, params, norm, phiinv, Nvec, _ = build_augmented_system(
            self.model, self.toas)
        mtcm, mtcy = gls_normal_equations(M, r, Nvec=Nvec, phiinv=phiinv)
        return _gls_cholesky_solve, (jnp.asarray(mtcm), jnp.asarray(mtcy))

    def gls_normal_equations_executable(self, mesh=None, plan=None,
                                        scatter: Optional[bool] = None):
        """(jitted fn, (M, r, Nvec, phiinv)) — the Woodbury-form GLS
        normal-equation build (``M^T C^-1 M + diag(phiinv)``, ``M^T C^-1
        r``) at this fitter's augmented-system shapes, as one jittable
        executable for AOT analysis.

        ``plan`` (an :class:`~pint_tpu.runtime.plan.ExecutionPlan` over
        the 'toa' axis) supplies the mesh the production fit path uses,
        so the scalewatch/dryrun observatory measures the routed
        executable.  With a mesh the default (``scatter=None`` — the
        tuned ``plan.strategy`` build, scatter when untuned: exactly
        what :func:`_sharded_normal_equations` routes) is the
        production reduce-scatter kernel (:mod:`pint_tpu.runtime.
        workperbyte`): per-shard partial Grams ``psum_scatter``'d so
        each device holds only its slice — the executable
        :func:`~pint_tpu.runtime.workperbyte.verify_scatter_contract`
        checks for a real ``reduce-scatter`` (and no full-Gram
        ``all-reduce``) in the compiled HLO.  ``scatter=False`` keeps
        the legacy jit-of-sharded-operands build whose contractions
        compile into full all-reduces — the comparison candidate the
        plan-strategy tunable ranks collective bytes against.

        Either way the TOA count is zero-weight PADDED to the shard
        multiple (``Nvec`` pads with 1.0 — the serving batcher's
        construction, contributing exactly zero to every contraction),
        never trimmed: the analyzed executable computes the same system
        the unsharded build does, to 1e-9.  The jitted fns are
        module-level for the same warm-cache reason as
        :func:`_gls_cholesky_solve`."""
        if plan is not None:
            if mesh is not None:
                raise UsageError("plan= and mesh= cannot be combined; the "
                                 "plan carries its own mesh")
            mesh = plan.mesh
        r = np.asarray(self.resids.time_resids)
        M, params, norm, phiinv, Nvec, _ = build_augmented_system(
            self.model, self.toas)
        pspec = _design_spec(self.model, self.toas)
        if scatter is None:
            scatter = _tuned_gram_build() == "scatter"
        if mesh is not None and scatter:
            from pint_tpu.runtime.workperbyte import (
                SCATTER_ROW_CHUNKS,
                scattered_gram_operands,
                scattered_normal_equations_fn,
            )

            row_chunks = SCATTER_ROW_CHUNKS \
                if len(r) >= 2 * SCATTER_ROW_CHUNKS * int(
                    mesh.shape[mesh.axis_names[0]]) else 1
            args, _ = scattered_gram_operands(M, r, Nvec, phiinv, mesh,
                                              row_chunks=row_chunks)
            return scattered_normal_equations_fn(
                mesh, spec=pspec, row_chunks=row_chunks), tuple(args)
        args = [jnp.asarray(M), jnp.asarray(r), jnp.asarray(Nvec),
                jnp.asarray(phiinv)]
        if mesh is not None:
            import jax
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            axis = mesh.axis_names[0]
            shards = int(mesh.shape[axis])
            if len(r) < shards:
                raise UsageError(
                    f"cannot shard {len(r)} TOAs over {shards} devices")
            pad = (-len(r)) % shards
            if pad:
                # zero-weight pad rows instead of the old trim: the
                # padded rows cannot enter the normal equations, so the
                # analyzed system IS the fit's system (the trim silently
                # dropped up to shards-1 TOAs from the solve)
                args[0] = jnp.concatenate(
                    [args[0], jnp.zeros((pad, M.shape[1]),
                                        dtype=jnp.float64)])
                args[1] = jnp.concatenate(
                    [args[1], jnp.zeros(pad, dtype=jnp.float64)])
                args[2] = jnp.concatenate(
                    [args[2], jnp.ones(pad, dtype=jnp.float64)])
            specs = [P(axis, None), P(axis), P(axis), P()]
            args = [jax.device_put(a, NamedSharding(mesh, s))
                    for a, s in zip(args, specs)]
        return _gls_normal_equations_for(pspec), tuple(args)

    # -- streaming updates (pint_tpu.streaming) -------------------------

    def streaming(self, **kw):
        """The fitter's lazily constructed
        :class:`~pint_tpu.streaming.update.StreamingGLS` engine (built
        on first use from the CURRENT converged state; construction
        options — block ladder, warm-step count, warm pool — are
        accepted only then)."""
        if getattr(self, "_stream", None) is None:
            from pint_tpu.streaming.update import StreamingGLS

            self._stream = StreamingGLS(self, **kw)
        elif kw:
            raise UsageError(
                "this fitter's streaming engine already exists; "
                "construction options must be passed on the first "
                "streaming()/update_toas() call")
        return self._stream

    def update_toas(self, new_toas, steps=None, **engine_kw):
        """Ingest newly arrived TOAs incrementally: validate/quarantine
        gate, rank-k Cholesky update of the normal-equation factor for
        the certified rows, warm-started Gauss-Newton from the previous
        solution (``O(k K^2)`` instead of a full refit).  ``steps`` is
        a per-call override; any other keyword is a CONSTRUCTION
        option forwarded to :meth:`streaming` (honored only when this
        call builds the engine).  Returns the
        :class:`~pint_tpu.streaming.update.UpdateOutcome`."""
        eng = self.streaming(**engine_kw)
        return eng.update_toas(new_toas, steps=steps)

    def quarantine_rows(self, block_id: int, rows):
        """Quarantine previously certified rows of one stream block:
        rank-k DOWNDATE of exactly those rows + warm refit."""
        return self.streaming().quarantine_rows(block_id, rows)

    def release_quarantined(self, block_id: int, rows):
        """Release repaired rows back into the fit: rank-k UPDATE —
        never a full rebuild (regression-pinned) — + warm refit."""
        return self.streaming().release_quarantined(block_id, rows)

    def fit_toas(self, maxiter: int = 1, threshold: float = 0.0,
                 full_cov: bool = False, debug: bool = False,
                 robust=None, plan=None) -> float:
        """``plan`` routes the normal-equation build through the
        execution-plan layer (``"auto"`` selects from the
        preflight-certified device set over the 'toa' axis; or pass an
        :class:`~pint_tpu.runtime.plan.ExecutionPlan`).  The elastic-
        supervised sharded build replaces the host Schur fast path; on
        device failure the plan degrades one rung and the fit
        continues.  The surviving plan stays on ``self.plan``."""
        if plan is not None:
            if isinstance(plan, str):
                from pint_tpu.runtime.plan import select_plan

                if plan != "auto":
                    raise UsageError(f"plan={plan!r}: pass 'auto' or an "
                                     "ExecutionPlan")
                plan = select_plan("gls_normal_eq",
                                   n_items=len(self.toas))
            self.plan = plan
        # tuned solve-ladder entry rung (pint_tpu.autotune): resolved
        # once per fit against the manifest's vkey (full parameter
        # signature — any edit falls back to the full ladder).  None is
        # both "tuning off" and the healthy rung-0 outcome; a tuned
        # rung skips only loadings measured to FAIL on this system, so
        # the applied jitter — and the solution — is identical to the
        # static path's.
        from pint_tpu import autotune as _autotune

        self._solve_ladder = _autotune.resolve_solve_ladder(self)
        if self._check_robust_arg(robust):
            # typed and actionable, instead of a TypeError on the kwarg:
            # Huber IRLS reweights a *diagonal* whitener, which a
            # correlated-noise covariance does not have
            raise UsageError(
                "robust fitting is available on the WLS-family fitters "
                "only (Huber IRLS assumes uncorrelated errors)")
        with _span("gls.fit_toas", ntoas=len(self.toas),
                   nfree=len(self.model.free_params), maxiter=maxiter,
                   full_cov=full_cov) as sp, _jaxevents.watch(sp):
            self.model.validate()
            self.model.validate_toas(self.toas)
            self.update_resids()
            for it in range(max(1, maxiter)):
                with _span("gls.step", iteration=it):
                    dpars, errs, covmat, params = self._gls_step(
                        threshold=threshold, full_cov=full_cov)
                    self._apply_step(dpars, errs, covmat, params)
                    self.update_resids()
                if self.solve_diagnostics is not None:
                    _tevent("gls.solve", iteration=it,
                            **self.solve_diagnostics.to_dict())
                if not full_cov:
                    self._store_noise_ampls(dpars, len(params))
            chi2 = self.resids.calc_chi2()
            if np.isnan(chi2):
                # a one-shot fit must not hand back a silently poisoned chi2
                raise NonFiniteSystemError(
                    "GLS fit produced NaN chi2 (non-finite residuals or a "
                    "poisoned solve)")
            sp.attrs["chi2"] = float(chi2)
            self.converged = True
            self.update_model(chi2)
            return chi2


class DownhillGLSFitter(DownhillFitter):
    """Iterative GLS with lambda-halving line search (reference
    ``fitter.py:1399``)."""

    def __init__(self, toas, model, **kw):
        super().__init__(toas, model, **kw)
        self.method = "downhill_gls"
        self.full_cov = False
        self.threshold = 0.0

    def _solve_step(self):
        dpars, errs, covmat, params = GLSFitter._gls_step(
            self, threshold=self.threshold, full_cov=self.full_cov)
        ntm = len(params)
        return dpars[:ntm], params, covmat[:ntm, :ntm]

    def fit_toas(self, maxiter: int = 20, full_cov: bool = False,
                 threshold: float = 0.0, **kw) -> float:
        self.full_cov = full_cov
        self.threshold = threshold
        chi2 = super().fit_toas(maxiter=maxiter, **kw)
        if not full_cov:
            # noise amplitudes must describe the *accepted* parameter state:
            # re-solve once at the converged point (a lambda-scaled or
            # rejected last step would otherwise leak in)
            dpars, _, _, params = GLSFitter._gls_step(
                self, threshold=threshold, full_cov=False)
            GLSFitter._store_noise_ampls(self, dpars, len(params))
        return chi2

    def _chi2_func(self):
        return self.resids.calc_chi2()
