"""Photon-domain MCMC fitters: sample timing parameters against a pulse
profile template using per-photon likelihoods.

Counterpart of reference ``mcmc_fitter.py:441 MCMCFitterBinnedTemplate`` /
``:485 MCMCFitterAnalyticTemplate``.  lnlike = sum_i log(w_i f(phi_i) +
(1 - w_i)) (Pletsch & Clark 2015), with f either a binned template lookup
or the analytic LCTemplate.  The whole walker ensemble evaluates through
one jit+vmap call: model phases and the template are computed in-trace
(reference loops walkers through Python/emcee instead).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pint_tpu.fitter import Fitter
from pint_tpu.logging import log
from pint_tpu.models.priors import Prior
from pint_tpu.sampler import EnsembleSampler
from pint_tpu.templates.lctemplate import LCTemplate

__all__ = ["MCMCFitterBinnedTemplate", "MCMCFitterAnalyticTemplate",
           "marginalize_over_phase"]


def marginalize_over_phase(phases, template_bins, weights=None,
                           nbins: Optional[int] = None):
    """Maximize the template likelihood over a constant phase offset by
    brute-force scan (reference ``event_optimize.py marginalize_over_phase``).
    Returns (dphis, lnlikes)."""
    template_bins = np.asarray(template_bins, dtype=np.float64)
    n = len(template_bins)
    dphis = np.arange(n) / n
    phases = np.asarray(phases) % 1.0
    lnls = np.empty(n)
    w = weights
    for i, dphi in enumerate(dphis):
        idx = ((phases + dphi) * n).astype(int) % n
        f = template_bins[idx]
        vals = f if w is None else w * f + (1 - w)
        lnls[i] = np.sum(np.log(np.maximum(vals, 1e-300)))
    return dphis, lnls


class _PhotonMCMCFitter(Fitter):
    """Shared machinery: free timing params sampled, photon-template
    likelihood, batched ensemble."""

    def __init__(self, toas, model, template, weights=None,
                 sampler: Optional[EnsembleSampler] = None, nwalkers: int = 32,
                 prior_info: Optional[dict] = None, errfact: float = 0.1,
                 minMJD=None, maxMJD=None, backend=None, seed=None, **kw):
        if weights is not None:
            weights = np.asarray(weights, dtype=np.float64)
        if minMJD is not None or maxMJD is not None:
            mjds = np.asarray(toas.get_mjds(), dtype=np.float64)
            keep = np.ones(len(toas), dtype=bool)
            if minMJD is not None:
                keep &= mjds >= float(minMJD)
            if maxMJD is not None:
                keep &= mjds <= float(maxMJD)
            toas = toas[keep]
            if weights is not None:
                weights = weights[keep]
        super().__init__(toas, model, **kw)
        self.method = "MCMC_photon"
        self.template = template
        wv, valid = toas.get_flag_value("weight", as_type=float)
        if weights is not None:
            self.weights = weights
        elif len(valid) == len(toas):
            self.weights = np.asarray(wv, dtype=np.float64)
        else:
            self.weights = None
        self.sampler = sampler or EnsembleSampler(nwalkers, seed=seed,
                                                  backend=backend)
        self.errfact = errfact
        if prior_info is not None:
            from pint_tpu.bayesian import apply_prior_info

            apply_prior_info(self.model, prior_info)
        self.fitkeys = list(self.model.free_params)
        self.n_fit_params = len(self.fitkeys)
        self.maxpost = -np.inf
        self.maxpost_fitvals = None
        self._batch_fn = None
        self._batch_fn_jit = None

    # -- template density in-trace (subclasses provide) ----------------------
    def _template_density(self, phifrac):
        raise NotImplementedError

    def _build_batch(self):
        import jax
        import jax.numpy as jnp

        free = tuple(self.fitkeys)
        c = self.model._get_compiled(self.toas, free)
        eval_fn = self.model._cache["fns"][(free, len(self.toas))]["eval"]
        const_pv = self.model._const_pv()
        batch, ctx = c["batch"], c["ctx"]
        w = jnp.asarray(self.weights) if self.weights is not None else None
        specs = []
        for p in self.fitkeys:
            spec = getattr(self.model, p).prior.jax_spec()
            specs.append(spec)

        def lnpost_one(values):
            lnpr = 0.0
            for i, spec in enumerate(specs):
                if spec is None:
                    continue  # improper flat prior contributes 0
                kind, a, b = spec
                if kind == "uniform":
                    inb = (values[i] >= a) & (values[i] <= b)
                    lnpr = lnpr + jnp.where(inb, 0.0, -jnp.inf)
                else:
                    lnpr = lnpr - 0.5 * ((values[i] - a) / b) ** 2
            ph, _ = eval_fn(values, const_pv, batch, ctx)
            phi = jnp.mod(ph.frac, 1.0)
            f = self._template_density(phi)
            vals = f if w is None else w * f + (1.0 - w)
            return lnpr + jnp.sum(jnp.log(jnp.maximum(vals, 1e-300)))

        # plain vmap (no outer jit): see bayesian.py _build_batch_fn — an
        # outer jit would inline eval_fn and let XLA degrade the dd phase
        return jax.vmap(lnpost_one)

    def lnposterior_batch(self, pts):
        import jax

        if isinstance(pts, jax.Array):
            # mesh path: the sampler placed the walker axis over devices
            # (NamedSharding); np.asarray here would gather it straight
            # back to host and silently serialize the whole batch on one
            # device.  jit propagates the input sharding through the
            # vmapped graph (SPMD), which is the entire point — at the
            # documented ~1e-7-cycle fused-jit dd relaxation (measured 0
            # on CPU, tests/test_fused_relaxation.py)
            if self._batch_fn is None:
                self._batch_fn = self._build_batch()
            if self._batch_fn_jit is None:
                # jit the SAME built graph the host path uses (one source
                # of truth; bayesian.lnposterior_batch mirrors this)
                self._batch_fn_jit = jax.jit(self._batch_fn)
            return np.asarray(self._batch_fn_jit(pts))
        if self._batch_fn is None:
            self._batch_fn = self._build_batch()
        return np.asarray(self._batch_fn(np.atleast_2d(
            np.asarray(pts, dtype=np.float64))))

    def lnposterior(self, theta) -> float:
        return float(self.lnposterior_batch(np.asarray(theta)[None, :])[0])

    def get_fitvals(self):
        return np.array([float(getattr(self.model, p).value or 0.0)
                         for p in self.fitkeys])

    def get_fiterrs(self):
        return np.array([float(getattr(self.model, p).uncertainty or 0.0)
                         for p in self.fitkeys])

    def fit_toas(self, maxiter: int = 200, pos=None, seed=None,
                 burn_frac: float = 0.25, resume: bool = False,
                 autocorr: bool = False, **kw) -> float:
        """With ``autocorr=True`` the chain runs until the autocorrelation
        convergence criteria hold (reference ``event_optimize.py:239
        run_sampler_autocorr``) instead of a fixed length."""
        self.sampler.initialize_batched(self.lnposterior_batch,
                                        self.n_fit_params)
        requested_steps = maxiter  # burn-in is a fraction of the REQUEST,
        # unaffected by the resume subtraction below
        if resume:
            # continue the chain from the backend checkpoint (bit-identical
            # to an uninterrupted run; reference event_optimize --backend)
            pos = self.sampler.resume()
            maxiter = max(0, maxiter - len(self.sampler._chain))
        elif pos is None:
            pos = self.sampler.get_initial_pos(
                self.fitkeys, self.get_fitvals(), self.get_fiterrs(),
                self.errfact, seed=seed)
            lp = self.lnposterior_batch(pos)
            pos[~np.isfinite(lp)] = self.get_fitvals()
        if maxiter > 0 and autocorr:
            from pint_tpu.sampler import run_sampler_autocorr

            self.autocorr = run_sampler_autocorr(
                self.sampler, pos, maxiter,
                int(requested_steps * burn_frac))
        elif maxiter > 0:
            self.sampler.run_mcmc(pos, maxiter)
        if not len(self.sampler._chain):
            raise ValueError(
                "fit_toas produced an empty chain (maxiter=0 with no resumed "
                "steps); request at least one step or resume a backend")
        if autocorr:
            # the chain may stop early on convergence (or the resume may
            # already satisfy the request), but the requested burn-in is
            # absolute — never re-fraction a shortened chain
            discard = max(0, min(int(requested_steps * burn_frac),
                                 len(self.sampler._chain) - 1))
        else:
            discard = int(len(self.sampler._chain) * burn_frac)
        chain = self.sampler.get_chain(flat=True, discard=discard)
        lnp = self.sampler.get_log_prob(flat=True, discard=discard)
        imax = int(np.argmax(lnp))
        self.maxpost = float(lnp[imax])
        self.maxpost_fitvals = chain[imax]
        stds = chain.std(axis=0)
        for i, p in enumerate(self.fitkeys):
            getattr(self.model, p).value = float(self.maxpost_fitvals[i])
            getattr(self.model, p).uncertainty = float(stds[i])
            self.errors[p] = float(stds[i])
        self.fitted_params = list(self.fitkeys)
        self.converged = True
        return self.maxpost

    def update_resids(self):  # photon data has no time residuals
        return None

    # -- reference MCMCFitter accessor surface (mcmc_fitter.py:109+) --------
    def get_event_phases(self) -> np.ndarray:
        """Fractional pulse phase of every photon under the current model
        (reference ``mcmc_fitter.py get_event_phases``)."""
        return self.phaseogram_phases()

    def get_weights(self) -> np.ndarray:
        """Per-photon weights (ones when unweighted; reference
        ``mcmc_fitter.py get_weights``)."""
        return self.weights if self.weights is not None \
            else np.ones(len(self.toas))

    def get_template_vals(self, phases) -> np.ndarray:
        """Template density at the given phases (reference
        ``mcmc_fitter.py get_template_vals``)."""
        return np.asarray(self._template_density(
            np.asarray(phases, dtype=np.float64) % 1.0))

    def get_parameters(self) -> np.ndarray:
        """Current sampled-parameter values (reference
        ``mcmc_fitter.py get_parameters``)."""
        return np.asarray(self.get_fitvals(), dtype=np.float64)

    def set_parameters(self, theta) -> None:
        """Write sampled-parameter values into the model (reference
        ``mcmc_fitter.py set_parameters``)."""
        for p, v in zip(self.fitkeys, np.asarray(theta, dtype=np.float64)):
            getattr(self.model, p).value = float(v)

    def get_parameter_names(self) -> list:
        """Names of the sampled parameters (reference
        ``mcmc_fitter.py get_parameter_names``)."""
        return list(self.fitkeys)

    def get_model_parameters(self) -> dict:
        """{name: value} of the sampled timing parameters (reference
        ``mcmc_fitter.py get_model_parameters``)."""
        return dict(zip(self.fitkeys, self.get_parameters()))

    def get_template_parameters(self):
        """Template parameters when an LCTemplate is attached (reference
        ``mcmc_fitter.py get_template_parameters``); None for binned
        array templates."""
        if isinstance(self.template, LCTemplate):
            return self.template.get_parameters()
        return None

    def clip_template_params(self, pos):
        """Hook clipping template-parameter walkers into bounds (reference
        ``mcmc_fitter.py clip_template_params``); timing-only sampling
        here, so positions pass through."""
        return pos

    def get_errors(self) -> np.ndarray:
        """Current per-parameter errors (reference
        ``mcmc_fitter.py get_errors``)."""
        return np.asarray(self.get_fiterrs(), dtype=np.float64)

    def phaseogram(self, bins: int = 64, rotate: float = 0.0, file=None):
        """Phaseogram (phase vs time, summed profile on top) via
        :func:`pint_tpu.plot_utils.phaseogram`; requires matplotlib
        (reference ``mcmc_fitter.py phaseogram``)."""
        from pint_tpu.plot_utils import phaseogram as _phaseogram

        mjds = np.asarray(self.toas.get_mjds(), dtype=np.float64)
        return _phaseogram(mjds, self.get_event_phases(),
                           weights=self.weights, bins=bins, rotate=rotate,
                           plotfile=file)

    def phaseogram_phases(self) -> np.ndarray:
        ph = self.model.phase(self.toas)
        return np.asarray(ph.frac) % 1.0


class MCMCFitterBinnedTemplate(_PhotonMCMCFitter):
    """Template held as a binned lookup (reference ``mcmc_fitter.py:441``)."""

    def __init__(self, toas, model, template, nbins: int = 256, **kw):
        if isinstance(template, LCTemplate):
            grid = (np.arange(nbins) + 0.5) / nbins
            template_bins = np.asarray(template(grid), dtype=np.float64)
        else:
            template_bins = np.asarray(template, dtype=np.float64)
            nbins = len(template_bins)
            # normalize to a density (mean 1 over the cycle)
            template_bins = template_bins / template_bins.mean()
        self.template_bins = template_bins
        self.nbins = nbins
        super().__init__(toas, model, template, **kw)

    def set_template(self, template):
        """Replace the template (e.g. after an FFTFIT start-phase rotation):
        rebuilds the binned lookup AND the jitted likelihood, which bakes
        the bins in as constants."""
        self.template = template
        if isinstance(template, LCTemplate):
            grid = (np.arange(self.nbins) + 0.5) / self.nbins
            self.template_bins = np.asarray(template(grid), dtype=np.float64)
        else:
            tb = np.asarray(template, dtype=np.float64)
            self.template_bins = tb / tb.mean()
        self._batch_fn = None
        self._batch_fn_jit = None

    def _template_density(self, phifrac):
        import jax.numpy as jnp

        tb = jnp.asarray(self.template_bins)
        idx = jnp.clip((phifrac * self.nbins).astype(int), 0, self.nbins - 1)
        return tb[idx]


class MCMCFitterAnalyticTemplate(_PhotonMCMCFitter):
    """Analytic LCTemplate evaluated in-trace (reference
    ``mcmc_fitter.py:485``); template parameters stay fixed during timing
    sampling (fit them separately with LCFitter)."""

    def __init__(self, toas, model, template: LCTemplate, **kw):
        if not isinstance(template, LCTemplate):
            raise TypeError("MCMCFitterAnalyticTemplate needs an LCTemplate")
        super().__init__(toas, model, template, **kw)

    def _template_density(self, phifrac):
        return self.template(phifrac)
