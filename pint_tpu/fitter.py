"""Fitters: WLS (SVD), downhill iteration, auto dispatch.

Counterpart of reference ``fitter.py`` (class map at SURVEY §2):
``Fitter.auto`` (``fitter.py:193``), one-shot ``WLSFitter`` SVD solve
(``fitter.py:1821,2645``), ``DownhillWLSFitter`` lambda-halving state machine
(``fitter.py:843,919,1281``).  GLS-family fitters live in
:mod:`pint_tpu.gls_fitter` once noise models are present.

The linear algebra is jax/XLA (device-executable); the outer iteration is
Python (data-dependent control flow stays off the trace, SURVEY §7 "hard
parts").
"""

from __future__ import annotations

import copy
from typing import List, Optional

import numpy as np

from pint_tpu.exceptions import (
    ConvergenceFailure,
    CorrelatedErrors,
    DegeneracyWarning,
    MaxiterReached,
    NonFiniteSystemError,
    StepProblem,
    UsageError,
)
from pint_tpu.logging import log
from pint_tpu.residuals import Residuals
from pint_tpu.telemetry import jaxevents as _jaxevents
from pint_tpu.telemetry import span as _span
from pint_tpu.utils import normalize_designmatrix

__all__ = ["Fitter", "WLSFitter", "DownhillFitter", "DownhillWLSFitter",
           "LMFitter", "PowellFitter", "ModelState", "WLSState", "GLSState",
           "WidebandState", "fit_wls_svd", "apply_Sdiag_threshold",
           "get_gls_mtcm_mtcy", "get_gls_mtcm_mtcy_fullcov"]


class Fitter:
    """Base fitter: holds a model copy, TOAs, residuals, and fit products."""

    #: class-level defaults so subclasses with bespoke __init__ (wideband,
    #: MCMC) still carry the robust/quarantine state slots
    robust_weights = None
    robust_iterations = 0
    toas_full = None

    def __init__(self, toas, model, residuals: Optional[Residuals] = None,
                 track_mode: Optional[str] = None):
        from pint_tpu.runtime.preflight import check_device

        toas = self._consume_quarantine(toas)
        self.toas = toas
        self.model_init = model
        self.model = copy.deepcopy(model)
        self.track_mode = track_mode
        self.resids_init = Residuals(toas, self.model, track_mode=track_mode)
        self.resids = residuals or Residuals(toas, self.model, track_mode=track_mode)
        self.method = "base"
        self.converged = False
        self.parameter_covariance_matrix = None
        self.errors = {}
        # device-health preflight: the profile of the platform that will
        # execute this fit rides along with the results; a mismatch with a
        # required platform fails loudly per the config policy
        self.device_profile = check_device()
        self.solve_diagnostics = None
        #: per-TOA IRLS weights after a fit_toas(robust=...); None for a
        #: plain (non-robust) fit
        self.robust_weights = None
        self.robust_iterations = 0

    # -- reference-parity constructor dispatch ------------------------------
    @staticmethod
    def auto(toas, model, downhill: bool = True, **kw) -> "Fitter":
        """Choose the appropriate fitter for the model/TOAs (reference
        ``fitter.py:193``)."""
        wideband = getattr(toas, "wideband", False) or (
            any("pp_dm" in fl for fl in toas.flags)
        )
        if wideband:
            from pint_tpu.wideband import WidebandDownhillFitter, WidebandTOAFitter

            return (WidebandDownhillFitter if downhill else WidebandTOAFitter)(toas, model, **kw)
        if model.has_correlated_errors:
            from pint_tpu.gls_fitter import DownhillGLSFitter, GLSFitter

            return (DownhillGLSFitter if downhill else GLSFitter)(toas, model, **kw)
        return (DownhillWLSFitter if downhill else WLSFitter)(toas, model, **kw)

    # -- helpers ------------------------------------------------------------
    def _consume_quarantine(self, toas):
        """Quarantined rows (TOAs.validate) never reach a fit: returns the
        certified complement, keeping the full container reachable as
        ``self.toas_full`` for the doctor audit.  Every fitter __init__ —
        including the wideband family's bespoke ones — routes its TOAs
        through here."""
        qm = getattr(toas, "quarantine_mask", None)
        if qm is not None and np.any(qm):
            self.toas_full = toas
            toas = toas.certified()
            log.info(f"{type(self).__name__}: {int(np.sum(qm))} quarantined "
                     f"TOA(s) excluded; fitting {len(toas)} certified rows")
        return toas

    def update_resids(self):
        self.resids = Residuals(self.toas, self.model, track_mode=self.track_mode)
        return self.resids

    def _data_sigma(self) -> np.ndarray:
        """Scaled TOA uncertainties the linear solves consume; under an
        active robust (IRLS) fit the current Huber weights enter as
        sigma/sqrt(w), so a healthy fit (weights None) pays nothing."""
        sigma = np.asarray(self.resids.get_data_error())
        if self.robust_weights is not None:
            w = np.asarray(self.robust_weights, dtype=np.float64)
            sigma = sigma / np.sqrt(np.maximum(w, 1e-12))
        return sigma

    def _robust_update_weights(self, huber_k: float) -> np.ndarray:
        """Recompute Huber weights from the CURRENT whitened residuals,
        centered on their median: the phase-mean subtraction inside
        Residuals is itself non-robust (outliers drag it), and the
        constant shift is absorbed by the design matrix's Offset column
        anyway — without the recentering every row would look displaced
        and the weights would stop naming the actual outliers."""
        from pint_tpu.integrity.robust import huber_weights

        z = np.asarray(self.resids.time_resids) \
            / np.asarray(self.resids.get_data_error())
        finite = np.isfinite(z)
        if finite.any():
            z = z - np.median(z[finite])
        return huber_weights(z, k=huber_k)

    @staticmethod
    def _check_robust_arg(robust):
        if robust not in (None, False, "huber"):
            raise UsageError(
                f"robust must be None or 'huber', got {robust!r}")
        return bool(robust)

    def _run_irls(self, inner_fit, huber_k: Optional[float],
                  robust_maxiter: int, robust_tol: float,
                  tolerate_step_problem: bool = False) -> float:
        """The one IRLS harness both robust entry points share: weights
        from the current residuals, ``inner_fit()`` with weights held
        fixed, reweight, repeat until the weights settle.  With
        ``tolerate_step_problem`` an inner fit that can no longer decrease
        its (reweighted) objective after the first round falls through to
        the convergence check instead of raising.  Reports the PLAIN
        (unweighted) chi2, the same statistic as a non-robust fit."""
        from pint_tpu.integrity.robust import HUBER_K, irls_converged

        k = huber_k if huber_k is not None else HUBER_K
        self.update_resids()
        self.robust_weights = self._robust_update_weights(k)
        for it in range(max(1, robust_maxiter)):
            self.robust_iterations = it + 1
            try:
                inner_fit()
            except StepProblem:
                if not tolerate_step_problem or it == 0:
                    raise
                # the reweighted objective is already at its minimum for
                # these weights; fall through to the convergence check
            w_new = self._robust_update_weights(k)
            done = irls_converged(self.robust_weights, w_new, robust_tol)
            self.robust_weights = w_new
            if done:
                break
        else:
            log.warning(f"Huber IRLS hit robust_maxiter={robust_maxiter} "
                        "without the weights settling")
        chi2 = self.resids.chi2
        self.update_model(chi2)
        return chi2

    def fit_step_executables(self) -> dict:
        """``{name: (jitted fn, example args)}`` for the fit-step
        executables at this fitter's current state — the model's compiled
        phase evaluation (``fit.eval``) and its fit-parameter Jacobian
        (``fit.jac``).  The AOT cost-attribution hook consumed by
        :mod:`pint_tpu.telemetry.costs`: lowering at these args reuses
        the executables the fit itself runs (same shapes, same cache)."""
        model, toas = self.model, self.toas
        free = tuple(model.free_params)
        c = model._get_compiled(toas, free)
        fns = model._cache["fns"][(free, len(toas))]
        args = (model._free_values(free), model._const_pv(), c["batch"],
                c["ctx"])
        return {"fit.eval": (fns["eval"], args),
                "fit.jac": (fns["jac_frac"], args)}

    def doctor(self, designmatrix: bool = True) -> str:
        """Human-readable audit of this fit's inputs and state: device
        profile, TOA quarantine report, model/TOA compatibility findings
        (mask params selecting nothing, degenerate free-parameter pairs),
        and robust downweighting (:mod:`pint_tpu.integrity.doctor`)."""
        from pint_tpu.integrity.doctor import render_doctor_report

        return render_doctor_report(self, designmatrix=designmatrix)

    def update_model(self, chi2: Optional[float] = None):
        """Stamp fit products and TOA properties into the model (reference
        ``fitter.py:470``): START/FINISH/NTOA/EPHEM/DMDATA always, plus
        CHI2/CHI2R/TRES (and DMRES for wideband) after a fit."""
        m = self.model
        mjds = np.asarray(self.toas.get_mjds(), dtype=np.float64)
        if len(mjds):
            m.START.value = float(mjds.min())
            m.FINISH.value = float(mjds.max())
        m.NTOA.value = len(self.toas)
        if getattr(self.toas, "ephem", None):
            m.EPHEM.value = self.toas.ephem
        wideband = getattr(self, "is_wideband", False)
        m.DMDATA.value = "Y" if wideband else None
        if chi2 is not None:
            m.CHI2.value = chi2
            dof = self.resids.dof
            # never leave a stale CHI2R (e.g. from the input par) next to
            # a fresh CHI2
            m.CHI2R.value = chi2 / dof if dof > 0 else None
            if wideband:
                rms = self.resids.rms_weighted()
                m.TRES.value = rms["toa"] * 1e6
                m.DMRES.value = rms["dm"]
            else:
                m.TRES.value = self.resids.rms_weighted() * 1e6

    # -- maximum-likelihood noise fitting -----------------------------------
    def _get_free_noise_params(self) -> List[str]:
        """Unfrozen noise parameters (reference ``fitter.py:1160``)."""
        from pint_tpu.noisefit import free_noise_params

        return free_noise_params(self.model,
                                 wideband=getattr(self, "is_wideband", False))

    def _update_noise_params(self, names, values, errors=None):
        """Write ML noise estimates back to the model (reference
        ``fitter.py:1166``)."""
        for i, p in enumerate(names):
            par = getattr(self.model, p)
            # sign-degenerate parameters enter the likelihood squared;
            # report the physical (non-negative) branch
            v = float(values[i])
            if p.startswith(("EFAC", "EQUAD", "ECORR", "DMEFAC", "DMEQUAD")):
                v = abs(v)
            par.value = v
            if errors is not None:
                err = float(errors[i])
                par.uncertainty = err
                self.errors[p] = err

    def fit_noise(self, uncertainty: bool = False,
                  noisefit_method: str = "L-BFGS-B"):
        """One ML noise-parameter fit at the current timing solution
        (reference ``fitter.py:1179 _fit_noise``, autodiff gradients for
        every parameter class instead of hand gradients / Nelder-Mead).

        Returns a :class:`pint_tpu.noisefit.NoiseFitResult` (None when no
        noise parameter is free).  Does NOT write back to the model — the
        alternating loop in ``DownhillFitter.fit_toas`` does that via
        :meth:`_update_noise_params`.  Wideband fitters fit the joint
        TOA+DM likelihood (DMEFAC/DMEQUAD included).
        """
        from pint_tpu.noisefit import fit_noise_ml

        dm_resids = None
        if getattr(self, "is_wideband", False):
            dm_resids = np.asarray(self.resids.dm.resids)
        return fit_noise_ml(self.model, self.toas,
                            np.asarray(self.resids.time_resids),
                            dm_resids=dm_resids,
                            method=noisefit_method, uncertainty=uncertainty)

    def get_fitparams(self) -> dict:
        return {p: getattr(self.model, p).value for p in self.model.free_params}

    def get_designmatrix(self):
        # iterative fits recompute M every step; constant (linear) columns
        # come from the model's cache (timing_model._jac_frac_linear_cached)
        return self.model.designmatrix(self.toas, reuse_linear=True)

    def _set_covariance(self, cov, params):
        """Store the post-fit parameter covariance as a labeled
        :class:`~pint_tpu.pint_matrix.CovarianceMatrix` (reference
        ``fitter.py`` exposes ``parameter_covariance_matrix`` with labeled
        axes, built by ``pint_matrix.py:660``)."""
        from pint_tpu.pint_matrix import CovarianceMatrix

        labels = {p: (i, i + 1, "") for i, p in enumerate(params)}
        self.parameter_covariance_matrix = CovarianceMatrix(
            np.asarray(cov), [labels, labels])

    def get_parameter_correlation_matrix(self, pretty_print: bool = False):
        cov = self.parameter_covariance_matrix
        if cov is None:
            return None
        corr = cov.to_correlation_matrix()
        if pretty_print:
            print(corr.prettyprint())
        return corr

    # -- reference accessor long tail (fitter.py user API) -------------------
    def get_allparams(self) -> dict:
        """{name: value} for every parameter, free or frozen (reference
        ``fitter.py get_allparams``)."""
        return {p: getattr(self.model, p).value for p in self.model.params}

    def get_fitparams_num(self) -> dict:
        """{name: float value} for the free parameters (reference
        ``fitter.py get_fitparams_num``)."""
        return {p: float(getattr(self.model, p).value or 0.0)
                for p in self.model.free_params}

    def get_fitparams_uncertainty(self) -> dict:
        """{name: uncertainty} for the free parameters (reference
        ``fitter.py get_fitparams_uncertainty``)."""
        return {p: getattr(self.model, p).uncertainty
                for p in self.model.free_params}

    def get_params_dict(self, which: str = "free",
                        kind: str = "quantity") -> dict:
        """Parameter mapping (reference ``fitter.py get_params_dict``):
        ``which`` in free/all, ``kind`` in quantity/value/uncertainty."""
        names = self.model.free_params if which == "free" else self.model.params
        if kind in ("quantity", "value"):
            return {p: getattr(self.model, p).value for p in names}
        if kind == "uncertainty":
            return {p: getattr(self.model, p).uncertainty for p in names}
        raise UsageError(f"Unknown kind {kind!r}")

    def set_params(self, fitp: dict) -> None:
        """Set parameter values from a {name: value} mapping (reference
        ``fitter.py set_params``)."""
        for p, v in fitp.items():
            getattr(self.model, p).value = v

    set_fitparams = set_params

    def set_param_uncertainties(self, fitp: dict) -> None:
        """Set parameter uncertainties from a mapping (reference
        ``fitter.py set_param_uncertainties``)."""
        for p, v in fitp.items():
            getattr(self.model, p).uncertainty = float(v)

    @property
    def covariance_matrix(self):
        """The labeled post-fit parameter covariance (reference exposes
        both spellings)."""
        return self.parameter_covariance_matrix

    def get_parameter_covariance_matrix(self, with_phase: bool = False):
        """The labeled covariance, optionally including the Offset row
        (reference ``fitter.py get_parameter_covariance_matrix``)."""
        cov = self.parameter_covariance_matrix
        if cov is None or with_phase:
            return cov
        names = [n for n in cov.get_label_names(axis=0) if n != "Offset"]
        return cov.get_label_matrix(names)

    def make_resids(self, model) -> Residuals:
        """Residuals of THIS fitter's TOAs under an arbitrary model
        (reference ``fitter.py make_resids``)."""
        return Residuals(self.toas, model, track_mode=self.track_mode)

    def reset_model(self) -> None:
        """Forget the fit: restore the initial model and residuals
        (reference ``fitter.py reset_model``)."""
        self.model = copy.deepcopy(self.model_init)
        self.converged = False
        self.parameter_covariance_matrix = None
        self.errors = {}
        self.update_resids()

    def plot(self):
        """Plot residuals vs MJD with error bars (reference
        ``fitter.py plot``; requires matplotlib)."""
        import matplotlib.pyplot as plt

        mjds = np.asarray(self.toas.get_mjds(), dtype=np.float64)
        r = np.asarray(self.resids.time_resids) * 1e6
        err = np.asarray(self.resids.get_data_error()) * 1e6
        fig, ax = plt.subplots(figsize=(8, 4.5))
        ax.errorbar(mjds, r, yerr=err, fmt="+")
        ax.set_xlabel("MJD")
        ax.set_ylabel("Residual (us)")
        ax.set_title(getattr(self.model.PSR, "value", "") or "")
        ax.grid(True)
        plt.show()
        return fig

    def ftest(self, parameter, component=None, remove: bool = False,
              full_output: bool = False, maxiter: int = 1):
        """Significance of adding/removing parameters (reference
        ``fitter.py:565``): builds the modified model, refits it, and
        returns {"ft": p-value} (plus residual RMS / chi2 / dof with
        ``full_output``).  ``parameter`` is a Parameter (or list);
        ``component`` the hosting component name(s) when adding.

        The low-level two-number form ``ftest(chi2_other, dof_other)`` is
        also accepted and compares directly against this fitter's fit.
        """
        from pint_tpu.utils import FTest

        if isinstance(parameter, (int, float, np.integer, np.floating)) \
                and isinstance(component,
                               (int, float, np.integer, np.floating)):
            return FTest(float(parameter), int(component),
                         self.resids.chi2, self.resids.dof)

        params = parameter if isinstance(parameter, (list, tuple)) \
            else [parameter]
        comps = component if isinstance(component, (list, tuple)) \
            else [component] * len(params)
        if not remove and len(comps) != len(params):
            raise UsageError("one component per parameter required")
        m = copy.deepcopy(self.model)
        if remove:
            for p in params:
                m.remove_param(p.name)
        else:
            for p, cname in zip(params, comps):
                if cname not in m.components:
                    raise UsageError(f"component {cname!r} not in model")
                par = copy.deepcopy(p)
                par.frozen = False
                m.components[cname].add_param(par, setup=True)
        m.setup()
        f2 = type(self)(self.toas, m, track_mode=self.track_mode)
        f2.fit_toas(maxiter=max(1, maxiter))
        chi2_base, dof_base = self.resids.chi2, self.resids.dof
        chi2_new, dof_new = f2.resids.chi2, f2.resids.dof
        if remove:
            # the NEW model is the simpler one
            ft = FTest(chi2_new, dof_new, chi2_base, dof_base)
        else:
            ft = FTest(chi2_base, dof_base, chi2_new, dof_new)
        out = {"ft": ft}
        if full_output:
            rms = f2.resids.rms_weighted()
            if isinstance(rms, dict):  # wideband: report the TOA axis
                rms = rms["toa"]
            out["resid_rms_test"] = rms * 1e6
            out["chi2_test"] = chi2_new
            out["dof_test"] = dof_new
        return out

    def print_summary(self):
        print(self.get_summary())

    def get_summary(self, nodmx: bool = True) -> str:
        """Human-readable fit report (reference ``fitter.py:295,442``)."""
        r = self.resids

        def _toa_rms(resids):
            rms = resids.rms_weighted()
            return rms["toa"] if isinstance(rms, dict) else rms  # wideband

        lines = [
            f"Fitted model using {self.method} with {len(self.model.free_params)} free parameters to {len(self.toas)} TOAs",
            f"Prefit residuals Wrms = {_toa_rms(self.resids_init) * 1e6:.4f} us, "
            f"Postfit residuals Wrms = {_toa_rms(r) * 1e6:.4f} us",
            f"Chisq = {r.chi2:.3f} for {r.dof} d.o.f. for reduced Chisq of {r.reduced_chi2:.3f}",
            "",
            f"{'PAR':<12} {'Prefit':>20} {'Postfit':>20} {'Uncertainty':>14} {'Units':>10}",
        ]
        for p in self.model.free_params:
            if nodmx and p.startswith("DMX"):
                continue
            pre = getattr(self.model_init, p).value
            post = getattr(self.model, p).value
            unc = self.errors.get(p)
            lines.append(
                f"{p:<12} {str(pre):>20} {str(post):>20} "
                f"{(f'{unc:.3g}' if unc is not None else '-'):>14} "
                f"{getattr(self.model, p).units:>10}"
            )
        return "\n".join(lines) + "\n\n" + self.get_derived_params()

    def get_derived_params(self, returndict: bool = False):
        """Derived quantities from the fitted model, feeding the post-fit
        residual rms into the ELL1 validity check (reference
        ``fitter.py:414``)."""
        rms = self.resids.rms_weighted()
        if isinstance(rms, dict):  # wideband: use the TOA-residual rms
            rms = rms["toa"]
        return self.model.get_derived_params(
            rms=rms * 1e6, ntoas=len(self.toas), returndict=returndict)

    def fit_toas(self, maxiter: int = 1, **kw) -> float:
        raise NotImplementedError

    # minimal API parity with reference fitters
    def minimize_func(self, values: List[float], params: List[str]) -> float:
        for v, p in zip(values, params):
            getattr(self.model, p).value = v
        self.update_resids()
        return self.resids.chi2


def _wls_step(M: np.ndarray, params: List[str], r: np.ndarray, sigma: np.ndarray,
              threshold: Optional[float] = None):
    """One whitened, normalized SVD least-squares solve.

    Returns (dpars, cov, singular_values).  Thin wrapper over the public
    :func:`fit_wls_svd` (single source for the SVD/degeneracy numerics)
    with the default near-machine-precision threshold."""
    if threshold is None:
        threshold = np.finfo(np.float64).eps * max(np.asarray(M).shape)
    dpars, cov, _, (_, S, _) = fit_wls_svd(r, sigma, M, list(params),
                                           threshold)
    return dpars, cov, S


class WLSFitter(Fitter):
    """One-shot weighted-least-squares fitter (reference ``fitter.py:1821``)."""

    def __init__(self, toas, model, **kw):
        super().__init__(toas, model, **kw)
        if model.has_correlated_errors:
            raise CorrelatedErrors(model)
        self.method = "weighted_least_square"

    def fit_toas(self, maxiter: int = 1, threshold: Optional[float] = None,
                 debug: bool = False, robust=None,
                 huber_k: Optional[float] = None, robust_maxiter: int = 30,
                 robust_tol: float = 1e-3) -> float:
        """One-shot WLS fit; ``robust="huber"`` wraps the solve in a
        host-side IRLS loop that Huber-downweights outlier TOAs (weights
        exposed as ``self.robust_weights`` and in :meth:`doctor`)."""
        if self._check_robust_arg(robust):
            return self._fit_toas_robust(maxiter=maxiter, threshold=threshold,
                                         huber_k=huber_k,
                                         robust_maxiter=robust_maxiter,
                                         robust_tol=robust_tol)
        # a plain fit must never inherit weights from an earlier robust
        # fit on this same fitter — _data_sigma would keep applying them
        self.robust_weights = None
        self.robust_iterations = 0
        return self._fit_wls(maxiter=maxiter, threshold=threshold)

    def _fit_toas_robust(self, maxiter: int, threshold: Optional[float],
                         huber_k: Optional[float], robust_maxiter: int,
                         robust_tol: float) -> float:
        return self._run_irls(
            lambda: self._fit_wls(maxiter=maxiter, threshold=threshold),
            huber_k=huber_k, robust_maxiter=robust_maxiter,
            robust_tol=robust_tol)

    def _fit_wls(self, maxiter: int = 1,
                 threshold: Optional[float] = None) -> float:
        with _span("wls.fit_toas", ntoas=len(self.toas),
                   nfree=len(self.model.free_params),
                   maxiter=maxiter) as sp, _jaxevents.watch(sp):
            chi2 = self.resids.chi2
            for it in range(max(1, maxiter)):
                with _span("wls.step", iteration=it):
                    r = self.resids.time_resids
                    sigma = self._data_sigma()
                    M, params, units = self.get_designmatrix()
                    dpars, cov, S = _wls_step(M, params, r, sigma, threshold)
                    for dp, p in zip(dpars, params):
                        if p == "Offset":
                            continue
                        par = getattr(self.model, p)
                        par.value = float(par.value or 0.0) + float(dp)
                    self.update_resids()
                    chi2 = self.resids.chi2
                self._set_covariance(cov, params)
                self.fitted_params = params
                for i, p in enumerate(params):
                    if p == "Offset":
                        continue
                    err = float(np.sqrt(cov[i, i]))
                    self.errors[p] = err
                    getattr(self.model, p).uncertainty = err
            sp.attrs["chi2"] = float(chi2)
            self.converged = True
            self.update_model(chi2)
            return chi2


class DownhillFitter(Fitter):
    """Iterative fitter with lambda-halving line search (reference
    ``fitter.py:843 ModelState`` / ``fitter.py:919 step``)."""

    def __init__(self, toas, model, **kw):
        super().__init__(toas, model, **kw)
        self.method = "downhill"

    def _solve_step(self):
        r = self.resids.time_resids
        sigma = self._data_sigma()
        M, params, units = self.get_designmatrix()
        dpars, cov, S = _wls_step(M, params, r, sigma)
        return dpars, params, cov

    def _fit_metric(self) -> float:
        """The scalar the downhill line search minimizes: plain chi2, or
        the Huber-weighted chi2 while an IRLS pass holds weights fixed
        (so a robust step that shrugs off an outlier is still accepted)."""
        if self.robust_weights is None:
            return self.resids.chi2
        r = np.asarray(self.resids.time_resids)
        s = np.asarray(self.resids.get_data_error())
        return float(np.sum(self.robust_weights * (r / s) ** 2))

    def fit_toas(self, maxiter: int = 20, required_chi2_decrease: float = 1e-2,
                 max_chi2_increase: float = 1e-2, min_lambda: float = 1e-3,
                 debug: bool = False, noise_fit_niter: int = 2,
                 noisefit_method: str = "L-BFGS-B",
                 compute_noise_uncertainties: bool = True,
                 raise_on_maxiter: bool = False, robust=None,
                 huber_k: Optional[float] = None, robust_maxiter: int = 30,
                 robust_tol: float = 1e-3) -> float:
        """Downhill timing fit; when any noise parameter is unfrozen the
        timing fit alternates with ML noise fits (reference
        ``fitter.py:1086-1150``): ``noise_fit_niter`` rounds of
        (timing fit, noise fit), uncertainty Hessian on the last noise fit,
        then one final timing fit at the updated noise values.

        ``raise_on_maxiter=True`` turns the exhausted-iteration warning
        into a typed :class:`~pint_tpu.exceptions.MaxiterReached`.
        ``robust="huber"`` wraps the downhill fit in a host-side IRLS
        loop (WLS-family fitters only)."""
        if self._check_robust_arg(robust):
            if not isinstance(self, DownhillWLSFitter) \
                    and type(self) is not DownhillFitter:
                raise UsageError(
                    "robust fitting is available on the WLS-family fitters "
                    "only (Huber IRLS assumes uncorrelated errors)")
            if self._get_free_noise_params():
                raise UsageError(
                    "robust fitting cannot be combined with free noise "
                    "parameters; freeze them or fit noise separately")
            return self._fit_toas_robust_downhill(
                maxiter=maxiter,
                required_chi2_decrease=required_chi2_decrease,
                max_chi2_increase=max_chi2_increase, min_lambda=min_lambda,
                debug=debug, raise_on_maxiter=raise_on_maxiter,
                huber_k=huber_k, robust_maxiter=robust_maxiter,
                robust_tol=robust_tol)
        # a plain fit must never inherit weights from an earlier robust
        # fit on this same fitter (_solve_step/_fit_metric consume them)
        self.robust_weights = None
        self.robust_iterations = 0
        if self._get_free_noise_params():
            kw = dict(maxiter=maxiter,
                      required_chi2_decrease=required_chi2_decrease,
                      max_chi2_increase=max_chi2_increase,
                      min_lambda=min_lambda, debug=debug,
                      raise_on_maxiter=raise_on_maxiter)
            for ii in range(noise_fit_niter):
                self._fit_toas_timing(**kw)
                last = ii == noise_fit_niter - 1
                res = self.fit_noise(
                    uncertainty=last and compute_noise_uncertainties,
                    noisefit_method=noisefit_method)
                log.info(f"noise fit round {ii + 1}/{noise_fit_niter}: {res}")
                self._update_noise_params(res.names, res.values, res.errors)
                self.update_resids()
            return self._fit_toas_timing(**kw)
        return self._fit_toas_timing(
            maxiter=maxiter, required_chi2_decrease=required_chi2_decrease,
            max_chi2_increase=max_chi2_increase, min_lambda=min_lambda,
            debug=debug, raise_on_maxiter=raise_on_maxiter)

    def _fit_toas_robust_downhill(self, huber_k: Optional[float],
                                  robust_maxiter: int, robust_tol: float,
                                  **timing_kw) -> float:
        return self._run_irls(
            lambda: self._fit_toas_timing(**timing_kw),
            huber_k=huber_k, robust_maxiter=robust_maxiter,
            robust_tol=robust_tol, tolerate_step_problem=True)

    def _fit_toas_timing(self, maxiter: int = 20,
                         required_chi2_decrease: float = 1e-2,
                         max_chi2_increase: float = 1e-2,
                         min_lambda: float = 1e-3,
                         debug: bool = False,
                         raise_on_maxiter: bool = False) -> float:
        with _span(f"{self.method}.fit_toas", ntoas=len(self.toas),
                   nfree=len(self.model.free_params),
                   maxiter=maxiter) as sp, _jaxevents.watch(sp):
            return self._fit_toas_timing_inner(
                sp, maxiter, required_chi2_decrease, max_chi2_increase,
                min_lambda, debug, raise_on_maxiter)

    def _fit_toas_timing_inner(self, sp, maxiter, required_chi2_decrease,
                               max_chi2_increase, min_lambda, debug,
                               raise_on_maxiter) -> float:
        best_chi2 = self._fit_metric()
        self.converged = False
        for it in range(maxiter):
            dpars, params, cov = self._solve_step()
            base_vals = {p: float(getattr(self.model, p).value or 0.0)
                         for p in params if p != "Offset"}
            lam = 1.0
            improved = False
            while lam >= min_lambda:
                for dp, p in zip(dpars, params):
                    if p == "Offset":
                        continue
                    getattr(self.model, p).value = base_vals[p] + lam * float(dp)
                self.update_resids()
                chi2 = self._fit_metric()
                if chi2 < best_chi2 + max_chi2_increase:
                    improved = True
                    break
                lam *= 0.5
            if not improved:
                # restore and stop
                for p, v in base_vals.items():
                    getattr(self.model, p).value = v
                self.update_resids()
                if it == 0:
                    raise StepProblem(
                        f"chi2 would not decrease from {best_chi2:.3f}")
                break
            decrease = best_chi2 - chi2
            best_chi2 = chi2
            sp.add_event("downhill.step", iteration=it, chi2=float(chi2),
                         lambda_=lam)
            self._set_covariance(cov, params)
            self.fitted_params = params
            for i, p in enumerate(params):
                if p == "Offset":
                    continue
                err = float(np.sqrt(cov[i, i]))
                self.errors[p] = err
                getattr(self.model, p).uncertainty = err
            if decrease < required_chi2_decrease and lam == 1.0:
                self.converged = True
                break
        else:
            if raise_on_maxiter:
                raise MaxiterReached(
                    f"Downhill fit hit maxiter={maxiter} without meeting "
                    f"tolerance (chi2 {best_chi2:.3f})")
            log.warning(f"Downhill fit hit maxiter={maxiter}")
        sp.attrs["chi2"] = float(best_chi2)
        sp.attrs["converged"] = self.converged
        self.update_model(best_chi2)
        return best_chi2


class DownhillWLSFitter(DownhillFitter):
    """Reference ``fitter.py:1281``."""

    def __init__(self, toas, model, **kw):
        if model.has_correlated_errors:
            raise CorrelatedErrors(model)
        super().__init__(toas, model, **kw)
        self.method = "downhill_wls"


class LMFitter(Fitter):
    """Levenberg-Marquardt fitter (reference ``fitter.py:2426``): damped
    normal equations A = M^T C^-1 M + phiinv + lambda*diag(M^T C^-1 M),
    with the reference's lambda schedule (decrease on success, increase x3
    on a chi2 increase, x10 when ill-conditioned)."""

    def __init__(self, toas, model, **kw):
        super().__init__(toas, model, **kw)
        self.method = "levenberg_marquardt"

    #: WidebandLMFitter flips this to stack the DM rows
    wideband_system = False

    def _residual_vector(self) -> np.ndarray:
        return np.asarray(self.resids.time_resids)

    def _normal_system(self):
        """(mtcm_plain, phiinv, mtcy, norm, params) at the current model."""
        from pint_tpu.gls_fitter import build_augmented_system

        r = self._residual_vector()
        M, params, norm, phiinv, Nvec, dims = build_augmented_system(
            self.model, self.toas, wideband=self.wideband_system)
        self._noise_dims = dims
        cinv = 1.0 / Nvec
        mtcm_plain = M.T @ (cinv[:, None] * M)
        mtcy = M.T @ (cinv * r)
        return mtcm_plain, phiinv, mtcy, norm, params

    def _current_chi2(self) -> float:
        return self.resids.calc_chi2()

    def fit_toas(self, maxiter: int = 50, min_chi2_decrease: float = 1e-3,
                 lambda_factor_decrease: float = 2.0,
                 lambda_factor_increase: float = 3.0,
                 min_lambda: float = 0.5, threshold: float = 1e-14,
                 debug: bool = False) -> float:
        from pint_tpu.gls_fitter import _solve_svd

        self.update_resids()
        chi2 = self._current_chi2()
        lam = min_lambda
        self.converged = False
        for it in range(maxiter):
            mtcm_plain, phiinv, mtcy, norm, params = self._normal_system()
            mtcm = mtcm_plain + np.diag(phiinv)
            lf = lam if lam > min_lambda else 0.0
            A = mtcm + lf * np.diag(np.diag(mtcm_plain))
            xvar, xhat, self.solve_diagnostics = _solve_svd(
                A, mtcy, threshold, params)
            step = xhat / norm
            base = {p: float(getattr(self.model, p).value or 0.0)
                    for p in params if p != "Offset"}
            for dp, p in zip(step[:len(params)], params):
                if p != "Offset":
                    getattr(self.model, p).value = base[p] + float(dp)
            self.update_resids()
            new_chi2 = self._current_chi2()
            decrease = chi2 - new_chi2
            if not np.isfinite(new_chi2) or decrease < -min_chi2_decrease:
                # reject: restore and raise damping
                for p, v in base.items():
                    getattr(self.model, p).value = v
                self.update_resids()
                lam *= lambda_factor_increase
                if lam > 1e9:
                    raise ConvergenceFailure("LM damping diverged")
                continue
            # accept; a small change of either sign means convergence (small
            # increases within the tolerance were accepted above)
            chi2 = new_chi2
            if decrease < min_chi2_decrease:
                self.converged = True
                break
            lam = max(lam / lambda_factor_decrease, min_lambda)
        else:
            log.warning(f"LM fit hit maxiter={maxiter}")
        # uncertainties/covariance from the UNDAMPED curvature at the final
        # parameters — inv(mtcm + lambda*diag) would be biased low by the
        # damping state at exit
        mtcm_plain, phiinv, mtcy, norm, params = self._normal_system()
        xvar, _, _ = _solve_svd(mtcm_plain + np.diag(phiinv), mtcy,
                                threshold, params)
        errs = np.sqrt(np.diag(xvar)) / norm
        covmat = (xvar / norm).T / norm
        ntm = len(params)
        self._set_covariance(covmat[:ntm, :ntm], params)
        self.fitted_params = params
        for i, p in enumerate(params):
            if p != "Offset":
                self.errors[p] = float(errs[i])
                getattr(self.model, p).uncertainty = float(errs[i])
        self.update_model(chi2)
        return chi2


class PowellFitter(Fitter):
    """Derivative-free scipy Powell minimization over the free parameters
    (reference ``fitter.py:1777``; legacy/backstop fitter)."""

    def __init__(self, toas, model, **kw):
        super().__init__(toas, model, **kw)
        self.method = "Powell"

    def fit_toas(self, maxiter: int = 20, **kw) -> float:
        from scipy.optimize import minimize

        params = list(self.model.free_params)
        x0 = np.array([float(getattr(self.model, p).value or 0.0)
                       for p in params])
        # scale: parameter uncertainties when available, else 1e-8 relative
        scale = np.array([
            float(getattr(self.model, p).uncertainty or 0.0) or
            (abs(x) * 1e-8 if x else 1e-10) for p, x in zip(params, x0)])

        def fun(z):
            return self.minimize_func(list(x0 + z * scale), params)

        res = minimize(fun, np.zeros(len(params)), method="Powell",
                       options={"maxiter": maxiter, "xtol": 1e-10,
                                "ftol": 1e-10})
        self.minimize_func(list(x0 + res.x * scale), params)
        self.fitted_params = params
        self.converged = bool(res.success)
        chi2 = self.resids.chi2
        self.update_model(chi2)
        return chi2


# ---------------------------------------------------------------------------
# public linear-algebra helpers (reference fitter.py:2621-2726 free functions)
# ---------------------------------------------------------------------------

def apply_Sdiag_threshold(Sdiag, VT, threshold, params):
    """Replace singular values <= ``threshold * Sdiag.max()`` with inf and
    warn, naming the degenerate parameter combination (reference
    ``fitter.py:2621``).  Dividing by inf then zeroes those directions —
    i.e. the pseudo-inverse restricted to the non-singular subspace."""
    import warnings

    Sdiag = np.asarray(Sdiag, dtype=np.float64).copy()
    smax = Sdiag.max() if Sdiag.size else 1.0
    for c in np.nonzero(Sdiag <= threshold * smax)[0]:
        v = np.asarray(VT)[c]
        v = v / max(np.abs(v).max(), 1e-300)
        combo = " + ".join(f"{co:.3g}*{p}" for co, p in
                           sorted(zip(v, params), key=lambda t: -abs(t[0]))
                           if abs(co) > threshold)
        warnings.warn("Parameter degeneracy; the following linear "
                      f"combination yields almost no change: {combo}",
                      DegeneracyWarning)
        Sdiag[c] = np.inf
    return Sdiag


def fit_wls_svd(r, sigma, M, params, threshold):
    """One whitened, column-normalized SVD WLS solve (reference
    ``fitter.py:2645``): returns ``(dpars, Sigma, Adiag, (U, S, VT))`` with
    ``Sigma`` the parameter covariance and ``Adiag`` the column norms used
    for conditioning.  Degenerate directions are dropped via
    :func:`apply_Sdiag_threshold`."""
    r = np.asarray(r, dtype=np.float64)
    sigma = np.asarray(sigma, dtype=np.float64)
    if not (np.all(np.isfinite(r)) and np.all(np.isfinite(M))
            and np.all(np.isfinite(sigma))):
        raise NonFiniteSystemError(
            "WLS residuals/design matrix/uncertainties contain NaN/inf; "
            "refusing the solve (the SVD would emit silent garbage or "
            "fail untyped)")
    Mw = np.asarray(M, dtype=np.float64) / sigma[:, None]
    rw = r / sigma
    Mn, Adiag = normalize_designmatrix(Mw)
    Mn, Adiag = np.asarray(Mn), np.asarray(Adiag)
    U, S, VT = np.linalg.svd(Mn, full_matrices=False)
    S = apply_Sdiag_threshold(S, VT, threshold, list(params))
    dpars = (VT.T @ ((U.T @ rw) / S)) / Adiag
    Sigma = ((VT.T / S**2) @ VT) / np.outer(Adiag, Adiag)
    return dpars, Sigma, Adiag, (U, S, VT)


def get_gls_mtcm_mtcy(phiinv, Nvec, M, residuals):
    """``(M^T N^-1 M + diag(phiinv), M^T N^-1 y)`` for the basis-augmented
    GLS normal equations (reference ``fitter.py:2712``): ``M`` holds the
    timing design matrix plus correlated-noise basis columns, ``Nvec`` the
    white variances, ``phiinv`` the basis weights (zeros for the timing
    columns)."""
    Ninv = 1.0 / np.asarray(Nvec, dtype=np.float64)
    M = np.asarray(M, dtype=np.float64)
    mtcm = M.T @ (Ninv[:, None] * M) + np.diag(np.asarray(phiinv))
    mtcy = M.T @ (Ninv * np.asarray(residuals, dtype=np.float64))
    return mtcm, mtcy


def get_gls_mtcm_mtcy_fullcov(cov, M, residuals):
    """``(M^T C^-1 M, M^T C^-1 y)`` with the FULL data covariance ``C``
    (reference ``fitter.py:2696``; the ``full_cov=True`` GLS path)."""
    import scipy.linalg as sl

    M = np.asarray(M, dtype=np.float64)
    cf = sl.cho_factor(np.asarray(cov, dtype=np.float64))
    cm = sl.cho_solve(cf, M)
    return M.T @ cm, cm.T @ np.asarray(residuals, dtype=np.float64)


# ---------------------------------------------------------------------------
# lazily-evaluated model states (reference fitter.py:843 ModelState family)
# ---------------------------------------------------------------------------

class ModelState:
    """A (model, fit products) snapshot during a downhill fit: residuals,
    chi2, the linearized step and its covariance, all computed lazily and
    cached (reference ``fitter.py:843``).  Immutable by convention; taking
    a step yields a NEW state.  The heavy lifting delegates to the matching
    downhill fitter's ``_solve_step`` so the numerics are exactly the ones
    the fit itself uses."""

    def __init__(self, fitter, model=None):
        self.fitter = fitter
        self.model = model if model is not None else fitter.model
        self._cache = {}

    def _fitter_cls(self):
        return DownhillWLSFitter

    def _work(self):
        if "work" not in self._cache:
            self._cache["work"] = self._fitter_cls()(
                self.fitter.toas, self.model,
                track_mode=getattr(self.fitter, "track_mode", None))
        return self._cache["work"]

    @property
    def params(self):
        return list(self.model.free_params)

    @property
    def resids(self):
        return self._work().resids

    @property
    def chi2(self):
        if "chi2" not in self._cache:
            self._cache["chi2"] = float(self.resids.chi2)
        return self._cache["chi2"]

    def _solve(self):
        if "step" not in self._cache:
            dpars, params, cov = self._work()._solve_step()
            self._cache["step"] = (np.asarray(dpars), list(params),
                                   np.asarray(cov))
        return self._cache["step"]

    @property
    def step(self):
        return self._solve()[0]

    @property
    def parameter_covariance_matrix(self):
        return self._solve()[2]

    def predicted_chi2(self, step=None, lambda_=1.0):
        """Quadratic-model chi2 prediction after ``lambda_ * step`` (the
        quantity the downhill line search compares against).

        For a Gauss-Newton step ``s = Sigma b`` the linearized decrease is
        ``(2 lambda - lambda^2) s^T Sigma^-1 s`` — stated purely in the
        solver's own metric (covariance), so it is consistent with
        ``.chi2`` for EVERY state flavor, including the correlated-noise
        GLS and wideband forms (a whitened-residual formula here would be
        a different metric for those)."""
        dpars, _, cov = self._solve()
        s = np.asarray(dpars if step is None else step, dtype=np.float64)
        sn, *_ = np.linalg.lstsq(cov, s, rcond=None)
        dec = float(s @ sn)
        return self.chi2 - (2 * lambda_ - lambda_**2) * dec

    def take_step_model(self, step, lambda_=1.0):
        """A new model displaced by ``lambda_ * step`` along the solver's
        parameter list.  The leading 'Offset' column (the weighted-mean
        phase absorbed by the designmatrix) has no model parameter and is
        skipped."""
        import copy as _copy

        _, params, _ = self._solve()
        new = _copy.deepcopy(self.model)
        for p, s in zip(params, np.asarray(step) * lambda_):
            if p not in new.params:
                continue
            par = getattr(new, p)
            par.value = float(par.value or 0.0) + float(s)
        return new

    def take_step(self, step=None, lambda_=1.0):
        if step is None:
            step = self.step
        return type(self)(self.fitter, self.take_step_model(step, lambda_))


class WLSState(ModelState):
    """Uncorrelated-noise state (reference ``fitter.py:1225``)."""


class GLSState(ModelState):
    """Correlated-noise (Woodbury GLS) state (reference ``fitter.py:1332``)."""

    def _fitter_cls(self):
        from pint_tpu.gls_fitter import DownhillGLSFitter

        return DownhillGLSFitter


class WidebandState(ModelState):
    """Wideband (TOA + DM) state (reference ``fitter.py:1494``)."""

    def _fitter_cls(self):
        from pint_tpu.wideband import WidebandDownhillFitter

        return WidebandDownhillFitter
