"""Precision MJD utilities under the reference's ``pulsar_mjd`` names.

Counterpart of reference ``pulsar_mjd.py`` (``str_to_mjds``/``mjds_to_str``
``pulsar_mjd.py:488,521``, ``day_frac`` ``pulsar_mjd.py:529``, error-free
transforms ``pulsar_mjd.py:586,609,638``, longdouble helpers
``pulsar_mjd.py:314-365``, jd<->mjd conversions ``pulsar_mjd.py:389-430``).

The device-side precision story lives in :mod:`pint_tpu.dd` (double-double
pairs); this module is the HOST-side boundary: exact string<->(int, frac)
MJD splits, the "pulsar_mjd" leap-second convention (every day is 86400 s;
a leap second is unrepresentable), and numpy-longdouble interop.  The
reference's astropy ``TimeFormat`` subclasses (``PulsarMJD`` etc.) have no
counterpart because astropy is not a dependency — ``TOAs.utc_mjd`` carries
the same (longdouble + float64-tail) information directly.
"""

from __future__ import annotations

import numpy as np

from pint_tpu.timescales import _LEAP_TABLE, tai_minus_utc

__all__ = [
    "two_sum", "two_product", "split", "day_frac",
    "str_to_mjds", "mjds_to_str", "jds_to_mjds", "mjds_to_jds",
    "jds_to_mjds_pulsar", "mjds_to_jds_pulsar",
    "data2longdouble", "longdouble2str", "str2longdouble",
    "quantity2longdouble_withunit", "safe_kind_conversion",
    "time_to_longdouble", "time_from_longdouble",
    "time_to_mjd_string", "time_from_mjd_string",
    "TimeFormatMJD", "PulsarMJD", "MJDLong", "PulsarMJDLong",
    "MJDString", "PulsarMJDString",
]

DJM0 = 2400000.5  # JD of MJD epoch (erfa.DJM0)


# ---------------------------------------------------------------------------
# error-free transforms (reference pulsar_mjd.py:586,609,638; host numpy —
# IEEE-correct on CPU, unlike on-device TPU f64, see dd.py)
# ---------------------------------------------------------------------------

def two_sum(a, b):
    """Exact a + b = s + e as two float64s (Knuth two-sum)."""
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    s = a + b
    bb = s - a
    e = (a - (s - bb)) + (b - bb)
    return s, e


# 2**27 + 1, Dekker splitter: host-side numpy float64 always (this module
# never runs on device)
_SPLITTER = 134217729.0  # jaxlint: disable=f32-unsafe-literal


def split(a):
    """Dekker split: a = hi + lo with both halves 26-bit."""
    a = np.asarray(a, np.float64)
    t = _SPLITTER * a
    hi = t - (t - a)
    return hi, a - hi


def two_product(a, b):
    """Exact a * b = p + e as two float64s (Dekker product)."""
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    p = a * b
    ah, al = split(a)
    bh, bl = split(b)
    e = ((ah * bh - p) + ah * bl + al * bh) + al * bl
    return p, e


def day_frac(val1, val2, factor=None, divisor=None):
    """Sum (optionally scaled) as exact (integer day, frac) float64 pair,
    frac in [-0.5, 0.5] (reference ``pulsar_mjd.py:529``)."""
    sum12, err12 = two_sum(val1, val2)
    if factor is not None:
        sum12, carry = two_product(sum12, factor)
        carry += err12 * factor
        sum12, err12 = two_sum(sum12, carry)
    if divisor is not None:
        q1 = sum12 / divisor
        p1, p2 = two_product(q1, divisor)
        d1, d2 = two_sum(sum12, -p1)
        d2 += err12
        d2 -= p2
        q2 = (d1 + d2) / divisor
        sum12, err12 = two_sum(q1, q2)
    day = np.round(sum12)
    extra, frac = two_sum(sum12, -day)
    frac += extra + err12
    # the carry can push frac past +-0.5; renormalize once
    excess = np.round(frac)
    day = day + excess
    extra, frac = two_sum(sum12, -day)
    frac += extra + err12
    return day, frac


# ---------------------------------------------------------------------------
# string <-> (imjd, fmjd)
# ---------------------------------------------------------------------------

def _str_to_mjds_one(s) -> tuple:
    if isinstance(s, bytes):
        s = s.decode()
    from fractions import Fraction

    v = Fraction(s.strip().translate(str.maketrans("DdE", "eee")))
    i = int(v) if v >= 0 else -int(-v) - (1 if v != int(v) else 0)
    return i, float(v - i)


def str_to_mjds(s):
    """Exact decimal MJD string -> (int MJD, frac) with no rounding loss
    (reference ``pulsar_mjd.py:488``; arrays of strings accepted)."""
    if isinstance(s, (str, bytes)):
        return _str_to_mjds_one(s)
    arr = np.asarray(s)
    imjd = np.empty(arr.shape, dtype=np.int64)
    fmjd = np.empty(arr.shape, dtype=np.float64)
    for idx in np.ndindex(arr.shape):
        imjd[idx], fmjd[idx] = _str_to_mjds_one(str(arr[idx]))
    return imjd, fmjd


def _mjds_to_str_one(mjd1, mjd2) -> str:
    imjd, fmjd = day_frac(mjd1, mjd2)
    imjd = int(imjd)
    fmjd = float(fmjd)
    while fmjd < 0.0:
        imjd -= 1
        fmjd += 1.0
    return str(imjd) + f"{fmjd:.16f}"[1:]


def mjds_to_str(mjd1, mjd2):
    """(int, frac) MJD pair -> decimal string (reference
    ``pulsar_mjd.py:521``)."""
    m1 = np.asarray(mjd1)
    m2 = np.asarray(mjd2)
    if m1.shape == ():
        return _mjds_to_str_one(float(m1), float(m2))
    out = np.empty(m1.shape, dtype="U30")
    for idx in np.ndindex(m1.shape):
        out[idx] = _mjds_to_str_one(float(m1[idx]), float(m2[idx]))
    return out


# ---------------------------------------------------------------------------
# JD <-> MJD, plain and pulsar_mjd-convention
# ---------------------------------------------------------------------------

def jds_to_mjds(jd1, jd2):
    return day_frac(np.asarray(jd1) - DJM0, jd2)


def mjds_to_jds(mjd1, mjd2):
    return day_frac(np.asarray(mjd1) + DJM0, mjd2)


def _leap_at_end_of_day(imjd):
    """Seconds inserted at the end of UTC day ``imjd`` (0 or 1)."""
    return (tai_minus_utc(np.asarray(imjd, np.float64) + 1.0)
            - tai_minus_utc(np.asarray(imjd, np.float64))).astype(np.float64)


def _to_day_floor(day, frac):
    """(day, frac in [-0.5, 0.5]) -> (floor day, frac in [0, 1))."""
    shift = np.floor(frac)
    return day + shift, frac - shift


def mjds_to_jds_pulsar(mjd1, mjd2):
    """pulsar_mjd (every day 86400 s) -> true UTC JD pair.

    On a leap-second day the pulsar-MJD fraction advances 86400 s while the
    real day holds 86401, so the true UTC fraction is rescaled
    (reference ``pulsar_mjd.py:430 mjds_to_jds_pulsar`` semantics via erfa).
    """
    day, frac = _to_day_floor(*day_frac(mjd1, mjd2))
    day_len = 86400.0 + _leap_at_end_of_day(day)
    return day + DJM0, frac * 86400.0 / day_len


def jds_to_mjds_pulsar(jd1, jd2):
    """True UTC JD pair -> pulsar_mjd convention; raises during a leap
    second, which pulsar_mjd cannot represent (reference
    ``pulsar_mjd.py:400``)."""
    day, frac = _to_day_floor(*day_frac(np.asarray(jd1) - DJM0, jd2))
    day_len = 86400.0 + _leap_at_end_of_day(day)
    sec = frac * day_len
    if np.any(sec > 86400.0):
        raise ValueError(
            "UTC times during a leap second cannot be represented in "
            "pulsar_mjd format")
    return day, sec / 86400.0


# ---------------------------------------------------------------------------
# longdouble interop (reference pulsar_mjd.py:314-365)
# ---------------------------------------------------------------------------

def str2longdouble(str_data):
    """String (Fortran 1.0d2 exponents allowed) -> numpy longdouble."""
    if not isinstance(str_data, (str, bytes)):
        raise TypeError(f"Need a string: {str_data!r}")
    if isinstance(str_data, bytes):
        str_data = str_data.decode()
    return np.longdouble(str_data.translate(str.maketrans("Dd", "ee")))


def data2longdouble(data):
    """Anything -> numpy longdouble (strings via :func:`str2longdouble`)."""
    return str2longdouble(data) if type(data) is str else np.longdouble(data)


def longdouble2str(x):
    """numpy longdouble -> string."""
    return str(x)


def quantity2longdouble_withunit(data):
    """Quantity-like -> same unit at longdouble precision.  Without astropy
    in this stack a bare number is returned as longdouble; an object with
    ``.unit``/``.to_value`` round-trips through its unit like the
    reference."""
    unit = getattr(data, "unit", None)
    if unit is None:
        return np.longdouble(data)
    return np.longdouble(data.to_value(unit)) * unit


def safe_kind_conversion(values, dtype):
    """Sequence -> array of ``dtype`` guarding object-kind surprises
    (reference ``pulsar_mjd.py`` helper)."""
    from collections.abc import Sequence

    if isinstance(values, Sequence):
        return np.asarray(values, dtype=dtype)
    return dtype(values)


# ---------------------------------------------------------------------------
# Time-object interop: duck-typed on (jd1, jd2) so astropy Time works when
# installed, and any pair-carrying object works without it
# ---------------------------------------------------------------------------

def time_to_longdouble(t):
    """Time-like (``.jd1``/``.jd2``, e.g. astropy Time) -> longdouble MJD."""
    jd1 = getattr(t, "jd1", None)
    if jd1 is None:
        return np.longdouble(t)
    return (np.longdouble(jd1) - np.longdouble(DJM0)) + np.longdouble(t.jd2)


def time_from_longdouble(t, scale="utc", format="pulsar_mjd"):
    """longdouble MJD -> (jd1, jd2) pair; feeds astropy Time(*pair) when
    available."""
    t = np.longdouble(t)
    i = np.floor(t)
    return np.float64(i) + DJM0, np.float64(t - i)


def time_to_mjd_string(t):
    """Time-like -> exact decimal MJD string.  Bare longdouble input is
    split at longdouble precision BEFORE entering float64 pair arithmetic
    (a direct float64 cast would round ~90 ns off a typical MJD)."""
    jd1 = getattr(t, "jd1", None)
    if jd1 is None:
        t = np.longdouble(t)
        i = np.floor(t)
        return mjds_to_str(np.float64(i), np.float64(t - i))
    mjd1, mjd2 = jds_to_mjds(jd1, t.jd2)
    return mjds_to_str(mjd1, mjd2)


def time_from_mjd_string(s, scale="utc", format="pulsar_mjd"):
    """Decimal MJD string -> exact (jd1, jd2) pair."""
    i, f = str_to_mjds(s)
    return np.float64(i) + DJM0, np.float64(f)


# ---------------------------------------------------------------------------
# time-format classes (reference pulsar_mjd.py TimeFormat subclasses).
# There is no astropy Time here — the formats are plain conversion
# namespaces between the user-facing value (float / longdouble / string
# MJD) and the internal (jd1, jd2) pair, which is exactly the computation
# the reference's astropy formats perform.  ``pulsar_mjd`` variants apply
# the leap-second-smearing UTC convention (mjds_to_jds_pulsar).
# ---------------------------------------------------------------------------

class TimeFormatMJD:
    """Base: float-MJD <-> (jd1, jd2).  Reference ``pulsar_mjd.py:150``
    family; scale handling is the caller's concern (like ``Time(...,
    scale=)`` in the reference)."""

    name = "mjd"
    _to_jds = staticmethod(mjds_to_jds)
    _from_jds = staticmethod(jds_to_mjds)

    @classmethod
    def set_jds(cls, val1, val2=0.0):
        """User value pair -> (jd1, jd2)."""
        return cls._to_jds(*day_frac(val1, val2))

    @classmethod
    def to_value(cls, jd1, jd2):
        """(jd1, jd2) -> float MJD (lossy by design, like the reference's
        plain ``.mjd``)."""
        m1, m2 = cls._from_jds(jd1, jd2)
        out = np.asarray(m1) + np.asarray(m2)
        return out.reshape(())[()] if out.size == 1 else out


class PulsarMJD(TimeFormatMJD):
    """Pulsar-convention UTC MJD: each day has exactly 86400 equal-length
    seconds, leap seconds smeared (reference ``pulsar_mjd.py:68``)."""

    name = "pulsar_mjd"
    _to_jds = staticmethod(mjds_to_jds_pulsar)
    _from_jds = staticmethod(jds_to_mjds_pulsar)


class MJDLong(TimeFormatMJD):
    """MJD carried as numpy longdouble (reference ``pulsar_mjd.py:150``):
    full 80-bit precision in and out."""

    name = "mjd_long"

    @classmethod
    def set_jds(cls, val1, val2=0.0):
        v = np.asarray(val1, dtype=np.longdouble) \
            + np.asarray(val2, dtype=np.longdouble)
        hi = np.asarray(v, dtype=np.float64)
        lo = np.asarray(v - hi.astype(np.longdouble), dtype=np.float64)
        return cls._to_jds(*day_frac(hi, lo))

    @classmethod
    def to_value(cls, jd1, jd2):
        m1, m2 = cls._from_jds(jd1, jd2)
        out = np.asarray(m1, dtype=np.longdouble) \
            + np.asarray(m2, dtype=np.longdouble)
        return out.reshape(())[()] if out.size == 1 else out


class PulsarMJDLong(MJDLong):
    """Longdouble MJD under the pulsar-UTC convention (reference
    ``pulsar_mjd.py:231``)."""

    name = "pulsar_mjd_long"
    _to_jds = staticmethod(mjds_to_jds_pulsar)
    _from_jds = staticmethod(jds_to_mjds_pulsar)


class MJDString(TimeFormatMJD):
    """MJD as exact decimal strings (reference ``pulsar_mjd.py:288``)."""

    name = "mjd_string"

    @classmethod
    def set_jds(cls, val1, val2=None):
        return cls._to_jds(*str_to_mjds(val1))

    @classmethod
    def to_value(cls, jd1, jd2):
        m1, m2 = (np.asarray(v) for v in cls._from_jds(jd1, jd2))
        if m1.size == 1:  # scalar in -> plain str out
            return mjds_to_str(m1.reshape(()), m2.reshape(()))
        return mjds_to_str(m1, m2)


class PulsarMJDString(MJDString):
    """String MJD under the pulsar-UTC convention (reference
    ``pulsar_mjd.py:330``)."""

    name = "pulsar_mjd_string"
    _to_jds = staticmethod(mjds_to_jds_pulsar)
    _from_jds = staticmethod(jds_to_mjds_pulsar)
