"""Residuals: observed-minus-model phase/time with chi2 and likelihood.

Counterpart of reference ``residuals.py:40 Residuals``: phase residuals with
'nearest' or pulse-number tracking (``residuals.py:331``), optional
(weighted-)mean subtraction, time residuals (``residuals.py:500``), chi2 with
WLS/ECORR/GLS dispatch (``residuals.py:686,655,608,584``), lnlikelihood.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pint_tpu.exceptions import CorrelatedErrors
from pint_tpu.logging import log
from pint_tpu.utils import sherman_morrison_dot, weighted_mean, woodbury_dot

__all__ = ["Residuals"]


class Residuals:
    residual_type = "toa"
    unit = "s"

    def __init__(self, toas, model, subtract_mean: bool = True,
                 use_weighted_mean: bool = True,
                 track_mode: Optional[str] = None):
        self.toas = toas
        self.model = model
        self.subtract_mean = subtract_mean and "PhaseOffset" not in model.components
        self.use_weighted_mean = use_weighted_mean
        if track_mode is None:
            pn = toas.get_pulse_numbers()
            track_mode = "use_pulse_numbers" if pn is not None else "nearest"
        self.track_mode = track_mode
        self._phase_resids = None
        self._time_resids = None

    # ------------------------------------------------------------------
    def calc_phase_resids(self) -> np.ndarray:
        """Residual pulse phase in cycles (float64)."""
        abs_phase = "AbsPhase" in self.model.components
        ph = self.model.phase(self.toas, abs_phase=abs_phase)
        int_, frac = np.asarray(ph.int_), np.asarray(ph.frac)
        if self.track_mode == "use_pulse_numbers":
            pn = self.toas.get_pulse_numbers()
            if pn is None:
                raise ValueError("track_mode=use_pulse_numbers but no pulse numbers")
            dpn = (self.toas.delta_pulse_number
                   if self.toas.delta_pulse_number is not None else 0.0)
            resids = (int_ - pn + dpn) + frac
        else:
            resids = frac.copy()
            dpn = self.toas.delta_pulse_number
            if dpn is not None:
                resids = resids + dpn
        if self.subtract_mean:
            if self.use_weighted_mean:
                err = self.toas.get_errors()
                if np.any(err == 0):
                    mean = np.mean(resids)
                else:
                    w = 1.0 / (err * err)
                    mean, _ = weighted_mean(resids, w)
                    mean = float(mean)
            else:
                mean = np.mean(resids)
            resids = resids - mean
        self._phase_resids = resids
        return resids

    @property
    def phase_resids(self) -> np.ndarray:
        if self._phase_resids is None:
            self.calc_phase_resids()
        return self._phase_resids

    def calc_time_resids(self) -> np.ndarray:
        """Residuals in seconds (phase / F0)."""
        self._time_resids = self.phase_resids / float(self.model.F0.value)
        return self._time_resids

    @property
    def time_resids(self) -> np.ndarray:
        if self._time_resids is None:
            self.calc_time_resids()
        return self._time_resids

    @property
    def resids(self) -> np.ndarray:
        return self.time_resids

    # ------------------------------------------------------------------
    def get_data_error(self, scaled: bool = True) -> np.ndarray:
        """TOA uncertainties in seconds (EFAC/EQUAD scaled when requested)."""
        if scaled:
            return self.model.scaled_toa_uncertainty(self.toas)
        return np.asarray(self.toas.get_errors()) * 1e-6

    def _corr_basis_weight(self):
        """(U, w) for the correlated chi2/likelihood with the overall phase
        offset marginalized (reference ``residuals.py:600-604``).  Without
        it the weighted-mean subtraction removes low-frequency power the
        phi prior still predicts."""
        U, w = self.model.noise_model_basis_weight(self.toas)
        return self.model.augment_basis_for_offset(U, w, n=len(self.toas))

    def calc_chi2(self) -> float:
        """chi2 with the same dispatch as the reference (``residuals.py:686``):
        diagonal WLS; Sherman-Morrison for ECORR-only with an explicit
        PhaseOffset (reference ``_calc_ecorr_chi2`` precondition,
        ``residuals.py:613``); Woodbury with offset marginalization
        otherwise."""
        r = self.time_resids
        sigma = self.get_data_error()
        if np.any(sigma == 0):
            return np.inf
        if not self.model.has_correlated_errors:
            return float(np.sum((r / sigma) ** 2))
        ecorr_only = all(
            getattr(c, "is_ecorr", False)
            for c in self.model.noise_components
            if getattr(c, "introduces_correlated_errors", False)
        )
        if ecorr_only and "PhaseOffset" in self.model.components:
            U, w = self.model.noise_model_basis_weight(self.toas)
            dot, _ = sherman_morrison_dot(sigma**2, np.asarray(U), np.asarray(w), r, r)
        else:
            U, w = self._corr_basis_weight()
            dot, _ = woodbury_dot(sigma**2, U, w, r, r)
        return float(dot)

    @property
    def chi2(self) -> float:
        return self.calc_chi2()

    @property
    def dof(self) -> int:
        """N_toa - n_free - (1 for the implicit mean offset, only when one is
        actually being subtracted; an explicit PhaseOffset's PHOFF is already
        counted in free_params).  Reference ``residuals.py`` dof accounting."""
        return len(self.toas) - len(self.model.free_params) - int(self.subtract_mean)

    @property
    def reduced_chi2(self) -> float:
        return self.chi2 / self.dof

    @property
    def chi2_reduced(self) -> float:
        return self.reduced_chi2

    def rms_weighted(self) -> float:
        """Weighted RMS of time residuals, seconds."""
        err = self.get_data_error(scaled=False)
        if np.any(err == 0):
            return float(np.sqrt(np.mean(self.time_resids**2)))
        w = 1.0 / err**2
        mean, _ = weighted_mean(self.time_resids, w)
        return float(np.sqrt(np.sum(w * (self.time_resids - float(mean)) ** 2) / np.sum(w)))

    def calc_whitened_resids(self) -> np.ndarray:
        """(r - correlated-noise realization) / scaled sigma (reference
        ``residuals.py:552-582``: the noise realization from a post-fit
        ``noise_ampls`` is subtracted before normalizing; without stored
        amplitudes this reduces to r / sigma)."""
        r = self.time_resids
        nr = self.noise_resids()
        if nr:
            r = r - sum(nr.values())
        return r / self.get_data_error()

    def lnlikelihood(self) -> float:
        """Gaussian log-likelihood including the noise log-determinant
        (reference ``residuals.py:730``)."""
        r = self.time_resids
        sigma = self.get_data_error()
        if not self.model.has_correlated_errors:
            chi2 = np.sum((r / sigma) ** 2)
            logdet = np.sum(np.log(sigma**2))
            return float(-0.5 * (chi2 + logdet + len(r) * np.log(2 * np.pi)))
        U, w = self._corr_basis_weight()
        dot, logdet = woodbury_dot(sigma**2, U, w, r, r)
        return float(-0.5 * (dot + logdet + len(r) * np.log(2 * np.pi)))

    def noise_resids(self) -> dict:
        """Per-component correlated-noise realizations in seconds: the
        maximum-likelihood GP amplitudes a GLS fit stored (``noise_ampls``)
        projected back through each component's basis (reference
        ``residuals.py`` noise_resids)."""
        ampls = getattr(self, "noise_ampls", None)
        if not ampls:
            return {}
        Us, _, dims = self.model.noise_basis_by_component(self.toas)
        out = {}
        for (comp, (off, size)), U in zip(dims.items(), Us):
            a = np.asarray(ampls.get(comp, np.zeros(size)))
            out[comp] = np.asarray(U) @ a
        return out

    def ecorr_average(self, use_noise_model: bool = True) -> dict:
        """Epoch-averaged residuals using the ECORR time binning (reference
        ``residuals.py:859``).

        Returns dict with ``mjds``, ``freqs``, ``time_resids``,
        ``noise_resids`` (per component), ``errors`` (including the ECORR
        variance when ``use_noise_model``), and ``indices`` (TOA indices per
        segment)."""
        ecorrs = [c for c in self.model.noise_components
                  if getattr(c, "is_ecorr", False)]
        if not ecorrs:
            raise ValueError("ECORR not present in noise model")
        U, ecorr_err2 = ecorrs[0].basis_weight_pair(self.model, self.toas)
        U = np.asarray(U)
        ecorr_err2 = np.asarray(ecorr_err2)
        if use_noise_model:
            err = np.asarray(self.model.scaled_toa_uncertainty(self.toas))
        else:
            err = np.asarray(self.toas.get_errors()) * 1e-6
            ecorr_err2 = ecorr_err2 * 0.0
        wt = 1.0 / (err * err)
        a_norm = U.T @ wt

        def wtsum(x):
            return (U.T @ (wt * np.asarray(x))) / a_norm

        avg = {
            "mjds": wtsum(np.asarray(self.toas.get_mjds(), np.float64)),
            "freqs": wtsum(self.toas.freq_mhz),
            "time_resids": wtsum(self.time_resids),
            "noise_resids": {k: wtsum(v)
                             for k, v in self.noise_resids().items()},
            "errors": np.sqrt(1.0 / a_norm + ecorr_err2),
            "indices": [list(np.where(U[:, i])[0]) for i in range(U.shape[1])],
        }
        return avg

    def update(self):
        self._phase_resids = None
        self._time_resids = None
        return self
