"""Residuals: observed-minus-model phase/time with chi2 and likelihood.

Counterpart of reference ``residuals.py:40 Residuals``: phase residuals with
'nearest' or pulse-number tracking (``residuals.py:331``), optional
(weighted-)mean subtraction, time residuals (``residuals.py:500``), chi2 with
WLS/ECORR/GLS dispatch (``residuals.py:686,655,608,584``), lnlikelihood.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pint_tpu.exceptions import CorrelatedErrors, UsageError
from pint_tpu.logging import log
from pint_tpu.utils import sherman_morrison_dot, weighted_mean, woodbury_dot

__all__ = ["Residuals"]


class Residuals:
    residual_type = "toa"
    unit = "s"

    def __init__(self, toas, model, subtract_mean: bool = True,
                 use_weighted_mean: bool = True,
                 track_mode: Optional[str] = None):
        self.toas = toas
        self.model = model
        self.subtract_mean = subtract_mean and "PhaseOffset" not in model.components
        self.use_weighted_mean = use_weighted_mean
        if track_mode is None:
            pn = toas.get_pulse_numbers()
            track_mode = "use_pulse_numbers" if pn is not None else "nearest"
        self.track_mode = track_mode
        self._phase_resids = None
        self._time_resids = None

    # ------------------------------------------------------------------
    def calc_phase_resids(self) -> np.ndarray:
        """Residual pulse phase in cycles (float64)."""
        abs_phase = "AbsPhase" in self.model.components
        ph = self.model.phase(self.toas, abs_phase=abs_phase)
        int_, frac = np.asarray(ph.int_), np.asarray(ph.frac)
        if self.track_mode == "use_pulse_numbers":
            pn = self.toas.get_pulse_numbers()
            if pn is None:
                raise UsageError(
                    "track_mode=use_pulse_numbers but no pulse numbers")
            dpn = (self.toas.delta_pulse_number
                   if self.toas.delta_pulse_number is not None else 0.0)
            resids = (int_ - pn + dpn) + frac
        else:
            resids = frac.copy()
            dpn = self.toas.delta_pulse_number
            if dpn is not None:
                resids = resids + dpn
        if self.subtract_mean:
            if self.use_weighted_mean:
                err = self.toas.get_errors()
                if np.any(err == 0):
                    mean = np.mean(resids)
                else:
                    w = 1.0 / (err * err)
                    mean, _ = weighted_mean(resids, w)
                    mean = float(mean)
            else:
                mean = np.mean(resids)
            resids = resids - mean
        self._phase_resids = resids
        return resids

    @property
    def phase_resids(self) -> np.ndarray:
        if self._phase_resids is None:
            self.calc_phase_resids()
        return self._phase_resids

    def calc_time_resids(self) -> np.ndarray:
        """Residuals in seconds (phase / F0)."""
        self._time_resids = self.phase_resids / float(self.model.F0.value)
        return self._time_resids

    @property
    def time_resids(self) -> np.ndarray:
        if self._time_resids is None:
            self.calc_time_resids()
        return self._time_resids

    @property
    def resids(self) -> np.ndarray:
        return self.time_resids

    # ------------------------------------------------------------------
    def get_data_error(self, scaled: bool = True) -> np.ndarray:
        """TOA uncertainties in seconds (EFAC/EQUAD scaled when requested)."""
        if scaled:
            return self.model.scaled_toa_uncertainty(self.toas)
        return np.asarray(self.toas.get_errors()) * 1e-6

    def _corr_basis_weight(self):
        """(U, w) for the correlated chi2/likelihood with the overall phase
        offset marginalized (reference ``residuals.py:600-604``).  Without
        it the weighted-mean subtraction removes low-frequency power the
        phi prior still predicts."""
        U, w = self.model.noise_model_basis_weight(self.toas)
        return self.model.augment_basis_for_offset(U, w, n=len(self.toas))

    def calc_chi2(self) -> float:
        """chi2 with the same dispatch as the reference (``residuals.py:686``):
        diagonal WLS; Sherman-Morrison for ECORR-only with an explicit
        PhaseOffset (reference ``_calc_ecorr_chi2`` precondition,
        ``residuals.py:613``); Woodbury with offset marginalization
        otherwise."""
        r = self.time_resids
        sigma = self.get_data_error()
        if np.any(sigma == 0):
            return np.inf
        if not self.model.has_correlated_errors:
            return float(np.sum((r / sigma) ** 2))
        ecorr_only = all(
            getattr(c, "is_ecorr", False)
            for c in self.model.noise_components
            if getattr(c, "introduces_correlated_errors", False)
        )
        if ecorr_only and "PhaseOffset" in self.model.components:
            U, w = self.model.noise_model_basis_weight(self.toas)
            dot, _ = sherman_morrison_dot(sigma**2, np.asarray(U), np.asarray(w), r, r)
        else:
            U, w = self._corr_basis_weight()
            dot, _ = woodbury_dot(sigma**2, U, w, r, r)
        return float(dot)

    @property
    def chi2(self) -> float:
        return self.calc_chi2()

    @property
    def dof(self) -> int:
        """N_toa - n_free - (1 for the implicit mean offset, only when one is
        actually being subtracted; an explicit PhaseOffset's PHOFF is already
        counted in free_params).  Reference ``residuals.py`` dof accounting."""
        return len(self.toas) - len(self.model.free_params) - int(self.subtract_mean)

    @property
    def reduced_chi2(self) -> float:
        return self.chi2 / self.dof

    @property
    def chi2_reduced(self) -> float:
        return self.reduced_chi2

    def rms_weighted(self) -> float:
        """Weighted RMS of time residuals, seconds."""
        err = self.get_data_error(scaled=False)
        if np.any(err == 0):
            return float(np.sqrt(np.mean(self.time_resids**2)))
        w = 1.0 / err**2
        mean, _ = weighted_mean(self.time_resids, w)
        return float(np.sqrt(np.sum(w * (self.time_resids - float(mean)) ** 2) / np.sum(w)))

    def calc_whitened_resids(self) -> np.ndarray:
        """(r - correlated-noise realization) / scaled sigma (reference
        ``residuals.py:552-582``: the noise realization from a post-fit
        ``noise_ampls`` is subtracted before normalizing; without stored
        amplitudes this reduces to r / sigma)."""
        r = self.time_resids
        nr = self.noise_resids()
        if nr:
            r = r - sum(nr.values())
        return r / self.get_data_error()

    # -- reference user-API long tail ---------------------------------------
    def calc_phase_mean(self, weighted: bool = True) -> float:
        """Mean residual phase in cycles, optionally weighted (reference
        ``residuals.py:468``)."""
        r = self.phase_resids
        if not weighted:
            return float(np.mean(r))
        err = self.toas.get_errors()
        if np.any(err == 0):
            return float(np.mean(r))
        w = 1.0 / (err * err)
        mean, _ = weighted_mean(r, w)
        return float(mean)

    def calc_time_mean(self, calctype: str = "taylor",
                       weighted: bool = True) -> float:
        """Mean residual time [s] (reference ``residuals.py:481``)."""
        r = self.phase_resids / self.get_PSR_freq(calctype)
        if not weighted:
            return float(np.mean(r))
        err = self.toas.get_errors()
        if np.any(err == 0):
            return float(np.mean(r))
        w = 1.0 / (err * err)
        mean, _ = weighted_mean(r, w)
        return float(mean)

    def get_PSR_freq(self, calctype: str = "modelF0") -> np.ndarray:
        """Spin frequency [Hz]: the model F0 ('modelF0') or the spindown
        Taylor series evaluated at each TOA ('taylor'/'numerical';
        reference ``residuals.py:283``)."""
        calctype = calctype.lower()
        if calctype not in ("modelf0", "taylor", "numerical"):
            raise UsageError(f"Unknown calctype {calctype!r}")
        F0 = float(self.model.F0.value)
        if calctype == "modelf0":
            return F0
        # Taylor series around PEPOCH at the barycentered emission times
        sd = self.model.components.get("Spindown")
        if sd is None:
            return F0
        terms = [float(getattr(self.model, f"F{i}").value or 0.0)
                 for i in range(sd.num_spin_terms)]
        tdb = np.asarray(self.toas.tdb, dtype=np.float64)
        dt = (tdb - float(self.model.PEPOCH.value)) * 86400.0 \
            - np.asarray(self.model.delay(self.toas))
        freq = np.zeros_like(dt)
        # d(phase)/dt = sum F_i dt^i / i!
        fact = 1.0
        for i, f in enumerate(terms):
            if i > 0:
                fact *= i
            freq = freq + f * dt**i / fact
        return freq

    @property
    def resids_value(self) -> np.ndarray:
        """Time residuals as a bare float array [s] (reference
        ``resids_value``)."""
        return np.asarray(self.time_resids, dtype=np.float64)

    def d_lnlikelihood_d_param(self, param: str,
                               step: Optional[float] = None) -> float:
        """d(lnlikelihood)/d(param) by central difference (reference
        computes analytic gradients for noise parameters,
        ``residuals.py:735-826``; the ML noise fitter in
        ``pint_tpu.noisefit`` uses jax autodiff for the same thing — this
        scalar hook exists for API parity and spot checks).

        The step defaults to 1e-3 of the parameter's uncertainty when one
        is set — timing parameters like F0 have |value|/sigma ~ 1e14, so
        any value-scaled step would leave the likelihood's linear
        regime."""
        par = getattr(self.model, param)
        v0 = float(par.value or 0.0)
        if step is None:
            sig = float(par.uncertainty or 0.0)
            h = 1e-3 * sig if sig > 0 else max(abs(v0) * 1e-6, 1e-6)
        else:
            h = max(abs(v0) * step, step)
        # a step below one float64 ulp of the value perturbs nothing
        h = max(h, 8.0 * np.spacing(abs(v0)))
        vals = []
        # values flow into the compiled evaluators as arguments; no cache
        # invalidation needed for a pure value perturbation
        for v in (v0 + h, v0 - h):
            par.value = v
            r = Residuals(self.toas, self.model, track_mode=self.track_mode)
            vals.append(r.lnlikelihood())
        par.value = v0
        return (vals[0] - vals[1]) / (2 * h)

    def lnlikelihood(self) -> float:
        """Gaussian log-likelihood including the noise log-determinant
        (reference ``residuals.py:730``)."""
        r = self.time_resids
        sigma = self.get_data_error()
        if not self.model.has_correlated_errors:
            chi2 = np.sum((r / sigma) ** 2)
            logdet = np.sum(np.log(sigma**2))
            return float(-0.5 * (chi2 + logdet + len(r) * np.log(2 * np.pi)))
        U, w = self._corr_basis_weight()
        dot, logdet = woodbury_dot(sigma**2, U, w, r, r)
        return float(-0.5 * (dot + logdet + len(r) * np.log(2 * np.pi)))

    def noise_resids(self) -> dict:
        """Per-component correlated-noise realizations in seconds: the
        maximum-likelihood GP amplitudes a GLS fit stored (``noise_ampls``)
        projected back through each component's basis (reference
        ``residuals.py`` noise_resids)."""
        ampls = getattr(self, "noise_ampls", None)
        if not ampls:
            return {}
        Us, _, dims = self.model.noise_basis_by_component(self.toas)
        out = {}
        for (comp, (off, size)), U in zip(dims.items(), Us):
            a = np.asarray(ampls.get(comp, np.zeros(size)))
            out[comp] = np.asarray(U) @ a
        return out

    def ecorr_average(self, use_noise_model: bool = True) -> dict:
        """Epoch-averaged residuals using the ECORR time binning (reference
        ``residuals.py:859``).

        Returns dict with ``mjds``, ``freqs``, ``time_resids``,
        ``noise_resids`` (per component), ``errors`` (including the ECORR
        variance when ``use_noise_model``), and ``indices`` (TOA indices per
        segment)."""
        ecorrs = [c for c in self.model.noise_components
                  if getattr(c, "is_ecorr", False)]
        if not ecorrs:
            raise UsageError("ECORR not present in noise model")
        U, ecorr_err2 = ecorrs[0].basis_weight_pair(self.model, self.toas)
        U = np.asarray(U)
        ecorr_err2 = np.asarray(ecorr_err2)
        if use_noise_model:
            err = np.asarray(self.model.scaled_toa_uncertainty(self.toas))
        else:
            err = np.asarray(self.toas.get_errors()) * 1e-6
            ecorr_err2 = ecorr_err2 * 0.0
        wt = 1.0 / (err * err)
        a_norm = U.T @ wt

        def wtsum(x):
            return (U.T @ (wt * np.asarray(x))) / a_norm

        avg = {
            "mjds": wtsum(np.asarray(self.toas.get_mjds(), np.float64)),
            "freqs": wtsum(self.toas.freq_mhz),
            "time_resids": wtsum(self.time_resids),
            "noise_resids": {k: wtsum(v)
                             for k, v in self.noise_resids().items()},
            "errors": np.sqrt(1.0 / a_norm + ecorr_err2),
            "indices": [list(np.where(U[:, i])[0]) for i in range(U.shape[1])],
        }
        return avg

    def update(self):
        self._phase_resids = None
        self._time_resids = None
        return self
