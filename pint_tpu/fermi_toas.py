"""Fermi-LAT photon TOAs with PSF-based probability weights.

Counterpart of reference ``fermi_toas.py:20 calc_lat_weights`` /
``:144 get_Fermi_TOAs``: load FT1 photon events, attach per-photon target
probabilities either from a gtsrcprob column or from the energy-dependent
PSF approximation (Bruel SearchPulsation parameterization).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pint_tpu.event_toas import get_fits_TOAs, load_fits_TOAs
from pint_tpu.fits_utils import get_hdu, read_fits
from pint_tpu.logging import log
from pint_tpu.toa import TOAs

__all__ = ["calc_lat_weights", "load_Fermi_TOAs", "get_Fermi_TOAs"]

_default_uncertainty = 1.0  # us


def calc_lat_weights(energies, angseps_deg, logeref: float = 4.1,
                     logesig: float = 0.5) -> np.ndarray:
    """Photon weights from the energy-dependent LAT PSF
    (reference ``fermi_toas.py:20``; Bruel SearchPulsation parameters).

    ``angseps_deg``: angular separation photon->target in degrees.
    """
    psfpar0, psfpar1, psfpar2 = 5.445, 0.848, 0.084
    norm, gam, scalepsf = 1.0, 2.0, 3.0
    energies = np.asarray(energies, dtype=np.float64)
    angseps_deg = np.asarray(angseps_deg, dtype=np.float64)
    logE = np.log10(energies)
    sigma = np.sqrt(psfpar0**2 * np.power(100.0 / energies, 2.0 * psfpar1)
                    + psfpar2**2) / scalepsf
    fgeom = norm * np.power(
        1 + angseps_deg**2 / (2.0 * gam * sigma**2), -gam)
    return fgeom * np.exp(-((logE - logeref) / (np.sqrt(2.0) * logesig)) ** 2)


def load_Fermi_TOAs(ft1name: str, weightcolumn: Optional[str] = None,
                    targetcoord=None, logeref: float = 4.1,
                    logesig: float = 0.5, minweight: float = 0.0,
                    minmjd: float = -np.inf, maxmjd: float = np.inf,
                    errors: float = _default_uncertainty):
    """Raw Fermi photon data: (mjds, energies, weights)
    (reference ``fermi_toas.py:70``)."""
    hdus = read_fits(ft1name)
    hdu = get_hdu(hdus, "EVENTS")
    data = hdu.data()
    from pint_tpu.fits_utils import read_fits_event_mjds

    mjds = read_fits_event_mjds(hdu)
    energies = np.asarray(data.get("ENERGY"), dtype=np.float64) \
        if "ENERGY" in data else None
    weights = None
    if weightcolumn is not None:
        if weightcolumn == "CALC":
            if targetcoord is None:
                raise ValueError("weightcolumn='CALC' needs targetcoord "
                                 "(ra_deg, dec_deg)")
            ra = np.asarray(data["RA"], dtype=np.float64)
            dec = np.asarray(data["DEC"], dtype=np.float64)
            tra, tdec = np.radians(targetcoord[0]), np.radians(targetcoord[1])
            ra_r, dec_r = np.radians(ra), np.radians(dec)
            cossep = (np.sin(dec_r) * np.sin(tdec)
                      + np.cos(dec_r) * np.cos(tdec) * np.cos(ra_r - tra))
            angsep = np.degrees(np.arccos(np.clip(cossep, -1, 1)))
            weights = calc_lat_weights(energies, angsep, logeref, logesig)
        else:
            weights = np.asarray(data[weightcolumn], dtype=np.float64)
    keep = (np.asarray(mjds, dtype=np.float64) >= minmjd) & \
           (np.asarray(mjds, dtype=np.float64) <= maxmjd)
    if weights is not None:
        keep &= weights >= minweight
    mjds = mjds[keep]
    if energies is not None:
        energies = energies[keep]
    if weights is not None:
        weights = weights[keep]
    log.info(f"Loaded {len(mjds)} Fermi photons from {ft1name}")
    return mjds, energies, weights, hdu.header


def get_Fermi_TOAs(ft1name: str, weightcolumn: Optional[str] = None,
                   targetcoord=None, logeref: float = 4.1,
                   logesig: float = 0.5, minweight: float = 0.0,
                   minmjd: float = -np.inf, maxmjd: float = np.inf,
                   errors: float = _default_uncertainty,
                   ephem: Optional[str] = None, planets: bool = False) -> TOAs:
    """Fermi FT1 file -> TOAs with -weight/-energy flags
    (reference ``fermi_toas.py:144``)."""
    mjds, energies, weights, hdr = load_Fermi_TOAs(
        ft1name, weightcolumn=weightcolumn, targetcoord=targetcoord,
        logeref=logeref, logesig=logesig, minweight=minweight,
        minmjd=minmjd, maxmjd=maxmjd, errors=errors)
    timeref = str(hdr.get("TIMEREF", "LOCAL")).strip().upper()
    timesys = str(hdr.get("TIMESYS", "TT")).strip().upper()
    if timesys == "TT" and timeref != "SOLARSYSTEM":
        # see event_toas.get_fits_TOAs: the pipeline expects UTC input
        from pint_tpu.timescales import tt_to_utc_mjd

        mjds = tt_to_utc_mjd(mjds)
    n = len(mjds)
    flags = []
    for i in range(n):
        fl = {}
        if energies is not None:
            fl["energy"] = repr(float(energies[i]))
        if weights is not None:
            fl["weight"] = repr(float(weights[i]))
        flags.append(fl)
    if timeref == "SOLARSYSTEM":
        obsname = "barycenter"
    elif timeref == "GEOCENTRIC":
        obsname = "geocenter"
    else:
        from pint_tpu.observatory import get_observatory

        try:
            obsname = get_observatory("Fermi").name
        except KeyError:
            raise ValueError(
                "Unbarycentered Fermi events need the spacecraft orbit: "
                "load an FT2 file with get_satellite_observatory('Fermi', ft2name)")
    ts = TOAs(
        utc_mjd=np.asarray(mjds, dtype=np.longdouble),
        error_us=np.full(n, float(errors)),
        freq_mhz=np.full(n, np.inf),
        obs=np.array([obsname] * n, dtype=object),
        flags=flags,
    )
    if obsname == "barycenter":
        ts.clock_corr_s = np.zeros(n)
    else:
        ts.apply_clock_corrections(include_bipm=False)
    ts.compute_TDBs(ephem=ephem or "DE440")
    ts.compute_posvels(ephem=ephem or "DE440", planets=planets)
    return ts
