"""TEMPO-style polycos: piecewise polynomial phase predictors.

Counterpart of reference ``polycos.py:85 PolycoEntry`` / ``:484 Polycos``
(generate from a TimingModel, evaluate absolute phase / spin frequency,
read/write the TEMPO polyco file format).

Evaluation semantics (TEMPO convention): with dt = (t - tmid) in minutes,

    phase(t) = rphase + 60 * f0 * dt + sum_{i} c_i * dt^i
    freq(t)  = f0 + (1/60) * sum_{i>=1} i * c_i * dt^(i-1)

Generation fits the residual polynomial (after removing the linear
60*f0*dt ramp) with a least-squares Vandermonde solve on Chebyshev-spaced
nodes; all segments are evaluated through the model's compiled vectorized
phase function in one batch.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from pint_tpu.logging import log
from pint_tpu.phase import Phase

__all__ = ["PolycoEntry", "Polycos", "tempo_polyco_table_reader",
           "tempo_polyco_table_writer"]

MIN_PER_DAY = 1440.0


class PolycoEntry:
    def __init__(self, tmid: float, mjdspan_min: float, rphase_int: int,
                 rphase_frac: float, f0: float, ncoeff: int, coeffs,
                 obs: str = "@", obsfreq: float = 1400.0, psrname: str = "",
                 binary_phase: Optional[float] = None):
        self.tmid = float(tmid)
        self.mjdspan = float(mjdspan_min)
        self.rphase_int = int(rphase_int)
        self.rphase_frac = float(rphase_frac)
        self.f0 = float(f0)
        self.ncoeff = int(ncoeff)
        self.coeffs = np.asarray(coeffs, dtype=np.float64)
        self.obs = obs
        self.obsfreq = float(obsfreq)
        self.psrname = psrname
        self.binary_phase = binary_phase

    @property
    def tstart(self) -> float:
        return self.tmid - self.mjdspan / (2 * MIN_PER_DAY)

    @property
    def tstop(self) -> float:
        return self.tmid + self.mjdspan / (2 * MIN_PER_DAY)

    def valid(self, t_mjd) -> np.ndarray:
        t = np.asarray(t_mjd, dtype=np.float64)
        return (t >= self.tstart) & (t < self.tstop)

    def evalabsphase(self, t_mjd) -> Phase:
        """Absolute phase as an (int, frac) Phase."""
        dt_min = (np.asarray(t_mjd, dtype=np.longdouble) - np.longdouble(self.tmid)) * MIN_PER_DAY
        dt64 = np.asarray(dt_min, dtype=np.float64)
        poly = np.zeros_like(dt64)
        for i in range(self.ncoeff - 1, -1, -1):
            poly = poly * dt64 + self.coeffs[i]
        # carry the big linear ramp in longdouble, split int/frac exactly
        ramp = np.longdouble(60.0) * np.longdouble(self.f0) * dt_min
        total = (np.longdouble(self.rphase_int)
                 + np.longdouble(self.rphase_frac) + ramp
                 + np.asarray(poly, dtype=np.longdouble))
        ip = np.floor(total)
        return Phase(np.asarray(ip, dtype=np.float64),
                     np.asarray(total - ip, dtype=np.float64))

    def evalphase(self, t_mjd) -> np.ndarray:
        """Fractional phase in [0, 1)."""
        return np.asarray(self.evalabsphase(t_mjd).frac) % 1.0

    def evalfreq(self, t_mjd) -> np.ndarray:
        dt = (np.asarray(t_mjd, dtype=np.float64) - self.tmid) * MIN_PER_DAY
        out = np.zeros_like(dt)
        for i in range(self.ncoeff - 1, 0, -1):
            out = out * dt + i * self.coeffs[i]
        return self.f0 + out / 60.0

    def evalfreqderiv(self, t_mjd) -> np.ndarray:
        dt = (np.asarray(t_mjd, dtype=np.float64) - self.tmid) * MIN_PER_DAY
        out = np.zeros_like(dt)
        for i in range(self.ncoeff - 1, 1, -1):
            out = out * dt + i * (i - 1) * self.coeffs[i]
        return out / 3600.0


class Polycos:
    """A set of PolycoEntry segments with dispatch by epoch
    (reference ``polycos.py:484``)."""

    def __init__(self, entries: Optional[List[PolycoEntry]] = None):
        self.entries: List[PolycoEntry] = entries or []

    # -- generation ----------------------------------------------------------
    @classmethod
    def generate_polycos(cls, model, mjdStart: float, mjdEnd: float,
                         obs: str, segLength: float = 60.0, ncoeff: int = 12,
                         obsFreq: float = 1400.0) -> "Polycos":
        """Fit per-segment polynomials to the model phase
        (reference ``polycos.py:~700 generate_polycos``).  segLength in
        minutes."""
        from pint_tpu.toa import TOAs
        from pint_tpu.observatory import get_observatory

        obsname = get_observatory(obs).name
        span_d = segLength / MIN_PER_DAY
        nseg = max(1, int(np.ceil((mjdEnd - mjdStart) / span_d - 1e-9)))
        nnode = max(2 * ncoeff, ncoeff + 4)
        entries = []
        # Chebyshev-spaced nodes per segment, all segments in one TOA batch
        k = np.arange(nnode)
        cheb = np.cos(np.pi * (k + 0.5) / nnode)[::-1]  # (-1, 1)
        all_mjds = []
        tmids = []
        for s in range(nseg):
            t0 = mjdStart + s * span_d
            # quantize tmid to the TEMPO text format's %.11f precision UP
            # FRONT so the coefficients are fit against the exact value the
            # file will carry — otherwise the write/read round trip shifts
            # the evaluation epoch by up to 0.5e-11 d (~0.4 us) and the
            # prediction degrades by f0*dt (~3e-5 cycles at 60 Hz).
            # (find_entry's EDGE_TOL absorbs the ~1e-11 d coverage shifts
            # the rounding introduces at segment boundaries.)
            tmid = round(t0 + span_d / 2, 11)
            tmids.append(tmid)
            all_mjds.append(tmid + cheb * span_d / 2)
        mjds = np.concatenate(all_mjds)
        n = len(mjds)
        ts = TOAs(
            utc_mjd=np.asarray(mjds, dtype=np.longdouble),
            error_us=np.ones(n), freq_mhz=np.full(n, obsFreq),
            obs=np.array([obsname] * n, dtype=object),
            flags=[{} for _ in range(n)],
        )
        include_bipm = str(model.CLOCK.value or "").upper().startswith("TT(BIPM")
        if obsname != "barycenter":
            ts.apply_clock_corrections(include_bipm=include_bipm)
        else:
            ts.clock_corr_s = np.zeros(n)
        ts.compute_TDBs(ephem=model.EPHEM.value or "DE440")
        ts.compute_posvels(ephem=model.EPHEM.value or "DE440",
                           planets=bool(model.PLANET_SHAPIRO.value))
        ph = model.phase(ts, abs_phase="AbsPhase" in model.components)
        ph_int = np.asarray(ph.int_)
        ph_frac = np.asarray(ph.frac)
        f0 = float(model.F0.value)
        psr = str(model.PSR.value or "")
        for s in range(nseg):
            sl = slice(s * nnode, (s + 1) * nnode)
            tmid = tmids[s]
            dt_min = (mjds[sl] - tmid) * MIN_PER_DAY
            # reference phase: value at the node closest to tmid
            imid = np.argmin(np.abs(dt_min))
            rint = ph_int[sl][imid]
            rfrac = ph_frac[sl][imid]
            # target: phase - rphase - 60 f0 dt  (all small numbers)
            y = (ph_int[sl] - rint) + (ph_frac[sl] - rfrac) \
                - 60.0 * f0 * dt_min
            # fit in x = dt/halfspan (Vandermonde in raw minutes is
            # hopelessly ill-conditioned: 60^11 ~ 4e19), then rescale the
            # power-series coefficients back to per-minute powers for the
            # TEMPO evaluation convention
            half = segLength / 2.0
            V = np.vander(dt_min / half, ncoeff, increasing=True)
            cx, *_ = np.linalg.lstsq(V, y, rcond=None)
            coeffs = cx / half ** np.arange(ncoeff)
            resid = V @ cx - y
            rms = float(np.sqrt(np.mean(resid**2)))
            if rms > 1e-8:
                log.warning(f"polyco segment {s}: fit rms {rms:.2e} cycles")
            entries.append(PolycoEntry(
                tmid, segLength, int(rint), float(rfrac), f0, ncoeff, coeffs,
                obs=obsname, obsfreq=obsFreq, psrname=psr))
        return cls(entries)

    # -- dispatch ------------------------------------------------------------
    #: boundary tolerance [days]: segment edges derive from tmid values
    #: quantized to the file format's 1e-11-day precision, which can open
    #: ~1e-11-day gaps at the span boundaries; the polynomial is perfectly
    #: valid that far outside its nominal window
    EDGE_TOL = 1e-9

    def find_entry(self, t_mjd: float) -> PolycoEntry:
        for e in self.entries:
            if e.tstart <= t_mjd < e.tstop:
                return e
        best, dist = None, np.inf
        for e in self.entries:
            d = max(e.tstart - t_mjd, t_mjd - e.tstop, 0.0)
            if d < dist:
                best, dist = e, d
        if best is not None and dist <= self.EDGE_TOL:
            return best
        raise ValueError(f"No polyco entry covers MJD {t_mjd}")

    def eval_abs_phase(self, t_mjd) -> Phase:
        t = np.atleast_1d(np.asarray(t_mjd, dtype=np.float64))
        ints = np.empty(len(t))
        fracs = np.empty(len(t))
        for i, ti in enumerate(t):
            ph = self.find_entry(ti).evalabsphase(ti)
            ints[i] = np.asarray(ph.int_)
            fracs[i] = np.asarray(ph.frac)
        return Phase(ints, fracs)

    def eval_phase(self, t_mjd) -> np.ndarray:
        return np.asarray(self.eval_abs_phase(t_mjd).frac) % 1.0

    def eval_spin_freq(self, t_mjd) -> np.ndarray:
        t = np.atleast_1d(np.asarray(t_mjd, dtype=np.float64))
        return np.array([float(self.find_entry(ti).evalfreq(ti)) for ti in t])

    def eval_spin_freq_derivative(self, t_mjd) -> np.ndarray:
        """Spin frequency derivative [Hz/s] at each time (reference
        ``polycos.py:1008``)."""
        t = np.atleast_1d(np.asarray(t_mjd, dtype=np.float64))
        return np.array([float(self.find_entry(ti).evalfreqderiv(ti))
                         for ti in t])

    # -- IO ------------------------------------------------------------------
    def write_polyco_file(self, filename: str):
        tempo_polyco_table_writer(self.entries, filename)

    @classmethod
    def read_polyco_file(cls, filename: str) -> "Polycos":
        return cls(tempo_polyco_table_reader(filename))

    #: reference-parity alias (``polycos.py:549``)
    read = read_polyco_file

    #: registered file formats: {name: {"read": fn, "write": fn}}
    polycoFormats: dict = {"tempo": {"read": None, "write": None}}

    @classmethod
    def add_polyco_file_format(cls, formatName: str, methodMood: str,
                               readMethod=None, writeMethod=None) -> None:
        """Register a custom polyco file format (reference
        ``polycos.py:567``): ``methodMood`` in 'r'/'w'/'rw'; the read
        method takes a filename and returns a list of PolycoEntry, the
        write method takes (entries, filename)."""
        if methodMood not in ("r", "w", "rw"):
            raise ValueError("methodMood must be 'r', 'w', or 'rw'")
        if "r" in methodMood and readMethod is None:
            raise ValueError(f"format {formatName!r}: mood {methodMood!r} "
                             "needs a readMethod")
        if "w" in methodMood and writeMethod is None:
            raise ValueError(f"format {formatName!r}: mood {methodMood!r} "
                             "needs a writeMethod")
        entry = cls.polycoFormats.setdefault(
            formatName, {"read": None, "write": None})
        if readMethod is not None:
            entry["read"] = readMethod
        if writeMethod is not None:
            entry["write"] = writeMethod

    @classmethod
    def read_polyco_file_format(cls, filename: str,
                                format: str = "tempo") -> "Polycos":
        """Read using a registered format (defaults to TEMPO)."""
        if format == "tempo":
            return cls.read_polyco_file(filename)
        fmt = cls.polycoFormats.get(format)
        if fmt is None or fmt["read"] is None:
            raise ValueError(f"No registered reader for format {format!r}")
        return cls(fmt["read"](filename))


def tempo_polyco_table_writer(entries: List[PolycoEntry], filename: str):
    """TEMPO polyco.dat format (reference ``polycos.py:360``)."""
    with open(filename, "w") as f:
        for e in entries:
            mjd_int = int(e.tmid)
            mjd_frac = e.tmid - mjd_int
            date = "DD-MMM-YY"
            utc = f"{(mjd_frac * 24):02.0f}0000.00"
            f.write(f"{e.psrname:<10s} {date:>9s} {utc:>11s} "
                    f"{e.tmid:20.11f} {0.0:21.6f} {0.0:6.3f} {-6.0:7.3f}\n")
            # Phase frac lives in [-0.5, 0.5): recombine and split so the
            # written reference phase never gains a spurious cycle
            total = e.rphase_int + e.rphase_frac
            ip = int(np.floor(total))
            rphase = f"{ip}.{f'{total - ip:.6f}'[2:]}"
            f.write(f"{rphase:>20s} {e.f0:18.12f} {e.obs:>5s} "
                    f"{e.mjdspan:5.0f} {e.ncoeff:5d} {e.obsfreq:10.3f}\n")
            for i in range(0, e.ncoeff, 3):
                row = e.coeffs[i:i + 3]
                f.write("".join(f"{c:25.17e}" for c in row) + "\n")


def tempo_polyco_table_reader(filename: str) -> List[PolycoEntry]:
    """Parse a TEMPO polyco.dat (reference ``polycos.py:232``)."""
    entries = []
    with open(filename) as f:
        lines = [ln.rstrip("\n") for ln in f if ln.strip()]
    i = 0
    while i < len(lines):
        h1 = lines[i].split()
        psrname = h1[0]
        tmid = float(h1[3])
        h2 = lines[i + 1].split()
        rphase_s = h2[0]
        f0 = float(h2[1])
        obs = h2[2]
        span = float(h2[3])
        ncoeff = int(h2[4])
        obsfreq = float(h2[5])
        if "." in rphase_s:
            ip, fp = rphase_s.split(".")
            rint, rfrac = int(ip), float("0." + fp)
        else:
            rint, rfrac = int(rphase_s), 0.0
        ncl = (ncoeff + 2) // 3
        coeffs = []
        for j in range(ncl):
            coeffs += [float(x.replace("D", "E"))
                       for x in lines[i + 2 + j].split()]
        entries.append(PolycoEntry(tmid, span, rint, rfrac, f0, ncoeff,
                                   coeffs[:ncoeff], obs=obs, obsfreq=obsfreq,
                                   psrname=psrname))
        i += 2 + ncl
    return entries
