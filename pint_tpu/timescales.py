"""Time-scale conversions: UTC -> TAI -> TT -> TDB, without astropy/ERFA.

The reference delegates UTC->TT->TDB to astropy ``Time`` (ERFA C inside,
``toa.py:2251``, ``observatory/__init__.py:443``).  In this framework the
conversions are implemented natively so ingestion has zero astronomy-library
dependencies:

* leap seconds from a built-in IERS table (UTC is only defined since 1972),
* TT = TAI + 32.184 s,
* TDB - TT from a truncated Fairhead-Bretagnon-style analytic series
  (geocentric terms; ~10 us accuracy — pluggable, see :class:`TDBProvider`,
  so a full FB90 table or ephemeris-integrated TE405 can be dropped in).

MJDs follow the "pulsar_mjd" convention of the reference
(``pulsar_mjd.py:86``): the fractional day is seconds-since-midnight/86400,
i.e. leap seconds never make a day longer than 86400 s.  All host math is in
numpy longdouble and converts losslessly to DD pairs for the device.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "tai_minus_utc",
    "tt_minus_utc",
    "utc_to_tt_mjd",
    "tdb_minus_tt",
    "tdb_minus_tt_series",
    "set_tdb_provider",
    "utc_to_tdb_mjd",
    "gps_to_utc_seconds",
]

# (MJD of UTC start, TAI-UTC seconds) — IERS leap-second history since 1972.
_LEAP_TABLE = np.array(
    [
        (41317.0, 10.0), (41499.0, 11.0), (41683.0, 12.0), (42048.0, 13.0),
        (42413.0, 14.0), (42778.0, 15.0), (43144.0, 16.0), (43509.0, 17.0),
        (43874.0, 18.0), (44239.0, 19.0), (44786.0, 20.0), (45151.0, 21.0),
        (45516.0, 22.0), (46247.0, 23.0), (47161.0, 24.0), (47892.0, 25.0),
        (48257.0, 26.0), (48804.0, 27.0), (49169.0, 28.0), (49534.0, 29.0),
        (50083.0, 30.0), (50630.0, 31.0), (51179.0, 32.0), (53736.0, 33.0),
        (54832.0, 34.0), (56109.0, 35.0), (57204.0, 36.0), (57754.0, 37.0),
    ]
)

TT_MINUS_TAI = 32.184  # seconds, by definition
GPS_MINUS_TAI = -19.0  # TAI - GPS = 19 s, constant since GPS epoch


def tai_minus_utc(utc_mjd) -> np.ndarray:
    """TAI-UTC in seconds at the given UTC MJD(s)."""
    utc_mjd = np.atleast_1d(np.asarray(utc_mjd, dtype=np.float64))
    idx = np.searchsorted(_LEAP_TABLE[:, 0], utc_mjd, side="right") - 1
    if np.any(idx < 0):
        raise ValueError("UTC is undefined before MJD 41317 (1972-01-01)")
    return _LEAP_TABLE[idx, 1]


def tt_minus_utc(utc_mjd) -> np.ndarray:
    """TT-UTC in seconds."""
    return tai_minus_utc(utc_mjd) + TT_MINUS_TAI


def gps_to_utc_seconds(utc_mjd) -> np.ndarray:
    """UTC - UTC(GPS) offset in seconds: -(TAI-UTC) + 19."""
    return -(tai_minus_utc(utc_mjd) - 19.0)


def utc_to_tt_mjd(utc_mjd):
    """UTC MJD (pulsar_mjd convention) -> TT MJD, longdouble in/out."""
    utc_mjd = np.asarray(utc_mjd, dtype=np.longdouble)
    dt = tt_minus_utc(np.asarray(utc_mjd, dtype=np.float64)).reshape(utc_mjd.shape)
    return utc_mjd + np.asarray(dt, dtype=np.longdouble) / np.longdouble(86400.0)


def utc_to_tdb_offset_seconds(utc_mjd, ephem: "str | None" = None) -> np.ndarray:
    """(TDB - UTC) in seconds at the given UTC epochs, float64.

    Computed without forming absolute-MJD sums, so degraded-longdouble
    platforms can apply the offset to a (hi, lo) pair with an error-free
    transform instead of rounding at ulp(MJD) ~ 0.3 us.
    """
    utc64 = np.asarray(utc_mjd, dtype=np.float64)
    dt = tt_minus_utc(utc64)
    tt64 = utc64 + dt / 86400.0
    return dt + tdb_minus_tt(tt64, ephem=ephem)


def tt_to_utc_mjd(tt_mjd):
    """TT MJD -> UTC MJD (inverse of utc_to_tt_mjd; TT-UTC evaluated at the
    TT epoch is exact away from a leap-second boundary, where the offset is
    constant over the ~69 s difference anyway)."""
    tt_mjd = np.asarray(tt_mjd, dtype=np.longdouble)
    dt = tt_minus_utc(np.asarray(tt_mjd, dtype=np.float64)).reshape(tt_mjd.shape)
    return tt_mjd - np.asarray(dt, dtype=np.longdouble) / np.longdouble(86400.0)


# Truncated analytic TDB-TT series (geocentric).  Terms: (amplitude_s,
# frequency_rad_per_julian_century, phase_rad); the classic leading terms of
# the Fairhead & Bretagnon (1990) series as tabulated in the Astronomical
# Almanac.  Accuracy ~10 us 1980-2050; the full 1.7 ms annual term dominates.
_TDB_TERMS = np.array(
    [
        (1.656674e-3, 628.3075850, 6.240054),
        (2.2418e-5, 575.3384885, 4.296977),
        (1.3840e-5, 1256.6151700, 6.196905),
        (4.770e-6, 52.9690965, 0.444401),
        (4.677e-6, 606.9776754, 4.021195),
        (2.257e-6, 21.3299095, 5.543113),
        (1.694e-6, -0.3523118, 5.025133),
        (1.554e-6, 628.6598968, 5.198467),
        (1.276e-6, 1203.6460735, 4.444888),
        (1.193e-6, 1150.6769770, 2.322313),
        (1.115e-6, 7.4781599, 5.154724),
        (0.794e-6, 786.0419392, 3.910456),
        (0.600e-6, 575.3384885, 2.435898),
        (0.496e-6, 1097.7078805, 5.171764),
    ]
)
# secular mixed term: +1.02e-8 * T * sin(628.3076 T + 4.249) s
_TDB_SECULAR = (1.02e-8, 628.3075850, 4.249032)


def tdb_minus_tt_series(tt_mjd) -> np.ndarray:
    """TDB-TT in seconds from the truncated analytic series (geocentric,
    ~10 us accuracy 1980-2050)."""
    tt_mjd = np.asarray(tt_mjd, dtype=np.float64)
    T = ((tt_mjd - 51544.5) / 36525.0).reshape(-1)
    amp = _TDB_TERMS[:, 0][:, None]
    freq = _TDB_TERMS[:, 1][:, None]
    ph = _TDB_TERMS[:, 2][:, None]
    out = np.sum(amp * np.sin(freq * T[None, :] + ph), axis=0)
    a, f, p = _TDB_SECULAR
    out = out + a * T * np.sin(f * T + p)
    return out.reshape(tt_mjd.shape)


from pint_tpu.exceptions import EphemCoverageError as _EphemCoverageError

_tdb_provider = None  # explicit user override via set_tdb_provider
_warned_tdb_fallback = False


def tdb_minus_tt(tt_mjd, ephem: "str | None" = None) -> np.ndarray:
    """TDB-TT in seconds (geocentric), float64.

    Source priority: (1) an explicitly installed provider
    (:func:`set_tdb_provider`); (2) the loaded kernel's own time-ephemeris
    segment when present (DE430t/DE440t 't' kernels — ns-exact, better than
    the reference's ERFA analytic series); (3) direct integration of the
    defining rate equation with the loaded ephemeris
    (:mod:`pint_tpu.tdb_integrated` — timing-relevant variation exact to
    ephemeris quality); (4) the truncated analytic series (~10 us).
    """
    global _warned_tdb_fallback
    if _tdb_provider is not None:
        return _tdb_provider(np.asarray(tt_mjd, dtype=np.float64))
    try:
        from pint_tpu.ephemeris import load_ephemeris

        eph = load_ephemeris(ephem or "DE440")
        if getattr(eph, "has_tdb_tt", lambda: False)():
            return eph.tdb_minus_tt(tt_mjd)
        from pint_tpu.tdb_integrated import integrated_tdb_minus_tt

        return integrated_tdb_minus_tt(tt_mjd, ephem=ephem)
    except (FileNotFoundError, ImportError, KeyError,
            _EphemCoverageError) as e:
        # expected degradations only (missing kernel/scipy, epochs outside
        # kernel coverage); programming errors must surface, not silently
        # downgrade precision by 4 orders of magnitude
        if not _warned_tdb_fallback:
            _warned_tdb_fallback = True
            from pint_tpu.logging import log

            log.warning(f"Integrated TDB-TT unavailable ({e}); using the "
                        "truncated analytic series (~10 us)")
        return tdb_minus_tt_series(np.asarray(tt_mjd, dtype=np.float64))


def set_tdb_provider(fn) -> None:
    """Install an alternative TDB-TT provider (signature: tt_mjd -> seconds);
    pass None to restore the kernel/series default."""
    global _tdb_provider
    _tdb_provider = fn


def utc_to_tdb_mjd(utc_mjd, ephem: "str | None" = None):
    """UTC MJD -> TDB MJD, longdouble precision end to end."""
    tt = utc_to_tt_mjd(utc_mjd)
    dt = tdb_minus_tt(np.asarray(tt, dtype=np.float64),
                      ephem=ephem).reshape(np.shape(tt))
    return tt + np.asarray(dt, dtype=np.longdouble) / np.longdouble(86400.0)
