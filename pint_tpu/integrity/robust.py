"""Outlier-robust reweighting for WLS fits (IRLS with a Huber psi).

The Huber M-estimator keeps the quadratic loss for whitened residuals
inside ``k`` sigma and switches to linear loss outside, which in IRLS
form is a per-TOA weight ``w = min(1, k/|z|)`` applied to the *variance*
(sigma_eff = sigma / sqrt(w)).  ``k = 1.345`` gives 95% asymptotic
efficiency under a clean Gaussian, the textbook default.  The reweighting
loop runs host-side around the fitters' existing (jitted) solve step, so
a healthy fit (all weights 1) pays nothing.
"""

from __future__ import annotations

import numpy as np

__all__ = ["HUBER_K", "huber_weights", "irls_converged"]

#: 95%-efficiency Huber tuning constant
HUBER_K = 1.345


def huber_weights(whitened: np.ndarray, k: float = HUBER_K) -> np.ndarray:
    """Per-TOA Huber IRLS weights from whitened residuals ``z = r/sigma``.

    ``w = 1`` for |z| <= k, ``k/|z|`` beyond — an outlier at 1000 sigma
    keeps ~k/1000 of its weight.  Non-finite residuals get weight 0 (the
    row cannot vote at all).
    """
    z = np.abs(np.asarray(whitened, dtype=np.float64))
    w = np.ones_like(z)
    out = z > k
    # z>k guarantees z>0 here, no division hazard
    w[out] = k / z[out]
    w[~np.isfinite(z)] = 0.0
    return w


def irls_converged(w_old: np.ndarray, w_new: np.ndarray,
                   tol: float = 1e-3) -> bool:
    """True when the weight vector has stopped moving (max abs change)."""
    return float(np.max(np.abs(w_new - w_old))) < tol if len(w_new) else True
