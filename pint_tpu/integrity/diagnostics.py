"""Structured ingestion diagnostics.

A :class:`Diagnostics` report accumulates every problem the validating
ingestion path (``io/par.py``, ``io/tim.py``, ``TOAs.validate``) finds,
each pinned to its source location.  Under the ``strict`` ingestion policy
the first *error*-severity entry raises a typed exception instead; under
``lenient`` entries are recorded (warnings logged once each); under
``collect`` everything is recorded silently so a caller can audit the
whole file in one pass (the tempo2 read-time discipline: suspect input is
rejected or flagged before it can reach a fit).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from pint_tpu.exceptions import UsageError
from pint_tpu.logging import log

__all__ = ["Diagnostic", "Diagnostics"]

#: severity levels, mildest first
SEVERITIES = ("info", "warning", "error")


@dataclass(frozen=True)
class Diagnostic:
    """One ingestion finding: where it is, how bad it is, what it says."""

    severity: str  # info | warning | error
    code: str      # short machine-readable slug, e.g. "tim-unknown-line"
    message: str
    file: Optional[str] = None
    line: Optional[int] = None   # 1-based
    column: Optional[int] = None  # 1-based

    def render(self) -> str:
        where = self.file or "<input>"
        if self.line is not None:
            where += f":{self.line}"
            if self.column is not None:
                where += f":{self.column}"
        return f"[{self.severity}] {where}: {self.message} ({self.code})"


class Diagnostics:
    """Ordered accumulator of :class:`Diagnostic` records for one ingestion
    pass.  Mutable and cheap; attach it to the parse result so callers can
    audit what lenient mode skipped."""

    def __init__(self, source: Optional[str] = None):
        self.source = source
        self.records: List[Diagnostic] = []

    # -- recording ----------------------------------------------------------
    def add(self, severity: str, code: str, message: str,
            file: Optional[str] = None, line: Optional[int] = None,
            column: Optional[int] = None, quiet: bool = False) -> Diagnostic:
        if severity not in SEVERITIES:
            raise UsageError(f"severity must be one of {SEVERITIES}")
        d = Diagnostic(severity, code, message, file or self.source, line,
                       column)
        self.records.append(d)
        if not quiet and severity != "info":
            log.warning(d.render())
        return d

    def info(self, code, message, **kw):
        return self.add("info", code, message, **kw)

    def warning(self, code, message, **kw):
        return self.add("warning", code, message, **kw)

    def error(self, code, message, **kw):
        return self.add("error", code, message, **kw)

    # -- inspection ---------------------------------------------------------
    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.records if d.severity == "warning"]

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.records if d.severity == "error"]

    def codes(self) -> List[str]:
        return [d.code for d in self.records]

    def __len__(self) -> int:
        return len(self.records)

    def __iter__(self):
        return iter(self.records)

    def __bool__(self) -> bool:
        # truthiness means "something was found", so `if diags:` reads right
        return bool(self.records)

    def extend(self, other: "Diagnostics") -> "Diagnostics":
        self.records.extend(other.records)
        return self

    def render(self) -> str:
        head = f"Ingestion diagnostics for {self.source or '<input>'}: " \
               f"{len(self.errors)} error(s), {len(self.warnings)} warning(s)"
        return "\n".join([head] + ["  " + d.render() for d in self.records])

    def __repr__(self) -> str:
        return (f"<Diagnostics {self.source or '<input>'}: "
                f"{len(self.errors)}E/{len(self.warnings)}W>")
