"""``fitter.doctor()``: a human-readable audit of everything the
input-integrity layer knows about one fit.

Sections
--------
* **Device** — the preflight :class:`DeviceProfile` (platform, f64 health).
* **TOAs** — counts, span, and the quarantine audit (quarantined rows +
  reasons), recomputed cheaply when the container has never been
  validated.
* **Model/TOA compatibility** — checks that need both sides: mask
  parameters selecting no TOAs, a JUMP covering every TOA (degenerate
  with the overall phase offset), and free-parameter *pairs* whose
  design-matrix columns are nearly collinear (the classic
  freeze-one-of-them degeneracies).
* **Robust weights** — after a ``fit_toas(robust="huber")``, the TOAs the
  IRLS loop downweighted.
"""

from __future__ import annotations

from typing import List

import numpy as np

__all__ = ["render_doctor_report", "model_toa_findings"]

#: |correlation| of two normalized design-matrix columns above which the
#: pair is reported as degenerate (freeze one of them)
DEGENERATE_CORR = 0.9999


def model_toa_findings(model, toas, designmatrix: bool = True) -> List[str]:
    """Compatibility problems between a timing model and a TOA set, as
    human-readable strings (empty list = clean)."""
    findings: List[str] = []
    # component-declared requirements (MissingTOAs and friends)
    try:
        model.validate_toas(toas)
    except Exception as e:
        findings.append(f"model.validate_toas: {e}")
    # a JUMP (or any mask parameter) selecting every TOA is degenerate
    # with the overall phase offset; one selecting none fits nothing
    from pint_tpu.models.parameter import maskParameter

    n = len(toas)
    for pname in model.params:
        par = getattr(model, pname)
        if not isinstance(par, maskParameter) or par.frozen:
            continue
        try:
            sel = np.asarray(par.select_toa_mask(toas))
        except Exception:
            continue
        nsel = int(sel.sum()) if sel.dtype == bool else len(sel)
        if nsel == 0:
            findings.append(f"free mask parameter {pname} selects no TOAs")
        elif nsel == n and pname.startswith("JUMP"):
            findings.append(
                f"free {pname} selects every TOA — fully degenerate with "
                f"the overall phase offset; freeze it or narrow its mask")
    # near-collinear free-parameter pairs in the design matrix
    if designmatrix and len(model.free_params) >= 2 and n > 2:
        try:
            M, params, _ = model.designmatrix(toas)
            M = np.asarray(M, dtype=np.float64)
            norms = np.linalg.norm(M, axis=0)
            norms[norms == 0] = 1.0
            Mn = M / norms
            corr = Mn.T @ Mn
            for i in range(len(params)):
                for j in range(i + 1, len(params)):
                    if abs(corr[i, j]) > DEGENERATE_CORR:
                        findings.append(
                            f"free parameters {params[i]} and {params[j]} "
                            f"are degenerate (|column corr| = "
                            f"{abs(corr[i, j]):.6f}); freeze one of them")
        except Exception as e:  # a broken model must not break the audit
            findings.append(f"design-matrix degeneracy check failed: {e}")
    return findings


def _toa_section(fitter) -> List[str]:
    toas = getattr(fitter, "toas_full", None) or fitter.toas
    lines = [f"TOAs: {len(toas)} read"]
    if len(toas):
        lines[0] += (f", span MJD {toas.first_MJD():.1f}-"
                     f"{toas.last_MJD():.1f}, "
                     f"{len(toas.observatories)} observatory(ies)")
    report = getattr(toas, "last_validation", None)
    if report is None:
        # never validated: run the structural checks (no coverage I/O)
        from pint_tpu.integrity.quarantine import run_toa_checks

        report = run_toa_checks(toas, check_coverage=False)
    for ln in report.render().splitlines():
        lines.append(ln)
    if getattr(fitter, "toas_full", None) is not None:
        lines.append(f"fit uses {len(fitter.toas)} certified TOA(s)")
    return lines


def _robust_section(fitter) -> List[str]:
    w = getattr(fitter, "robust_weights", None)
    if w is None:
        return []
    w = np.asarray(w)
    down = np.nonzero(w < 0.999)[0]
    lines = [f"Robust fit: Huber IRLS converged in "
             f"{getattr(fitter, 'robust_iterations', '?')} iteration(s), "
             f"{len(down)}/{len(w)} TOA(s) downweighted"]
    order = down[np.argsort(w[down])][:15]
    mjds = np.asarray(fitter.toas.get_mjds(), dtype=np.float64)
    for i in order:
        lines.append(f"  row {int(i)} (MJD {mjds[i]:.4f}): weight "
                     f"{w[i]:.4f}")
    if len(down) > 15:
        lines.append(f"  ... and {len(down) - 15} more")
    return lines


def render_doctor_report(fitter, designmatrix: bool = True) -> str:
    """The full audit for one fitter, as a printable string."""
    out: List[str] = ["== pint_tpu fit doctor =="]
    prof = getattr(fitter, "device_profile", None)
    if prof is not None:
        out.append(
            f"Device: {getattr(prof, 'platform', '?')} "
            f"({getattr(prof, 'device_kind', '?')}), "
            f"f64_native={getattr(prof, 'f64_native', '?')}")
    out.extend(_toa_section(fitter))
    compat = model_toa_findings(fitter.model, fitter.toas,
                                designmatrix=designmatrix)
    out.append(f"Model/TOA compatibility: "
               f"{'clean' if not compat else f'{len(compat)} finding(s)'}")
    out.extend("  " + f for f in compat)
    out.extend(_robust_section(fitter))
    diags = getattr(fitter, "solve_diagnostics", None)
    if diags is not None:
        out.append(f"Last solve: {diags}")
    return "\n".join(out)
