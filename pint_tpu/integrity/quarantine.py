"""TOA quarantine: detect rows that must not reach a fit.

``TOAs.validate()`` delegates here.  Each check yields ``(index, code,
message)`` findings; offenders are moved into a boolean quarantine mask
(True = quarantined) that rides on the TOAs object and is carried through
slicing, merging, and pickling.  Fitters consume only the certified
complement (``TOAs.certified()``), following the correlated-noise
literature's warning that a few contaminated TOAs can bias the whole GLS
solution (Coles et al. 2011) and the tempo2 read-time rejection
discipline.

Checks
------
* ``toa-nonfinite-mjd`` — NaN/inf arrival times;
* ``toa-bad-error`` — non-positive, non-finite, or absurd (> ``max_error_us``)
  uncertainties (a zero error makes chi2 infinite; an absurd one silently
  deweights the row to nothing);
* ``toa-nonfinite-freq`` — NaN observing frequency (+inf is the legal
  "infinite frequency" sentinel);
* ``toa-duplicate`` — repeated (MJD, observatory, frequency) rows: every
  occurrence after the first is quarantined;
* ``toa-clock-coverage`` — epochs past the end of the observatory's clock
  chain (the correction would be an extrapolation);
* ``toa-ephem-coverage`` — epochs outside the loaded SPK kernel's span.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

__all__ = ["QuarantineFinding", "QuarantineReport", "RowDelta",
           "row_delta", "run_toa_checks"]

#: anything beyond this TOA uncertainty is a corrupt column, not a
#: measurement (1e9 us = ~17 min)
ABSURD_ERROR_US = 1e9


@dataclass(frozen=True)
class QuarantineFinding:
    index: int
    code: str
    message: str

    def render(self) -> str:
        return f"  row {self.index}: {self.message} ({self.code})"


@dataclass(frozen=True)
class RowDelta:
    """The typed changed-row delta of one re-validation pass: what a
    consumer holding derived per-row state (the streaming cache's
    factor, a serving-side index) must do — downdate the newly
    ``quarantined`` rows, update the newly ``released`` ones, ingest
    the ``added`` ones — instead of invalidating and rebuilding from
    scratch.  Indices are into the validated TOAs container."""

    #: rows validated for the first time AND certified by this pass —
    #: directly ingestable (a new row this same pass quarantined is
    #: deliberately in NEITHER list: it was never certified, so there
    #: is nothing to ingest and nothing to downdate)
    added: Tuple[int, ...]
    quarantined: Tuple[int, ...]  #: rows newly quarantined by this pass
    released: Tuple[int, ...]     #: rows newly released by this pass

    @property
    def empty(self) -> bool:
        return not (self.added or self.quarantined or self.released)


def row_delta(prev_mask: Optional[np.ndarray],
              new_mask: np.ndarray) -> RowDelta:
    """Delta between two quarantine masks.  ``prev_mask`` ``None``
    means the container was never validated: every row the pass
    certifies is ``added``.  A container that GREW since the previous
    pass (merged-in rows) reports the certified part of the new tail
    as ``added`` and diffs the overlap.  ``added`` never includes rows
    the same pass quarantined — the documented consumer recipe is
    "ingest the added ones", and handing it rows that just failed
    validation would put bad rows in the fit (review regression)."""
    new_mask = np.asarray(new_mask, dtype=bool)
    n = len(new_mask)
    if prev_mask is None:
        return RowDelta(
            added=tuple(int(i) for i in np.nonzero(~new_mask)[0]),
            quarantined=(), released=())
    prev_mask = np.asarray(prev_mask, dtype=bool)
    o = min(len(prev_mask), n)
    return RowDelta(
        added=tuple(int(i) for i in range(o, n) if not new_mask[i]),
        quarantined=tuple(
            int(i) for i in np.nonzero(~prev_mask[:o] & new_mask[:o])[0]),
        released=tuple(
            int(i) for i in np.nonzero(prev_mask[:o] & ~new_mask[:o])[0]))


@dataclass
class QuarantineReport:
    """Outcome of one ``TOAs.validate()`` pass."""

    n_toas: int
    findings: List[QuarantineFinding] = field(default_factory=list)
    #: typed changed-row delta vs the container's previous mask
    #: (stamped by :meth:`~pint_tpu.toa.TOAs.validate`; None when the
    #: checks were run standalone)
    delta: Optional[RowDelta] = None

    @property
    def mask(self) -> np.ndarray:
        """Boolean quarantine mask (True = quarantined)."""
        m = np.zeros(self.n_toas, dtype=bool)
        for f in self.findings:
            m[f.index] = True
        return m

    @property
    def n_quarantined(self) -> int:
        return int(self.mask.sum())

    def codes(self) -> List[str]:
        return sorted({f.code for f in self.findings})

    def reasons_by_row(self) -> List[List[str]]:
        out: List[List[str]] = [[] for _ in range(self.n_toas)]
        for f in self.findings:
            out[f.index].append(f.message)
        return out

    def __bool__(self) -> bool:
        return bool(self.findings)

    def render(self, limit: int = 20) -> str:
        head = (f"TOA quarantine: {self.n_quarantined}/{self.n_toas} row(s) "
                f"quarantined ({', '.join(self.codes()) or 'clean'})")
        body = [f.render() for f in self.findings[:limit]]
        if len(self.findings) > limit:
            body.append(f"  ... and {len(self.findings) - limit} more")
        return "\n".join([head] + body)


def _check_mjds(mjd64: np.ndarray) -> List[QuarantineFinding]:
    bad = ~np.isfinite(mjd64)
    return [QuarantineFinding(int(i), "toa-nonfinite-mjd",
                              f"non-finite MJD {mjd64[i]!r}")
            for i in np.nonzero(bad)[0]]


def _check_errors(err_us: np.ndarray,
                  max_error_us: float) -> List[QuarantineFinding]:
    out = []
    for i in np.nonzero(~np.isfinite(err_us) | (err_us <= 0)
                        | (err_us > max_error_us))[0]:
        e = err_us[i]
        if not np.isfinite(e):
            msg = f"non-finite uncertainty {e!r}"
        elif e <= 0:
            msg = f"non-positive uncertainty {e} us"
        else:
            msg = f"absurd uncertainty {e:g} us (> {max_error_us:g})"
        out.append(QuarantineFinding(int(i), "toa-bad-error", msg))
    return out


def _check_freqs(freq_mhz: np.ndarray) -> List[QuarantineFinding]:
    # +inf is the legal infinite-frequency sentinel; NaN and -inf are not
    bad = np.isnan(freq_mhz) | (freq_mhz == -np.inf)
    return [QuarantineFinding(int(i), "toa-nonfinite-freq",
                              f"non-finite frequency {freq_mhz[i]!r}")
            for i in np.nonzero(bad)[0]]


def _check_duplicates(mjd64: np.ndarray, mjd_lo: np.ndarray,
                      obs: np.ndarray,
                      freq_mhz: np.ndarray) -> List[QuarantineFinding]:
    """Every occurrence after the first of an identical (MJD, obs, freq)
    row.  Keys on the FULL-precision (hi, lo) arrival time — float64
    alone quantizes MJDs at ~0.6 us, which would falsely merge genuinely
    distinct sub-microsecond-separated TOAs.  Vectorized (lexsort +
    adjacent compare): this runs on every get_TOAs load, so a per-row
    Python loop would tax serving-scale ingestion."""
    out: List[QuarantineFinding] = []
    idx = np.nonzero(np.isfinite(mjd64))[0]  # NaNs: the MJD check's job
    if len(idx) < 2:
        return out
    obs_inv = np.unique(obs.astype(str)[idx], return_inverse=True)[1]
    # primary key mjd64, then lo, freq, obs; original index last so the
    # head of every equal run is the FIRST occurrence
    order = np.lexsort((idx, obs_inv, freq_mhz[idx], mjd_lo[idx],
                        mjd64[idx]))
    s = idx[order]
    same = ((mjd64[s][1:] == mjd64[s][:-1])
            & (mjd_lo[s][1:] == mjd_lo[s][:-1])
            & (freq_mhz[s][1:] == freq_mhz[s][:-1])
            & (obs_inv[order][1:] == obs_inv[order][:-1]))
    if not same.any():
        return out
    # run head for each sorted position: latest position that starts a run
    head_pos = np.maximum.accumulate(
        np.where(np.concatenate([[True], ~same]), np.arange(len(s)), -1))
    for j in np.nonzero(same)[0] + 1:
        i, first = int(s[j]), int(s[head_pos[j]])
        out.append(QuarantineFinding(
            i, "toa-duplicate",
            f"duplicate of row {first} (MJD {mjd64[i]:.10f}, {obs[i]}, "
            f"{freq_mhz[i]:g} MHz)"))
    return out


def _check_clock_coverage(mjd64: np.ndarray,
                          obs: np.ndarray) -> List[QuarantineFinding]:
    from pint_tpu.observatory import get_observatory

    out = []
    for site in np.unique(obs.astype(str)):
        try:
            ob = get_observatory(site)
            last = float(ob.last_clock_correction_mjd(limits="allow"))
        except Exception:
            continue  # no clock chain for this site: nothing to cover
        if not np.isfinite(last):
            continue
        m = (obs.astype(str) == site) & np.isfinite(mjd64) & (mjd64 > last)
        for i in np.nonzero(m)[0]:
            out.append(QuarantineFinding(
                int(i), "toa-clock-coverage",
                f"MJD {mjd64[i]:.3f} is past the end of the {site} clock "
                f"chain (last correction at MJD {last:.3f})"))
    return out


def _check_ephem_coverage(mjd64: np.ndarray,
                          ephem: str) -> List[QuarantineFinding]:
    from pint_tpu.ephemeris import load_ephemeris

    try:
        eph = load_ephemeris(ephem)
        lo, hi = eph.coverage_mjd()
    except Exception:
        return []  # analytic/unavailable ephemeris: no span to enforce
    out = []
    bad = np.isfinite(mjd64) & ((mjd64 < lo) | (mjd64 > hi))
    for i in np.nonzero(bad)[0]:
        out.append(QuarantineFinding(
            int(i), "toa-ephem-coverage",
            f"MJD {mjd64[i]:.3f} outside ephemeris {ephem} coverage "
            f"[{lo:.1f}, {hi:.1f}]"))
    return out


def run_toa_checks(toas, check_coverage: bool = True,
                   max_error_us: float = ABSURD_ERROR_US,
                   ephem: Optional[str] = None) -> QuarantineReport:
    """Run every quarantine check over a TOAs container; returns the
    report (the caller decides what the policy does with it)."""
    mjd64 = np.asarray(toas.utc_mjd, dtype=np.float64)
    # sub-double part of the arrival time (x87 longdouble residual plus
    # the explicit lo column on degraded-longdouble platforms)
    with np.errstate(invalid="ignore"):
        mjd_lo = np.asarray(
            np.asarray(toas.utc_mjd) - mjd64.astype(np.longdouble),
            dtype=np.float64)
    mjd_lo = np.where(np.isfinite(mjd_lo), mjd_lo, 0.0)
    extra_lo = getattr(toas, "utc_mjd_lo", None)
    if extra_lo is not None:
        mjd_lo = mjd_lo + np.asarray(extra_lo, dtype=np.float64)
    err_us = np.asarray(toas.error_us, dtype=np.float64)
    freq = np.asarray(toas.freq_mhz, dtype=np.float64)
    obs = np.asarray(toas.obs)
    findings: List[QuarantineFinding] = []
    findings += _check_mjds(mjd64)
    findings += _check_errors(err_us, max_error_us)
    findings += _check_freqs(freq)
    findings += _check_duplicates(mjd64, mjd_lo, obs, freq)
    if check_coverage:
        findings += _check_clock_coverage(mjd64, obs)
        eph = ephem or getattr(toas, "ephem", None)
        if eph:
            findings += _check_ephem_coverage(mjd64, str(eph))
    findings.sort(key=lambda f: (f.index, f.code))
    return QuarantineReport(n_toas=len(mjd64), findings=findings)
