"""Input-integrity layer: validating ingestion, TOA quarantine, robust
reweighting, and the fit doctor.

Three pillars (the data-side twin of ``pint_tpu/runtime``'s guardrails):

1. **Validating ingestion** — ``io/par.py`` / ``io/tim.py`` run under the
   strict/lenient/collect ingestion policy (:mod:`pint_tpu.config`),
   raising typed :class:`~pint_tpu.exceptions.ParSyntaxError` /
   :class:`~pint_tpu.exceptions.TimSyntaxError` with file:line:column
   context, or accumulating a :class:`Diagnostics` report.
2. **TOA quarantine** — ``TOAs.validate()`` (:mod:`.quarantine`) masks
   rows no fit should see; fitters consume ``TOAs.certified()``.
3. **Outlier-robust fitting** — Huber IRLS weights (:mod:`.robust`) for
   ``fit_toas(robust="huber")``, audited by ``fitter.doctor()``
   (:mod:`.doctor`).
"""

from pint_tpu.integrity.diagnostics import Diagnostic, Diagnostics  # noqa: F401
from pint_tpu.integrity.quarantine import (  # noqa: F401
    ABSURD_ERROR_US,
    QuarantineFinding,
    QuarantineReport,
    RowDelta,
    row_delta,
    run_toa_checks,
)
from pint_tpu.integrity.robust import HUBER_K, huber_weights  # noqa: F401
from pint_tpu.integrity.doctor import (  # noqa: F401
    model_toa_findings,
    render_doctor_report,
)

__all__ = [
    "Diagnostic", "Diagnostics",
    "QuarantineFinding", "QuarantineReport", "RowDelta", "row_delta",
    "run_toa_checks",
    "ABSURD_ERROR_US", "HUBER_K", "huber_weights",
    "model_toa_findings", "render_doctor_report",
]
