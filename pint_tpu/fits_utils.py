"""Minimal FITS reader for photon-event files.

Counterpart of reference ``fits_utils.py`` (which wraps astropy.io.fits —
not available in this deployment, so the container format is implemented
directly from the FITS 4.0 standard): 2880-byte blocks of 80-char header
cards, BINTABLE extensions with big-endian columns described by
TTYPEn/TFORMn.  Covers what event files need — L (logical), B, I, J, K
integers, E/D floats, A strings, and repeat counts.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["FITSHDU", "read_fits", "read_fits_event_mjds",
           "read_fits_event_mjds_tuples"]

BLOCK = 2880
CARD = 80

_TFORM_DTYPE = {
    "L": "u1", "X": "u1", "B": "u1", "I": ">i2", "J": ">i4", "K": ">i8",
    "E": ">f4", "D": ">f8", "C": ">c8", "M": ">c16", "A": "S",
}


def _parse_header(block_iter) -> Optional[Dict[str, object]]:
    """Read header blocks until END; returns card dict or None at EOF."""
    cards: Dict[str, object] = {}
    done = False
    got_any = False
    while not done:
        block = block_iter(BLOCK)
        if len(block) < BLOCK:
            return cards if got_any else None
        got_any = True
        for i in range(0, BLOCK, CARD):
            card = block[i:i + CARD].decode("ascii", errors="replace")
            key = card[:8].strip()
            if key == "END":
                done = True
                break
            if not key or key in ("COMMENT", "HISTORY") or card[8] != "=":
                continue
            val = card[10:]
            # strip trailing comment (not inside a quoted string)
            if val.lstrip().startswith("'"):
                q = val.find("'", val.find("'") + 1)
                sval = val[val.find("'") + 1:q]
                cards[key] = sval.strip()
            else:
                val = val.split("/")[0].strip()
                if val in ("T", "F"):
                    cards[key] = val == "T"
                elif val:
                    try:
                        cards[key] = int(val)
                    except ValueError:
                        try:
                            cards[key] = float(val.replace("D", "E"))
                        except ValueError:
                            cards[key] = val
    return cards


def _data_size(hdr: Dict[str, object]) -> int:
    naxis = int(hdr.get("NAXIS", 0))
    if naxis == 0:
        return 0
    size = 1
    for i in range(1, naxis + 1):
        size *= int(hdr.get(f"NAXIS{i}", 0))
    bitpix = abs(int(hdr.get("BITPIX", 8)))
    size *= bitpix // 8
    # heap (variable-length arrays) follows the main table in extensions
    if "XTENSION" in hdr:
        size += int(hdr.get("PCOUNT", 0))
    return size


def _tform_to_dtype(tform: str) -> Tuple[str, int]:
    """TFORM string -> (numpy dtype string, repeat)."""
    tform = tform.strip()
    i = 0
    while i < len(tform) and tform[i].isdigit():
        i += 1
    repeat = int(tform[:i]) if i else 1
    code = tform[i] if i < len(tform) else "E"
    if code == "A":
        return f"S{repeat}", 1
    if code == "X":
        # bit arrays are stored packed: ceil(r/8) bytes on disk; exposed as
        # the raw packed bytes
        return "u1", (repeat + 7) // 8
    if code not in _TFORM_DTYPE:
        raise ValueError(f"Unsupported TFORM {tform!r}")
    return _TFORM_DTYPE[code], repeat


class FITSHDU:
    def __init__(self, header: Dict[str, object], data: Optional[bytes]):
        self.header = header
        self._data = data
        self._parsed: Optional[Dict[str, np.ndarray]] = None

    @property
    def name(self) -> str:
        return str(self.header.get("EXTNAME", "")).strip()

    @property
    def is_bintable(self) -> bool:
        return str(self.header.get("XTENSION", "")).strip() == "BINTABLE"

    def columns(self) -> List[str]:
        n = int(self.header.get("TFIELDS", 0))
        return [str(self.header.get(f"TTYPE{i}", f"col{i}")).strip()
                for i in range(1, n + 1)]

    def data(self) -> Dict[str, np.ndarray]:
        """Parse the BINTABLE into {column: array} (native byte order);
        cached — multi-million-row event tables are parsed once."""
        if self._parsed is not None:
            return self._parsed
        if not self.is_bintable:
            raise ValueError("Not a binary-table HDU")
        hdr = self.header
        nrows = int(hdr["NAXIS2"])
        rowbytes = int(hdr["NAXIS1"])
        nfields = int(hdr["TFIELDS"])
        fields = []
        for i in range(1, nfields + 1):
            name = str(hdr.get(f"TTYPE{i}", f"col{i}")).strip()
            dt, rep = _tform_to_dtype(str(hdr[f"TFORM{i}"]))
            fields.append((name, dt, (rep,) if rep > 1 else ()))
        dtype = np.dtype([(n, d, s) for n, d, s in fields])
        if dtype.itemsize != rowbytes:
            raise ValueError(
                f"Row size mismatch: dtype {dtype.itemsize} vs NAXIS1 {rowbytes}")
        arr = np.frombuffer(self._data[:nrows * rowbytes], dtype=dtype)
        out = {}
        for n, d, s in fields:
            col = arr[n]
            if d.startswith(">") or d.startswith("<"):
                col = col.astype(d[1:])
            out[n] = col
        self._parsed = out
        return out


def read_fits(path: str) -> List[FITSHDU]:
    hdus: List[FITSHDU] = []
    with open(path, "rb") as f:
        while True:
            hdr = _parse_header(f.read)
            if hdr is None:
                break
            size = _data_size(hdr)
            padded = ((size + BLOCK - 1) // BLOCK) * BLOCK
            data = f.read(padded)[:size] if size else None
            hdus.append(FITSHDU(hdr, data))
            if size and len(data) < size:
                break
    return hdus


def get_hdu(hdus: List[FITSHDU], extname: str) -> FITSHDU:
    for h in hdus:
        if h.name.upper() == extname.upper():
            return h
    raise KeyError(f"No HDU named {extname!r}; have "
                   f"{[h.name for h in hdus]}")


def _mjdref(hdr: Dict[str, object]):
    """(MJDREFI, MJDREFF) from the header, longdouble-safe
    (reference ``fits_utils.py``)."""
    if "MJDREFI" in hdr:
        return np.longdouble(hdr["MJDREFI"]) + np.longdouble(str(hdr.get("MJDREFF", 0)))
    if "MJDREF" in hdr:
        return np.longdouble(str(hdr["MJDREF"]))
    raise KeyError("No MJDREF in FITS header")


def read_fits_event_mjds_tuples(hdu: FITSHDU, timecolumn: str = "TIME"):
    """Event times as (mjd_int, mjd_frac) tuples
    (reference ``fits_utils.py read_fits_event_mjds_tuples``)."""
    hdr = hdu.header
    mjdref = _mjdref(hdr)
    timezero = np.longdouble(str(hdr.get("TIMEZERO", 0.0)))
    met = hdu.data()[timecolumn].astype(np.float64)
    mjds = mjdref + (np.asarray(met, dtype=np.longdouble) + timezero) / np.longdouble(86400.0)
    ints = np.floor(mjds)
    return ints.astype(np.int64), np.asarray(mjds - ints, dtype=np.float64)


def read_fits_event_mjds(hdu: FITSHDU, timecolumn: str = "TIME") -> np.ndarray:
    """Event times as longdouble MJDs (reference ``read_fits_event_mjds``)."""
    hdr = hdu.header
    mjdref = _mjdref(hdr)
    timezero = np.longdouble(str(hdr.get("TIMEZERO", 0.0)))
    met = hdu.data()[timecolumn].astype(np.float64)
    return mjdref + (np.asarray(met, dtype=np.longdouble) + timezero) / np.longdouble(86400.0)
