"""MCMC samplers: jax-native ensemble stretch move + optional emcee wrapper.

Counterpart of reference ``sampler.py:60 EmceeSampler`` (a thin wrapper over
``emcee.EnsembleSampler``).  The TPU-native primary here is
:class:`EnsembleSampler` — the Goodman & Weare (2010) affine-invariant
stretch move with the whole half-ensemble evaluated through one vectorized
lnposterior call (SURVEY §2c: "vmap lnposterior over walkers"), so each
iteration is two batched device evaluations instead of nwalkers Python
round-trips.  When ``emcee`` is installed the :class:`EmceeSampler` wrapper
offers the reference-parity surface.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from pint_tpu.logging import log

__all__ = ["MCMCSampler", "EnsembleSampler", "EmceeSampler", "NpzBackend"]


class NpzBackend:
    """Checkpoint/resume backend for :class:`EnsembleSampler` — the
    zero-dependency analogue of the emcee HDF5 backend the reference uses
    for long photon-MCMC runs (reference ``scripts/event_optimize.py:900-910``).

    Stores chain, log-probs, acceptance counters and the exact RNG state, so
    a resumed run continues the Markov chain *bit-identically* to an
    uninterrupted one.
    """

    def __init__(self, path: str):
        # np.savez appends '.npz' to bare names; normalize so save and
        # load always address the same file
        path = str(path)
        self.path = path if path.endswith(".npz") else path + ".npz"

    def exists(self) -> bool:
        import os

        return os.path.exists(self.path)

    def save(self, sampler: "EnsembleSampler") -> None:
        import pickle

        np.savez(
            self.path,
            chain=np.asarray(sampler._chain),
            lnprob=np.asarray(sampler._lnprob),
            naccepted=sampler.naccepted,
            ntotal=sampler.ntotal,
            nwalkers=sampler.nwalkers,
            a=sampler.a,
            ndim=sampler.ndim if sampler.ndim is not None else -1,
            rng_state=np.frombuffer(
                pickle.dumps(sampler.rng.bit_generator.state), dtype=np.uint8),
        )

    def load_into(self, sampler: "EnsembleSampler") -> np.ndarray:
        """Restore state; returns the last walker positions to resume from."""
        import pickle

        with np.load(self.path, allow_pickle=False) as d:
            if int(d["nwalkers"]) != sampler.nwalkers:
                raise ValueError(
                    f"backend has {int(d['nwalkers'])} walkers, sampler has "
                    f"{sampler.nwalkers}")
            sampler._chain = list(d["chain"])
            sampler._lnprob = list(d["lnprob"])
            sampler.naccepted = int(d["naccepted"])
            sampler.ntotal = int(d["ntotal"])
            if int(d["ndim"]) >= 0:
                sampler.ndim = int(d["ndim"])
            sampler.rng.bit_generator.state = pickle.loads(
                d["rng_state"].tobytes())
        if not sampler._chain:
            raise ValueError("backend contains no steps")
        return sampler._chain[-1]


class MCMCSampler:
    """Abstract sampler interface (reference ``sampler.py:7``)."""

    def __init__(self):
        self.method = None

    def initialize_sampler(self, lnpostfn, ndim: int):
        raise NotImplementedError

    def get_initial_pos(self, fitkeys, fitvals, fiterrs, errfact, **kw):
        """Gaussian ball around the fit values (reference ``sampler.py:43``)."""
        fitvals = np.asarray(fitvals, dtype=np.float64)
        fiterrs = np.asarray(fiterrs, dtype=np.float64)
        scale = np.where(fiterrs > 0, fiterrs,
                         np.abs(fitvals) * 1e-8 + 1e-12) * errfact
        rng = np.random.default_rng(kw.get("seed"))
        return fitvals + scale * rng.standard_normal((self.nwalkers, len(fitvals)))

    def run_mcmc(self, pos, nsteps):
        raise NotImplementedError


class EnsembleSampler(MCMCSampler):
    """Affine-invariant stretch-move ensemble sampler, batched.

    ``lnpost_batch`` maps an (N, ndim) array of walker positions to (N,)
    log-posteriors — e.g. ``BayesianTiming.lnposterior_batch`` (jit+vmap on
    device).  The two half-ensembles update alternately (the standard
    parallelizable variant of Goodman & Weare 2010), so detailed balance is
    preserved while every posterior evaluation is batched.
    """

    def __init__(self, nwalkers: int, a: float = 2.0,
                 seed: Optional[int] = None, backend=None,
                 checkpoint_every: int = 50):
        super().__init__()
        if nwalkers % 2:
            raise ValueError("nwalkers must be even (half-ensemble updates)")
        self.nwalkers = nwalkers
        self.a = a
        self.rng = np.random.default_rng(seed)
        self.method = "stretch"
        self._lnpost_batch: Optional[Callable] = None
        self.ndim = None
        self._chain: List[np.ndarray] = []
        self._lnprob: List[np.ndarray] = []
        self.naccepted = 0
        self.ntotal = 0
        self.backend = (NpzBackend(backend) if isinstance(backend, str)
                        else backend)
        self.checkpoint_every = checkpoint_every

    def resume(self) -> np.ndarray:
        """Restore chain + RNG state from the backend; returns the walker
        positions to continue from."""
        if self.backend is None:
            raise ValueError("no backend configured")
        pos = self.backend.load_into(self)
        log.info(f"Resumed {len(self._chain)} steps from "
                 f"{self.backend.path}")
        return pos

    def initialize_sampler(self, lnpostfn, ndim: int):
        """``lnpostfn`` may be scalar (point -> float) or batched
        ((N, ndim) -> (N,)); batched callables must expose ``.batched = True``
        or be passed via ``lnpost_batch=``."""
        self.ndim = ndim
        if getattr(lnpostfn, "batched", False):
            self._lnpost_batch = lnpostfn
        else:
            self._lnpost_batch = lambda pts: np.array(
                [lnpostfn(p) for p in np.asarray(pts)])

    def initialize_batched(self, lnpost_batch: Callable, ndim: int):
        self.ndim = ndim
        self._lnpost_batch = lnpost_batch

    def run_mcmc(self, pos, nsteps: int, progress: bool = False) -> np.ndarray:
        """Advance the ensemble *nsteps*; returns the final position."""
        x = np.array(pos, dtype=np.float64)
        n, ndim = x.shape
        if n != self.nwalkers:
            raise ValueError(f"pos has {n} walkers, expected {self.nwalkers}")
        lp = np.array(self._lnpost_batch(x), dtype=np.float64)
        half = n // 2
        for step in range(nsteps):
            for first in (True, False):
                s = slice(0, half) if first else slice(half, n)
                o = slice(half, n) if first else slice(0, half)
                xs, xo = x[s], x[o]
                # z ~ g(z) propto 1/sqrt(z) on [1/a, a]
                u = self.rng.random(half)
                z = ((self.a - 1.0) * u + 1.0) ** 2 / self.a
                partners = self.rng.integers(0, half, size=half)
                prop = xo[partners] + z[:, None] * (xs - xo[partners])
                lp_prop = np.array(self._lnpost_batch(prop), dtype=np.float64)
                lnratio = (ndim - 1) * np.log(z) + lp_prop - lp[s]
                accept = np.log(self.rng.random(half)) < lnratio
                x[s] = np.where(accept[:, None], prop, xs)
                lp_s = lp[s]
                lp_s[accept] = lp_prop[accept]
                lp[s] = lp_s
                self.naccepted += int(accept.sum())
                self.ntotal += half
            self._chain.append(x.copy())
            self._lnprob.append(lp.copy())
            if (self.backend is not None
                    and (step + 1) % self.checkpoint_every == 0):
                self.backend.save(self)
                # each save rewrites the whole chain; grow the interval so
                # cumulative checkpoint I/O stays ~linear in chain length
                if len(self._chain) >= 20 * self.checkpoint_every:
                    self.checkpoint_every *= 2
        if self.backend is not None:
            self.backend.save(self)
        return x

    @property
    def acceptance_fraction(self) -> float:
        return self.naccepted / max(self.ntotal, 1)

    def get_chain(self, flat: bool = False, discard: int = 0,
                  thin: int = 1) -> np.ndarray:
        """(nsteps, nwalkers, ndim) chain (emcee-compatible layout)."""
        c = np.array(self._chain)[discard::thin]
        return c.reshape(-1, self.ndim) if flat else c

    def get_log_prob(self, flat: bool = False, discard: int = 0,
                     thin: int = 1) -> np.ndarray:
        lp = np.array(self._lnprob)[discard::thin]
        return lp.reshape(-1) if flat else lp

    def chains_to_dict(self, names: List[str]) -> Dict[str, np.ndarray]:
        chain = self.get_chain()
        return {name: chain[:, :, i] for i, name in enumerate(names)}

    def reset(self):
        self._chain, self._lnprob = [], []
        self.naccepted = self.ntotal = 0


class EmceeSampler(MCMCSampler):
    """Reference-parity wrapper over emcee (optional dependency;
    reference ``sampler.py:60``)."""

    def __init__(self, nwalkers: int):
        super().__init__()
        try:
            import emcee  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "emcee is not installed; use pint_tpu.sampler.EnsembleSampler "
                "(jax-native, batched) instead") from e
        self.nwalkers = nwalkers
        self.sampler = None
        self.method = "emcee"

    def is_initialized(self) -> bool:
        return self.sampler is not None

    def initialize_sampler(self, lnpostfn, ndim: int):
        import emcee

        self.ndim = ndim
        self.sampler = emcee.EnsembleSampler(self.nwalkers, ndim, lnpostfn)

    def run_mcmc(self, pos, nsteps):
        return self.sampler.run_mcmc(pos, nsteps)

    def get_chain(self, **kw):
        return self.sampler.get_chain(**kw)

    def get_log_prob(self, **kw):
        return self.sampler.get_log_prob(**kw)

    @property
    def acceptance_fraction(self) -> float:
        return float(np.mean(self.sampler.acceptance_fraction))

    def chains_to_dict(self, names):
        chains = [self.sampler.chain[:, :, ii].T for ii in range(len(names))]
        return dict(zip(names, chains))
