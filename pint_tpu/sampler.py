"""MCMC samplers: jax-native ensemble stretch move + optional emcee wrapper.

Counterpart of reference ``sampler.py:60 EmceeSampler`` (a thin wrapper over
``emcee.EnsembleSampler``).  The TPU-native primary here is
:class:`EnsembleSampler` — the Goodman & Weare (2010) affine-invariant
stretch move with the whole half-ensemble evaluated through one vectorized
lnposterior call (SURVEY §2c: "vmap lnposterior over walkers"), so each
iteration is two batched device evaluations instead of nwalkers Python
round-trips.  When ``emcee`` is installed the :class:`EmceeSampler` wrapper
offers the reference-parity surface.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from pint_tpu.logging import log

__all__ = ["MCMCSampler", "EnsembleSampler", "EmceeSampler", "NpzBackend",
           "integrated_autocorr_time", "run_sampler_autocorr"]


def _next_pow_two(n: int) -> int:
    i = 1
    while i < n:
        i <<= 1
    return i


def _acf_1d(x: np.ndarray) -> np.ndarray:
    """Normalized autocorrelation of a 1-D series via FFT (the emcee
    ``function_1d`` algorithm)."""
    x = np.asarray(x, dtype=np.float64)
    n = _next_pow_two(len(x))
    f = np.fft.fft(x - np.mean(x), n=2 * n)
    acf = np.fft.ifft(f * np.conjugate(f))[: len(x)].real
    if acf[0] == 0:
        return np.ones_like(acf)
    return acf / acf[0]


def integrated_autocorr_time(chain: np.ndarray, c: float = 5.0) -> np.ndarray:
    """Per-parameter integrated autocorrelation time of an ensemble chain
    (emcee's Sokal-windowed estimator, the algorithm behind the reference's
    ``sampler.get_autocorr_time(tol=0)`` calls in
    ``scripts/event_optimize.py:239``).

    ``chain`` is (nsteps, nwalkers, ndim); the ACF is averaged over walkers
    per parameter and summed up to the automatic window
    ``min { m : m >= c * tau(m) }``.
    """
    chain = np.asarray(chain, dtype=np.float64)
    if chain.ndim != 3:
        raise ValueError("chain must be (nsteps, nwalkers, ndim)")
    nsteps, nwalkers, ndim = chain.shape
    taus = np.empty(ndim)
    for k in range(ndim):
        f = np.zeros(nsteps)
        for w in range(nwalkers):
            f += _acf_1d(chain[:, w, k])
        f /= nwalkers
        tau_m = 2.0 * np.cumsum(f) - 1.0
        m = np.arange(nsteps)
        window = np.argmax(m >= c * tau_m) if np.any(m >= c * tau_m) \
            else nsteps - 1
        taus[k] = tau_m[window]
    return taus


def run_sampler_autocorr(sampler, pos, nsteps: int, burnin: int,
                         csteps: int = 100, crit1: int = 10):
    """Run *sampler* until the autocorrelation-time convergence criteria
    hold (reference ``scripts/event_optimize.py:239``): first the chain must
    exceed ``crit1`` autocorrelation times with tau stable to 10% (checked
    every ``csteps``), then stable to 1% (checked every ``csteps/4``), with
    at least 1000 post-burnin steps.  Returns the list of mean-tau
    estimates."""
    autocorr = []
    old_tau = np.inf
    converged1 = converged2 = False
    converge_step = None
    for _ in sampler.sample(pos, iterations=nsteps):
        it = sampler.iteration
        if not converged1:
            if it >= burnin and it % csteps == 0:
                tau = sampler.get_autocorr_time(tol=0, quiet=True)
                if np.any(np.isnan(tau)):
                    continue
                autocorr.append(float(np.mean(tau)))
                converged1 = bool(np.all(tau * crit1 < it)
                                  and np.all(np.abs(old_tau - tau) / tau < 0.1))
                old_tau = tau
                if converged1:
                    log.info(f"10% convergence reached with a mean estimated "
                             f"integrated step: {autocorr[-1]}")
            continue
        if not converged2:
            if it % max(int(csteps / 4), 1) == 0:
                tau = sampler.get_autocorr_time(tol=0, quiet=True)
                if np.any(np.isnan(tau)):
                    continue
                autocorr.append(float(np.mean(tau)))
                converged2 = bool(np.all(tau * crit1 < it)
                                  and np.all(np.abs(old_tau - tau) / tau < 0.01))
                old_tau = tau
                converge_step = it
        if converged2 and (it - burnin) >= 1000:
            log.info(f"Convergence reached at {converge_step}")
            break
    return autocorr


class NpzBackend:
    """Checkpoint/resume backend for :class:`EnsembleSampler` — the
    zero-dependency analogue of the emcee HDF5 backend the reference uses
    for long photon-MCMC runs (reference ``scripts/event_optimize.py:900-910``).

    Stores chain, log-probs, acceptance counters and the exact RNG state, so
    a resumed run continues the Markov chain *bit-identically* to an
    uninterrupted one.
    """

    def __init__(self, path: str):
        # np.savez appends '.npz' to bare names; normalize so save and
        # load always address the same file
        path = str(path)
        self.path = path if path.endswith(".npz") else path + ".npz"

    def exists(self) -> bool:
        import os

        return os.path.exists(self.path)

    def save(self, sampler: "EnsembleSampler") -> None:
        import os
        import pickle

        # atomic write (tmp + rename), same discipline as the grid sweep
        # chunks: a crash mid-save must not corrupt the only checkpoint
        tmp = self.path + ".tmp.npz"
        np.savez(
            tmp,
            chain=np.asarray(sampler._chain),
            lnprob=np.asarray(sampler._lnprob),
            naccepted=sampler.naccepted,
            ntotal=sampler.ntotal,
            nwalkers=sampler.nwalkers,
            a=sampler.a,
            ndim=sampler.ndim if sampler.ndim is not None else -1,
            fingerprint=np.array(sampler.fingerprint or ""),
            rng_state=np.frombuffer(
                pickle.dumps(sampler.rng.bit_generator.state), dtype=np.uint8),
        )
        os.replace(tmp, self.path)

    def load_into(self, sampler: "EnsembleSampler") -> np.ndarray:
        """Restore state; returns the last walker positions to resume from."""
        import pickle

        with np.load(self.path, allow_pickle=False) as d:
            if int(d["nwalkers"]) != sampler.nwalkers:
                raise ValueError(
                    f"backend has {int(d['nwalkers'])} walkers, sampler has "
                    f"{sampler.nwalkers}")
            stored_fp = str(d["fingerprint"]) if "fingerprint" in d else ""
            if sampler.fingerprint and stored_fp \
                    and stored_fp != sampler.fingerprint:
                from pint_tpu.exceptions import CheckpointError

                raise CheckpointError(
                    f"{self.path}: checkpoint belongs to a different run "
                    "(model/TOAs fingerprint mismatch); refusing to "
                    "continue the wrong chain — delete the file to start "
                    "over")
            sampler._chain = list(d["chain"])
            sampler._lnprob = list(d["lnprob"])
            sampler.naccepted = int(d["naccepted"])
            sampler.ntotal = int(d["ntotal"])
            if int(d["ndim"]) >= 0:
                sampler.ndim = int(d["ndim"])
            sampler.rng.bit_generator.state = pickle.loads(
                d["rng_state"].tobytes())
        if not sampler._chain:
            raise ValueError("backend contains no steps")
        return sampler._chain[-1]


class MCMCSampler:
    """Abstract sampler interface (reference ``sampler.py:7``)."""

    def __init__(self):
        self.method = None

    def initialize_sampler(self, lnpostfn, ndim: int):
        raise NotImplementedError

    def get_initial_pos(self, fitkeys, fitvals, fiterrs, errfact, **kw):
        """Gaussian ball around the fit values (reference ``sampler.py:43``)."""
        fitvals = np.asarray(fitvals, dtype=np.float64)
        fiterrs = np.asarray(fiterrs, dtype=np.float64)
        scale = np.where(fiterrs > 0, fiterrs,
                         np.abs(fitvals) * 1e-8 + 1e-12) * errfact
        rng = np.random.default_rng(kw.get("seed"))
        return fitvals + scale * rng.standard_normal((self.nwalkers, len(fitvals)))

    def run_mcmc(self, pos, nsteps):
        raise NotImplementedError


class EnsembleSampler(MCMCSampler):
    """Affine-invariant stretch-move ensemble sampler, batched.

    ``lnpost_batch`` maps an (N, ndim) array of walker positions to (N,)
    log-posteriors — e.g. ``BayesianTiming.lnposterior_batch`` (jit+vmap on
    device).  The two half-ensembles update alternately (the standard
    parallelizable variant of Goodman & Weare 2010), so detailed balance is
    preserved while every posterior evaluation is batched.
    """

    def __init__(self, nwalkers: int, a: float = 2.0,
                 seed: Optional[int] = None, backend=None,
                 checkpoint_every: int = 50, mesh=None, plan=None,
                 retries: int = 2, retry_backoff: float = 0.5):
        super().__init__()
        if nwalkers % 2:
            raise ValueError("nwalkers must be even (half-ensemble updates)")
        self.nwalkers = nwalkers
        # transient device loss during a batched lnposterior evaluation is
        # retried with exponential backoff (runtime guardrail); anything
        # non-device-shaped propagates immediately
        from pint_tpu.runtime.checkpoint import RetryPolicy

        self.retry_policy = RetryPolicy(max_retries=retries,
                                        backoff_base=retry_backoff)
        self.a = a
        self.rng = np.random.default_rng(seed)
        self.method = "stretch"
        self._lnpost_batch: Optional[Callable] = None
        self.ndim = None
        self._chain: List[np.ndarray] = []
        self._lnprob: List[np.ndarray] = []
        self.naccepted = 0
        self.ntotal = 0
        self.backend = (NpzBackend(backend) if isinstance(backend, str)
                        else backend)
        self.checkpoint_every = checkpoint_every
        #: optional run-identity string (see runtime.checkpoint
        #: fingerprint_of); when set, saved into checkpoints and verified
        #: on resume so a checkpoint from a different model/TOAs cannot
        #: silently continue the wrong chain
        self.fingerprint: Optional[str] = None
        # mesh: shard the walker axis of every batched lnposterior call
        # over the first mesh axis — the TPU replacement for the reference's
        # process/MPI walker pools (scripts/event_optimize.py:804-905).
        # Proposal/acceptance bookkeeping stays on host (tiny).  The
        # sharded path hands the batch fn a device array, which the
        # fitters evaluate through a jitted SPMD executable; lnposterior
        # values match the unsharded path to fp precision (~1e-9 rel, the
        # fused-jit envelope measured in tests/test_fused_relaxation.py),
        # and the sharded path itself is deterministic for a given seed.
        self.mesh = mesh
        # plan: the execution-plan layer's routed alternative to a raw
        # mesh ("auto" selects a walker-axis plan from the preflight-
        # certified devices).  A shard_map plan runs the batch fn pure-
        # data-parallel (each device evaluates its walker slice, with
        # the walker buffer donated — it is iteration state rebuilt
        # every proposal); on device loss the elastic supervisor evicts
        # the chip and degrades the plan one rung instead of failing
        # the chain.
        self.plan = plan
        self._shard_map_ok: Optional[bool] = None

    def _resolve_plan(self):
        if isinstance(self.plan, str):
            from pint_tpu.exceptions import UsageError
            from pint_tpu.runtime.plan import select_plan

            if self.plan != "auto":
                raise UsageError(f"plan={self.plan!r}: pass 'auto' or an "
                                 "ExecutionPlan")
            # half-ensemble updates dispatch nwalkers/2 at a time
            self.plan = select_plan("walker",
                                    n_items=max(1, self.nwalkers // 2))
        return self.plan

    def _eval_lnpost(self, pts: np.ndarray) -> np.ndarray:
        """Batched lnposterior with device-loss retry, optionally
        walker-sharded over the mesh/plan.  Under a plan, a classified
        failure that exhausts its retries degrades the mesh one rung
        (elastic supervision) instead of killing the chain; anything
        unclassifiable propagates — re-running it on fewer devices
        would fail identically or worse."""
        from pint_tpu.runtime.checkpoint import with_retries

        def once():
            return with_retries(lambda: self._eval_lnpost_once(pts),
                                self.retry_policy,
                                what="lnposterior batch")

        plan = self._resolve_plan()
        if plan is None or plan.mesh is None:
            return once()
        from pint_tpu.runtime import elastic as _elastic

        def attempt(p):
            if p is not self.plan:
                self.plan = p
                self._shard_map_ok = None  # re-wrap on the new mesh
            return once()

        result, final, self.last_elastic_report = \
            _elastic.run_with_degradation(
                plan, attempt, what="lnposterior batch")
        self.plan = final
        return result

    def _eval_lnpost_once(self, pts: np.ndarray) -> np.ndarray:
        plan = self._resolve_plan()
        mesh = self.mesh if plan is None else plan.mesh
        if mesh is None:
            return np.array(self._lnpost_batch(pts), dtype=np.float64)
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        n = pts.shape[0]
        ndev = int(mesh.devices.size)
        pad = (-n) % ndev
        if pad:
            pts = np.concatenate([pts, np.tile(pts[-1:], (pad, 1))])
        if plan is not None and plan.kind == "shard_map" \
                and self._shard_map_ok is not False:
            # pure data-parallel: each device evaluates its walker
            # slice; no collective can appear.  Non-traceable batch
            # callables (custom Python posteriors) fall back to the
            # sharded-device_put path below, once, remembered.
            try:
                wrapped = plan.shard_map_batch(self._lnpost_batch)
                lp = np.array(wrapped(jnp.asarray(pts)),
                              dtype=np.float64)
                self._shard_map_ok = True
                return lp[:n] if pad else lp
            except (TypeError, ValueError) as e:
                if self._shard_map_ok is None:
                    log.info(f"walker plan: shard_map fallback to sharded "
                             f"dispatch ({type(e).__name__}: {e}); the "
                             "batch callable is not jax-traceable")
                    self._shard_map_ok = False
                else:
                    raise
        sharding = NamedSharding(mesh, P(mesh.axis_names[0]))
        dev_pts = jax.device_put(pts, sharding)
        lp = np.array(self._lnpost_batch(dev_pts), dtype=np.float64)
        return lp[:n] if pad else lp

    def resume(self) -> np.ndarray:
        """Restore chain + RNG state from the backend; returns the walker
        positions to continue from."""
        if self.backend is None:
            raise ValueError("no backend configured")
        pos = self.backend.load_into(self)
        log.info(f"Resumed {len(self._chain)} steps from "
                 f"{self.backend.path}")
        return pos

    def initialize_sampler(self, lnpostfn, ndim: int):
        """``lnpostfn`` may be scalar (point -> float) or batched
        ((N, ndim) -> (N,)); batched callables must expose ``.batched = True``
        or be passed via ``lnpost_batch=``."""
        self.ndim = ndim
        if getattr(lnpostfn, "batched", False):
            self._lnpost_batch = lnpostfn
        else:
            self._lnpost_batch = lambda pts: np.array(
                [lnpostfn(p) for p in np.asarray(pts)])

    def initialize_batched(self, lnpost_batch: Callable, ndim: int):
        self.ndim = ndim
        self._lnpost_batch = lnpost_batch

    def _one_step(self, x: np.ndarray, lp: np.ndarray, step: int):
        """One full ensemble update (both half-ensembles), in place."""
        n, ndim = x.shape
        half = n // 2
        for first in (True, False):
            s = slice(0, half) if first else slice(half, n)
            o = slice(half, n) if first else slice(0, half)
            xs, xo = x[s], x[o]
            # z ~ g(z) propto 1/sqrt(z) on [1/a, a]
            u = self.rng.random(half)
            z = ((self.a - 1.0) * u + 1.0) ** 2 / self.a
            partners = self.rng.integers(0, half, size=half)
            prop = xo[partners] + z[:, None] * (xs - xo[partners])
            lp_prop = self._eval_lnpost(prop)
            lnratio = (ndim - 1) * np.log(z) + lp_prop - lp[s]
            accept = np.log(self.rng.random(half)) < lnratio
            x[s] = np.where(accept[:, None], prop, xs)
            lp_s = lp[s]
            lp_s[accept] = lp_prop[accept]
            lp[s] = lp_s
            self.naccepted += int(accept.sum())
            self.ntotal += half
        self._chain.append(x.copy())
        self._lnprob.append(lp.copy())
        if (self.backend is not None
                and (step + 1) % self.checkpoint_every == 0):
            self.backend.save(self)
            from pint_tpu import config as _config

            if _config._telemetry_mode != "off":
                from pint_tpu import telemetry as _tel

                _tel.event("mcmc.checkpoint_save",
                           steps=len(self._chain), path=self.backend.path)
                _tel.metrics.counter(
                    "pint_tpu_mcmc_checkpoint_saves_total",
                    "MCMC chain checkpoint writes").inc()
            # each save rewrites the whole chain; grow the interval so
            # cumulative checkpoint I/O stays ~linear in chain length
            if len(self._chain) >= 20 * self.checkpoint_every:
                self.checkpoint_every *= 2

    def run_mcmc(self, pos, nsteps: int, progress: bool = False) -> np.ndarray:
        """Advance the ensemble *nsteps*; returns the final position."""
        x = np.array(pos, dtype=np.float64)
        for x in self.sample(pos, nsteps):
            pass
        return x

    def sample(self, pos, iterations: int, progress: bool = False):
        """Generator yielding the current position after every step
        (emcee-compatible incremental API; consumed by
        :func:`run_sampler_autocorr`).  The final backend checkpoint runs
        even when the consumer breaks out early (convergence), so a resume
        always continues the exact chain that was reported."""
        x = np.array(pos, dtype=np.float64)
        if x.shape[0] != self.nwalkers:
            raise ValueError(
                f"pos has {x.shape[0]} walkers, expected {self.nwalkers}")
        lp = self._eval_lnpost(x)
        steps_done = 0
        try:
            for step in range(iterations):
                self._one_step(x, lp, step)
                steps_done += 1
                yield x
        finally:
            if self.backend is not None:
                self.backend.save(self)
            from pint_tpu import config as _config

            if _config._telemetry_mode != "off" and steps_done:
                from pint_tpu.telemetry import metrics as _metrics

                _metrics.counter("pint_tpu_mcmc_steps_total",
                                 "ensemble MCMC steps advanced").inc(
                    steps_done)

    @property
    def iteration(self) -> int:
        """Number of steps accumulated in the chain (emcee-compatible)."""
        return len(self._chain)

    def get_autocorr_time(self, tol: float = 50.0, quiet: bool = False,
                          discard: int = 0, c: float = 5.0) -> np.ndarray:
        """Per-parameter integrated autocorrelation time (emcee-compatible
        semantics: with ``tol>0`` a chain shorter than ``tol*tau`` raises,
        or warns with ``quiet=True``)."""
        chain = self.get_chain(discard=discard)
        if len(chain) < 2:
            return np.full(self.ndim or 1, np.nan)
        tau = integrated_autocorr_time(chain, c=c)
        if tol > 0 and np.any(tau * tol > len(chain)):
            msg = (f"The chain is shorter than {tol} times the integrated "
                   f"autocorrelation time for {int(np.sum(tau * tol > len(chain)))} "
                   f"parameter(s); tau estimates are unreliable")
            if not quiet:
                raise RuntimeError(msg)
            log.warning(msg)
        return tau

    @property
    def acceptance_fraction(self) -> float:
        return self.naccepted / max(self.ntotal, 1)

    def get_chain(self, flat: bool = False, discard: int = 0,
                  thin: int = 1) -> np.ndarray:
        """(nsteps, nwalkers, ndim) chain (emcee-compatible layout)."""
        c = np.array(self._chain)[discard::thin]
        return c.reshape(-1, self.ndim) if flat else c

    def get_log_prob(self, flat: bool = False, discard: int = 0,
                     thin: int = 1) -> np.ndarray:
        lp = np.array(self._lnprob)[discard::thin]
        return lp.reshape(-1) if flat else lp

    def chains_to_dict(self, names: List[str]) -> Dict[str, np.ndarray]:
        chain = self.get_chain()
        return {name: chain[:, :, i] for i, name in enumerate(names)}

    def reset(self):
        self._chain, self._lnprob = [], []
        self.naccepted = self.ntotal = 0


class EmceeSampler(MCMCSampler):
    """Reference-parity wrapper over emcee (optional dependency;
    reference ``sampler.py:60``)."""

    def __init__(self, nwalkers: int):
        super().__init__()
        try:
            import emcee  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "emcee is not installed; use pint_tpu.sampler.EnsembleSampler "
                "(jax-native, batched) instead") from e
        self.nwalkers = nwalkers
        self.sampler = None
        self.method = "emcee"

    def is_initialized(self) -> bool:
        return self.sampler is not None

    def initialize_sampler(self, lnpostfn, ndim: int):
        import emcee

        self.ndim = ndim
        self.sampler = emcee.EnsembleSampler(self.nwalkers, ndim, lnpostfn)

    def run_mcmc(self, pos, nsteps):
        return self.sampler.run_mcmc(pos, nsteps)

    def sample(self, pos, iterations, progress: bool = False):
        """Incremental sampling passthrough so
        :func:`run_sampler_autocorr` drives emcee the same way it drives
        the jax-native ensemble."""
        return self.sampler.sample(pos, iterations=iterations,
                                   progress=progress)

    @property
    def iteration(self) -> int:
        return self.sampler.iteration

    def get_autocorr_time(self, **kw):
        return self.sampler.get_autocorr_time(**kw)

    def get_chain(self, **kw):
        return self.sampler.get_chain(**kw)

    def get_log_prob(self, **kw):
        return self.sampler.get_log_prob(**kw)

    @property
    def acceptance_fraction(self) -> float:
        return float(np.mean(self.sampler.acceptance_fraction))

    def chains_to_dict(self, names):
        chains = [self.sampler.chain[:, :, ii].T for ii in range(len(names))]
        return dict(zip(names, chains))
