"""Conversions between binary-model parameterizations.

Counterpart of reference ``binaryconvert.py`` (``convert_binary``): build a
new TimingModel with a different BINARY component, transforming the
parameters (ELL1 <-> DD families, SINI <-> SHAPMAX, M2/SINI <-> H3/STIG(M),
ELL1 <-> ELL1k, DDGR -> DD post-Keplerians).  First-order uncertainty
propagation is done with a numerical Jacobian of each transform (the
reference uses the ``uncertainties`` package for the same effect).
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Tuple

import numpy as np

from pint_tpu.derived_quantities import TSUN_S, dr, dth, gamma, omdot, pbdot, sini
from pint_tpu.logging import log

__all__ = ["convert_binary"]

SECPERDAY = 86400.0

_ELL1_FAMILY = {"ELL1", "ELL1H", "ELL1k"}
_DD_FAMILY = {"DD", "DDS", "DDH", "DDGR", "DDK", "BT"}


def _propagate(transform, values: np.ndarray, errors: np.ndarray,
               rel_step: float = 1e-7):
    """y = transform(x) with sigma_y from the numerical Jacobian."""
    values = np.asarray(values, dtype=np.float64)
    y0 = np.asarray(transform(values), dtype=np.float64)
    J = np.zeros((len(y0), len(values)))
    for j, v in enumerate(values):
        h = abs(v) * rel_step if v != 0 else 1e-12
        xp = values.copy(); xp[j] += h
        xm = values.copy(); xm[j] -= h
        J[:, j] = (np.asarray(transform(xp)) - np.asarray(transform(xm))) / (2 * h)
    var = J @ np.diag(np.asarray(errors, dtype=np.float64) ** 2) @ J.T
    return y0, np.sqrt(np.diag(var))


def _getv(model, name, default=0.0):
    p = getattr(model, name, None)
    if p is None or p.value is None:
        return default
    return float(p.value)


def _gete(model, name):
    p = getattr(model, name, None)
    if p is None or p.uncertainty is None:
        return 0.0
    return float(p.uncertainty)


def _pb_days(model) -> float:
    pb = _getv(model, "PB", 0.0)
    if pb:
        return pb
    fb0 = _getv(model, "FB0", 0.0)
    return 1.0 / (fb0 * SECPERDAY) if fb0 else 0.0


# -- elementary transforms ---------------------------------------------------

def _eps_to_ecc_om_t0(eps1, eps2, tasc, pb_d):
    ecc = np.hypot(eps1, eps2)
    om = np.arctan2(eps1, eps2)  # rad
    t0 = tasc + (om / (2 * np.pi)) * pb_d
    return ecc, np.degrees(om) % 360.0, t0


def _ecc_om_t0_to_eps(ecc, om_deg, t0, pb_d):
    om = np.radians(om_deg)
    eps1 = ecc * np.sin(om)
    eps2 = ecc * np.cos(om)
    tasc = t0 - (om / (2 * np.pi)) * pb_d
    return eps1, eps2, tasc


def _m2sini_to_h3stig(m2_msun, sini_):
    cbar = np.sqrt(1.0 - sini_**2)
    stig = sini_ / (1.0 + cbar)
    h3 = TSUN_S * m2_msun * stig**3
    return h3, stig


def _h3stig_to_m2sini(h3, stig):
    m2 = h3 / (TSUN_S * stig**3)
    sini_ = 2.0 * stig / (1.0 + stig**2)
    return m2, sini_


def _sini_to_shapmax(sini_):
    return -np.log(1.0 - sini_)


def _shapmax_to_sini(shapmax):
    return 1.0 - np.exp(-shapmax)


# -- driver ------------------------------------------------------------------

def convert_binary(model, output: str, NHARMS: int = 7,
                   useSTIGMA: bool = True, KOM: float = 0.0, **kw):
    """Return a new TimingModel with the binary component converted to
    *output* (reference ``binaryconvert.py convert_binary``).

    ``NHARMS``/``useSTIGMA`` steer the ELL1H orthometric parameterization
    (reference defaults to H3/H4; here STIGMA is the default since the
    exact Freire & Wex H3/STIGMA form needs no harmonic truncation);
    ``KOM`` [deg] seeds the ascending-node longitude when converting to
    DDK, where KIN is derived from SINI and the sign is the user's to
    check (reference ``binaryconvert.py:1050``)."""
    from pint_tpu.models.binary.components import PulsarBinary
    from pint_tpu.models.timing_model import Component

    output = output.upper().replace("ELL1K", "ELL1k")
    binary_comp = None
    for c in model.components.values():
        if isinstance(c, PulsarBinary):
            binary_comp = c
            break
    if binary_comp is None:
        raise ValueError("Model has no binary component to convert")
    current = binary_comp.binary_model_name
    if current == output:
        return copy.deepcopy(model)
    cls_name = f"Binary{output}"
    if cls_name not in Component.component_types:
        raise ValueError(f"Unknown binary model {output!r}")

    new_model = copy.deepcopy(model)
    new_model.remove_component(type(binary_comp).__name__)
    new_comp = Component.component_types[cls_name]()
    new_model.add_component(new_comp, validate=False)
    new_model.BINARY.value = output

    # copy every parameter both models share
    for pname in binary_comp.params:
        if pname in new_comp.params:
            src = binary_comp._params_dict[pname]
            dst = new_comp._params_dict[pname]
            dst.value = src.value
            dst.uncertainty = src.uncertainty
            dst.frozen = src.frozen

    pb_d = _pb_days(model)

    cur_ell1 = current in _ELL1_FAMILY
    out_ell1 = output in _ELL1_FAMILY

    if cur_ell1 and not out_ell1:
        # EPS1/EPS2/TASC -> ECC/OM/T0 (reference _from_ELL1)
        x = [_getv(model, "EPS1"), _getv(model, "EPS2"), _getv(model, "TASC")]
        e = [_gete(model, "EPS1"), _gete(model, "EPS2"), _gete(model, "TASC")]
        (vals, errs) = _propagate(
            lambda v: _eps_to_ecc_om_t0(v[0], v[1], v[2], pb_d), x, e)
        for nm, v, s in zip(("ECC", "OM", "T0"), vals, errs):
            par = new_comp._params_dict[nm]
            par.value = float(v)
            par.uncertainty = float(s) or None
            par.frozen = getattr(model, "EPS1").frozen
    elif out_ell1 and not cur_ell1:
        # ECC/OM/T0 -> EPS1/EPS2/TASC (reference _to_ELL1)
        ecc = _getv(model, "ECC")
        if ecc > 0.01:
            log.warning(f"ECC={ecc}: the ELL1 small-eccentricity expansion "
                        "is inaccurate above ~0.01")
        x = [ecc, _getv(model, "OM"), _getv(model, "T0")]
        e = [_gete(model, "ECC"), _gete(model, "OM"), _gete(model, "T0")]
        (vals, errs) = _propagate(
            lambda v: _ecc_om_t0_to_eps(v[0], v[1], v[2], pb_d), x, e)
        for nm, v, s in zip(("EPS1", "EPS2", "TASC"), vals, errs):
            par = new_comp._params_dict[nm]
            par.value = float(v)
            par.uncertainty = float(s) or None
            par.frozen = getattr(model, "ECC").frozen

    # Shapiro parameterizations.  The DDS-*target* block runs after the
    # DDK/orthometric source blocks below (mirroring the DDK-target block)
    # so KIN/H3-source models have their derived SINI on new_comp first.
    if current == "DDS" and output != "DDS":
        sh = _getv(model, "SHAPMAX")
        if sh and "SINI" in new_comp.params:
            (v,), (sg,) = _propagate(lambda x: [_shapmax_to_sini(x[0])],
                                     [sh], [_gete(model, "SHAPMAX")])
            new_comp.SINI.value = float(v)
            new_comp.SINI.uncertainty = float(sg) or None
            new_comp.SINI.frozen = model.SHAPMAX.frozen

    # DDK source: KIN -> SINI (reference ``binaryconvert.py:967``); the
    # DDK-*target* block runs after the orthometric one below, so DDS/DDH/
    # ELL1H sources have their derived SINI on new_comp by then
    if current == "DDK" and output != "DDK":
        kin = _getv(model, "KIN")
        if kin and "SINI" in new_comp.params:
            (v,), (sg,) = _propagate(
                lambda x: [np.sin(np.radians(x[0]))],
                [kin], [_gete(model, "KIN")])
            new_comp.SINI.value = float(v)
            new_comp.SINI.uncertainty = float(sg) or None
            new_comp.SINI.frozen = model.KIN.frozen

    ortho_out = output in ("DDH", "ELL1H")
    ortho_cur = current in ("DDH", "ELL1H")
    if ortho_out and not ortho_cur:
        # read M2/SINI from the NEW component: for DDS/DDK sources the
        # source model has no SINI value (it lives in SHAPMAX/KIN) — the
        # blocks above already derived it, with uncertainty, onto new_comp
        def _newv(nm):
            if nm not in new_comp.params:
                return 0.0, 0.0
            p = new_comp._params_dict[nm]
            return float(p.value or 0.0), float(p.uncertainty or 0.0)

        m2, m2_e = _newv("M2")
        s, s_e = _newv("SINI")
        if m2 and s:
            stig_name = "STIGMA" if "STIGMA" in new_comp.params else "STIG"

            def _h3_stig_h4(x):
                h3_, stig_ = _m2sini_to_h3stig(x[0], x[1])
                return [h3_, stig_, h3_ * stig_]

            vals, errs = _propagate(_h3_stig_h4, [m2, s], [m2_e, s_e])
            new_comp._params_dict["H3"].value = float(vals[0])
            new_comp._params_dict["H3"].uncertainty = float(errs[0]) or None
            if useSTIGMA or stig_name == "STIG" \
                    or "H4" not in new_comp.params:
                new_comp._params_dict[stig_name].value = float(vals[1])
                new_comp._params_dict[stig_name].uncertainty = \
                    float(errs[1]) or None
            else:
                # H3/H4 truncated-harmonic form: H4 = H3 * stigma
                new_comp._params_dict["H4"].value = float(vals[2])
                new_comp._params_dict["H4"].uncertainty = \
                    float(errs[2]) or None
            if "NHARMS" in new_comp.params:
                new_comp._params_dict["NHARMS"].value = int(NHARMS)
            for nm in ("M2", "SINI"):
                if nm in new_comp.params:
                    new_comp._params_dict[nm].value = None
    elif ortho_cur and not ortho_out:
        stig_name = "STIGMA" if "STIGMA" in binary_comp.params else "STIG"
        h3, stig = _getv(model, "H3"), _getv(model, stig_name)
        if h3 and stig and "M2" in new_comp.params:
            vals, errs = _propagate(
                lambda x: _h3stig_to_m2sini(x[0], x[1]),
                [h3, stig], [_gete(model, "H3"), _gete(model, stig_name)])
            new_comp.M2.value = float(vals[0])
            new_comp.M2.uncertainty = float(errs[0]) or None
            new_comp.M2.frozen = model.H3.frozen
            new_comp.SINI.value = float(vals[1])
            new_comp.SINI.uncertainty = float(errs[1]) or None
            new_comp.SINI.frozen = getattr(model, stig_name).frozen

    # DDS target: SINI -> SHAPMAX.  Runs after every SINI-producing block
    # so DDK/DDH/ELL1H sources (whose SINI was derived onto new_comp above)
    # keep their Shapiro shape instead of silently dropping it.
    if output == "DDS" and current != "DDS":
        has_src = getattr(model, "SINI", None) is not None \
            and model.SINI.value is not None
        s = _getv(model, "SINI") or \
            (float(new_comp.SINI.value or 0.0)
             if "SINI" in new_comp.params else 0.0)
        s_e = _gete(model, "SINI") or \
            (float(new_comp.SINI.uncertainty or 0.0)
             if "SINI" in new_comp.params else 0.0)
        if s:
            (v,), (sg,) = _propagate(lambda x: [_sini_to_shapmax(x[0])],
                                     [s], [s_e])
            new_comp.SHAPMAX.value = float(v)
            new_comp.SHAPMAX.uncertainty = float(sg) or None
            new_comp.SHAPMAX.frozen = model.SINI.frozen if has_src \
                else new_comp.SINI.frozen
        if "SINI" in new_comp.params:
            new_comp.SINI.value = None  # DDS derives SINI from SHAPMAX

    # DDK target: SINI -> KIN, seed KOM (reference ``binaryconvert.py:1050``).
    # Runs after every SINI-producing block so DDS/DDH/ELL1H sources work.
    if output == "DDK" and current != "DDK":
        s = _getv(model, "SINI") or \
            (float(new_comp.SINI.value or 0.0)
             if "SINI" in new_comp.params else 0.0)
        s_e = _gete(model, "SINI") or \
            (float(new_comp.SINI.uncertainty or 0.0)
             if "SINI" in new_comp.params else 0.0)
        if s:
            (v,), (sg,) = _propagate(
                lambda x: [np.degrees(np.arcsin(x[0]))], [s], [s_e])
            new_comp.KIN.value = float(v)
            new_comp.KIN.uncertainty = float(sg) or None
            src_sini = getattr(model, "SINI", None)
            if src_sini is not None and src_sini.value is not None:
                new_comp.KIN.frozen = src_sini.frozen
            elif "SINI" in new_comp.params:
                # SINI was derived onto new_comp (DDS/DDH/ELL1H source):
                # a free source inclination must stay free as KIN
                new_comp.KIN.frozen = new_comp.SINI.frozen
            log.warning(f"Setting KIN={new_comp.KIN.value} deg from SINI: "
                        "check that the sign is correct")
        new_comp.KOM.value = float(KOM)
        if "SINI" in new_comp.params:
            new_comp.SINI.value = None  # DDK derives SINI from KIN

    # ELL1k: OMDOT/LNEDOT <-> EPS1DOT/EPS2DOT
    if output == "ELL1k" and current in ("ELL1", "ELL1H"):
        e1, e2 = _getv(new_model, "EPS1"), _getv(new_model, "EPS2")
        e1d, e2d = _getv(model, "EPS1DOT"), _getv(model, "EPS2DOT")
        ecc2 = e1**2 + e2**2
        if ecc2 > 0:
            omdot_rad_s = (e2 * e1d - e1 * e2d) / ecc2
            lnedot_s = (e1 * e1d + e2 * e2d) / ecc2
            new_comp.OMDOT.value = np.degrees(omdot_rad_s) * 365.25 * SECPERDAY
            new_comp.LNEDOT.value = lnedot_s * 365.25 * SECPERDAY  # 1/s -> 1/yr
    elif current == "ELL1k" and output in ("ELL1", "ELL1H"):
        e1, e2 = _getv(model, "EPS1"), _getv(model, "EPS2")
        omd = np.radians(_getv(model, "OMDOT")) / (365.25 * SECPERDAY)
        lnedot_s = _getv(model, "LNEDOT") / (365.25 * SECPERDAY)  # 1/yr -> 1/s
        new_comp.EPS1DOT.value = lnedot_s * e1 + omd * e2
        new_comp.EPS2DOT.value = lnedot_s * e2 - omd * e1

    # DDGR -> explicit post-Keplerians (reference _DDGR_to_PK)
    if current == "DDGR" and output != "DDGR":
        mtot, m2 = _getv(model, "MTOT"), _getv(model, "M2")
        if mtot and m2:
            mp = mtot - m2
            ecc = _getv(new_model, "ECC") or np.hypot(
                _getv(new_model, "EPS1"), _getv(new_model, "EPS2"))
            x = _getv(model, "A1")
            new_comp._params_dict["OMDOT"].value = omdot(mp, m2, pb_d, ecc)
            new_comp._params_dict["GAMMA"].value = gamma(mp, m2, pb_d, ecc)
            new_comp._params_dict["PBDOT"].value = pbdot(mp, m2, pb_d, ecc)
            if "SINI" in new_comp.params:
                new_comp._params_dict["SINI"].value = min(sini(mp, m2, pb_d, x), 1.0)
                new_comp._params_dict["M2"].value = m2
            if "DR" in new_comp.params:
                new_comp._params_dict["DR"].value = dr(mp, m2, pb_d)
                new_comp._params_dict["DTH"].value = dth(mp, m2, pb_d)

    new_model.setup()
    new_model.validate()
    return new_model
