"""Native C++ host kernels: exact double-double arithmetic + decimal
string -> dd conversion, compiled on first use and loaded through ctypes.

This is the TPU-native replacement for the reference's numpy-longdouble
dependence (SURVEY §2b row 1): the dd pair carries ~106 mantissa bits (vs
64 for x87 extended) and works on every platform, including arm64 where
longdouble == double.  Falls back transparently to the pure-Python dd path
when no C++ toolchain is available (``available()`` reports which).

Build: ``g++/cc -O2 -fPIC -shared`` into ``_build/pint_native_<hash>.so``,
keyed on a SHA-256 of the source so a stale or wrong-architecture cached
object can never be loaded (the build dir is gitignored; nothing compiled
is committed).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from typing import List, Optional, Tuple

import numpy as np

from pint_tpu.logging import log

__all__ = ["available", "dd_add_batch", "dd_mul_batch", "dd_div_batch",
           "dd_horner_batch", "str2dd_batch", "parse_double_batch"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "_src", "pint_native.cpp")
_BUILD_DIR = os.path.join(_HERE, "_build")


def _so_path() -> str:
    """Cache path keyed on source hash: rebuilds exactly when source changes."""
    with open(_SRC, "rb") as f:
        h = hashlib.sha256(f.read()).hexdigest()[:12]
    return os.path.join(_BUILD_DIR, f"pint_native_{h}.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False

_D = np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS")
_I64 = np.ctypeslib.ndpointer(dtype=np.int64, flags="C_CONTIGUOUS")


def _build(so: str) -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    for cc in ("g++", "c++", "clang++"):
        try:
            r = subprocess.run(
                [cc, "-O2", "-fPIC", "-shared", "-std=c++14", "-o", so, _SRC],
                capture_output=True, text=True, timeout=120)
        except (FileNotFoundError, subprocess.TimeoutExpired):
            continue
        if r.returncode == 0:
            for old in os.listdir(_BUILD_DIR):  # drop superseded objects
                if (old.startswith("pint_native") and old.endswith(".so")
                        and os.path.join(_BUILD_DIR, old) != so):
                    try:
                        os.unlink(os.path.join(_BUILD_DIR, old))
                    except OSError:
                        pass
            return True
        log.warning(f"native build with {cc} failed: {r.stderr[:500]}")
    return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    try:
        so = _so_path()
        if not os.path.exists(so) and not _build(so):
            log.info("no C++ toolchain: using the pure-Python dd path")
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            # corrupt or wrong-architecture cached object (e.g. a _build dir
            # shared across machines): drop it and rebuild once
            try:
                os.unlink(so)
            except OSError:
                pass
            if not _build(so):
                log.info("no C++ toolchain: using the pure-Python dd path")
                return None
            lib = ctypes.CDLL(so)
    except OSError as e:
        log.warning(f"could not load native kernels: {e}")
        return None
    n = ctypes.c_int64
    for name in ("dd_add_batch", "dd_mul_batch", "dd_div_batch"):
        fn = getattr(lib, name)
        fn.argtypes = [_D, _D, _D, _D, _D, _D, n]
        fn.restype = None
    lib.dd_horner_batch.argtypes = [_D, _D, n, _D, _D, _D, _D, n]
    lib.dd_horner_batch.restype = None
    lib.str2dd_batch.argtypes = [ctypes.c_char_p, _I64, n, _D, _D]
    lib.str2dd_batch.restype = ctypes.c_int
    lib.parse_double_batch.argtypes = [ctypes.c_char_p, _I64, n, _D]
    lib.parse_double_batch.restype = ctypes.c_int
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def _require() -> ctypes.CDLL:
    lib = _load()
    if lib is None:
        raise RuntimeError(
            "native dd kernels unavailable — no C++ toolchain could build "
            "pint_native.cpp; call pint_tpu.native.available() first and "
            "fall back to the pure-Python dd path (pint_tpu.dd)")
    return lib


def _pair(x):
    hi = np.ascontiguousarray(x[0], dtype=np.float64)
    lo = np.ascontiguousarray(x[1], dtype=np.float64)
    return hi, lo


def _binop(name, a, b):
    lib = _require()
    ah, al = _pair(a)
    bh, bl = _pair(b)
    ah, bh = np.broadcast_arrays(ah, bh)
    al, bl = np.broadcast_arrays(al, bl)
    ah = np.ascontiguousarray(ah); al = np.ascontiguousarray(al)
    bh = np.ascontiguousarray(bh); bl = np.ascontiguousarray(bl)
    oh = np.empty_like(ah)
    ol = np.empty_like(al)
    getattr(lib, name)(ah.ravel(), al.ravel(), bh.ravel(), bl.ravel(),
                       oh.ravel(), ol.ravel(), oh.size)
    return oh, ol


def dd_add_batch(a, b):
    """(hi, lo) + (hi, lo) elementwise in exact dd arithmetic."""
    return _binop("dd_add_batch", a, b)


def dd_mul_batch(a, b):
    return _binop("dd_mul_batch", a, b)


def dd_div_batch(a, b):
    return _binop("dd_div_batch", a, b)


def dd_horner_batch(coeffs: List[Tuple[float, float]], x):
    """sum_k c_k x^k with dd coefficients and dd x (batched over x)."""
    lib = _require()
    ch = np.ascontiguousarray([c[0] for c in coeffs], dtype=np.float64)
    cl = np.ascontiguousarray([c[1] for c in coeffs], dtype=np.float64)
    xh, xl = _pair(x)
    xh = np.ascontiguousarray(xh); xl = np.ascontiguousarray(xl)
    oh = np.empty_like(xh)
    ol = np.empty_like(xl)
    lib.dd_horner_batch(ch, cl, len(coeffs), xh.ravel(), xl.ravel(),
                        oh.ravel(), ol.ravel(), oh.size)
    return oh, ol


def _pack_strings(strings: List[str]):
    enc = [s.encode() for s in strings]
    offsets = np.zeros(len(enc), dtype=np.int64)
    pos = 0
    parts = []
    for i, b in enumerate(enc):
        offsets[i] = pos
        parts.append(b + b"\0")
        pos += len(b) + 1
    return b"".join(parts), offsets


def str2dd_batch(strings: List[str]):
    """Decimal strings -> (hi, lo) double-double, exact to 2^-106
    (the reference's ``str_to_mjds``, ``pulsar_mjd.py:488``, without
    longdouble).  Invalid entries become NaN."""
    lib = _require()
    buf, offsets = _pack_strings(strings)
    n = len(strings)
    oh = np.empty(n, dtype=np.float64)
    ol = np.empty(n, dtype=np.float64)
    bad = lib.str2dd_batch(buf, offsets, n, oh, ol)
    if bad:
        log.warning(f"str2dd_batch: {bad} unparseable values -> NaN")
    return oh, ol


def parse_double_batch(strings: List[str]) -> np.ndarray:
    """Fast batch float parsing (fortran D exponents tolerated)."""
    lib = _require()
    buf, offsets = _pack_strings(strings)
    out = np.empty(len(strings), dtype=np.float64)
    bad = lib.parse_double_batch(buf, offsets, len(strings), out)
    if bad:
        log.warning(f"parse_double_batch: {bad} unparseable values -> NaN")
    return out
