// Native host kernels for pint_tpu: exact double-double arithmetic and
// decimal-string -> double-double conversion.
//
// These replace the reference's dependence on numpy longdouble (x87 80-bit,
// absent on arm64) for the host-side precision path (reference
// pulsar_mjd.py:488 str_to_mjds, :586 two_sum/two_product, utils.py:411
// taylor_horner).  The double-double pair (hi, lo) carries ~106 bits of
// mantissa — more than 80-bit extended — and the kernels below are
// branch-free batch loops over contiguous arrays, called through ctypes.
//
// Error-free transforms follow Dekker (1971) / Knuth; products use FMA.

#include <cmath>
#include <cstdint>
#include <cstring>

extern "C" {

struct dd {
    double hi, lo;
};

static inline dd two_sum(double a, double b) {
    double s = a + b;
    double bb = s - a;
    double err = (a - (s - bb)) + (b - bb);
    return {s, err};
}

static inline dd quick_two_sum(double a, double b) {
    double s = a + b;
    return {s, b - (s - a)};
}

static inline dd two_prod(double a, double b) {
    double p = a * b;
    return {p, std::fma(a, b, -p)};
}

static inline dd dd_add(dd x, dd y) {
    dd s = two_sum(x.hi, y.hi);
    dd t = two_sum(x.lo, y.lo);
    double lo = s.lo + t.hi;
    dd r = quick_two_sum(s.hi, lo);
    lo = r.lo + t.lo;
    return quick_two_sum(r.hi, lo);
}

static inline dd dd_mul(dd x, dd y) {
    dd p = two_prod(x.hi, y.hi);
    double lo = p.lo + x.hi * y.lo + x.lo * y.hi;
    return quick_two_sum(p.hi, lo);
}

static inline dd dd_div(dd x, dd y) {
    double q1 = x.hi / y.hi;
    dd r = dd_add(x, {-q1 * y.hi, -std::fma(q1, y.hi, -q1 * y.hi)});
    r = dd_add(r, {-q1 * y.lo, 0.0});
    double q2 = r.hi / y.hi;
    dd r2 = dd_add(r, {-q2 * y.hi, -std::fma(q2, y.hi, -q2 * y.hi)});
    r2 = dd_add(r2, {-q2 * y.lo, 0.0});
    double q3 = r2.hi / y.hi;
    dd q = quick_two_sum(q1, q2);
    return dd_add(q, {q3, 0.0});
}

// ---------------------------------------------------------------------------
// batched dd arithmetic
// ---------------------------------------------------------------------------

void dd_add_batch(const double* ah, const double* al, const double* bh,
                  const double* bl, double* oh, double* ol, int64_t n) {
    for (int64_t i = 0; i < n; i++) {
        dd r = dd_add({ah[i], al[i]}, {bh[i], bl[i]});
        oh[i] = r.hi;
        ol[i] = r.lo;
    }
}

void dd_mul_batch(const double* ah, const double* al, const double* bh,
                  const double* bl, double* oh, double* ol, int64_t n) {
    for (int64_t i = 0; i < n; i++) {
        dd r = dd_mul({ah[i], al[i]}, {bh[i], bl[i]});
        oh[i] = r.hi;
        ol[i] = r.lo;
    }
}

void dd_div_batch(const double* ah, const double* al, const double* bh,
                  const double* bl, double* oh, double* ol, int64_t n) {
    for (int64_t i = 0; i < n; i++) {
        dd r = dd_div({ah[i], al[i]}, {bh[i], bl[i]});
        oh[i] = r.hi;
        ol[i] = r.lo;
    }
}

// out = sum_k c_k x^k / k!  when factorial != 0 (taylor series), or plain
// Horner when factorial == 0; coefficients are dd pairs.
void dd_horner_batch(const double* ch, const double* cl, int64_t nc,
                     const double* xh, const double* xl, double* oh,
                     double* ol, int64_t n) {
    for (int64_t i = 0; i < n; i++) {
        dd x = {xh[i], xl[i]};
        dd acc = {nc > 0 ? ch[nc - 1] : 0.0, nc > 0 ? cl[nc - 1] : 0.0};
        for (int64_t k = nc - 2; k >= 0; k--) {
            acc = dd_add(dd_mul(acc, x), {ch[k], cl[k]});
        }
        oh[i] = acc.hi;
        ol[i] = acc.lo;
    }
}

// ---------------------------------------------------------------------------
// decimal string -> dd (exact to 2^-106)
// ---------------------------------------------------------------------------

static dd pow10_dd(int n) {
    // 10^n as a dd, exact products up to the dd precision
    dd r = {1.0, 0.0};
    dd ten = {10.0, 0.0};
    for (int i = 0; i < n; i++) r = dd_mul(r, ten);
    return r;
}

// Parse one "[+-]IIII[.FFFF][eE[+-]X]" decimal into a dd.  Returns 0 on
// success.  Digits are accumulated in 15-digit chunks (exact in double).
static int str2dd_one(const char* s, dd* out) {
    while (*s == ' ' || *s == '\t') s++;
    int sign = 1;
    if (*s == '+') s++;
    else if (*s == '-') { sign = -1; s++; }
    dd acc = {0.0, 0.0};
    int frac_digits = 0, seen_point = 0, seen_digit = 0;
    int64_t chunk = 0;
    int chunk_len = 0;
    for (; *s; s++) {
        char c = *s;
        if (c >= '0' && c <= '9') {
            seen_digit = 1;
            chunk = chunk * 10 + (c - '0');
            chunk_len++;
            if (seen_point) frac_digits++;
            // 15-digit chunks: 10^15 < 2^53, so (double)chunk is exact
            if (chunk_len == 15) {
                acc = dd_add(dd_mul(acc, pow10_dd(15)), {(double)chunk, 0.0});
                chunk = 0;
                chunk_len = 0;
            }
        } else if ((c == '.') && !seen_point) {
            seen_point = 1;
        } else if (c == 'e' || c == 'E' || c == 'd' || c == 'D') {
            break;
        } else if (c == ' ' || c == '\t' || c == '\r' || c == '\n') {
            break;
        } else {
            return 1;
        }
    }
    if (!seen_digit) return 1;
    if (chunk_len > 0) {
        acc = dd_add(dd_mul(acc, pow10_dd(chunk_len)), {(double)chunk, 0.0});
    }
    int expo = 0;
    if (*s == 'e' || *s == 'E' || *s == 'd' || *s == 'D') {
        s++;
        int esign = 1;
        if (*s == '+') s++;
        else if (*s == '-') { esign = -1; s++; }
        int ev = 0;
        for (; *s >= '0' && *s <= '9'; s++) ev = ev * 10 + (*s - '0');
        expo = esign * ev;
    }
    int net = expo - frac_digits;
    dd r = acc;
    if (net > 0) r = dd_mul(acc, pow10_dd(net));
    else if (net < 0) r = dd_div(acc, pow10_dd(-net));
    if (sign < 0) { r.hi = -r.hi; r.lo = -r.lo; }
    *out = r;
    return 0;
}

// buf: n zero-terminated strings back to back; offsets[i] = start of i-th.
int str2dd_batch(const char* buf, const int64_t* offsets, int64_t n,
                 double* oh, double* ol) {
    int bad = 0;
    for (int64_t i = 0; i < n; i++) {
        dd r;
        if (str2dd_one(buf + offsets[i], &r)) {
            r = {0.0 / 0.0, 0.0};
            bad++;
        }
        oh[i] = r.hi;
        ol[i] = r.lo;
    }
    return bad;
}

// ---------------------------------------------------------------------------
// fast tim-file numeric column scan: for pre-split whitespace tokens this
// parses plain doubles (fortran D-exponent tolerated)
// ---------------------------------------------------------------------------

int parse_double_batch(const char* buf, const int64_t* offsets, int64_t n,
                       double* out) {
    int bad = 0;
    for (int64_t i = 0; i < n; i++) {
        dd r;
        if (str2dd_one(buf + offsets[i], &r)) {
            out[i] = 0.0 / 0.0;
            bad++;
        } else {
            out[i] = r.hi;
        }
    }
    return bad;
}

}  // extern "C"
