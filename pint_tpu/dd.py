"""Double-double ("two-float") arithmetic on JAX arrays.

This is the TPU-native replacement for the reference's reliance on numpy
``longdouble`` (x87 80-bit) time arithmetic (reference ``pulsar_mjd.py``
throughout, esp. the error-free transforms at ``pulsar_mjd.py:586,609,638``).
A value is represented as an unevaluated sum ``hi + lo`` of two float64s with
``|lo| <= ulp(hi)/2``, giving ~32 significant digits — enough for absolute
pulse phase (~1e12 cycles) to ~1e-12 cycles.

Everything here is pure ``jax.numpy`` arithmetic (adds/mults only — no
branches, no FMA dependence), so it is jit-able, vmap-able, shard_map-able and
**differentiable**: the error terms have identically-zero tangents, so
``jax.jacfwd`` through double-double code yields ordinary float64 derivatives,
which is exactly the precision a design matrix needs.

Classic algorithms: Knuth two_sum, Dekker split/two_prod, Bailey/Hida
add/mul/div (the same family the reference ports in ``pulsar_mjd.py``).
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "DD",
    "two_sum",
    "quick_two_sum",
    "two_prod",
    "dd_from_float",
    "dd_from_longdouble",
    "dd_from_string",
    "dd_to_longdouble",
    "dd_add",
    "dd_sub",
    "dd_neg",
    "dd_mul",
    "dd_div",
    "dd_abs",
    "dd_sum",
    "dd_round_split",
    "taylor_horner_dd",
]

# 2**27 + 1, the Dekker/Veltkamp splitter for float64: exact by definition
# only at f64 — the x64-required contract this module states up top
_SPLITTER = 134217729.0  # jaxlint: disable=f32-unsafe-literal


def _opaque(x):
    """Hide a rounded intermediate from XLA's algebraic simplifier.

    The error-free transforms below depend on exact IEEE rounding of specific
    intermediate expressions.  Under ``--xla_allow_excess_precision=true``
    (forced by some TPU compile environments) XLA may fold patterns like
    ``(a + b) - a`` to ``b``, silently collapsing the error terms to zero and
    degrading double-double to plain float64 (~1e-5 cycles of absolute pulse
    phase; measured 2.7e-3 cycles on a v5e).  An ``optimization_barrier`` on
    the rounded value makes the cancellation structurally invisible.
    """
    from jax import lax

    return lax.optimization_barrier(x)


def two_sum(a, b):
    """Error-free transform: a + b = s + e exactly (Knuth, branch-free)."""
    s = _opaque(a + b)
    bb = s - a
    e = (a - (s - bb)) + (b - bb)
    return s, e


def quick_two_sum(a, b):
    """Error-free a + b = s + e, requiring |a| >= |b| (Dekker)."""
    s = _opaque(a + b)
    e = b - (s - a)
    return s, e


def _split(a):
    t = _opaque(_SPLITTER * a)
    hi = t - (t - a)
    lo = a - hi
    return hi, lo


def two_prod(a, b):
    """Error-free transform: a * b = p + e exactly (Dekker, FMA-free)."""
    p = _opaque(a * b)
    ah, al = _split(a)
    bh, bl = _split(b)
    e = ((ah * bh - p) + ah * bl + al * bh) + al * bl
    return p, e


class DD(NamedTuple):
    """A double-double value/array: the unevaluated sum ``hi + lo``.

    NamedTuple => automatically a JAX pytree; flows through jit/vmap/scan.
    """

    hi: jnp.ndarray
    lo: jnp.ndarray

    # -- arithmetic operators ------------------------------------------------
    def __add__(self, other):
        return dd_add(self, other)

    __radd__ = __add__

    def __sub__(self, other):
        return dd_sub(self, other)

    def __rsub__(self, other):
        return dd_add(dd_neg(self), other)

    def __mul__(self, other):
        return dd_mul(self, other)

    __rmul__ = __mul__

    def __truediv__(self, other):
        return dd_div(self, other)

    def __neg__(self):
        return dd_neg(self)

    # -- conversions ---------------------------------------------------------
    def to_float(self) -> jnp.ndarray:
        """Collapse to float64 (loses the low word)."""
        return self.hi + self.lo

    @property
    def shape(self):
        return jnp.shape(self.hi)

    def __getitem__(self, idx):
        return DD(self.hi[idx], self.lo[idx])


def _as_dd(x) -> DD:
    if isinstance(x, DD):
        return x
    return DD(jnp.asarray(x, dtype=jnp.float64), jnp.zeros_like(jnp.asarray(x, dtype=jnp.float64)))


def dd_from_float(x) -> DD:
    """Promote a float64 array/scalar to DD with zero low word."""
    x = jnp.asarray(x, dtype=jnp.float64)
    return DD(x, jnp.zeros_like(x))


def dd_from_longdouble(x) -> DD:
    """Host-side: split numpy longdouble(s) into an exact (hi, lo) pair."""
    x = np.asarray(x, dtype=np.longdouble)
    hi = np.asarray(x, dtype=np.float64)
    lo = np.asarray(x - hi.astype(np.longdouble), dtype=np.float64)
    return DD(jnp.asarray(hi), jnp.asarray(lo))


def dd_from_string(s: str) -> DD:
    """Host-side: exact decimal string -> DD (e.g. MJD strings from .tim files).

    Uses rational arithmetic so the (hi, lo) pair is correctly rounded to the
    full ~106-bit precision, independent of platform longdouble
    (the role of reference ``pulsar_mjd.py:488 str_to_mjds``).
    """
    from fractions import Fraction

    v = Fraction(s.strip())
    hi = float(v)
    lo = float(v - Fraction(hi))
    return DD(jnp.float64(hi), jnp.float64(lo))


def dd_to_longdouble(x: DD) -> np.longdouble:
    """Host-side: collapse to numpy longdouble (for interop/printing)."""
    return np.asarray(x.hi, dtype=np.longdouble) + np.asarray(x.lo, dtype=np.longdouble)


def dd_add(x, y) -> DD:
    """DD + (DD | float). Accurate (Bailey) two-term renormalized sum."""
    x = _as_dd(x)
    if isinstance(y, DD):
        s1, s2 = two_sum(x.hi, y.hi)
        t1, t2 = two_sum(x.lo, y.lo)
        s2 = s2 + t1
        s1, s2 = quick_two_sum(s1, s2)
        s2 = s2 + t2
        hi, lo = quick_two_sum(s1, s2)
        return DD(hi, lo)
    y = jnp.asarray(y, dtype=jnp.float64)
    s1, s2 = two_sum(x.hi, y)
    s2 = s2 + x.lo
    hi, lo = quick_two_sum(s1, s2)
    return DD(hi, lo)


def dd_neg(x: DD) -> DD:
    return DD(-x.hi, -x.lo)


def dd_sub(x, y) -> DD:
    if isinstance(y, DD):
        return dd_add(_as_dd(x), dd_neg(y))
    return dd_add(_as_dd(x), -jnp.asarray(y, dtype=jnp.float64))


def dd_mul(x, y) -> DD:
    """DD * (DD | float)."""
    x = _as_dd(x)
    if isinstance(y, DD):
        p1, p2 = two_prod(x.hi, y.hi)
        p2 = p2 + x.hi * y.lo + x.lo * y.hi
        hi, lo = quick_two_sum(p1, p2)
        return DD(hi, lo)
    y = jnp.asarray(y, dtype=jnp.float64)
    p1, p2 = two_prod(x.hi, y)
    p2 = p2 + x.lo * y
    hi, lo = quick_two_sum(p1, p2)
    return DD(hi, lo)


def dd_div(x, y) -> DD:
    """DD / (DD | float), three-step long division (Bailey)."""
    x = _as_dd(x)
    y = _as_dd(y) if not isinstance(y, DD) else y
    q1 = x.hi / y.hi
    r = dd_sub(x, dd_mul(y, q1))
    q2 = r.hi / y.hi
    r = dd_sub(r, dd_mul(y, q2))
    q3 = r.hi / y.hi
    s1, s2 = quick_two_sum(q1, q2)
    s2 = s2 + q3
    hi, lo = quick_two_sum(s1, s2)
    return DD(hi, lo)


def dd_abs(x: DD) -> DD:
    sgn = jnp.where(x.hi < 0, -1.0, 1.0)
    return DD(x.hi * sgn, x.lo * sgn)


def dd_sum(x: DD, axis=None) -> DD:
    """Sum of a DD array, keeping dd precision (compensated sequential fold).

    ``axis=None`` sums over all elements (numpy convention); an integer axis
    reduces that axis only.
    """
    hi, lo = x.hi, x.lo
    if not hi.ndim:
        return x
    if axis is None:
        hs, ls = hi.reshape(-1), lo.reshape(-1)
    else:
        hs, ls = jnp.moveaxis(hi, axis, 0), jnp.moveaxis(lo, axis, 0)
    acc = DD(hs[0], ls[0])
    for i in range(1, hs.shape[0]):
        acc = dd_add(acc, DD(hs[i], ls[i]))
    return acc


def dd_round_split(x: DD):
    """Split into (nearest integer, fractional remainder in [-0.5, 0.5]).

    Returns ``(k, f)`` with ``k`` an integral-valued float64 array and ``f``
    float64 such that ``x = k + f`` to dd accuracy.  This is the device
    analogue of the reference's int+frac Phase decomposition
    (``phase.py:80-87``).  ``hi - k`` is exact (both are multiples of
    ulp(hi) and the difference is small), so no precision is lost.
    """
    k = jnp.round(x.hi)
    f = (x.hi - k) + x.lo
    extra = jnp.round(f)
    return k + extra, f - extra


def two_sum_np(a, b):
    """Host-side (pure numpy, IEEE-correct on CPU) error-free a + b = s + e.

    The jnp :func:`two_sum` must never be used for host-side table building:
    under a TPU default backend it executes on-device, where f64 excess
    precision breaks the transform (see below)."""
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    s = a + b
    bb = s - a
    e = (a - (s - bb)) + (b - bb)
    return s, e


def two_prod_np(a, b):
    """Host-side error-free a * b = p + e (Dekker split, pure numpy)."""
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    p = a * b
    t = np.float64(_SPLITTER) * a
    ah = t - (t - a)
    al = a - ah
    t = np.float64(_SPLITTER) * b
    bh = t - (t - b)
    bl = b - bh
    e = ((ah * bh - p) + ah * bl + al * bh) + al * bl
    return p, e


# ---------------------------------------------------------------------------
# Exact-by-construction folded products (TPU-safe).
#
# TPU f64 runs with excess-precision semantics (`--xla_allow_excess_precision`
# is forced by some compile environments, and the hardware emulation is not
# IEEE-correctly-rounded): the classic error-free transforms above silently
# degrade to plain float64 there (measured: two_sum's error term collapses,
# costing ~2.7e-3 cycles of absolute pulse phase on a v5e).  The functions
# below never rely on rounding behavior: every intermediate product/difference
# is *exactly representable* in float64 (bit-mask splits keep partial products
# <= 53 significant bits), so any arithmetic that is at least as precise as
# IEEE — including excess precision — returns the exact value.
# ---------------------------------------------------------------------------

# Static magnitude bounds (powers of two).  The *decomposition* below stays
# correct for any values; only the headline product's exactness needs the
# bounds, and they are generous: |F0| < 2**12 Hz (fastest known pulsar is
# 716 Hz), |t| < 2**35 s (~1000 years of data span), |d| < 2**15 days.
_C_POW = 12
_T_POW = 35
_D_POW = 15
_SPLIT_BITS = 25


def _scaled_split(x, pow_bound, bits=_SPLIT_BITS):
    """Split ``x = hi + lo`` with ``hi`` a multiple of 2**(pow_bound-bits).

    Given |x| < 2**pow_bound, ``hi`` carries at most ``bits+1`` significant
    bits.  Uses only power-of-two scaling (exact in binary fp) and round —
    no error-free transforms, so it cannot be broken by excess-precision or
    non-IEEE f64 (TPU).  ``lo = x - hi`` is exact whenever representable and
    otherwise off by <= ulp — harmless, since the decomposition error only
    enters the final result multiplied by the *other* factor's low part."""
    s = 2.0 ** (pow_bound - bits)
    hi = jnp.round(x * (1.0 / s)) * s
    return hi, x - hi


def _fold(k, f, p):
    """Accumulate p into the (integer, fraction) accumulator pair."""
    kp = jnp.round(p)
    return k + kp, f + (p - kp)


def _mul_mod1_impl(c, t):
    """(k, f) with ``c * t = k + f``, |error| <~ 2**-31 cycles, ``k``
    integral.  The dominant partial product ch*th (<= 2**47, both factors
    <= 26 bits) is exactly representable, so its mod-1 fold is exact under
    any arithmetic at least as accurate as IEEE; the three small partials
    (<= 2**21 cycles) contribute only their own rounding error."""
    ch, cl = _scaled_split(c, _C_POW)
    th, tl = _scaled_split(t, _T_POW)
    k = jnp.zeros_like(t)
    f = jnp.zeros_like(t)
    k, f = _fold(k, f, ch * th)   # exact: 26 x 26 bits
    k, f = _fold(k, f, ch * tl)   # <= 2**21 cycles: abs err <= 2**-31
    k, f = _fold(k, f, cl * th)   # <= 2**21 cycles
    f = f + cl * tl               # <= 2**-5 cycles
    kp = jnp.round(f)
    return k + kp, f - kp


@jax.custom_jvp
def mul_mod1(c, t):
    """Folded product: ``c * t = k + f`` with ``k`` integral float64 and
    ``f`` in [-0.5, 0.5], absolute error <~ 2**-31 cycles for |c| < 2**12,
    |t| < 2**35.  Built only from power-of-two scaling, round, multiply and
    benign adds — safe on TPUs whose f64 is emulated / excess-precise, where
    the classic double-double transforms silently degrade.  The JVP routes
    the full derivative into ``f`` (phase derivatives live in the fractional
    part)."""
    return _mul_mod1_impl(c, t)


@mul_mod1.defjvp
def _mul_mod1_jvp(primals, tangents):
    c, t = primals
    dc, dt = tangents
    k, f = _mul_mod1_impl(c, t)
    return (k, f), (jnp.zeros_like(k), t * dc + c * dt)


_DAY_S_F = 86400.0


def _day2sec_impl(d):
    """``d`` days -> two float64 second-components summing to d*86400 with
    <= ~2**-45 s error.  86400 has 10 significant bits, so the high split
    product (<= 26+10 bits) is exact."""
    dh, dl = _scaled_split(d, _D_POW)
    return dh * _DAY_S_F, dl * _DAY_S_F


@jax.custom_jvp
def day2sec_exact(d):
    """Day->second conversion as an unevaluated 2-term sum (TPU-safe)."""
    return _day2sec_impl(d)


@day2sec_exact.defjvp
def _day2sec_jvp(primals, tangents):
    (d,), (dd_,) = primals, tangents
    e1, e2 = _day2sec_impl(d)
    return (e1, e2), (dd_ * _DAY_S_F, jnp.zeros_like(d))


def taylor_horner_dd(x: DD, coeffs: Sequence) -> DD:
    """Evaluate sum_i coeffs[i] * x**i / i! in double-double (Horner form).

    The dd counterpart of reference ``utils.py:411 taylor_horner`` — used for
    spindown phase where x ~ 1e8 s and the result needs ~21 digits.  ``coeffs``
    may be python floats or traced jax scalars (fit parameters).
    """
    import math

    n = len(coeffs)
    if n == 0:
        return dd_from_float(jnp.zeros_like(x.hi))
    acc = dd_from_float(jnp.zeros_like(x.hi))
    for i in range(n - 1, -1, -1):
        c = jnp.asarray(coeffs[i], dtype=jnp.float64) / math.factorial(i)
        acc = dd_add(dd_mul(acc, x), c)
    return acc
