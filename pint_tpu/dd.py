"""Double-double ("two-float") arithmetic on JAX arrays.

This is the TPU-native replacement for the reference's reliance on numpy
``longdouble`` (x87 80-bit) time arithmetic (reference ``pulsar_mjd.py``
throughout, esp. the error-free transforms at ``pulsar_mjd.py:586,609,638``).
A value is represented as an unevaluated sum ``hi + lo`` of two float64s with
``|lo| <= ulp(hi)/2``, giving ~32 significant digits — enough for absolute
pulse phase (~1e12 cycles) to ~1e-12 cycles.

Everything here is pure ``jax.numpy`` arithmetic (adds/mults only — no
branches, no FMA dependence), so it is jit-able, vmap-able, shard_map-able and
**differentiable**: the error terms have identically-zero tangents, so
``jax.jacfwd`` through double-double code yields ordinary float64 derivatives,
which is exactly the precision a design matrix needs.

Classic algorithms: Knuth two_sum, Dekker split/two_prod, Bailey/Hida
add/mul/div (the same family the reference ports in ``pulsar_mjd.py``).
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax.numpy as jnp
import numpy as np

__all__ = [
    "DD",
    "two_sum",
    "quick_two_sum",
    "two_prod",
    "dd_from_float",
    "dd_from_longdouble",
    "dd_from_string",
    "dd_to_longdouble",
    "dd_add",
    "dd_sub",
    "dd_neg",
    "dd_mul",
    "dd_div",
    "dd_abs",
    "dd_sum",
    "dd_round_split",
    "taylor_horner_dd",
]

_SPLITTER = 134217729.0  # 2**27 + 1, Dekker/Veltkamp splitter for float64


def two_sum(a, b):
    """Error-free transform: a + b = s + e exactly (Knuth, branch-free)."""
    s = a + b
    bb = s - a
    e = (a - (s - bb)) + (b - bb)
    return s, e


def quick_two_sum(a, b):
    """Error-free a + b = s + e, requiring |a| >= |b| (Dekker)."""
    s = a + b
    e = b - (s - a)
    return s, e


def _split(a):
    t = _SPLITTER * a
    hi = t - (t - a)
    lo = a - hi
    return hi, lo


def two_prod(a, b):
    """Error-free transform: a * b = p + e exactly (Dekker, FMA-free)."""
    p = a * b
    ah, al = _split(a)
    bh, bl = _split(b)
    e = ((ah * bh - p) + ah * bl + al * bh) + al * bl
    return p, e


class DD(NamedTuple):
    """A double-double value/array: the unevaluated sum ``hi + lo``.

    NamedTuple => automatically a JAX pytree; flows through jit/vmap/scan.
    """

    hi: jnp.ndarray
    lo: jnp.ndarray

    # -- arithmetic operators ------------------------------------------------
    def __add__(self, other):
        return dd_add(self, other)

    __radd__ = __add__

    def __sub__(self, other):
        return dd_sub(self, other)

    def __rsub__(self, other):
        return dd_add(dd_neg(self), other)

    def __mul__(self, other):
        return dd_mul(self, other)

    __rmul__ = __mul__

    def __truediv__(self, other):
        return dd_div(self, other)

    def __neg__(self):
        return dd_neg(self)

    # -- conversions ---------------------------------------------------------
    def to_float(self) -> jnp.ndarray:
        """Collapse to float64 (loses the low word)."""
        return self.hi + self.lo

    @property
    def shape(self):
        return jnp.shape(self.hi)

    def __getitem__(self, idx):
        return DD(self.hi[idx], self.lo[idx])


def _as_dd(x) -> DD:
    if isinstance(x, DD):
        return x
    return DD(jnp.asarray(x, dtype=jnp.float64), jnp.zeros_like(jnp.asarray(x, dtype=jnp.float64)))


def dd_from_float(x) -> DD:
    """Promote a float64 array/scalar to DD with zero low word."""
    x = jnp.asarray(x, dtype=jnp.float64)
    return DD(x, jnp.zeros_like(x))


def dd_from_longdouble(x) -> DD:
    """Host-side: split numpy longdouble(s) into an exact (hi, lo) pair."""
    x = np.asarray(x, dtype=np.longdouble)
    hi = np.asarray(x, dtype=np.float64)
    lo = np.asarray(x - hi.astype(np.longdouble), dtype=np.float64)
    return DD(jnp.asarray(hi), jnp.asarray(lo))


def dd_from_string(s: str) -> DD:
    """Host-side: exact decimal string -> DD (e.g. MJD strings from .tim files).

    Uses rational arithmetic so the (hi, lo) pair is correctly rounded to the
    full ~106-bit precision, independent of platform longdouble
    (the role of reference ``pulsar_mjd.py:488 str_to_mjds``).
    """
    from fractions import Fraction

    v = Fraction(s.strip())
    hi = float(v)
    lo = float(v - Fraction(hi))
    return DD(jnp.float64(hi), jnp.float64(lo))


def dd_to_longdouble(x: DD) -> np.longdouble:
    """Host-side: collapse to numpy longdouble (for interop/printing)."""
    return np.asarray(x.hi, dtype=np.longdouble) + np.asarray(x.lo, dtype=np.longdouble)


def dd_add(x, y) -> DD:
    """DD + (DD | float). Accurate (Bailey) two-term renormalized sum."""
    x = _as_dd(x)
    if isinstance(y, DD):
        s1, s2 = two_sum(x.hi, y.hi)
        t1, t2 = two_sum(x.lo, y.lo)
        s2 = s2 + t1
        s1, s2 = quick_two_sum(s1, s2)
        s2 = s2 + t2
        hi, lo = quick_two_sum(s1, s2)
        return DD(hi, lo)
    y = jnp.asarray(y, dtype=jnp.float64)
    s1, s2 = two_sum(x.hi, y)
    s2 = s2 + x.lo
    hi, lo = quick_two_sum(s1, s2)
    return DD(hi, lo)


def dd_neg(x: DD) -> DD:
    return DD(-x.hi, -x.lo)


def dd_sub(x, y) -> DD:
    if isinstance(y, DD):
        return dd_add(_as_dd(x), dd_neg(y))
    return dd_add(_as_dd(x), -jnp.asarray(y, dtype=jnp.float64))


def dd_mul(x, y) -> DD:
    """DD * (DD | float)."""
    x = _as_dd(x)
    if isinstance(y, DD):
        p1, p2 = two_prod(x.hi, y.hi)
        p2 = p2 + x.hi * y.lo + x.lo * y.hi
        hi, lo = quick_two_sum(p1, p2)
        return DD(hi, lo)
    y = jnp.asarray(y, dtype=jnp.float64)
    p1, p2 = two_prod(x.hi, y)
    p2 = p2 + x.lo * y
    hi, lo = quick_two_sum(p1, p2)
    return DD(hi, lo)


def dd_div(x, y) -> DD:
    """DD / (DD | float), three-step long division (Bailey)."""
    x = _as_dd(x)
    y = _as_dd(y) if not isinstance(y, DD) else y
    q1 = x.hi / y.hi
    r = dd_sub(x, dd_mul(y, q1))
    q2 = r.hi / y.hi
    r = dd_sub(r, dd_mul(y, q2))
    q3 = r.hi / y.hi
    s1, s2 = quick_two_sum(q1, q2)
    s2 = s2 + q3
    hi, lo = quick_two_sum(s1, s2)
    return DD(hi, lo)


def dd_abs(x: DD) -> DD:
    sgn = jnp.where(x.hi < 0, -1.0, 1.0)
    return DD(x.hi * sgn, x.lo * sgn)


def dd_sum(x: DD, axis=None) -> DD:
    """Sum of a DD array, keeping dd precision (compensated sequential fold).

    ``axis=None`` sums over all elements (numpy convention); an integer axis
    reduces that axis only.
    """
    hi, lo = x.hi, x.lo
    if not hi.ndim:
        return x
    if axis is None:
        hs, ls = hi.reshape(-1), lo.reshape(-1)
    else:
        hs, ls = jnp.moveaxis(hi, axis, 0), jnp.moveaxis(lo, axis, 0)
    acc = DD(hs[0], ls[0])
    for i in range(1, hs.shape[0]):
        acc = dd_add(acc, DD(hs[i], ls[i]))
    return acc


def dd_round_split(x: DD):
    """Split into (nearest integer, fractional remainder in [-0.5, 0.5]).

    Returns ``(k, f)`` with ``k`` an integral-valued float64 array and ``f``
    float64 such that ``x = k + f`` to dd accuracy.  This is the device
    analogue of the reference's int+frac Phase decomposition
    (``phase.py:80-87``).  ``hi - k`` is exact (both are multiples of
    ulp(hi) and the difference is small), so no precision is lost.
    """
    k = jnp.round(x.hi)
    f = (x.hi - k) + x.lo
    extra = jnp.round(f)
    return k + extra, f - extra


def taylor_horner_dd(x: DD, coeffs: Sequence) -> DD:
    """Evaluate sum_i coeffs[i] * x**i / i! in double-double (Horner form).

    The dd counterpart of reference ``utils.py:411 taylor_horner`` — used for
    spindown phase where x ~ 1e8 s and the result needs ~21 digits.  ``coeffs``
    may be python floats or traced jax scalars (fit parameters).
    """
    import math

    n = len(coeffs)
    if n == 0:
        return dd_from_float(jnp.zeros_like(x.hi))
    acc = dd_from_float(jnp.zeros_like(x.hi))
    for i in range(n - 1, -1, -1):
        c = jnp.asarray(coeffs[i], dtype=jnp.float64) / math.factorial(i)
        acc = dd_add(dd_mul(acc, x), c)
    return acc
