"""Maximum-likelihood fitting of pulse-profile templates to photon phases.

Counterpart of reference ``templates/lcfitters.py LCFitter``: unbinned
(optionally weighted) Poisson log-likelihood over photon phases, maximized
with scipy; chi-squared binned fit as a fallback.  The log-likelihood is
the reference's eqn (Pletsch & Clark 2015): sum_i log(w_i f(phi_i) + 1-w_i).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pint_tpu.logging import log
from pint_tpu.templates.lctemplate import LCTemplate

__all__ = ["LCFitter", "hessian"]


def hessian(func, x0, eps: float = 1e-5) -> np.ndarray:
    """Numerical Hessian by central differences."""
    n = len(x0)
    H = np.zeros((n, n))
    f0 = func(x0)
    for i in range(n):
        for j in range(i, n):
            xpp = x0.copy(); xpp[i] += eps; xpp[j] += eps
            xpm = x0.copy(); xpm[i] += eps; xpm[j] -= eps
            xmp = x0.copy(); xmp[i] -= eps; xmp[j] += eps
            xmm = x0.copy(); xmm[i] -= eps; xmm[j] -= eps
            H[i, j] = H[j, i] = (func(xpp) - func(xpm) - func(xmp) + func(xmm)) \
                / (4 * eps * eps)
    return H


class LCFitter:
    def __init__(self, template: LCTemplate, phases, weights=None,
                 binned_bins: int = 100):
        self.template = template
        self.phases = np.asarray(phases, dtype=np.float64) % 1.0
        self.weights = (np.asarray(weights, dtype=np.float64)
                        if weights is not None else None)
        self.binned_bins = binned_bins
        self.ll_best = None

    # -- likelihood ----------------------------------------------------------
    def loglikelihood(self, p=None) -> float:
        """log L = sum log(w f(phi) + (1-w)); unweighted w == 1."""
        if p is not None:
            self.template.set_parameters(p)
        f = np.asarray(self.template(self.phases))
        if self.weights is None:
            vals = f
        else:
            vals = self.weights * f + (1.0 - self.weights)
        if np.any(vals <= 0):
            return -np.inf
        return float(np.sum(np.log(vals)))

    def __call__(self, p=None) -> float:
        return -self.loglikelihood(p)

    # -- fitting -------------------------------------------------------------
    def fit(self, method: str = "Nelder-Mead", maxiter: int = 2000,
            estimate_errors: bool = True, quiet: bool = True) -> bool:
        """Default optimizer is Nelder-Mead: the likelihood surface mixes
        very different scales (widths ~1e-2, angles ~1) and gradient-free
        simplex handles it far more reliably than numerically-differenced
        L-BFGS here."""
        from scipy.optimize import minimize

        x0 = self.template.get_parameters()

        def nll(p):
            try:
                v = self(p)
            except (ValueError, FloatingPointError):
                return 1e30
            return v if np.isfinite(v) else 1e30

        res = minimize(nll, x0, method=method,
                       options={"maxiter": maxiter})
        self.template.set_parameters(res.x)
        for p in self.template.primitives:
            p.set_location(p.get_location() % 1.0)
        self.ll_best = -res.fun
        if estimate_errors:
            try:
                H = hessian(nll, res.x)
                cov = np.linalg.inv(H)
                self.errors = np.sqrt(np.maximum(np.diag(cov), 0.0))
            except np.linalg.LinAlgError:
                log.warning("Hessian not invertible; no template errors")
                self.errors = np.zeros_like(res.x)
            # nll() mutated the template while probing the Hessian: restore
            # the optimizer solution
            self.template.set_parameters(res.x)
            for p in self.template.primitives:
                p.set_location(p.get_location() % 1.0)
        if not quiet:
            log.info(f"LCFitter: logL = {self.ll_best:.2f}, "
                     f"success = {res.success}")
        return bool(res.success)

    def fit_position(self, unbinned: bool = True) -> tuple:
        """Fit only an overall rotation of the template; returns
        (shift, error) (reference ``lcfitters.py fit_position``)."""
        from scipy.optimize import minimize_scalar

        base = [p.get_location() for p in self.template.primitives]

        def nll(dphi):
            for p, b in zip(self.template.primitives, base):
                p.set_location((b + dphi) % 1.0)
            return -self.loglikelihood()

        res = minimize_scalar(nll, bounds=(-0.5, 0.5), method="bounded",
                              options={"xatol": 1e-6})
        shift = float(res.x)
        # curvature -> error
        eps = 1e-4
        d2 = (nll(shift + eps) - 2 * nll(shift) + nll(shift - eps)) / eps**2
        err = 1.0 / np.sqrt(d2) if d2 > 0 else np.nan
        for p, b in zip(self.template.primitives, base):
            p.set_location((b + shift) % 1.0)
        return shift, float(err)

    def remap_errors(self):  # parity no-op
        pass

    def __str__(self):
        ll = self.ll_best if self.ll_best is not None else self.loglikelihood()
        return f"LCFitter: {len(self.phases)} photons, logL = {ll:.2f}\n" \
            + repr(self.template)


#: reference re-export (each template module offers isvector)
from pint_tpu.templates.lcnorm import isvector  # noqa: E402,F401
