"""Maximum-likelihood fitting of pulse-profile templates to photon phases.

Counterpart of reference ``templates/lcfitters.py LCFitter``: unbinned
(optionally weighted) Poisson log-likelihood over photon phases, maximized
with scipy; chi-squared binned fit as a fallback.  The log-likelihood is
the reference's eqn (Pletsch & Clark 2015): sum_i log(w_i f(phi_i) + 1-w_i).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pint_tpu.logging import log
from pint_tpu.templates.lctemplate import LCTemplate

__all__ = ["LCFitter", "hessian", "get_errors", "make_err_plot"]


def hessian(func, x0, eps: float = 1e-5) -> np.ndarray:
    """Numerical Hessian by central differences."""
    n = len(x0)
    H = np.zeros((n, n))
    f0 = func(x0)
    for i in range(n):
        for j in range(i, n):
            xpp = x0.copy(); xpp[i] += eps; xpp[j] += eps
            xpm = x0.copy(); xpm[i] += eps; xpm[j] -= eps
            xmp = x0.copy(); xmp[i] -= eps; xmp[j] += eps
            xmm = x0.copy(); xmm[i] -= eps; xmm[j] -= eps
            H[i, j] = H[j, i] = (func(xpp) - func(xpm) - func(xmp) + func(xmm)) \
                / (4 * eps * eps)
    return H


def shifted(m, delta: float = 0.5):
    """Binned profile circularly shifted in phase by ``delta`` via the FFT
    shift theorem (reference ``lcfitters.py:30``)."""
    m = np.asarray(m, dtype=np.float64)
    f = np.fft.fft(m, axis=-1)
    n = f.shape[-1]
    arg = np.fft.fftfreq(n) * (n * np.pi * 2.0j * delta)
    return np.real(np.fft.ifft(np.exp(arg) * f, axis=-1))


def weighted_light_curve(nbins: int, phases, weights, normed: bool = False,
                         phase_shift: float = 0.0):
    """(bin edges, weighted counts, errors) of a weighted folded profile
    (reference ``lcfitters.py:38``)."""
    phases = np.asarray(phases, dtype=np.float64)
    weights = np.asarray(weights, dtype=np.float64)
    bins = np.linspace(0 + phase_shift, 1 + phase_shift, nbins + 1)
    counts = np.histogram(phases, bins=bins)[0]
    w1 = np.histogram(phases, bins=bins, weights=weights)[0].astype(float)
    w2 = np.histogram(phases, bins=bins,
                      weights=weights**2)[0].astype(float)
    errors = np.where(counts > 1, w2**0.5, counts)
    norm = w1.sum() / nbins if normed else 1.0
    return bins, w1 / norm, errors / norm


def hess_from_grad(grad_fn, x0, eps: float = 1e-5) -> np.ndarray:
    """Hessian by finite-differencing a gradient function (reference
    ``lcfitters.py hess_from_grad``)."""
    x0 = np.asarray(x0, dtype=np.float64)
    n = len(x0)
    H = np.empty((n, n))
    for i in range(n):
        xp = x0.copy()
        xp[i] += eps
        gp = np.asarray(grad_fn(xp))
        xp[i] -= 2 * eps
        gm = np.asarray(grad_fn(xp))
        H[i] = (gp - gm) / (2 * eps)
    return 0.5 * (H + H.T)


def calc_step_size(fit_values, errors, minstep: float = 1e-5) -> np.ndarray:
    """Per-parameter optimizer step sizes from current errors (reference
    ``lcfitters.py calc_step_size``)."""
    errors = np.asarray(errors, dtype=np.float64)
    vals = np.abs(np.asarray(fit_values, dtype=np.float64))
    return np.maximum(np.where(errors > 0, errors, 0.1 * vals), minstep)


class LCFitter:
    def __init__(self, template: LCTemplate, phases, weights=None,
                 binned_bins: int = 100):
        self.template = template
        self.phases = np.asarray(phases, dtype=np.float64) % 1.0
        self.weights = (np.asarray(weights, dtype=np.float64)
                        if weights is not None else None)
        self.binned_bins = binned_bins
        self.ll_best = None

    # -- likelihood ----------------------------------------------------------
    def loglikelihood(self, p=None) -> float:
        """log L = sum log(w f(phi) + (1-w)); unweighted w == 1."""
        if p is not None:
            self.template.set_parameters(p)
        f = np.asarray(self.template(self.phases))
        if self.weights is None:
            vals = f
        else:
            vals = self.weights * f + (1.0 - self.weights)
        if np.any(vals <= 0):
            return -np.inf
        return float(np.sum(np.log(vals)))

    def __call__(self, p=None) -> float:
        return -self.loglikelihood(p)

    # -- fitting -------------------------------------------------------------
    def fit(self, method: str = "Nelder-Mead", maxiter: int = 2000,
            estimate_errors: bool = True, quiet: bool = True) -> bool:
        """Default optimizer is Nelder-Mead: the likelihood surface mixes
        very different scales (widths ~1e-2, angles ~1) and gradient-free
        simplex handles it far more reliably than numerically-differenced
        L-BFGS here."""
        from scipy.optimize import minimize

        x0 = self.template.get_parameters()

        def nll(p):
            try:
                v = self(p)
            except (ValueError, FloatingPointError):
                return 1e30
            return v if np.isfinite(v) else 1e30

        res = minimize(nll, x0, method=method,
                       options={"maxiter": maxiter})
        self.template.set_parameters(res.x)
        for p in self.template.primitives:
            p.set_location(p.get_location() % 1.0)
        self.ll_best = -res.fun
        if estimate_errors:
            self.errors = self._hessian_errors(nll, res.x)
        if not quiet:
            log.info(f"LCFitter: logL = {self.ll_best:.2f}, "
                     f"success = {res.success}")
        return bool(res.success)

    def fit_position(self, unbinned: bool = True) -> tuple:
        """Fit only an overall rotation of the template; returns
        (shift, error) (reference ``lcfitters.py fit_position``)."""
        from scipy.optimize import minimize_scalar

        base = [p.get_location() for p in self.template.primitives]

        def nll(dphi):
            for p, b in zip(self.template.primitives, base):
                p.set_location((b + dphi) % 1.0)
            return -self.loglikelihood()

        res = minimize_scalar(nll, bounds=(-0.5, 0.5), method="bounded",
                              options={"xatol": 1e-6})
        shift = float(res.x)
        # curvature -> error
        eps = 1e-4
        d2 = (nll(shift + eps) - 2 * nll(shift) + nll(shift - eps)) / eps**2
        err = 1.0 / np.sqrt(d2) if d2 > 0 else np.nan
        for p, b in zip(self.template.primitives, base):
            p.set_location((b + shift) % 1.0)
        return shift, float(err)

    # -- reference fit-method family and stats (lcfitters.py) ---------------
    def fit_fmin(self, **kw):
        """Nelder-Mead fit (reference ``lcfitters.py fit_fmin``)."""
        return self.fit(method="Nelder-Mead", **kw)

    def fit_bfgs(self, **kw):
        """BFGS fit (reference ``lcfitters.py fit_bfgs``)."""
        return self.fit(method="BFGS", **kw)

    def fit_cg(self, **kw):
        """Conjugate-gradient fit (reference ``lcfitters.py fit_cg``)."""
        return self.fit(method="CG", **kw)

    def fit_l_bfgs_b(self, **kw):
        """L-BFGS-B fit (reference ``lcfitters.py fit_l_bfgs_b``)."""
        return self.fit(method="L-BFGS-B", **kw)

    def fit_tnc(self, **kw):
        """Truncated-Newton fit (reference ``lcfitters.py fit_tnc``)."""
        return self.fit(method="TNC", **kw)

    def aic(self) -> float:
        """Akaike information criterion at the current parameters
        (reference ``lcfitters.py aic``)."""
        k = self.template.num_parameters()
        return 2.0 * k - 2.0 * self.loglikelihood()

    def bic(self) -> float:
        """Bayesian information criterion (reference
        ``lcfitters.py bic``)."""
        k = self.template.num_parameters()
        return k * np.log(len(self.phases)) - 2.0 * self.loglikelihood()

    def chi(self, bins: int = 50):
        """(chi2, dof) of the binned profile against the template
        (reference ``lcfitters.py chi``)."""
        edges = np.linspace(0.0, 1.0, bins + 1)
        centers = 0.5 * (edges[1:] + edges[:-1])
        if self.weights is None:
            counts, _ = np.histogram(self.phases, bins=edges)
            ntot = len(self.phases)
        else:
            counts, _ = np.histogram(self.phases, bins=edges,
                                     weights=self.weights)
            ntot = float(self.weights.sum())
        expect = np.asarray(self.template(centers)) / bins * ntot
        var = np.maximum(expect, 1e-12)
        chi2 = float(np.sum((counts - expect) ** 2 / var))
        return chi2, bins - self.template.num_parameters()

    def _hessian_errors(self, nll, x0) -> np.ndarray:
        """sqrt(diag(H^-1)) of the negative log-likelihood at ``x0``,
        restoring the template (the probe mutates it) — the ONE
        implementation behind both fit() and hess_errors()."""
        try:
            H = hessian(nll, x0)
            cov = np.linalg.inv(H)
            errs = np.sqrt(np.maximum(np.diag(cov), 0.0))
        except np.linalg.LinAlgError:
            log.warning("Hessian not invertible; no template errors")
            errs = np.zeros(len(x0))
        self.template.set_parameters(x0)
        for p in self.template.primitives:
            p.set_location(p.get_location() % 1.0)
        return errs

    def hess_errors(self) -> np.ndarray:
        """Parameter errors from the likelihood Hessian at the current
        parameters (reference ``lcfitters.py hess_errors``)."""
        x0 = self.template.get_parameters().copy()

        def nll(p):
            # same guard as fit(): a probe stepping into zero density must
            # register as a huge nll, not inf/exception (inv(H with inf)
            # silently yields NaN)
            try:
                v = self(p)
            except (ValueError, FloatingPointError):
                return 1e30
            return v if np.isfinite(v) else 1e30

        self.errors = self._hessian_errors(nll, x0)
        return self.errors

    def bootstrap_errors(self, nsamp: int = 20, fit_kwargs=None,
                         rng=None) -> np.ndarray:
        """Parameter errors by refitting phase resamples (reference
        ``lcfitters.py bootstrap_errors``)."""
        import copy as _copy

        rng = rng or np.random.default_rng()
        fit_kwargs = dict(fit_kwargs or {})
        fit_kwargs.setdefault("estimate_errors", False)
        x0 = self.template.get_parameters().copy()
        samples = []
        for _ in range(nsamp):
            idx = rng.integers(0, len(self.phases), len(self.phases))
            sub = LCFitter(_copy.deepcopy(self.template), self.phases[idx],
                           weights=None if self.weights is None
                           else self.weights[idx])
            sub.template.set_parameters(x0.copy())
            sub.fit(**fit_kwargs)
            samples.append(sub.template.get_parameters().copy())
        self.template.set_parameters(x0)
        errs = np.std(np.asarray(samples), axis=0)
        self.errors = errs
        return errs

    def binned_loglikelihood(self, p=None, bins: int = None) -> float:
        """log-likelihood on a binned profile (Poisson factor dropped;
        reference ``lcfitters.py binned_loglikelihood``)."""
        bins = bins or self.binned_bins
        if p is not None:
            self.template.set_parameters(p)
        edges = np.linspace(0.0, 1.0, bins + 1)
        centers = 0.5 * (edges[1:] + edges[:-1])
        f = np.asarray(self.template(centers))
        counts, _ = np.histogram(self.phases, bins=edges)  # raw photons/bin
        if self.weights is None:
            vals = f
        else:
            wsum, _ = np.histogram(self.phases, bins=edges,
                                   weights=self.weights)
            wbar = np.divide(wsum, np.maximum(counts, 1))
            vals = wbar * f + (1.0 - wbar)
        if np.any(vals[counts > 0] <= 0):
            return -np.inf
        return float(np.sum(counts * np.log(np.maximum(vals, 1e-300))))

    def binned_gradient(self, p=None, bins: int = None,
                        eps: float = 1e-6) -> np.ndarray:
        """Finite-difference gradient of :meth:`binned_loglikelihood`
        (reference ``lcfitters.py binned_gradient``)."""
        x0 = self.template.get_parameters().copy() if p is None \
            else np.asarray(p, dtype=np.float64)
        g = np.empty(len(x0))
        for i in range(len(x0)):
            xp = x0.copy()
            xp[i] += eps
            lp = self.binned_loglikelihood(xp, bins=bins)
            xp[i] -= 2 * eps
            lm = self.binned_loglikelihood(xp, bins=bins)
            g[i] = (lp - lm) / (2 * eps)
        self.template.set_parameters(x0)
        return g

    def remap_errors(self):  # parity no-op
        pass

    def __str__(self):
        ll = self.ll_best if self.ll_best is not None else self.loglikelihood()
        return f"LCFitter: {len(self.phases)} photons, logL = {ll:.2f}\n" \
            + repr(self.template)


def get_errors(template, total, n: int = 100, rng=None, quiet: bool = True):
    """Monte-Carlo estimate of template TOA (phase) errors (reference
    ``lcfitters.py:908 get_errors``).

    For each of ``n`` realizations: draw ``total`` photons from the
    template, re-fit the overall phase by maximum likelihood, and measure
    the log-likelihood curvature at the optimum two ways — with a fixed
    0.01-cycle step and with a step equal to the first estimate itself
    (the reference's self-consistent re-measurement).

    Returns ``(fitvals - ph0, errors, errors_r)``: the phase-fit offsets
    and the two curvature error estimates, each length ``n``.
    """
    from scipy.optimize import minimize_scalar

    rng = rng or np.random.default_rng()
    ph0 = template.get_location()
    work = template.copy()

    def logl(phi, phases):
        work.set_overall_phase(phi % 1)
        vals = np.asarray(work(phases))
        if np.any(vals <= 0):
            return np.inf
        return -np.log(vals).sum()

    fitvals = np.empty(n)
    errors = np.empty(n)
    errors_r = np.empty(n)
    delta = 0.01
    mean = 0.0
    for i in range(n):
        work.set_overall_phase(ph0)
        ph = work.random(total, rng=rng)
        res = minimize_scalar(logl, bounds=(ph0 - 0.5, ph0 + 0.5),
                              args=(ph,), method="bounded",
                              options={"xatol": 1e-7})
        phi0, fopt = float(res.x), float(res.fun)
        fitvals[i] = phi0
        mean += logl(phi0 + delta, ph) - fopt
        curv = (logl(phi0 + delta, ph) - 2 * fopt
                + logl(phi0 - delta, ph)) / delta**2
        if curv > 0:
            errors[i] = curv
            step = curv ** -0.5
            errors_r[i] = (logl(phi0 + step, ph) - 2 * fopt
                           + logl(phi0 - step, ph)) / step**2
        else:
            # flat/concave likelihood at the bounded optimum (low counts):
            # no meaningful curvature error for this realization
            errors[i] = errors_r[i] = np.nan
    if not quiet:
        log.info(f"get_errors: mean dlogL at +{delta} = {mean / n:.2f}")
    return fitvals - ph0, errors ** -0.5, errors_r ** -0.5


def make_err_plot(template, totals=(10, 20, 50, 100, 500), n: int = 100,
                  rng=None, fignum=None):
    """Histogram the normalized MC phase-fit offsets of :func:`get_errors`
    for several photon totals (reference ``lcfitters.py:942``).  Returns
    the matplotlib figure (Agg-safe; caller saves or shows)."""
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(num=fignum)
    bins = np.arange(-5, 5.1, 0.25)
    for tot in totals:
        fvals, errs, _ = get_errors(template, tot, n=n, rng=rng)
        ax.hist(fvals / errs, bins=bins, histtype="step", density=True,
                label=f"N = {tot}")
    g = np.linspace(-5, 5, 201)
    ax.plot(g, np.exp(-0.5 * g**2) / np.sqrt(2 * np.pi), "k--",
            label="unit normal")
    ax.set_xlabel("normalized phase offset")
    ax.legend(loc="upper right")
    return fig


#: reference re-export (each template module offers isvector)
from pint_tpu.templates.lcnorm import isvector  # noqa: E402,F401
