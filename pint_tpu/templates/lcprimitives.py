"""Light-curve primitive components: normalized peak shapes on phase [0,1).

Counterpart of reference ``templates/lcprimitives.py`` (LCGaussian,
LCLorentzian, LCVonMises and kin).  Each primitive integrates to 1 over one
period and exposes ``(phases) -> density``.  Evaluation cores are
jnp-compatible, so a whole-template photon log-likelihood can be jitted and
vmapped over MCMC walkers (the TPU-native replacement for the reference's
per-walker Python loop).

Wrapping: Gaussian/Lorentzian shapes are periodized by summing image terms
over a fixed window of wraps (trace-static), matching the reference's
approach of wrapping narrow peaks.
"""

from __future__ import annotations

import numpy as np

__all__ = ["LCPrimitive", "LCGaussian", "LCLorentzian", "LCVonMises",
           "LCTopHat"]

_NWRAP = 6  # image terms each side; adequate for width > ~0.005


def _np_or_jnp(x):
    import jax.numpy as jnp

    return jnp if not isinstance(x, np.ndarray) and not np.isscalar(x) else np


class LCPrimitive:
    """Base: parameters [width-like..., location]; pdf integrates to 1."""

    name = "base"
    pnames: list = []

    def __init__(self, p=None):
        self.p = np.asarray(p if p is not None else self.p0, dtype=np.float64)
        self.free = np.ones_like(self.p, dtype=bool)

    def get_location(self) -> float:
        return float(self.p[-1])

    def set_location(self, loc: float):
        self.p[-1] = loc % 1.0

    def get_width(self, error: bool = False) -> float:
        return float(self.p[0])

    def num_parameters(self, free: bool = True) -> int:
        return int(self.free.sum()) if free else len(self.p)

    def get_parameters(self, free: bool = True) -> np.ndarray:
        return self.p[self.free] if free else self.p.copy()

    def set_parameters(self, p, free: bool = True):
        if free:
            self.p[self.free] = p
        else:
            self.p[:] = p
        return True

    def _pdf(self, phases, p):
        raise NotImplementedError

    def __call__(self, phases):
        return self._pdf(phases, self.p)

    def integrate(self, x1: float = 0.0, x2: float = 1.0, simps: int = 512) -> float:
        """Numerical integral over [x1, x2] (analytic not needed at the
        fitting accuracy; the pdf is smooth and periodic)."""
        g = np.linspace(x1, x2, simps + 1)
        y = np.asarray(self(g))
        return float(np.trapezoid(y, g))

    def copy(self):
        import copy as _c

        return _c.deepcopy(self)

    def __repr__(self):
        pars = ", ".join(f"{n}={v:.4f}" for n, v in zip(self.pnames, self.p))
        return f"{type(self).__name__}({pars})"


class LCGaussian(LCPrimitive):
    """Wrapped Gaussian peak: p = [sigma, location]
    (reference ``lcprimitives.py LCGaussian``)."""

    name = "Gaussian"
    pnames = ["Width", "Location"]
    p0 = [0.03, 0.5]

    def _pdf(self, phases, p):
        import jax.numpy as jnp

        xp = jnp if not isinstance(phases, np.ndarray) else np
        sigma, loc = p[0], p[1]
        z = (xp.asarray(phases) - loc) % 1.0
        out = 0.0
        for k in range(-_NWRAP, _NWRAP + 1):
            out = out + xp.exp(-0.5 * ((z + k) / sigma) ** 2)
        return out / (sigma * np.sqrt(2 * np.pi))


class LCLorentzian(LCPrimitive):
    """Periodized Lorentzian: p = [gamma (HWHM), location]."""

    name = "Lorentzian"
    pnames = ["Width", "Location"]
    p0 = [0.03, 0.5]

    def _pdf(self, phases, p):
        import jax.numpy as jnp

        xp = jnp if not isinstance(phases, np.ndarray) else np
        gamma, loc = p[0], p[1]
        # exact wrapped Lorentzian:
        # sum_k gamma/((z+k)^2+gamma^2) = pi sinh(2 pi g)/(cosh(2 pi g)-cos(2 pi z))
        # normalized over one cycle this is sinh/(cosh - cos)
        a = 2 * np.pi * gamma
        z = 2 * np.pi * (xp.asarray(phases) - loc)
        return xp.sinh(a) / (xp.cosh(a) - xp.cos(z))


class LCVonMises(LCPrimitive):
    """Von Mises peak (circular normal): p = [width ~ 1/sqrt(kappa), loc]
    (reference parameterization: width = kappa^(-1/2)/(2 pi))."""

    name = "VonMises"
    pnames = ["Width", "Location"]
    p0 = [0.03, 0.5]

    def _pdf(self, phases, p):
        import jax.numpy as jnp
        from jax.scipy.special import i0e

        xp = jnp if not isinstance(phases, np.ndarray) else np
        width, loc = p[0], p[1]
        kappa = 1.0 / (2 * np.pi * width) ** 2
        # density per unit PHASE (one cycle), not per radian:
        # f(phi) = exp(kappa cos z) / I0(kappa), z = 2 pi (phi - loc)
        z = 2 * np.pi * (xp.asarray(phases) - loc)
        if xp is np:
            from scipy.special import i0e as np_i0e

            return np.exp(kappa * (np.cos(z) - 1.0)) / np_i0e(kappa)
        return jnp.exp(kappa * (jnp.cos(z) - 1.0)) / i0e(kappa)


class LCTopHat(LCPrimitive):
    """Top hat of given width centered at location (host-side only shape)."""

    name = "TopHat"
    pnames = ["Width", "Location"]
    p0 = [0.1, 0.5]

    def _pdf(self, phases, p):
        import jax.numpy as jnp

        xp = jnp if not isinstance(phases, np.ndarray) else np
        width, loc = p[0], p[1]
        z = (xp.asarray(phases) - loc + 0.5) % 1.0 - 0.5
        return xp.where(xp.abs(z) <= width / 2, 1.0 / width, 0.0)
