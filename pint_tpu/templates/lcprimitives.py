"""Light-curve primitive components: normalized peak shapes on phase [0,1).

Counterpart of reference ``templates/lcprimitives.py`` (LCGaussian,
LCLorentzian, LCVonMises and kin).  Each primitive integrates to 1 over one
period and exposes ``(phases) -> density``.  Evaluation cores are
jnp-compatible, so a whole-template photon log-likelihood can be jitted and
vmapped over MCMC walkers (the TPU-native replacement for the reference's
per-walker Python loop).

Wrapping: Gaussian/Lorentzian shapes are periodized by summing image terms
over a fixed window of wraps (trace-static), matching the reference's
approach of wrapping narrow peaks.
"""

from __future__ import annotations

import math

import numpy as np

__all__ = ["LCPrimitive", "LCWrappedFunction", "LCGaussian", "LCGaussian2",
           "LCLorentzian", "LCLorentzian2", "LCVonMises", "LCTopHat",
           "LCKing", "LCHarmonic", "LCSkewGaussian", "FastBessel",
           "LCEmpiricalFourier", "LCKernelDensity", "convert_primitive",
           "approx_gradient", "check_gradient", "two_comp_mc"]

_NWRAP = 6  # image terms each side; adequate for width > ~0.005


def _np_or_jnp(x):
    import jax.numpy as jnp

    return jnp if not isinstance(x, np.ndarray) and not np.isscalar(x) else np


class LCPrimitive:
    """Base: parameters [width-like..., location]; pdf integrates to 1."""

    name = "base"
    pnames: list = []
    #: False for shapes whose component pdf can go negative (Fourier
    #: harmonics): they are not standalone densities, so mixture
    #: (per-component) sampling is invalid for them
    mixture_safe = True

    def __init__(self, p=None):
        self.p = np.asarray(p if p is not None else self.p0, dtype=np.float64)
        self.free = np.ones_like(self.p, dtype=bool)

    def get_location(self) -> float:
        return float(self.p[-1])

    def set_location(self, loc: float):
        self.p[-1] = loc % 1.0

    def get_width(self, error: bool = False) -> float:
        return float(self.p[0])

    def num_parameters(self, free: bool = True) -> int:
        return int(self.free.sum()) if free else len(self.p)

    def get_parameters(self, free: bool = True) -> np.ndarray:
        return self.p[self.free] if free else self.p.copy()

    def set_parameters(self, p, free: bool = True):
        if free:
            self.p[self.free] = p
        else:
            self.p[:] = p
        return True

    def _pdf(self, phases, p):
        raise NotImplementedError

    def __call__(self, phases):
        return self._pdf(phases, self.p)

    def hwhm(self, right: bool = False) -> float:
        """Half width at half maximum; subclasses with non-gaussian shapes
        override (reference ``lcprimitives.py hwhm``)."""
        return float(self.p[int(right) if self.is_two_sided() else 0]) \
            * math.sqrt(2 * math.log(2))

    def is_two_sided(self) -> bool:
        return False

    def random(self, n: int, rng=None) -> np.ndarray:
        """Draw n phases from this primitive (rejection fallback; analytic
        subclasses override)."""
        rng = rng or np.random.default_rng()
        grid = np.linspace(0.0, 1.0, 1024)
        fmax = float(np.max(np.asarray(self(grid)))) * 1.05
        out = np.empty(0)
        while len(out) < n:
            m = int((n - len(out)) * 1.5 * fmax) + 16
            x = rng.random(m)
            keep = rng.random(m) * fmax < np.asarray(self(x))
            out = np.concatenate([out, x[keep]])
        return out[:n]

    def integrate(self, x1: float = 0.0, x2: float = 1.0, simps: int = 512) -> float:
        """Numerical integral over [x1, x2] (analytic not needed at the
        fitting accuracy; the pdf is smooth and periodic)."""
        g = np.linspace(x1, x2, simps + 1)
        y = np.asarray(self(g))
        return float(np.trapezoid(y, g))

    def copy(self):
        import copy as _c

        return _c.deepcopy(self)

    def __repr__(self):
        pars = ", ".join(f"{n}={v:.4f}" for n, v in zip(self.pnames, self.p))
        return f"{type(self).__name__}({pars})"


class LCGaussian(LCPrimitive):
    """Wrapped Gaussian peak: p = [sigma, location]
    (reference ``lcprimitives.py LCGaussian``)."""

    name = "Gaussian"
    pnames = ["Width", "Location"]
    p0 = [0.03, 0.5]

    def _pdf(self, phases, p):
        import jax.numpy as jnp

        xp = jnp if not isinstance(phases, np.ndarray) else np
        sigma, loc = p[0], p[1]
        z = (xp.asarray(phases) - loc) % 1.0
        out = 0.0
        for k in range(-_NWRAP, _NWRAP + 1):
            out = out + xp.exp(-0.5 * ((z + k) / sigma) ** 2)
        return out / (sigma * np.sqrt(2 * np.pi))

    def random(self, n, rng=None):
        rng = rng or np.random.default_rng()
        return (self.p[1] + self.p[0] * rng.standard_normal(n)) % 1.0


class LCGaussian2(LCPrimitive):
    """Wrapped two-sided Gaussian: p = [sigma_left, sigma_right, location]
    (reference ``lcprimitives.py:794 LCGaussian2``): each side is a half
    normal with its own width, continuous at the mode, integral 1."""

    name = "Gaussian2"
    pnames = ["Width1", "Width2", "Location"]
    p0 = [0.03, 0.03, 0.5]

    def is_two_sided(self):
        return True

    def _pdf(self, phases, p):
        import jax.numpy as jnp

        xp = jnp if not isinstance(phases, np.ndarray) else np
        w1, w2, loc = p[0], p[1], p[2]
        amp = math.sqrt(2.0 / np.pi)  # 2/sqrt(2 pi), shared peak height scale
        z0 = xp.asarray(phases) - loc
        out = 0.0
        for k in range(-_NWRAP, _NWRAP + 1):
            z = z0 + k
            zz = z * xp.where(z <= 0, 1.0 / w1, 1.0 / w2)
            out = out + xp.exp(-0.5 * zz**2)
        return out * (amp / (w1 + w2))

    def random(self, n, rng=None):
        rng = rng or np.random.default_rng()
        w1, w2, loc = self.p
        left = rng.random(n) < w1 / (w1 + w2)
        draw = np.abs(rng.standard_normal(n))
        return (loc + np.where(left, -w1 * draw, w2 * draw)) % 1.0


class LCLorentzian(LCPrimitive):
    """Periodized Lorentzian: p = [gamma (HWHM), location]."""

    name = "Lorentzian"
    pnames = ["Width", "Location"]
    p0 = [0.03, 0.5]

    def _pdf(self, phases, p):
        import jax.numpy as jnp

        xp = jnp if not isinstance(phases, np.ndarray) else np
        gamma, loc = p[0], p[1]
        # exact wrapped Lorentzian:
        # sum_k gamma/((z+k)^2+gamma^2) = pi sinh(2 pi g)/(cosh(2 pi g)-cos(2 pi z))
        # normalized over one cycle this is sinh/(cosh - cos)
        a = 2 * np.pi * gamma
        z = 2 * np.pi * (xp.asarray(phases) - loc)
        return xp.sinh(a) / (xp.cosh(a) - xp.cos(z))

    def hwhm(self, right=False):
        return float(self.p[0])

    def random(self, n, rng=None):
        rng = rng or np.random.default_rng()
        return (self.p[1] + self.p[0] * rng.standard_cauchy(n)) % 1.0


class LCLorentzian2(LCPrimitive):
    """Wrapped two-sided Lorentzian: p = [gamma_left, gamma_right, location]
    (reference ``lcprimitives.py:1086 LCLorentzian2``)."""

    name = "Lorentzian2"
    pnames = ["Width1", "Width2", "Location"]
    p0 = [0.03, 0.03, 0.5]

    def is_two_sided(self):
        return True

    def hwhm(self, right=False):
        return float(self.p[int(right)])

    def _pdf(self, phases, p):
        import jax.numpy as jnp

        xp = jnp if not isinstance(phases, np.ndarray) else np
        g1, g2, loc = p[0], p[1], p[2]
        amp = 2.0 / np.pi / (g1 + g2)  # shared peak height, integral 1
        z0 = (xp.asarray(phases) - loc + 0.5) % 1.0 - 0.5
        out = 0.0
        for k in range(-_NWRAP, _NWRAP + 1):
            z = z0 + k
            zz = z * xp.where(z <= 0, 1.0 / g1, 1.0 / g2)
            out = out + amp / (1.0 + zz * zz)
        return out

    def random(self, n, rng=None):
        rng = rng or np.random.default_rng()
        g1, g2, loc = self.p
        left = rng.random(n) < g1 / (g1 + g2)
        draw = np.abs(rng.standard_cauchy(n))
        return (loc + np.where(left, -g1 * draw, g2 * draw)) % 1.0


class LCVonMises(LCPrimitive):
    """Von Mises peak (circular normal): p = [width ~ 1/sqrt(kappa), loc]
    (reference parameterization: width = kappa^(-1/2)/(2 pi))."""

    name = "VonMises"
    pnames = ["Width", "Location"]
    p0 = [0.03, 0.5]

    def _pdf(self, phases, p):
        import jax.numpy as jnp
        from jax.scipy.special import i0e

        xp = jnp if not isinstance(phases, np.ndarray) else np
        width, loc = p[0], p[1]
        kappa = 1.0 / (2 * np.pi * width) ** 2
        # density per unit PHASE (one cycle), not per radian:
        # f(phi) = exp(kappa cos z) / I0(kappa), z = 2 pi (phi - loc)
        z = 2 * np.pi * (xp.asarray(phases) - loc)
        if xp is np:
            from scipy.special import i0e as np_i0e

            return np.exp(kappa * (np.cos(z) - 1.0)) / np_i0e(kappa)
        return jnp.exp(kappa * (jnp.cos(z) - 1.0)) / i0e(kappa)

    def random(self, n, rng=None):
        rng = rng or np.random.default_rng()
        kappa = 1.0 / (2 * np.pi * self.p[0]) ** 2
        draw = rng.vonmises(0.0, kappa, n) / (2 * np.pi)
        return (self.p[1] + draw) % 1.0


class LCTopHat(LCPrimitive):
    """Top hat of given width centered at location (host-side only shape)."""

    name = "TopHat"
    pnames = ["Width", "Location"]
    p0 = [0.1, 0.5]

    def hwhm(self, right=False):
        return float(self.p[0]) / 2

    def _pdf(self, phases, p):
        import jax.numpy as jnp

        xp = jnp if not isinstance(phases, np.ndarray) else np
        width, loc = p[0], p[1]
        z = (xp.asarray(phases) - loc + 0.5) % 1.0 - 0.5
        return xp.where(xp.abs(z) <= width / 2, 1.0 / width, 0.0)

    def random(self, n, rng=None):
        rng = rng or np.random.default_rng()
        w, loc = self.p
        return (loc + (rng.random(n) - 0.5) * w) % 1.0


class LCKing(LCPrimitive):
    """Wrapped King-function peak: p = [sigma, gamma, location] (reference
    ``lcprimitives.py:1250 LCKing``): (1+z^2/(2 s^2 g))^-g with the
    (g-1)/g normalization of the unwrapped profile."""

    name = "King"
    pnames = ["Sigma", "Gamma", "Location"]
    p0 = [0.03, 5.0, 0.5]

    def hwhm(self, right=False):
        s, g, _ = self.p
        # solve (1+u/g)^-g = 1/2 for u = z^2/(2 s^2)
        u = g * (2.0 ** (1.0 / g) - 1.0)
        return float(np.sqrt(2.0 * u) * s)

    def _pdf(self, phases, p):
        import jax.numpy as jnp

        xp = jnp if not isinstance(phases, np.ndarray) else np
        s, g, loc = p[0], p[1], p[2]
        z0 = (xp.asarray(phases) - loc + 0.5) % 1.0 - 0.5
        out = 0.0
        for k in range(-_NWRAP, _NWRAP + 1):
            u = 0.5 * ((z0 + k) / s) ** 2
            out = out + (1.0 + u / g) ** (-g)
        # normalize the infinite-domain profile: int (1+u/g)^-g dz
        # = s sqrt(2 pi g) Gamma(g-1/2)/Gamma(g)  (exact); gammaln from the
        # active backend so traced parameters stay jit/grad-compatible
        if xp is np:
            from scipy.special import gammaln
        else:
            from jax.scipy.special import gammaln

        norm = s * xp.sqrt(2 * np.pi * g) * xp.exp(
            gammaln(g - 0.5) - gammaln(g))
        return out / norm


class LCHarmonic(LCPrimitive):
    """A single Fourier harmonic, 1 + 2 cos(2 pi k (phi - loc)): p = [loc]
    (reference ``lcprimitives.py:1336 LCHarmonic``).  Integrates to 1 over a
    cycle by construction; ``order`` selects the harmonic number."""

    name = "Harmonic"
    pnames = ["Location"]
    p0 = [0.0]
    mixture_safe = False  # pdf dips negative; only the sum is a density

    def __init__(self, p=None, order: int = 1):
        super().__init__(p)
        self.order = int(order)

    def hwhm(self, right=False):
        return 0.25 / self.order

    def _pdf(self, phases, p):
        import jax.numpy as jnp

        xp = jnp if not isinstance(phases, np.ndarray) else np
        loc = p[0]
        return 1.0 + 2.0 * xp.cos((2 * np.pi * self.order)
                                  * (xp.asarray(phases) - loc))


class LCEmpiricalFourier(LCPrimitive):
    """Empirical Fourier light-curve representation; only parameter is an
    overall phase shift (reference ``lcprimitives.py:1361``).  Cannot be
    mixed with other primitives.  Build from photon phases or a stored
    two-column (alpha, beta) coefficient file."""

    name = "EmpiricalFourier"
    pnames = ["Shift"]
    p0 = [0.0]
    mixture_safe = False  # truncated Fourier sums can dip negative

    def __init__(self, phases=None, input_file=None, nharm: int = 20):
        super().__init__([0.0])
        self.nharm = int(nharm)
        self.alphas = np.zeros(self.nharm)
        self.betas = np.zeros(self.nharm)
        if input_file is not None:
            self.from_file(input_file)
        if phases is not None:
            self.from_phases(phases)

    def from_phases(self, phases):
        phases = np.asarray(phases, dtype=np.float64)
        ks = 2 * np.pi * np.arange(1, self.nharm + 1)
        self.alphas = np.cos(ks[:, None] * phases[None, :]).mean(axis=1)
        self.betas = np.sin(ks[:, None] * phases[None, :]).mean(axis=1)

    def from_file(self, input_file):
        rows = []
        with open(input_file) as f:
            for line in f:
                ln = line.strip()
                if not ln or ln.startswith("#"):
                    continue
                tok = ln.split()
                if len(tok) == 2:
                    rows.append((float(tok[0]), float(tok[1])))
        if not rows:
            raise ValueError(f"No Fourier coefficients in {input_file}")
        arr = np.asarray(rows)
        self.alphas, self.betas = arr[:, 0], arr[:, 1]
        self.nharm = len(rows)

    def to_file(self, output_file):
        with open(output_file, "w") as f:
            f.write("# fourier\n")
            for a, b in zip(self.alphas, self.betas):
                f.write(f"{a}\t{b}\n")

    def _pdf(self, phases, p):
        import jax.numpy as jnp

        xp = jnp if not isinstance(phases, np.ndarray) else np
        shift = p[0]
        ks = xp.asarray(2 * np.pi * np.arange(1, self.nharm + 1))
        # shift theorem on the real coefficient pairs (xp ops so a traced
        # shift parameter stays jit/grad-compatible)
        c, s = xp.cos(ks * shift), xp.sin(ks * shift)
        a = c * xp.asarray(self.alphas) - s * xp.asarray(self.betas)
        b = s * xp.asarray(self.alphas) + c * xp.asarray(self.betas)
        ph = xp.asarray(phases)
        out = 1.0 + 2.0 * xp.sum(a[:, None] * xp.cos(ks[:, None] * ph[None, :])
                                 + b[:, None] * xp.sin(ks[:, None] * ph[None, :]),
                                 axis=0)
        return out

    def integrate(self, x1=0.0, x2=1.0, simps=512):
        if (x1, x2) == (0.0, 1.0):
            return 1.0  # Fourier norm is exact by construction
        return super().integrate(x1, x2, simps)


class LCKernelDensity(LCPrimitive):
    """Wrapped gaussian kernel-density estimate of the light curve; only
    parameter is an overall phase shift (reference ``lcprimitives.py:1456``).
    Cannot be mixed with other primitives.  The empirical bandwidth follows
    Silverman's rule on the circular standard deviation, floored to resolve
    narrow peaks; the grid-sampled estimate is renormalized exactly."""

    name = "KernelDensity"
    pnames = ["Shift"]
    p0 = [0.0]

    def __init__(self, phases=None, bw: float = None, ngrid: int = 512):
        super().__init__([0.0])
        self.ngrid = int(ngrid)
        self.bw = bw  # user-supplied bandwidth, or None for per-fit auto
        self.bw_used = None  # bandwidth of the latest from_phases fit
        self.grid = np.linspace(0.0, 1.0, self.ngrid, endpoint=False)
        self.vals = np.ones(self.ngrid)
        if phases is not None:
            self.from_phases(phases)

    def from_phases(self, phases):
        phases = np.asarray(phases, dtype=np.float64) % 1.0
        n = len(phases)
        bw = self.bw
        if bw is None:
            # circular std via resultant length; re-estimated per dataset
            C = np.cos(2 * np.pi * phases).mean()
            S = np.sin(2 * np.pi * phases).mean()
            R = np.hypot(C, S)
            circ_std = np.sqrt(-2 * np.log(max(R, 1e-12))) / (2 * np.pi)
            bw = max(1.06 * circ_std * n ** (-0.2), 0.5 / self.ngrid)
        self.bw_used = bw
        # wrapped-gaussian KDE evaluated on the grid (vectorized, 3 wraps)
        d = (self.grid[:, None] - phases[None, :] + 0.5) % 1.0 - 0.5
        k = np.exp(-0.5 * (d / bw) ** 2)
        for w in (-1.0, 1.0):
            k += np.exp(-0.5 * ((d + w) / bw) ** 2)
        vals = k.sum(axis=1) / (n * bw * np.sqrt(2 * np.pi))
        self.vals = vals / np.mean(vals)  # exact unit integral on the grid

    def _pdf(self, phases, p):
        import jax.numpy as jnp

        xp = jnp if not isinstance(phases, np.ndarray) else np
        z = (xp.asarray(phases) - p[0]) % 1.0
        idx = z * self.ngrid
        i0 = xp.floor(idx).astype(int) % self.ngrid
        i1 = (i0 + 1) % self.ngrid
        frac = idx - xp.floor(idx)
        vals = xp.asarray(self.vals)
        return vals[i0] * (1 - frac) + vals[i1] * frac


class LCWrappedFunction(LCPrimitive):
    """Base for profiles defined by wrapping an infinite-support density
    (reference ``lcprimitives.py:559 LCWrappedFunction``).

    Subclasses provide ``base_func(phases, p, index)`` — the unwrapped
    density evaluated at ``phases + index`` — and optionally
    ``base_int(x1, x2, p)``, its exact integral.  ``_pdf`` sums image terms
    over a fixed +-``_NWRAP`` window (trace-static, jit-friendly — the
    reference instead iterates to convergence, which is data-dependent
    control flow) and, when ``base_int`` is available and the evaluation is
    host-side, adds the truncated tail back as a uniform component so the
    wrapped density still integrates to exactly 1 (the reference's
    normalization adjustment).
    """

    def base_func(self, phases, p, index=0):
        raise NotImplementedError

    def base_int(self, x1, x2, p):
        return None

    def _pdf(self, phases, p):
        xp = _np_or_jnp(phases)
        z = xp.asarray(phases) % 1.0
        out = 0.0
        for k in range(-_NWRAP, _NWRAP + 1):
            out = out + self.base_func(z, p, index=k)
        if xp is np:
            covered = self.base_int(-_NWRAP, _NWRAP + 1, p)
            if covered is not None:
                out = out + (1.0 - covered)  # uniform remainder
        return out


class LCSkewGaussian(LCWrappedFunction):
    """Wrapped skew-normal peak: p = [width, shape, location] (reference
    ``lcprimitives.py:858 LCSkewGaussian``).  ``shape`` > 0 skews right;
    shape = 0 reduces exactly to :class:`LCGaussian`.  ``location`` is the
    location parameter of the skew-normal (not its mode)."""

    name = "SkewGaussian"
    pnames = ["Width", "Shape", "Location"]
    p0 = [0.03, 0.0, 0.5]

    def base_func(self, phases, p, index=0):
        xp = _np_or_jnp(phases)
        if xp is np:
            from scipy.special import erf
        else:
            from jax.scipy.special import erf
        width, shape, x0 = p[0], p[1], p[2]
        z = (xp.asarray(phases) + index - x0) / width
        return (1.0 / (width * math.sqrt(2 * math.pi))) \
            * xp.exp(-0.5 * z * z) * (1.0 + erf(shape * z / math.sqrt(2.0)))

    def base_int(self, x1, x2, p):
        from scipy.stats import skewnorm

        width, shape, x0 = p[0], p[1], p[2]  # scalars, or per-photon columns
        return np.asarray(skewnorm.cdf(x2, shape, loc=x0, scale=width)
                          - skewnorm.cdf(x1, shape, loc=x0, scale=width))

    def get_location(self) -> float:
        return float(self.p[2])

    def set_location(self, loc: float):
        self.p[2] = loc % 1.0

    def hwhm(self, right: bool = False) -> float:
        """Numeric HWHM about the mode (no closed form for skew normal)."""
        g = np.linspace(0, 1, 4096, endpoint=False)
        y = np.asarray(self(g))
        imax = int(np.argmax(y))
        half = y[imax] / 2.0
        d = (g - g[imax] + 0.5) % 1.0 - 0.5
        sel = (d > 0) if right else (d < 0)
        below = sel & (y < half)
        if not np.any(below):
            return 0.25
        return float(np.min(np.abs(d[below])))

    def random(self, n: int, rng=None) -> np.ndarray:
        """Exact skew-normal sampling: z = delta|u| + sqrt(1-delta^2) v with
        (u, v) iid standard normal, delta = shape/sqrt(1+shape^2)."""
        rng = rng or np.random.default_rng()
        width, shape, x0 = self.p
        delta = shape / math.sqrt(1.0 + shape * shape)
        u = np.abs(rng.standard_normal(n))
        v = rng.standard_normal(n)
        z = delta * u + math.sqrt(1.0 - delta * delta) * v
        return (x0 + width * z) % 1.0


class FastBessel:
    """Fast modified Bessel function I_nu via log-log interpolation with
    the exact asymptotic tail (reference ``lcprimitives.py:1675``): the
    von-Mises normalization 1/(2 pi I0(kappa)) is evaluated millions of
    times in photon likelihoods, and scipy's i0 overflows past x ~ 700
    where log I_nu(x) ~ x - log(sqrt(2 pi x)) + log(1 + (4 nu^2 - 1)/8x)
    is already exact to float precision."""

    def __init__(self, order: int = 0):
        if order not in (0, 1):
            raise NotImplementedError("orders 0 and 1 only")
        from scipy.special import i0, i1

        self.order = order
        x = np.logspace(-1, 3.5, 20001)
        safe = x < 700
        logy = np.empty_like(x)
        logy[safe] = np.log((i0 if order == 0 else i1)(x[safe]))
        xt = x[~safe]
        logy[~safe] = xt - 0.5 * np.log(2 * np.pi * xt) \
            + np.log1p((4 * order**2 - 1) / (8 * xt))
        self._logx = np.log(x)
        self._logy = logy

    def __call__(self, x):
        return np.exp(self.log(x))

    def log(self, x):
        """log I_nu(x): stays finite far beyond the float overflow of
        I_nu itself (x > ~709), which is the form likelihoods want.
        Outside the table the exact limits take over — the asymptotic
        expansion above, the small-x series below (np.interp would
        otherwise CLAMP to the edge values, wildly wrong for large x)."""
        x = np.asarray(x, dtype=np.float64)
        out = np.interp(np.log(np.maximum(x, 1e-300)), self._logx,
                        self._logy)
        lo, hi = np.exp(self._logx[0]), np.exp(self._logx[-1])
        nu = self.order
        big = x > hi
        if np.any(big):
            xb = x[big] if x.ndim else x
            asym = xb - 0.5 * np.log(2 * np.pi * xb) \
                + np.log1p((4 * nu**2 - 1) / (8 * xb))
            out = np.where(np.asarray(big), asym, out) if x.ndim \
                else float(asym)
        small = x < lo
        if np.any(small):
            xs = x[small] if x.ndim else x
            # I0 ~ 1 + x^2/4, I1 ~ x/2 (1 + x^2/8)
            ser = np.log1p(xs * xs / 4) if nu == 0 \
                else np.log(xs / 2) + np.log1p(xs * xs / 8)
            out = np.where(np.asarray(small), ser, out) if x.ndim \
                else float(ser)
        return out


def two_comp_mc(n, w1, w2, loc, func, rng=None):
    """Monte-Carlo photon phases from a two-sided peak (reference
    ``lcprimitives.py:45 two_comp_mc``): draw from ``func`` (a scipy-style
    ``rvs(loc=, scale=, size=)``) with left scale ``w1`` / right scale
    ``w2``, folding each draw onto its side of ``loc``; side membership is
    Bernoulli in w1/(w1+w2) so the composite density is continuous."""
    rng = rng or np.random.default_rng()
    w1, w2 = float(w1), float(w2)
    n1 = int(np.sum(rng.random(n) < w1 / (w1 + w2)))
    left = np.asarray(func(loc=0.0, scale=w1, size=n1))
    left = loc - np.abs(left)
    right = np.asarray(func(loc=0.0, scale=w2, size=n - n1))
    right = loc + np.abs(right)
    return np.concatenate([left, right]) % 1.0


def convert_primitive(p1: LCPrimitive, ptype=LCLorentzian) -> LCPrimitive:
    """Build a primitive of another type with matched location and HWHM
    (reference ``lcprimitives.py:1607 convert_primitive``).  Supported
    targets are the width+location families (Gaussian/Lorentzian/VonMises/
    TopHat and the two-sided variants); anything else raises."""
    one_sided = (LCGaussian, LCLorentzian, LCVonMises, LCTopHat)
    two_sided = (LCGaussian2, LCLorentzian2)
    if ptype not in one_sided + two_sided:
        raise ValueError(
            f"convert_primitive cannot target {ptype.__name__}: only "
            "width+location shapes have a well-defined HWHM mapping")
    loc = p1.get_location()
    if p1.is_two_sided():
        h1, h2 = p1.hwhm(False), p1.hwhm(True)
    else:
        h1 = h2 = p1.hwhm()

    def width_from_hwhm(h):
        if ptype in (LCLorentzian, LCLorentzian2):
            return h  # gamma is the HWHM
        if ptype is LCTopHat:
            return 2 * h
        return h / math.sqrt(2 * math.log(2))  # gaussian-like sigma

    if ptype in two_sided:
        return ptype([width_from_hwhm(h1), width_from_hwhm(h2), loc])
    return ptype([width_from_hwhm(0.5 * (h1 + h2)), loc])


def approx_gradient(prim: LCPrimitive, phases, eps: float = 1e-6) -> np.ndarray:
    """Numeric d(pdf)/d(params) matrix (nparam, nphase) (reference
    ``lcprimitives.py:74``)."""
    phases = np.asarray(phases, dtype=np.float64)
    out = []
    for i in range(len(prim.p)):
        hi = prim.p.copy()
        lo = prim.p.copy()
        hi[i] += eps / 2
        lo[i] -= eps / 2
        out.append((np.asarray(prim._pdf(phases, hi))
                    - np.asarray(prim._pdf(phases, lo))) / eps)
    return np.asarray(out)


def check_gradient(prim: LCPrimitive, n: int = 100, seed: int = 0,
                   atol: float = 1e-5, rtol: float = 1e-4) -> bool:
    """Cross-check the jax autodiff gradient of the pdf against numeric
    differencing (reference ``lcprimitives.py:146 check_gradient``; here the
    analytic side is jacfwd of the same jnp evaluation core)."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    phases = rng.random(n)
    num = approx_gradient(prim, phases)
    ana = jax.jacfwd(lambda p: prim._pdf(jnp.asarray(phases), p))(
        jnp.asarray(prim.p))
    ana = np.asarray(ana).T
    return np.allclose(ana, num, atol=atol, rtol=rtol)


#: reference re-export (each template module offers isvector)
from pint_tpu.templates.lcnorm import isvector  # noqa: E402,F401
