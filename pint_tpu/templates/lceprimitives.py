"""Energy-dependent light-curve primitives (reference ``templates/lceprimitives.py``).

A peak's parameters drift linearly in log10(energy) about a reference
energy: ``p_i(E) = p_i + slope_i * (log10(E) - log10(E0))``, with widths
kept positive.  Evaluation takes (phases, log10_ens) pairs — each photon
carries its own energy — which is the form the Fermi-LAT weighted-photon
likelihood consumes.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pint_tpu.templates.lcprimitives import (LCGaussian, LCGaussian2, LCSkewGaussian,
                                             LCLorentzian, LCLorentzian2,
                                             LCPrimitive, LCVonMises)

__all__ = ["LCEPrimitive", "LCEGaussian", "LCEGaussian2", "LCESkewGaussian",
           "LCEWrappedFunction", "edep_gradient",
           "LCELorentzian",
           "LCELorentzian2", "LCEVonMises"]


class LCEPrimitive(LCPrimitive):
    """Wraps a primitive shape with per-parameter log-energy slopes.

    Parameter vector: [base parameters..., slopes...].  ``E0`` (MeV) sets
    the pivot energy at which the base parameters apply.
    """

    base_cls = LCPrimitive

    def __init__(self, p=None, slopes=None, e0_mev: float = 1000.0):
        base = self.base_cls(p)
        nb = len(base.p)
        slopes = np.zeros(nb) if slopes is None else np.asarray(
            slopes, dtype=np.float64)
        if len(slopes) != nb:
            raise ValueError("one slope per base parameter required")
        self.nb = nb
        self.e0 = float(e0_mev)
        self.p = np.concatenate([base.p, slopes])
        self.free = np.ones_like(self.p, dtype=bool)
        self.pnames = list(self.base_cls.pnames) + [
            f"Slope_{n}" for n in self.base_cls.pnames]

    def is_energy_dependent(self) -> bool:
        return True

    def _base_at_current(self):
        """A base-class primitive carrying this primitive's CURRENT base
        parameters — shape queries (hwhm, two-sidedness) must come from
        the base shape, not LCPrimitive defaults."""
        b = self.base_cls()
        b.p = np.asarray(self.p[:self.nb], dtype=np.float64).copy()
        return b

    def is_two_sided(self) -> bool:
        return self._base_at_current().is_two_sided()

    def hwhm(self, right: bool = False) -> float:
        return self._base_at_current().hwhm(right=right)

    def get_location(self) -> float:
        return float(self.p[self.nb - 1])

    def set_location(self, loc: float):
        self.p[self.nb - 1] = loc % 1.0

    #: base-parameter columns clamped positive along the energy track;
    #: None means every column but the trailing location (width-like
    #: shapes).  Subclasses with sign-free shape parameters narrow this.
    clamp_cols = None

    def parameters_at(self, log10_ens) -> np.ndarray:
        """(..., nb) effective base parameters at the given energies."""
        le = np.asarray(log10_ens, dtype=np.float64)
        dle = le - np.log10(self.e0)
        base, slopes = self.p[:self.nb], self.p[self.nb:]
        out = base[None, :] + np.atleast_1d(dle)[:, None] * slopes[None, :]
        # width-like columns must stay positive at every energy
        cols = range(self.nb - 1) if self.clamp_cols is None \
            else self.clamp_cols
        for c in cols:
            out[:, c] = np.maximum(out[:, c], 1e-4)
        return out

    def __call__(self, phases, log10_ens=None):
        if log10_ens is None:
            return self.base_cls._pdf(self, np.asarray(phases), self.p[:self.nb])
        phases = np.atleast_1d(np.asarray(phases, dtype=np.float64))
        pars = self.parameters_at(log10_ens)
        if pars.shape[0] == 1:
            return self.base_cls._pdf(self, phases, pars[0])
        # one vectorized evaluation: the _pdf bodies index p[i] and broadcast
        # elementwise, so per-photon parameter COLUMNS evaluate all photons
        # at their own energies in one pass (Fermi data: all energies unique)
        return np.asarray(self.base_cls._pdf(
            self, phases, [pars[:, i] for i in range(self.nb)]))


class LCEGaussian(LCEPrimitive):
    """Energy-dependent wrapped Gaussian (reference LCEGaussian)."""

    base_cls = LCGaussian
    name = "EGaussian"


class LCELorentzian(LCEPrimitive):
    base_cls = LCLorentzian
    name = "ELorentzian"


class LCEVonMises(LCEPrimitive):
    base_cls = LCVonMises
    name = "EVonMises"


#: reference re-export (each template module offers isvector)
from pint_tpu.templates.lcnorm import isvector  # noqa: E402,F401


class LCEGaussian2(LCEPrimitive):
    """Energy-dependent two-sided Gaussian (reference LCEGaussian2)."""

    base_cls = LCGaussian2
    name = "EGaussian2"


class LCELorentzian2(LCEPrimitive):
    """Energy-dependent two-sided Lorentzian (reference LCELorentzian2)."""

    base_cls = LCLorentzian2
    name = "ELorentzian2"


def edep_gradient(prim, phases, log10_ens=None, eps: float = 1e-6):
    """Numeric d(pdf)/d(params) for an energy-dependent primitive over its
    FULL parameter vector [base..., slopes...] (reference
    ``lceprimitives.py:8 edep_gradient``; this is a linear model, so the
    slope rows are the base rows weighted by dlog10(E) — computed here by
    differencing the same evaluation path the likelihood uses, which also
    respects the positivity clamp's saturated-gradient zeroing)."""
    phases = np.asarray(phases, dtype=np.float64)
    out = []
    for i in range(len(prim.p)):
        hi, lo = prim.p.copy(), prim.p.copy()
        hi[i] += eps / 2
        lo[i] -= eps / 2
        save = prim.p
        try:
            prim.p = hi
            vp = np.asarray(prim(phases, log10_ens))
            prim.p = lo
            vm = np.asarray(prim(phases, log10_ens))
        finally:
            prim.p = save
        out.append((vp - vm) / eps)
    return np.asarray(out)


class LCEWrappedFunction(LCEPrimitive):
    """Energy-dependent base for wrapped-function shapes (reference
    ``lceprimitives.py:150 LCEWrappedFunction``): subclasses set
    ``base_cls`` to an :class:`~pint_tpu.templates.lcprimitives
    .LCWrappedFunction` shape, whose ``base_func``/``base_int`` hooks are
    pulled onto this class so the wrapped ``_pdf`` resolves here too."""

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        if hasattr(cls.base_cls, "base_func"):
            cls.base_func = cls.base_cls.base_func
            cls.base_int = cls.base_cls.base_int

    def gradient(self, phases, log10_ens=None, free: bool = False):
        g = edep_gradient(self, phases, log10_ens)
        return g[self.free] if free else g


class LCESkewGaussian(LCEWrappedFunction):
    """Energy-dependent wrapped skew-normal (reference
    ``lceprimitives.py LCESkewGaussian``): [width, shape, location] base
    parameters plus one log-energy slope each."""

    base_cls = LCSkewGaussian
    name = "ESkewGaussian"
    clamp_cols = (0,)  # width only: Shape is legitimately signed
