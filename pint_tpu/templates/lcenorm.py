"""Energy-dependent component normalizations (reference ``templates/lcenorm.py``).

The normalization angles drift linearly in log10(energy) about a pivot
energy, exactly parallel to :class:`LCEPrimitive`:
``a_i(E) = a_i + slope_i * (log10(E) - log10(E0))``.
"""

from __future__ import annotations

import numpy as np

from pint_tpu.templates.lcnorm import NormAngles

__all__ = ["ENormAngles"]


class ENormAngles(NormAngles):
    def __init__(self, norms, slopes=None, e0_mev: float = 1000.0):
        super().__init__(norms)
        self.e0 = float(e0_mev)
        self.slopes = (np.zeros(self.dim) if slopes is None
                       else np.asarray(slopes, dtype=np.float64))
        if len(self.slopes) != self.dim:
            raise ValueError("one slope per norm angle required")
        # parameter vector: [angles..., slopes...]
        self.p = np.concatenate([self.p, self.slopes])
        self.free = np.ones(2 * self.dim, dtype=bool)

    def is_energy_dependent(self) -> bool:
        return True

    def __call__(self, log10_ens=None) -> np.ndarray:
        angles, slopes = self.p[:self.dim], self.p[self.dim:]
        if log10_ens is None:
            return self._angles_to_norms(angles)
        le = np.atleast_1d(np.asarray(log10_ens, dtype=np.float64))
        dle = le - np.log10(self.e0)
        a = angles[None, :] + dle[:, None] * slopes[None, :]
        # row-wise spherical map, vectorized over photons
        s2 = np.sin(a) ** 2
        c2 = np.cos(a) ** 2
        prod = np.concatenate(
            [np.ones((len(le), 1)), np.cumprod(c2, axis=1)[:, :-1]], axis=1)
        out = s2 * prod
        return out[0] if np.isscalar(log10_ens) else out

    def num_parameters(self, free: bool = True) -> int:
        return int(self.free.sum()) if free else len(self.p)

    def set_single_norm(self, index: int, value: float):
        norms = self._angles_to_norms(self.p[:self.dim])
        norms[index] = value
        if norms.sum() > 1:
            raise ValueError("norms would sum to > 1")
        self.p[:self.dim] = self._norms_to_angles(norms)

    def __repr__(self):
        return (f"ENormAngles(norms={self._angles_to_norms(self.p[:self.dim])!r}, "
                f"slopes={self.p[self.dim:]!r})")


#: reference re-export (each template module offers isvector)
from pint_tpu.templates.lcnorm import isvector  # noqa: E402,F401
