"""Normalization of light-curve component weights.

Counterpart of reference ``templates/lcnorm.py NormAngles``: the n component
weights (each in [0,1], summing to <= 1, remainder = uniform background) are
parameterized by n angles so unconstrained optimizers can fit them.  Using
the same spherical parameterization as the reference:

    norm_i = cos^2(a_1) ... cos^2(a_{i-1}) sin^2(a_i) ... (product chain)

which maps R^n -> the simplex interior.
"""

from __future__ import annotations

import numpy as np

__all__ = ["NormAngles"]


def isvector(x):
    """True when x has at least one array dimension (reference
    ``templates/lcnorm.py:16``; re-exported across the template modules
    there)."""
    import numpy as _np

    return len(_np.asarray(x).shape) > 0


class NormAngles:
    def __init__(self, norms):
        norms = np.asarray(norms, dtype=np.float64)
        if norms.sum() > 1.0:
            raise ValueError("Provided norms sum to > 1")
        self.dim = len(norms)
        self.p = self._norms_to_angles(norms)
        self.free = np.ones(self.dim, dtype=bool)

    # -- mapping -------------------------------------------------------------
    @staticmethod
    def _angles_to_norms(angles):
        """sin^2(a_i) * prod_{j<i} cos^2(a_j)."""
        s2 = np.sin(angles) ** 2
        c2 = np.cos(angles) ** 2
        prod = np.concatenate([[1.0], np.cumprod(c2)[:-1]])
        return s2 * prod

    @staticmethod
    def _norms_to_angles(norms):
        angles = np.empty(len(norms))
        rem = 1.0
        for i, n in enumerate(norms):
            frac = 0.0 if rem <= 0 else min(n / rem, 1.0)
            angles[i] = np.arcsin(np.sqrt(frac))
            rem -= n
        return angles

    # -- API -----------------------------------------------------------------
    def __call__(self) -> np.ndarray:
        return self._angles_to_norms(self.p)

    def copy(self) -> "NormAngles":
        import copy as _copy

        return _copy.deepcopy(self)

    def get_total(self) -> float:
        """Sum of the amplitudes (reference ``lcnorm.py get_total``)."""
        return float(self().sum())

    def set_total(self, total: float) -> None:
        """Rescale the amplitudes to the given sum (reference
        ``lcnorm.py set_total``)."""
        if not 0.0 <= total <= 1.0:
            # same domain the constructor enforces; silently clamping
            # would destroy the amplitude ratios
            raise ValueError(f"total must be within [0, 1], got {total}")
        cur = self.get_total()
        if cur <= 0:
            raise ValueError("cannot rescale zero-amplitude norms")
        self.p[:self.dim] = self._norms_to_angles(
            self._angles_to_norms(self.p[:self.dim]) * (total / cur))

    def get_free_mask(self) -> np.ndarray:
        return np.asarray(self.free, dtype=bool)

    def get_parameter_names(self, free: bool = True) -> list:
        idx = np.nonzero(self.free)[0] if free else range(len(self.p))
        return [f"Ang{i + 1}" for i in idx]

    def get_bounds(self) -> list:
        """[(lo, hi)] per free angle (angles live in [0, pi/2])."""
        return [(0.0, np.pi / 2)] * int(np.sum(self.free))

    def get_errors(self, free: bool = True) -> np.ndarray:
        e = getattr(self, "errors", np.zeros_like(self.p))
        return e[self.free] if free else e

    def set_errors(self, errs, free: bool = True) -> None:
        """Store parameter errors; a free-length vector scatters into the
        full-length store so :meth:`get_errors` masks consistently."""
        errs = np.asarray(errs, dtype=np.float64)
        if free and len(errs) != len(self.p):
            full = np.zeros_like(self.p)
            full[self.free] = errs
            errs = full
        self.errors = errs

    def is_energy_dependent(self) -> bool:
        return False

    def gradient(self, log10_ens=None, free: bool = True,
                 eps: float = 1e-7) -> np.ndarray:
        """(n_norm, n_param) finite-difference d(amplitudes)/d(angles)
        (reference ``lcnorm.py gradient`` is analytic; FD here).  With
        per-photon energies the energy-averaged gradient is returned."""
        p0 = self.get_parameters(free=free).copy()

        def amps():
            if log10_ens is None:
                return np.asarray(self())
            if not self.is_energy_dependent():
                raise TypeError(
                    "log10_ens given but these norms are not "
                    "energy-dependent (use ENormAngles)")
            v = np.asarray(self(log10_ens))
            return v if v.ndim == 1 else v.mean(axis=0)

        out = np.empty((self.dim, len(p0)))
        for i in range(len(p0)):
            pp = p0.copy()
            pp[i] += eps
            self.set_parameters(pp, free=free)
            hi = amps()
            pp[i] -= 2 * eps
            self.set_parameters(pp, free=free)
            lo = amps()
            out[:, i] = (hi - lo) / (2 * eps)
            self.set_parameters(p0, free=free)
        return out

    def sanity_checks(self) -> bool:
        return bool(np.all(np.isfinite(self.p)))

    def get_parameters(self, free: bool = True) -> np.ndarray:
        return self.p[self.free] if free else self.p.copy()

    def set_parameters(self, p, free: bool = True):
        if free:
            self.p[self.free] = p
        else:
            self.p[:] = p

    def num_parameters(self, free: bool = True) -> int:
        return int(self.free.sum()) if free else self.dim

    def set_single_norm(self, index: int, value: float):
        norms = self()
        norms[index] = value
        if norms.sum() > 1:
            raise ValueError("norms would sum to > 1")
        self.p = self._norms_to_angles(norms)

    def __repr__(self):
        return f"NormAngles(norms={self()!r})"


def numerical_gradient(fn, x0, eps: float = 1e-6) -> np.ndarray:
    """Central-difference gradient of a scalar/vector function (reference
    ``lcnorm.py numerical_gradient``)."""
    x0 = np.asarray(x0, dtype=np.float64)
    cols = []
    for i in range(len(x0)):
        xp = x0.copy()
        xp[i] += eps
        hi = np.asarray(fn(xp))
        xp[i] -= 2 * eps
        lo = np.asarray(fn(xp))
        cols.append((hi - lo) / (2 * eps))
    return np.array(cols)


def numerical_hessian(fn, x0, eps: float = 1e-4):
    """Central-difference Hessian of a scalar function (reference
    ``lcnorm.py numerical_hessian``) — thin wrapper over the package's
    one implementation in :func:`pint_tpu.templates.lcfitters.hessian`."""
    from pint_tpu.templates.lcfitters import hessian

    return hessian(fn, x0, eps=eps)
