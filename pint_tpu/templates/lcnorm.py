"""Normalization of light-curve component weights.

Counterpart of reference ``templates/lcnorm.py NormAngles``: the n component
weights (each in [0,1], summing to <= 1, remainder = uniform background) are
parameterized by n angles so unconstrained optimizers can fit them.  Using
the same spherical parameterization as the reference:

    norm_i = cos^2(a_1) ... cos^2(a_{i-1}) sin^2(a_i) ... (product chain)

which maps R^n -> the simplex interior.
"""

from __future__ import annotations

import numpy as np

__all__ = ["NormAngles"]


def isvector(x):
    """True when x has at least one array dimension (reference
    ``templates/lcnorm.py:16``; re-exported across the template modules
    there)."""
    import numpy as _np

    return len(_np.asarray(x).shape) > 0


class NormAngles:
    def __init__(self, norms):
        norms = np.asarray(norms, dtype=np.float64)
        if norms.sum() > 1.0:
            raise ValueError("Provided norms sum to > 1")
        self.dim = len(norms)
        self.p = self._norms_to_angles(norms)
        self.free = np.ones(self.dim, dtype=bool)

    # -- mapping -------------------------------------------------------------
    @staticmethod
    def _angles_to_norms(angles):
        """sin^2(a_i) * prod_{j<i} cos^2(a_j)."""
        s2 = np.sin(angles) ** 2
        c2 = np.cos(angles) ** 2
        prod = np.concatenate([[1.0], np.cumprod(c2)[:-1]])
        return s2 * prod

    @staticmethod
    def _norms_to_angles(norms):
        angles = np.empty(len(norms))
        rem = 1.0
        for i, n in enumerate(norms):
            frac = 0.0 if rem <= 0 else min(n / rem, 1.0)
            angles[i] = np.arcsin(np.sqrt(frac))
            rem -= n
        return angles

    # -- API -----------------------------------------------------------------
    def __call__(self) -> np.ndarray:
        return self._angles_to_norms(self.p)

    def get_parameters(self, free: bool = True) -> np.ndarray:
        return self.p[self.free] if free else self.p.copy()

    def set_parameters(self, p, free: bool = True):
        if free:
            self.p[self.free] = p
        else:
            self.p[:] = p

    def num_parameters(self, free: bool = True) -> int:
        return int(self.free.sum()) if free else self.dim

    def set_single_norm(self, index: int, value: float):
        norms = self()
        norms[index] = value
        if norms.sum() > 1:
            raise ValueError("norms would sum to > 1")
        self.p = self._norms_to_angles(norms)

    def __repr__(self):
        return f"NormAngles(norms={self()!r})"
