"""LCTemplate: a normalized pulse-profile model — mixture of primitives plus
uniform background.

Counterpart of reference ``templates/lctemplate.py LCTemplate`` (mixture
evaluation, parameter get/set across primitives + norms, random draws,
gaussian-template-file IO compatible with pygaussfit output).  The
evaluation core is jnp-compatible so the photon likelihood
``sum log(w * f(phi) + (1-w))`` jits and vmaps over walkers.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from pint_tpu.templates.lcnorm import NormAngles
from pint_tpu.templates.lcprimitives import LCGaussian, LCPrimitive

__all__ = ["LCTemplate", "prim_io", "make_twoside_gaussian"]


class LCTemplate:
    def __init__(self, primitives: List[LCPrimitive], norms):
        self.primitives = list(primitives)
        self.norms = norms if isinstance(norms, NormAngles) else NormAngles(norms)
        if self.norms.dim != len(self.primitives):
            raise ValueError("One norm per primitive required")

    def is_energy_dependent(self) -> bool:
        return any(getattr(x, "is_energy_dependent", lambda: False)()
                   for x in list(self.primitives) + [self.norms])

    # -- evaluation ----------------------------------------------------------
    def __call__(self, phases, log10_ens=None, suppress_bg: bool = False):
        """Template density at the given phases; with ``log10_ens`` each
        photon is evaluated at its own energy (energy-dependent primitives /
        norms drift their parameters; reference ``lceprimitives.py`` /
        ``lcenorm.py`` semantics)."""
        if log10_ens is None:
            log10_ens = getattr(self, "_fixed_log10_en", None)
        if log10_ens is None:
            norms = self.norms()
            bg = 1.0 - norms.sum()
            out = bg if not suppress_bg else 0.0
            for n, prim in zip(norms, self.primitives):
                out = out + n * prim(phases)
            if suppress_bg:
                out = out / norms.sum()
            return out
        phases = np.atleast_1d(np.asarray(phases, dtype=np.float64))
        try:
            norms = self.norms(log10_ens)  # (N, ncomp) if energy-dependent
        except TypeError:
            norms = np.broadcast_to(self.norms(), (len(phases),
                                                   self.norms.dim))
        norms = np.atleast_2d(norms)
        bgsum = norms.sum(axis=1)
        out = np.zeros(len(phases)) if suppress_bg else 1.0 - bgsum
        for i, prim in enumerate(self.primitives):
            try:
                dens = np.asarray(prim(phases, log10_ens))
            except TypeError:  # energy-independent component
                dens = np.asarray(prim(phases))
            out = out + norms[:, i] * dens
        if suppress_bg:
            out = out / bgsum
        return out

    def gradient_phases(self, phases, eps: float = 1e-7):
        """d(template)/d(phase) by central difference (host path)."""
        return (self(np.asarray(phases) + eps) - self(np.asarray(phases) - eps)) / (2 * eps)

    def integrate(self, x1: float = 0.0, x2: float = 1.0) -> float:
        norms = self.norms()
        bg = 1.0 - norms.sum()
        return float(bg * (x2 - x1) + sum(
            n * p.integrate(x1, x2) for n, p in zip(norms, self.primitives)))

    # -- parameter plumbing --------------------------------------------------
    def num_parameters(self, free: bool = True) -> int:
        return sum(p.num_parameters(free) for p in self.primitives) + \
            self.norms.num_parameters(free)

    def get_parameters(self, free: bool = True) -> np.ndarray:
        return np.concatenate(
            [p.get_parameters(free) for p in self.primitives]
            + [self.norms.get_parameters(free)])

    def set_parameters(self, pars, free: bool = True) -> bool:
        pars = np.asarray(pars, dtype=np.float64)
        i = 0
        for p in self.primitives:
            n = p.num_parameters(free)
            p.set_parameters(pars[i:i + n], free)
            i += n
        n = self.norms.num_parameters(free)
        self.norms.set_parameters(pars[i:i + n], free)
        return True

    def get_errors(self, free: bool = True) -> np.ndarray:
        return np.zeros(self.num_parameters(free))

    def get_location(self) -> float:
        """Location of the highest-amplitude peak."""
        norms = self.norms()
        i = int(np.argmax(norms))
        return self.primitives[i].get_location()

    def get_amplitudes(self) -> np.ndarray:
        return self.norms()

    # -- sampling ------------------------------------------------------------
    def random(self, n: int, rng=None) -> np.ndarray:
        """Draw n photon phases from the template: multinomial split over
        (background, components), each primitive drawing analytically where
        it can (reference ``lctemplate.py random`` technique); rejection
        sampling is the per-primitive fallback."""
        rng = rng or np.random.default_rng()
        if not all(getattr(p, "mixture_safe", True) for p in self.primitives):
            # Fourier-style components are not standalone densities (their
            # pdfs dip negative); only whole-template rejection is valid
            return self._random_rejection(n, rng)
        norms = np.asarray(self.norms(), dtype=np.float64)
        probs = np.concatenate([[max(1.0 - norms.sum(), 0.0)], norms])
        probs = probs / probs.sum()
        counts = rng.multinomial(n, probs)
        parts = [rng.random(counts[0])]  # uniform background
        for c, prim in zip(counts[1:], self.primitives):
            if c:
                parts.append(np.asarray(prim.random(int(c), rng=rng)))
        out = np.concatenate(parts)
        rng.shuffle(out)
        return out

    def _random_rejection(self, n: int, rng) -> np.ndarray:
        grid = np.linspace(0, 1, 2048)
        fmax = float(np.max(self(grid))) * 1.05
        out = np.empty(0)
        while len(out) < n:
            m = int((n - len(out)) * 1.5 * fmax) + 16
            x = rng.random(m)
            keep = rng.random(m) * fmax < np.asarray(self(x))
            out = np.concatenate([out, x[keep]])
        return out[:n]

    def rotate(self, dphi: float):
        for p in self.primitives:
            p.set_location((p.get_location() + dphi) % 1.0)

    # -- reference user-API long tail (templates/lctemplate.py) ------------
    def copy(self) -> "LCTemplate":
        """Deep copy (reference ``lctemplate.py copy``)."""
        import copy as _copy

        return _copy.deepcopy(self)

    def add_primitive(self, prim, norm: float = 0.1) -> None:
        """Append a pulse component with amplitude ``norm``, scaling the
        existing amplitudes by (1 - norm) so the total stays normalized
        (reference ``lctemplate.py add_primitive``)."""
        amps = self.get_amplitudes()
        new = np.concatenate([amps * (1.0 - norm), [norm]])
        self.primitives.append(prim)
        self.norms = NormAngles(new)

    def delete_primitive(self, index: int = -1) -> None:
        """Remove a pulse component, redistributing its amplitude over the
        rest (reference ``lctemplate.py delete_primitive``)."""
        if len(self.primitives) == 1:
            raise ValueError("Template must retain at least one component")
        amps = self.get_amplitudes()
        keep = np.delete(amps, index)
        total = keep.sum()
        if total > 0:
            keep = keep * amps.sum() / total
        self.primitives.pop(index)
        self.norms = NormAngles(keep)

    def cdf(self, x, log10_ens=None) -> np.ndarray:
        """Cumulative profile on [0, 1] (reference ``lctemplate.py
        cdf``), by dense trapezoid integration of the pdf."""
        grid = np.linspace(0.0, 1.0, 2049)
        pdf = np.asarray(self(grid, log10_ens=log10_ens))
        c = np.concatenate([[0.0], np.cumsum((pdf[1:] + pdf[:-1]) * 0.5
                                             * np.diff(grid))])
        c /= c[-1]
        # clip, not mod: cdf(1.0) must be 1, not wrap to cdf(0)
        return np.interp(np.clip(np.asarray(x, dtype=np.float64), 0.0, 1.0),
                         grid, c)

    def norm(self) -> float:
        """Total pulsed fraction (sum of component amplitudes; reference
        ``lctemplate.py norm``)."""
        return float(np.sum(self.get_amplitudes()))

    def delta(self, index=None) -> float:
        """Radio-lag-convention peak position Delta (reference
        ``lctemplate.py delta``): location of the highest-amplitude (or
        ``index``-th) component.  Delegates to :meth:`get_location` so
        "peak" has exactly one definition."""
        if index is None:
            return float(self.get_location())
        return float(self.primitives[int(index)].get_location())

    #: reference spelling
    Delta = delta

    def get_fixed_energy_version(self, log10_en: float = 3.0) -> "LCTemplate":
        """Snapshot pinned at ``log10_en`` (reference ``lctemplate.py
        get_fixed_energy_version``): the copy evaluates energy-dependent
        primitives/norms at that energy whenever no per-photon energies are
        given; energy-independent templates copy unchanged."""
        out = self.copy()
        if self.is_energy_dependent():
            out._fixed_log10_en = np.atleast_1d(np.float64(log10_en))
        return out

    def closest_to_peak(self, phases) -> float:
        """Smallest |phase - peak| over the given phases (reference
        ``lctemplate.py closest_to_peak``)."""
        d = np.abs((np.asarray(phases, dtype=np.float64)
                    - self.delta() + 0.5) % 1.0 - 0.5)
        return float(np.min(d))

    def mean_value(self, phases, log10_ens=None) -> float:
        """Mean template value over the given phases."""
        return float(np.mean(np.asarray(self(phases,
                                             log10_ens=log10_ens))))

    def max_value(self) -> float:
        """Maximum of the profile on a dense grid."""
        grid = np.linspace(0.0, 1.0, 2048, endpoint=False)
        return float(np.max(np.asarray(self(grid))))

    def check_bounds(self) -> bool:
        """True when every free parameter is inside its domain (reference
        ``lctemplate.py check_bounds``)."""
        try:
            p = self.get_parameters()
            return bool(np.all(np.isfinite(p)))
        except Exception:
            return False

    def approx_gradient(self, phases, log10_ens=None,
                        eps: float = 1e-6) -> np.ndarray:
        """(nparam, nphase) finite-difference gradient of the pdf wrt the
        free parameters (reference ``lctemplate.py approx_gradient``)."""
        p0 = self.get_parameters().copy()
        out = np.empty((len(p0), len(np.atleast_1d(phases))))
        for i in range(len(p0)):
            for s, sign in ((eps, +1.0), (-2 * eps, -1.0)):
                p0[i] += s
                self.set_parameters(p0)
                v = np.asarray(self(phases, log10_ens=log10_ens))
                if sign > 0:
                    hi = v
                else:
                    lo = v
            p0[i] += eps
            self.set_parameters(p0)
            out[i] = (hi - lo) / (2 * eps)
        return out

    #: reference offers both spellings
    approx_derivative = approx_gradient

    def check_gradient(self, phases=None, quiet: bool = True) -> bool:
        """Self-consistency of the finite-difference gradient at two eps
        scales (reference ``lctemplate.py check_gradient``)."""
        if phases is None:
            phases = np.linspace(0.05, 0.95, 19)
        g1 = self.approx_gradient(phases, eps=1e-5)
        g2 = self.approx_gradient(phases, eps=1e-6)
        ok = np.allclose(g1, g2, rtol=1e-2, atol=1e-6)
        if not quiet and not ok:
            print("check_gradient: eps-scales disagree")
        return bool(ok)

    def __repr__(self):
        lines = [f"LCTemplate: norms={self.norms()}, bg={1 - self.norms().sum():.4f}"]
        lines += [f"  {p!r}" for p in self.primitives]
        return "\n".join(lines)

    # -- IO ------------------------------------------------------------------
    def write_profile(self, fname: str):
        """pygaussfit-compatible ascii (const/phas/fwhm/ampl lines)."""
        norms = self.norms()
        with open(fname, "w") as f:
            f.write(f"const = {1 - norms.sum():.6f}\n")
            for n, p in zip(norms, self.primitives):
                f.write(f"phas{1} = {p.get_location():.6f}\n"
                        .replace("phas1", "phas"))
                f.write(f"fwhm = {p.get_width() * 2.35482:.6f}\n")
                f.write(f"ampl = {n:.6f}\n")


def prim_io(template: str):
    """Read a pygaussfit-style gaussian template file -> (primitives, norms)
    (reference ``lctemplate.py`` gaussian reader used by event_optimize)."""
    phass, ampls, fwhms = [], [], []
    for line in open(template):
        ls = line.lstrip()
        if ls.startswith("phas"):
            phass.append(float(line.split("=")[-1].split()[0]))
        elif ls.startswith("ampl"):
            ampls.append(float(line.split("=")[-1].split()[0]))
        elif ls.startswith("fwhm"):
            fwhms.append(float(line.split("=")[-1].split()[0]))
    if not (len(phass) == len(ampls) == len(fwhms)) or not phass:
        raise ValueError(f"Malformed gaussian template file {template}")
    prims = [LCGaussian([f / 2.35482, ph % 1.0]) for ph, f in zip(phass, fwhms)]
    norms = np.asarray(ampls, dtype=np.float64)
    total = norms.sum()
    if total > 1.0:
        # renormalize with a 1-ulp margin: a/total can still sum above 1.0
        # in float64, which NormAngles rightly rejects
        norms = norms / (total * (1.0 + 1e-12))
    return prims, list(norms)


def gauss_template_from_file(fname: str) -> LCTemplate:
    prims, norms = prim_io(fname)
    return LCTemplate(prims, norms)


def make_twoside_gaussian(center: float, width1: float, width2: float,
                          norm: float = 1.0) -> LCTemplate:
    """Asymmetric peak approximated by two half-weighted gaussians
    (reference helper)."""
    g1 = LCGaussian([width1, center])
    g2 = LCGaussian([width2, center])
    return LCTemplate([g1, g2], [norm / 2, norm / 2])


#: reference re-export (each template module offers isvector)
from pint_tpu.templates.lcnorm import isvector  # noqa: E402,F401
