"""LCTemplate: a normalized pulse-profile model — mixture of primitives plus
uniform background.

Counterpart of reference ``templates/lctemplate.py LCTemplate`` (mixture
evaluation, parameter get/set across primitives + norms, random draws,
gaussian-template-file IO compatible with pygaussfit output).  The
evaluation core is jnp-compatible so the photon likelihood
``sum log(w * f(phi) + (1-w))`` jits and vmaps over walkers.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from pint_tpu.templates.lcnorm import NormAngles
from pint_tpu.templates.lcprimitives import LCGaussian, LCPrimitive

__all__ = ["LCTemplate", "prim_io", "make_twoside_gaussian",
           "gradient_derivative", "check_gradient_derivative"]


class LCTemplate:
    def __init__(self, primitives: List[LCPrimitive], norms):
        self.primitives = list(primitives)
        self.norms = norms if isinstance(norms, NormAngles) else NormAngles(norms)
        if self.norms.dim != len(self.primitives):
            raise ValueError("One norm per primitive required")

    def is_energy_dependent(self) -> bool:
        return any(getattr(x, "is_energy_dependent", lambda: False)()
                   for x in list(self.primitives) + [self.norms])

    # -- evaluation ----------------------------------------------------------
    def __call__(self, phases, log10_ens=None, suppress_bg: bool = False):
        """Template density at the given phases; with ``log10_ens`` each
        photon is evaluated at its own energy (energy-dependent primitives /
        norms drift their parameters; reference ``lceprimitives.py`` /
        ``lcenorm.py`` semantics)."""
        if log10_ens is None:
            log10_ens = getattr(self, "_fixed_log10_en", None)
        if log10_ens is None:
            norms = self.norms()
            bg = 1.0 - norms.sum()
            out = bg if not suppress_bg else 0.0
            for n, prim in zip(norms, self.primitives):
                out = out + n * prim(phases)
            if suppress_bg:
                out = out / norms.sum()
            return out
        phases = np.atleast_1d(np.asarray(phases, dtype=np.float64))
        try:
            norms = self.norms(log10_ens)  # (N, ncomp) if energy-dependent
        except TypeError:
            norms = np.broadcast_to(self.norms(), (len(phases),
                                                   self.norms.dim))
        norms = np.atleast_2d(norms)
        bgsum = norms.sum(axis=1)
        out = np.zeros(len(phases)) if suppress_bg else 1.0 - bgsum
        for i, prim in enumerate(self.primitives):
            try:
                dens = np.asarray(prim(phases, log10_ens))
            except TypeError:  # energy-independent component
                dens = np.asarray(prim(phases))
            out = out + norms[:, i] * dens
        if suppress_bg:
            out = out / bgsum
        return out

    def gradient_phases(self, phases, eps: float = 1e-7):
        """d(template)/d(phase) by central difference (host path)."""
        return (self(np.asarray(phases) + eps) - self(np.asarray(phases) - eps)) / (2 * eps)

    def integrate(self, x1: float = 0.0, x2: float = 1.0) -> float:
        norms = self.norms()
        bg = 1.0 - norms.sum()
        return float(bg * (x2 - x1) + sum(
            n * p.integrate(x1, x2) for n, p in zip(norms, self.primitives)))

    # -- parameter plumbing --------------------------------------------------
    def num_parameters(self, free: bool = True) -> int:
        return sum(p.num_parameters(free) for p in self.primitives) + \
            self.norms.num_parameters(free)

    def get_parameters(self, free: bool = True) -> np.ndarray:
        return np.concatenate(
            [p.get_parameters(free) for p in self.primitives]
            + [self.norms.get_parameters(free)])

    def set_parameters(self, pars, free: bool = True) -> bool:
        pars = np.asarray(pars, dtype=np.float64)
        i = 0
        for p in self.primitives:
            n = p.num_parameters(free)
            p.set_parameters(pars[i:i + n], free)
            i += n
        n = self.norms.num_parameters(free)
        self.norms.set_parameters(pars[i:i + n], free)
        return True

    def get_errors(self, free: bool = True) -> np.ndarray:
        """Stored parameter errors (set by :meth:`set_errors` / the
        fitters), free-masked by default; zeros when never set."""
        out = []
        for p in self.primitives:
            e = np.asarray(getattr(p, "errors", np.zeros_like(
                np.asarray(p.p, dtype=np.float64))), dtype=np.float64)
            out.append(e[np.asarray(p.free, dtype=bool)] if free else e)
        ne = self.norms.get_errors(free=free) \
            if hasattr(self.norms, "get_errors") \
            else np.zeros(len(self.norms.get_parameters(free=free)))
        out.append(np.asarray(ne, dtype=np.float64))
        return np.concatenate(out)

    def get_location(self) -> float:
        """Location of the highest-amplitude peak."""
        norms = self.norms()
        i = int(np.argmax(norms))
        return self.primitives[i].get_location()

    def get_amplitudes(self) -> np.ndarray:
        return self.norms()

    # -- sampling ------------------------------------------------------------
    def random(self, n: int, rng=None) -> np.ndarray:
        """Draw n photon phases from the template: multinomial split over
        (background, components), each primitive drawing analytically where
        it can (reference ``lctemplate.py random`` technique); rejection
        sampling is the per-primitive fallback."""
        rng = rng or np.random.default_rng()
        if not all(getattr(p, "mixture_safe", True) for p in self.primitives):
            # Fourier-style components are not standalone densities (their
            # pdfs dip negative); only whole-template rejection is valid
            return self._random_rejection(n, rng)
        norms = np.asarray(self.norms(), dtype=np.float64)
        probs = np.concatenate([[max(1.0 - norms.sum(), 0.0)], norms])
        probs = probs / probs.sum()
        counts = rng.multinomial(n, probs)
        parts = [rng.random(counts[0])]  # uniform background
        for c, prim in zip(counts[1:], self.primitives):
            if c:
                parts.append(np.asarray(prim.random(int(c), rng=rng)))
        out = np.concatenate(parts)
        rng.shuffle(out)
        return out

    def _random_rejection(self, n: int, rng) -> np.ndarray:
        grid = np.linspace(0, 1, 2048)
        fmax = float(np.max(self(grid))) * 1.05
        out = np.empty(0)
        while len(out) < n:
            m = int((n - len(out)) * 1.5 * fmax) + 16
            x = rng.random(m)
            keep = rng.random(m) * fmax < np.asarray(self(x))
            out = np.concatenate([out, x[keep]])
        return out[:n]

    def rotate(self, dphi: float):
        for p in self.primitives:
            p.set_location((p.get_location() + dphi) % 1.0)

    # -- reference user-API long tail (templates/lctemplate.py) ------------
    def copy(self) -> "LCTemplate":
        """Deep copy (reference ``lctemplate.py copy``)."""
        import copy as _copy

        return _copy.deepcopy(self)

    def _norms_energy_dependent(self) -> bool:
        return getattr(self.norms, "is_energy_dependent", lambda: False)()

    def _require_plain_norms(self, what: str) -> None:
        if self._norms_energy_dependent():
            raise NotImplementedError(
                f"{what} on an energy-dependent template would silently "
                "discard the norm slopes; take get_fixed_energy_version() "
                "first or edit the ENormAngles directly")

    def add_primitive(self, prim, norm: float = 0.1) -> None:
        """Append a pulse component with amplitude ``norm``, scaling the
        existing amplitudes by (1 - norm) so the total stays normalized
        (reference ``lctemplate.py add_primitive``)."""
        self._require_plain_norms("add_primitive")
        amps = self.get_amplitudes()
        new = np.concatenate([amps * (1.0 - norm), [norm]])
        old_free = np.asarray(self.norms.free, dtype=bool)
        self.primitives.append(prim)
        self.norms = NormAngles(new)
        self.norms.free[:len(old_free)] = old_free

    def delete_primitive(self, index: int = -1) -> None:
        """Remove a pulse component, redistributing its amplitude over the
        rest (reference ``lctemplate.py delete_primitive``)."""
        if len(self.primitives) == 1:
            raise ValueError("Template must retain at least one component")
        self._require_plain_norms("delete_primitive")
        amps = self.get_amplitudes()
        keep = np.delete(amps, index)
        total = keep.sum()
        if total > 0:
            keep = keep * amps.sum() / total
        old_free = np.delete(np.asarray(self.norms.free, dtype=bool), index)
        self.primitives.pop(index)
        self.norms = NormAngles(keep)
        self.norms.free[:] = old_free

    def cdf(self, x, log10_ens=None) -> np.ndarray:
        """Cumulative profile on [0, 1] (reference ``lctemplate.py
        cdf``), by dense trapezoid integration of the pdf."""
        grid = np.linspace(0.0, 1.0, 2049)
        pdf = np.asarray(self(grid, log10_ens=log10_ens))
        c = np.concatenate([[0.0], np.cumsum((pdf[1:] + pdf[:-1]) * 0.5
                                             * np.diff(grid))])
        c /= c[-1]
        # clip, not mod: cdf(1.0) must be 1, not wrap to cdf(0)
        return np.interp(np.clip(np.asarray(x, dtype=np.float64), 0.0, 1.0),
                         grid, c)

    def norm(self) -> float:
        """Total pulsed fraction (sum of component amplitudes; reference
        ``lctemplate.py norm``)."""
        return float(np.sum(self.get_amplitudes()))

    def delta(self, index=None) -> float:
        """Radio-lag-convention peak position Delta (reference
        ``lctemplate.py delta``): location of the highest-amplitude (or
        ``index``-th) component.  Delegates to :meth:`get_location` so
        "peak" has exactly one definition."""
        if index is None:
            return float(self.get_location())
        return float(self.primitives[int(index)].get_location())

    #: reference spelling
    Delta = delta

    def get_fixed_energy_version(self, log10_en: float = 3.0) -> "LCTemplate":
        """Snapshot pinned at ``log10_en`` (reference ``lctemplate.py
        get_fixed_energy_version``): the copy evaluates energy-dependent
        primitives/norms at that energy whenever no per-photon energies are
        given; energy-independent templates copy unchanged."""
        out = self.copy()
        if self.is_energy_dependent():
            out._fixed_log10_en = np.atleast_1d(np.float64(log10_en))
        return out

    def closest_to_peak(self, phases) -> float:
        """Smallest |phase - peak| over the given phases (reference
        ``lctemplate.py closest_to_peak``)."""
        d = np.abs((np.asarray(phases, dtype=np.float64)
                    - self.delta() + 0.5) % 1.0 - 0.5)
        return float(np.min(d))

    def mean_value(self, phases, log10_ens=None) -> float:
        """Mean template value over the given phases."""
        return float(np.mean(np.asarray(self(phases,
                                             log10_ens=log10_ens))))

    def max_value(self, resolution: int = 2048) -> float:
        """Maximum of the profile on a dense grid."""
        grid = np.linspace(0.0, 1.0, int(resolution), endpoint=False)
        return float(np.max(np.asarray(self(grid))))

    def check_bounds(self) -> bool:
        """True when every free parameter is inside its domain (reference
        ``lctemplate.py check_bounds``)."""
        try:
            p = self.get_parameters()
            return bool(np.all(np.isfinite(p)))
        except Exception:
            return False

    def approx_gradient(self, phases, log10_ens=None,
                        eps: float = 1e-6, free: bool = True) -> np.ndarray:
        """(nparam, nphase) finite-difference gradient of the pdf wrt the
        free (or, with ``free=False``, all) parameters (reference
        ``lctemplate.py approx_gradient``)."""
        p0 = self.get_parameters(free=free).copy()
        out = np.empty((len(p0), len(np.atleast_1d(phases))))
        for i in range(len(p0)):
            for s, sign in ((eps, +1.0), (-2 * eps, -1.0)):
                p0[i] += s
                self.set_parameters(p0, free=free)
                v = np.asarray(self(phases, log10_ens=log10_ens))
                if sign > 0:
                    hi = v
                else:
                    lo = v
            p0[i] += eps
            self.set_parameters(p0, free=free)
            out[i] = (hi - lo) / (2 * eps)
        return out

    #: reference offers both spellings
    approx_derivative = approx_gradient

    def check_gradient(self, phases=None, quiet: bool = True) -> bool:
        """Self-consistency of the finite-difference gradient at two eps
        scales (reference ``lctemplate.py check_gradient``)."""
        if phases is None:
            phases = np.linspace(0.05, 0.95, 19)
        g1 = self.approx_gradient(phases, eps=1e-5)
        g2 = self.approx_gradient(phases, eps=1e-6)
        ok = np.allclose(g1, g2, rtol=1e-2, atol=1e-6)
        if not quiet and not ok:
            print("check_gradient: eps-scales disagree")
        return bool(ok)

    def set_overall_phase(self, ph: float) -> None:
        """Move the FIRST component's peak to phase ``ph``, shifting every
        component rigidly (reference ``lctemplate.py:313``; delegates to
        :meth:`rotate`)."""
        self.rotate(float(ph) - self.primitives[0].get_location())

    def norm_ok(self) -> bool:
        """Total amplitude within [0, 1] (reference
        ``lctemplate.py:339``)."""
        return self.norm() <= 1.0

    def has_bridge(self) -> bool:
        """Reference ``lctemplate.py:86``: bridge components are modeled
        as ordinary wide primitives here."""
        return False

    def max(self, resolution: int = 2048) -> float:
        """Maximum of the profile (reference spelling of
        :meth:`max_value`)."""
        return self.max_value(resolution=resolution)

    def get_parameter_names(self, free: bool = True) -> list:
        """Flat parameter-name list, primitives then norms (reference
        ``lctemplate.py get_parameter_names``)."""
        out = []
        for i, prim in enumerate(self.primitives):
            n = prim.num_parameters(free=free)
            base = getattr(prim, "name", type(prim).__name__)
            out += [f"P{i}_{base}_p{j}" for j in range(n)]
        out += [f"Norm_a{j}" for j in
                range(len(self.norms.get_parameters(free=free)))]
        return out

    def get_free_mask(self) -> np.ndarray:
        """Boolean mask of free entries over the full parameter vector
        (reference ``lctemplate.py get_free_mask``)."""
        masks = [np.asarray(p.free, dtype=bool) for p in self.primitives]
        masks.append(np.asarray(self.norms.free, dtype=bool))
        return np.concatenate(masks)

    def free_parameters(self) -> None:
        """Unfreeze everything (reference ``lctemplate.py
        free_parameters``)."""
        for p in self.primitives:
            p.free[:] = True
        self.norms.free[:] = True

    def freeze_parameters(self) -> None:
        """Freeze everything (reference ``lctemplate.py
        freeze_parameters``)."""
        for p in self.primitives:
            p.free[:] = False
        self.norms.free[:] = False

    def set_errors(self, errs, free: bool = True) -> None:
        """Distribute a flat (free-length by default) error vector onto the
        components (reference ``lctemplate.py set_errors``); each component
        stores a FULL-length vector so its free mask indexes it."""
        errs = np.asarray(errs, dtype=np.float64)
        i = 0
        for p in self.primitives:
            n = p.num_parameters(free=free)
            sub = errs[i:i + n]
            if free:
                full = np.zeros_like(np.asarray(p.p, dtype=np.float64))
                full[np.asarray(p.free, dtype=bool)] = sub
                p.errors = full
            else:
                p.errors = sub.copy()
            i += n
        self.norms.set_errors(errs[i:], free=free)

    def derivative(self, phases, log10_ens=None,
                   eps: float = 1e-6) -> np.ndarray:
        """d(pdf)/d(phase) by central difference (reference
        ``lctemplate.py derivative``); one implementation shared with
        :meth:`gradient_phases`."""
        if log10_ens is None:
            return self.gradient_phases(phases, eps=eps)
        ph = np.asarray(phases, dtype=np.float64)
        hi = np.asarray(self((ph + eps) % 1.0, log10_ens=log10_ens))
        lo = np.asarray(self((ph - eps) % 1.0, log10_ens=log10_ens))
        return (hi - lo) / (2 * eps)

    def gradient(self, phases, log10_ens=None, free: bool = True):
        """Gradient of the pdf wrt the (free or all) parameters — the
        finite-difference implementation (reference has hand-coded
        gradients; autodiff/FD replaces them here)."""
        return self.approx_gradient(phases, log10_ens=log10_ens, free=free)

    def approx_hessian(self, phases, log10_ens=None,
                       eps: float = 1e-4) -> np.ndarray:
        """(nparam, nparam, nphase) finite-difference Hessian of the pdf
        (reference ``lctemplate.py approx_hessian``)."""
        p0 = self.get_parameters().copy()
        n = len(p0)
        ph = np.atleast_1d(np.asarray(phases, dtype=np.float64))

        def f(p):
            self.set_parameters(p)
            return np.asarray(self(ph, log10_ens=log10_ens))

        H = np.empty((n, n, len(ph)))
        for i in range(n):
            for j in range(i, n):
                pp = p0.copy(); pp[i] += eps; pp[j] += eps; fpp = f(pp)
                pm = p0.copy(); pm[i] += eps; pm[j] -= eps; fpm = f(pm)
                mp = p0.copy(); mp[i] -= eps; mp[j] += eps; fmp = f(mp)
                mm = p0.copy(); mm[i] -= eps; mm[j] -= eps; fmm = f(mm)
                H[i, j] = H[j, i] = (fpp - fpm - fmp + fmm) / (4 * eps**2)
        self.set_parameters(p0)
        return H

    hessian = approx_hessian

    def check_derivative(self, phases=None, eps: float = 1e-6,
                         quiet: bool = True) -> bool:
        """Phase-derivative self-consistency at two eps scales (reference
        ``lctemplate.py check_derivative``)."""
        if phases is None:
            phases = np.linspace(0.05, 0.95, 19)
        d1 = self.derivative(phases, eps=eps)
        d2 = self.derivative(phases, eps=eps * 10)
        return bool(np.allclose(d1, d2, rtol=1e-2, atol=1e-4))

    def single_component(self, index: int) -> "LCTemplate":
        """Template of one component alone at unit amplitude (reference
        ``lctemplate.py single_component``)."""
        import copy as _copy

        return LCTemplate([_copy.deepcopy(self.primitives[index])], [1.0])

    def mean_single_component(self, index: int, phases,
                              log10_ens=None) -> float:
        """Mean pdf of one component over the given phases."""
        return float(np.mean(np.asarray(
            self.single_component(index)(phases, log10_ens=log10_ens))))

    def _permute_norms(self, order) -> None:
        """Reorder norm components in place, preserving the norms object
        TYPE (ENormAngles keeps its slopes) and free mask."""
        if self._norms_energy_dependent():
            amps = self.norms._angles_to_norms(self.norms.p[:self.norms.dim])
            angles = self.norms._norms_to_angles(amps[order])
            self.norms.p[:self.norms.dim] = angles
            self.norms.p[self.norms.dim:] = self.norms.p[self.norms.dim:][order]
            f = self.norms.free
            f[:self.norms.dim] = f[:self.norms.dim][order]
            f[self.norms.dim:] = f[self.norms.dim:][order]
        else:
            amps = self.get_amplitudes()
            free = np.asarray(self.norms.free, dtype=bool)[order]
            self.norms.p[:] = self.norms._norms_to_angles(amps[order])
            self.norms.free[:] = free

    def order_primitives(self) -> None:
        """Sort components by peak location (reference
        ``lctemplate.py order_primitives``)."""
        order = np.argsort([p.get_location() for p in self.primitives])
        self.primitives = [self.primitives[i] for i in order]
        self._permute_norms(order)

    def swap_primitive(self, i: int, j: int = None) -> None:
        """Swap two components (reference ``lctemplate.py
        swap_primitive``); default swaps ``i`` with ``i+1``."""
        j = i + 1 if j is None else j
        self.primitives[i], self.primitives[j] = \
            self.primitives[j], self.primitives[i]
        order = np.arange(len(self.primitives))
        order[i], order[j] = order[j], order[i]
        self._permute_norms(order)

    def get_gaussian_prior(self) -> "GaussianPrior":
        """Default gaussian prior over the free parameters: weak width
        priors on each primitive's parameters, none on the norms
        (reference ``lctemplate.py:288``)."""
        locs, widths, mods = [], [], []
        for prim in self.primitives:
            p = prim.get_parameters(free=False)
            locs += list(p)
            # generous widths: half the parameter scale, min 0.1
            widths += [max(0.1, abs(v) * 0.5) for v in p]
            # ONLY the actual location parameter lives on the circle:
            # energy-dependent primitives append slopes after the base
            # vector, so "last entry" would wrap a slope instead
            loc_idx = getattr(prim, "nb", len(p)) - 1
            mods += [k == loc_idx for k in range(len(p))]
        t = self.norms.get_parameters(free=False)
        locs += list(t)
        widths += [10.0] * len(t)  # effectively unconstrained
        mods += [False] * len(t)
        return GaussianPrior(locs, widths, mods, mask=self.get_free_mask())

    def prof_string(self, outputfile=None) -> str:
        """Tempo-style .prof text block (reference ``lctemplate.py
        prof_string``)."""
        lines = [f"# {type(p).__name__} loc={p.get_location():.6f}"
                 for p in self.primitives]
        s = "\n".join(lines) + "\n"
        if outputfile:
            with open(outputfile, "w") as f:
                f.write(s)
        return s

    def __repr__(self):
        lines = [f"LCTemplate: norms={self.norms()}, bg={1 - self.norms().sum():.4f}"]
        lines += [f"  {p!r}" for p in self.primitives]
        return "\n".join(lines)

    # -- IO ------------------------------------------------------------------
    def write_profile(self, fname: str):
        """pygaussfit-compatible ascii (const/phas/fwhm/ampl lines)."""
        norms = self.norms()
        with open(fname, "w") as f:
            f.write(f"const = {1 - norms.sum():.6f}\n")
            for n, p in zip(norms, self.primitives):
                f.write(f"phas{1} = {p.get_location():.6f}\n"
                        .replace("phas1", "phas"))
                f.write(f"fwhm = {p.get_width() * 2.35482:.6f}\n")
                f.write(f"ampl = {n:.6f}\n")


def prim_io(template: str):
    """Read a pygaussfit-style gaussian template file -> (primitives, norms)
    (reference ``lctemplate.py`` gaussian reader used by event_optimize)."""
    phass, ampls, fwhms = [], [], []
    for line in open(template):
        ls = line.lstrip()
        if ls.startswith("phas"):
            phass.append(float(line.split("=")[-1].split()[0]))
        elif ls.startswith("ampl"):
            ampls.append(float(line.split("=")[-1].split()[0]))
        elif ls.startswith("fwhm"):
            fwhms.append(float(line.split("=")[-1].split()[0]))
    if not (len(phass) == len(ampls) == len(fwhms)) or not phass:
        raise ValueError(f"Malformed gaussian template file {template}")
    prims = [LCGaussian([f / 2.35482, ph % 1.0]) for ph, f in zip(phass, fwhms)]
    norms = np.asarray(ampls, dtype=np.float64)
    total = norms.sum()
    if total > 1.0:
        # renormalize with a 1-ulp margin: a/total can still sum above 1.0
        # in float64, which NormAngles rightly rejects
        norms = norms / (total * (1.0 + 1e-12))
    return prims, list(norms)


def gauss_template_from_file(fname: str) -> LCTemplate:
    prims, norms = prim_io(fname)
    return LCTemplate(prims, norms)


def make_twoside_gaussian(center: float, width1: float, width2: float,
                          norm: float = 1.0) -> LCTemplate:
    """Asymmetric peak approximated by two half-weighted gaussians
    (reference helper)."""
    g1 = LCGaussian([width1, center])
    g2 = LCGaussian([width2, center])
    return LCTemplate([g1, g2], [norm / 2, norm / 2])


#: reference re-export (each template module offers isvector)
from pint_tpu.templates.lcnorm import isvector  # noqa: E402,F401


# ---------------------------------------------------------------------------
# template factory helpers (reference lctemplate.py:892-948,975)
# ---------------------------------------------------------------------------

def get_gauss1(pulse_frac=1, x1=0.5, width1=0.01) -> LCTemplate:
    """One-gaussian template (reference ``lctemplate.py:923``)."""
    return LCTemplate([LCGaussian(p=[width1, x1])], [pulse_frac])


def get_gauss2(pulse_frac=1, x1=0.1, x2=0.55, ratio=1.5,
               width1=0.01, width2=0.02, lorentzian=False,
               bridge_frac=0, skew=False) -> LCTemplate:
    """Two-peak template, optionally Lorentzian/skewed/bridged (reference
    ``lctemplate.py:892``)."""
    from pint_tpu.templates.lcprimitives import (LCGaussian2, LCLorentzian,
                                                 LCLorentzian2)

    n1, n2 = (np.asarray([ratio, 1.0]) * (1 - bridge_frac)
              * (pulse_frac / (1.0 + ratio)))
    if skew:
        prim = LCLorentzian2 if lorentzian else LCGaussian2
        p1 = [width1, width1 * (1 + skew), x1]
        p2 = [width2 * (1 + skew), width2, x2]
    else:
        if lorentzian:
            # NO 2*pi conversion: this port's LCLorentzian takes gamma in
            # phase units (the reference's engine works in radians)
            prim = LCLorentzian
        else:
            prim = LCGaussian
        p1, p2 = [width1, x1], [width2, x2]
    if bridge_frac > 0:
        nb = bridge_frac * pulse_frac
        b = LCGaussian(p=[0.1, (x2 + x1) / 2])
        return LCTemplate([prim(p=p1), b, prim(p=p2)], [n1, nb, n2])
    return LCTemplate([prim(p=p1), prim(p=p2)], [n1, n2])


def get_2pb(pulse_frac=0.9, lorentzian=False) -> LCTemplate:
    """Two peaks + gaussian bridge (reference ``lctemplate.py:928``)."""
    from pint_tpu.templates.lcprimitives import LCLorentzian

    prim = LCLorentzian if lorentzian else LCGaussian
    p1 = prim(p=[0.03, 0.1])
    b = LCGaussian(p=[0.15, 0.3])
    p2 = prim(p=[0.03, 0.55])
    return LCTemplate([p1, b, p2], [0.3 * pulse_frac, 0.4 * pulse_frac,
                                    0.3 * pulse_frac])


def adaptive_samples(func, npt: int, log10_ens=3, nres: int = 200):
    """Phase sample points concentrated where ``func`` varies fastest
    (reference ``lctemplate.py:950``): inverse-CDF placement on the
    |df/dphi|-weighted measure."""
    grid = np.linspace(0.0, 1.0, nres + 1)
    try:
        vals = np.asarray(func(grid, log10_ens))
    except TypeError:
        vals = np.asarray(func(grid))
    dens = np.abs(np.gradient(vals)) + 1e-9
    cdf = np.concatenate([[0.0], np.cumsum(0.5 * (dens[1:] + dens[:-1]))])
    cdf /= cdf[-1]
    return np.interp(np.linspace(0.0, 1.0, npt), cdf, grid)


class GaussianPrior:
    """Quadratic (gaussian) penalty on selected template parameters
    (reference ``lctemplate.py:975``; used by the template MCMC)."""

    def __init__(self, locations, widths, mod, mask=None):
        locations = np.asarray(locations, dtype=np.float64)
        self.mod = np.asarray(mod, dtype=bool)
        self.x0 = np.where(self.mod, np.mod(locations, 1), locations)
        self.s0 = np.asarray(widths, dtype=np.float64) * 2**0.5
        if mask is None:
            self.mask = np.ones(len(locations), dtype=bool)
        else:
            self.mask = np.asarray(mask, dtype=bool)
            self.x0 = self.x0[self.mask]
            self.s0 = self.s0[self.mask]
            self.mod = self.mod[self.mask]

    def __len__(self) -> int:
        return int(self.mask.sum())

    def __call__(self, parameters) -> float:
        if not np.any(self.mask):
            return 0.0
        p = np.asarray(parameters, dtype=np.float64)[self.mask]
        p = np.where(self.mod, np.mod(p, 1), p)
        return float(np.sum(((p - self.x0) / self.s0) ** 2))

    def gradient(self, parameters) -> np.ndarray:
        parameters = np.asarray(parameters, dtype=np.float64)
        out = np.zeros(len(self.mask))
        if not np.any(self.mask):
            return out
        p = parameters[self.mask]
        p = np.where(self.mod, np.mod(p, 1), p)
        out[self.mask] = 2.0 * (p - self.x0) / self.s0**2
        return out


def gradient_derivative(templ, phases, eps: float = 1e-5) -> np.ndarray:
    """d/dphi of the parameter gradient, (nparam, nphase) — the mixed
    second derivative used by TOA-uncertainty propagation (reference
    ``lctemplate.py gradient_derivative``); central difference in phase of
    the same gradient the fit uses."""
    ph = np.asarray(phases, dtype=np.float64)
    gp = np.asarray(templ.gradient((ph + eps) % 1.0, free=False))
    gm = np.asarray(templ.gradient((ph - eps) % 1.0, free=False))
    return (gp - gm) / (2 * eps)


def check_gradient_derivative(templ, n: int = 10001, quiet: bool = True):
    """Validate :func:`gradient_derivative` against coarse differencing of
    the gradient over a phase grid (reference ``lctemplate.py:1065``).
    Returns ``(pcs, gd, ngd)`` — bin centers, analytic-path values, and the
    numeric reference."""
    dom = np.linspace(0, 1, n)
    pcs = 0.5 * (dom[:-1] + dom[1:])
    g = np.asarray(templ.gradient(dom, free=False))
    ngd = (g[:, 1:] - g[:, :-1]) / (dom[1] - dom[0])
    gd = gradient_derivative(templ, pcs)
    if not quiet:
        for i in range(gd.shape[0]):
            print(f"param {i}: max |delta| = {np.max(np.abs(gd[i] - ngd[i])):.3g}")
    return pcs, gd, ngd
