"""Pulse-profile template machinery for photon-domain likelihoods
(counterpart of reference ``templates/``; SURVEY §2 "templates (photon)")."""

from pint_tpu.templates.lcfitters import (LCFitter, get_errors,
                                          make_err_plot)
from pint_tpu.templates.lcnorm import NormAngles
from pint_tpu.templates.lcprimitives import (
    LCGaussian,
    LCLorentzian,
    LCPrimitive,
    LCSkewGaussian,
    LCTopHat,
    LCVonMises,
    LCWrappedFunction,
    two_comp_mc,
)
from pint_tpu.templates.lctemplate import (
    LCTemplate,
    gauss_template_from_file,
    make_twoside_gaussian,
    prim_io,
)

__all__ = [
    "LCFitter", "NormAngles", "LCGaussian", "LCLorentzian", "LCPrimitive",
    "LCSkewGaussian", "LCWrappedFunction", "two_comp_mc", "get_errors",
    "make_err_plot", "LCTopHat", "LCVonMises", "LCTemplate",
    "gauss_template_from_file", "make_twoside_gaussian", "prim_io",
]
