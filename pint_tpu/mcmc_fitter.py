"""MCMC fitter: posterior sampling of timing-model parameters.

Counterpart of reference ``mcmc_fitter.py:109 MCMCFitter`` (emcee-based
posterior fit with lnprior + lnlike over residual chi2 or photon templates).
The sampling engine is :class:`pint_tpu.sampler.EnsembleSampler` by default
— the walker ensemble is advanced with *batched* lnposterior evaluations
(jit+vmap via ``BayesianTiming.lnposterior_batch``), the TPU mapping of the
reference's one-process-per-walker pattern (SURVEY §2c row 2).

``MCMCFitterBinnedTemplate`` / ``MCMCFitterAnalyticTemplate`` (photon-domain
template likelihoods, reference ``mcmc_fitter.py:441,485``) live in
:mod:`pint_tpu.event_fitter` with the template machinery.
"""

from __future__ import annotations

import copy
from typing import Callable, List, Optional

import numpy as np

from pint_tpu.bayesian import BayesianTiming
from pint_tpu.fitter import Fitter
from pint_tpu.logging import log
from pint_tpu.residuals import Residuals
from pint_tpu.sampler import EnsembleSampler, MCMCSampler
from pint_tpu.telemetry import jaxevents as _jaxevents
from pint_tpu.telemetry import span as _tspan

__all__ = ["MCMCFitter", "MCMCFitterBinnedTemplate",
           "MCMCFitterAnalyticTemplate", "set_priors_basic",
           "lnprior_basic", "lnlikelihood_basic", "lnlikelihood_chi2", "concat_toas"]


def __getattr__(name):
    # the photon-template fitters live with the template machinery; keep the
    # reference's import location working (reference ``mcmc_fitter.py:441``)
    if name in ("MCMCFitterBinnedTemplate", "MCMCFitterAnalyticTemplate"):
        import pint_tpu.event_fitter as ef

        return getattr(ef, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def lnprior_basic(ftr, theta) -> float:
    """Sum of parameter log-priors at ``theta`` (reference
    ``mcmc_fitter.py lnprior_basic``).  Works for both the residual-chi2
    fitter (via its BayesianTiming) and the photon-template fitters (via
    the parameters' prior objects directly)."""
    theta = np.asarray(theta, dtype=np.float64)
    if isinstance(ftr, MCMCFitter):
        return float(ftr.bt.lnprior(theta))
    return float(sum(getattr(ftr.model, p).prior.logpdf(v)
                     for p, v in zip(ftr.fitkeys, theta)))


def lnlikelihood_chi2(ftr, theta) -> float:
    """Residual-based log-likelihood at ``theta`` (reference
    ``mcmc_fitter.py lnlikelihood_chi2``).  Only defined for residual
    fitters; the photon-template fitters have no chi2 likelihood."""
    if not isinstance(ftr, MCMCFitter):
        raise TypeError(
            f"{type(ftr).__name__} has no residual chi2 likelihood; use "
            "its lnposterior (photon-template) instead")
    return float(ftr.bt.lnlikelihood(np.asarray(theta, dtype=np.float64)))


def set_priors_basic(ftr, priorerrfact: float = 10.0):
    """Uniform priors at +/- priorerrfact * uncertainty around the current
    values (reference ``mcmc_fitter.py set_priors_basic``); raises for a
    free parameter with no uncertainty (the reference does too — a silent
    skip would leave an improper prior that only fails much later)."""
    from pint_tpu.bayesian import apply_prior_info

    info = {}
    for p in ftr.fitkeys:
        par = getattr(ftr.model, p)
        if not par.uncertainty:
            raise ValueError(
                f"Parameter {p} has no uncertainty; cannot build its "
                "basic uniform prior")
        half = priorerrfact * float(par.uncertainty)
        v = float(par.value or 0.0)
        info[p] = {"distr": "uniform", "pmin": v - half, "pmax": v + half}
    apply_prior_info(ftr.model, info)
    if hasattr(ftr, "_bt"):
        ftr._bt = None  # cached BayesianTiming must see the new priors
    if hasattr(ftr, "_batch_fn"):
        ftr._batch_fn = None  # photon fitters bake prior specs in at build
    return info


def concat_toas(toas_list):
    """Concatenate TOAs objects (reference ``mcmc_fitter.py concat_toas``;
    alias of :func:`pint_tpu.toa.merge_TOAs`)."""
    from pint_tpu.toa import merge_TOAs

    return merge_TOAs(list(toas_list))


class MCMCFitter(Fitter):
    """Posterior sampling fit (reference ``mcmc_fitter.py:109``).

    Parameters mirror the reference: a sampler object (default: jax-native
    :class:`EnsembleSampler` with 32 walkers), optional prior_info, phase
    tracking via pulse numbers.  ``fit_toas(maxiter=N)`` runs N ensemble
    steps and sets the model to the maximum-posterior sample.
    """

    def __init__(self, toas, model, sampler: Optional[MCMCSampler] = None,
                 prior_info: Optional[dict] = None,
                 use_pulse_numbers: bool = False, nwalkers: int = 32,
                 errfact: float = 0.1, resids: bool = True,
                 lnprior=None, lnlike=None, setpriors=None,
                 weights=None, phs=None, phserr=None,
                 minMJD: float = 40000.0, maxMJD: float = 60000.0, **kw):
        if not resids:
            raise TypeError(
                "resids=False selects the reference's photon-template mode; "
                "use MCMCFitterBinnedTemplate / MCMCFitterAnalyticTemplate "
                "(pint_tpu.event_fitter) for that")
        super().__init__(toas, model, **kw)
        self.method = "MCMC"
        self.sampler = sampler or EnsembleSampler(nwalkers)
        self.errfact = errfact
        # reference kwarg surface (mcmc_fitter.py:139-158): custom
        # lnprior/lnlike callables with signature (fitter, theta) switch
        # sampling onto a scalar python path exactly like the reference's;
        # with the defaults the fast batched BayesianTiming posterior runs
        self.use_resids = True
        self._custom_post = lnprior is not None or lnlike is not None
        self.lnprior = lnprior if lnprior is not None else lnprior_basic
        self.lnlikelihood = (lnlike if lnlike is not None
                             else lnlikelihood_chi2)
        self.set_priors = setpriors if setpriors is not None \
            else set_priors_basic
        self.weights = weights
        self.phs, self.phserr = phs, phserr
        self.minMJD, self.maxMJD = minMJD, maxMJD
        # constructor priors install on the LIVE model once, so every
        # (re)build of the BayesianTiming below sees them; BayesianTiming
        # validates priors at construction, so it is built lazily to allow
        # the reference flow (construct fitter, THEN set_priors_basic)
        if prior_info:
            from pint_tpu.bayesian import apply_prior_info

            apply_prior_info(self.model, prior_info)
        self._bt: Optional[BayesianTiming] = None
        self._bt_args = dict(use_pulse_numbers=use_pulse_numbers)
        self.fitkeys = list(self.model.free_params)
        self.n_fit_params = len(self.fitkeys)
        self.maxpost = -np.inf
        self.maxpost_fitvals = None

    @property
    def bt(self) -> BayesianTiming:
        if self._bt is not None \
                and self._bt.param_labels != self.model.free_params:
            self._bt = None  # free-parameter set changed since first build
        if self._bt is None:
            self._bt = BayesianTiming(self.model, self.toas, **self._bt_args)
            if self.fitkeys != list(self._bt.param_labels):
                # not every sampler tracks a chain (EmceeSampler wraps its
                # own); reset only what exists
                if getattr(self.sampler, "ntotal", 0) \
                        and hasattr(self.sampler, "reset"):
                    log.warning(
                        "Free-parameter set changed after sampling started; "
                        "resetting the chain (old samples would mislabel "
                        "columns)")
                    self.sampler.reset()
                self.fitkeys = list(self._bt.param_labels)
                self.n_fit_params = len(self.fitkeys)
        return self._bt

    def get_fitvals(self) -> np.ndarray:
        return np.array([float(getattr(self.model, p).value or 0.0)
                         for p in self.fitkeys])

    def get_fiterrs(self) -> np.ndarray:
        return np.array([float(getattr(self.model, p).uncertainty or 0.0)
                         for p in self.fitkeys])

    def batched_posterior(self):
        """The typed batched-lnposterior entry point
        (:class:`pint_tpu.bayesian.BatchedPosterior`) — the SAME
        construction the ensemble sampling below evaluates, exposed so
        the amortized engine (:class:`pint_tpu.amortized.elbo.
        AmortizedVI`) trains its flow against exactly the posterior
        this fitter samples."""
        return self.bt.batched_posterior()

    def lnposterior(self, theta) -> float:
        if self._custom_post:
            lp = self.lnprior(self, theta)
            if not np.isfinite(lp):
                return -np.inf
            return lp + self.lnlikelihood(self, theta)
        return self.bt.lnposterior(theta)

    def fit_toas(self, maxiter: int = 100, pos=None, seed: Optional[int] = None,
                 burn_frac: float = 0.25, checkpoint: Optional[str] = None,
                 plan=None, **kw) -> float:
        """Run the ensemble for *maxiter* steps; model is set to the
        maximum-posterior sample and chi2 at that point is returned.

        ``checkpoint`` names an npz file: the chain (and exact RNG state)
        is persisted through :class:`pint_tpu.sampler.NpzBackend`, and a
        crashed run resumes from it — only the remaining steps are
        sampled, continuing the Markov chain bit-identically to an
        uninterrupted run.

        ``plan`` routes the walker axis through the execution-plan layer
        (``"auto"`` selects a walker-axis shard_map plan from the
        preflight-certified devices; or pass an
        :class:`~pint_tpu.runtime.plan.ExecutionPlan`) — each device
        evaluates its walker slice, and a device lost mid-chain is
        evicted with the plan degraded one rung instead of killing the
        run."""
        if plan is not None:
            if not isinstance(self.sampler, EnsembleSampler):
                from pint_tpu.exceptions import UsageError

                raise UsageError(
                    "plan= requires the jax-native EnsembleSampler")
            self.sampler.plan = plan
        with _tspan("mcmc.fit_toas", ntoas=len(self.toas),
                    nwalkers=self.sampler.nwalkers, maxiter=maxiter,
                    checkpointed=checkpoint is not None) as sp, \
                _jaxevents.watch(sp):
            return self._fit_toas_mcmc(sp, maxiter, pos, seed, burn_frac,
                                       checkpoint, **kw)

    def _fit_toas_mcmc(self, sp, maxiter, pos, seed, burn_frac,
                       checkpoint, **kw) -> float:
        if checkpoint is not None:
            from pint_tpu.grid import _model_param_sig
            from pint_tpu.runtime.checkpoint import fingerprint_of
            from pint_tpu.sampler import EnsembleSampler as _ES, NpzBackend

            if not isinstance(self.sampler, _ES):
                raise TypeError(
                    "checkpoint= requires the jax-native EnsembleSampler")
            if self.sampler.backend is None \
                    or getattr(self.sampler.backend, "path", None) \
                    not in (checkpoint, checkpoint + ".npz"):
                self.sampler.backend = NpzBackend(checkpoint)
            # run identity: a checkpoint from a different model/TOAs must
            # refuse to resume (CheckpointError), mirroring the grid
            # sweep's fingerprint guard.  The FREE parameter values are
            # deliberately excluded — they are the sampled quantities and
            # move when a chain is extended on the same fitter; the
            # posterior's identity is the fit keys, the data, and the
            # frozen parameters
            self.sampler.fingerprint = fingerprint_of(
                fitkeys=tuple(self.fitkeys), ntoas=len(self.toas),
                toas_version=getattr(self.toas, "_version", 0),
                frozen=tuple(s for s in _model_param_sig(self.model)
                             if s[0] not in self.fitkeys))
            if self.sampler.backend.exists() and pos is None:
                pos = self.sampler.resume()
                sp.add_event("mcmc.resume",
                             resumed_steps=self.sampler.iteration)
                maxiter = max(0, maxiter - self.sampler.iteration)
        if self._custom_post:
            # the bt property resyncs fitkeys/n_fit_params when the free
            # set changed since construction; the default branch touches
            # it via lnposterior_batch, this one must do so explicitly
            _ = self.bt
            # reference-style scalar posterior around the user callables
            # (single definition: lnposterior carries the custom branch)
            def post_batch(thetas):
                return np.array([self.lnposterior(t)
                                 for t in np.asarray(thetas)])

            if isinstance(self.sampler, EnsembleSampler):
                self.sampler.initialize_batched(post_batch,
                                                self.n_fit_params)
            else:
                self.sampler.initialize_sampler(self.lnposterior,
                                                self.n_fit_params)
        else:
            post_batch = self.bt.lnposterior_batch
            self.sampler.initialize_batched(post_batch,
                                            self.n_fit_params) \
                if isinstance(self.sampler, EnsembleSampler) else \
                self.sampler.initialize_sampler(self.bt.lnposterior,
                                                self.n_fit_params)
        if pos is None:
            pos = self.sampler.get_initial_pos(
                self.fitkeys, self.get_fitvals(), self.get_fiterrs(),
                self.errfact, seed=seed)
            # clip the initial ball inside the prior support
            lp = post_batch(pos)
            bad = ~np.isfinite(lp)
            if bad.any():
                pos[bad] = self.get_fitvals()
        self.sampler.run_mcmc(pos, maxiter)
        # burn-in from the TOTAL accumulated chain, not this call's step
        # count: after a checkpoint resume maxiter holds only the
        # remaining steps, and discarding from it would leave resumed
        # runs inequivalent to uninterrupted ones
        nsteps = self.sampler.get_chain().shape[0]
        chain = self.sampler.get_chain(flat=True,
                                       discard=int(nsteps * burn_frac))
        lnp = self.sampler.get_log_prob(flat=True,
                                        discard=int(nsteps * burn_frac))
        imax = int(np.argmax(lnp))
        self.maxpost = float(lnp[imax])
        self.maxpost_fitvals = chain[imax]
        stds = chain.std(axis=0)
        for i, p in enumerate(self.fitkeys):
            getattr(self.model, p).value = float(self.maxpost_fitvals[i])
            getattr(self.model, p).uncertainty = float(stds[i])
            self.errors[p] = float(stds[i])
        self.fitted_params = list(self.fitkeys)
        self.update_resids()
        chi2 = self.resids.chi2
        self.model.CHI2.value = chi2
        self.converged = True
        sp.attrs["chi2"] = float(chi2)
        sp.attrs["steps"] = int(nsteps)
        sp.attrs["acceptance"] = float(self.sampler.acceptance_fraction)
        sp.attrs["maxpost"] = float(self.maxpost)
        return chi2

    def get_posterior_samples(self, burn_frac: float = 0.25) -> np.ndarray:
        n = self.sampler.get_chain().shape[0]
        return self.sampler.get_chain(flat=True, discard=int(n * burn_frac))

    def get_fit_summary(self, burn_frac: float = 0.25) -> str:
        samples = self.get_posterior_samples(burn_frac)
        nsteps = self.sampler.get_chain().shape[0]
        lines = [f"MCMC fit: {self.sampler.nwalkers} walkers x "
                 f"{nsteps} steps, acceptance "
                 f"{self.sampler.acceptance_fraction:.2f}",
                 f"{'PAR':<12} {'median':>20} {'std':>12} {'maxpost':>20}"]
        med = np.median(samples, axis=0)
        std = np.std(samples, axis=0)
        for i, p in enumerate(self.fitkeys):
            lines.append(f"{p:<12} {med[i]:>20.12g} {std[i]:>12.3g} "
                         f"{self.maxpost_fitvals[i]:>20.12g}")
        return "\n".join(lines)


def lnlikelihood_basic(ftr, theta):
    """Photon-template log-likelihood at ``theta`` (reference
    ``mcmc_fitter.py:59``): template density at the wrapped event phases,
    weight-mixed when photon weights are present.  Densities are clamped
    at 1e-300 exactly like the fitter's own batched posterior
    (``event_fitter.py _build_batch``), so this helper decomposes it."""
    if not hasattr(ftr, "_template_density"):
        raise TypeError(
            f"{type(ftr).__name__} has no photon template; "
            "lnlikelihood_basic is for the template MCMC fitters "
            "(use lnlikelihood_chi2 for residual fitters)")
    for p, v in zip(ftr.fitkeys, np.atleast_1d(np.asarray(theta, float))):
        getattr(ftr.model, p).value = float(v)
    ph = np.asarray(ftr.model.phase(ftr.toas).frac) % 1.0
    probs = np.maximum(np.asarray(ftr._template_density(ph)), 1e-300)
    if getattr(ftr, "weights", None) is None:
        return float(np.sum(np.log(probs)))
    return float(np.sum(np.log(ftr.weights * probs + 1.0 - ftr.weights)))
