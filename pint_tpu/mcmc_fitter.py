"""MCMC fitter: posterior sampling of timing-model parameters.

Counterpart of reference ``mcmc_fitter.py:109 MCMCFitter`` (emcee-based
posterior fit with lnprior + lnlike over residual chi2 or photon templates).
The sampling engine is :class:`pint_tpu.sampler.EnsembleSampler` by default
— the walker ensemble is advanced with *batched* lnposterior evaluations
(jit+vmap via ``BayesianTiming.lnposterior_batch``), the TPU mapping of the
reference's one-process-per-walker pattern (SURVEY §2c row 2).

``MCMCFitterBinnedTemplate`` / ``MCMCFitterAnalyticTemplate`` (photon-domain
template likelihoods, reference ``mcmc_fitter.py:441,485``) live in
:mod:`pint_tpu.event_fitter` with the template machinery.
"""

from __future__ import annotations

import copy
from typing import Callable, List, Optional

import numpy as np

from pint_tpu.bayesian import BayesianTiming
from pint_tpu.fitter import Fitter
from pint_tpu.logging import log
from pint_tpu.residuals import Residuals
from pint_tpu.sampler import EnsembleSampler, MCMCSampler

__all__ = ["MCMCFitter"]


class MCMCFitter(Fitter):
    """Posterior sampling fit (reference ``mcmc_fitter.py:109``).

    Parameters mirror the reference: a sampler object (default: jax-native
    :class:`EnsembleSampler` with 32 walkers), optional prior_info, phase
    tracking via pulse numbers.  ``fit_toas(maxiter=N)`` runs N ensemble
    steps and sets the model to the maximum-posterior sample.
    """

    def __init__(self, toas, model, sampler: Optional[MCMCSampler] = None,
                 prior_info: Optional[dict] = None,
                 use_pulse_numbers: bool = False, nwalkers: int = 32,
                 errfact: float = 0.1, **kw):
        super().__init__(toas, model, **kw)
        self.method = "MCMC"
        self.sampler = sampler or EnsembleSampler(nwalkers)
        self.errfact = errfact
        self.bt = BayesianTiming(self.model, toas,
                                 use_pulse_numbers=use_pulse_numbers,
                                 prior_info=prior_info)
        self.fitkeys = self.bt.param_labels
        self.n_fit_params = len(self.fitkeys)
        self.maxpost = -np.inf
        self.maxpost_fitvals = None

    def get_fitvals(self) -> np.ndarray:
        return np.array([float(getattr(self.model, p).value or 0.0)
                         for p in self.fitkeys])

    def get_fiterrs(self) -> np.ndarray:
        return np.array([float(getattr(self.model, p).uncertainty or 0.0)
                         for p in self.fitkeys])

    def lnposterior(self, theta) -> float:
        return self.bt.lnposterior(theta)

    def fit_toas(self, maxiter: int = 100, pos=None, seed: Optional[int] = None,
                 burn_frac: float = 0.25, **kw) -> float:
        """Run the ensemble for *maxiter* steps; model is set to the
        maximum-posterior sample and chi2 at that point is returned."""
        self.sampler.initialize_batched(self.bt.lnposterior_batch,
                                        self.n_fit_params) \
            if isinstance(self.sampler, EnsembleSampler) else \
            self.sampler.initialize_sampler(self.bt.lnposterior,
                                            self.n_fit_params)
        if pos is None:
            pos = self.sampler.get_initial_pos(
                self.fitkeys, self.get_fitvals(), self.get_fiterrs(),
                self.errfact, seed=seed)
            # clip the initial ball inside the prior support
            lp = self.bt.lnposterior_batch(pos)
            bad = ~np.isfinite(lp)
            if bad.any():
                pos[bad] = self.get_fitvals()
        self.sampler.run_mcmc(pos, maxiter)
        chain = self.sampler.get_chain(flat=True,
                                       discard=int(maxiter * burn_frac))
        lnp = self.sampler.get_log_prob(flat=True,
                                        discard=int(maxiter * burn_frac))
        imax = int(np.argmax(lnp))
        self.maxpost = float(lnp[imax])
        self.maxpost_fitvals = chain[imax]
        stds = chain.std(axis=0)
        for i, p in enumerate(self.fitkeys):
            getattr(self.model, p).value = float(self.maxpost_fitvals[i])
            getattr(self.model, p).uncertainty = float(stds[i])
            self.errors[p] = float(stds[i])
        self.fitted_params = list(self.fitkeys)
        self.update_resids()
        chi2 = self.resids.chi2
        self.model.CHI2.value = chi2
        self.converged = True
        return chi2

    def get_posterior_samples(self, burn_frac: float = 0.25) -> np.ndarray:
        n = self.sampler.get_chain().shape[0]
        return self.sampler.get_chain(flat=True, discard=int(n * burn_frac))

    def get_fit_summary(self, burn_frac: float = 0.25) -> str:
        samples = self.get_posterior_samples(burn_frac)
        nsteps = self.sampler.get_chain().shape[0]
        lines = [f"MCMC fit: {self.sampler.nwalkers} walkers x "
                 f"{nsteps} steps, acceptance "
                 f"{self.sampler.acceptance_fraction:.2f}",
                 f"{'PAR':<12} {'median':>20} {'std':>12} {'maxpost':>20}"]
        med = np.median(samples, axis=0)
        std = np.std(samples, axis=0)
        for i, p in enumerate(self.fitkeys):
            lines.append(f"{p:<12} {med[i]:>20.12g} {std[i]:>12.3g} "
                         f"{self.maxpost_fitvals[i]:>20.12g}")
        return "\n".join(lines)
