"""Labeled matrices: design/covariance/correlation matrices whose axes carry
(parameter name, (start, end, unit)) maps.

Counterpart of reference ``pint_matrix.py:24 PintMatrix``, ``:306
DesignMatrix``, ``:660 CovarianceMatrix``, ``:346/805`` maker classes and
``:532,569,840`` combinators.  The numerical content is produced by the
TimingModel's autodiff design matrices (``timing_model.designmatrix`` /
``dm_designmatrix``); this layer is pure metadata bookkeeping, so it stays
host-side numpy — the labeled form is for humans and combinators, while the
raw arrays flow to the jitted solvers.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "PintMatrix",
    "DesignMatrix",
    "CovarianceMatrix",
    "CorrelationMatrix",
    "DesignMatrixMaker",
    "PhaseDesignMatrixMaker",
    "TOADesignMatrixMaker",
    "NoiseDesignMatrixMaker",
    "CovarianceMatrixMaker",
    "combine_design_matrices_by_quantity",
    "combine_design_matrices_by_param",
    "combine_covariance_matrix",
]

#: axis labels: per axis, a dict {label_name: (start, end, unit)}
AxisLabels = List[Dict[str, Tuple[int, int, str]]]


class PintMatrix:
    """A numpy matrix with named index ranges on every axis
    (reference ``pint_matrix.py:24``)."""

    def __init__(self, matrix: np.ndarray, axis_labels: AxisLabels):
        self.matrix = np.asarray(matrix)
        self.axis_labels = [dict(a) for a in axis_labels]
        if len(self.axis_labels) != self.matrix.ndim:
            raise ValueError(
                f"matrix has {self.matrix.ndim} axes but "
                f"{len(self.axis_labels)} label sets were given")
        for ax, labels in enumerate(self.axis_labels):
            cover = sorted((s, e) for s, e, _ in labels.values())
            for (s1, e1), (s2, e2) in zip(cover, cover[1:]):
                if s2 < e1:
                    raise ValueError(f"Axis {ax} labels overlap: {labels}")

    # -- basic introspection -------------------------------------------------
    @property
    def ndim(self) -> int:
        return self.matrix.ndim

    @property
    def shape(self) -> tuple:
        return self.matrix.shape

    @property
    def labels(self) -> List[List[str]]:
        return [list(a.keys()) for a in self.axis_labels]

    def diag(self, k: int = 0) -> np.ndarray:
        return np.diag(self.matrix, k)

    def get_label_names(self, axis: Optional[int] = None):
        if axis is not None:
            return list(self.axis_labels[axis].keys())
        return [list(a.keys()) for a in self.axis_labels]

    def get_unique_label_names(self) -> List[str]:
        seen: List[str] = []
        for a in self.axis_labels:
            for n in a:
                if n not in seen:
                    seen.append(n)
        return seen

    def get_label(self, label: str, axis: Optional[int] = None):
        """(axis, start, end, unit) entries for a label name."""
        hits = []
        axes = range(self.ndim) if axis is None else [axis]
        for ax in axes:
            if label in self.axis_labels[ax]:
                s, e, u = self.axis_labels[ax][label]
                hits.append((label, ax, s, e, u))
        if not hits:
            raise KeyError(f"Label {label!r} not found")
        return hits

    def get_label_size(self, label: str, axis: int = 0) -> int:
        _, _, s, e, _ = self.get_label(label, axis)[0]
        return e - s

    def get_label_matrix(self, labels: List[str]) -> "PintMatrix":
        """Submatrix covering the named labels on every axis
        (reference ``pint_matrix.py:253``)."""
        slices = []
        new_labels: AxisLabels = []
        for ax in range(self.ndim):
            entries = [(n,) + tuple(self.axis_labels[ax][n])
                       for n in labels if n in self.axis_labels[ax]]
            if not entries:
                slices.append(slice(None))
                new_labels.append(dict(self.axis_labels[ax]))
                continue
            entries.sort(key=lambda t: t[1])
            idx = np.concatenate([np.arange(s, e) for _, s, e, _ in entries])
            slices.append(idx)
            off, lab = 0, {}
            for n, s, e, u in entries:
                lab[n] = (off, off + (e - s), u)
                off += e - s
            new_labels.append(lab)
        sub = self.matrix
        for ax, sl in enumerate(slices):
            sub = np.take(sub, sl, axis=ax) if isinstance(sl, np.ndarray) else sub
        return type(self)(sub, new_labels)

    def append_along_axis(self, other: "PintMatrix", axis: int) -> "PintMatrix":
        off = self.shape[axis]
        labels = [dict(a) for a in self.axis_labels]
        for n, (s, e, u) in other.axis_labels[axis].items():
            labels[axis][n] = (s + off, e + off, u)
        return type(self)(np.concatenate([self.matrix, other.matrix], axis=axis),
                          labels)

    def __repr__(self):
        return f"{type(self).__name__}(shape={self.shape}, labels={self.labels})"


class DesignMatrix(PintMatrix):
    """Design matrix: axis 0 = data quantity, axis 1 = parameters
    (reference ``pint_matrix.py:306``)."""

    matrix_type = "design"

    @property
    def derivative_params(self) -> List[str]:
        # preserve column order
        items = sorted(self.axis_labels[1].items(), key=lambda kv: kv[1][0])
        return [k for k, _ in items]

    @property
    def param_units(self) -> List[str]:
        items = sorted(self.axis_labels[1].items(), key=lambda kv: kv[1][0])
        return [u for _, (_, _, u) in items]

    @property
    def derivative_quantity(self) -> List[str]:
        return list(self.axis_labels[0].keys())


class CovarianceMatrix(PintMatrix):
    """Symmetric labeled covariance (reference ``pint_matrix.py:660``)."""

    matrix_type = "covariance"

    def to_correlation_matrix(self) -> "CorrelationMatrix":
        d = np.sqrt(np.diag(self.matrix))
        return CorrelationMatrix((self.matrix / d).T / d, self.axis_labels)

    def prettyprint(self, prec: int = 3, offset: bool = False) -> str:
        names = [n for n, _ in sorted(self.axis_labels[0].items(),
                                      key=lambda kv: kv[1][0])]
        if not offset and "Offset" in names:
            keep = [n for n in names if n != "Offset"]
            return self.get_label_matrix(keep).prettyprint(prec=prec, offset=True)
        w = max(len(n) for n in names) + 1
        lines = [" " * w + " ".join(f"{n:>{prec + 7}}" for n in names)]
        for i, n in enumerate(names):
            row = " ".join(f"{self.matrix[i, j]:>{prec + 7}.{prec}e}"
                           for j in range(i + 1))
            lines.append(f"{n:<{w}}{row}")
        return "\n".join(lines)


class CorrelationMatrix(CovarianceMatrix):
    matrix_type = "correlation"


# ---------------------------------------------------------------------------
# Makers: build labeled matrices from (toas, model)
# ---------------------------------------------------------------------------

class DesignMatrixMaker:
    """Build the labeled design matrix for a data quantity
    (reference ``pint_matrix.py:346``): 'toa'/'phase' (timing derivatives),
    'dm' (wideband DM derivatives) or 'toa_noise' (GP noise basis)."""

    def __init__(self, derivative_quantity: str = "toa",
                 quantity_unit: str = "s"):
        self.derivative_quantity = derivative_quantity
        self.quantity_unit = quantity_unit

    def __call__(self, data, model, derivative_params=None,
                 offset: bool = True) -> Optional[DesignMatrix]:
        q = self.derivative_quantity
        if q in ("toa", "phase"):
            M, names, units = model.designmatrix(data, incoffset=offset)
        elif q == "dm":
            M, names, units = model.dm_designmatrix(data, incoffset=offset)
        else:
            M = names = units = None
        if M is not None and derivative_params is not None:
            # restrict to the requested columns (reference maker semantics)
            want = (["Offset"] if offset and "Offset" in names else []) \
                + [p for p in derivative_params if p != "Offset"]
            missing = [p for p in want if p not in names]
            if missing:
                raise KeyError(f"Parameters {missing} have no design column "
                               f"(frozen or unknown)")
            idx = [names.index(p) for p in want]
            M, names = M[:, idx], want
            units = [units[i] for i in idx]
        if M is not None:
            col = {n: (i, i + 1, u)
                   for i, (n, u) in enumerate(zip(names, units))}
            return DesignMatrix(M,
                                [{q: (0, M.shape[0], self.quantity_unit)}, col])
        if q == "toa_noise":
            Mn = model.noise_model_designmatrix(data)
            if Mn is None:
                return None
            dims = model.noise_model_dimensions(data)
            labels = {comp: (off, off + size, "s")
                      for comp, (off, size) in dims.items()}
            return DesignMatrix(Mn, [{q: (0, Mn.shape[0], self.quantity_unit)},
                                     labels])
        raise ValueError(f"Unknown derivative quantity {q!r}")


class PhaseDesignMatrixMaker(DesignMatrixMaker):
    """Phase-quantity maker (reference ``pint_matrix.py:423``)."""

    def __init__(self, derivative_quantity: str = "phase",
                 quantity_unit: str = ""):
        super().__init__(derivative_quantity, quantity_unit)


class TOADesignMatrixMaker(DesignMatrixMaker):
    """TOA-quantity maker (reference ``pint_matrix.py:482``)."""

    def __init__(self, derivative_quantity: str = "toa",
                 quantity_unit: str = "s"):
        super().__init__(derivative_quantity, quantity_unit)


class NoiseDesignMatrixMaker(DesignMatrixMaker):
    """GP noise-basis maker (reference ``pint_matrix.py:504``)."""

    def __init__(self, derivative_quantity: str = "toa_noise",
                 quantity_unit: str = "s"):
        super().__init__(derivative_quantity, quantity_unit)


class CovarianceMatrixMaker:
    """Build the labeled data covariance for a quantity
    (reference ``pint_matrix.py:805``)."""

    def __init__(self, covariance_quantity: str = "toa",
                 quantity_unit: str = "s"):
        self.covariance_quantity = covariance_quantity
        self.quantity_unit = quantity_unit

    def __call__(self, data, model) -> CovarianceMatrix:
        if self.covariance_quantity == "toa":
            cov = model.toa_covariance_matrix(data)
        elif self.covariance_quantity == "dm":
            sig = model.scaled_dm_uncertainty(data)
            cov = np.diag(sig**2)
        else:
            raise ValueError(
                f"Unknown covariance quantity {self.covariance_quantity!r}")
        lab = {self.covariance_quantity: (0, cov.shape[0], self.quantity_unit)}
        return CovarianceMatrix(cov, [lab, lab])


# ---------------------------------------------------------------------------
# Combinators
# ---------------------------------------------------------------------------

def combine_design_matrices_by_quantity(design_matrices) -> DesignMatrix:
    """Stack row blocks of different data quantities sharing the same
    parameter columns (reference ``pint_matrix.py:532``)."""
    mats = [m for m in design_matrices if m is not None]
    base = mats[0]
    for m in mats[1:]:
        if m.derivative_params != base.derivative_params:
            raise ValueError("Parameter columns do not match: "
                             f"{m.derivative_params} vs {base.derivative_params}")
    rows = np.concatenate([m.matrix for m in mats], axis=0)
    row_labels: Dict[str, Tuple[int, int, str]] = {}
    off = 0
    for m in mats:
        for n, (s, e, u) in m.axis_labels[0].items():
            row_labels[n] = (s + off, e + off, u)
        off += m.shape[0]
    return DesignMatrix(rows, [row_labels, dict(base.axis_labels[1])])


def combine_design_matrices_by_param(matrix1: DesignMatrix,
                                     matrix2: DesignMatrix,
                                     padding: float = 0.0) -> DesignMatrix:
    """Append the columns of *matrix2*; rows of matrix2 may cover only a
    leading subset of matrix1's rows — missing rows are padded
    (reference ``pint_matrix.py:569``)."""
    n1, n2 = matrix1.shape[0], matrix2.shape[0]
    m2 = matrix2.matrix
    if n2 < n1:
        m2 = np.vstack([m2, np.full((n1 - n2, m2.shape[1]), padding)])
    elif n2 > n1:
        raise ValueError("Second design matrix has more rows than the first")
    cols = np.hstack([matrix1.matrix, m2])
    off = matrix1.shape[1]
    col_labels = dict(matrix1.axis_labels[1])
    for n, (s, e, u) in matrix2.axis_labels[1].items():
        col_labels[n] = (s + off, e + off, u)
    return DesignMatrix(cols, [dict(matrix1.axis_labels[0]), col_labels])


def combine_covariance_matrix(covariance_matrices,
                              crossterm_padding: float = 0.0) -> CovarianceMatrix:
    """Block-diagonal combination (reference ``pint_matrix.py:840``)."""
    mats = list(covariance_matrices)
    n = sum(m.shape[0] for m in mats)
    out = np.full((n, n), crossterm_padding)
    labels: Dict[str, Tuple[int, int, str]] = {}
    off = 0
    for m in mats:
        k = m.shape[0]
        out[off:off + k, off:off + k] = m.matrix
        for nm, (s, e, u) in m.axis_labels[0].items():
            labels[nm] = (s + off, e + off, u)
        off += k
    return CovarianceMatrix(out, [labels, dict(labels)])
