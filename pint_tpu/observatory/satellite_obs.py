"""Orbiting observatories: spacecraft position from orbit files.

Counterpart of reference ``satellite_obs.py:283 SatelliteObs`` /
``:87 load_FPorbit`` / ``:427 get_satellite_observatory``: load a Fermi FT2,
generic FPorbit, or nuSTAR orbit file, spline-interpolate the geocentric ECI
(J2000) position to TOA epochs, and compose with the Earth's SSB position.

Orbit files are FITS BINTABLEs read with the native
:mod:`pint_tpu.fits_utils` reader (no astropy in this deployment).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
from scipy.interpolate import CubicSpline

from pint_tpu import ephemeris as ephem_mod
from pint_tpu.fits_utils import FITSHDU, read_fits
from pint_tpu.logging import log
from pint_tpu.observatory import Observatory, _registry
from pint_tpu.utils import PosVel

__all__ = ["SatelliteObs", "load_FT2", "load_Fermi_FT2", "load_orbit",
           "load_FPorbit", "load_nustar_orbit",
           "get_satellite_observatory"]


def _find_orbit_hdu(hdus) -> FITSHDU:
    for name in ("SC_DATA", "ORBIT", "PREFILTER", "ORBIT_DATA"):
        for h in hdus:
            if h.name.upper() == name:
                return h
    for h in hdus[1:]:
        if h.is_bintable:
            return h
    raise ValueError("No orbit extension found")


def _mjds_of(hdu: FITSHDU, timecol: str) -> np.ndarray:
    from pint_tpu.fits_utils import _mjdref

    hdr = hdu.header
    mjdref = _mjdref(hdr)
    tz = float(hdr.get("TIMEZERO", 0.0))
    met = hdu.data()[timecol].astype(np.float64)
    return np.asarray(mjdref, dtype=np.float64) + (met + tz) / 86400.0


def load_FT2(ft2name: str) -> Tuple[np.ndarray, np.ndarray]:
    """(mjds_tt, positions_km) from a Fermi FT2 file (SC_POSITION in m,
    ECI J2000; reference ``satellite_obs.py:39 load_FT2``)."""
    hdu = _find_orbit_hdu(read_fits(ft2name))
    data = hdu.data()
    mjds = _mjds_of(hdu, "START")
    pos_km = np.asarray(data["SC_POSITION"], dtype=np.float64) / 1e3
    return mjds, pos_km


def load_FPorbit(orbit_filename: str) -> Tuple[np.ndarray, np.ndarray]:
    """(mjds_tt, positions_km) from an FPorbit file (X/Y/Z in m;
    reference ``satellite_obs.py:87``)."""
    hdu = _find_orbit_hdu(read_fits(orbit_filename))
    data = hdu.data()
    mjds = _mjds_of(hdu, "TIME")
    pos_km = np.column_stack([data["X"], data["Y"], data["Z"]]) \
        .astype(np.float64) / 1e3
    order = np.argsort(mjds)
    return mjds[order], pos_km[order]


def load_nustar_orbit(orb_filename: str) -> Tuple[np.ndarray, np.ndarray]:
    """(mjds_tt, positions_km) from a nuSTAR .orb file (POSITION in km;
    reference ``satellite_obs.py:~200``)."""
    hdu = _find_orbit_hdu(read_fits(orb_filename))
    data = hdu.data()
    mjds = _mjds_of(hdu, "TIME")
    colname = "POSITION" if "POSITION" in data else "SC_POSITION"
    pos_km = np.asarray(data[colname], dtype=np.float64)
    return mjds, pos_km


_LOADERS = {"FT2": load_FT2, "FPORBIT": load_FPorbit, "ORB": load_nustar_orbit}


class SatelliteObs(Observatory):
    """Observatory on an orbit file: geocentric ECI position splined to TOA
    epochs (reference ``satellite_obs.py:283``)."""

    def __init__(self, name: str, ft2name: str, fmt: str = "FT2",
                 maxextrap: float = 2.0):
        super().__init__(name, include_gps=False, include_bipm=False)
        loader = _LOADERS.get(fmt.upper(), load_FPorbit)
        self._mjds, self._pos_km = loader(ft2name)
        if len(self._mjds) < 4:
            raise ValueError("Orbit file has too few rows to interpolate")
        self.maxextrap = maxextrap / 1440.0  # minutes -> days
        self._spline = CubicSpline(self._mjds, self._pos_km, axis=0)
        self._dspline = self._spline.derivative()

    def clock_corrections(self, utc_mjd, **kw):
        # spacecraft event times carry no ground-clock chain
        return np.zeros_like(np.atleast_1d(np.asarray(utc_mjd,
                                                      dtype=np.float64)))

    def _check_bounds(self, t):
        lo, hi = self._mjds[0], self._mjds[-1]
        if np.any(t < lo - self.maxextrap) or np.any(t > hi + self.maxextrap):
            raise ValueError(
                f"TOA epochs outside orbit file span [{lo:.3f}, {hi:.3f}] "
                f"(+/- {self.maxextrap * 1440:.0f} min)")

    def get_gcrs(self, utc_mjd, tt_mjd=None):
        """Geocentric position/velocity [m, m/s] at the given epochs."""
        t = np.atleast_1d(np.asarray(tt_mjd if tt_mjd is not None
                                     else utc_mjd, dtype=np.float64))
        self._check_bounds(t)
        pos_m = self._spline(t) * 1e3
        vel_ms = self._dspline(t) * 1e3 / 86400.0
        return pos_m, vel_ms

    def posvel(self, utc_mjd, tdb_mjd, ephem: str = "DE440") -> PosVel:
        eph = ephem_mod.load_ephemeris(ephem)
        tdb = np.atleast_1d(np.asarray(tdb_mjd, dtype=np.float64))
        epos, evel = eph.posvel_ssb("earth", tdb)
        spos_m, svel_ms = self.get_gcrs(utc_mjd, tt_mjd=tdb)
        return PosVel(epos + spos_m / 1e3, evel + svel_ms / 1e3,
                      obj=self.name, origin="ssb")


def get_satellite_observatory(name: str, ft2name: str, fmt: str = "FT2",
                              overwrite: bool = False, **kw) -> SatelliteObs:
    """Create and register a satellite observatory
    (reference ``satellite_obs.py:427``)."""
    key = name.lower()
    if key in _registry and not overwrite:
        log.warning(f"Observatory {name} already registered; returning it "
                    "(pass overwrite=True to reload)")
        return _registry[key]
    obs = SatelliteObs(name, ft2name, fmt=fmt, **kw)
    return obs


#: reference spelling (``satellite_obs.py:18``)
load_Fermi_FT2 = load_FT2


def load_orbit(obs_name: str, orb_filename) -> Tuple[np.ndarray, np.ndarray]:
    """Load one or more orbit files for the named mission (reference
    ``satellite_obs.py:242``): Fermi uses FT2, NuSTAR its own format,
    NICER/RXTE/others FPorbit.  ``orb_filename`` may be a list, an
    ``@listfile`` (one path per line), or a single path; multiple files are
    concatenated in time order."""
    if isinstance(orb_filename, (list, tuple)):
        paths = list(orb_filename)
    elif str(orb_filename).startswith("@"):
        with open(str(orb_filename)[1:]) as f:
            paths = [ln.strip() for ln in f if ln.strip()]
    else:
        paths = [str(orb_filename)]
    name = obs_name.lower()
    if "fermi" in name:
        loader = load_FT2
    elif "nustar" in name:
        loader = load_nustar_orbit
    else:
        loader = load_FPorbit
    mjds_all, pos_all = [], []
    for p in paths:
        m, x = loader(p)
        mjds_all.append(np.asarray(m))
        pos_all.append(np.asarray(x))
    mjds = np.concatenate(mjds_all)
    pos = np.concatenate(pos_all, axis=0)
    order = np.argsort(mjds)
    return mjds[order], pos[order]
