"""Clock-correction file readers: tempo ``time.dat`` and tempo2 ``.clk``.

Native counterpart of reference ``observatory/clock_file.py:25,441,566``.
A :class:`ClockFile` holds (mjd, clock_correction_us) samples and evaluates
by linear interpolation, with a configurable out-of-range policy.  The
global-repository download machinery of the reference
(``global_clock_corrections.py``) is replaced by a search over local
directories (``$PINT_CLOCK_DIR``, package data) since deployment targets are
zero-egress; :func:`find_clock_file` returns a zero correction with a
one-time warning when no file is found.
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from pint_tpu.exceptions import ClockCorrectionOutOfRange, NoClockCorrections
from pint_tpu.logging import log

__all__ = ["ClockFile", "GlobalClockFile", "read_tempo_clock_file",
           "read_tempo2_clock_file", "find_clock_file"]


class GlobalClockFile:
    """A clock file served from the global repository, refreshed on demand
    (reference ``clock_file.py:781``): evaluating past the end of the
    loaded data triggers an update check against the repository (the
    local-mirror transport of
    :mod:`pint_tpu.observatory.global_clock_corrections`).

    Delegates everything else to the freshly parsed :class:`ClockFile`.
    """

    def __init__(self, filename: str, fmt: str = "tempo",
                 url_base=None, valid_beyond_ends: bool = False):
        self.filename = filename
        self.fmt = fmt
        self.url_base = url_base
        self.valid_beyond_ends = valid_beyond_ends
        path = self._fetch("if_missing")
        self._load(path)

    def _fetch(self, policy: str):
        from pint_tpu.observatory.global_clock_corrections import (
            get_clock_correction_file)

        try:
            path = get_clock_correction_file(self.filename,
                                             download_policy=policy,
                                             url_base=self.url_base)
        except (KeyError, FileNotFoundError) as e:
            raise NoClockCorrections(
                f"Clock file {self.filename} not available: {e}") from e
        if path is None:
            raise NoClockCorrections(
                f"Clock file {self.filename} not available from the "
                "repository or local search directories")
        return path

    @staticmethod
    def _stat_sig(path):
        st = os.stat(path)
        return (str(path), st.st_mtime, st.st_size)

    def _load(self, path, file_hash=None):
        from pint_tpu.utils import compute_hash

        self._path = path
        self._sig = self._stat_sig(path)
        self._hash = file_hash if file_hash is not None \
            else compute_hash(path)
        self.clock_file = ClockFile.read(
            path, fmt=self.fmt, valid_beyond_ends=self.valid_beyond_ends)

    def update(self) -> bool:
        """Refresh from the repository per its index policy; returns True
        when new data actually arrived (reference ``clock_file.py:828``)."""
        from pint_tpu.utils import compute_hash

        path = self._fetch("if_expired")
        if self._stat_sig(path) == self._sig:
            return False  # same file, untouched: skip the content hash
        h = compute_hash(path)
        if h != self._hash:
            self._load(path, file_hash=h)
            return True
        self._sig = self._stat_sig(path)  # touched but identical content
        return False

    @property
    def mjd(self):
        return self.clock_file.mjd

    @property
    def clock_us(self):
        return self.clock_file.clock_us

    def last_correction_mjd(self) -> float:
        return self.clock_file.last_correction_mjd()

    @property
    def time(self):
        """Sample epochs of the loaded data (reference
        ``clock_file.py time``)."""
        return self.clock_file.mjd

    @property
    def clock(self):
        """Corrections [us] of the loaded data (reference
        ``clock_file.py clock``)."""
        return self.clock_file.clock_us

    @property
    def leading_comment(self) -> str:
        """Header line of the underlying file (reference
        ``clock_file.py leading_comment``)."""
        return getattr(self.clock_file, "hdrline", "")

    @property
    def comments(self) -> list:
        """Per-sample comments; the parsers here keep only the header, so
        this is empty placeholders (reference ``clock_file.py
        comments``)."""
        return [""] * len(self.clock_file.mjd)

    def export(self, filename: str) -> None:
        """Write the underlying clock file out (reference
        ``clock_file.py:903``)."""
        self.clock_file.export(filename)

    def evaluate(self, mjd, limits: str = "warn"):
        """Clock correction [s] at the given MJDs; requests past the end of
        the loaded data (or with no data loaded at all) first try to
        refresh from the repository.  A failed refresh falls back to the
        already-loaded data, which then applies its own out-of-range
        ``limits`` policy."""
        mjd_arr = np.atleast_1d(np.asarray(mjd, dtype=np.float64))
        needs_more = mjd_arr.size and (
            len(self.clock_file.mjd) == 0
            or mjd_arr.max() > self.clock_file.mjd[-1])
        if needs_more:
            try:
                self.update()
            except NoClockCorrections as e:
                _warn_once(self.filename, "refresh-failed",
                           f"Clock file {self.filename} could not be "
                           f"refreshed ({e}); using the loaded data")
        return self.clock_file.evaluate(mjd_arr, limits=limits)


class ClockFile:
    """Measured clock offsets vs MJD with linear-interpolation evaluation."""

    def __init__(self, mjd, clock_us, filename="", hdrline="", valid_beyond_ends=False):
        self.mjd = np.asarray(mjd, dtype=np.float64)
        self.clock_us = np.asarray(clock_us, dtype=np.float64)
        order = np.argsort(self.mjd, kind="stable")
        self.mjd, self.clock_us = self.mjd[order], self.clock_us[order]
        self.filename = filename
        self.hdrline = hdrline
        self.valid_beyond_ends = valid_beyond_ends

    @classmethod
    def read(cls, path: str, fmt: str = "tempo", **kw) -> "ClockFile":
        if fmt == "tempo2":
            return read_tempo2_clock_file(path, **kw)
        return read_tempo_clock_file(path, **kw)

    def evaluate(self, mjd, limits: str = "warn") -> np.ndarray:
        """Clock correction in seconds at the given MJD(s)."""
        mjd = np.atleast_1d(np.asarray(mjd, dtype=np.float64))
        if len(self.mjd) == 0:
            return np.zeros_like(mjd)
        out_of_range = (mjd < self.mjd[0]) | (mjd > self.mjd[-1])
        if np.any(out_of_range) and not self.valid_beyond_ends:
            msg = (
                f"Clock file {self.filename or '<unnamed>'} does not cover "
                f"MJD {mjd[out_of_range].min():.1f}..{mjd[out_of_range].max():.1f}"
            )
            if limits == "error":
                raise ClockCorrectionOutOfRange(msg)
            if self.filename:
                _warn_once(self.filename, "out-of-range", msg)
            elif not getattr(self, "_warned_out_of_range", False):
                # filename-less (programmatic) clock files dedup on a
                # per-INSTANCE flag: a shared "<unnamed>" key would let
                # the first such file swallow every other one's distinct
                # diagnostic, and an id(self)-based key could be
                # recycled onto a new instance after garbage collection
                self._warned_out_of_range = True
                log.warning(msg)
        return np.interp(mjd, self.mjd, self.clock_us) * 1e-6

    def last_correction_mjd(self) -> float:
        return float(self.mjd[-1]) if len(self.mjd) else -np.inf

    @property
    def time(self) -> np.ndarray:
        """Sample epochs, MJD (reference ``clock_file.py time``)."""
        return self.mjd

    @property
    def clock(self) -> np.ndarray:
        """Corrections [us] at the sample epochs (reference
        ``clock_file.py clock``)."""
        return self.clock_us

    @staticmethod
    def merge(clocks, trim: bool = True) -> "ClockFile":
        """Sum a chain of clock files into one (reference
        ``clock_file.py:195``): the merged corrections are the sum of the
        inputs evaluated on the union of their sample epochs; with
        ``trim`` the result covers only the overlap of all inputs."""
        clocks = list(clocks)
        if not clocks:
            raise ValueError("need at least one clock file")
        if any(len(c.mjd) == 0 for c in clocks):
            raise ValueError(
                "cannot merge: a clock file in the chain has no samples "
                f"({[c.filename for c in clocks if len(c.mjd) == 0]})")
        mjds = np.unique(np.concatenate([c.mjd for c in clocks]))
        if trim:
            lo = max(c.mjd[0] for c in clocks)
            hi = min(c.mjd[-1] for c in clocks)
            if lo > hi:
                raise ValueError(
                    "cannot merge: clock files do not overlap in time "
                    f"({[c.filename for c in clocks]})")
            mjds = mjds[(mjds >= lo) & (mjds <= hi)]
        total_us = np.zeros_like(mjds)
        for c in clocks:
            total_us += c.evaluate(mjds, limits="warn") * 1e6
        return ClockFile(mjds, total_us,
                         filename="+".join(c.filename for c in clocks),
                         hdrline="# merged chain")

    def export(self, filename: str) -> None:
        """Write this clock file out (reference ``clock_file.py:411``):
        byte-for-byte from the backing file when its full path is known,
        else re-serialized in tempo2 format (``filename`` alone is a
        basename and must NOT be resolved against the cwd — it could name
        an unrelated file)."""
        import shutil

        src = getattr(self, "source_path", None)
        if src and os.path.exists(src):
            shutil.copyfile(src, filename)
            return
        log.info(f"export: no backing file for {self.filename!r}; "
                 "writing tempo2 format")
        self.write_tempo2_clock_file(filename)

    def __add__(self, other: "ClockFile") -> "ClockFile":
        """Merge two clock files by summing corrections on the union grid."""
        mjds = np.union1d(self.mjd, other.mjd)
        tot = self.evaluate(mjds, limits="warn") + other.evaluate(mjds, limits="warn")
        return ClockFile(mjds, tot * 1e6, filename=f"{self.filename}+{other.filename}")

    def write_tempo2_clock_file(self, path: str, hdrline: Optional[str] = None):
        with open(path, "w") as f:
            f.write((hdrline or self.hdrline or "# UTC(obs) UTC") + "\n")
            for m, c in zip(self.mjd, self.clock_us):
                f.write(f"{m:.5f} {c * 1e-6:.12e}\n")

    def write_tempo_clock_file(self, path: str, obscode: str = "1"):
        with open(path, "w") as f:
            f.write("# fake header\n   MJD       EECO-REF    NIST-REF NS      DATE    COMMENTS\n")
            for m, c in zip(self.mjd, self.clock_us):
                f.write(f"{m:9.2f} {0.0:9.3f} {c:9.3f} {obscode}\n")


def read_tempo_clock_file(path: str, obscode: Optional[str] = None, **kw) -> ClockFile:
    """Parse a TEMPO-format ``time*.dat`` file (reference ``clock_file.py:25``).

    Layout: columns MJD, EECO-REF offset [us], NIST-REF offset [us], obscode
    flag; the correction applied to TOAs is col3 - col2.  Lines starting with
    '#' or header text are skipped; a line beginning with 'MJD' is the header.
    """
    mjds: List[float] = []
    corr: List[float] = []
    # truncation signature: a line whose MJD parses but whose offset
    # columns do not, with no well-formed data line after it — a file cut
    # mid-line.  Legacy special lines mid-file still skip silently.
    bad_tail = False
    with open(path) as f:
        for ln in f:
            s = ln.strip()
            if not s or s.startswith("#") or s[0].isalpha():
                continue
            # 'si' special lines and comments
            fields = s.split()
            try:
                mjd = float(fields[0])
            except ValueError:
                continue
            if not (15000 < mjd < 100000):
                continue
            try:
                c1 = float(fields[1])
                c2 = float(fields[2]) if len(fields) > 2 else 0.0
            except (ValueError, IndexError):
                bad_tail = True
                continue
            bad_tail = False
            code = fields[3] if len(fields) > 3 else None
            if obscode is not None and code is not None and code.lower() != obscode.lower():
                continue
            mjds.append(mjd)
            corr.append(c2 - c1)
    if bad_tail:
        from pint_tpu.exceptions import PintFileError

        raise PintFileError(
            f"{path}: truncated clock file — final data line is malformed")
    cf = ClockFile(mjds, corr, filename=os.path.basename(path), **kw)
    cf.source_path = os.path.abspath(path)
    return cf


def read_tempo2_clock_file(path: str, **kw) -> ClockFile:
    """Parse a TEMPO2 ``.clk`` file (reference ``clock_file.py:441``).

    The header is the first ``#``-prefixed line (``# UTC(obs) UTC(GPS)``
    style); ``##`` lines and later ``#`` lines are comments.  Data lines are
    ``MJD offset_seconds [uncertainty flags...]``; unparseable lines are
    skipped (a bare-text header line therefore also falls through safely).
    """
    mjds: List[float] = []
    corr: List[float] = []
    hdrline = ""
    bad_tail = False  # see read_tempo_clock_file: cut-mid-line signature
    with open(path) as f:
        for ln in f:
            s = ln.strip()
            if not s:
                continue
            if s.startswith("#"):
                if not hdrline and not s.startswith("##"):
                    hdrline = s
                continue
            fields = s.split()
            try:
                m_, c_ = float(fields[0]), float(fields[1])
            except (ValueError, IndexError):
                # bare-text header lines fall through safely, but a line
                # whose MJD parses and offset does not is data corruption
                try:
                    bad_tail = 15000 < float(fields[0]) < 100000
                except ValueError:
                    pass
                continue
            bad_tail = False
            mjds.append(m_)
            corr.append(c_ * 1e6)  # seconds -> us
    if bad_tail:
        from pint_tpu.exceptions import PintFileError

        raise PintFileError(
            f"{path}: truncated clock file — final data line is malformed")
    cf = ClockFile(mjds, corr, filename=os.path.basename(path),
                   hdrline=hdrline, **kw)
    cf.source_path = os.path.abspath(path)
    return cf


_warned: set = set()
_cache: dict = {}


def _warn_once(filename: str, kind: str, message: str) -> None:
    """One warning per (filename, kind) per process: clock diagnostics
    repeat per TOA batch with VARYING text (different MJD ranges), so the
    logging layer's exact-message dedup can't catch them and a bench tail
    fills with the same missing-file story, drowning real diagnostics.
    The first occurrence carries the detail; repeats are dropped here."""
    key = (filename, kind)
    if key not in _warned:
        _warned.add(key)
        log.warning(message)


def _clock_search_paths() -> List[str]:
    paths = []
    for env in ("PINT_CLOCK_OVERRIDE", "PINT_CLOCK_DIR"):
        if os.environ.get(env):
            paths.append(os.environ[env])
    for env in ("TEMPO", "TEMPO2"):
        if os.environ.get(env):
            paths.append(os.path.join(os.environ[env], "clock"))
    # the global-repository cache (populated by update_clock_files /
    # get_clock_correction_file / update_all) participates in the live
    # chain whenever it exists — explicit url_base= calls populate it
    # without either env var being set
    cache = os.environ.get(
        "PINT_CLOCK_CACHE",
        os.path.join(os.path.expanduser("~"), ".pint_tpu", "clock_cache"))
    if os.path.isdir(cache):
        paths.append(cache)
    paths.append(os.path.join(os.path.dirname(__file__), "..", "data", "clock"))
    return [p for p in paths if os.path.isdir(p)]


def find_clock_file(name: str, fmt: str = "tempo", limits: str = "warn",
                    valid_beyond_ends: bool = False) -> Optional[ClockFile]:
    """Locate and parse the named clock file, searching local directories.

    Returns None (with a one-time warning) when the file cannot be found —
    the zero-egress analogue of the reference's warn-and-continue policy for
    missing global clock corrections (``observatory/__init__.py:387``).
    With ``limits="error"`` a missing file always raises, cached or not.
    """
    key = (name, fmt, valid_beyond_ends)
    if key in _cache:
        cf = _cache[key]
        if cf is None and limits == "error":
            raise NoClockCorrections(f"Clock file {name} not found")
        return cf
    for d in _clock_search_paths():
        cand = os.path.join(d, name)
        if os.path.exists(cand):
            cf = ClockFile.read(cand, fmt=fmt, valid_beyond_ends=valid_beyond_ends)
            _cache[key] = cf
            return cf
    _cache[key] = None
    if limits == "error":
        raise NoClockCorrections(f"Clock file {name} not found")
    _warn_once(name, "missing",
               f"Clock file {name} not found; assuming zero correction")
    return None
