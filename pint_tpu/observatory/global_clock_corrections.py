"""Global clock-correction repository access.

Counterpart of reference ``global_clock_corrections.py:40,150,188,229``
(``get_file`` / ``Index`` / ``get_clock_correction_file`` / ``update_all``).

The reference downloads versioned clock files from the IPTA github
repository into the astropy cache, refreshing them per the repository's
``index.txt`` (per-file update interval + invalid-if-older-than stamps).
This deployment is zero-egress, so the transport is swapped while the full
policy machinery is kept: a *repository* is any local directory (or
``file://`` URL) laid out like the IPTA repo — ``index.txt`` plus the files
it lists — typically a mirror of
https://ipta.github.io/pulsar-clock-corrections/.  Files are copied from
the repository into a cache directory with the same ``download_policy``
semantics the reference implements ("always" / "never" / "if_expired" /
"if_missing" + invalid_if_older_than); mtimes track when the cache copy
was refreshed.

Configuration:

* ``$PINT_CLOCK_REPO`` — the repository directory (index.txt + files).
* ``$PINT_CLOCK_CACHE`` — cache directory (default
  ``~/.pint_tpu/clock_cache``).
* ``$PINT_CLOCK_DIR``, ``$TEMPO2/clock``, ``$TEMPO/clock`` — plain local
  search directories honored as a repository-less fallback (the same
  override order :mod:`pint_tpu.observatory.clock_file` uses).
"""

from __future__ import annotations

import os
import shutil
import time
from pathlib import Path
from typing import Dict, List, NamedTuple, Optional

from pint_tpu.logging import log

__all__ = ["Index", "IndexEntry", "get_file", "get_clock_correction_file",
           "update_all", "clock_search_dirs", "index_name",
           "index_update_interval_days"]

index_name = "index.txt"
#: the index itself is refreshed when older than this (reference
#: ``global_clock_corrections.py:37``)
index_update_interval_days = 1.0

_POLICIES = ("always", "never", "if_expired", "if_missing")


def clock_search_dirs() -> List[str]:
    """Repository-less local directories searched for clock files."""
    dirs = []
    if os.environ.get("PINT_CLOCK_DIR"):
        dirs.append(os.environ["PINT_CLOCK_DIR"])
    if os.environ.get("TEMPO2"):
        dirs.append(os.path.join(os.environ["TEMPO2"], "clock"))
    if os.environ.get("TEMPO"):
        dirs.append(os.path.join(os.environ["TEMPO"], "clock"))
    return [d for d in dirs if os.path.isdir(d)]


def _repo_dir(url_base: Optional[str]) -> Optional[Path]:
    base = url_base or os.environ.get("PINT_CLOCK_REPO")
    if base is None:
        return None
    if base.startswith("file://"):
        base = base[len("file://"):]
    if base.startswith(("http://", "https://")):
        log.warning(f"Clock repository {base} needs network access, which "
                    "this deployment does not have; set $PINT_CLOCK_REPO to "
                    "a local mirror instead")
        return None
    return Path(base)


def _cache_dir() -> Path:
    d = Path(os.environ.get("PINT_CLOCK_CACHE",
                            Path.home() / ".pint_tpu" / "clock_cache"))
    d.mkdir(parents=True, exist_ok=True)
    return d


def get_file(name: str, update_interval_days: float = 7.0,
             download_policy: str = "if_expired",
             url_base: Optional[str] = None,
             invalid_if_older_than: Optional[float] = None) -> Path:
    """Return a cached local path for repository file *name*, refreshing the
    cache copy per *download_policy* (reference
    ``global_clock_corrections.py:40 get_file``).

    ``invalid_if_older_than`` is a unix timestamp (the reference uses an
    astropy Time); a cache copy older than it is refreshed regardless of
    the update interval.  Raises FileNotFoundError when the policy forbids
    (or the repository cannot provide) a copy.
    """
    if download_policy not in _POLICIES:
        raise ValueError(f"Unknown download policy {download_policy!r}")
    cache = _cache_dir() / Path(name).name
    local = cache if cache.exists() else None

    if download_policy == "never":
        if local is None:
            raise FileNotFoundError(name)
        return local
    if download_policy == "if_missing" and local is not None:
        return local

    if local is not None and invalid_if_older_than is not None \
            and local.stat().st_mtime < invalid_if_older_than:
        log.info(f"Clock file {name} cache copy is older than its "
                 "invalid-if-older-than stamp; refreshing")
        local = None

    if download_policy == "if_expired" and local is not None:
        age = time.time() - local.stat().st_mtime
        if age < update_interval_days * 86400.0:
            return local

    # refresh from the repository ("download" = copy from local mirror)
    repo = _repo_dir(url_base)
    src = None
    if repo is not None:
        for cand in (repo / name, repo / Path(name).name):
            if cand.exists():
                src = cand
                break
    if src is None:
        for d in clock_search_dirs():
            cand = Path(d) / Path(name).name
            if cand.exists():
                src = cand
                break
    if src is None:
        if local is not None:
            if download_policy == "always":
                # 'always' promises a guaranteed refresh (the reference
                # raises here); silently serving a stale copy breaks it
                raise FileNotFoundError(
                    f"Clock file {name}: download_policy='always' but no "
                    "repository copy is available to refresh from (stale "
                    f"cache copy exists at {local})")
            log.warning(f"Clock file {name} is due for refresh but no "
                        "repository copy is available; using the stale "
                        f"cache copy {local}")
            return local
        raise FileNotFoundError(
            f"Clock file {name} not available: no cache copy and no "
            "repository (set $PINT_CLOCK_REPO to a local mirror of "
            "https://ipta.github.io/pulsar-clock-corrections/)")
    shutil.copy2(src, cache)
    os.utime(cache)  # mtime records when the cache copy was refreshed
    return cache


class IndexEntry(NamedTuple):
    file: str
    update_interval_days: float
    invalid_if_older_than: Optional[float]  # unix timestamp
    extra: str = ""


class Index:
    """Parsed repository ``index.txt`` (reference
    ``global_clock_corrections.py:150``): maps basenames to
    :class:`IndexEntry` rows (repo-relative path, update interval [days],
    invalid-if-older-than ISO date or ``---``, free-form description)."""

    def __init__(self, download_policy: str = "if_expired",
                 url_base: Optional[str] = None):
        index_file = get_file(index_name, index_update_interval_days,
                              download_policy=download_policy,
                              url_base=url_base)
        self.files: Dict[str, IndexEntry] = {}
        with open(index_file) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                e = line.split(maxsplit=3)
                if len(e) < 2:
                    continue
                stamp = None
                if len(e) > 2 and e[2] != "---":
                    import calendar

                    stamp = calendar.timegm(time.strptime(
                        e[2].split()[0], "%Y-%m-%d"))
                entry = IndexEntry(
                    file=e[0],
                    update_interval_days=float(e[1]),
                    invalid_if_older_than=stamp,
                    extra=e[3] if len(e) > 3 else "")
                self.files[Path(e[0]).name] = entry


def get_clock_correction_file(filename: str,
                              download_policy: str = "if_expired",
                              url_base: Optional[str] = None) -> Optional[str]:
    """Resolve a named clock file through the repository index when one is
    configured, falling back to the plain local search directories
    (reference ``global_clock_corrections.py:188``).

    With a repository: unknown names raise KeyError; known names honor the
    index's per-file expiry.  Without one: returns the first local-search
    hit, else None with a warning (the historical zero-egress behavior).
    """
    if _repo_dir(url_base) is not None:
        index = Index(download_policy=download_policy, url_base=url_base)
        details = index.files[filename]
        return str(get_file(details.file,
                            update_interval_days=details.update_interval_days,
                            download_policy=download_policy,
                            url_base=url_base,
                            invalid_if_older_than=details.invalid_if_older_than))
    for d in clock_search_dirs():
        cand = os.path.join(d, filename)
        if os.path.exists(cand):
            return cand
    if download_policy != "never":
        log.warning(
            f"Clock file {filename} not found locally and this deployment "
            "cannot download (zero egress); set $PINT_CLOCK_REPO or "
            "$PINT_CLOCK_DIR to a mirror of "
            "https://ipta.github.io/pulsar-clock-corrections/")
    return None


def update_all(export_to: Optional[str] = None,
               download_policy: str = "if_expired",
               url_base: Optional[str] = None) -> List[str]:
    """Refresh every file in the repository index, optionally exporting the
    copies to a directory (reference ``global_clock_corrections.py:229``).
    Returns the refreshed file names."""
    if _repo_dir(url_base) is None:
        log.warning("update_all: no clock repository configured; set "
                    "$PINT_CLOCK_REPO to a local mirror")
        return []
    index = Index(download_policy=download_policy, url_base=url_base)
    done = []
    for filename, details in index.files.items():
        try:
            f = get_file(details.file,
                         update_interval_days=details.update_interval_days,
                         download_policy=download_policy, url_base=url_base,
                         invalid_if_older_than=details.invalid_if_older_than)
        except FileNotFoundError:
            log.warning(f"update_all: {filename} listed in index but not "
                        "present in the repository")
            continue
        if export_to is not None:
            Path(export_to).mkdir(parents=True, exist_ok=True)
            shutil.copy2(f, Path(export_to) / Path(filename).name)
        done.append(filename)
    return done
