"""Global clock-correction repository access.

Counterpart of reference ``global_clock_corrections.py:40,150,229``
(``get_clock_correction_file``/``Index``/``update_all``).  The reference
downloads versioned clock files from the IPTA github repository; this
deployment is zero-egress, so files are resolved from local mirrors instead:
``$PINT_CLOCK_DIR``, ``$TEMPO2/clock``, ``$TEMPO/clock`` — the same override
mechanism the reference honors before downloading.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

from pint_tpu.logging import log

__all__ = ["Index", "get_clock_correction_file", "update_all",
           "clock_search_dirs"]


def clock_search_dirs() -> List[str]:
    dirs = []
    if os.environ.get("PINT_CLOCK_DIR"):
        dirs.append(os.environ["PINT_CLOCK_DIR"])
    if os.environ.get("TEMPO2"):
        dirs.append(os.path.join(os.environ["TEMPO2"], "clock"))
    if os.environ.get("TEMPO"):
        dirs.append(os.path.join(os.environ["TEMPO"], "clock"))
    return [d for d in dirs if os.path.isdir(d)]


class Index:
    """Parser for the repository's index.txt: file -> (update interval,
    invalid-if-older-than) rows (reference ``global_clock_corrections.py:150``)."""

    def __init__(self, path: str):
        self.files: Dict[str, dict] = {}
        with open(path) as f:
            for line in f:
                if line.startswith("#") or not line.strip():
                    continue
                parts = line.split()
                if len(parts) >= 2:
                    self.files[parts[0]] = {
                        "update_interval_days": float(parts[1]),
                        "invalid_if_older_than": (parts[2] if len(parts) > 2
                                                  else None),
                    }


def get_clock_correction_file(filename: str,
                              download_policy: str = "if_missing",
                              url_base: Optional[str] = None) -> Optional[str]:
    """Resolve a named clock file from the local mirror directories
    (reference ``get_file``; downloading is unavailable in zero-egress
    deployments, so a missing file returns None with a warning)."""
    for d in clock_search_dirs():
        cand = os.path.join(d, filename)
        if os.path.exists(cand):
            return cand
    if download_policy != "never":
        log.warning(
            f"Clock file {filename} not found locally and this deployment "
            "cannot download (zero egress); set $PINT_CLOCK_DIR to a mirror "
            "of https://ipta.github.io/pulsar-clock-corrections/")
    return None


def update_all(export_dir: Optional[str] = None, **kw):
    """Reference parity stub: refreshes would require network access."""
    log.warning("update_all: no network access in this deployment; clock "
                "files must be mirrored via $PINT_CLOCK_DIR")
